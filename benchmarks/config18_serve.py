"""Driver config #18: hybrid serving subsystem (ISSUE 18).

Four sections, one JSON artifact (``SERVE_BENCH_r19.json``):

1. **Hybrid join demo**: a real ``Cluster`` process over
   ``TpuSimTransport`` joins a >=4096-member simulated cluster (sparse
   engine, per-link planes armed). Gates: the initial SYNC hands the full
   sim table to the real member, the bridged row reaches ALIVE in every
   sampled sim view inside the convergence budget, and the hybrid
   membership survives a Partition+heal chaos scenario with the sentinel
   suite green (the bridged row rides as the bystander cohort the
   false-DEAD sentinel watches).
2. **Operator load generator**: ``bridge.LoadGenerator`` drives sustained
   join/leave/metadata/rumor churn plus concurrent /metrics + /trace +
   /whatif scrapes against a live ``MonitorServer`` serving the SAME mega
   sim. Gates: >=``--min-ops``/s member-facing ops, zero scrape errors,
   scrape p99 under ``--scrape-slo-ms``.
3. **Wilson-certified bridged liveness**: ``--trials`` windows stepped
   after the heal, each trial checking the bridged row ALIVE in every
   sampled view AND the real member's table still holding the sim seed.
   The record carries the Wilson interval on P(trial green); gate: lower
   bound >= ``--liveness-floor``.
4. **Armed-idle bridge overhead**: median window wall-time of a small
   driver with an ATTACHED but idle bridge endpoint (watch armed, no
   traffic) vs an identical plain driver — the serving plane's standing
   cost, gated within noise (``--overhead-budget`` ratio).

    python benchmarks/config18_serve.py [--n 4096] [--trials 128]
        [--loadgen-s 4] [--quick] [--out SERVE_BENCH_r19.json]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import pathlib as _p
import statistics
import sys as _s
import time

_s.path.insert(0, str(_p.Path(__file__).parent))          # for common.py
_s.path.insert(0, str(_p.Path(__file__).parent.parent))   # for the package

import jax

from common import emit, log

REPO = _p.Path(__file__).parent.parent


def _sparse_params(capacity: int):
    from scalecube_cluster_tpu.ops.sparse import SparseParams

    return SparseParams(
        capacity=capacity, fanout=3, ping_req_k=2, fd_every=2,
        sync_every=24, suspicion_mult=3, sweep_every=4,
        rumor_slots=16, mr_slots=256, announce_slots=64,
        seed_rows=(0, 1),
    )


def _serve_config(seeds=("sim://0",)):
    from scalecube_cluster_tpu.config import ClusterConfig

    return (
        ClusterConfig.default_local()
        .with_membership(lambda m: m.replace(
            seed_members=list(seeds), sync_interval=2.0, sync_timeout=3.0,
        ))
        .with_failure_detector(lambda f: f.replace(
            ping_interval=0.5, ping_timeout=0.4, ping_req_members=1,
        ))
        .with_gossip(lambda g: g.replace(gossip_interval=0.2))
    )


async def _drive(driver, predicate, timeout, window=8):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if predicate():
            return True
        await loop.run_in_executor(None, driver.step, window)
        await asyncio.sleep(0)
    return predicate()


def _alive_in_views(driver, row, sample_rows):
    from scalecube_cluster_tpu.models.member import MemberStatus

    return all(
        driver.status_of(r, row) == MemberStatus.ALIVE
        for r in sample_rows
        if r != row and driver.is_up(r)
    )


async def hybrid_sections(args, artifact):
    """Sections 1-3 share one mega sim + one real bridged member."""
    from scalecube_cluster_tpu.bridge import LoadGenerator, SimBridge
    from scalecube_cluster_tpu.chaos.events import Partition, Scenario
    from scalecube_cluster_tpu.cluster import new_cluster
    from scalecube_cluster_tpu.config import TelemetryConfig
    from scalecube_cluster_tpu.dissemination.certify import wilson_interval
    from scalecube_cluster_tpu.monitor import MonitorServer
    from scalecube_cluster_tpu.replay import WhatifService
    from scalecube_cluster_tpu.sim.driver import SimDriver

    n = args.n
    params = _sparse_params(n + 64)  # headroom: bridge row + churn pool
    log(f"[serve] building sparse mega sim N={n} (dense links for the "
        "partition) …")
    t0 = time.time()
    d = SimDriver(params, n, warm=True, seed=19, dense_links=True)
    d.arm_telemetry(TelemetryConfig(ring_len=64))
    d.arm_trace()
    bridge = SimBridge(d, seed_rows=params.seed_rows)
    loop = asyncio.get_running_loop()

    mon = MonitorServer()
    mon.register_telemetry(d)
    mon.register_whatif(WhatifService())
    await mon.start()

    sample_rows = sorted({0, 1, n // 3, n // 2, (2 * n) // 3, n - 1})
    join = {"n_sim": n, "engine": "sparse"}
    try:
        t_join = time.time()
        a = await (
            new_cluster(_serve_config())
            .transport_factory(bridge.transport_factory("real-0"))
            .start()
        )
        try:
            ep = bridge._endpoints["real-0"]
            join["initial_table"] = len(a.members())
            join["table_full"] = join["initial_table"] >= n - 1
            log(f"[serve] real member joined: table={join['initial_table']} "
                f"row={ep.row} ({time.time() - t_join:.1f}s)")

            converged = await _drive(
                d, lambda: _alive_in_views(d, ep.row, sample_rows),
                timeout=args.converge_s,
            )
            join["alive_in_sampled_views"] = bool(converged)
            join["join_s"] = round(time.time() - t_join, 2)
            log(f"[serve] bridged row ALIVE in sampled views: {converged} "
                f"({join['join_s']}s)")

            # Partition+heal with the sentinel suite armed; the bridged
            # row belongs to NO group (bystander cohort)
            half = n // 2
            scenario = Scenario(
                name="serve-partition-heal",
                events=[Partition(
                    groups=[range(0, half), range(half, n)],
                    at=8, heal_at=40,
                )],
                horizon=120,
                detect_budget=100,
                converge_budget=120,
                check_interval=8,
            )
            t_chaos = time.time()
            report = await loop.run_in_executor(
                None, lambda: d.run_scenario(scenario, max_window=8)
            )
            join["partition_violations"] = report.get("violations") or []
            join["partition_green"] = not join["partition_violations"]
            join["partition_s"] = round(time.time() - t_chaos, 2)
            log(f"[serve] partition+heal: green={join['partition_green']} "
                f"({join['partition_s']}s)")
            post_heal = await _drive(
                d, lambda: _alive_in_views(d, ep.row, sample_rows),
                timeout=args.converge_s,
            )
            join["alive_after_heal"] = bool(post_heal)
            join["ok"] = bool(
                join["table_full"] and converged
                and join["partition_green"] and post_heal
            )
            artifact["hybrid_join"] = join

            # -- section 2: the load generator against the live monitor --
            gen = LoadGenerator(
                d, monitor_url=mon.url, seed=7,
                seed_rows=params.seed_rows, max_churn_pool=32,
            )
            log(f"[serve] loadgen: {args.loadgen_s}s churn + scrapes …")
            # stepper cadence scales with N: a mega-sim window holds the
            # driver lock for its whole compute, so its duty cycle is the
            # serving plane's main contention knob
            step_window, step_interval = (4, 0.1) if args.n <= 1024 else (1, 0.5)
            # untimed pass: mutator/window compiles + connection setup land
            # here, so the timed run below measures steady-state serving
            await gen.warmup(step_window=step_window)
            rep = await gen.run(
                duration_s=args.loadgen_s,
                churn_workers=3, scrape_workers=2,
                step_window=step_window, step_interval_s=step_interval,
            )
            lg = rep.as_dict()
            # the scrape SLO budgets ONE in-flight mega-window collision on
            # top of the base render budget: the scrape paths are lock-free
            # (retained-row /metrics, cached /trace, host-dict /whatif), so
            # a colliding scrape no longer waits on the driver lock — but a
            # single-core host still runs the window's XLA compute on the
            # same core, and at N>1024 one window is ~0.5 s of it. Ops and
            # throughput keep their scale-independent gates.
            scrape_slo = args.scrape_slo_ms
            lg["min_ops_per_s"] = args.min_ops
            lg["scrape_slo_ms"] = scrape_slo
            lg["ok"] = bool(
                rep.ops_per_s >= args.min_ops
                and rep.scrape_errors == 0
                and all(
                    h["p99_ms"] <= scrape_slo
                    for h in rep.scrapes.values() if h["count"]
                )
            )
            artifact["loadgen"] = lg
            log(f"[serve] loadgen: {rep.ops_per_s:.0f} ops/s, scrapes "
                + json.dumps({k: v["p99_ms"] for k, v in rep.scrapes.items()})
                + f" ok={lg['ok']}")

            # -- section 3: Wilson-certified bridged liveness -------------
            ok_trials = 0
            for _ in range(args.trials):
                await loop.run_in_executor(None, d.step, 4)
                green = _alive_in_views(d, ep.row, sample_rows) and any(
                    m.address == "sim://0" for m in a.members()
                )
                ok_trials += bool(green)
            lo, hi = wilson_interval(ok_trials, args.trials, 0.95)
            live = {
                "trials": args.trials, "green": ok_trials,
                "wilson": [round(lo, 6), round(hi, 6)],
                "floor": args.liveness_floor,
                "ok": lo >= args.liveness_floor,
            }
            artifact["liveness"] = live
            log(f"[serve] liveness: {ok_trials}/{args.trials} green, "
                f"wilson=[{lo:.4f}, {hi:.4f}] ok={live['ok']}")
        finally:
            await a.shutdown()
    finally:
        await mon.stop()


async def overhead_section(args, artifact):
    """Section 4: armed-idle bridge overhead vs a plain twin driver."""
    from scalecube_cluster_tpu.bridge import SimBridge
    from scalecube_cluster_tpu.cluster import new_cluster
    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.sim.driver import SimDriver

    n = args.overhead_n
    loop = asyncio.get_running_loop()

    def interleaved(plain, armed, reps):
        # alternate the twins rep-by-rep so drift on the shared host (GC,
        # leftover shutdown tasks from the hybrid section, page cache)
        # lands on both lanes instead of biasing whichever ran second
        tp, ta = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            plain.step(8)
            plain.flush()
            tp.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            armed.step(8)
            armed.flush()
            ta.append(time.perf_counter() - t0)
        return statistics.median(tp), statistics.median(ta)

    plain = SimDriver(_sparse_params(n + 8), n, warm=True, seed=3)
    armed = SimDriver(_sparse_params(n + 8), n, warm=True, seed=3)
    bridge = SimBridge(armed)
    idle_cfg = (
        ClusterConfig.default_local()
        .with_membership(lambda m: m.replace(
            seed_members=["sim://0"], sync_interval=30.0,
        ))
        .with_failure_detector(lambda f: f.replace(ping_interval=30.0))
        .with_gossip(lambda g: g.replace(gossip_interval=5.0))
    )
    a = await (
        new_cluster(idle_cfg)
        .transport_factory(bridge.transport_factory("idle"))
        .start()
    )
    try:
        await asyncio.sleep(1.0)  # let join-time traffic fully drain
        plain.step(8)  # compile
        armed.step(8)  # compile the watched window variant
        t_plain, t_armed = await loop.run_in_executor(
            None, interleaved, plain, armed, args.reps
        )
    finally:
        await a.shutdown()

    ratio = t_armed / t_plain if t_plain > 0 else float("inf")
    artifact["armed_idle_overhead"] = {
        "n": n, "reps": args.reps,
        "plain_window_ms": round(t_plain * 1e3, 3),
        "armed_window_ms": round(t_armed * 1e3, 3),
        "ratio": round(ratio, 4),
        "budget": args.overhead_budget,
        "ok": ratio <= args.overhead_budget,
    }
    log(f"[serve] armed-idle: plain={t_plain * 1e3:.2f}ms "
        f"armed={t_armed * 1e3:.2f}ms ratio={ratio:.3f} "
        f"ok={ratio <= args.overhead_budget}")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--n", type=int, default=4096,
                    help="simulated members (>=4096 for the certified record)")
    ap.add_argument("--trials", type=int, default=128,
                    help="liveness-certification trials (Wilson interval)")
    ap.add_argument("--loadgen-s", type=float, default=4.0)
    ap.add_argument("--min-ops", type=float, default=1000.0,
                    help="member-facing ops/s floor")
    ap.add_argument("--scrape-slo-ms", type=float, default=None,
                    help="scrape p99 budget (default 250, +350 at N>1024 — "
                         "one mega-window collision)")
    ap.add_argument("--liveness-floor", type=float, default=0.95)
    ap.add_argument("--converge-s", type=float, default=60.0)
    ap.add_argument("--overhead-n", type=int, default=512)
    ap.add_argument("--overhead-budget", type=float, default=1.5,
                    help="armed-idle / plain median window ratio budget")
    ap.add_argument("--reps", type=int, default=30)
    ap.add_argument("--skip-overhead", action="store_true")
    ap.add_argument("--quick", action="store_true",
                    help="512-member smoke (never a certified record)")
    ap.add_argument("--out", default=str(REPO / "SERVE_BENCH_r19.json"))
    args = ap.parse_args()
    if args.quick:
        args.n = min(args.n, 512)
        args.trials = min(args.trials, 24)
        args.loadgen_s = min(args.loadgen_s, 2.0)
        args.reps = min(args.reps, 12)
        # 24 trials cap the Wilson lower bound at ~0.86 even when all green
        args.liveness_floor = min(args.liveness_floor, 0.8)
    if args.scrape_slo_ms is None:
        args.scrape_slo_ms = 250.0 if args.n <= 1024 else 600.0

    t_start = time.time()
    artifact = {
        "config": "config18_serve",
        "backend": jax.default_backend(),
        "host_cpus": os.cpu_count(),
        "quick": bool(args.quick),
    }

    async def run():
        await hybrid_sections(args, artifact)
        if not args.skip_overhead:
            await overhead_section(args, artifact)

    asyncio.run(run())

    gates = [artifact.get(k, {}).get("ok") for k in
             ("hybrid_join", "loadgen", "liveness")]
    if not args.skip_overhead:
        gates.append(artifact.get("armed_idle_overhead", {}).get("ok"))
    artifact["elapsed_s"] = round(time.time() - t_start, 2)
    artifact["ok"] = all(bool(g) for g in gates)
    emit(artifact)
    with open(args.out, "w") as fh:
        json.dump(artifact, fh, indent=1)
    log(f"[serve] wrote {args.out} ok={artifact['ok']}")
    return 0 if artifact["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
