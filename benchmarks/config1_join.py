"""Driver config #1: 3-node Alice/Bob/Carol joinAwait over loopback transport.

The reference quick-start (README.md:22-37): Alice starts, Bob and Carol
join via Alice as seed, everyone sees everyone. Runs the REAL scalar
protocol engine (asyncio event loops, memory transport) — functional parity,
not simulation. Reports time-to-full-membership.
"""

from __future__ import annotations

import pathlib as _p
import sys as _s

_s.path.insert(0, str(_p.Path(__file__).parent))          # for common.py
_s.path.insert(0, str(_p.Path(__file__).parent.parent))   # for the package

import asyncio
import time

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.transport import MemoryTransportRegistry


from common import emit, log


async def run() -> dict:
    MemoryTransportRegistry.reset_default()
    cfg = ClusterConfig.default_local()
    t0 = time.perf_counter()
    alice = await new_cluster(cfg.replace(member_alias="Alice")).start()
    bob = await new_cluster(
        cfg.replace(member_alias="Bob").with_membership(
            lambda m: m.replace(seed_members=(alice.address,))
        )
    ).start()
    carol = await new_cluster(
        cfg.replace(member_alias="Carol").with_membership(
            lambda m: m.replace(seed_members=(alice.address,))
        )
    ).start()
    deadline = time.perf_counter() + 10.0
    while time.perf_counter() < deadline:
        if all(len(c.members()) == 3 for c in (alice, bob, carol)):
            break
        await asyncio.sleep(0.02)
    join_time = time.perf_counter() - t0
    ok = all(len(c.members()) == 3 for c in (alice, bob, carol))
    for c in (alice, bob, carol):
        await c.shutdown()
    return {"ok": ok, "join_seconds": round(join_time, 3)}


def main() -> None:
    result = asyncio.run(run())
    log(f"3-node join: {result}")
    emit({"config": 1, "metric": "three_node_join_seconds",
          "value": result["join_seconds"], "ok": result["ok"]})


if __name__ == "__main__":
    main()
