"""Driver config #12: dissemination strategy zoo — certified spread curves.

The r13 acceptance gate: for every (strategy x topology) in the matrix,
measure the rumor spread-time distribution (ticks from injection to 100%
up-member coverage, over seeds, full SWIM tick running, zero loss) and
certify the worst seed against the cited theory bound with explicit
constants (``dissemination/certify.py``'s table — Pittel '87 push,
Karp et al. push-pull, arXiv:1504.03277 pipelined steady state,
arXiv:1311.2839 / arXiv:1805.08531 deterministic doubling schedules).
The ring's LINEAR class is certified from below too — the comparative
content ("expander log, ring linear") is asserted, not eyeballed.

Also records a strategy-armed throughput control: the DEFAULT spec must
trace the byte-identical program, so its ticks/s is the r11 dense arm's
number (any drift here means the strategy seam touched the default
path).

    python benchmarks/config12_strategies.py [--n 256] [--seeds 5]
        [--quick] [--strategy S --topology T] [--engine dense|pview]
        [--control-n 4096] [--no-control] [--out STRATEGY_BENCH_r13.json]

One JSON line on stdout (collect_results harvests it); ``--out`` writes
the full artifact with per-entry coverage curves.
"""

from __future__ import annotations

import argparse
import json
import pathlib as _p
import sys as _s
import time

_s.path.insert(0, str(_p.Path(__file__).parent))          # for common.py
_s.path.insert(0, str(_p.Path(__file__).parent.parent))   # for the package

from common import emit, log

#: --quick certification subset (still >= 3 strategies x >= 3 topologies)
QUICK_MATRIX = (
    ("push", "full", "dense"),
    ("push", "ring", "dense"),
    ("push", "expander", "dense"),
    ("push_pull", "full", "dense"),
    ("push_pull", "expander", "dense"),
    ("pipelined", "ring", "dense"),
    ("pipelined", "expander", "dense"),
    ("accelerated", "ring", "dense"),
    ("accelerated", "expander", "dense"),
    # r14 fifth strategy: the robust/tuneable family (arXiv:1506.02288)
    ("tuneable", "expander", "dense"),
    ("push", "expander", "pview"),
)


def _throughput_control(n: int) -> dict:
    """Default-spec dense ticks/s (one rumor round through the sweep
    window) — the program-identity control: params carry the DEFAULT
    DissemSpec, so this must reproduce the r11 dense arm's number."""
    import jax
    import numpy as np

    import scalecube_cluster_tpu.ops.state as S
    from scalecube_cluster_tpu.ops.kernel import make_run
    from scalecube_cluster_tpu.utils.cluster_math import gossip_periods_to_sweep

    params = S.SimParams(
        capacity=n, fanout=3, repeat_mult=3, ping_req_k=3, fd_every=5,
        sync_every=150, suspicion_mult=5, rumor_slots=8, seed_rows=(0,),
        full_metrics=False,
    )
    budget = gossip_periods_to_sweep(params.repeat_mult, n)
    state = S.init_state(params, n, warm=True)
    step = make_run(params, budget)
    key = jax.random.PRNGKey(0)
    state = S.spread_rumor(state, 0, origin=0)
    state, key, _ms, _w = step(state, key)  # compile + warm
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    state = S.spread_rumor(state, 0, origin=97)
    state, key, ms, _w = step(state, key)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    cov = np.asarray(ms["rumor_coverage"])[:, 0]
    assert (cov >= 1.0).any(), f"control N={n}: no convergence in {budget}"
    # backend is part of the record: the trajectory fold compares rounds,
    # and a CPU-measured control must not read as a TPU regression
    return {"n": n, "ticks_per_s": round(budget / dt, 2),
            "backend": jax.default_backend()}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256,
                    help="member count of the certification runs")
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--fanout", type=int, default=3)
    ap.add_argument("--rumor-slots", type=int, default=8)
    ap.add_argument("--quick", action="store_true",
                    help="2 seeds + the pruned matrix")
    ap.add_argument("--strategy", default=None,
                    help="single-combo mode (bench.py --strategy delegate)")
    ap.add_argument("--topology", default=None)
    ap.add_argument("--engine", default="dense", choices=("dense", "pview"))
    ap.add_argument("--control-n", type=int, default=4096,
                    help="size of the default-spec throughput control")
    ap.add_argument("--no-control", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    # backend probe + bounded retry (bench.py's r6 path): a wedged tunnel
    # must leave a structured failure artifact, not a hang
    from bench import emit_failure, probe_backend

    ok, attempts = probe_backend()
    if not ok:
        emit_failure("backend_probe", 1, attempts, "config12 probe failed")
        raise SystemExit(1)

    from scalecube_cluster_tpu.dissemination.certify import (
        DEFAULT_MATRIX,
        spread_certifier,
    )

    if args.strategy or args.topology:
        matrix = ((args.strategy or "push", args.topology or "full",
                   args.engine),)
    elif args.quick:
        matrix = QUICK_MATRIX
    else:
        matrix = DEFAULT_MATRIX
    seeds = tuple(range(2 if args.quick else args.seeds))

    t0 = time.perf_counter()
    record = spread_certifier(
        matrix=matrix, n=args.n, seeds=seeds, fanout=args.fanout,
        rumor_slots=args.rumor_slots, log=log,
    )
    record["wall_seconds"] = round(time.perf_counter() - t0, 1)
    record["config"] = "config12_strategies"
    if not args.no_control:
        try:
            record["default_spec_control"] = _throughput_control(args.control_n)
            log(f"default-spec control: {record['default_spec_control']}")
        except Exception as exc:  # noqa: BLE001 — control is advisory
            record["default_spec_control"] = {"error": repr(exc)}

    if args.out:
        out = _p.Path(args.out)
        with open(out, "w") as f:
            json.dump({"config": "config12_strategies", "result": record}, f,
                      indent=1)
        log(f"wrote {out}")

    # one stdout JSON line, curves elided (they live in --out)
    emit({
        "metric": "strategy_spread_certified",
        "value": record["n_certified"],
        "unit": "combos",
        "n_entries": record["n_entries"],
        "ok": record["ok"],
        "certified_strategies": record["certified_strategies"],
        "certified_topologies": record["certified_topologies"],
        "pipeline_steady_state_ok": (
            record["pipeline_steady_state"]["certified"]
            if record["pipeline_steady_state"] is not None
            else None  # matrix had no pipelined entry (single-combo mode)
        ),
        "default_spec_control": record.get("default_spec_control"),
        "wall_seconds": record["wall_seconds"],
    })
    if not record["ok"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
