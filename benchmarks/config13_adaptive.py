"""Driver config #13: adaptive failure detection — false-positive certification.

The r14 acceptance gate: under the loss-adversarial chaos family
(``AsymmetricLoss`` starving a cohort's inbound links, a ``FlakyObserver``
spraying failed probes, a ``SlowMember`` on delay rings) swept over
ambient uniform-loss floors, the ADAPTIVE failure-detection plane
(Lifeguard-style local health + confirmation-scaled suspicion,
``adaptive.py``) must record ZERO false-DEAD verdicts about the
degraded-but-alive cohort while the STATIC-timeout control records >0 —
and the one TRUE crash in every scenario must still be detected within
the EXISTING chaos sentinel budget (the static protocol math; the
adaptive plane never gets extra detection slack).

Both arms run the same scenarios through ``SimDriver.run_scenario`` with
the r14 false-positive sentinel watching the degraded cohort
(``fp_enforce=False`` on the control arm: its violations are RECORDED,
documented, and expected — not hidden, not fatal).

    python benchmarks/config13_adaptive.py [--n 48] [--seeds 3] [--quick]
        [--loss-floors 0,10,20] [--out ADAPTIVE_BENCH_r14.json]

One JSON line on stdout (collect_results harvests it); ``--out`` writes
the full artifact with per-entry reports.
"""

from __future__ import annotations

import argparse
import json
import pathlib as _p
import sys as _s
import time

_s.path.insert(0, str(_p.Path(__file__).parent))          # for common.py
_s.path.insert(0, str(_p.Path(__file__).parent.parent))   # for the package

from common import emit, log

#: knobs of the comparison — chosen so the static control sits right at
#: the refutation race (suspicion window ~= refute dissemination window)
#: while the adaptive floor (min_mult) always lets refutes win; see
#: docs/ADAPTIVE_FD.md "knob guidance"
STATIC_SUSPICION_MULT = 3
ADAPTIVE_KNOBS = dict(min_mult=5, max_mult=10, conf_target=4, lh_max=8)


def _scenario(n: int, until: int, horizon: int):
    from scalecube_cluster_tpu.chaos import events as ev

    degraded = dict(
        asym_rows=[5, 6, 7], flaky_rows=[9], slow_rows=[11], crash_row=20,
    )
    scen = ev.Scenario(
        name="loss_adversarial_r14",
        events=(
            ev.AsymmetricLoss(rows=degraded["asym_rows"], pct=70.0, at=4,
                              until=until, direction="in"),
            ev.FlakyObserver(rows=degraded["flaky_rows"], pct=70.0, at=4,
                             until=until),
            ev.SlowMember(rows=degraded["slow_rows"], mean_delay_ticks=2.0,
                          at=4, until=until),
            ev.Crash(rows=[degraded["crash_row"]], at=30),
        ),
        horizon=horizon,
    )
    return scen, degraded


def run_entry(n: int, seed: int, loss_floor: float, adaptive: bool,
              until: int = 220, horizon: int = 260) -> dict:
    """One (seed, loss floor, arm) scenario run; returns the folded record."""
    from scalecube_cluster_tpu.adaptive import AdaptiveSpec
    from scalecube_cluster_tpu.ops.state import SimParams
    from scalecube_cluster_tpu.sim.driver import SimDriver

    spec = (
        AdaptiveSpec(enabled=True, **ADAPTIVE_KNOBS)
        if adaptive
        else AdaptiveSpec()
    )
    params = SimParams(
        capacity=n, fd_every=1, sync_every=40,
        suspicion_mult=STATIC_SUSPICION_MULT, rumor_slots=8, seed_rows=(0,),
        delay_slots=4, adaptive=spec,
    )
    d = SimDriver(params, n, warm=True, seed=seed)
    if loss_floor > 0:
        d.state = d._ops.set_uniform_loss(d.state, loss_floor, floor=True)
    scen, _deg = _scenario(n, until, horizon)
    if not adaptive:
        scen = scen.replace(fp_enforce=False)  # control arm: record, don't judge
    t0 = time.perf_counter()
    rep = d.run_scenario(scen)
    s = rep["sentinels"]
    det = s["detections"][0]
    return {
        "arm": "adaptive" if adaptive else "static",
        "seed": seed,
        "loss_floor_pct": round(loss_floor * 100),
        "false_positive_dead_max": s.get("false_positive_dead_max"),
        "fp_watch_members": s.get("false_positive_watch_members"),
        "crash_detected_at": det["detected_at"],
        "crash_deadline": det["deadline"],
        "crash_ok": det["ok"],
        "violations": rep["violations"],
        "wall_seconds": round(time.perf_counter() - t0, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=48)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--loss-floors", default="0,10,20",
                    help="comma list of ambient uniform-loss floors, percent")
    ap.add_argument("--quick", action="store_true",
                    help="2 seeds x 2 loss floors")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from bench import emit_failure, probe_backend

    ok, attempts = probe_backend()
    if not ok:
        emit_failure("backend_probe", 1, attempts, "config13 probe failed")
        raise SystemExit(1)

    floors = [float(x) / 100.0 for x in args.loss_floors.split(",")]
    seeds = list(range(args.seeds))
    if args.quick:
        floors = floors[:2]
        seeds = seeds[:2]

    t0 = time.perf_counter()
    entries = []
    for floor in floors:
        for seed in seeds:
            for adaptive in (False, True):
                rec = run_entry(args.n, seed, floor, adaptive)
                entries.append(rec)
                log(
                    f"loss={rec['loss_floor_pct']}% seed={seed} "
                    f"{rec['arm']}: fp_dead={rec['false_positive_dead_max']} "
                    f"crash@{rec['crash_detected_at']}"
                    f"<= {rec['crash_deadline']} "
                    f"violations={rec['violations']}"
                )

    adaptive_entries = [e for e in entries if e["arm"] == "adaptive"]
    static_entries = [e for e in entries if e["arm"] == "static"]
    adaptive_fp = sum(e["false_positive_dead_max"] or 0 for e in adaptive_entries)
    static_fp = sum(e["false_positive_dead_max"] or 0 for e in static_entries)
    # the certification: adaptive FP identically zero across the sweep,
    # the static control demonstrably fallible (>0 somewhere), every
    # adaptive crash detection inside the EXISTING budget, zero violations
    certified = (
        adaptive_fp == 0
        and static_fp > 0
        and all(e["crash_ok"] for e in adaptive_entries)
        and all(e["violations"] == 0 for e in adaptive_entries)
    )
    record = {
        "config": "config13_adaptive",
        "n": args.n,
        "seeds": seeds,
        "loss_floors_pct": [round(f * 100) for f in floors],
        "static_suspicion_mult": STATIC_SUSPICION_MULT,
        "adaptive_knobs": ADAPTIVE_KNOBS,
        "entries": entries,
        "adaptive_false_dead_total": adaptive_fp,
        "static_false_dead_total": static_fp,
        "adaptive_detections_ok": all(e["crash_ok"] for e in adaptive_entries),
        "certified": certified,
        "wall_seconds": round(time.perf_counter() - t0, 1),
    }
    import jax

    record["backend"] = jax.default_backend()

    if args.out:
        out = _p.Path(args.out)
        with open(out, "w") as f:
            json.dump({"config": "config13_adaptive", "result": record}, f,
                      indent=1)
        log(f"wrote {out}")

    emit({
        "metric": "adaptive_fd_certified",
        "value": int(certified),
        "unit": "bool",
        "adaptive_false_dead_total": adaptive_fp,
        "static_false_dead_total": static_fp,
        "adaptive_detections_ok": record["adaptive_detections_ok"],
        "n_entries": len(entries),
        "backend": record["backend"],
        "wall_seconds": record["wall_seconds"],
    })
    if not certified:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
