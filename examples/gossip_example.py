"""Spread a rumor to every member via infection-style gossip
(GossipExample.java)."""

import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models.message import Message


async def main() -> None:
    cfg = ClusterConfig.default_local()
    alice = await new_cluster(cfg.replace(member_alias="Alice")).start()
    join = cfg.with_membership(lambda m: m.replace(seed_members=(alice.address,)))

    members = [alice]
    for name in ("Bob", "Carol", "Dave", "Eve"):
        node = await new_cluster(join.replace(member_alias=name)).start()
        node.listen_gossip().subscribe(
            lambda msg, who=name: print(f"[{who}] heard gossip: {msg.data!r}")
        )
        members.append(node)
    await asyncio.sleep(1.0)

    await alice.spread_gossip(Message.with_data("Joe Dirt", qualifier="gossip/example"))
    await asyncio.sleep(2.0)

    for node in members:
        await node.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
