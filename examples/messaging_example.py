"""Point-to-point messaging between members: fire-and-forget send and
correlated request/response (MessagingExample.java)."""

import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models.message import Message


async def main() -> None:
    cfg = ClusterConfig.default_local()
    ping_pong_count = 3

    pong_side = await new_cluster(cfg.replace(member_alias="Pong")).start()

    def on_message(msg: Message) -> None:
        if msg.qualifier == "ping":
            print(f"Pong got {msg.data!r}, replying")
            reply = Message.with_data("pong!", qualifier="pong", cid=msg.correlation_id)
            asyncio.ensure_future(pong_side.send(msg.sender, reply))

    pong_side.listen_messages().subscribe(on_message)

    ping_side = await new_cluster(
        cfg.replace(member_alias="Ping").with_membership(
            lambda m: m.replace(seed_members=(pong_side.address,))
        )
    ).start()
    await asyncio.sleep(0.5)

    target = ping_side.other_members()[0]
    for i in range(ping_pong_count):
        resp = await ping_side.request_response(
            target, Message.with_data(f"ping #{i}", qualifier="ping")
        )
        print(f"Ping got {resp.data!r}")

    await ping_side.shutdown()
    await pong_side.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
