"""Dissemination strategy zoo (r13): compare spread curves on two topologies.

Runs one rumor to full coverage at N=512 under three strategies on the
expander overlay and two on the ring, printing each curve (coverage per
tick) plus its certified theory bound — the log-vs-linear gap between
the topologies and the deterministic schedules' tight constants are the
point of the exercise.

    JAX_PLATFORMS=cpu python examples/strategy_example.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scalecube_cluster_tpu.dissemination import DissemSpec
from scalecube_cluster_tpu.dissemination.certify import (
    certify_spread,
    measure_spread,
)

N = 512
COMBOS = [
    ("push", "expander"),
    ("push_pull", "expander"),
    ("accelerated", "expander"),
    ("push", "ring"),
    ("accelerated", "ring"),
]


def sparkline(curve, width: int = 48) -> str:
    blocks = " ▁▂▃▄▅▆▇█"
    if len(curve) > width:
        stride = -(-len(curve) // width)
        curve = curve[::stride]
    return "".join(blocks[min(int(c * (len(blocks) - 1)), len(blocks) - 1)]
                   for c in curve)


def main() -> None:
    print(f"rumor spread at N={N}, fanout 3, zero loss (1 seed each):\n")
    for strategy, topology in COMBOS:
        spec = DissemSpec(strategy=strategy, topology=topology)
        rec = certify_spread(measure_spread(spec, n=N, seeds=(0,)))
        t = rec["spread_ticks"][0]
        shown = "inc." if t is None else f"{t:>4}"  # None = never full
        mark = "OK " if rec["certified"] else "VIOLATION"
        lower = (f", >= {rec['lower_bound_ticks']} (certified linear)"
                 if rec["lower_bound_ticks"] else "")
        print(f"{strategy:>12} x {topology:<9} {shown} ticks  "
              f"<= bound {rec['bound_ticks']}{lower}  [{mark}]")
        print(f"{'':>12}   {sparkline(rec['coverage_curves'][0])}\n")
    print("expander spreads in O(log N) rounds; the ring is a linear")
    print("wavefront — and the accelerated doubling schedule hits its")
    print("deterministic bound with almost no slack on both.")


if __name__ == "__main__":
    main()
