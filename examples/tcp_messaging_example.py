"""Messaging over the second wire transport — real TCP sockets instead of
the in-process memory transport (the reference demonstrates transport
plurality with WebsocketMessagingExample; here the alternate wire is TCP)."""

import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models.message import Message


async def main() -> None:
    cfg = ClusterConfig.default_local().with_transport(
        lambda t: t.replace(transport_factory="tcp")
    )
    server = await new_cluster(cfg.replace(member_alias="server")).start()
    print(f"server on real socket {server.address}")

    def on_message(msg: Message) -> None:
        if msg.qualifier == "hello":
            reply = Message.with_data("world", qualifier="hello/ack", cid=msg.correlation_id)
            asyncio.ensure_future(server.send(msg.sender, reply))

    server.listen_messages().subscribe(on_message)

    client = await new_cluster(
        cfg.replace(member_alias="client").with_membership(
            lambda m: m.replace(seed_members=(server.address,))
        )
    ).start()
    await asyncio.sleep(1.0)
    resp = await client.request_response(
        client.other_members()[0], Message.with_data("hello", qualifier="hello")
    )
    print(f"client got {resp.data!r} over TCP")
    await client.shutdown()
    await server.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
