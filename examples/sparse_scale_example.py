"""TPU-native addition: the SPARSE (record-queue) engine at 16k members —
the same facade surface as every other example, driven by the large-N
kernel (membership changes as a bounded rumor pool; see
``scalecube_cluster_tpu/ops/sparse.py``). Passing a ``SparseParams`` to
``SimDriver`` is the entire engine switch."""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.ops.sparse import SparseParams
from scalecube_cluster_tpu.sim import SimCluster, SimDriver


def main() -> None:
    params = SparseParams(
        capacity=16_384, fanout=3, repeat_mult=3, ping_req_k=3, fd_every=5,
        sync_every=150, suspicion_mult=5, rumor_slots=8,
        mr_slots=2048, announce_slots=512, seed_rows=(0, 1),
    )
    driver = SimDriver(params, n_initial=16_000, warm=True, seed=0)
    cluster = SimCluster(driver)

    observer = cluster.node(0)
    observer.listen_membership().subscribe(
        lambda ev: print(f"[node0] {ev.type.name}: {ev.member.id}")
    )

    print(f"{len(observer.members())} members up")
    slot = cluster.node(7).spread_gossip("big announcement")
    driver.run_until(lambda d: d.rumor_coverage(slot) >= 1.0, max_ticks=120)
    print(f"rumor reached all {int(driver.state.up.sum())} members "
          f"by tick {driver.tick}")

    victim = 123
    cluster.node(victim).crash()
    print(f"node {victim} crashed; waiting for SWIM to notice...")
    driver.step(600)  # suspicion timeout + dissemination
    status = driver.status_of(0, victim)
    print(f"node0 now sees node{victim} as {status.name if status else None}")

    row = driver.join(seed_rows=[0, 1])
    driver.step(100)
    print(f"fresh member joined at row {row}; node0 sees "
          f"{len(observer.members())} members")


if __name__ == "__main__":
    main()
