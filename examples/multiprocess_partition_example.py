"""Multi-process partition repro over real TCP — the issue-187 analogue.

The reference ships shell scripts that run a seed + nodes as separate OS
processes and block one node's traffic with iptables, then watch SUSPECT →
REMOVED and the rejoin-as-new-id flow
(``/root/reference/examples/scripts/issues/187/README:1-8``). This script is
that repro for the TPU-native framework's REAL transport path: three OS
processes, each an asyncio `Cluster` over genuine TCP sockets, the
"iptables" role played by the `NetworkEmulatorTransport` seam (block all
inbound+outbound on the victim), asserting at the survivors:

1. all three members see each other (full TCP join);
2. after the block, survivors SUSPECT then REMOVE the victim within the
   suspicion timeout;
3. a fresh process joining from the victim's machine arrives as a NEW
   member id (restart = new identity, `FailureDetectorTest.java:393-401`).

Run: ``python examples/multiprocess_partition_example.py`` (exits 0 on
success, ~20 s; also wrapped by ``tests/test_multiprocess_tcp.py``).

Child protocol (stdin/stdout JSON lines): parent sends {"cmd": "block"|
"unblock"|"members"|"exit"}; children emit {"event": ...} lines for ready,
membership events, and command acks.
"""

from __future__ import annotations

import asyncio
import json
import os
import pathlib
import subprocess
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

TIMINGS = dict(
    ping_interval=0.3, ping_timeout=0.12, gossip_interval=0.1,
    sync_interval=2.0, suspicion_mult=3,
)


def _config(seed=None, alias="node"):
    from scalecube_cluster_tpu.config import ClusterConfig

    cfg = (
        ClusterConfig.default_local()
        .with_failure_detector(
            lambda f: f.replace(
                ping_interval=TIMINGS["ping_interval"],
                ping_timeout=TIMINGS["ping_timeout"],
                ping_req_members=1,
            )
        )
        .with_gossip(lambda g: g.replace(gossip_interval=TIMINGS["gossip_interval"]))
        .with_membership(
            lambda m: m.replace(
                sync_interval=TIMINGS["sync_interval"],
                suspicion_mult=TIMINGS["suspicion_mult"],
                seed_members=(seed,) if seed else (),
            )
        )
        .with_transport(lambda t: t.replace(transport_factory="tcp", port=0))
        .replace(member_alias=alias)
    )
    return cfg


async def child_main(seed: str | None, alias: str) -> None:
    """One cluster node in its own OS process, TCP transport wrapped in the
    emulator seam, driven by JSON commands on stdin."""
    from scalecube_cluster_tpu.cluster import new_cluster
    from scalecube_cluster_tpu.transport.api import TransportConfig, create_transport
    from scalecube_cluster_tpu.transport.emulator import NetworkEmulatorTransport

    emu_holder = {}

    def transport_factory():
        raw = create_transport(TransportConfig(port=0, transport_factory="tcp"))
        wrapped = NetworkEmulatorTransport(raw)
        emu_holder["emu"] = wrapped.network_emulator
        return wrapped

    cluster = new_cluster(_config(seed, alias)).transport_factory(transport_factory)
    cluster = await cluster.start()

    def out(obj):
        print(json.dumps(obj), flush=True)

    def on_event(ev):
        out({
            "event": "membership",
            "type": ev.type.value,
            "member": ev.member.id,
            "alias": ev.member.alias,
        })

    cluster.listen_membership().subscribe(on_event)
    out({"event": "ready", "address": cluster.address, "id": cluster.member().id})

    loop = asyncio.get_running_loop()
    reader = asyncio.StreamReader()
    await loop.connect_read_pipe(
        lambda: asyncio.StreamReaderProtocol(reader), sys.stdin
    )
    while True:
        line = await reader.readline()
        if not line:
            break
        cmd = json.loads(line)["cmd"]
        if cmd == "block":
            emu = emu_holder["emu"]
            emu.block_all_outbound()
            emu.block_all_inbound()
            out({"event": "ack", "cmd": "block"})
        elif cmd == "unblock":
            emu = emu_holder["emu"]
            emu.unblock_all_outbound()
            emu.unblock_all_inbound()
            out({"event": "ack", "cmd": "unblock"})
        elif cmd == "members":
            out({
                "event": "members",
                "ids": sorted(m.id for m in cluster.members()),
                "aliases": sorted(str(m.alias) for m in cluster.members()),
            })
        elif cmd == "exit":
            out({"event": "ack", "cmd": "exit"})
            break
    await cluster.shutdown()


class Node:
    def __init__(self, seed: str | None, alias: str):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        self.proc = subprocess.Popen(
            [sys.executable, __file__, "--child", alias] + ([seed] if seed else []),
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
        )
        self.alias = alias
        self.events: list[dict] = []
        self._buf = b""
        ready = self._read_until(lambda o: o.get("event") == "ready", 30)
        self.address = ready["address"]
        self.id = ready["id"]

    def send(self, cmd: str) -> None:
        self.proc.stdin.write((json.dumps({"cmd": cmd}) + "\n").encode())
        self.proc.stdin.flush()

    def _read_until(self, pred, timeout: float):
        # raw-fd line reader with a real deadline: selecting on the fd and
        # THEN readline() would deadlock when Python's buffer already holds
        # lines, and bare readline() would block forever on a hung child
        import select

        fd = self.proc.stdout.fileno()
        deadline = time.time() + timeout
        while True:
            while b"\n" in self._buf:
                line, self._buf = self._buf.split(b"\n", 1)
                if not line.strip():
                    continue
                obj = json.loads(line)
                self.events.append(obj)
                if pred(obj):
                    return obj
            remaining = deadline - time.time()
            if remaining <= 0:
                raise TimeoutError(f"{self.alias}: timeout waiting for condition")
            ready, _, _ = select.select([fd], [], [], remaining)
            if not ready:
                raise TimeoutError(f"{self.alias}: timeout waiting for condition")
            chunk = os.read(fd, 65536)
            if not chunk:
                raise RuntimeError(f"{self.alias}: child exited early")
            self._buf += chunk

    def wait_event(self, etype: str, member_id: str | None = None, timeout=30.0):
        def pred(o):
            return (
                o.get("event") == "membership"
                and o.get("type") == etype
                and (member_id is None or o.get("member") == member_id)
            )

        for o in self.events:  # already seen?
            if pred(o):
                return o
        return self._read_until(pred, timeout)

    def members(self, timeout=10.0):
        self.send("members")
        return self._read_until(lambda o: o.get("event") == "members", timeout)

    def stop(self):
        try:
            self.send("exit")
            self.proc.wait(timeout=10)
        except Exception:
            self.proc.kill()


def main() -> int:
    print("== starting 3-process TCP cluster", flush=True)
    nodes: list[Node] = []

    def track(n: Node) -> Node:
        nodes.append(n)
        return n

    seed = track(Node(None, "alice"))
    bob = track(Node(seed.address, "bob"))
    carol = track(Node(seed.address, "carol"))
    try:
        seed.wait_event("added", bob.id)
        seed.wait_event("added", carol.id)
        bob.wait_event("added", carol.id)
        assert set(seed.members(timeout=15)["ids"]) == {seed.id, bob.id, carol.id}
        print(f"== full join over real TCP: {seed.id}, {bob.id}, {carol.id}",
              flush=True)

        print("== blocking carol at the transport seam (issue-187 analogue)",
              flush=True)
        carol.send("block")
        t0 = time.time()
        seed.wait_event("removed", carol.id, timeout=60)
        bob.wait_event("removed", carol.id, timeout=60)
        print(f"== survivors removed carol after {time.time()-t0:.1f}s "
              f"(SUSPECT -> suspicion timeout -> REMOVED)", flush=True)
        assert carol.id not in seed.members()["ids"]

        print("== rejoining from a fresh process", flush=True)
        carol.stop()
        carol2 = track(Node(seed.address, "carol"))
        seed.wait_event("added", carol2.id, timeout=30)
        assert carol2.id != carol.id, "restart must join as a NEW member id"
        print(f"== rejoined as NEW id {carol2.id} (old {carol.id})", flush=True)
        print("== PASS", flush=True)
        return 0
    finally:
        # stop EVERY child (incl. carol/carol2 on mid-test failures) so a
        # failing run never orphans cluster processes with open TCP ports
        for n in nodes:
            n.stop()


if __name__ == "__main__":
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        alias = sys.argv[i + 1]
        seed = sys.argv[i + 2] if len(sys.argv) > i + 2 else None
        asyncio.run(child_main(seed, alias))
    else:
        sys.exit(main())
