"""Plug a custom metadata codec into the registry
(CustomMetadataEncodingExample.java — the reference registers a custom
MetadataCodec through META-INF/services; here it's the codec registry)."""

import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.transport.codecs import (
    MetadataCodec,
    register_metadata_codec,
)


class CsvMetadataCodec(MetadataCodec):
    """Encodes a dict as 'k=v,k=v' — deliberately minimal wire format."""

    def serialize(self, metadata) -> bytes:
        return ",".join(f"{k}={v}" for k, v in sorted(metadata.items())).encode()

    def deserialize(self, payload: bytes):
        return dict(kv.split("=", 1) for kv in payload.decode().split(",") if kv)


async def main() -> None:
    register_metadata_codec("csv", CsvMetadataCodec())
    cfg = ClusterConfig.default_local().replace(metadata_codec="csv")

    a = await new_cluster(cfg.replace(member_alias="A", metadata={"role": "seed"})).start()
    b = await new_cluster(
        cfg.replace(member_alias="B", metadata={"role": "worker"}).with_membership(
            lambda m: m.replace(seed_members=(a.address,))
        )
    ).start()
    await asyncio.sleep(1.0)
    print("A sees B's metadata:", a.metadata_of(a.member_by_id(b.member().id)))
    print("B sees A's metadata:", b.metadata_of(b.member_by_id(a.member().id)))
    await b.shutdown()
    await a.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
