"""The O(N·k) partial-view engine end to end (r11, ops/pview.py).

Runs a 4096-member cluster on the pview engine — per-member state is a
k-slot neighbor table + the bounded rumor pools, no [N, N] plane anywhere
(the same budget that fits one MILLION members in a single 16 GiB window;
PVIEW_BENCH_r11.json) — through the full r6-r10 surface: donated
double-buffered stepping, telemetry + trace planes armed, a chaos
Partition + Crash + heal scenario with every sentinel green, and a
checkpoint/restore roundtrip. Everything below is the same driver API the
dense and sparse engines use; only the params class differs."""

import json
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.chaos import Crash, Partition, Scenario
from scalecube_cluster_tpu.config import TelemetryConfig
from scalecube_cluster_tpu.ops.pview import PviewParams
from scalecube_cluster_tpu.sim import SimDriver


def main() -> None:
    n = 4096
    params = PviewParams(
        capacity=n,
        view_slots=24,      # k: the whole protocol-visible world per member
        active_slots=8,     # ka: FD/gossip/SYNC sample from these slots
        fanout=3, repeat_mult=3, ping_req_k=3, fd_every=5, sync_every=40,
        suspicion_mult=3, rumor_slots=4, seed_rows=(0, 2048),
    )
    driver = SimDriver(params, n_initial=n, warm=True, seed=0)
    print(f"engine: {driver.engine}  (no [N, N] plane; "
          f"tables are [{n}, {params.view_slots}])")

    # the full observability surface arms exactly like dense/sparse
    driver.arm_telemetry(TelemetryConfig(ring_len=64))
    driver.arm_trace(tracer_rows=(42,), rumor_slots=(0,))

    # a rumor spreads over sampled fanout edges — O(log N) rounds still
    # (the scattered warm overlay: ~23 ticks to full coverage at N=4096)
    slot = driver.spread_rumor(origin=7, payload="partial-view hello")
    driver.step(30)
    print(f"rumor coverage after 30 ticks: {driver.rumor_coverage(slot):.3f}")

    # chaos: split the cluster, crash a member, heal — the sentinels
    # certify bounded detection, no false-DEAD, and that the tombstone
    # purge + seed-SYNC cadence re-converge the halves inside the budget
    scenario = Scenario(
        name="pview-split-heal",
        events=[
            Crash(rows=[42], at=20),
            Partition(
                groups=[range(0, n // 2), range(n // 2, n)],
                at=60, heal_at=220,
            ),
        ],
        horizon=1400,
        check_interval=16,
    )
    report = driver.run_scenario(scenario)
    print(json.dumps(
        {k: report["sentinels"][k] for k in
         ("false_dead_members_max", "key_regressions",
          "view_invariant_breaks", "violations")},
        indent=1,
    ))
    print("scenario ok:", report["ok"])
    print("detection:", report["sentinels"]["detections"])

    # checkpoint/restore: the engine name travels in the archive and the
    # restore path deep-copies (donation-safe)
    with tempfile.TemporaryDirectory() as tmp:
        path = str(pathlib.Path(tmp) / "pview.npz")
        driver.checkpoint(path)
        d2 = SimDriver(params, n_initial=n, warm=True, seed=1)
        d2.restore(path)
        d2.step(10)
        print(f"restored driver stepped to tick {d2.tick}")


if __name__ == "__main__":
    main()
