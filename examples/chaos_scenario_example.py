"""Chaos scenario end to end: a scripted partition→heal→converge timeline
with the on-device invariant sentinels armed (r7 chaos engine).

One declarative :class:`Scenario` splits a 256-member cluster clean in half
long enough for both sides to declare each other dead, heals it, and lets
the sentinels certify the protocol's recovery guarantees: the seed-row SYNC
re-bridges the split, every view re-converges inside the budget, no
never-faulted member is ever tombstoned, and no record key regresses. The
same scenario object runs unmodified on the sparse or mesh-sharded drivers
(and, via ``chaos.EmulatorChaosRunner``, on the scalar/real-transport
engine)."""

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.chaos import Crash, Partition, Restart, Scenario
from scalecube_cluster_tpu.ops.state import SimParams
from scalecube_cluster_tpu.sim import SimDriver


def main() -> None:
    n = 256
    params = SimParams(
        capacity=n, fanout=3, repeat_mult=3, ping_req_k=3, fd_every=5,
        sync_every=40, suspicion_mult=3, rumor_slots=4, seed_rows=(0, 128),
    )
    driver = SimDriver(params, n_initial=n, warm=True, seed=0)

    scenario = Scenario(
        name="split-heal-converge",
        events=[
            # one member hard-crashes first: the detection-latency sentinel
            # must see every survivor tombstone it inside the budget
            Crash(rows=[42], at=20),
            # clean half/half split (everyone is in a group, so re-merge can
            # only happen through the seed rows' SYNC re-bridging)
            Partition(
                groups=[range(0, n // 2), range(n // 2, n)],
                at=100,
                heal_at=450,
            ),
            # the crashed member returns as a FRESH identity after the heal
            Restart(rows=[42], at=900, seed_rows=(0,)),
        ],
        horizon=1800,
    )

    print(f"running '{scenario.name}' on the dense driver (N={n}) ...")
    report = driver.run_scenario(scenario)

    print(f"\nevents applied: "
          f"{[e['event'] for e in report['events_applied']]}")
    sent = report["sentinels"]
    det = sent["detections"][0]
    print(f"crash of row {det['row']} detected by every survivor at tick "
          f"{det['detected_at']} (budget {det['deadline']})")
    for conv in sent["convergence"]:
        print(f"{conv['label']}: re-converged at tick {conv['converged_at']} "
              f"(budget {conv['deadline']})")
    print(f"never-faulted members protected: {sent['never_faulted_members']}, "
          f"false-DEAD: {sent['false_dead_members_max']}, "
          f"key regressions: {sent['key_regressions']}")
    print(f"\nverdict: {'OK' if report['ok'] else 'VIOLATIONS'} "
          f"({report['violations']} violation(s))")
    # the same structured report is served live at GET /chaos once a
    # MonitorServer.register_health(driver) is attached
    print("\nfull report:")
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
