"""Telemetry plane end to end: scrape /metrics DURING a chaos scenario.

A 128-member dense cluster runs a crash + partition/heal scenario with the
telemetry plane armed. While the scenario executes on the sim thread, the
main thread scrapes the monitor's ``GET /metrics`` (Prometheus text) and
``GET /events`` (the unified bus tail) — the observability loop a real
deployment would run, against a simulated cluster. Afterwards a manual
flight-recorder dump is replayed into a human-readable timeline.
"""

import asyncio
import json
import pathlib
import sys
import tempfile
import threading
import urllib.request

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.chaos import Crash, Partition, Restart, Scenario
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.ops.state import SimParams
from scalecube_cluster_tpu.sim import SimDriver
from scalecube_cluster_tpu.telemetry import load_flight_dump, replay_timeline


async def main() -> None:
    n = 128
    params = SimParams(
        capacity=n, fanout=3, repeat_mult=3, ping_req_k=3, fd_every=5,
        sync_every=40, suspicion_mult=3, rumor_slots=4, seed_rows=(0, 64),
    )
    driver = SimDriver(params, n_initial=n, warm=True, seed=0)

    cfg = ClusterConfig.default_sim().with_telemetry(
        lambda t: t.replace(ring_len=256, flight_windows=48,
                            flight_dir=tempfile.gettempdir())
    )
    plane = driver.arm_telemetry(cfg)

    from scalecube_cluster_tpu.monitor import MonitorServer

    server = await MonitorServer().start()
    server.register_telemetry(driver, plane)
    print(f"monitor: {server.url}/metrics  {server.url}/events")

    scenario = Scenario(
        name="crash-split-heal",
        events=[
            Crash(rows=[17], at=20),
            Partition(groups=[range(0, n // 2), range(n // 2, n)],
                      at=80, heal_at=300),
            Restart(rows=[17], at=500, seed_rows=(0,)),
        ],
        horizon=1200,
    )

    report_box = {}
    th = threading.Thread(
        target=lambda: report_box.update(report=driver.run_scenario(scenario))
    )
    th.start()

    loop = asyncio.get_running_loop()

    def scrape(path: str) -> str:
        # generous timeout: a scrape that lands while the sim thread is
        # compiling a fresh window program waits behind that compile (the
        # flush takes the driver lock) — slow once, then sub-ms
        with urllib.request.urlopen(server.url + path, timeout=60) as resp:
            return resp.read().decode()

    while th.is_alive():
        await asyncio.sleep(0.5)
        text = await loop.run_in_executor(None, scrape, "/metrics")
        picks = [
            line for line in text.splitlines()
            if line.startswith(("scalecube_ticks_total",
                                "scalecube_window{"))
            and ("n_up" in line or "fd_new_suspects" in line
                 or "ticks_total" in line)
        ]
        print("scrape:", "; ".join(picks))
    th.join()

    events = json.loads(await loop.run_in_executor(None, scrape, "/events"))
    chaos_events = [e for e in events["events"] if e["source"] == "chaos"]
    print(f"\nbus: {len(events['events'])} records, "
          f"{len(chaos_events)} from chaos, e.g. "
          f"{chaos_events[0]['kind']} .. {chaos_events[-1]['kind']}")

    report = report_box["report"]
    print(f"scenario ok={report['ok']} violations={report['violations']}")

    dump_path = plane.flight_record("example-post-run")
    timeline = replay_timeline(load_flight_dump(dump_path))
    print(f"\nflight dump {dump_path} replays to {len(timeline)} lines; tail:")
    for line in timeline[-8:]:
        print(" ", line)

    await server.stop()


if __name__ == "__main__":
    asyncio.run(main())
