"""Fleet engine (r15): a 1000-seed spread-time histogram in seconds.

One XLA program advances 1000 independent 64-member clusters (the
scenario-batched vmap window, sharded over the local device mesh); the
per-seed ticks-to-full-coverage fold stays on device and comes back as
ONE [S] readback, which this example renders as a histogram against the
Karp et al. push-pull bound (FOCS'00, via arXiv:1504.03277) — the
difference between "5 seeds stayed under the bound" (the r13 spot
check) and "P(within bound) ≥ 0.996 at 95% confidence" (a Monte Carlo
certificate with a Wilson interval).

    JAX_PLATFORMS=cpu python examples/fleet_example.py [seeds]
"""

from __future__ import annotations

import os
import sys

# the scenario mesh is what engages the CPU cores (see docs/FLEET.md) —
# must be set before jax initializes
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

from scalecube_cluster_tpu.dissemination import DissemSpec
from scalecube_cluster_tpu.dissemination.certify import certify_spread_mc

N = 64
SEEDS = int(sys.argv[1]) if len(sys.argv) > 1 else 1000


def main() -> None:
    spec = DissemSpec(strategy="push_pull", topology="full")
    t0 = time.perf_counter()
    rec = certify_spread_mc(spec, n=N, n_seeds=SEEDS)
    dt = time.perf_counter() - t0

    print(f"push_pull/full at N={N}: {SEEDS} seeds in {dt:.1f}s "
          f"({rec['windows_dispatched']} fleet windows over "
          f"{rec['fleet_devices']} device(s))\n")
    hist = {int(k): v for k, v in rec["spread_histogram"].items()}
    peak = max(hist.values())
    for t in range(min(hist), max(hist) + 1):
        c = hist.get(t, 0)
        bar = "█" * max(1, round(c / peak * 50)) if c else ""
        print(f"  {t:3d} ticks | {bar} {c if c else ''}")
    print(f"\n  Karp push-pull bound ({rec['formula']}): "
          f"{rec['bound_ticks']} ticks — {rec['citation']}")
    print(f"  median {rec['spread_ticks_median']} "
          f"(95% CI {rec['median_ci']}), "
          f"p99 {rec['spread_ticks_p99']} (95% CI {rec['p99_ci']}), "
          f"max {rec['spread_ticks_max']}")
    print(f"  P(spread <= bound): {rec['p_within_bound']} — "
          f"Wilson 95% interval {rec['wilson']}")
    print(f"  verdict: {rec['verdict_kind']}, certified={rec['certified']}")


if __name__ == "__main__":
    main()
