"""Hybrid serving (r19): three real ``Cluster`` processes joining a
4096-member simulated membership over ``TpuSimTransport``.

Alice, Bob and Carol are ordinary scalar-engine protocol members — the same
asyncio objects as ``cluster_join_example.py`` — but their transport is a
:class:`SimBridge` splicing each of them into one row of a sparse-engine
mega sim. Each discovers the full simulated table through its initial SYNC
against ``sim://0``, discovers the *other* real members through the sim's
gossip (their bridged rows ride the same window folds as any simulated
row), and survives simulated chaos like any other member. Run with an
optional size: ``python examples/hybrid_cluster_example.py 1024``.
"""

import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.bridge import SimBridge
from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models.member import MemberStatus
from scalecube_cluster_tpu.ops.sparse import SparseParams
from scalecube_cluster_tpu.sim.driver import SimDriver

N = int(sys.argv[1]) if len(sys.argv) > 1 else 4096


def bridged_config() -> ClusterConfig:
    return (
        ClusterConfig.default_local()
        .with_membership(lambda m: m.replace(
            seed_members=["sim://0"], sync_interval=1.0, sync_timeout=1.0,
        ))
        .with_failure_detector(lambda f: f.replace(
            ping_interval=0.5, ping_timeout=0.4, ping_req_members=1,
        ))
        .with_gossip(lambda g: g.replace(gossip_interval=0.2))
    )


async def main() -> None:
    print(f"building a {N}-member sparse mega sim …")
    params = SparseParams(
        capacity=N + 16, fanout=3, ping_req_k=2, fd_every=2, sync_every=24,
        suspicion_mult=3, sweep_every=4, rumor_slots=16, mr_slots=256,
        announce_slots=64, seed_rows=(0, 1),
    )
    driver = SimDriver(params, N, warm=True, seed=7)
    bridge = SimBridge(driver, seed_rows=(0, 1))

    members = {}
    for name in ("Alice", "Bob", "Carol"):
        members[name] = await (
            new_cluster(bridged_config().replace(member_alias=name))
            .transport_factory(bridge.transport_factory(name.lower()))
            .start()
        )
        row = bridge._endpoints[name.lower()].row
        print(f"{name} joined over TpuSimTransport as row {row} "
              f"(table={len(members[name].members()) + 1})")

    # step sim windows so the bridged rows disseminate and the window-fold
    # SYNCs deliver sim-side progress back to the real members
    loop = asyncio.get_running_loop()
    for _ in range(6):
        await loop.run_in_executor(None, driver.step, 4)
        await asyncio.sleep(0.3)

    for name, c in members.items():
        row = bridge._endpoints[name.lower()].row
        status = driver.status_of(0, row)
        aliases = sorted(
            m.alias for m in c.members() if m.alias in
            ("Alice", "Bob", "Carol")
        )
        print(f"{name}: row {row} is {status.name} in the sim view; "
              f"sees real peers {aliases} among {len(c.members())} members")

    # simulated churn is visible to the real members like any other record
    crash_row = N // 2
    driver.crash(crash_row)
    for _ in range(10):
        await loop.run_in_executor(None, driver.step, 8)
        await asyncio.sleep(0.1)
    alice = members["Alice"]
    gone = driver.status_of(0, crash_row) == MemberStatus.DEAD
    print(f"sim row {crash_row} crashed → DEAD in sim views: {gone}")

    assert any(m.address == "sim://0" for m in alice.members())
    for c in members.values():
        await c.shutdown()
    print("done")


if __name__ == "__main__":
    asyncio.run(main())
