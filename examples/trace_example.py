"""Causal trace plane end to end: a detection lineage through chaos.

A 96-member dense cluster runs a Partition + Crash scenario with the trace
plane auto-attached (``run_scenario(trace=True)`` samples the crashed row
as a tracer). Afterwards the script:

1. prints the sewn probe-miss → suspect → DEAD span tree of the crashed
   member (the causal explanation of the detection the sentinel only
   *times*),
2. prints the traced rumor's infection tree (who infected whom, when),
3. runs the tick-phase profiler on the same driver, and
4. writes ``trace_example_perfetto.json`` — open it at
   https://ui.perfetto.dev to see protocol spans, the rumor tree, and the
   phase timeline on one timeline.
"""

import json
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.chaos import Crash, Partition, Scenario
from scalecube_cluster_tpu.ops.state import SimParams
from scalecube_cluster_tpu.sim import SimDriver
from scalecube_cluster_tpu.trace.export import write_chrome_trace
from scalecube_cluster_tpu.trace.profile import profile_driver


def show_tree(node, depth=0):
    span = f"[{node['start_tick']:>5}..{node['end_tick']:>5}]"
    attrs = {
        k: v for k, v in node["attributes"].items()
        if v not in (None, 0, False) and k != "subject"
    }
    print("  " * depth + f"{span} {node['name']}  {attrs}")
    for ev in node["events"][:4]:
        print("  " * (depth + 1) + f"· tick {ev['tick']}: {ev['name']} "
              + str({k: v for k, v in ev.items() if k not in ('tick', 'name')}))
    for child in node["children"]:
        show_tree(child, depth + 1)


def main() -> None:
    n = 96
    params = SimParams(
        capacity=n, fanout=3, repeat_mult=3, ping_req_k=3, fd_every=5,
        sync_every=40, suspicion_mult=3, rumor_slots=4, seed_rows=(0, 48),
    )
    driver = SimDriver(params, n_initial=n, warm=True, seed=0)

    # arm EXPLICITLY so a rumor slot is traced too (run_scenario would
    # otherwise auto-attach with the crash rows only)
    plane = driver.arm_trace(tracer_rows=(17,), rumor_slots=(0,))
    slot = driver.spread_rumor(origin=3, payload={"feature": "flag-42"})

    scenario = Scenario(
        name="split-then-crash",
        events=[
            Partition(groups=[range(0, n // 2), range(n // 2, n)],
                      at=30, heal_at=120),
            Crash(rows=[17], at=160),
        ],
    )
    print("running scenario (trace-armed)...")
    report = driver.run_scenario(scenario, trace=True)
    print(f"scenario ok={report['ok']} violations={report['violations']}")

    det = report["sentinels"]["detections"][0]
    print(f"\ncrash of row 17 at t={det['crashed_at']}, detected at "
          f"t={det['detected_at']} (budget {det['deadline']})")
    print("\n== detection lineage (why the sentinel is green) ==")
    show_tree(report["trace_spans"][17])

    print("\n== rumor infection tree ==")
    trees = plane.rumor_trees()
    tree = [t for t in trees if t["slot"] == slot][0]
    print(f"slot {slot}: origin {tree['origin']}, {tree['n_infected']} "
          f"infected, depth {tree['depth']}, spread "
          f"[{tree['first_infection_tick']}..{tree['last_infection_tick']}]")

    print("\n== tick-phase profile (split-jit window, 32 ticks) ==")
    prof = profile_driver(driver, n_ticks=32)
    for phase, pct in sorted(prof["phases_pct"].items(),
                             key=lambda kv: -kv[1]):
        print(f"  {phase:<10} {pct:>6.2f}%  ({prof['phases_s'][phase]:.4f}s)")
    print(f"  phase coverage of wall: {prof['phase_coverage']:.2%}")

    out = pathlib.Path(tempfile.gettempdir()) / "trace_example_perfetto.json"
    write_chrome_trace(str(out), plane.perfetto(profile=prof))
    print(f"\nwrote {out} — open at https://ui.perfetto.dev")
    events = json.load(open(out))["traceEvents"]
    print(f"({len(events)} trace events)")


if __name__ == "__main__":
    main()
