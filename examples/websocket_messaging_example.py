"""Messaging over WebSocket frames — the reference's
WebsocketMessagingExample: the exact MessagingExample flow with the wire
protocol swapped by config, demonstrating the transport SPI supports more
than one real wire (HTTP-upgrade + RFC 6455 binary frames here, vs
length-prefixed TCP in tcp_messaging_example.py)."""

import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models.message import Message


async def main() -> None:
    cfg = ClusterConfig.default_local().with_transport(
        lambda t: t.replace(transport_factory="websocket")
    )
    server = await new_cluster(cfg.replace(member_alias="server")).start()
    print(f"server speaking RFC 6455 on {server.address}")

    def on_message(msg: Message) -> None:
        if msg.qualifier == "hello":
            reply = Message.with_data("world", qualifier="hello/ack", cid=msg.correlation_id)
            asyncio.ensure_future(server.send(msg.sender, reply))

    server.listen_messages().subscribe(on_message)

    client = await new_cluster(
        cfg.replace(member_alias="client").with_membership(
            lambda m: m.replace(seed_members=(server.address,))
        )
    ).start()
    await asyncio.sleep(1.0)
    resp = await client.request_response(
        client.other_members()[0], Message.with_data("hello", qualifier="hello")
    )
    print(f"client got {resp.data!r} over WebSocket")
    await client.shutdown()
    await server.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
