"""Watch the membership event stream while members join and leave
(MembershipEventsExample.java)."""

import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig


async def main() -> None:
    cfg = ClusterConfig.default_local()
    observer = await new_cluster(cfg.replace(member_alias="Observer")).start()
    observer.listen_membership().subscribe(
        lambda ev: print(f"[Observer] {ev.type.name}: {ev.member.alias or ev.member.id[:8]}")
    )

    join = cfg.with_membership(lambda m: m.replace(seed_members=(observer.address,)))
    alice = await new_cluster(join.replace(member_alias="Alice")).start()
    bob = await new_cluster(join.replace(member_alias="Bob")).start()
    await asyncio.sleep(1.0)

    print("-- Alice leaves gracefully --")
    await alice.shutdown()
    await asyncio.sleep(2.0)

    await bob.shutdown()
    await observer.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
