"""Hierarchical namespace visibility: parent/child namespaces form one
cluster, siblings stay invisible (ClusterJoinNamespacesExamples.java)."""

import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig


async def start(alias: str, namespace: str, seeds=()):
    cfg = ClusterConfig.default_local().with_membership(
        lambda m: m.replace(seed_members=tuple(seeds), namespace=namespace)
    )
    return await new_cluster(cfg.replace(member_alias=alias)).start()


async def main() -> None:
    root = await start("root", "develop")
    child1 = await start("child1", "develop/child1", [root.address])
    child2 = await start("child2", "develop/child2", [root.address])
    await asyncio.sleep(1.0)

    for c in (root, child1, child2):
        names = sorted(m.alias or m.id[:8] for m in c.members())
        print(f"{c.member().alias} ({c.member().namespace}) sees: {names}")
    # root sees both children; each child sees root but NOT its sibling

    for c in (root, child1, child2):
        await c.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
