"""Custom member-id generator and alias (MemberIdExample.java)."""

import asyncio
import itertools
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig

counter = itertools.count(1)


def sequential_id() -> str:
    return f"node-{next(counter):04d}"


async def main() -> None:
    cfg = ClusterConfig.default_local().replace(member_id_generator=sequential_id)
    a = await new_cluster(cfg.replace(member_alias="first")).start()
    b = await new_cluster(
        cfg.replace(member_alias="second").with_membership(
            lambda m: m.replace(seed_members=(a.address,))
        )
    ).start()
    await asyncio.sleep(0.5)
    for c in (a, b):
        print(f"alias={c.member().alias} id={c.member().id} address={c.address}")
    await b.shutdown()
    await a.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
