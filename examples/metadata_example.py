"""Share and update member metadata; peers observe UPDATED events and fetch
the new value (ClusterMetadataExample.java)."""

import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig


async def main() -> None:
    cfg = ClusterConfig.default_local()
    joe = await new_cluster(
        cfg.replace(member_alias="Joe", metadata={"name": "Joe"})
    ).start()

    caller = await new_cluster(
        cfg.replace(member_alias="Caller").with_membership(
            lambda m: m.replace(seed_members=(joe.address,))
        )
    ).start()

    def on_event(ev) -> None:
        if ev.is_updated:
            print(f"[Caller] metadata UPDATED for {ev.member.alias or ev.member.id[:8]}: "
                  f"{caller.metadata_of(ev.member)}")

    caller.listen_membership().subscribe(on_event)
    await asyncio.sleep(1.0)
    joe_member = caller.member_by_id(joe.member().id)
    print(f"[Caller] initial metadata of Joe: {caller.metadata_of(joe_member)}")

    await joe.update_metadata({"name": "Joe", "status": "on vacation"})
    await asyncio.sleep(2.0)

    await caller.shutdown()
    await joe.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
