"""Alice/Bob/Carol seed join — the reference quick-start
(ClusterJoinExamples.java / README.md:22-37)."""

import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.cluster import new_cluster
from scalecube_cluster_tpu.config import ClusterConfig


async def main() -> None:
    cfg = ClusterConfig.default_local()
    alice = await new_cluster(cfg.replace(member_alias="Alice")).start()
    print(f"Alice started at {alice.address}")

    join_alice = cfg.with_membership(lambda m: m.replace(seed_members=(alice.address,)))
    bob = await new_cluster(join_alice.replace(member_alias="Bob")).start()
    carol = await new_cluster(join_alice.replace(member_alias="Carol")).start()

    await asyncio.sleep(1.0)
    for c in (alice, bob, carol):
        names = sorted(m.alias or m.id[:8] for m in c.members())
        print(f"{c.member().alias} sees: {names}")
    for c in (alice, bob, carol):
        await c.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
