"""TPU-native addition: 1024 simulated members on one chip — join, rumor,
crash detection, and membership events through the same facade shapes the
scalar engine offers. No reference counterpart (the reference tops out at
~50 in-JVM members in its experiments)."""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from scalecube_cluster_tpu.ops.state import SimParams
from scalecube_cluster_tpu.sim import SimCluster, SimDriver


def main() -> None:
    params = SimParams(
        capacity=1024, fanout=3, repeat_mult=3, ping_req_k=3, fd_every=5,
        sync_every=150, suspicion_mult=5, rumor_slots=8, seed_rows=(0,),
    )
    driver = SimDriver(params, n_initial=1000, warm=True, seed=0)
    cluster = SimCluster(driver)

    observer = cluster.node(0)
    observer.listen_membership().subscribe(
        lambda ev: print(f"[node0] {ev.type.name}: {ev.member.id}")
    )

    print(f"{len(observer.members())} members up")
    slot = cluster.node(7).spread_gossip("big announcement")
    driver.run_until(lambda d: d.rumor_coverage(slot) >= 1.0, max_ticks=60)
    print(f"rumor reached all 1000 members in {driver.tick} ticks "
          f"({driver.tick * 0.2:.1f} simulated seconds)")

    print("-- crashing node 500 --")
    cluster.node(500).crash()
    # suspicion timeout at N=1000 is 5 * ceil_log2(1001) * 5 = 250 ticks;
    # add the dissemination + removal window
    driver.step(320)
    print(f"node0 now sees {len(observer.members())} members")

    print("-- joining a fresh member --")
    newbie = cluster.join(seed_rows=[0])
    driver.step(30)
    print(f"{newbie.member.id} sees {len(newbie.members())} members")


if __name__ == "__main__":
    main()
