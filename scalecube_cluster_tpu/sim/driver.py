"""SimDriver: the host loop around the device-resident SWIM simulation.

The reference's per-node schedulers (``Schedulers.newSingle`` per node,
``ClusterImpl.java:257``) collapse into one host loop calling the compiled
tick (SURVEY.md §2.3 "Host-driver loop"); everything protocol-ish happens on
device. The driver owns:

* the jitted (optionally mesh-sharded) step and the RNG key chain;
* the id↔row mapping (``Member`` handles with ``sim://row`` addresses);
* membership-event extraction for *watched* rows — per-tick host diffs of
  those rows' views, emitting the reference's ADDED / LEAVING / REMOVED /
  UPDATED stream (``MembershipEvent.java:15-20``) without pulling the whole
  N×N state off-device;
* metrics history (per-tick scalars from the kernel);
* checkpoint/resume of the full state (SURVEY.md §5.4 — an addition over
  the reference, whose state is soft).

Dispatch is PIPELINED (r6): the jitted windows donate the state buffers
(XLA updates the N×N planes in place instead of copying them at window
entry), and ``step()`` never reads device results back on its own — the
per-window health reductions (counter sums, pool high-water, segmentation
worst) accumulate ON DEVICE and come to host only at an explicit sync
point: :meth:`flush`, :meth:`health_snapshot`, :meth:`checkpoint`, or the
``health_counters`` / ``pool_high_water`` / ``segmentation_warnings``
properties. With no monitor, watch, or ``record_metrics`` consumer
attached, a ``step()`` therefore performs ZERO device→host transfers and
JAX async dispatch runs windows back-to-back while the host races ahead
enqueueing — one ``block_until_ready`` per monitor poll, not per window.
Attaching a consumer (a watch stream, ``record_metrics=True``) opts that
driver into per-window readbacks, which ``dispatch_stats`` makes visible.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.events import MembershipEvent
from ..models.member import Member, MemberStatus
from ..utils.streams import EventStream
from ..ops import kernel as _kernel
from ..ops import state as _state
from ..ops.lattice import (
    ALIVE,
    DEAD,
    LEAVING,
    SUSPECT,
    UNKNOWN,
    key_np_dtype,
    layout_for,
)
from ..ops.state import SimParams, SimState


def row_address(row: int) -> str:
    return f"sim://{row}"


class CheckpointError(RuntimeError):
    """A checkpoint file that cannot be restored (truncated, corrupt, schema
    from the future, or written by the other engine) — raised instead of
    letting numpy/pickle fail arbitrarily deep in the load path."""


#: Checkpoint schema: 1 = the implicit pre-r7 layout (no version stamp),
#: 2 = r7 crash-safe layout (tmp+rename, _schema + _crc32 + _engine fields),
#: 3 = r9 bit-plane layout (dense ``infected`` / ``pending_inf`` stored as
#: word-packed uint32; ``view_key`` carries its dtype — i16 under
#: ``plane_dtype="i16"``). Restore accepts schema <= 2 archives by packing
#: the legacy bool planes on load (``ops.state.restore`` sniffs dtypes).
CHECKPOINT_SCHEMA = 3


_RANK_TO_STATUS_NP = np.array([ALIVE, LEAVING, SUSPECT, DEAD], dtype=np.int8)


def _status_of_key(k: int) -> int:
    """Host-side decode of a packed table key (lattice.py layout)."""
    return UNKNOWN if k < 0 else int(_RANK_TO_STATUS_NP[k & 3])


@dataclass
class _Watch:
    row: int
    prev_key: np.ndarray  # [N] int32 packed keys
    stream: EventStream = field(default_factory=EventStream)
    log: List[MembershipEvent] = field(default_factory=list)
    # Member handle captured when the observer first learned each row, so
    # later events name the identity the observer actually knew — a reused
    # row (crash + rejoin) must not retroactively relabel old records.
    known: Dict[int, Member] = field(default_factory=dict)


def auto_params(
    capacity: int,
    *,
    per_link_fidelity: bool = False,
    link_delay: bool = False,
    dense_threshold: int = 8192,
    config=None,
    **overrides,
):
    """Pick the canonical engine for a capacity (VERDICT r3 item 8: make the
    two-engine policy executable, not folklore).

    Policy: the DENSE kernel is canonical where per-link emulator fidelity
    is affordable and wanted — [N, N] link matrices, per-link delay rings,
    full-matrix metrics — i.e. ``per_link_fidelity``/``link_delay`` runs up
    to ``dense_threshold`` members. The SPARSE (record-queue) engine is
    canonical past that: per-tick cost rides the change rate instead of N²,
    which is what lets one chip run 49k-member churn and the 8-chip mesh
    the 98k north star. Per-link loss/delay remain AVAILABLE in sparse mode
    (``dense_links=True`` at construction) but cost an [N, N] float plane —
    the reason small-N fidelity work stays on the dense kernel.

    Returns a :class:`SimParams` or :class:`.sparse.SparseParams`;
    ``SimDriver`` then selects the engine by the params type as before.
    ``config`` (a ClusterConfig) routes through the matching
    ``from_config``; ``overrides`` go straight to the params constructor.
    """
    import dataclasses as _dc
    import inspect as _inspect

    from ..ops import sparse as _sparse

    if config is not None:
        # a configured compile-cache directory takes effect before the first
        # window compiles (persistent XLA cache; no-op when unset)
        from .. import compile_cache as _cc

        _cc.enable_persistent_compile_cache(config=config)
    force_sparse = overrides.pop("force_sparse", False)
    force_pview = overrides.pop("force_pview", False)
    use_dense = (per_link_fidelity or link_delay) and capacity <= dense_threshold
    if capacity <= 512:
        # tiny clusters: dense is both faster to compile and exact
        use_dense = True
    if force_sparse:
        use_dense = False
    if force_pview:
        # the r11 O(N·k) engine: the only one that fits 100k+ members in
        # one 16 GiB window (no [N, N] plane anywhere — see ops/pview.py)
        from ..ops import pview as _pview

        cls = _pview.PviewParams
    else:
        cls = SimParams if use_dense else _sparse.SparseParams
    if config is not None:
        # from_config accepts only its own kwargs; remaining overrides are
        # applied to the derived params afterwards
        fc_names = set(_inspect.signature(cls.from_config).parameters)
        fc_kw = {k: v for k, v in overrides.items() if k in fc_names}
        rest = {k: v for k, v in overrides.items() if k not in fc_names}
        params = cls.from_config(config, capacity=capacity, **fc_kw)
        return _dc.replace(params, **rest) if rest else params
    return cls(capacity=capacity, **overrides)


class SimDriver:
    """Drive one simulated cluster; all mutation goes through this object."""

    def __init__(
        self,
        params,
        n_initial: int,
        warm: bool = True,
        seed: int = 0,
        mesh=None,
        record_metrics: bool = False,
        dense_links: bool | None = None,
        compile_cache_dir: str | None = None,
    ):
        """``params`` selects the engine: a :class:`SimParams` drives the
        dense kernel, a :class:`.sparse.SparseParams` the sparse
        (record-queue) one — same driver surface either way.
        ``dense_links`` overrides the per-link matrix default (dense mode:
        True; sparse mode: False — the lean scalar-loss layout).
        ``compile_cache_dir`` points the persistent XLA compilation cache
        at a directory (``ClusterConfig.sim.compile_cache_dir`` /
        ``SCALECUBE_COMPILE_CACHE_DIR`` are the config/env spellings)."""
        from ..ops import engine_api as _engine_api

        if compile_cache_dir:
            from .. import compile_cache as _cc

            _cc.enable_persistent_compile_cache(compile_cache_dir)
        self.params = params
        # ONE engine-dispatch spelling (r11, ops/engine_api.py): the params
        # type selects the EngineOps descriptor every consumer (window
        # builders, telemetry/trace/chaos planes, monitor) resolves through
        self._eng = _engine_api.resolve(params)
        self.engine = self._eng.name
        self.sparse = self.engine == "sparse"  # historical spelling, kept
        self._ops = self._eng.ops
        self.mesh = mesh
        self.record_metrics = record_metrics
        if mesh is not None and not self._eng.supports_mesh:
            raise ValueError(
                f"the {self.engine} engine is single-device (no sharded "
                "window builders) — construct without mesh="
            )
        # refuse pallas x mesh at construction, not at the first (lazy)
        # window build — the kernel is single-device (the mesh delivery
        # path is the ragged all-to-all, docs/SHARDING.md; the kernel's
        # r20 column split covers VMEM, not sharding)
        if mesh is not None and getattr(params, "delivery_kernel", "xla") == "pallas":
            raise ValueError(
                "delivery_kernel='pallas' is single-device for now — "
                "construct without mesh="
            )
        if dense_links is None:
            dense_links = self._eng.dense_links_default
        # r14 adaptive failure detection: an ENABLED AdaptiveSpec on params
        # arms the Lifeguard-style plane — the driver owns the AdaptiveState
        # pytree and threads it through the adaptive window programs
        aspec = getattr(params, "adaptive", None)
        if aspec is not None and not aspec.is_default:
            # r17: engines that register a sharded adaptive window builder
            # (pview) run the adaptive plane on meshes — the AdaptiveState's
            # [N] planes row-shard like every other member-axis tensor
            if mesh is not None and self._eng.make_sharded_adaptive_run is None:
                raise ValueError(
                    f"adaptive failure detection is single-device for the "
                    f"{self.engine} engine — construct without mesh= or use "
                    "the default AdaptiveSpec"
                )
            from ..adaptive import init_adaptive_state

            self._ad = init_adaptive_state(params.capacity)
            if mesh is not None:
                from ..ops.sharding import shard_adaptive_state

                self._ad = shard_adaptive_state(self._ad, mesh)
        else:
            self._ad = None
        init = self._eng.init_state(params, n_initial, warm, dense_links)
        self._dense_links = init.loss.ndim != 0
        if mesh is not None:
            self.state = self._eng.shard_state(init, mesh)
        else:
            self.state = init
        # key-plane bit layout (wide i32 / narrow i16 — r9): every host-side
        # decode (event diffs, view_of) must use the state's actual layout
        key_plane = self._eng.key_plane(init) if self._eng.key_plane else None
        self._lay = layout_for(
            key_plane.dtype if key_plane is not None else jnp.int32
        )
        self._step_cache: Dict[tuple, Callable] = {}
        # per-program dispatch stats for jit_cache_audit(): calls + first
        # dispatch wall time (first dispatch includes the jit compile, or
        # the persistent-cache load when one hits)
        self._step_stats: Dict[tuple, dict] = {}
        # r19: jitted+donated spellings of the interactive host mutators
        # (join/leave/metadata/rumor). The eager spellings dispatch each
        # ``.at[].set`` as its own device op — 100-300 ms per announce
        # chain at serving shapes, far below the loadgen's sustained
        # member-facing op rate. Row/slot operands are passed as traced
        # i32 scalars so ONE compile per mutator serves every row.
        self._mutator_jits: Dict[str, Callable] = {}
        # r18: construction seed + warm flag kept host-side — the flight
        # recorder's reconstruction section embeds them so an incident dump
        # can rebuild a bit-identical replay driver (replay.py)
        self.seed = int(seed)
        self._init_warm = bool(warm)
        self._key = jax.random.PRNGKey(seed)
        self._rng = np.random.default_rng(seed ^ 0x5EED)  # host-side (transport) draws
        self.n_initial = n_initial
        self.members: Dict[int, Member] = {
            r: Member(id=f"sim-{r}", address=row_address(r)) for r in range(n_initial)
        }
        self.metrics_history: List[dict] = []
        # gossip-stream fragmentation warning (the reference's
        # checkGossipSegmentation, GossipProtocolImpl.java:217-236; default
        # threshold 1000, GossipConfig.java:12)
        self.segmentation_threshold = 1000
        self._watches: Dict[int, _Watch] = {}
        self._rumor_payloads: Dict[int, object] = {}
        self._next_member_ordinal = n_initial
        self._transports: Dict[int, object] = {}  # row -> SimTransport
        # engine-health accumulators (VERDICT r4 item 8: the sparse pool's
        # backpressure failure mode must be visible live, not only in the
        # churn bench). Per-window sums/maxima accumulate ON DEVICE (the
        # _win_* fields below) and fold into these host dicts only at a
        # flush() sync point — reading the public properties flushes.
        self._health_counters: Dict[str, int] = {
            "announce_dropped": 0, "announce_dropped_fd": 0,
            "announce_dropped_expiry": 0, "announce_dropped_refute": 0,
            "announce_dropped_sync": 0, "pool_evicted": 0, "announced": 0,
            # host-path announce drops (join/leave self-announce finding a
            # pool with no majority-covered victim) — detected in join()
            "announce_dropped_host": 0,
            # r21: ragged all-to-all budget drops (pview windows emit the
            # psummed ``delivery_overflow`` sentinel; 0 everywhere else) —
            # accumulated device-side like every other window counter
            "delivery_overflow": 0,
        }
        self._pool_high_water = 0
        self._segmentation_warnings = 0
        # device-resident deferred reductions (None = nothing staged)
        self._win_names: List[str] = []
        self._win_accum = None  # i32 [len(_win_names)] summed counter deltas
        self._win_pool_hw = None  # i32 scalar max of mr_active_count
        self._win_seg_warn = None  # i32 scalar count of over-threshold windows
        self._join_probe = None  # i32 scalar count of dropped host announces
        # health probes (the join() in-pool readback) run only for a
        # registered consumer: MonitorServer.register_health or a
        # health_snapshot() call turns this on
        self._health_interest = False
        # dispatch-pipeline observability (exposed via dispatch_snapshot()
        # and monitor.py): queue_depth counts windows enqueued since the
        # last host sync; readbacks counts device→host transfer events
        self.dispatch_stats: Dict[str, int] = {
            "windows_dispatched": 0, "ticks_dispatched": 0, "readbacks": 0,
            "flushes": 0, "queue_depth": 0, "queue_high_water": 0,
        }
        # the deferred reductions accumulate in DEVICE i32 (x64 is off);
        # bound the flush-free span so a busy counter can't wrap 2^31 on a
        # long unmonitored soak — worst per-tick counter is ~announce_slots
        # (<= a few thousand), so 100k ticks stays orders under the limit.
        # The induced flush is one coalesced sync per cap-ful of ticks.
        self._ticks_since_flush = 0
        self.flush_ticks_cap = 100_000
        # MonitorServer runs in another thread; its polls (health_snapshot,
        # view_of via sim_snapshot) race the sim thread's step(). Donation
        # makes an unsynchronized interleaving fatal (a poll can grab a
        # self.state reference the sim thread donates before the poll
        # dispatches → "Array has been deleted"), and the deferred
        # accumulators would double-count if a flush interleaved a step's
        # read-modify-write. One reentrant lock covers both; uncontended in
        # single-thread use.
        self._lock = threading.RLock()
        self._recent_joins: List[tuple] = []  # (tick, row) of driver joins
        self._join_horizon = 300  # ticks a join stays in the lag cohorts
        # armed chaos runner (chaos.DriverChaosRunner): fault timeline +
        # on-device invariant sentinels; surfaced via chaos_snapshot(),
        # health_snapshot()'s "chaos" section and the monitor's GET /chaos
        self._chaos = None
        # armed telemetry plane (r8, telemetry.TelemetryPlane): device
        # metric ring + event bus + /metrics exporter + flight recorder;
        # None = unarmed (the plane is a pure consumer — arming must never
        # perturb the trajectory or add per-window transfers)
        self._telemetry = None
        # armed causal trace plane (r10, trace.TracePlane): protocol span
        # capture into a donated device ring, threaded through the traced
        # window programs; None = unarmed. Same neutrality contract as
        # telemetry: bit-identical trajectory, zero per-window readbacks.
        self._trace = None
        # armed closed-loop control plane (r16, control.ControlPlane):
        # pure-host telemetry-driven knob steering; None = unarmed. When
        # armed and taking no action the trajectory stays bit-identical —
        # sensor reads are epoch-cadence sync points, never hot-path ops.
        self._control = None
        # host-side tick shadow: lets bus records and flight dumps stamp the
        # current tick WITHOUT a device read (step() advances it; restore
        # re-seeds it from the checkpoint's host-visible tick plane)
        self._host_tick = 0
        # host-tracked free rumor slots (r8 satellite: the interactive
        # spread_rumor path must not sync the donated pipeline). Slots the
        # device sweeper frees are reclaimed lazily — only when this list
        # runs dry does spread_rumor pay one coalesced readback.
        self._free_rumor_slots = list(range(params.rumor_slots))
        # deferred end-of-window rumor-coverage vector ([R], device) — the
        # r8 satellite that folds rumor_coverage()'s old [N]-plane readback
        # into the flush discipline; _rumor_cov_host is the flushed cache,
        # _rumor_cov_dirty marks host mutations newer than the cache
        self._win_rumor_cov = None
        self._rumor_cov_host = None
        self._rumor_cov_dirty = True
        # rumors awaiting full coverage, slot -> spread tick (feeds the
        # telemetry plane's rumor-spread histogram at flush time)
        self._rumor_spread_pending: Dict[int, int] = {}

    # -- time ---------------------------------------------------------------
    @property
    def tick(self) -> int:
        # locked: the monitor thread reads this (sim_snapshot) and the read
        # must not interleave with a donating step — see self._lock
        with self._lock:
            return int(self.state.tick)

    # -- stepping -----------------------------------------------------------
    def _get_step(self, n_ticks: int, n_watch: int) -> Callable:
        """Cached jitted ``run_ticks`` executable per (window, watch-count).

        The whole window runs as ONE device call (``lax.scan``) — per-tick
        host dispatch costs a device round trip each, which on a tunneled
        TPU dwarfs the tick itself. Watched rows' view keys come back
        stacked per tick so membership events for the window are diffed
        from a single transfer. An armed trace plane (r10) keys separate
        TRACED window programs — same trajectory, ring threaded through."""
        traced = self._trace is not None
        adaptive = self._ad is not None
        cache_key = (n_ticks, n_watch, traced, adaptive)
        if cache_key not in self._step_cache:
            if traced:
                if self.mesh is not None:
                    # r20: engines registering a sharded traced builder
                    # (pview) capture on the mesh — the ring rides the
                    # donated carry replicated (arm_trace placed it)
                    self._step_cache[cache_key] = (
                        self._eng.make_sharded_traced_run(
                            self.mesh, self.params, n_ticks, self._trace.spec
                        )
                    )
                else:
                    self._step_cache[cache_key] = self._eng.make_traced_run(
                        self.params, n_ticks, self._trace.spec
                    )
            elif adaptive:
                if self.mesh is not None:
                    self._step_cache[cache_key] = (
                        self._eng.make_sharded_adaptive_run(
                            self.mesh, self.params, n_ticks
                        )
                    )
                else:
                    self._step_cache[cache_key] = self._eng.make_adaptive_run(
                        self.params, n_ticks
                    )
            elif self.mesh is not None:
                self._step_cache[cache_key] = self._eng.make_sharded_run(
                    self.mesh, self.params, n_ticks, self._dense_links
                )
            else:
                self._step_cache[cache_key] = self._eng.make_run(
                    self.params, n_ticks
                )
            self._step_stats[cache_key] = {"calls": 0, "first_dispatch_s": None}
        return self._step_cache[cache_key]

    def step(self, n_ticks: int = 1) -> dict:
        """Advance the sim ``n_ticks`` periods in one device call; returns
        the last tick's metrics (DEVICE arrays — coercing them to Python
        numbers is the caller's explicit sync).

        The trajectory is identical to ``n_ticks`` single steps (the key
        chain inside the window is the same split sequence). The call is
        fully asynchronous on the no-consumer path: health reductions stay
        on device (see :meth:`flush`), the donated state updates in place,
        and back-to-back ``step()`` calls pipeline — window k+1 is enqueued
        while window k executes. A watch or ``record_metrics=True`` opts
        into one device→host readback per window (events/history must be
        observed in order), which ``dispatch_stats`` counts."""
        with self._lock:
            return self._step_locked(n_ticks)

    def _step_locked(self, n_ticks: int) -> dict:
        rows = sorted(self._watches)
        watch_arr = jnp.asarray(rows, dtype=jnp.int32) if rows else None
        step = self._get_step(n_ticks, len(rows))
        stats = self._step_stats[
            (n_ticks, len(rows), self._trace is not None, self._ad is not None)
        ]
        t0 = time.perf_counter()
        if self._trace is not None:
            # traced window: the trace ring rides the donated carry; the
            # cursor upload is host→device (never a readback) and the host
            # mirror advances by the static K·n_ticks append count
            ring = self._trace.ring
            self.state, self._key, ms, watched, ring.buf = step(
                self.state, self._key, ring.buf, ring.device_cursor(),
                watch_rows=watch_arr,
            )
            ring.advance(self._trace.spec.n_tracers * n_ticks)
            # window-boundary summary: the view-column dissemination diff,
            # appended as FLAG_SUMMARY records (pure device ops — the r8
            # on_window pattern; the diff must NOT live inside the window
            # jit, see trace/capture.py)
            self._trace.on_window(self.state)
        elif self._ad is not None:
            # adaptive window (r14): the AdaptiveState pytree rides the
            # donated carry next to the engine state
            self.state, self._ad, self._key, ms, watched = step(
                self.state, self._ad, self._key, watch_rows=watch_arr
            )
        else:
            self.state, self._key, ms, watched = step(
                self.state, self._key, watch_rows=watch_arr
            )
        dispatch_s = time.perf_counter() - t0
        if stats["calls"] == 0:
            # first dispatch = trace + compile (or persistent-cache load)
            stats["first_dispatch_s"] = round(dispatch_s, 4)
        stats["calls"] += 1
        ds = self.dispatch_stats
        ds["windows_dispatched"] += 1
        ds["ticks_dispatched"] += n_ticks
        ds["queue_depth"] += 1
        ds["queue_high_water"] = max(ds["queue_high_water"], ds["queue_depth"])
        self._accumulate_window(ms)
        self._host_tick += n_ticks
        if self._telemetry is not None:
            # one pure-jnp ring append + host wall-clock histograms — the
            # armed plane stays inside the zero-readback discipline
            self._telemetry.on_window(ms, self.state, n_ticks, dispatch_s)
        if self._control is not None:
            # r16 closed loop: a counter bump per window; at control-epoch
            # boundaries the plane reads the newest ring row (one
            # epoch-cadence readback) and may live-swap knobs — never a
            # device op inside the window programs
            self._control.on_window()
        self._ticks_since_flush += n_ticks
        if self._ticks_since_flush >= self.flush_ticks_cap:
            self.flush()  # i32 overflow guard — see flush_ticks_cap
        if self.record_metrics:
            host_ms = {name: np.asarray(v) for name, v in ms.items()}
            self._note_readback(len(host_ms))
            for i in range(n_ticks):
                self.metrics_history.append(
                    {name: v[i] for name, v in host_ms.items()}
                )
        if rows:
            keys = np.asarray(watched)  # [n_ticks, W, N]
            self._note_readback(1)
            for i in range(n_ticks):
                for w_idx, row in enumerate(rows):
                    w = self._watches[row]
                    self._diff_row(w, keys[i, w_idx])
                    w.prev_key = keys[i, w_idx]
        return {name: v[-1] for name, v in ms.items()}

    # -- pipelined-dispatch bookkeeping -------------------------------------
    def _note_readback(self, n: int = 1) -> None:
        """Record ``n`` device→host transfer events. Any readback of this
        window's outputs also drains the dispatch queue (results force every
        enqueued predecessor), so the depth resets."""
        with self._lock:
            self.dispatch_stats["readbacks"] += n
            self.dispatch_stats["queue_depth"] = 0

    def _accumulate_window(self, ms: dict) -> None:
        """Fold one window's metrics into the DEVICE-side reductions —
        pure jnp ops, no transfer; host sees them at the next flush()."""
        names = [n for n in self._health_counters if n in ms]
        if names:
            vec = jnp.stack([ms[n].sum() for n in names])
            if self._win_accum is None:
                self._win_accum, self._win_names = vec, names
            else:
                self._win_accum = self._win_accum + vec
        if "mr_active_count" in ms:
            hw = ms["mr_active_count"].max()
            self._win_pool_hw = (
                hw if self._win_pool_hw is None else jnp.maximum(self._win_pool_hw, hw)
            )
        if "gossip_segmentation" in ms:
            over = (
                ms["gossip_segmentation"].max() > self.segmentation_threshold
            ).astype(jnp.int32)
            self._win_seg_warn = (
                over if self._win_seg_warn is None else self._win_seg_warn + over
            )
        if "rumor_coverage" in ms:
            # end-of-window per-slot coverage: staging the LAST tick's [R]
            # vector (a device reference, no transfer) supersedes any
            # earlier staged window — coverage is a gauge, not a sum
            self._win_rumor_cov = ms["rumor_coverage"][-1]
            self._rumor_cov_dirty = False

    def flush(self) -> None:
        """Coalesced host readback of every deferred reduction — THE sync
        point of the pipelined driver (monitor-poll cadence, not window
        cadence). Also drains the dispatch queue: forcing the newest staged
        value forces every enqueued window before it. Thread-safe against a
        concurrently stepping sim thread."""
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        flushed = 0
        if self._win_accum is not None:
            vals = np.asarray(self._win_accum)
            for name, v in zip(self._win_names, vals):
                self._health_counters[name] += int(v)
            self._win_accum = None
            flushed += 1
        if self._win_pool_hw is not None:
            self._pool_high_water = max(
                self._pool_high_water, int(np.asarray(self._win_pool_hw))
            )
            self._win_pool_hw = None
            flushed += 1
        if self._win_seg_warn is not None:
            new = int(np.asarray(self._win_seg_warn))
            self._win_seg_warn = None
            if new:
                import logging

                logging.getLogger(__name__).warning(
                    "gossip stream fragmented past threshold %d in %d "
                    "window(s) since the last flush",
                    self.segmentation_threshold, new,
                )
            self._segmentation_warnings += new
            flushed += 1
        if self._join_probe is not None:
            self._health_counters["announce_dropped_host"] += int(
                np.asarray(self._join_probe)
            )
            self._join_probe = None
            flushed += 1
        if self._win_rumor_cov is not None:
            self._rumor_cov_host = np.asarray(self._win_rumor_cov)
            self._win_rumor_cov = None
            flushed += 1
            if (
                self._rumor_spread_pending
                and self._telemetry is not None
                and not self._rumor_cov_dirty
            ):
                # a rumor that reached every up member since its spread:
                # record window-granular spread time (the /metrics
                # rumor-spread histogram) and stop tracking it. Skipped
                # while the staged vector is STALE (_rumor_cov_dirty: a
                # spread/crash postdates the window) — a rumor spread into
                # a reclaimed slot must not inherit the previous
                # occupant's full-coverage plane as a bogus ~0-tick sample
                for slot, t0 in list(self._rumor_spread_pending.items()):
                    if self._rumor_cov_host[slot] >= 1.0:
                        self._telemetry.hist_spread.observe(
                            max(self._host_tick - t0, 1)
                        )
                        del self._rumor_spread_pending[slot]
        if flushed:
            self._note_readback(flushed)
            self.dispatch_stats["flushes"] += 1
        self._ticks_since_flush = 0

    def sync(self) -> None:
        """Block until every enqueued window has executed (no transfer)."""
        with self._lock:
            jax.block_until_ready(self.state)
            self.dispatch_stats["queue_depth"] = 0

    def dispatch_snapshot(self) -> dict:
        """Pipeline observability: queue depth (windows enqueued since the
        last host sync), total/per-window readback counts, flush count —
        the numbers that make the dispatch overlap checkable instead of
        asserted (exposed over HTTP via monitor.dispatch_snapshot)."""
        with self._lock:
            return self._dispatch_snapshot_locked()

    def _dispatch_snapshot_locked(self) -> dict:
        ds = dict(self.dispatch_stats)
        w = max(ds["windows_dispatched"], 1)
        ds["readbacks_per_window"] = round(ds["readbacks"] / w, 4)
        ds["staged_reductions"] = sum(
            x is not None
            for x in (
                self._win_accum, self._win_pool_hw, self._win_seg_warn,
                self._join_probe, self._win_rumor_cov,
            )
        )
        return ds

    def jit_cache_audit(self) -> dict:
        """In-process jit-program cache audit + the persistent XLA cache
        report: which window programs exist, how often each dispatched, and
        what the first dispatch (compile or cache load) cost."""
        from .. import compile_cache as _cc

        with self._lock:  # _step_stats mutates under the lock in step()
            programs = [
                {
                    "n_ticks": k[0], "n_watch": k[1], "traced": k[2],
                    "calls": v["calls"],
                    "first_dispatch_s": v["first_dispatch_s"],
                }
                for k, v in sorted(self._step_stats.items())
            ]
        return {
            "programs": programs,
            "persistent_cache": _cc.compile_cache_report(),
        }

    # -- deferred-health properties (reading = one coalesced flush) ---------
    @property
    def health_counters(self) -> Dict[str, int]:
        self.flush()
        return self._health_counters

    @health_counters.setter
    def health_counters(self, value: Dict[str, int]) -> None:
        self._health_counters = dict(value)

    @property
    def pool_high_water(self) -> int:
        self.flush()
        return self._pool_high_water

    @pool_high_water.setter
    def pool_high_water(self, value: int) -> None:
        self._pool_high_water = int(value)

    @property
    def segmentation_warnings(self) -> int:
        self.flush()
        return self._segmentation_warnings

    @segmentation_warnings.setter
    def segmentation_warnings(self, value: int) -> None:
        self._segmentation_warnings = int(value)

    def run_until(
        self, predicate: Callable[["SimDriver"], bool], max_ticks: int = 10_000
    ) -> bool:
        for _ in range(max_ticks):
            if predicate(self):
                return True
            self.step()
        return predicate(self)

    # -- membership events (host-side diff of watched rows) ----------------
    def watch(self, row: int) -> EventStream:
        """Start emitting MembershipEvents as observed by node ``row``."""
        if row not in self._watches:
            key = np.asarray(self._eng.view_row(self.state, row))
            w = _Watch(row=row, prev_key=key)
            for j in np.nonzero(key >= 0)[0]:
                w.known[int(j)] = self._member_handle(int(j))
            self._watches[row] = w
        return self._watches[row].stream

    def events_of(self, row: int) -> List[MembershipEvent]:
        self.watch(row)
        return self._watches[row].log

    def _member_handle(self, row: int) -> Member:
        if row not in self.members:
            self.members[row] = Member(id=f"sim-{row}", address=row_address(row))
        return self.members[row]

    def _diff_row(self, w: _Watch, key: np.ndarray) -> None:
        changed = key != w.prev_key
        for j in np.nonzero(changed)[0]:
            j = int(j)
            old_k, new_k = int(w.prev_key[j]), int(key[j])
            old_s, new_s = _status_of_key(old_k), _status_of_key(new_k)
            evs: List[MembershipEvent] = []
            lay = self._lay
            old_e = (old_k >> lay.epoch_shift) & lay.epoch_mask if old_k >= 0 else -1
            new_e = (new_k >> lay.epoch_shift) & lay.epoch_mask if new_k >= 0 else -1
            if old_k >= 0 and new_k >= 0 and old_e != new_e:
                # Identity epoch flip: the row was re-occupied by a FRESH
                # member (restart = new member id). The old identity is gone
                # (the reference's DEST_GONE -> DEAD -> REMOVED,
                # FailureDetectorImpl.computeMemberStatus:382-404) and the
                # new one, if alive-ish, is a separate ADDED.
                if old_s not in (UNKNOWN, DEAD):
                    evs.append(
                        MembershipEvent.removed(w.known.pop(j, self._member_handle(j)))
                    )
                else:
                    w.known.pop(j, None)
                if new_s in (ALIVE, SUSPECT, LEAVING):
                    w.known[j] = self._member_handle(j)
                    evs.append(MembershipEvent.added(w.known[j]))
            # old DEAD counts as "not a member": REMOVED already fired when
            # the record went DEAD; a later DEAD->ALIVE flip (a zombie/rejoin
            # refutation beating the tombstone) is a fresh ADDED.
            elif old_s in (UNKNOWN, DEAD) and new_s in (ALIVE, SUSPECT, LEAVING):
                w.known[j] = self._member_handle(j)
                evs.append(MembershipEvent.added(w.known[j]))
            elif new_s == LEAVING and old_s != LEAVING:
                evs.append(
                    MembershipEvent.leaving(w.known.get(j, self._member_handle(j)))
                )
            elif new_s == DEAD and old_s != DEAD:
                # reference removes member+record on death and publishes
                # REMOVED (onDeadMemberDetected:740-767); the later
                # DEAD->UNKNOWN table cleanup is internal, not an event
                evs.append(
                    MembershipEvent.removed(w.known.pop(j, self._member_handle(j)))
                )
            elif (
                new_s == ALIVE
                and old_s in (ALIVE, SUSPECT)
                and ((new_k >> 2) & lay.inc_mask) > ((old_k >> 2) & lay.inc_mask)
            ):
                # incarnation bump while alive = metadata/refutation update
                evs.append(
                    MembershipEvent.updated(
                        w.known.get(j, self._member_handle(j)), None, None
                    )
                )
            for ev in evs:
                w.log.append(ev)
                w.stream.emit(ev)

    # -- lifecycle / churn --------------------------------------------------
    def _mutator(self, name: str, static_argnums=()) -> Callable:
        """One jitted+donated program per interactive host mutator (r19).

        The serving plane's sustained op rate cannot pay the eager
        spelling (each ``.at[].set`` is a separate device dispatch and a
        full copy-on-write of every touched plane); jitting the whole
        mutator makes each op one async dispatch updating the donated
        state in place, exactly like the window programs."""
        fn = self._mutator_jits.get(name)
        if fn is None:
            fn = jax.jit(
                getattr(self._ops, name),
                static_argnums=static_argnums,
                donate_argnums=0,
            )
            self._mutator_jits[name] = fn
        return fn

    def join(self, seed_rows: Sequence[int] = (0,)) -> int:
        """Activate a free row as a fresh member; returns its row.

        Prefers a row no up member still has records about — reusing a row
        whose previous occupant is still SUSPECT/DEAD in peers' tables would
        conflate the two identities (the reference's restart-on-same-address
        gets a fresh member id precisely to avoid this)."""
        with self._lock:
            return self._join_locked(seed_rows)

    def _join_locked(self, seed_rows: Sequence[int]) -> int:
        up = np.asarray(self.state.up)
        free = np.nonzero(~up)[0]
        if len(free) == 0:
            raise RuntimeError("no free rows (capacity exhausted)")
        remembered = np.asarray(  # [N] — some up member still has a record
            self._eng.remembered_rows(self.state)
        )
        forgotten = free[~remembered[free]]
        row = int(forgotten[0]) if len(forgotten) else int(free[0])
        self.state = self._mutator("join_row", static_argnums=2)(
            self.state, jnp.int32(row), tuple(seed_rows)
        )
        # a restart reuses the row but is a NEW member identity (reference:
        # rejoin after restart gets a fresh member id)
        self.members[row] = Member(
            id=f"sim-{self._next_member_ordinal}", address=row_address(row)
        )
        self._next_member_ordinal += 1
        # the joiner's self-announce can still drop if the pool holds ONLY
        # sub-majority-covered rumors (no eviction victim) — the exact
        # invisibility the /health endpoint exists to surface. The probe is
        # GATED on a registered health consumer (ADVICE r5: an unmonitored
        # interactive join must not pay a device→host sync) and even then
        # stays a DEVICE scalar, batched into the next flush() readback.
        if self._eng.has_pool and self._health_interest:
            in_pool = (
                (self.state.mr_subject == row) & self.state.mr_active
            ).any()
            miss = (~in_pool).astype(jnp.int32)
            self._join_probe = (
                miss if self._join_probe is None else self._join_probe + miss
            )
        # bounded: prune past the cohort horizon on append (a monitor may
        # never poll health_snapshot — churn runs join continuously); dedup
        # by row (a crash+rejoin within the horizon is a NEW identity — the
        # stale entry would report a phantom old cohort)
        tick = self.tick
        self._recent_joins = [
            (t, r) for (t, r) in self._recent_joins[-4096:]
            if tick - t <= self._join_horizon and r != row
        ]
        self._recent_joins.append((tick, row))
        self._rumor_cov_dirty = True  # up-set changed under the cache
        self._publish("driver", "join", row=row, member=self.members[row].id)
        return row

    def crash(self, row: int) -> None:
        with self._lock:
            self.state = self._ops.crash_row(self.state, row)
            self._rumor_cov_dirty = True  # up-set changed under the cache
            self._publish("driver", "crash", row=row)

    def leave(self, row: int, crash_after_ticks: int = 0) -> None:
        with self._lock:
            self.state = self._mutator("begin_leave")(
                self.state, jnp.int32(row)
            )
            self._publish("driver", "leave", row=row)
        if crash_after_ticks:
            self.step(crash_after_ticks)
            self.crash(row)

    def update_metadata(self, row: int) -> None:
        with self._lock:
            self.state = self._mutator("update_metadata")(
                self.state, jnp.int32(row)
            )

    def update_metadata_batch(self, rows: Sequence[int]) -> None:
        """Metadata bumps for a whole batch of rows in ONE dispatch (r19).

        At sustained serving rates the per-call overhead (pytree flatten,
        executable launch) dominates the sub-millisecond mutator itself, so
        operator consoles batch their bumps; a ``fori_loop`` threads the
        donated state through the batch on-device. One compile per batch
        length (use a fixed batch size)."""
        with self._lock:
            fn = self._mutator_jits.get("update_metadata_batch")
            if fn is None:
                ops = self._ops

                def _batch(state, batch_rows):
                    def body(i, s):
                        return ops.update_metadata(s, batch_rows[i])

                    return jax.lax.fori_loop(
                        0, batch_rows.shape[0], body, state
                    )

                fn = jax.jit(_batch, donate_argnums=0)
                self._mutator_jits["update_metadata_batch"] = fn
            self.state = fn(self.state, jnp.asarray(rows, jnp.int32))

    # -- rumors (spreadGossip) ----------------------------------------------
    def spread_rumor(self, origin: int, payload: object) -> int:
        """Start a user rumor; returns its slot. Payloads live host-side.

        Slot allocation is HOST-tracked (r8, same bug class as the r6
        ``join()`` fix): the old path scanned ``rumor_active`` with a
        blocking ``np.asarray`` on every call, syncing the whole donated
        pipeline per interactive spread. Now a free-slot list is maintained
        host-side; only when it runs dry (every host-known slot spent) does
        the call pay ONE coalesced readback to reclaim slots the device
        rumor sweep has since freed."""
        with self._lock:
            slot = self._claim_rumor_slot_locked()
            if self.engine == "dense":
                # slot stays STATIC here (the dense engine's packed infection
                # plane resolves it to a bit-word index at trace time); the
                # pool is bounded, so the per-slot compiles are too
                self.state = self._mutator("spread_rumor", static_argnums=1)(
                    self.state, slot, jnp.int32(origin)
                )
            else:
                # sparse/pview spreads are pure scatter updates, so the slot
                # can ride as a traced operand: one compile serves the pool
                self.state = self._mutator("spread_rumor")(
                    self.state, jnp.int32(slot), jnp.int32(origin)
                )
            self._rumor_payloads[slot] = payload
            self._rumor_cov_dirty = True  # cached coverage predates this rumor
            self._rumor_spread_pending[slot] = self._host_tick
            self._publish("driver", "rumor_spread", slot=slot, origin=origin)
            return slot

    def _claim_rumor_slot_locked(self) -> int:
        if self._free_rumor_slots is None:
            # unknown after restore: rebuild from the checkpointed state
            self._free_rumor_slots = self._reclaim_rumor_slots_locked()
        if not self._free_rumor_slots:
            self._free_rumor_slots = self._reclaim_rumor_slots_locked()
        if not self._free_rumor_slots:
            raise RuntimeError("no free rumor slots")
        return self._free_rumor_slots.pop(0)

    def _reclaim_rumor_slots_locked(self) -> list:
        """One coalesced ``rumor_active`` readback (value semantics: reflects
        every enqueued window) — the exhausted-list slow path only."""
        active = np.asarray(self.state.rumor_active)
        self._note_readback(1)
        return [int(s) for s in np.nonzero(~active)[0]]

    def rumor_coverage(self, slot: int) -> float:
        """Fraction of up members infected with rumor ``slot``, evaluated at
        the last window boundary. r8: reads the DEFERRED end-of-window
        coverage vector (flushed with the other health accumulators — an
        [R] transfer at the sync point) instead of pulling the full [N]
        infection plane per call. When host mutations postdate the last
        window (a rumor just spread, a member crashed), one jitted [R]
        device reduce refreshes the cache instead."""
        with self._lock:
            self._flush_locked()
            if self._rumor_cov_host is None or self._rumor_cov_dirty:
                if not hasattr(self, "_cov_fn"):
                    def _cov(state):
                        up = state.up
                        # dense stores the bitmap word-packed (r9); sparse
                        # still carries bools — branch at trace time
                        inf = (
                            state.infected_bool
                            if hasattr(state, "infected_bool")
                            else state.infected
                        )
                        return (
                            (inf & up[:, None]).sum(0).astype(jnp.float32)
                            / jnp.maximum(up.sum(), 1)
                        )

                    self._cov_fn = jax.jit(_cov)
                self._rumor_cov_host = np.asarray(self._cov_fn(self.state))
                self._rumor_cov_dirty = False
                self._note_readback(1)
            return float(self._rumor_cov_host[slot])

    def rumor_payload(self, slot: int) -> object:
        return self._rumor_payloads.get(slot)

    # -- links (NetworkEmulator surface) ------------------------------------
    def set_link_loss(self, src, dst, loss: float) -> None:
        self.state = self._ops.set_link_loss(self.state, src, dst, loss)

    def set_link_delay(self, src, dst, mean_delay_ticks: float) -> None:
        """Outbound mean delay in ticks (emulator delay half; needs
        ``params.delay_slots > 0``)."""
        self.state = self._ops.set_link_delay(self.state, src, dst, mean_delay_ticks)

    def block_partition(self, group_a, group_b) -> None:
        self.state = self._ops.block_partition(self.state, group_a, group_b)

    def heal_partition(self, group_a, group_b) -> None:
        self.state = self._ops.heal_partition(self.state, group_a, group_b)

    def link_loss(self, src: int, dst: int) -> float:
        # scalar uniform-loss mode (init_state(dense_links=False)) has no
        # per-link matrix to index — mirror kernel._loss_at
        if self.state.loss.ndim == 0:
            return float(self.state.loss)
        return float(self.state.loss[src, dst])

    # -- views --------------------------------------------------------------
    def view_of(self, row: int) -> tuple[np.ndarray, np.ndarray]:
        """(status, incarnation) of node ``row``'s table — one device gather.
        Lock-guarded: sim_snapshot calls this from the monitor thread, and
        the read must not interleave with a donating step."""
        with self._lock:
            key = np.asarray(self._eng.view_row(self.state, row))
        status = np.where(key < 0, np.int8(UNKNOWN), _RANK_TO_STATUS_NP[key & 3])
        inc = np.where(key < 0, 0, (key >> 2) & self._lay.inc_mask).astype(np.int32)
        return status, inc

    def status_of(self, observer: int, subject: int) -> MemberStatus | None:
        with self._lock:
            s = _status_of_key(
                int(self._eng.view_row(self.state, observer)[subject])
            )
        return None if s == UNKNOWN else MemberStatus(s)

    def is_up(self, row: int) -> bool:
        with self._lock:
            return bool(self.state.up[row])

    # -- engine health (VERDICT r4 item 8) -----------------------------------
    def health_snapshot(self) -> dict:
        """Live protocol-health view: rumor-pool backpressure (occupancy,
        high-water, per-source announce drops, priority evictions) plus
        identity-dissemination staleness — per-subject counts of up
        observers that have not yet learned a subject's current identity,
        and lag cohorts for recent driver ``join()``s. This is the failure
        mode the r4 49k churn run exposed (pool saturation -> dropped join
        announces -> joiners invisible for tens of seconds), surfaced as a
        monitor snapshot instead of a benchmark-only artifact.

        The staleness reduce is one fused [N, N] pass on device, computed
        on demand (monitor polling cadence, not tick cadence). Calling this
        registers health interest (enabling the join() in-pool probe) and
        performs the coalesced flush of every deferred per-window
        reduction — this is the pipelined driver's one sync point. Safe to
        call from the monitor thread while the sim thread steps (the
        driver lock serializes against donation)."""
        with self._lock:
            return self._health_snapshot_locked()

    def _health_snapshot_locked(self) -> dict:
        self._health_interest = True
        self._flush_locked()
        if not hasattr(self, "_health_fn"):
            # the engine's staleness reduce (engine_api seam): dense/sparse
            # run the [N, N] identity-lag pass, pview the table-edge one
            self._health_fn = jax.jit(self._eng.staleness)
        stale, n_up = self._health_fn(self.state)
        stale = np.asarray(stale)
        n_up = int(n_up)
        observers = max(n_up - 1, 1)
        tick = self.tick
        self._recent_joins = [
            (t, r) for (t, r) in self._recent_joins
            if 0 <= tick - t <= self._join_horizon
        ]
        cohorts = [
            {
                "row": r,
                "age_ticks": tick - t,
                "coverage": round(1.0 - float(stale[r]) / observers, 4),
            }
            for (t, r) in self._recent_joins
            if bool(self.state.up[r])
        ]
        out = {
            "engine": self.engine,
            "tick": tick,
            "n_up": n_up,
            "announce": dict(self._health_counters),
            "dispatch": self.dispatch_snapshot(),
            "staleness": {
                "stale_subjects": int((stale > 0).sum()),
                "worst_subject_stale_observers": int(stale.max()) if stale.size else 0,
                "recent_join_cohorts": cohorts,
                "worst_recent_join_coverage": (
                    min(c["coverage"] for c in cohorts) if cohorts else None
                ),
            },
        }
        # r8: per-slot user-rumor coverage from the DEFERRED end-of-window
        # vector (flushed above with the other accumulators — never a fresh
        # [N]-plane readback). ``stale`` marks host mutations (a spread, a
        # crash) newer than the last window boundary.
        cov = self._rumor_cov_host
        out["rumors"] = {
            "tracked_slots": sorted(self._rumor_payloads),
            "coverage": (
                {
                    int(s): round(float(cov[s]), 4)
                    for s in sorted(self._rumor_payloads)
                    if s < len(cov)
                }
                if cov is not None
                else None
            ),
            "stale": bool(self._rumor_cov_dirty),
        }
        if self._eng.has_pool:
            out["pool"] = {
                "mr_slots": self._eng.pool_slots(self.params),
                "active_now": int(np.asarray(self.state.mr_active).sum()),
                "high_water": self._pool_high_water,
            }
        if self._chaos is not None:
            out["chaos"] = self._chaos.snapshot()
        if self._trace is not None:
            # host-only counters (cursor arithmetic) — the ring itself is
            # NOT read here; /trace is the ring's sync point
            out["trace"] = self._trace.stats()
        if self._control is not None:
            # r16: rung + loop counters (host-only); the full decision
            # log lives on GET /control
            snap = self._control.snapshot()
            out["control"] = {
                k: snap[k]
                for k in ("rung", "rung_name", "actuated", "epoch",
                          "actuations", "stale_epochs", "last_sensors")
            }
        return out

    def enable_health_probes(self) -> None:
        """Register health interest without taking a snapshot (called by
        ``MonitorServer.register_health``): turns on the join() in-pool
        probe so host-path announce drops are counted from now on."""
        self._health_interest = True

    # -- telemetry plane (r8: rings + bus + /metrics + flight recorder) ------
    def arm_telemetry(self, config=None, bus=None):
        """Arm the telemetry plane on this driver; returns the
        :class:`..telemetry.TelemetryPlane`. ``config`` is a
        :class:`..config.ClusterConfig` or :class:`..config.TelemetryConfig`
        (None = defaults); ``bus`` an existing :class:`..telemetry
        .TelemetryBus` to merge into (e.g. one shared with transports).

        Arming is a pure consumer: per window it appends ONE f32 row to the
        on-device metric ring (a jnp reduction over the window's metric
        outputs — never state the tick reads back), so the armed driver
        keeps the r6 zero-per-window-readback discipline AND a bit-identical
        trajectory (tests/test_telemetry.py holds both properties)."""
        from ..config import ClusterConfig
        from ..telemetry.plane import TelemetryPlane

        with self._lock:
            if self._telemetry is not None:
                return self._telemetry
            if isinstance(config, ClusterConfig):
                config = config.telemetry
            self._telemetry = TelemetryPlane(self, config=config, bus=bus)
            self._telemetry.bus.publish(
                "driver", "telemetry_armed", tick=self._host_tick,
                engine=self.engine,
                capacity=self.params.capacity,
            )
            return self._telemetry

    @property
    def telemetry(self):
        """The armed :class:`..telemetry.TelemetryPlane`, or None."""
        return self._telemetry

    # -- causal trace plane (r10: span capture + Perfetto export) -------------
    def arm_trace(self, config=None, tracer_rows=None, rumor_slots=None):
        """Arm the causal trace plane; returns the
        :class:`..trace.TracePlane`. ``config`` is a
        :class:`..config.ClusterConfig` or :class:`..config.TraceConfig`
        (None = defaults: the first ``TraceConfig.tracers`` rows);
        ``tracer_rows`` / ``rumor_slots`` override the config's sampling.

        Arming swaps the window programs for the traced builders: every
        tick appends one [K, n_fields] int32 record block to the donated
        device trace ring INSIDE the window jit. The trajectory stays
        bit-identical to an unarmed driver and steady-state ``step()``
        stays transfer-free (tests/test_trace.py holds both); ring reads
        happen only at sync points (``/trace`` scrape, flight dump,
        :meth:`..trace.TracePlane.snapshot`)."""
        from ..config import ClusterConfig
        from ..trace.plane import TracePlane

        with self._lock:
            if self._trace is not None:
                return self._trace
            if self._ad is not None:
                raise ValueError(
                    "trace capture and adaptive failure detection cannot "
                    "share a driver yet — use set_adaptive(None) first, or "
                    "trace a static-FD driver"
                )
            if self._control is not None:
                raise ValueError(
                    "trace capture and the control plane cannot share a "
                    "driver (the controller may arm adaptive FD)"
                )
            if self.mesh is not None and self._eng.make_sharded_traced_run is None:
                # capability-named refusal: only engines registering a
                # sharded traced builder (pview, r20) capture on a mesh
                raise ValueError(
                    f"trace capture is single-device for the {self.engine} "
                    "engine — arm on an unsharded driver (the ring append "
                    "is row-global)"
                )
            if isinstance(config, ClusterConfig):
                config = config.trace
            self._trace = TracePlane(
                self, config=config, tracer_rows=tracer_rows,
                rumor_slots=rumor_slots,
            )
            if self.mesh is not None:
                # r20 trace-on-mesh: the ring must live REPLICATED on the
                # mesh — a default-device ring would force GSPMD to move
                # it every window append
                from ..ops.sharding import place_replicated

                ring = self._trace.ring
                ring.buf = place_replicated(ring.buf, self.mesh)
                ring._mesh = self.mesh
            self._publish(
                "driver", "trace_armed",
                tracers=list(self._trace.spec.tracer_rows),
                rumor_slots=list(self._trace.spec.rumor_slots),
            )
            return self._trace

    @property
    def trace(self):
        """The armed :class:`..trace.TracePlane`, or None."""
        return self._trace

    def _publish(self, source: str, kind: str, **fields) -> None:
        """Emit one host-side lifecycle record onto the armed telemetry bus
        (no-op when unarmed; never touches the device)."""
        if self._telemetry is not None:
            self._telemetry.bus.publish(
                source, kind, tick=self._host_tick, **fields
            )

    # -- chaos scenarios (fault timelines + invariant sentinels) -------------
    def set_dissemination(self, spec=None, *, strategy=None, topology=None,
                          **spec_kw) -> None:
        """Swap the dissemination strategy/topology (r13) on a live driver.

        Pass a full :class:`..dissemination.DissemSpec`, or field overrides
        (``strategy=``/``topology=``/any other spec field) applied on top
        of the current spec. The spec is a STATIC program property: the
        compiled window cache is invalidated and the next step compiles
        the strategy-armed windows (the state itself is spec-independent,
        so no state migration happens and checkpoints stay compatible).
        A no-op when the requested spec equals the armed one."""
        import dataclasses as _dc

        from ..dissemination import DissemSpec

        with self._lock:
            cur = getattr(self.params, "dissem", DissemSpec())
            if spec is None:
                overrides = {
                    k: v
                    for k, v in dict(
                        strategy=strategy, topology=topology, **spec_kw
                    ).items()
                    if v is not None
                }
                spec = _dc.replace(cur, **overrides) if overrides else cur
            if spec == cur:
                return
            self.params = _dc.replace(self.params, dissem=spec)
            self._step_cache.clear()
            self._step_stats.clear()

    def set_adaptive(self, spec=None, *, enabled: bool | None = None,
                     **spec_kw) -> None:
        """Swap the adaptive-FD spec (r14) on a live driver.

        Pass a full :class:`..adaptive.AdaptiveSpec` (or ``None`` plus
        field overrides applied to the current spec; ``set_adaptive(None)``
        with no overrides DISARMS). Like :meth:`set_dissemination` the spec
        is a static program property — the window cache is invalidated —
        but arming/disarming also creates/drops the AdaptiveState planes:
        local-health and confirmation memory start fresh (scores are
        evidence about the CURRENT network conditions; a knob change is a
        new experiment)."""
        import dataclasses as _dc

        from ..adaptive import AdaptiveSpec, init_adaptive_state

        with self._lock:
            cur = getattr(self.params, "adaptive", AdaptiveSpec())
            if spec is None:
                overrides = {
                    k: v
                    for k, v in dict(enabled=enabled, **spec_kw).items()
                    if v is not None
                }
                spec = (
                    _dc.replace(cur, **overrides)
                    if overrides
                    else AdaptiveSpec()
                )
            if spec == cur and (self._ad is not None) == (not spec.is_default):
                return
            if not spec.is_default:
                if (self.mesh is not None
                        and self._eng.make_sharded_adaptive_run is None):
                    raise ValueError(
                        "adaptive failure detection is single-device for "
                        f"the {self.engine} engine"
                    )
                if self._trace is not None:
                    raise ValueError(
                        "trace capture and adaptive failure detection cannot "
                        "share a driver yet"
                    )
            self.params = _dc.replace(self.params, adaptive=spec)
            if spec.is_default:
                self._ad = None
            else:
                self._ad = init_adaptive_state(self.params.capacity)
                if self.mesh is not None:
                    from ..ops.sharding import shard_adaptive_state

                    self._ad = shard_adaptive_state(self._ad, self.mesh)
            self._step_cache.clear()
            self._step_stats.clear()

    @property
    def adaptive_state(self):
        """The armed :class:`..adaptive.AdaptiveState`, or None (static FD)."""
        return self._ad

    def set_protocol_knobs(self, *, fanout: int | None = None,
                           suspicion_mult: int | None = None) -> None:
        """Live-swap static protocol knobs (r16 control actuator): gossip
        ``fanout`` and/or the static ``suspicion_mult``. Like the r13/r14
        swaps these are STATIC program properties — the compiled window
        cache is invalidated, the state itself is untouched (no knob
        lives in a state plane), and checkpoints stay compatible. A no-op
        when nothing changes."""
        import dataclasses as _dc

        with self._lock:
            updates = {}
            if fanout is not None and fanout != self.params.fanout:
                if fanout < 1:
                    raise ValueError("fanout must be >= 1")
                updates["fanout"] = int(fanout)
            if (
                suspicion_mult is not None
                and suspicion_mult != self.params.suspicion_mult
            ):
                if suspicion_mult < 1:
                    raise ValueError("suspicion_mult must be >= 1")
                updates["suspicion_mult"] = int(suspicion_mult)
            if not updates:
                return
            self.params = _dc.replace(self.params, **updates)
            self._step_cache.clear()
            self._step_stats.clear()

    # -- closed-loop control plane (r16: telemetry-driven knob steering) -----
    def arm_control(self, spec=None, config=None):
        """Arm the closed-loop control plane (r16); returns the
        :class:`..control.ControlPlane`. ``spec`` is a
        :class:`..control.ControlSpec` (None = defaults, or derived from
        ``config`` — a :class:`..config.ClusterConfig`). Requires (and
        auto-arms) the telemetry plane: the metric ring is the sensor.

        Arming is knob-PASSIVE: no knob changes until the decision rule
        fires, so an armed-but-idle driver's trajectory is bit-identical
        to an unarmed one (tests/test_control.py pins it). Sensor reads
        happen at control-epoch cadence and are sync points of the same
        contract as monitor polls."""
        from ..control import ControlPlane

        with self._lock:
            if self._control is not None:
                return self._control
            if self.mesh is not None:
                # r21 mesh lift: the actuators are mesh-capable now —
                # set_dissemination / set_protocol_knobs are cache clears
                # (sharded windows rebuild on the next step) and
                # set_adaptive shards through make_sharded_adaptive_run.
                # The one capability still missing is a sharded adaptive
                # window, so only a ladder whose rungs would arm adaptive
                # FD keeps a (narrowed, capability-named) refusal.
                from ..config import ClusterConfig
                from ..control import ControlSpec

                resolved = spec
                if resolved is None:
                    resolved = (
                        ControlSpec.from_config(config)
                        if isinstance(config, ClusterConfig) else ControlSpec()
                    )
                if self._eng.make_sharded_adaptive_run is None and any(
                    r.adaptive for r in resolved.ladder
                ):
                    raise ValueError(
                        "this ladder's rungs arm adaptive FD, and the "
                        f"{self.engine} engine has no sharded adaptive "
                        "window builder (make_sharded_adaptive_run) — use "
                        "a static-rung ladder or an unsharded driver"
                    )
            if self._trace is not None:
                raise ValueError(
                    "trace capture and the control plane cannot share a "
                    "driver (the controller may arm adaptive FD, which "
                    "traced windows do not support yet)"
                )
            self._control = ControlPlane(self, spec=spec, config=config)
            return self._control

    @property
    def control(self):
        """The armed :class:`..control.ControlPlane`, or None."""
        return self._control

    def control_snapshot(self) -> dict:
        """Live controller view (``GET /control``): spec + rung + the
        bounded decision log, or ``{"armed": False}``. Host values only —
        never a device read."""
        plane = self._control
        if plane is None:
            return {"armed": False}
        return plane.snapshot()

    def run_scenario(
        self,
        scenario,
        *,
        config=None,
        sentinels: bool = True,
        max_window: int = 32,
        trace: bool = False,
        strategy: str | None = None,
        topology: str | None = None,
        dissem=None,
        adaptive=None,
    ) -> dict:
        """Run a :class:`..chaos.Scenario` against this driver: scripted
        fault events applied between windows (partitions, loss storms, link
        flaps, crashes, restarts) with the on-device SWIM invariant
        sentinels armed. Stepping stays transfer-free (the r6 pipelined
        discipline — fault injection and sentinel checks are pure device
        ops); the returned structured report is the one sync point. The
        same scenario object runs unmodified on the dense, sparse, and
        mesh-sharded drivers, and on the scalar engine via
        :class:`..chaos.EmulatorChaosRunner`.

        ``trace=True`` auto-attaches the causal trace plane (r10) before
        arming: the scenario's crashed rows become tracer members (an
        already-armed plane is reused as-is), so sentinel violations — and
        successful detections — resolve to sewn probe-miss → suspect →
        DEAD span trees in the report.

        ``strategy=`` / ``topology=`` / ``dissem=`` (r13) arm a
        dissemination spec via :meth:`set_dissemination` before the
        scenario runs; the sentinel budgets are derived strategy-aware
        (deterministic schedules tighten re-convergence, WAN-delayed geo
        loosens it — ``chaos.sentinels.dissemination_budget_scale``)."""
        from ..chaos.engine import run_driver_scenario

        if dissem is not None or strategy is not None or topology is not None:
            self.set_dissemination(dissem, strategy=strategy, topology=topology)
        if adaptive is not None:
            # r14: arm (or swap) the adaptive-FD plane before the scenario
            self.set_adaptive(adaptive)
        return run_driver_scenario(
            self, scenario, config=config, sentinels=sentinels,
            max_window=max_window, trace=trace,
        )

    def chaos_snapshot(self) -> dict:
        """Live chaos view (``GET /chaos``): the armed scenario's progress +
        sentinel report, or ``{"armed": False}`` when none was ever armed.
        Reading sentinel accumulators is a sync point, like every other
        snapshot — poll cadence, not window cadence."""
        runner = self._chaos
        if runner is None:
            return {"armed": False}
        return runner.snapshot()

    # -- checkpoint/resume ---------------------------------------------------
    def checkpoint(self, path: str) -> None:
        """Full resumable snapshot: device state + RNG chains + the host-side
        identity map and rumor payloads (restoring into a fresh driver must
        reproduce the same member ids and payloads, not refabricate them).

        Crash-safe: the archive is written to a temp file in the target
        directory, fsynced, and moved into place with ``os.replace`` — a
        crash mid-write can never leave a half-written file under ``path``.
        The archive embeds a schema version, the engine name, and a CRC32 of
        the host-side pickle; :meth:`restore` verifies all three and raises
        :class:`CheckpointError` on truncated/corrupt/foreign files."""
        import os
        import pickle
        import tempfile
        import zlib

        with self._lock:
            payload = self._checkpoint_payload_locked(pickle, zlib)
        # mkstemp, not a pid-derived name: two concurrent checkpoint()s to
        # the same path (monitor thread + user thread) must not truncate
        # each other's half-written archive — each writes its own file and
        # the os.replace()s serialize at the filesystem
        target = os.path.abspath(path)
        fd, tmp = tempfile.mkstemp(
            prefix=os.path.basename(target) + ".tmp-",
            dir=os.path.dirname(target),
        )
        try:
            with os.fdopen(fd, "wb") as fh:
                np.savez_compressed(fh, **payload)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, target)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        self._publish("checkpoint", "saved", path=target)

    def _checkpoint_payload_locked(self, pickle, zlib) -> dict:
        self._flush_locked()  # fold staged device reductions into host counters
        host = {
            "members": dict(self.members),
            "rumor_payloads": dict(self._rumor_payloads),
            "next_member_ordinal": self._next_member_ordinal,
            "rng": self._rng.bit_generator.state,
            "metrics_len": len(self.metrics_history),
            # health accumulators belong to the timeline being checkpointed —
            # restoring must not report drops/joins from the abandoned branch
            "health_counters": dict(self._health_counters),
            "pool_high_water": self._pool_high_water,
            "segmentation_warnings": self._segmentation_warnings,
            "recent_joins": list(self._recent_joins),
            # r8: the host-tracked free rumor slots follow the timeline
            # (None on load = unknown -> lazily reclaimed from the state)
            "free_rumor_slots": (
                list(self._free_rumor_slots)
                if self._free_rumor_slots is not None else None
            ),
        }
        if self._control is not None:
            # r16: controller memory (rung, dwell, decision log) follows
            # the timeline — restoring must not replay dwell the
            # abandoned branch accumulated (host dict key; optional, so
            # older checkpoints and control-less drivers are unaffected)
            host["control_state"] = self._control.state_dict()
        host_bytes = pickle.dumps(host)
        payload = dict(
            self._ops.snapshot(self.state),
            _key=np.asarray(self._key),
            _host=np.frombuffer(host_bytes, dtype=np.uint8),
            _schema=np.int32(CHECKPOINT_SCHEMA),
            _crc32=np.uint32(zlib.crc32(host_bytes) & 0xFFFFFFFF),
            _engine=np.bytes_(self.engine.encode()),
        )
        if self._ad is not None:
            # r14: the adaptive planes follow the timeline (optional keys —
            # schema unchanged; a static-FD restore ignores them)
            from ..adaptive import adaptive_state_arrays

            payload.update(adaptive_state_arrays(self._ad))
        return payload

    def restore(self, path: str) -> None:
        import pickle

        try:
            with self._lock:
                self._restore_locked(path, pickle)
        except CheckpointError as exc:
            # a failed restore is a post-mortem moment: flight-record the
            # last K windows + event tail before surfacing the error
            if self._telemetry is not None:
                self._telemetry.flight_record(
                    "checkpoint_error",
                    context={"path": path, "error": str(exc)},
                )
            raise
        self._publish("checkpoint", "restored", path=path)

    def _restore_locked(self, path: str, pickle) -> None:
        import zlib

        try:
            with np.load(path) as npz:
                data = dict(npz)
        except FileNotFoundError:
            raise
        except Exception as exc:  # zipfile/npy deep failures -> one clear error
            raise CheckpointError(
                f"checkpoint {path!r} is unreadable (truncated or corrupt): {exc}"
            ) from exc
        schema = int(data.pop("_schema", 1))
        if schema > CHECKPOINT_SCHEMA:
            raise CheckpointError(
                f"checkpoint {path!r} has schema {schema}, newer than this "
                f"build's {CHECKPOINT_SCHEMA} — refusing a partial decode"
            )
        engine_raw = data.pop("_engine", None)
        if engine_raw is not None:
            engine = bytes(engine_raw.tobytes()).rstrip(b"\x00").decode()
            mine = self.engine
            if engine != mine:
                raise CheckpointError(
                    f"checkpoint {path!r} was written by the {engine} engine; "
                    f"this driver runs the {mine} engine"
                )
        crc_expect = data.pop("_crc32", None)
        if "_key" not in data or "_host" not in data:
            raise CheckpointError(
                f"checkpoint {path!r} is missing required members (truncated?)"
            )
        host_bytes = data.pop("_host").tobytes()
        if crc_expect is not None and (
            zlib.crc32(host_bytes) & 0xFFFFFFFF
        ) != int(crc_expect):
            raise CheckpointError(
                f"checkpoint {path!r} failed its CRC32 check (corrupt)"
            )
        # copy=True: asarray may zero-copy the aligned npz buffer (see
        # ops.state.restore) and the key rides through every jitted window
        self._key = jax.numpy.array(data.pop("_key"), copy=True)
        try:
            host = pickle.loads(host_bytes)
        except Exception as exc:
            raise CheckpointError(
                f"checkpoint {path!r} host section does not unpickle: {exc}"
            ) from exc
        self.members = host["members"]
        self._rumor_payloads = host["rumor_payloads"]
        self._next_member_ordinal = host["next_member_ordinal"]
        self._rng = np.random.default_rng()
        self._rng.bit_generator.state = host["rng"]
        del self.metrics_history[host["metrics_len"] :]  # drop abandoned timeline
        # staged reductions belong to the abandoned timeline — discard them
        self._win_accum = self._win_pool_hw = self._win_seg_warn = None
        self._join_probe = None
        self._win_rumor_cov = None
        self._rumor_cov_host = None
        self._rumor_cov_dirty = True
        self._rumor_spread_pending = {}
        # None = unknown (pre-r8 checkpoint): reclaimed lazily from the
        # restored state on the next spread_rumor
        self._free_rumor_slots = host.get("free_rumor_slots")
        # host tick shadow re-seeds from the checkpoint's tick plane (a
        # host-side numpy value — not a device read)
        self._host_tick = int(data["tick"])
        self._health_counters = dict(
            host.get("health_counters", {k: 0 for k in self._health_counters})
        )
        self._pool_high_water = host.get("pool_high_water", 0)
        # pre-r6 checkpoints lack the field; 0 matches the timeline rule
        # (warnings from the abandoned branch must not survive a restore)
        self._segmentation_warnings = host.get("segmentation_warnings", 0)
        self._recent_joins = [tuple(j) for j in host.get("recent_joins", [])]
        # r16: restore controller memory into an armed control plane (an
        # actuated rung re-applies its knobs — params are construction
        # state, not checkpoint state). A control-LESS checkpoint resets
        # an armed controller to fresh memory (abandoned-branch decisions
        # must not survive the timeline switch, and an actuated plane
        # re-bases to the ladder's base rung); a control-armed checkpoint
        # restored into a plane-less driver is ignored. ORDER MATTERS:
        # the rung re-application runs BEFORE the adaptive planes restore
        # below — set_adaptive's new-experiment reset must not discard
        # the evidence the checkpoint carries (the checkpoint's planes
        # were accumulated under the checkpoint's own rung).
        if self._control is not None:
            if "control_state" in host:
                self._control.load_state_dict(host["control_state"])
            else:
                self._control.reset_for_restore()
        # r14 adaptive planes: optional keys, popped BEFORE the engine
        # restore (they are not engine state planes). An adaptive-armed
        # driver restoring a static-FD checkpoint starts with fresh scores.
        ad_arrays = {
            k: data.pop(k)
            for k in ("_ad_lh", "_ad_conf_key", "_ad_conf")
            if k in data
        }
        if self._ad is not None:
            from ..adaptive import init_adaptive_state, restore_adaptive_state

            self._ad = (
                restore_adaptive_state(ad_arrays)
                if len(ad_arrays) == 3
                else init_adaptive_state(self.params.capacity)
            )
        try:
            state = self._ops.restore(data)
        except TypeError as exc:  # missing/extra planes: foreign or truncated
            raise CheckpointError(
                f"checkpoint {path!r} state planes do not match this engine: {exc}"
            ) from exc
        if self._eng.key_plane is not None:
            # a key-dtype mismatch would silently retrace every window
            # program against foreign-layout keys (i16 decode rules applied
            # to i32 bits, or vice versa) — refuse up front instead
            want = np.dtype(key_np_dtype(self.params.key_dtype))
            have = np.dtype(self._eng.key_plane(state).dtype)
            if have != want:
                raise CheckpointError(
                    f"checkpoint {path!r} stores {have} keys "
                    f"but this driver runs plane_dtype={self.params.key_dtype!r}"
                    " — restore into a driver configured for the stored layout"
                )
        if self.mesh is not None:
            state = self._eng.shard_state(state, self.mesh)
        self.state = state
        # reset the trace plane: clear the ring (decode orders records by
        # tick, so records from the abandoned timeline would sew into the
        # restored one as phantom lineage) and re-baseline the
        # window-boundary column mirror
        if self._trace is not None:
            self._trace.on_restore(state)
        # re-baseline watches so restore doesn't emit phantom events
        for w in self._watches.values():
            w.prev_key = np.asarray(self._eng.view_row(self.state, w.row))
            w.known = {
                int(j): self.members.get(int(j), self._member_handle(int(j)))
                for j in np.nonzero(w.prev_key >= 0)[0]
            }
