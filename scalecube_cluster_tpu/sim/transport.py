"""The Transport SPI over the simulated mesh.

Implements the same 4-method contract as the memory/TCP transports
(``Transport.java:11-79``: send / request_response / listen / start·stop) for
addresses ``sim://<row>``, so user-messaging code and the testlib scenario
helpers run unmodified against simulated members (SURVEY.md §7 stage 5).

Delivery honors the sim's directed link-loss matrix with draws from the
driver's host RNG — the same emulation semantics the kernel applies to
protocol traffic (loss 1.0 = blocked link ⇒ PeerUnavailableError surfaces as
a timeout for request_response, silent drop for send, exactly like the
NetworkEmulatorTransport decorator, ``NetworkEmulatorTransport.java:50-75``).
Messages are stamped with the sender header like SenderAwareTransport
(``ClusterImpl.java:587-603``).
"""

from __future__ import annotations

import asyncio

from ..models.message import HEADER_SENDER, Message
from ..transport.api import Listeners, Transport, TransportError
from .driver import SimDriver, row_address


def _parse_row(address: str) -> int:
    if not address.startswith("sim://"):
        raise TransportError(f"not a sim address: {address}")
    return int(address[len("sim://") :])


class SimTransport(Transport):
    """Messaging endpoint of one simulated member."""

    def __init__(self, driver: SimDriver, row: int):
        self._d = driver
        self.row = row
        self._listeners = Listeners()
        self._stopped = False

    # -- Transport contract --------------------------------------------------
    @property
    def address(self) -> str:
        return row_address(self.row)

    @property
    def is_stopped(self) -> bool:
        return self._stopped or not self._d.is_up(self.row)

    async def start(self) -> "SimTransport":
        self._stopped = False
        return self

    async def stop(self) -> None:
        self._stopped = True

    def listen(self) -> Listeners:
        return self._listeners

    async def send(self, address: str, message: Message) -> None:
        if self.is_stopped:
            raise TransportError("transport is stopped")
        dst = _parse_row(address)
        peer = self._d._transports.get(dst)
        if peer is None or peer.is_stopped or not self._d.is_up(dst):
            # fire-and-forget against a gone peer: silently dropped on the
            # wire (connection failure surfaces only for request_response)
            return
        loss = self._d.link_loss(self.row, dst)
        if loss > 0.0 and self._d._rng.random() < loss:
            return  # dropped by the emulated link
        stamped = message.with_header(HEADER_SENDER, self.address)
        loop = asyncio.get_running_loop()
        loop.call_soon(peer._listeners.emit, stamped)

    # request_response: inherited cid-filtered implementation (Transport base)
