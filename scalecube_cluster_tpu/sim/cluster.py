"""Cluster-facade-shaped handles over simulated members.

``SimCluster`` drives M virtual members from one object (SURVEY.md §2.2
"in sim mode a SimCluster drives M virtual members from one object");
``SimNode`` mirrors the reference ``Cluster`` surface
(``Cluster.java:10-151``: member/members/otherMembers/member(id|addr)/
metadata/updateMetadata/spreadGossip/listen·Membership/shutdown) for one
row. Messaging (``send``/``requestResponse``) is provided by
:class:`.transport.SimTransport`, reachable via :meth:`SimNode.transport`.
"""

from __future__ import annotations

from typing import List, Optional

from ..models.events import MembershipEvent
from ..models.member import Member, MemberStatus
from ..utils.streams import EventStream
from ..ops.lattice import ALIVE, LEAVING, SUSPECT, UNKNOWN
from .driver import SimDriver, row_address


class SimNode:
    """One simulated member, presented through the Cluster facade surface."""

    def __init__(self, driver: SimDriver, row: int):
        self._d = driver
        self.row = row

    # -- identity -----------------------------------------------------------
    @property
    def member(self) -> Member:
        return self._d._member_handle(self.row)

    @property
    def address(self) -> str:
        return row_address(self.row)

    # -- membership views (reference Cluster.members/otherMembers) ----------
    def members(self) -> List[Member]:
        status, _ = self._d.view_of(self.row)
        return [
            self._d._member_handle(int(j))
            for j in range(len(status))
            if status[j] in (ALIVE, SUSPECT, LEAVING)
        ]

    def other_members(self) -> List[Member]:
        return [m for m in self.members() if m.id != self.member.id]

    def member_by_id(self, member_id: str) -> Optional[Member]:
        return next((m for m in self.members() if m.id == member_id), None)

    def member_by_address(self, address: str) -> Optional[Member]:
        return next((m for m in self.members() if m.address == address), None)

    def status_of(self, other: "SimNode | int") -> Optional[MemberStatus]:
        row = other.row if isinstance(other, SimNode) else other
        return self._d.status_of(self.row, row)

    # -- metadata -----------------------------------------------------------
    def update_metadata(self) -> None:
        """Bump + re-announce (peers observe an UPDATED event)."""
        self._d.update_metadata(self.row)

    def incarnation_of(self, other: "SimNode | int") -> int:
        row = other.row if isinstance(other, SimNode) else other
        key = int(self._d._eng.view_row(self._d.state, self.row)[row])
        # layout follows the driver's key dtype (narrow i16 keys decode
        # with the narrow incarnation mask — r9)
        return (key >> 2) & self._d._lay.inc_mask if key >= 0 else 0

    # -- gossip -------------------------------------------------------------
    def spread_gossip(self, payload: object) -> int:
        """Start a rumor from this node; returns the rumor slot (track
        coverage via ``SimCluster.rumor_coverage``)."""
        return self._d.spread_rumor(self.row, payload)

    # -- events -------------------------------------------------------------
    def listen_membership(self) -> EventStream:
        return self._d.watch(self.row)

    def membership_events(self) -> List[MembershipEvent]:
        return self._d.events_of(self.row)

    # -- messaging ----------------------------------------------------------
    def transport(self):
        """The 4-method Transport SPI bound to this row (lazy singleton)."""
        from .transport import SimTransport

        if self.row not in self._d._transports:
            self._d._transports[self.row] = SimTransport(self._d, self.row)
        return self._d._transports[self.row]

    # -- lifecycle ----------------------------------------------------------
    @property
    def is_up(self) -> bool:
        return self._d.is_up(self.row)

    def leave(self, crash_after_ticks: int = 2) -> None:
        """Graceful shutdown: LEAVING gossip, then stop (reference
        doShutdown: LEAVING → dispose → stop transport)."""
        self._d.leave(self.row, crash_after_ticks=crash_after_ticks)

    def crash(self) -> None:
        self._d.crash(self.row)


class SimCluster:
    """All simulated members of one driver, plus cluster-level helpers."""

    def __init__(self, driver: SimDriver):
        self.driver = driver

    def node(self, row: int) -> SimNode:
        return SimNode(self.driver, row)

    def nodes(self) -> List[SimNode]:
        import numpy as np

        up = np.asarray(self.driver.state.up)
        return [SimNode(self.driver, int(r)) for r in np.nonzero(up)[0]]

    def join(self, seed_rows=(0,)) -> SimNode:
        return SimNode(self.driver, self.driver.join(seed_rows))

    def step(self, n_ticks: int = 1) -> dict:
        return self.driver.step(n_ticks)

    def rumor_coverage(self, slot: int) -> float:
        return self.driver.rumor_coverage(slot)
