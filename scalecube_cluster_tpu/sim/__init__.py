"""Host-side bridge over the vectorized simulation (SURVEY.md §7 stage 5).

Exposes the simulated mesh through the same surfaces the scalar engine has:

* :class:`SimDriver` — owns the device state and the jitted tick; host loop,
  id↔row mapping, per-observer membership-event extraction, churn helpers,
  metrics history, checkpoint/resume.
* :class:`SimCluster` / :class:`SimNode` — ``Cluster``-facade-shaped handles
  over individual simulated members (members/other_members/metadata/
  spread_gossip/update_metadata/leave/shutdown/event streams).
* :class:`SimTransport` — the 4-method Transport SPI (send/request_response/
  listen/stop) between simulated members, honoring the sim's link-loss
  matrix — the sibling of the memory/TCP transports that lets user-messaging
  code and testlib scenarios run unmodified against the simulated mesh.
"""

from .driver import SimDriver
from .cluster import SimCluster, SimNode
from .transport import SimTransport

__all__ = ["SimDriver", "SimCluster", "SimNode", "SimTransport"]
