"""Cluster introspection endpoint — the JMX monitor analogue.

The reference registers a per-node MBean ``io.scalecube.cluster:name=<id>``
exposing config, cluster size, incarnation, and alive/suspected/removed
member lists (``ClusterMonitorMBean.java:3``, ``ClusterMonitorModel.java:10``,
wired in ``ClusterImpl.startJmxMonitor:363-375``). The TPU-native equivalents
(SURVEY.md §2.2 Monitor row):

* :func:`cluster_snapshot` / :func:`sim_snapshot` — the MBean attribute set
  as a plain dict (JSON-ready), pulled from the scalar engine's state or
  from the device arrays in one gather.
* :class:`MonitorServer` — an optional stdlib asyncio HTTP endpoint serving
  those snapshots at ``/nodes`` and ``/nodes/<i>`` (JMX's remote access
  analogue; JSON instead of RMI).
* :class:`TickLogger` — structured per-tick event log (SURVEY.md §5.1: the
  reference's ``[localMember][period]`` DEBUG trace, as JSON lines).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import time
from typing import Any, Callable, Dict, List, Optional

_log = logging.getLogger(__name__)


# -- snapshots ---------------------------------------------------------------


def cluster_snapshot(cluster) -> Dict[str, Any]:
    """MBean attribute set for one scalar-engine Cluster instance."""
    mp = cluster.membership_protocol
    member = cluster.member()
    return {
        "member": {"id": member.id, "alias": member.alias, "address": member.address,
                   "namespace": member.namespace},
        "cluster_size": len(mp.members()),
        "incarnation": mp.incarnation,
        "alive_members": [m.id for m in mp.alive_members()],
        "suspected_members": [m.id for m in mp.suspected_members()],
        "removed_members": [m.id for m in mp.removed_members()],
        "config": {
            "namespace": cluster._config.membership.namespace,
            "sync_interval": cluster._config.membership.sync_interval,
            "suspicion_mult": cluster._config.membership.suspicion_mult,
            "ping_interval": cluster._config.failure_detector.ping_interval,
            "gossip_interval": cluster._config.gossip.gossip_interval,
            "gossip_fanout": cluster._config.gossip.gossip_fanout,
        },
    }


def sim_snapshot(driver, row: int) -> Dict[str, Any]:
    """MBean attribute set for one simulated member (one device gather)."""
    import numpy as np

    from .ops.lattice import ALIVE, DEAD, LEAVING, SUSPECT

    # one lock hold for every device read: this runs on the monitor thread
    # and must not interleave with the sim thread's donating step (the
    # driver lock is reentrant, so the accessors below nest fine)
    with driver._lock:
        status, inc = driver.view_of(row)
        up = driver.is_up(row)
        tick = driver.tick
        epoch = int(driver.state.epoch[row])
    member = driver._member_handle(row)

    def ids(mask: "np.ndarray") -> List[str]:
        return [driver._member_handle(int(j)).id for j in np.nonzero(mask)[0]]

    return {
        "member": {"id": member.id, "address": member.address},
        "row": row,
        "up": up,
        "tick": tick,
        "cluster_size": int((status <= LEAVING).sum()),
        "incarnation": int(inc[row]),
        # identity generation of this row (bumps on crash+reuse — the
        # restart-is-a-new-member rule; see ops.lattice epoch bits)
        "epoch": epoch,
        "alive_members": ids(status == ALIVE),
        "suspected_members": ids(status == SUSPECT),
        # DEAD tombstones ARE the removed set (reference removedMembersHistory)
        "removed_members": ids(status == DEAD),
        "config": dataclasses.asdict(driver.params),
    }


def dispatch_snapshot(driver) -> Dict[str, Any]:
    """Dispatch-pipeline view of one driver (r6): queue depth (windows
    enqueued since the last host sync), total and per-window device→host
    readback counts, flush count, plus the jit-program / persistent-cache
    audit. This is what makes the pipelined engine's overlap OBSERVABLE —
    a healthy unmonitored driver shows readbacks_per_window == 0.0 and a
    growing queue_high_water; a consumer-attached driver shows the
    readbacks it opted into."""
    return {
        **driver.dispatch_snapshot(),
        "jit_cache": driver.jit_cache_audit(),
    }


# -- HTTP endpoint -----------------------------------------------------------


class MonitorServer:
    """Minimal JSON-over-HTTP introspection server (stdlib asyncio only).

    ``providers`` maps a name to a zero-arg callable returning a JSON-able
    snapshot. Routes: ``/`` (name list), ``/nodes`` (all snapshots),
    ``/nodes/<name>`` (one).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._providers: Dict[str, Callable[[], Dict[str, Any]]] = {}
        self._health: Optional[Callable[[], Dict[str, Any]]] = None
        self._dispatch: Optional[Callable[[], Dict[str, Any]]] = None
        self._chaos: Optional[Callable[[], Dict[str, Any]]] = None
        # r16 closed-loop controller snapshot provider for /control
        self._control: Optional[Callable[[], Dict[str, Any]]] = None
        # r18 incident-replay what-if provider for /whatif (serves the
        # NEWEST computed counterfactual record — the MC itself is a
        # bench-cadence compute step, never an HTTP-GET one)
        self._whatif: Optional[Callable[[], Dict[str, Any]]] = None
        # r19 operator entry: POST /whatif runs an operator-supplied arm
        # ladder against the service's LIVE incident (the compute is
        # synchronous and minutes-scale at production seed counts — the
        # operator owns the wait; refusals come back as 400s)
        self._whatif_post: Optional[Callable[[dict], Dict[str, Any]]] = None
        # OpenMetrics family providers, concatenated at /metrics scrape
        # time (r8 telemetry plane); each returns a list of family dicts
        self._metric_providers: List[Callable[[], List[Dict[str, Any]]]] = []
        # unified event-bus tail provider for /events
        self._events: Optional[Callable[[], List[Dict[str, Any]]]] = None
        # causal trace providers (r10): GET /trace (decoded events + sewn
        # span trees) and GET /trace/perfetto (Chrome-trace JSON)
        self._trace: Optional[Callable[[], Dict[str, Any]]] = None
        self._trace_perfetto: Optional[Callable[[], Dict[str, Any]]] = None
        # r21 federation: shard label -> zero-arg fetch returning a worker's
        # /metrics exposition text; folded at /metrics/federated scrape time
        self._federation: Optional[Dict[str, Callable[[], str]]] = None
        self._federation_errors = 0  # lifetime-monotone scrape failures
        self._server: Optional[asyncio.AbstractServer] = None

    def register(self, name: str, provider: Callable[[], Dict[str, Any]]) -> None:
        self._providers[name] = provider

    def register_whatif(self, service) -> None:
        """Serve the r18 counterfactual what-if service at ``GET /whatif``:
        the newest :func:`.replay.whatif` record (arms, Wilson intervals,
        CI-separation verdicts). ``service`` is a
        :class:`.replay.WhatifService` (or any object with ``snapshot()``).
        When the service also exposes ``run_operator`` (r19), ``POST
        /whatif`` accepts an operator-supplied arm ladder against the
        service's live incident — validated with the same unknown-knob /
        reserved-name refusals as :func:`.replay.whatif`."""
        self._whatif = service.snapshot
        self._whatif_post = getattr(service, "run_operator", None)

    def register_cluster(self, cluster) -> None:
        self.register(cluster.member().id, lambda: cluster_snapshot(cluster))

    def register_sim(self, driver, rows) -> None:
        for row in rows:
            self.register(
                driver._member_handle(row).id,
                lambda r=row: sim_snapshot(driver, r),
            )

    def register_health(self, driver) -> None:
        """Expose the driver's engine-health snapshot at ``/health``: rumor-
        pool occupancy/high-water, per-source announce drops + priority
        evictions, and identity-staleness lag cohorts (VERDICT r4 item 8 —
        the sparse engine's known backpressure failure mode, live).

        Registering IS the consumer contract of the pipelined driver (r6):
        it turns on the join() in-pool probe, and every ``/health`` poll is
        the coalesced sync point for the deferred per-window readbacks.
        ``/dispatch`` additionally serves the pipeline's own vitals (queue
        depth, readback counts, jit/persistent-cache audit) WITHOUT forcing
        a flush — safe to poll at high frequency."""
        driver.enable_health_probes()
        self._health = lambda: driver.health_snapshot()
        self._dispatch = lambda: dispatch_snapshot(driver)
        # ``/chaos``: the armed scenario's progress + sentinel report (r7).
        # Registered alongside health because reading sentinel accumulators
        # is a sync point of exactly the same cadence contract.
        self._chaos = lambda: driver.chaos_snapshot()
        # ``/control`` (r16): the closed-loop controller's rung, spec, and
        # decision log. Resolved at REQUEST time (like /trace) so a plane
        # armed after registration is served; an unarmed driver answers
        # {"armed": false} — host values only, never a device read.
        self._control = lambda: driver.control_snapshot()

    def register_telemetry(self, driver, plane=None) -> None:
        """Serve the r8 telemetry plane: ``GET /metrics`` (OpenMetrics text
        for this driver — counters, gauges, histograms) and ``GET /events``
        (the unified event-bus tail as JSON). Arms the plane if the driver
        has none yet, and registers the health/dispatch/chaos providers too
        (a telemetry consumer wants all of them). Every scrape is a sync
        point of the same contract as ``/health`` — poll cadence, never
        window cadence; an unscraped driver stays transfer-free."""
        if plane is None:
            plane = driver.arm_telemetry()
        elif driver._telemetry is None:
            # an explicitly constructed plane must still be ATTACHED, or
            # step() never appends and the ring stays empty forever
            driver._telemetry = plane
        self.register_health(driver)
        # plane.families is THE scrape path (lock-guarded ring read +
        # readback bookkeeping live there, one spelling)
        self._metric_providers.append(plane.families)
        bus = plane.bus
        self._events = lambda: [r.as_dict() for r in bus.tail(256)]
        # the trace routes ride along (a telemetry consumer wants the why
        # as much as the how-much); late-bound so a plane armed AFTER
        # registration (e.g. run_scenario(trace=True) auto-attach) is
        # served without re-registering
        self.register_trace(driver, required=False)

    def register_trace(self, driver, plane=None, required: bool = True) -> None:
        """Serve the r10 causal trace plane: ``GET /trace`` (ring stats +
        decoded protocol events + sewn detection span trees, JSON) and
        ``GET /trace/perfetto`` (a Chrome-trace/Perfetto document of the
        span trees + rumor infection trees). The plane is resolved at
        REQUEST time, so arming after registration (the chaos runner's
        auto-attach) just works; ``required=True`` (the explicit-call
        default) still fails fast on a driver nobody armed — the monitor
        must never arm one itself (arming swaps compiled window programs,
        which cannot happen behind the sim thread's back). Every poll is a
        trace-ring sync point — poll cadence, never window cadence."""
        if required and plane is None and getattr(driver, "_trace", None) is None:
            raise ValueError(
                "driver has no armed trace plane — call arm_trace() first"
            )

        def _resolve():
            return plane if plane is not None else getattr(driver, "_trace", None)

        def _snapshot():
            p = _resolve()
            return p.trace_snapshot() if p is not None else {"armed": False}

        def _perfetto():
            p = _resolve()
            if p is not None:
                return p.perfetto()
            return {"traceEvents": [], "displayTimeUnit": "ms",
                    "metadata": {"armed": False}}

        self._trace = _snapshot
        self._trace_perfetto = _perfetto

    def register_federation(self, sources: Dict[str, Callable[[], str]]) -> None:
        """Serve ``GET /metrics/federated`` (r21): fold multiple workers'
        ``/metrics`` expositions into one, each sample re-labelled with its
        ``shard``. ``sources`` maps a shard label to a zero-arg callable
        returning the worker's exposition TEXT — callables, not URLs, so
        in-process planes federate without sockets; use
        :func:`scrape_metrics` to wrap a worker URL. Values pass through
        verbatim (per-shard series keep the source counters' lifetime
        monotonicity); fetch failures are skipped, counted by the monotone
        ``scalecube_federation_scrape_errors_total``."""
        self._federation = dict(sources)

    def register_federation_urls(self, urls: Dict[str, str],
                                 timeout: float = 5.0) -> None:
        """URL convenience over :meth:`register_federation` — the 2-process
        gloo lane's shape: shard label -> ``http://host:port`` of a worker
        monitor (its ``/metrics`` route is scraped on each federated poll)."""
        self.register_federation({
            label: (lambda u=url: scrape_metrics(u + "/metrics", timeout))
            for label, url in urls.items()
        })

    def register_cluster_metrics(self, cluster, bus=None) -> None:
        """Serve OpenMetrics for one scalar-engine Cluster node at
        ``/metrics`` (appended to any sim families already registered)."""
        from .telemetry.openmetrics import cluster_families

        self._metric_providers.append(lambda: cluster_families(cluster, bus))
        if bus is not None and self._events is None:
            self._events = lambda: [r.as_dict() for r in bus.tail(256)]

    async def start(self) -> "MonitorServer":
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            request = await reader.readline()
            content_length = 0
            while True:  # drain headers, keeping Content-Length (r19: POST)
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                if line.lower().startswith(b"content-length:"):
                    try:
                        content_length = int(line.split(b":", 1)[1])
                    except ValueError:
                        content_length = 0
            parts = request.split()
            method = parts[0].decode().upper() if parts else "GET"
            path = (parts[1].decode() if len(parts) > 1 else "/").split("?", 1)[0]
            payload_in = (
                await reader.readexactly(content_length)
                if content_length > 0 else b""
            )
            if method == "POST":
                status, body = self._route_post(path, payload_in)
            else:
                status, body = self._route(path)
            if isinstance(body, bytes):  # pre-rendered (OpenMetrics text)
                ctype, payload = self._text_content_type, body
            else:
                ctype, payload = b"application/json", json.dumps(body).encode()
            writer.write(
                b"HTTP/1.1 " + status + b"\r\nContent-Type: " + ctype
                + f"\r\nContent-Length: {len(payload)}\r\n\r\n".encode()
                + payload
            )
            await writer.drain()
        except Exception:  # noqa: BLE001 - monitor must never take a node down
            _log.exception("monitor request failed")
        finally:
            writer.close()

    #: content type of bytes bodies (the OpenMetrics exposition)
    _text_content_type = b"text/plain; version=0.0.4; charset=utf-8"

    def _route(self, path: str) -> tuple[bytes, Any]:
        if path == "/":
            return b"200 OK", {
                "nodes": sorted(self._providers),
                "health": self._health is not None,
                "dispatch": self._dispatch is not None,
                "chaos": self._chaos is not None,
                "control": self._control is not None,
                "whatif": self._whatif is not None,
                "metrics": bool(self._metric_providers),
                "federated": self._federation is not None,
                "events": self._events is not None,
                "trace": self._trace is not None,
            }
        if path == "/metrics":
            if not self._metric_providers:
                return b"404 Not Found", {"error": "no metrics provider registered"}
            from .telemetry.openmetrics import render

            families = [f for p in self._metric_providers for f in p()]
            return b"200 OK", render(families).encode()
        if path == "/metrics/federated":
            if self._federation is None:
                return b"404 Not Found", {"error": "no federation registered"}
            from .telemetry.openmetrics import (
                PREFIX, family, federated_families, render,
            )

            texts: Dict[str, str] = {}
            for label, fetch in self._federation.items():
                try:
                    texts[label] = fetch()
                except Exception:  # noqa: BLE001 - a down worker must not 500 the fold
                    _log.exception("federated scrape of shard %r failed", label)
                    self._federation_errors += 1
            fams = federated_families(texts)
            fams.append(family(
                f"{PREFIX}_federation_workers", "gauge",
                "Workers successfully scraped into this federated exposition.",
                [(f"{PREFIX}_federation_workers", {}, len(texts))],
            ))
            fams.append(family(
                f"{PREFIX}_federation_scrape_errors_total", "counter",
                "Federated worker scrapes that failed (lifetime).",
                [(f"{PREFIX}_federation_scrape_errors_total", {},
                  self._federation_errors)],
            ))
            return b"200 OK", render(fams).encode()
        if path == "/events":
            if self._events is None:
                return b"404 Not Found", {"error": "no event bus registered"}
            return b"200 OK", {"events": self._events()}
        if path == "/trace":
            if self._trace is None:
                return b"404 Not Found", {"error": "no trace provider registered"}
            return b"200 OK", self._trace()
        if path == "/trace/perfetto":
            if self._trace_perfetto is None:
                return b"404 Not Found", {"error": "no trace provider registered"}
            return b"200 OK", self._trace_perfetto()
        if path == "/chaos":
            if self._chaos is None:
                return b"404 Not Found", {"error": "no chaos provider registered"}
            return b"200 OK", self._chaos()
        if path == "/control":
            if self._control is None:
                return b"404 Not Found", {"error": "no control provider registered"}
            return b"200 OK", self._control()
        if path == "/whatif":
            if self._whatif is None:
                return b"404 Not Found", {"error": "no whatif service registered"}
            return b"200 OK", self._whatif()
        if path == "/health":
            if self._health is None:
                return b"404 Not Found", {"error": "no health provider registered"}
            return b"200 OK", self._health()
        if path == "/dispatch":
            if self._dispatch is None:
                return b"404 Not Found", {"error": "no dispatch provider registered"}
            return b"200 OK", self._dispatch()
        if path == "/nodes":
            return b"200 OK", {n: p() for n, p in self._providers.items()}
        if path.startswith("/nodes/"):
            name = path[len("/nodes/") :]
            if name in self._providers:
                return b"200 OK", self._providers[name]()
            return b"404 Not Found", {"error": f"unknown node {name!r}"}
        return b"404 Not Found", {"error": f"no route {path!r}"}

    def _route_post(self, path: str, body: bytes) -> tuple[bytes, Any]:
        """POST routes (r19). ``/whatif`` runs an operator arm ladder
        against the registered service's live incident; replay-grammar
        refusals (unknown knob, reserved/duplicate arm name, no incident)
        surface as 400s that quote the refusal verbatim."""
        if path != "/whatif":
            return b"404 Not Found", {"error": f"no POST route {path!r}"}
        if self._whatif_post is None:
            return b"404 Not Found", {
                "error": "whatif service accepts no operator arms — "
                         "register a replay.WhatifService(incident=...)"
            }
        try:
            doc = json.loads(body.decode("utf-8") or "null")
        except (UnicodeDecodeError, ValueError):
            return b"400 Bad Request", {"error": "body is not valid JSON"}
        from .replay import ReplayError

        try:
            return b"200 OK", self._whatif_post(doc)
        except ReplayError as exc:
            return b"400 Bad Request", {"error": str(exc)}


def scrape_metrics(url: str, timeout: float = 5.0) -> str:
    """Fetch one worker's exposition text (stdlib urllib — the repo rule).
    The federation route calls these synchronously; workers are expected
    on the local network (the gloo lane scrapes loopback)."""
    from urllib.request import urlopen

    with urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


# -- structured per-tick log -------------------------------------------------


class TickLogger:
    """JSON-lines log of per-tick metrics + host interventions, the
    structured analogue of the reference's causally ordered
    ``[localMember][period]`` DEBUG trace (SURVEY.md §5.1)."""

    def __init__(self, path: str):
        self._fh = open(path, "a", buffering=1)

    def log_tick(self, tick: int, metrics: Dict[str, Any]) -> None:
        record = {"t": tick, "ts": time.time()}
        for name, v in metrics.items():
            try:
                record[name] = v.item() if hasattr(v, "item") and getattr(v, "ndim", 1) == 0 else (
                    [float(x) for x in v] if hasattr(v, "__iter__") else v
                )
            except Exception:  # noqa: BLE001
                record[name] = str(v)
        self._fh.write(json.dumps(record) + "\n")

    def log_event(self, tick: int, kind: str, **fields: Any) -> None:
        self._fh.write(json.dumps({"t": tick, "event": kind, **fields}) + "\n")

    def close(self) -> None:
        self._fh.close()
