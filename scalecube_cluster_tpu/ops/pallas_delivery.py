"""Hand-written delivery combine for the pview fused gossip (r17).

The pview delivery step gathers, per fanout slot, each receiver's
inverse-elected sender's payload row (membership-rumor words + packed
user-rumor bits + infected-from lanes) and folds the F slots into the
receiver's accumulators: OR for the rumor planes, max for the source
lanes, a global count for the send metric. The XLA spelling
(:func:`delivery_combine_xla` — lifted verbatim from the unfused
``_gossip_phase``) materializes the [F, N, Wt] gathered payload and the
[F, N, R] deliver mask; the Pallas kernel (:func:`delivery_combine`)
walks a row block per grid step, loads each row's F sender rows with
dynamic slices, and folds in registers — the [F, N, *] intermediates
never exist.

On CPU the kernel runs in ``interpret=True`` mode, which executes the
same kernel logic through XLA primitives — that is the tier-1
certification story: interpret-mode output must be bit-equal to the XLA
spelling (tests/test_fused.py), so the TPU lowering of the *same kernel
body* computes the same function. Block shapes are TPU-lane friendly
(row blocks x 32-bit words / rumor lanes); the payload is presented as
one whole-array block, so at 1M members the TPU lowering wants the
column split documented in docs/TPU_LAYOUT_NOTES.md.

No [N, N] anywhere — everything is [N, Wt], [F, N], or [N, R]
(``forbid_wide_values`` holds over the kernel-armed program too).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .bitplane import unpack_bits


def delivery_combine_xla(payload, inv, rumor_origin, Wm: int, R: int):
    """The unfused tick's exact delivery-combine primitive sequence.

    Args:
      payload: [N, Wt] uint32 — ``Wm`` membership-rumor words, ``Wu``
        packed user-rumor words, then R infected-from lanes (i32 bits).
      inv: [F, N] int32 — per-slot inverse sender index (< 0: no sender).
      rumor_origin: [R] int32 rumor origin rows.
      Wm, R: static word/lane counts.

    Returns ``(u_or [N, R] bool, src_max [N, R] i32, m_or [N, Wm] u32,
    cnt i32 scalar)`` — the receiver-side fold (zeros/-1 identities), to
    be OR/max-folded into the pending-initialized accumulators.
    """
    F, n = inv.shape
    rows = jnp.arange(n)
    Wt = payload.shape[1]
    Wu = Wt - Wm - R
    j_all = jnp.maximum(inv, 0)
    has_all = (inv >= 0)[:, :, None]
    pl_all = payload[j_all]
    yu_all = unpack_bits(pl_all[:, :, Wm : Wm + Wu], R)
    from_all = pl_all[:, :, Wm + Wu :].astype(jnp.int32)
    deliver_u_all = (
        yu_all
        & has_all
        & (from_all != rows[None, :, None])
        & (rumor_origin[None, None, :] != rows[None, :, None])
    )
    u_or = deliver_u_all.any(axis=0)
    src_max = jnp.where(deliver_u_all, j_all[:, :, None], -1).max(axis=0)
    m_or = functools.reduce(
        jnp.bitwise_or,
        [jnp.where(has_all[s], pl_all[s, :, :Wm], jnp.uint32(0)) for s in range(F)],
        jnp.zeros((n, Wm), jnp.uint32),
    )
    cnt = deliver_u_all.sum()
    return u_or, src_max, m_or, cnt


def _delivery_kernel(F: int, Wm: int, Wu: int, R: int, BR: int,
                     origin_ref, inv_ref, payload_ref,
                     u_ref, src_ref, m_ref, cnt_ref):
    """Per-block body: fold F sender rows into each of BR receiver rows.

    Refs: origin [1, R] (replicated), inv [F, BR] (column block),
    payload [N, Wt] (whole array), outputs [BR, R]/[BR, R]/[BR, Wm]/
    [BR, 1] row blocks."""
    blk = pl.program_id(0)
    origin = origin_ref[0, :]

    def row(i, _):
        rid = blk * BR + i
        u = jnp.zeros((R,), jnp.bool_)
        src = jnp.full((R,), -1, jnp.int32)
        mw = jnp.zeros((Wm,), jnp.uint32)
        cnt = jnp.int32(0)
        for f in range(F):
            jv = inv_ref[f, i]
            has = jv >= 0
            jc = jnp.maximum(jv, 0)
            row_pl = payload_ref[pl.ds(jc, 1), :][0]
            ym = row_pl[:Wm]
            yu = unpack_bits(row_pl[None, Wm : Wm + Wu], R)[0]
            frm = row_pl[Wm + Wu :].astype(jnp.int32)
            deliver = yu & has & (frm != rid) & (origin != rid)
            u = u | deliver
            src = jnp.maximum(src, jnp.where(deliver, jc, -1))
            mw = mw | jnp.where(has, ym, jnp.uint32(0))
            cnt = cnt + deliver.sum(dtype=jnp.int32)
        u_ref[i, :] = u
        src_ref[i, :] = src
        m_ref[i, :] = mw
        cnt_ref[i, 0] = cnt
        return 0

    jax.lax.fori_loop(0, BR, row, 0)


def delivery_combine(payload, inv, rumor_origin, Wm: int, R: int, *,
                     block_rows: int = 256, interpret: bool | None = None):
    """Pallas spelling of :func:`delivery_combine_xla` — bit-equal
    outputs (certified in tier-1 via ``interpret=True``; the equality IS
    the CPU certification of the TPU kernel body).

    Receivers are padded to a multiple of ``block_rows`` with no-sender
    lanes (``inv = -1`` → every output identity) and sliced back."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    F, n = inv.shape
    Wt = payload.shape[1]
    Wu = Wt - Wm - R
    BR = min(block_rows, n)
    n_pad = -(-n // BR) * BR
    if n_pad != n:
        inv = jnp.pad(inv, ((0, 0), (0, n_pad - n)), constant_values=-1)
    kernel = functools.partial(_delivery_kernel, F, Wm, Wu, R, BR)
    u, src, mw, cnt = pl.pallas_call(
        kernel,
        grid=(n_pad // BR,),
        in_specs=[
            pl.BlockSpec((1, R), lambda b: (0, 0)),
            pl.BlockSpec((F, BR), lambda b: (0, b)),
            pl.BlockSpec(payload.shape, lambda b: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BR, R), lambda b: (b, 0)),
            pl.BlockSpec((BR, R), lambda b: (b, 0)),
            pl.BlockSpec((BR, Wm), lambda b: (b, 0)),
            pl.BlockSpec((BR, 1), lambda b: (b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, R), jnp.bool_),
            jax.ShapeDtypeStruct((n_pad, R), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, Wm), jnp.uint32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
        ],
        interpret=interpret,
    )(rumor_origin[None, :], inv, payload)
    return u[:n], src[:n], mw[:n], cnt[:n, 0].sum()
