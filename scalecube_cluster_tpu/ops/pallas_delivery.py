"""Hand-written delivery combine for the pview fused gossip (r17).

The pview delivery step gathers, per fanout slot, each receiver's
inverse-elected sender's payload row (membership-rumor words + packed
user-rumor bits + infected-from lanes) and folds the F slots into the
receiver's accumulators: OR for the rumor planes, max for the source
lanes, a global count for the send metric. The XLA spelling
(:func:`delivery_combine_xla` — lifted verbatim from the unfused
``_gossip_phase``) materializes the [F, N, Wt] gathered payload and the
[F, N, R] deliver mask; the Pallas kernel (:func:`delivery_combine`)
walks a row block per grid step, loads each row's F sender rows with
dynamic slices, and folds in registers — the [F, N, *] intermediates
never exist.

On CPU the kernel runs in ``interpret=True`` mode, which executes the
same kernel logic through XLA primitives — that is the tier-1
certification story: interpret-mode output must be bit-equal to the XLA
spelling (tests/test_fused.py), so the TPU lowering of the *same kernel
body* computes the same function. Block shapes are TPU-lane friendly
(row blocks x 32-bit words / rumor lanes).

r20 adds the membership-word COLUMN SPLIT promised in
docs/TPU_LAYOUT_NOTES.md: when the whole ``[N, Wt]`` payload block
would not fit the per-step VMEM budget (it is ~280 MiB at 1M members),
:func:`delivery_plan` picks a second grid axis over membership-word
tiles. The payload splits into ``payload_m [N, BCm]`` column tiles
(membership words — the only part that scales with capacity) plus a
whole ``payload_tail [N, Wu + R]`` block (packed user-rumor words +
infected-from lanes, always a handful of words). The OR fold over
membership words is associative per word with identity 0, so each
``(row block, col tile)`` grid step folds its tile independently;
``u_or``/``src_max``/``cnt`` depend only on the tail and are written
once per row block at col tile 0 (``pl.when``). Nothing about the fold
changes — only the BlockSpec maps — so bit-exactness versus the XLA
spelling is preserved (forced-split equality in tests/test_fused.py,
plus a 1M abstract-lowering proof that the plan actually tiles).

No [N, N] anywhere — everything is [N, Wt], [F, N], or [N, R]
(``forbid_wide_values`` holds over the kernel-armed program too).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .bitplane import unpack_bits

#: Per-grid-step budget for the payload operand block. 128 MiB leaves
#: comfortable headroom under a v5e core's VMEM+spill envelope for the
#: small inv/out blocks riding alongside; interpret mode ignores it for
#: correctness but uses the same plan so CPU certifies the TPU tiling.
DEFAULT_VMEM_BUDGET = 128 * 2 ** 20


class DeliveryPlan(NamedTuple):
    """Grid tiling decision for :func:`delivery_combine`.

    ``block_cols is None`` means the whole payload fits one block (the
    r17 single-axis grid); otherwise the grid gains a second axis of
    ``n_col_tiles`` membership-word tiles of ``block_cols`` words each
    (last tile zero-padded — OR identity)."""

    block_rows: int
    block_cols: Optional[int]
    n_col_tiles: int


def delivery_plan(n: int, Wt: int, Wm: int, *, block_rows: int = 256,
                  block_cols: Optional[int] = None,
                  vmem_budget_bytes: int = DEFAULT_VMEM_BUDGET) -> DeliveryPlan:
    """Pick the kernel grid: row blocks always, column tiles only when
    the whole ``[n, Wt]`` u32 payload block busts ``vmem_budget_bytes``
    (or when ``block_cols`` forces a split, for tests)."""
    BR = min(block_rows, n)
    if block_cols is None:
        if n * Wt * 4 <= vmem_budget_bytes or Wm == 0:
            return DeliveryPlan(BR, None, 1)
        block_cols = max(1, vmem_budget_bytes // (4 * n))
    block_cols = max(1, min(block_cols, Wm))
    return DeliveryPlan(BR, block_cols, -(-Wm // block_cols))


def delivery_combine_xla(payload, inv, rumor_origin, Wm: int, R: int):
    """The unfused tick's exact delivery-combine primitive sequence.

    Args:
      payload: [N, Wt] uint32 — ``Wm`` membership-rumor words, ``Wu``
        packed user-rumor words, then R infected-from lanes (i32 bits).
      inv: [F, N] int32 — per-slot inverse sender index (< 0: no sender).
      rumor_origin: [R] int32 rumor origin rows.
      Wm, R: static word/lane counts.

    Returns ``(u_or [N, R] bool, src_max [N, R] i32, m_or [N, Wm] u32,
    cnt i32 scalar)`` — the receiver-side fold (zeros/-1 identities), to
    be OR/max-folded into the pending-initialized accumulators.
    """
    F, n = inv.shape
    rows = jnp.arange(n)
    Wt = payload.shape[1]
    Wu = Wt - Wm - R
    j_all = jnp.maximum(inv, 0)
    has_all = (inv >= 0)[:, :, None]
    pl_all = payload[j_all]
    yu_all = unpack_bits(pl_all[:, :, Wm : Wm + Wu], R)
    from_all = pl_all[:, :, Wm + Wu :].astype(jnp.int32)
    deliver_u_all = (
        yu_all
        & has_all
        & (from_all != rows[None, :, None])
        & (rumor_origin[None, None, :] != rows[None, :, None])
    )
    u_or = deliver_u_all.any(axis=0)
    src_max = jnp.where(deliver_u_all, j_all[:, :, None], -1).max(axis=0)
    m_or = functools.reduce(
        jnp.bitwise_or,
        [jnp.where(has_all[s], pl_all[s, :, :Wm], jnp.uint32(0)) for s in range(F)],
        jnp.zeros((n, Wm), jnp.uint32),
    )
    cnt = deliver_u_all.sum()
    return u_or, src_max, m_or, cnt


def _delivery_kernel(F: int, Wm: int, Wu: int, R: int, BR: int,
                     origin_ref, inv_ref, payload_ref,
                     u_ref, src_ref, m_ref, cnt_ref):
    """Per-block body: fold F sender rows into each of BR receiver rows.

    Refs: origin [1, R] (replicated), inv [F, BR] (column block),
    payload [N, Wt] (whole array), outputs [BR, R]/[BR, R]/[BR, Wm]/
    [BR, 1] row blocks."""
    blk = pl.program_id(0)
    origin = origin_ref[0, :]

    def row(i, _):
        rid = blk * BR + i
        u = jnp.zeros((R,), jnp.bool_)
        src = jnp.full((R,), -1, jnp.int32)
        mw = jnp.zeros((Wm,), jnp.uint32)
        cnt = jnp.int32(0)
        for f in range(F):
            jv = inv_ref[f, i]
            has = jv >= 0
            jc = jnp.maximum(jv, 0)
            row_pl = payload_ref[pl.ds(jc, 1), :][0]
            ym = row_pl[:Wm]
            yu = unpack_bits(row_pl[None, Wm : Wm + Wu], R)[0]
            frm = row_pl[Wm + Wu :].astype(jnp.int32)
            deliver = yu & has & (frm != rid) & (origin != rid)
            u = u | deliver
            src = jnp.maximum(src, jnp.where(deliver, jc, -1))
            mw = mw | jnp.where(has, ym, jnp.uint32(0))
            cnt = cnt + deliver.sum(dtype=jnp.int32)
        u_ref[i, :] = u
        src_ref[i, :] = src
        m_ref[i, :] = mw
        cnt_ref[i, 0] = cnt
        return 0

    jax.lax.fori_loop(0, BR, row, 0)


def _delivery_kernel_cols(F: int, BCm: int, Wu: int, R: int, BR: int,
                          origin_ref, inv_ref, pm_ref, tail_ref,
                          u_ref, src_ref, m_ref, cnt_ref):
    """Column-split body: grid is (row blocks, membership-word tiles).

    Every step folds its [N, BCm] membership tile for the block's BR
    receivers; the tail fold (user-rumor bits, infected-from lanes —
    whole [N, Wu + R] block) runs once per row block at col tile 0, so
    u/src/cnt blocks are written exactly once and then revisited
    untouched (their index map is col-invariant)."""
    blk = pl.program_id(0)
    col = pl.program_id(1)

    def mrow(i, _):
        mw = jnp.zeros((BCm,), jnp.uint32)
        for f in range(F):
            jv = inv_ref[f, i]
            has = jv >= 0
            jc = jnp.maximum(jv, 0)
            pm_row = pm_ref[pl.ds(jc, 1), :][0]
            mw = mw | jnp.where(has, pm_row, jnp.uint32(0))
        m_ref[i, :] = mw
        return 0

    jax.lax.fori_loop(0, BR, mrow, 0)

    @pl.when(col == 0)
    def _tail_fold():
        origin = origin_ref[0, :]

        def row(i, _):
            rid = blk * BR + i
            u = jnp.zeros((R,), jnp.bool_)
            src = jnp.full((R,), -1, jnp.int32)
            cnt = jnp.int32(0)
            for f in range(F):
                jv = inv_ref[f, i]
                has = jv >= 0
                jc = jnp.maximum(jv, 0)
                t_row = tail_ref[pl.ds(jc, 1), :][0]
                yu = unpack_bits(t_row[None, :Wu], R)[0]
                frm = t_row[Wu:].astype(jnp.int32)
                deliver = yu & has & (frm != rid) & (origin != rid)
                u = u | deliver
                src = jnp.maximum(src, jnp.where(deliver, jc, -1))
                cnt = cnt + deliver.sum(dtype=jnp.int32)
            u_ref[i, :] = u
            src_ref[i, :] = src
            cnt_ref[i, 0] = cnt
            return 0

        jax.lax.fori_loop(0, BR, row, 0)


def delivery_combine(payload, inv, rumor_origin, Wm: int, R: int, *,
                     block_rows: int = 256,
                     block_cols: int | None = None,
                     vmem_budget_bytes: int = DEFAULT_VMEM_BUDGET,
                     interpret: bool | None = None):
    """Pallas spelling of :func:`delivery_combine_xla` — bit-equal
    outputs (certified in tier-1 via ``interpret=True``; the equality IS
    the CPU certification of the TPU kernel body).

    Receivers are padded to a multiple of ``block_rows`` with no-sender
    lanes (``inv = -1`` → every output identity) and sliced back. When
    :func:`delivery_plan` decides the whole payload block busts the VMEM
    budget (auto at 1M members, or forced via ``block_cols``), the
    membership words are tiled over a second grid axis — same fold, same
    bits, smaller blocks."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    F, n = inv.shape
    Wt = payload.shape[1]
    Wu = Wt - Wm - R
    plan = delivery_plan(n, Wt, Wm, block_rows=block_rows,
                         block_cols=block_cols,
                         vmem_budget_bytes=vmem_budget_bytes)
    BR = plan.block_rows
    n_pad = -(-n // BR) * BR
    if n_pad != n:
        inv = jnp.pad(inv, ((0, 0), (0, n_pad - n)), constant_values=-1)

    if plan.block_cols is None:
        kernel = functools.partial(_delivery_kernel, F, Wm, Wu, R, BR)
        u, src, mw, cnt = pl.pallas_call(
            kernel,
            grid=(n_pad // BR,),
            in_specs=[
                pl.BlockSpec((1, R), lambda b: (0, 0)),
                pl.BlockSpec((F, BR), lambda b: (0, b)),
                pl.BlockSpec(payload.shape, lambda b: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((BR, R), lambda b: (b, 0)),
                pl.BlockSpec((BR, R), lambda b: (b, 0)),
                pl.BlockSpec((BR, Wm), lambda b: (b, 0)),
                pl.BlockSpec((BR, 1), lambda b: (b, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n_pad, R), jnp.bool_),
                jax.ShapeDtypeStruct((n_pad, R), jnp.int32),
                jax.ShapeDtypeStruct((n_pad, Wm), jnp.uint32),
                jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            ],
            interpret=interpret,
        )(rumor_origin[None, :], inv, payload)
        return u[:n], src[:n], mw[:n], cnt[:n, 0].sum()

    BCm = plan.block_cols
    wm_pad = plan.n_col_tiles * BCm
    pm = payload[:, :Wm]
    if wm_pad != Wm:
        pm = jnp.pad(pm, ((0, 0), (0, wm_pad - Wm)))
    tail = payload[:, Wm:]
    kernel = functools.partial(_delivery_kernel_cols, F, BCm, Wu, R, BR)
    u, src, mw, cnt = pl.pallas_call(
        kernel,
        grid=(n_pad // BR, plan.n_col_tiles),
        in_specs=[
            pl.BlockSpec((1, R), lambda b, c: (0, 0)),
            pl.BlockSpec((F, BR), lambda b, c: (0, b)),
            pl.BlockSpec((pm.shape[0], BCm), lambda b, c: (0, c)),
            pl.BlockSpec(tail.shape, lambda b, c: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BR, R), lambda b, c: (b, 0)),
            pl.BlockSpec((BR, R), lambda b, c: (b, 0)),
            pl.BlockSpec((BR, BCm), lambda b, c: (b, c)),
            pl.BlockSpec((BR, 1), lambda b, c: (b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, R), jnp.bool_),
            jax.ShapeDtypeStruct((n_pad, R), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, wm_pad), jnp.uint32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
        ],
        interpret=interpret,
    )(rumor_origin[None, :], inv, pm, tail)
    return u[:n], src[:n], mw[:n, :Wm], cnt[:n, 0].sum()
