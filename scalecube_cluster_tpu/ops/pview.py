"""The partial-view ("pview") SWIM tick: O(N·k) memory, no [N, N] plane.

Every engine before this one materializes at least one full [N, N] plane
(``view_key``), so even the r9 bit-plane compaction caps one 16 GiB window
at N=24576 (BITPLANE_BENCH_r09.json). SWIM itself never needs full views:
gossip with bounded, sampled fanout keeps its O(log N)-round spread and
fault tolerance (Haeupler–Malkhi, arXiv:1311.2839; Censor-Hillel et al.,
arXiv:1209.6158), so a member's protocol-visible world can be k sampled
neighbors plus a bounded rumor pool. This module is that third engine:

* ``nbr_id`` / ``nbr_key`` — the [N, k] neighbor table: slot s of row i
  holds a member id (or -1 empty) and that member's packed precedence key
  (:mod:`.lattice`, i32 wide or saturating-i16 narrow layout via
  ``key_dtype``). Slots ``[0, ka)`` are the ACTIVE view (FD probe targets,
  gossip fanout peers, SYNC peers are sampled here); ``[ka, k)`` the
  PASSIVE view (the HyParView-style healing reservoir, refreshed by the
  SYNC-folded shuffle and promoted into the active view by the
  maintenance sweep).
* ``self_key`` — each row's record about itself ([N] i32): the dense
  engine's diagonal, kept separate so refutation/identity logic stays O(N).
* membership + user rumor pools — the sparse engine's bounded-pool design
  verbatim (``mr_*`` [M], ``minf_age`` [N, M] u8, ``rumor_*``/[N, R]);
  the allocation / priority-eviction / backpressure machinery is IMPORTED
  from :mod:`.sparse` (one spelling).
* delivery — gather/scatter over neighbor index tables (per-fanout-slot
  inverse sender indexes + row gathers, the sparse deviation-6 design):
  per-tick work is O(N·(f·T + M + A·k)), memory O(N·(k + M)) — no [N, N]
  or [N, ceil(N/32)] allocation anywhere in this module (statically
  enforced by tools/lint_plane_dtypes.py rule 3).

Randomness: the tick consumes the EXACT sparse-engine draw stream
(:func:`.rand.draw_sparse_fd` / :func:`.rand.draw_sparse_round` under the
same two-subkey split) — uniforms are interpreted as ACTIVE-SLOT indexes
instead of column indexes. The scalar oracle (:mod:`.pview_oracle`) replays
the identical draws and is bit-exact in lockstep.

Deliberate deviations from the reference (beyond the sparse engine's six,
which this engine inherits where the machinery is shared):

P1. **Partial views.** A member holds records about at most k neighbors
    (+ itself); the reference holds the full member table. SWIM's
    guarantees survive: FD only ever probes a bounded random subset per
    round, gossip only needs fanout-many live peers, and the bounded-
    fanout rumor spread keeps its O(log N) rounds and fault tolerance
    under sampled views (arXiv:1311.2839 §1, arXiv:1209.6158 §1) —
    docs/PARTIAL_VIEW.md carries the full argument.
P2. **Static log-size knobs.** A partial view cannot count the cluster,
    so every ``ceilLog2(cluster size)`` knob (suspicion timeout, spread
    window, sweep lifetime) uses the static capacity N — an upper bound
    that only adds dissemination redundancy / suspicion patience (real
    deployments gossip a size estimate; the sim's capacity is exact).
P3. **Insertion/eviction.** A record about an unknown subject inserts at
    the first empty slot, else evicts the minimum-key PASSIVE entry
    (newest facts get residency — the pool-eviction philosophy of sparse
    deviation 3). An evicted record is forgotten, not refuted; it heals
    via SYNC/shuffle exactly like an evicted pool rumor.
P4. **Symmetric SYNC exchange of pre-state.** A SYNC round trip merges
    the two parties' PRE-exchange tables into each other (k + 1 records
    each way, the self record included); the reference's ACK carries the
    peer's post-merge table. Anti-entropy still converges — the combined
    information flows on the next exchange — and the regather-free form
    keeps the phase O(K·k).
P5. **Per-receiver apply cap.** A receiver applies at most ``apply_slots``
    newly-arriving membership rumors per tick (lowest pool slots first);
    the rest are NOT marked infected, so their senders keep forwarding
    while the spread window lasts (the same retry-on-drop shape as sparse
    deviation 6). Steady-state change rates sit far below the cap.
P6. **SYNC receiver collision drop.** When several SYNC callers pick the
    same peer in one tick, the peer merges only the highest-slot caller's
    table that tick (the losers' round trips still count for their own
    ACK merge) — the sparse deviation-6 collision rule applied to
    anti-entropy.
P7. **Self-expiry does not announce.** A row whose own record expires
    SUSPECT→DEAD in its table refutes next tick anyway (the refutation is
    the announcement); other observers run their own timers.
P8. **Bounded tombstones.** DEAD table entries are purged (forgotten, not
    refuted) every ``tombstone_ticks`` — the reference removes DEAD
    members from its table immediately; a partial view keeps them one
    pool-rumor lifetime as re-admission guards. The purge is globally
    synchronous, which makes post-heal re-convergence DETERMINISTICALLY
    bounded: no table can re-infect another through a SYNC merge, and
    stale pool rumors age out on their own sweep.

Partition model (no [N, N] loss plane): ``part_id`` [N] + ``part_loss``
[G, G] — chaos partitions assign the faulted row groups to partition
cells and block/heal the cell pairs; uniform loss/delay stay scalars.
Loss(i, j) = max(uniform, part_loss[part_id[i], part_id[j]]).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from .lattice import (
    ALIVE,
    RANK_ALIVE,
    RANK_DEAD,
    RANK_LEAVING,
    RANK_SUSPECT,
    UNKNOWN_KEY,
    bump_inc,
    key_np_dtype,
    layout_of,
    precedence_key,
)
from .rand import (
    SALT_GOSSIP,
    SALT_SYNC_ACK,
    SALT_SYNC_REQ,
    draw_sparse_fd,
    draw_sparse_round,
    fetch_uniform,
    split_tick_key,
)
from .. import adaptive as _adp
from ..adaptive import AdaptiveSpec
from ..dissemination import strategies as _dz
from ..dissemination.spec import DissemSpec
from .sparse import TELEMETRY_SERIES as _SPARSE_TELEMETRY_SERIES, _alloc_phase, _allocate
from .state import NEVER, NO_CANDIDATE_I32, delay_mean_to_q

NO_CANDIDATE = NO_CANDIDATE_I32

#: chaos StateTimeline capability flag: Partition events run on the group
#: model (part_id/part_loss) without an [N, N] link plane
GROUP_PARTITIONS = True


@dataclasses.dataclass(frozen=True)
class _RaggedDelivery:
    """Trace-time arming record for the sharded delivery rewrite (r20)."""

    mesh: object
    axis: str
    budget: int | None


_RAGGED_DELIVERY: contextvars.ContextVar = contextvars.ContextVar(
    "pview_ragged_delivery", default=None
)


@contextlib.contextmanager
def ragged_delivery_context(mesh, axis: str, budget: int | None = None):
    """Arm the ragged all-to-all delivery rewrite for traces entered under
    this context (r20). While armed, both gossip phases replace the global
    inverse-sender election + row gather with the shard-local election +
    record exchange of :mod:`.ragged_a2a`, and surface the bucket-overflow
    sentinel as the ``delivery_overflow`` metric. Mirrors the sparse
    engine's ``mesh_context`` pattern: the context must be ACTIVE DURING
    TRACING, so sharded builders enter it inside the jitted closure."""
    token = _RAGGED_DELIVERY.set(_RaggedDelivery(mesh, axis, budget))
    try:
        yield
    finally:
        _RAGGED_DELIVERY.reset(token)


def _ragged_ctx() -> _RaggedDelivery | None:
    return _RAGGED_DELIVERY.get()


def _ceil_log2_static(n: int) -> int:
    return int(n).bit_length() if n > 0 else 0


@dataclasses.dataclass(frozen=True)
class PviewParams:
    """Static parameters of the partial-view tick (hashable; close over in
    jit). Shared protocol knobs mirror :class:`.sparse.SparseParams` (same
    reference anchors); the pview-only knobs size the neighbor table:
    ``view_slots`` (k, total slots/row), ``active_slots`` (ka, the sampled
    prefix), ``apply_slots`` (A, per-receiver rumor applies/tick —
    deviation P5), ``partition_groups`` (G, chaos partition cells)."""

    capacity: int
    view_slots: int = 24
    active_slots: int = 8
    fanout: int = 3
    repeat_mult: int = 3
    ping_req_k: int = 3
    fd_every: int = 5
    sync_every: int = 150
    sync_stagger: int = 1
    suspicion_mult: int = 5
    sweep_every: int = 8
    sample_tries: int = 4
    rumor_slots: int = 16
    mr_slots: int = 0  # 0 = auto: min(2048, max(256, capacity // 32))
    announce_slots: int = 256
    sync_slots: int = 0
    sync_announce: int = 2
    # Every Q-th periodic SYNC round of a row goes DETERMINISTICALLY to a
    # seed (round-robin over seeds) instead of the sampled table draw.
    # This bounds the partial-view RE-BRIDGING latency: after a partition's
    # mutual kill each side's tombstones make the other side unsampleable,
    # so without a deterministic seed visit the halves reconnect only
    # through the probabilistic union-pool seed draw — a
    # (1 - S/(ka+S))^rounds tail that can outlive any budget.
    seed_sync_every: int = 4
    # DEAD tombstones are PURGED from the tables every ``tombstone_ticks``
    # (0 = auto: sweep_ticks, the pool-rumor lifetime — the death rumor has
    # finished spreading by then). The reference removes DEAD members from
    # its table outright; a partial view keeps them one dissemination
    # window as re-admission guards and then forgets (deviation P8). The
    # purge is GLOBALLY SYNCHRONOUS (same tick on every row), so tables
    # cannot re-infect each other through SYNC merges, and pool rumors
    # age out on their own sweep — post-heal convergence is therefore
    # bounded by purge period + sweep_ticks, deterministically.
    tombstone_ticks: int = 0
    apply_slots: int = 8
    partition_groups: int = 4
    fd_accept_slots: int = 0
    refute_slots: int = 0
    delay_slots: int = 0
    fd_direct_timeout_ticks: int = 2
    fd_leg_timeout_ticks: int = 1
    sync_timeout_ticks: int = 15
    seed_rows: tuple = ()
    early_free: bool = True
    full_metrics: bool = False
    key_dtype: str = "i32"
    # Dissemination strategy/topology (r13, dissemination/): the default
    # spec traces the byte-identical legacy program. Structured topologies
    # are CLOSED-FORM circulant chords — no [N, N] (nor [N, k] extra)
    # adjacency state, so the O(N·k) forbid_wide_values contract holds
    # unchanged for every strategy.
    dissem: DissemSpec = DissemSpec()
    # Adaptive failure detection (r14, adaptive.py): default = the
    # byte-identical legacy program; enabled specs arm the Lifeguard-style
    # plane via make_pview_adaptive_run. All adaptive state is three [N]
    # i32 planes — forbid_wide_values holds over adaptive windows too.
    adaptive: AdaptiveSpec = AdaptiveSpec()
    # r17 fused-path delivery backend. Consulted ONLY by the fused tick
    # (pview_tick_fused / make_pview_fused_run): "xla" keeps the gather +
    # masked-OR combine as XLA ops, "pallas" routes the per-fanout-slot
    # inverse-sender delivery through ops/pallas_delivery.py (interpreted
    # on CPU, lowered on TPU). The LEGACY tick never reads this knob, so
    # the default path traces the byte-identical legacy program under
    # either value (r13/r14 default-off discipline).
    delivery_kernel: str = "xla"

    def __post_init__(self):
        if not (0 < self.active_slots < self.view_slots):
            raise ValueError(
                "need 0 < active_slots < view_slots (the passive reservoir "
                f"must be non-empty): got ka={self.active_slots}, "
                f"k={self.view_slots}"
            )
        key_np_dtype(self.key_dtype)  # validates the spelling
        if self.delivery_kernel not in ("xla", "pallas"):
            raise ValueError(
                "delivery_kernel must be 'xla' or 'pallas': got "
                f"{self.delivery_kernel!r}"
            )
        if self.partition_groups < 3:
            raise ValueError(
                "partition_groups must be >= 3 (cell 0 is the unpartitioned "
                "cell and a partition needs two DISTINCT cells): got "
                f"G={self.partition_groups}"
            )

    @property
    def mr_pool(self) -> int:
        return self.mr_slots or min(2048, max(256, self.capacity // 32))

    @property
    def log2n(self) -> int:
        """Static ceil-log2 of capacity — every cluster-size knob
        (deviation P2)."""
        return _ceil_log2_static(self.capacity)

    @property
    def spread_ticks(self) -> int:
        return self.repeat_mult * self.log2n

    @property
    def sweep_ticks(self) -> int:
        return 2 * (self.repeat_mult * self.log2n + 1)

    @property
    def suspicion_timeout_ticks(self) -> int:
        return self.suspicion_mult * self.log2n * self.fd_every

    @property
    def purge_sweeps(self) -> int:
        """Tombstone purge cadence in SWEEPS (ceil of tombstone_ticks /
        sweep_every) — the purge rides the maintenance sweep."""
        tt = self.tombstone_ticks or self.sweep_ticks
        return max(1, -(-tt // self.sweep_every))

    @staticmethod
    def from_config(
        config,
        capacity: int | None = None,
        initial_size: int | None = None,
        seed_rows: tuple = (0,),
        mr_slots: int | None = None,
        view_slots: int | None = None,
    ) -> "PviewParams":
        """Derive pview params from a ClusterConfig — the sparse tick-unit
        mapping (one tick = one gossip period) plus the table sizing."""
        sim = config.sim
        cap = capacity or sim.capacity or (initial_size or 0)
        if cap <= 1:
            raise ValueError(
                "sim capacity must be > 1 (set config.sim.capacity, or pass "
                "capacity= / initial_size=)"
            )
        dt = sim.tick_interval
        return PviewParams(
            capacity=cap,
            view_slots=view_slots or sim.view_slots,
            active_slots=sim.active_slots,
            fanout=config.gossip.gossip_fanout,
            repeat_mult=config.gossip.gossip_repeat_mult,
            ping_req_k=config.failure_detector.ping_req_members,
            fd_every=max(1, round(config.failure_detector.ping_interval / dt)),
            sync_every=max(1, round(config.membership.sync_interval / dt)),
            suspicion_mult=config.membership.suspicion_mult,
            rumor_slots=sim.rumor_slots,
            mr_slots=mr_slots or 0,
            seed_rows=tuple(seed_rows),
            delay_slots=sim.delay_slots,
            key_dtype=sim.plane_dtype,
            fd_direct_timeout_ticks=max(
                0, int(config.failure_detector.ping_timeout / dt)
            ),
            fd_leg_timeout_ticks=max(
                0,
                int(
                    (config.failure_detector.ping_interval
                     - config.failure_detector.ping_timeout) / dt / 2
                ),
            ),
            sync_timeout_ticks=max(0, int(config.membership.sync_timeout / dt)),
            dissem=DissemSpec.from_config(config),
            adaptive=AdaptiveSpec.from_config(config),
        )


class PviewState(struct.PyTreeNode):
    """Lean partial-view simulation state — O(N·(k + M)) total.

    Key-value convention: ``nbr_key`` is stored in the configured
    ``key_dtype`` plane (i32 wide / i16 narrow); every OTHER key carrier
    (``self_key``, ``sus_key``, ``mr_key``, proposals) is an i32 holding a
    value packed under the SAME layout (narrow values sign-extend
    losslessly), so all comparison logic runs in i32 and only the [N, k]
    plane pays the narrow footprint."""

    tick: jax.Array
    up: jax.Array  # bool [N]
    epoch: jax.Array  # i32 [N]
    joined_at: jax.Array  # i32 [N]
    self_key: jax.Array  # i32 [N] — own record (the dense diagonal)
    nbr_id: jax.Array  # i32 [N, k] — neighbor member ids, -1 empty
    nbr_key: jax.Array  # kdt [N, k] — neighbor precedence keys
    sus_key: jax.Array  # i32 [N]
    sus_since: jax.Array  # i32 [N]
    force_sync: jax.Array  # bool [N]
    leaving: jax.Array  # bool [N]
    mr_active: jax.Array  # bool [M]
    mr_subject: jax.Array  # i32 [M]
    mr_key: jax.Array  # i32 [M]
    mr_created: jax.Array  # i32 [M]
    mr_origin: jax.Array  # i32 [M]
    minf_age: jax.Array  # u8 [N, M]
    rumor_active: jax.Array  # bool [R]
    rumor_origin: jax.Array  # i32 [R]
    rumor_created: jax.Array  # i32 [R]
    infected: jax.Array  # bool [N, R]
    infected_at: jax.Array  # i32 [N, R]
    infected_from: jax.Array  # i32 [N, R]
    loss: jax.Array  # f32 scalar — uniform loss floor
    delay_q: jax.Array  # f32 scalar — uniform geometric delay parameter
    part_id: jax.Array  # i32 [N] — partition cell per row (0 = default)
    part_loss: jax.Array  # f32 [G, G] — partition cell-pair loss
    pending_minf: jax.Array  # bool [D, N, M]
    pending_inf: jax.Array  # bool [D, N, R]
    pending_src: jax.Array  # i32 [D, N, R]

    @property
    def capacity(self) -> int:
        return self.up.shape[0]

    @property
    def view_key(self):  # pragma: no cover - guard, not a code path
        raise AttributeError(
            "PviewState has no [N, N] view plane — use engine_api.view_row / "
            "tracer_view_cols to synthesize row/column views"
        )


# ---------------------------------------------------------------------------
# construction + host mutators
# ---------------------------------------------------------------------------


def init_pview_state(
    params: PviewParams,
    n_initial: int,
    warm: bool = True,
    uniform_loss: float = 0.0,
    uniform_delay: float = 0.0,
) -> PviewState:
    """Fresh partial-view sim; rows ``0..n_initial-1`` up.

    Warm start fills each row's table with a deterministic SCATTERED
    sample of the initial membership: the ACTIVE slots get geometric
    long-range chords (offsets n/2, n/4, ...), the passive tail the small
    offsets — a binary-dissemination overlay. The scatter matters: the
    reference's gossip draw is uniform over the FULL member table, and a
    k-sample only preserves the O(log N)-round epidemic bound if it spans
    the cluster (arXiv:1311.2839's direct-addressing reach). A ring
    neighborhood (the obvious i+1..i+k fill) degenerates infection into a
    LINEAR wavefront — ~ka members/tick — whenever the SYNC-folded
    shuffle is slow relative to the rumor's spread window, which is
    exactly the reference cadence (sync_every >> spread_ticks). Cold
    start knows only the configured seeds."""
    n, k, m, r = params.capacity, params.view_slots, params.mr_pool, params.rumor_slots
    g = params.partition_groups
    kdt = key_np_dtype(params.key_dtype)
    up = jnp.arange(n) < n_initial
    self_key = jnp.where(up, jnp.int32(0), UNKNOWN_KEY)  # ALIVE@0@0 packed == 0
    rows = np.arange(n)
    if warm and n_initial > 1:
        # distinct offsets, largest scales first (active prefix), then the
        # small-offset fill; padded with out-of-range values (-> empty
        # slots) when n_initial - 1 < k
        offs: list = []
        step = n_initial // 2
        while len(offs) < k and step > 1:
            # odd chords (step | 1): a set of even offsets can only ever
            # reach its own residue class — the parity trap
            c = step | 1
            if c < n_initial and c not in offs:
                offs.append(c)
            step //= 2
        d = 1
        while len(offs) < k and len(offs) < n_initial - 1:
            c = d % n_initial
            if c and c not in offs:
                offs.append(c)
            d += 1
        while len(offs) < k:
            offs.append(n_initial + len(offs))  # invalid -> empty slot
        offs_a = np.asarray(offs, np.int64)
        ids = (rows[:, None] + offs_a[None, :]) % max(n_initial, 1)
        valid = (rows[:, None] < n_initial) & (offs_a[None, :] < n_initial)
        ids = np.where(valid, ids, -1).astype(np.int32)
    else:
        ids = np.full((n, k), -1, np.int32)
        seeds = [s for s in params.seed_rows if s < n_initial]
        for i in range(n_initial):
            s_i = [s for s in seeds if s != i][: k]
            ids[i, : len(s_i)] = s_i
    nbr_id = jnp.asarray(ids)
    nbr_key = jnp.where(nbr_id >= 0, 0, UNKNOWN_KEY).astype(kdt)
    if uniform_delay > 0 and params.delay_slots <= 0:
        raise ValueError("uniform_delay > 0 requires params.delay_slots > 0")
    d = max(0, params.delay_slots)
    return PviewState(
        tick=jnp.int32(0),
        up=up,
        epoch=jnp.zeros((n,), jnp.int32),
        joined_at=jnp.zeros((n,), jnp.int32),
        self_key=self_key.astype(jnp.int32),
        nbr_id=nbr_id,
        nbr_key=nbr_key,
        sus_key=jnp.full((n,), NO_CANDIDATE, jnp.int32),
        sus_since=jnp.full((n,), NEVER, jnp.int32),
        force_sync=jnp.zeros((n,), bool),
        leaving=jnp.zeros((n,), bool),
        mr_active=jnp.zeros((m,), bool),
        mr_subject=jnp.full((m,), -1, jnp.int32),
        mr_key=jnp.zeros((m,), jnp.int32),
        mr_created=jnp.zeros((m,), jnp.int32),
        mr_origin=jnp.zeros((m,), jnp.int32),
        minf_age=jnp.zeros((n, m), jnp.uint8),
        rumor_active=jnp.zeros((r,), bool),
        rumor_origin=jnp.zeros((r,), jnp.int32),
        rumor_created=jnp.zeros((r,), jnp.int32),
        infected=jnp.zeros((n, r), bool),
        infected_at=jnp.zeros((n, r), jnp.int32),
        infected_from=jnp.full((n, r), -1, jnp.int32),
        loss=jnp.float32(uniform_loss),
        delay_q=jnp.float32(delay_mean_to_q(uniform_delay)),
        part_id=jnp.zeros((n,), jnp.int32),
        part_loss=jnp.zeros((g, g), jnp.float32),
        pending_minf=jnp.zeros((d, n, m), bool),
        pending_inf=jnp.zeros((d, n, r), bool),
        pending_src=jnp.full((d, n, r), -1, jnp.int32),
    )


def _kdt(state: PviewState):
    return state.nbr_key.dtype


def _keys_i32(state: PviewState) -> jax.Array:
    """The neighbor-key plane widened to i32 (sign-extension preserves the
    narrow layout's values, -1 included)."""
    return state.nbr_key.astype(jnp.int32)


def _pack_self(state_or_dtype, status, inc, epoch) -> jax.Array:
    """Pack under the configured layout, carried as i32 (see the state's
    key-value convention)."""
    kdt = _kdt(state_or_dtype) if isinstance(state_or_dtype, PviewState) else state_or_dtype
    return precedence_key(
        jnp.asarray(status, jnp.int32), jnp.asarray(inc, jnp.int32),
        jnp.asarray(epoch, jnp.int32), dtype=kdt,
    ).astype(jnp.int32)


def announce(state: PviewState, subject, key, origin) -> PviewState:
    """Host-side membership-rumor allocation — the sparse engine's pool
    machinery verbatim (:func:`.sparse._allocate` is imported; see its
    priority-eviction account)."""
    st, _a, _d, _e = _allocate(
        state,
        jnp.asarray([subject], jnp.int32),
        jnp.asarray([key], jnp.int32),
        jnp.asarray([origin], jnp.int32),
        jnp.ones((1,), bool),
        prio=jnp.ones((1,), bool),
    )
    return st


def _insert_rows_table(state: PviewState, rows, seed_rows):
    """Fresh table for joining ``rows``: seeds in ascending slots."""
    k = state.nbr_id.shape[1]
    kdt = _kdt(state)
    rows = jnp.asarray(rows, jnp.int32)
    seed_rows = jnp.asarray(seed_rows, jnp.int32)[: k]
    nk = rows.shape[0]
    slots = jnp.arange(k)
    s_cnt = seed_rows.shape[0]
    ids = jnp.where(
        slots[None, :] < s_cnt,
        seed_rows[jnp.minimum(slots, s_cnt - 1)][None, :],
        -1,
    )
    ids = jnp.broadcast_to(ids, (nk, k))
    # a joiner never tables itself
    ids = jnp.where(ids == rows[:, None], -1, ids)
    seed_keys = _pack_self(
        kdt,
        jnp.full((nk, k), ALIVE),
        jnp.zeros((nk, k)),
        state.epoch[jnp.maximum(ids, 0)],
    )
    keys = jnp.where(ids >= 0, seed_keys, UNKNOWN_KEY).astype(kdt)
    return ids, keys


def join_row(state: PviewState, row: int, seed_rows) -> PviewState:
    """Activate ``row`` as a fresh member knowing the seeds; identical
    identity-epoch semantics to ``sparse.join_row`` (restart = new member
    id via the epoch bits) + self-announce rumor."""
    was_used = state.self_key[row] >= 0
    new_epoch = jnp.where(was_used, (state.epoch[row] + 1) & 0xFF, state.epoch[row])
    self_key = _pack_self(state, ALIVE, 0, new_epoch)
    ids, keys = _insert_rows_table(state, [row], seed_rows)
    state = state.replace(
        up=state.up.at[row].set(True),
        epoch=state.epoch.at[row].set(new_epoch),
        joined_at=state.joined_at.at[row].set(state.tick),
        self_key=state.self_key.at[row].set(self_key),
        nbr_id=state.nbr_id.at[row].set(ids[0]),
        nbr_key=state.nbr_key.at[row].set(keys[0]),
        force_sync=state.force_sync.at[row].set(True),
        leaving=state.leaving.at[row].set(False),
        minf_age=state.minf_age.at[row].set(0),
        infected=state.infected.at[row].set(False),
        infected_from=state.infected_from.at[row].set(-1),
        pending_minf=state.pending_minf.at[:, row].set(False)
        if state.pending_minf.shape[0]
        else state.pending_minf,
        pending_inf=state.pending_inf.at[:, row].set(False)
        if state.pending_inf.shape[0]
        else state.pending_inf,
        pending_src=state.pending_src.at[:, row].set(-1)
        if state.pending_src.shape[0]
        else state.pending_src,
    )
    return announce(state, row, self_key, row)


def join_rows(state: PviewState, rows, seed_rows) -> PviewState:
    """Vectorized churn-burst join (distinct ``rows``)."""
    rows = jnp.asarray(rows, jnp.int32)
    nk = rows.shape[0]
    was_used = state.self_key[rows] >= 0
    new_epoch = jnp.where(was_used, (state.epoch[rows] + 1) & 0xFF, state.epoch[rows])
    epoch_after = state.epoch.at[rows].set(new_epoch)
    self_keys = _pack_self(state, jnp.full((nk,), ALIVE), jnp.zeros((nk,)), new_epoch)
    st = state.replace(epoch=epoch_after)
    ids, keys = _insert_rows_table(st, rows, seed_rows)
    state = st.replace(
        up=state.up.at[rows].set(True),
        joined_at=state.joined_at.at[rows].set(state.tick),
        self_key=state.self_key.at[rows].set(self_keys),
        nbr_id=state.nbr_id.at[rows].set(ids),
        nbr_key=state.nbr_key.at[rows].set(keys),
        force_sync=state.force_sync.at[rows].set(True),
        leaving=state.leaving.at[rows].set(False),
        minf_age=state.minf_age.at[rows].set(0),
        infected=state.infected.at[rows].set(False),
        infected_from=state.infected_from.at[rows].set(-1),
        pending_minf=state.pending_minf.at[:, rows].set(False)
        if state.pending_minf.shape[0]
        else state.pending_minf,
        pending_inf=state.pending_inf.at[:, rows].set(False)
        if state.pending_inf.shape[0]
        else state.pending_inf,
        pending_src=state.pending_src.at[:, rows].set(-1)
        if state.pending_src.shape[0]
        else state.pending_src,
    )
    state, _a, _d, _e = _allocate(
        state, rows, self_keys, rows, jnp.ones((nk,), bool),
        prio=jnp.ones((nk,), bool),
    )
    return state


def crash_row(state: PviewState, row: int) -> PviewState:
    return state.replace(up=state.up.at[row].set(False))


def crash_rows(state: PviewState, rows) -> PviewState:
    return state.replace(up=state.up.at[jnp.asarray(rows, jnp.int32)].set(False))


def begin_leave(state: PviewState, row: int) -> PviewState:
    own = state.self_key[row]
    leaving_key = ((own >> 2) << 2) | RANK_LEAVING
    state = state.replace(
        self_key=state.self_key.at[row].set(leaving_key),
        leaving=state.leaving.at[row].set(True),
    )
    return announce(state, row, leaving_key, row)


def update_metadata(state: PviewState, row: int) -> PviewState:
    """Metadata update = own-incarnation bump re-announced ALIVE; routed
    through :func:`.lattice.bump_inc` so the narrow layout saturates."""
    kdt = _kdt(state)
    new_key = bump_inc(
        state.self_key[row].astype(kdt), RANK_ALIVE
    ).astype(jnp.int32)
    state = state.replace(self_key=state.self_key.at[row].set(new_key))
    return announce(state, row, new_key, row)


def spread_rumor(state: PviewState, slot: int, origin: int) -> PviewState:
    return state.replace(
        rumor_active=state.rumor_active.at[slot].set(True),
        rumor_origin=state.rumor_origin.at[slot].set(origin),
        rumor_created=state.rumor_created.at[slot].set(state.tick),
        infected=state.infected.at[:, slot].set(False).at[origin, slot].set(True),
        infected_at=state.infected_at.at[origin, slot].set(state.tick),
        infected_from=state.infected_from.at[:, slot].set(-1),
    )


def set_uniform_loss(state: PviewState, loss, floor: bool = False) -> PviewState:
    new = jnp.maximum(state.loss, loss) if floor else jnp.asarray(loss, jnp.float32)
    return state.replace(loss=jnp.float32(new))


def _part_cell(rows) -> int:
    """Deterministic partition-cell id for a host-side row group: cells are
    hashed from the group's minimum row into [1, G). Two simultaneous
    partitions whose groups hash to the same cell merge (documented bound;
    G is ``PviewParams.partition_groups``)."""
    return int(min(int(r) for r in rows))


def _cells_for(state: PviewState, group_a, group_b) -> tuple[int, int]:
    g = state.part_loss.shape[0]
    ra, rb = _part_cell(group_a), _part_cell(group_b)
    ca = 1 + (ra % (g - 1))
    cb = 1 + (rb % (g - 1))
    if ca == cb:
        # Order-independent collision remap: bump the group with the LARGER
        # raw min row, so (a, b) and (b, a) resolve to the same cell pair.
        # ("Always bump the second" left the heal path one-directional:
        # both set_link_loss(a, b, 0) and set_link_loss(b, a, 0) landed on
        # the same ordered cell and part_loss[cb, ca] stayed 1.0 forever.)
        if ra <= rb:
            cb = 1 + (cb % (g - 1))
        else:
            ca = 1 + (ca % (g - 1))
    return ca, cb


def block_partition(state: PviewState, group_a, group_b) -> PviewState:
    ca, cb = _cells_for(state, group_a, group_b)
    part = (
        state.part_id.at[jnp.asarray(list(group_a), jnp.int32)].set(ca)
        .at[jnp.asarray(list(group_b), jnp.int32)].set(cb)
    )
    pl = state.part_loss.at[ca, cb].set(1.0).at[cb, ca].set(1.0)
    return state.replace(part_id=part, part_loss=pl)


def set_link_loss(state: PviewState, src, dst, loss) -> PviewState:
    """Group-pair loss only (the chaos partition heal path): ``src``/``dst``
    must be the row groups of an earlier :func:`block_partition`. Arbitrary
    per-link loss needs an [N, N] plane — exactly what this engine bans."""
    src = list(np.atleast_1d(np.asarray(src)))
    dst = list(np.atleast_1d(np.asarray(dst)))
    ca, cb = _cells_for(state, src, dst)
    pl = state.part_loss.at[ca, cb].set(jnp.float32(loss))
    return state.replace(part_loss=pl)


def heal_partition(state: PviewState, group_a, group_b) -> PviewState:
    s = set_link_loss(state, group_a, group_b, 0.0)
    return set_link_loss(s, group_b, group_a, 0.0)


def set_link_delay(state: PviewState, src, dst, mean_delay_ticks: float):
    raise ValueError(
        "per-link delay needs an [N, N] plane; the pview engine supports "
        "uniform delay only (init_pview_state(uniform_delay=...))"
    )


def sentinel_reduce(state: PviewState, sent: dict, spec: dict) -> dict:
    """Pview chaos-sentinel check over the [N, k] tables + self records
    (the partial-view analogue of :func:`.kernel.sentinel_core`):

    * false-DEAD — never-faulted up subjects tombstoned by any up observer
      (table edges only; a subject nobody tables cannot be falsely dead).
    * detection — a crashed row is detected once NO up observer holds a
      non-DEAD record about it (unknown counts as detected, matching the
      reference's removal semantics).
    * convergence — no up observer holds a non-ALIVE record about any up
      subject (partial-view re-convergence: every live edge agrees ALIVE).
    * key regressions — self records never regress (lattice monotonicity).
    * view invariant — no duplicate subjects and no self-entry within any
      row's table (the pview analogue of sparse's n_live drift: corruption
      no protocol-level check would see).
    """
    n = state.capacity
    keys = _keys_i32(state)
    sid = state.nbr_id
    sidc = jnp.maximum(sid, 0)
    valid = sid >= 0
    rank = keys & 3
    rel = state.tick - spec["t0"]

    sent = dict(sent)
    sent["key_regressions"] = sent["key_regressions"] + (
        state.self_key < sent["prev_diag"]
    ).sum().astype(jnp.int32)
    sent["prev_diag"] = state.self_key

    nf_up = spec["never_faulted"] & state.up
    fd_edge = valid & state.up[:, None] & (rank == RANK_DEAD) & nf_up[sidc]
    false_dead = (
        jnp.zeros((n + 1,), bool)
        .at[jnp.where(fd_edge, sid, n)]
        .max(fd_edge, mode="drop")[:n]
        .sum()
        .astype(jnp.int32)
    )
    sent["false_dead_max"] = jnp.maximum(sent["false_dead_max"], false_dead)

    if "fp_watch" in spec:
        # r14 false-positive sentinel (table-edge twin of the dense check):
        # degraded-but-alive watched subjects tombstoned by any up observer
        fp_up = spec["fp_watch"] & state.up
        fp_edge = valid & state.up[:, None] & (rank == RANK_DEAD) & fp_up[sidc]
        fp_dead = (
            jnp.zeros((n + 1,), bool)
            .at[jnp.where(fp_edge, sid, n)]
            .max(fp_edge, mode="drop")[:n]
            .sum()
            .astype(jnp.int32)
        )
        sent["fp_dead_max"] = jnp.maximum(sent["fp_dead_max"], fp_dead)

    crash_rows_ = spec["crash_rows"]
    if crash_rows_.shape[0]:
        holds = (
            valid[:, :, None]
            & state.up[:, None, None]
            & (sid[:, :, None] == crash_rows_[None, None, :])
            & (rank[:, :, None] != RANK_DEAD)
        )
        detected = ~holds.any(axis=(0, 1))
        active = (
            (rel >= spec["crash_at"])
            & (rel <= spec["crash_until"])
            & (sent["detect_tick"] < 0)
        )
        sent["detect_tick"] = jnp.where(active & detected, rel, sent["detect_tick"])

    if spec["conv_from"].shape[0]:
        bad_edge = (
            valid & state.up[:, None] & state.up[sidc] & (rank != RANK_ALIVE)
        )
        converged = ~bad_edge.any()
        active = (rel >= spec["conv_from"]) & (sent["conv_tick"] < 0)
        sent["conv_tick"] = jnp.where(active & converged, rel, sent["conv_tick"])

    dup = (
        valid[:, :, None]
        & valid[:, None, :]
        & (sid[:, :, None] == sid[:, None, :])
        & ~jnp.eye(sid.shape[1], dtype=bool)[None]
    ).any(axis=(1, 2))
    self_entry = (valid & (sid == jnp.arange(n)[:, None])).any(axis=1)
    breaks = (dup | self_entry).sum().astype(jnp.int32)
    sent["view_invariant_breaks"] = (
        sent.get("view_invariant_breaks", jnp.int32(0)) + breaks
    )
    return sent


def sentinel_init(state: PviewState, spec) -> dict:
    """Fresh sentinel accumulators baselined on the current self records.

    ``prev_diag`` must be an independent COPY: the live ``self_key`` leaf
    is donated away by the next window, and an aliased baseline would read
    "Array has been deleted" at the first sentinel check (dense gets this
    for free from its diag gather)."""
    sent = {
        "prev_diag": jnp.array(state.self_key, copy=True),
        "key_regressions": jnp.int32(0),
        "false_dead_max": jnp.int32(0),
        "detect_tick": jnp.full((len(spec.crash_rows),), -1, jnp.int32),
        "conv_tick": jnp.full((len(spec.conv_from),), -1, jnp.int32),
        "view_invariant_breaks": jnp.int32(0),
    }
    if spec.fp_watch.size and bool(spec.fp_watch.any()):
        sent["fp_dead_max"] = jnp.int32(0)  # r14 false-positive sentinel
    return sent


# pview telemetry ring layout: the sparse series (shared core + pool
# backpressure — the pool machinery IS the sparse pool) plus two r21
# mesh-observability columns. ``delivery_overflow`` is the ragged
# all-to-all drop sentinel (already psummed inside the sharded window, so
# the sum below folds to the same replicated global on every shard; a
# constant 0 on single-device and unbudgeted runs). ``shard_peak_mem_mb``
# is the per-shard donated-state footprint, baked in as a trace-time
# constant — it is the one deployment-dependent column, excluded from
# sharded-vs-single-device bit-identity comparisons by construction.
TELEMETRY_SERIES = _SPARSE_TELEMETRY_SERIES + (
    "delivery_overflow",
    "shard_peak_mem_mb",
)


def telemetry_window_vector(
    ms: dict, state: PviewState, *, shard_mem_mb: float = 0.0
) -> jax.Array:
    from .kernel import telemetry_window_core

    f32 = jnp.float32
    vec = telemetry_window_core(ms, state)
    vec.extend(
        [
            ms["announced"].sum().astype(f32),
            ms["announce_dropped"].sum().astype(f32),
            ms["pool_evicted"].sum().astype(f32),
            ms["mr_active_count"].max().astype(f32),
            # the key exists only under an armed ragged-delivery context
            # (sharded windows); unsharded windows fold the column to 0
            jnp.asarray(ms.get("delivery_overflow", 0), jnp.int32).sum().astype(f32),
            jnp.float32(shard_mem_mb),
        ]
    )
    return jnp.stack(vec)


def snapshot(state: PviewState) -> dict:
    return {
        f.name: np.asarray(getattr(state, f.name))
        for f in dataclasses.fields(PviewState)
    }


def restore(arrays: dict) -> PviewState:
    # copy=True: see the dense state.restore use-after-free account (r6)
    return PviewState(**{k: jnp.array(v, copy=True) for k, v in arrays.items()})


# ---------------------------------------------------------------------------
# in-tick helpers
# ---------------------------------------------------------------------------


def _loss_at(state: PviewState, i, j):
    base = jnp.broadcast_to(state.loss, jnp.shape(i))
    part = state.part_loss[state.part_id[i], state.part_id[j]]
    return jnp.maximum(base, part)


def _rt_at(state: PviewState, i, j):
    return (1.0 - _loss_at(state, i, j)) * (1.0 - _loss_at(state, j, i))


def _timely_rt(q1, q2, t: int):
    h = jnp.ones_like(q1)
    acc = h
    q2p = jnp.ones_like(q2)
    for _ in range(t):
        q2p = q2p * q2
        h = q1 * h + q2p
        acc = acc + h
    return (1.0 - q1) * (1.0 - q2) * acc


def _rt_timely(state: PviewState, i, j, t: int):
    p = _rt_at(state, i, j)
    if state.pending_inf.shape[0]:
        q = jnp.broadcast_to(state.delay_q, jnp.shape(i))
        p = p * _timely_rt(q, q, t)
    return p


def _sample_slots(state: PviewState, rows, u, n_picks: int, tries: int, ka: int):
    """Per-row ``n_picks`` distinct ACTIVE-SLOT draws by bounded rejection —
    the slot-space mirror of :func:`.sparse._sample_rejection`: each pick
    takes the first of ``tries`` uniform slot draws that holds a non-DEAD
    neighbor and differs from earlier picks. Slot distinctness IS member
    distinctness (table rows hold unique subjects).

    Returns (slot [R, P] clamped, member [R, P] clamped, valid [R, P])."""
    slots = jnp.minimum((u * np.float32(ka)).astype(jnp.int32), ka - 1)
    sid = state.nbr_id[rows[:, None], slots]
    skey = state.nbr_key[rows[:, None], slots].astype(jnp.int32)
    ok_base = (sid >= 0) & ((skey & 3) != RANK_DEAD)
    picks = []
    for p in range(n_picks):
        sel = jnp.full(rows.shape, -1, jnp.int32)
        for t in range(tries):
            c = slots[:, p * tries + t]
            ok = ok_base[:, p * tries + t]
            for q in picks:
                ok = ok & (c != q)
            sel = jnp.where((sel < 0) & ok, c, sel)
        picks.append(sel)
    slot = jnp.stack(picks, 1)
    valid = slot >= 0
    slot_c = jnp.maximum(slot, 0)
    member = state.nbr_id[rows[:, None], slot_c]
    return slot_c, jnp.maximum(member, 0), valid


def _apply_records(
    state: PviewState, subj, cand, valid, salt: int, ka: int
):
    """Merge one record per row into the row's world: ``subj``/``cand``
    [N] i32 (layout-valued), ``valid`` [N]. The ONE accept-and-place
    spelling every delivery path shares (gossip rumor apply, SYNC merge):

    * accept gates — identical to sparse: ``cand > own``; unknown subjects
      admit ALIVE/LEAVING only; ALIVE candidates pass the metadata-fetch
      gate (same salt-keyed stateless hash draw).
    * placement — subject == row routes to ``self_key``; a tabled subject
      updates in place; an unknown subject inserts at the first empty
      slot, else evicts the minimum-key passive entry (deviation P3).

    Returns (state, accepted [N] bool, sus_cand [N] i32 scatter-max input
    folded by the caller)."""
    n = state.capacity
    rows = jnp.arange(n)
    kdt = _kdt(state)
    k = state.nbr_id.shape[1]
    keys = _keys_i32(state)
    subj_c = jnp.clip(subj, 0, n - 1)

    to_self = valid & (subj == rows)
    to_tab = valid & ~to_self & (subj >= 0)

    match = state.nbr_id == subj[:, None]
    present = (match & to_tab[:, None]).any(axis=1)
    slot_p = jnp.argmax(match, axis=1).astype(jnp.int32)
    own_tab = jnp.where(present, keys[rows, slot_p], UNKNOWN_KEY)
    own = jnp.where(to_self, state.self_key, own_tab)

    needs_fetch = (cand & 3) == RANK_ALIVE
    u = fetch_uniform(state.tick, salt, rows, subj_c)
    fetch_ok = ~needs_fetch | (state.up[subj_c] & (u < _rt_at(state, rows, subj_c)))
    accept = (
        (to_self | to_tab)
        & (cand > own)
        & ((own >= 0) | ((cand & 3) <= RANK_LEAVING))
        & fetch_ok
    )

    new_self = jnp.where(accept & to_self, cand, state.self_key)

    acc_t = accept & to_tab
    empty = state.nbr_id < 0
    has_empty = empty.any(axis=1)
    slot_e = jnp.argmax(empty, axis=1).astype(jnp.int32)
    p_keys = keys[:, ka:]
    slot_v = (ka + jnp.argmin(p_keys, axis=1)).astype(jnp.int32)
    slot_w = jnp.where(present, slot_p, jnp.where(has_empty, slot_e, slot_v))
    onehot = acc_t[:, None] & (jnp.arange(k)[None, :] == slot_w[:, None])
    new_id = jnp.where(onehot, subj[:, None], state.nbr_id)
    new_key = jnp.where(onehot, cand[:, None].astype(kdt), state.nbr_key)

    sus_in = jnp.where(accept & ((cand & 3) == RANK_SUSPECT), cand, NO_CANDIDATE)
    sus_cand = (
        jnp.full((n + 1,), NO_CANDIDATE, jnp.int32)
        .at[jnp.where(accept, subj_c, n)]
        .max(sus_in, mode="drop")[:n]
    )
    state = state.replace(self_key=new_self, nbr_id=new_id, nbr_key=new_key)
    return state, accept, sus_cand


def _register_sus(state: PviewState, sus_cand) -> PviewState:
    new_sus = jnp.maximum(state.sus_key, sus_cand)
    return state.replace(
        sus_key=new_sus,
        sus_since=jnp.where(new_sus > state.sus_key, state.tick, state.sus_since),
    )


# ---------------------------------------------------------------------------
# phases
# ---------------------------------------------------------------------------


def _fd_phase(state: PviewState, r, params: PviewParams, trace: bool = False,
              ad=None, fused: bool = False):
    """Vectorized FD round over the active view — the sparse ``_fd_phase``
    with slot-space target/relay selection and the self-record ACK.

    ``fused=True`` (r17) additionally returns the POST-verdict i32-widened
    key plane — the fd-verdict→suspicion-evidence hand-off: the fused
    tick's maintenance sweep consumes it directly instead of re-widening
    (and re-gathering) the [N, k] ``nbr_key`` plane it just wrote. The
    hand-off value round-trips the verdict through the storage dtype
    (``cand → kdt → i32``), so it is bit-identical to what a re-widen of
    the written plane would read."""
    n = state.capacity
    rows = jnp.arange(n)
    ka = params.active_slots
    kdt = _kdt(state)
    keys = _keys_i32(state)
    tgt_slot_all, tgt_all, valid = _sample_slots(
        state, rows, r.fd_try, 1 + params.ping_req_k, params.sample_tries, ka
    )
    tgt_slot = tgt_slot_all[:, 0]
    tgt = tgt_all[:, 0]
    has_tgt = valid[:, 0] & state.up

    if params.delay_slots and ad is not None:
        # Lifeguard LHA (r14, AD-4): per-prober direct-timeout stretch
        q = jnp.broadcast_to(state.delay_q, (n,))
        p_direct = _rt_at(state, rows, tgt) * _adp.scaled_timely_rt(
            q, q, params.fd_direct_timeout_ticks, ad.lh,
            params.adaptive.lh_max,
        )
    elif params.delay_slots:
        p_direct = _rt_timely(state, rows, tgt, params.fd_direct_timeout_ticks)
    else:
        p_direct = _rt_at(state, rows, tgt)
    direct_ok = has_tgt & state.up[tgt] & (r.fd_direct < p_direct)

    relays = tgt_all[:, 1:]
    relay_valid = valid[:, 1:]
    tgt_b = tgt[:, None]
    p_relay = _rt_at(state, rows[:, None], relays) * _rt_at(state, relays, tgt_b)
    if params.delay_slots:
        q = jnp.broadcast_to(state.delay_q, relays.shape)
        p_relay = p_relay * _timely_rt(q, q, params.fd_leg_timeout_ticks)
        p_relay = p_relay * _timely_rt(q, q, params.fd_leg_timeout_ticks)
    relay_ok = relay_valid & state.up[relays] & state.up[tgt_b] & (r.fd_relay < p_relay)
    ack = direct_ok | relay_ok.any(axis=1)

    own_key = keys[rows, tgt_slot]
    alive_key = (state.self_key[tgt] >> 2) << 2
    suspect_key = ((own_key >> 2) << 2) | RANK_SUSPECT
    cand = jnp.where(ack, alive_key, suspect_key)
    accept = has_tgt & (cand > own_key)
    V = min(n, params.fd_accept_slots or max(64, n // 16))
    eff = accept & (jnp.cumsum(accept.astype(jnp.int32)) - 1 < V)

    onehot = eff[:, None] & (jnp.arange(state.nbr_id.shape[1])[None, :] == tgt_slot[:, None])
    st = state.replace(
        nbr_key=jnp.where(onehot, cand[:, None].astype(kdt), state.nbr_key)
    )
    sus_cand = (
        jnp.full((n,), NO_CANDIDATE, jnp.int32)
        .at[tgt]
        .max(jnp.where(eff & ~ack, cand, NO_CANDIDATE))
    )
    st = _register_sus(st, sus_cand)
    proposals = (tgt, cand, rows, eff)
    metrics = {
        "fd_probes": has_tgt.sum(),
        "fd_failed_probes": (has_tgt & ~ack).sum(),
        "fd_new_suspects": (eff & ~ack).sum(),
    }
    if ad is not None:
        # adaptive evidence (r14): sus_cand IS the per-subject max written
        # suspect key (the episode-key contribution)
        metrics["_ad_miss"] = has_tgt & ~ack
        metrics["_ad_succ"] = has_tgt & ack
        metrics["_ad_cnt"] = (
            jnp.zeros((n,), jnp.int32)
            .at[tgt]
            .add((eff & ~ack).astype(jnp.int32))
        )
        metrics["_ad_key"] = sus_cand
    if trace:
        metrics["trace_fd"] = {
            "tgt": tgt.astype(jnp.int32),
            "has_tgt": has_tgt,
            "ack": ack,
            "direct_ok": direct_ok,
            "suspect": eff & ~ack,
            "relays": relays.astype(jnp.int32),
            "relay_valid": relay_valid,
            "relay_ok": relay_ok,
        }
    if fused:
        cand_rt = cand.astype(kdt).astype(jnp.int32)
        keys_after = jnp.where(onehot, cand_rt[:, None], keys)
        return st, proposals, metrics, keys_after
    return st, proposals, metrics


def _maintenance_sweep(state: PviewState, params: PviewParams, trace=None,
                       ad=None, keys_i32=None):
    """Every ``sweep_every`` ticks: (1) suspicion-episode expiry over the
    [N, k] tables + the self records (sparse deviation 1 semantics, static
    timeout — deviation P2), with per-subject announcer election; (2) the
    TOMBSTONE PURGE (deviation P8) every ``purge_sweeps``-th sweep; (3)
    the ACTIVE-VIEW PROMOTION sweep — each empty/DEAD active slot swaps in
    the best (max-key) live passive entry, ascending active slots first.
    The promotion is the HyParView active-view repair, made deterministic.

    ``keys_i32`` (r17, fused path only): the i32-widened [N, k] key plane
    handed over by the FD phase — the expiry pass reads it instead of
    re-widening ``nbr_key`` (the purge/promotion passes re-read the plane
    they just rewrote, as before)."""
    n = state.capacity
    rows = jnp.arange(n)
    k = state.nbr_id.shape[1]
    ka = params.active_slots
    timeout = params.suspicion_timeout_ticks
    no_props = (
        jnp.zeros((n,), jnp.int32),
        jnp.zeros((n,), jnp.int32),
        rows,
        jnp.zeros((n,), bool),
    )

    def _expire(st: PviewState):
        keys = _keys_i32(st) if keys_i32 is None else keys_i32
        sid = st.nbr_id
        sidc = jnp.maximum(sid, 0)
        is_sus = (keys & 3) == RANK_SUSPECT
        if ad is not None:
            # r14 adaptive window: the static base (deviation P2) scaled by
            # the subject's confirmations and the observer's local health
            aspec = params.adaptive
            L = aspec.levels
            base0 = params.log2n * params.fd_every  # static int
            num_conf = _adp.conf_mult_num(aspec, ad.conf)  # [N]
            in_ep = keys <= ad.conf_key[sidc]
            num = jnp.where(
                in_ep, num_conf[sidc], jnp.int32(aspec.max_mult * L)
            )
            timeout_t = (
                base0 * num * (1 + ad.lh)[:, None]
            ) // jnp.int32(L)  # [N, k]
            in_ep_s = st.self_key <= ad.conf_key
            num_s = jnp.where(
                in_ep_s, num_conf, jnp.int32(aspec.max_mult * L)
            )
            timeout_s = (base0 * num_s * (1 + ad.lh)) // jnp.int32(L)  # [N]
        else:
            timeout_t = timeout
            timeout_s = timeout
        expired = (
            is_sus
            & st.up[:, None]
            & ((st.tick - st.sus_since[sidc]) >= timeout_t)
            & (keys <= st.sus_key[sidc])
        )
        new_keys = jnp.where(expired, keys + 1, keys)
        self_expired = (
            st.up
            & ((st.self_key & 3) == RANK_SUSPECT)
            & ((st.tick - st.sus_since) >= timeout_s)
            & (st.self_key <= st.sus_key)
        )
        new_self = jnp.where(self_expired, st.self_key + 1, st.self_key)
        any_suspect_left = (
            ((new_keys & 3) == RANK_SUSPECT) & st.up[:, None] & (sid >= 0)
        ).any() | (((new_self & 3) == RANK_SUSPECT) & st.up).any()
        sus_key = jnp.where(any_suspect_left, st.sus_key, NO_CANDIDATE)
        sus_since = jnp.where(any_suspect_left, st.sus_since, NEVER)
        # per-subject announcer election (sparse deviation 3): the lowest
        # expiring observer row announces; self-expiry never does (P7)
        first_row = (
            jnp.full((n + 1,), n, jnp.int32)
            .at[jnp.where(expired, sid, n)]
            .min(jnp.broadcast_to(rows[:, None], expired.shape), mode="drop")[:n]
        )
        mine = expired & (first_row[sidc] == rows[:, None])
        any_exp = mine.any(axis=1)
        col = jnp.argmax(mine, axis=1).astype(jnp.int32)
        subj = sid[rows, col]
        key = new_keys[rows, col]
        st = st.replace(
            nbr_key=new_keys.astype(_kdt(st)),
            self_key=new_self,
            sus_key=sus_key,
            sus_since=sus_since,
        )
        props = (jnp.maximum(subj, 0), key, rows, any_exp)
        if trace is not None:
            from ..trace import capture as _tc

            # tracer-subject expiry counts: [N, K] mask of expiring cells
            tr = jnp.asarray(trace.tracer_rows, jnp.int32)
            exp_cols = (
                (sid[:, :, None] == tr[None, None, :]) & expired[:, :, None]
            ).any(axis=1)
            return st, props, {
                "count": exp_cols.sum(axis=0).astype(jnp.int32),
                "by": _tc._exemplar(exp_cols),
            }
        return st, props

    def _skip_exp(st: PviewState):
        if trace is not None:
            from ..trace import capture as _tc

            return st, no_props, _tc.zero_sus_trace(trace)
        return st, no_props

    def _purge(st: PviewState):
        # tombstone purge (deviation P8): every purge_sweeps-th sweep,
        # forget every DEAD table entry — masked where, no cond (the
        # cadence test is on the traced tick)
        do = ((st.tick // params.sweep_every) % params.purge_sweeps) == 0
        keys = _keys_i32(st)
        drop = do & (st.nbr_id >= 0) & ((keys & 3) == RANK_DEAD)
        return st.replace(
            nbr_id=jnp.where(drop, -1, st.nbr_id),
            nbr_key=jnp.where(drop, UNKNOWN_KEY, keys).astype(_kdt(st)),
        )

    def _promote(st: PviewState):
        nbr_id, nbr_key = st.nbr_id, st.nbr_key
        for a in range(ka):
            keys = nbr_key.astype(jnp.int32)
            a_id = nbr_id[:, a]
            a_key = keys[:, a]
            bad = (a_id < 0) | ((a_key & 3) == RANK_DEAD)
            p_ids = nbr_id[:, ka:]
            p_keys = keys[:, ka:]
            ok_p = (p_ids >= 0) & ((p_keys & 3) != RANK_DEAD)
            score = jnp.where(ok_p, p_keys, NO_CANDIDATE)
            j = jnp.argmax(score, axis=1).astype(jnp.int32)
            has = score[rows, j] > NO_CANDIDATE
            do = bad & has
            src = ka + j
            sel_a = jnp.arange(k)[None, :] == a
            sel_p = jnp.arange(k)[None, :] == src[:, None]
            id_a = nbr_id[rows, src]
            key_a = nbr_key[rows, src]
            nbr_id = jnp.where(
                do[:, None] & sel_a, id_a[:, None],
                jnp.where(do[:, None] & sel_p, a_id[:, None], nbr_id),
            )
            nbr_key = jnp.where(
                do[:, None] & sel_a, key_a[:, None],
                jnp.where(do[:, None] & sel_p, nbr_key[:, a][:, None], nbr_key),
            )
        return st.replace(nbr_id=nbr_id, nbr_key=nbr_key)

    def _sweep(st: PviewState):
        has_suspects = (st.sus_since > NEVER).any()
        out = jax.lax.cond(has_suspects, _expire, _skip_exp, st)
        st2 = _promote(_purge(out[0]))
        return (st2,) + tuple(out[1:])

    def _skip(st: PviewState):
        return _skip_exp(st)

    on_tick = (state.tick % params.sweep_every) == 0
    return jax.lax.cond(on_tick, _sweep, _skip, state)


def _gossip_phase(state: PviewState, r, params: PviewParams,
                  adaptive: bool = False):
    """Infection-style dissemination — the sparse ``_gossip_phase`` with
    active-view peer sampling and the per-receiver A-pass record apply
    (deviation P5). Quiescent clusters skip the whole phase."""
    n = state.capacity
    m = params.mr_pool
    rows = jnp.arange(n)
    D = params.delay_slots
    F = params.fanout
    R = params.rumor_slots
    spread = params.spread_ticks
    from .bitplane import pack_bits as _pack_bits, unpack_bits as _unpack_bits

    work = state.rumor_active.any() | state.mr_active.any()
    if D:
        slot_now = state.tick % D
        work = (
            work
            | state.pending_inf[slot_now].any()
            | state.pending_minf[slot_now].any()
        )

    def _deliver(state: PviewState):
        mr_any = state.mr_active.any()
        if D:
            mr_any = mr_any | state.pending_minf[slot_now].any()
        young_u = (
            state.infected
            & state.rumor_active[None, :]
            & (state.tick - state.infected_at < spread)
        )
        # dissemination strategy seam (r13): pipelined budget window over
        # the USER-rumor payload (DZ-3; the default spec is a no-op)
        spec = params.dissem
        bmask = _dz.rumor_budget_mask(spec, young_u.shape[1], state.tick)
        if bmask is not None:
            young_u = young_u & bmask[None, :]

        def _mr_pre(st: PviewState):
            age = st.minf_age
            age = jnp.where(
                age > 0, jnp.minimum(age, jnp.uint8(254)) + jnp.uint8(1), age
            )
            young_m = (
                (age > 0)
                & st.mr_active[None, :]
                & (age.astype(jnp.int32) <= spread)
            )
            return age, _pack_bits(young_m)

        def _mr_pre_skip(st: PviewState):
            return st.minf_age, jnp.zeros((n, (m + 31) // 32), jnp.uint32)

        age, ym_p = jax.lax.cond(mr_any, _mr_pre, _mr_pre_skip, state)
        state = state.replace(minf_age=age)
        if spec.uniform_selection:
            _slots, peers, peer_valid = _sample_slots(
                state, rows, r.gossip_try, F, params.sample_tries,
                params.active_slots,
            )
        else:
            # structured topology / deterministic schedule (DZ-1): closed-
            # form circulant targets — global member ids, no table lookup
            # (rumor planes are table-independent), no [N, N] anywhere
            peers, peer_valid = _dz.structured_peers(
                spec, n, state.tick,
                _dz.try_stride_uniforms(r.gossip_try, params.sample_tries),
            )

        yu_p = _pack_bits(young_u)
        Wm, Wu = ym_p.shape[1], yu_p.shape[1]
        payload = jnp.concatenate(
            [ym_p, yu_p, state.infected_from.astype(jnp.uint32)], axis=1
        )
        if D:
            recv_u = state.pending_inf[slot_now]
            recv_src = state.pending_src[slot_now]
            recv_m_p = _pack_bits(state.pending_minf[slot_now])
            pend_u = state.pending_inf
            pend_src = state.pending_src
            pend_m = state.pending_minf
        else:
            recv_u = jnp.zeros_like(state.infected)
            recv_src = jnp.full_like(state.infected_from, -1)
            recv_m_p = jnp.zeros_like(ym_p)

        sender_has = young_u.any(axis=1) | (ym_p != 0).any(axis=1)
        p_all = peers.T  # [F, N]
        rows_b = jnp.broadcast_to(rows, (F, n))
        ok_all = (
            peer_valid.T
            & sender_has[None, :]
            & state.up[None, :]
            & state.up[p_all]
            & (r.gossip_edge.T < (1.0 - _loss_at(state, rows_b, p_all)))
        )
        sent = ok_all.sum()
        if D:
            qd = jnp.broadcast_to(state.delay_q, (F, n))
            d_all = jnp.zeros((F, n), jnp.int32)
            qpow = qd
            for _ in range(1, D):
                d_all = d_all + (r.gossip_delay.T < qpow)
                qpow = qpow * qd
            ok_now_all = ok_all & (d_all == 0)
        else:
            ok_now_all = ok_all
        _ragged = _ragged_ctx()
        if _ragged is not None:
            # r20 sharded delivery: shard-local election + ragged record
            # exchange instead of the global scatter-max + row gather.
            # Bit-identical to the global spelling under the default
            # (lossless) budget; the overflow sentinel is a metric below.
            from .ragged_a2a import ragged_delivery_combine
            u_or, src_max, m_or, rumor_sent, a2a_ovf = ragged_delivery_combine(
                payload, p_all, ok_now_all, state.rumor_origin, Wm, R,
                mesh=_ragged.mesh, axis=_ragged.axis, budget=_ragged.budget,
            )
            recv_u = recv_u | u_or
            recv_src = jnp.maximum(recv_src, src_max)
            recv_m_p = recv_m_p | m_or
        else:
            inv = (
                jnp.full((F, n), -1, jnp.int32)
                .at[jnp.arange(F)[:, None], p_all]
                .max(jnp.where(ok_now_all, rows[None, :], -1))
            )
            j_all = jnp.maximum(inv, 0)
            has_all = (inv >= 0)[:, :, None]
            pl_all = payload[j_all]
            yu_all = _unpack_bits(pl_all[:, :, Wm : Wm + Wu], R)
            from_all = pl_all[:, :, Wm + Wu :].astype(jnp.int32)
            deliver_u_all = (
                yu_all
                & has_all
                & (from_all != rows[None, :, None])
                & (state.rumor_origin[None, None, :] != rows[None, :, None])
            )
            recv_u = recv_u | deliver_u_all.any(axis=0)
            recv_src = jnp.maximum(
                recv_src,
                jnp.where(deliver_u_all, j_all[:, :, None], -1).max(axis=0),
            )
            recv_m_p = functools.reduce(
                jnp.bitwise_or,
                [jnp.where(has_all[s], pl_all[s, :, :Wm], jnp.uint32(0)) for s in range(F)],
                recv_m_p,
            )
            rumor_sent = deliver_u_all.sum()
        if spec.wants_pull:
            # push-pull reply (DZ-2): each sender whose undelayed contact
            # landed pulls the peer's payload back over the same round
            # trip — a per-slot row gather (one target per sender per
            # slot, so no inverse index), one hashed reverse-link draw
            for s in range(F):
                p_s = p_all[s]
                rev_u = fetch_uniform(state.tick, _dz.pull_salt(s), rows, p_s)
                rev_ok = ok_now_all[s] & (
                    rev_u < (1.0 - _loss_at(state, p_s, rows))
                )
                pl_rev = payload[p_s]
                yu_rev = _unpack_bits(pl_rev[:, Wm : Wm + Wu], R)
                from_rev = pl_rev[:, Wm + Wu :].astype(jnp.int32)
                reply_u = (
                    yu_rev
                    & rev_ok[:, None]
                    & (from_rev != rows[:, None])
                    & (state.rumor_origin[None, :] != rows[:, None])
                )
                recv_u = recv_u | reply_u
                recv_src = jnp.maximum(
                    recv_src, jnp.where(reply_u, p_s[:, None], -1)
                )
                recv_m_p = recv_m_p | jnp.where(
                    rev_ok[:, None], pl_rev[:, :Wm], jnp.uint32(0)
                )
                sent = sent + rev_ok.sum()
                rumor_sent = rumor_sent + reply_u.sum()
        if D:
            no_sender = jnp.full((n,), -1, jnp.int32)
            for s in range(F):
                ok_late = ok_all[s] & (d_all[s] > 0)
                inv_l = no_sender.at[p_all[s]].max(jnp.where(ok_late, rows, -1))
                jl = jnp.maximum(inv_l, 0)
                hasl = (inv_l >= 0)[:, None]
                pll = payload[jl]
                young_u_l = _unpack_bits(pll[:, Wm : Wm + Wu], R)
                lfrom = pll[:, Wm + Wu :].astype(jnp.int32)
                slot_d = (state.tick + d_all[s][jl]) % D
                late_u = (
                    young_u_l
                    & hasl
                    & (lfrom != rows[:, None])
                    & (state.rumor_origin[None, :] != rows[:, None])
                )
                pend_u = pend_u.at[slot_d, rows].max(late_u)
                pend_src = pend_src.at[slot_d, rows].max(
                    jnp.where(late_u, jl[:, None], -1)
                )
                pend_m = pend_m.at[slot_d, rows].max(
                    _unpack_bits(pll[:, :Wm], m)
                    & hasl
                    & (state.mr_origin[None, :] != rows[:, None])
                )

        newly_u = recv_u & ~state.infected & state.up[:, None] & state.rumor_active[None, :]
        state = state.replace(
            infected=state.infected | newly_u,
            infected_at=jnp.where(newly_u, state.tick, state.infected_at),
            infected_from=jnp.where(newly_u, recv_src, state.infected_from),
        )

        # membership-rumor infection + record application, capped at A per
        # receiver per tick (deviation P5): pass a picks each row's lowest
        # still-eligible pool slot, marks it delivered (minf_age = 1), and
        # routes the record through the shared accept-and-place spelling.
        def _mr_apply(state: PviewState):
            recv_m = _unpack_bits(recv_m_p, m) & (
                state.mr_origin[None, :] != rows[:, None]
            )
            remaining = (
                recv_m
                & (state.minf_age == 0)
                & state.up[:, None]
                & state.mr_active[None, :]
            )

            # A sequential apply passes as a lax.scan (the unrolled form
            # inlines A copies of the accept-and-place graph — compile
            # time, not semantics; pass order is identical)
            def apply_pass(carry, _):
                if adaptive:
                    st, minf, remaining, sus_acc, adcnt, delivered, accepts = carry
                else:
                    st, minf, remaining, sus_acc, delivered, accepts = carry
                col = jnp.argmax(remaining, axis=1).astype(jnp.int32)
                got = remaining[rows, col]
                subj = st.mr_subject[col]
                cand = st.mr_key[col]
                onehot = got[:, None] & (jnp.arange(m)[None, :] == col[:, None])
                minf = jnp.where(onehot, jnp.uint8(1), minf)
                remaining = remaining & ~onehot
                st, acc, sus_cand = _apply_records(
                    st, subj, cand, got, SALT_GOSSIP, params.active_slots
                )
                sus_acc = jnp.maximum(sus_acc, sus_cand)
                if adaptive:
                    # r14 confirmation counting: accepted SUSPECT records
                    # scatter-added per subject (AD-1)
                    acc_sus = acc & ((cand & 3) == RANK_SUSPECT)
                    adcnt = adcnt.at[jnp.where(acc_sus, subj, n)].add(
                        acc_sus.astype(jnp.int32), mode="drop"
                    )
                    return (
                        st, minf, remaining, sus_acc, adcnt,
                        delivered + got.sum(), accepts + acc.sum(),
                    ), None
                return (
                    st, minf, remaining, sus_acc,
                    delivered + got.sum(), accepts + acc.sum(),
                ), None

            if adaptive:
                carry0 = (
                    state, state.minf_age, remaining,
                    jnp.full((n,), NO_CANDIDATE, jnp.int32),
                    jnp.zeros((n,), jnp.int32),
                    jnp.int32(0), jnp.int32(0),
                )
                (
                    (state, minf, _rem, sus_acc, adcnt, delivered, accepts),
                    _,
                ) = jax.lax.scan(
                    apply_pass, carry0, None, length=params.apply_slots
                )
                state = _register_sus(state.replace(minf_age=minf), sus_acc)
                return state, delivered, accepts, adcnt, sus_acc
            carry0 = (
                state, state.minf_age, remaining,
                jnp.full((n,), NO_CANDIDATE, jnp.int32),
                jnp.int32(0), jnp.int32(0),
            )
            (state, minf, _rem, sus_acc, delivered, accepts), _ = jax.lax.scan(
                apply_pass, carry0, None, length=params.apply_slots
            )
            state = _register_sus(state.replace(minf_age=minf), sus_acc)
            return state, delivered, accepts

        if adaptive:
            def _mr_skip(st: PviewState):
                return (
                    st, jnp.int32(0), jnp.int32(0),
                    jnp.zeros((n,), jnp.int32),
                    jnp.full((n,), NO_CANDIDATE, jnp.int32),
                )

            state, n_mr_deliveries, n_mr_accepts, g_ad_cnt, g_ad_key = (
                jax.lax.cond(mr_any, _mr_apply, _mr_skip, state)
            )
        else:
            state, n_mr_deliveries, n_mr_accepts = jax.lax.cond(
                mr_any, _mr_apply, lambda st: (st, jnp.int32(0), jnp.int32(0)),
                state,
            )
        if D:
            state = state.replace(
                pending_inf=pend_u.at[slot_now].set(False),
                pending_src=pend_src.at[slot_now].set(-1),
                pending_minf=pend_m.at[slot_now].set(False),
            )
        mets = {
            "gossip_msgs": sent,
            "rumor_sends": rumor_sent,
            "rumor_deliveries": newly_u.sum(),
            "mr_deliveries": n_mr_deliveries,
            "mr_accepts": n_mr_accepts,
        }
        if _ragged is not None:
            mets["delivery_overflow"] = a2a_ovf
        if adaptive:
            mets["_ad_cnt"] = g_ad_cnt
            mets["_ad_key"] = g_ad_key
        return state, mets

    def _quiet(state: PviewState):
        mets = {
            "gossip_msgs": jnp.int32(0),
            "rumor_sends": jnp.int32(0),
            "rumor_deliveries": jnp.int32(0),
            "mr_deliveries": jnp.int32(0),
            "mr_accepts": jnp.int32(0),
        }
        if _ragged_ctx() is not None:
            mets["delivery_overflow"] = jnp.int32(0)
        if adaptive:
            mets["_ad_cnt"] = jnp.zeros((n,), jnp.int32)
            mets["_ad_key"] = jnp.full((n,), NO_CANDIDATE, jnp.int32)
        return state, mets

    return jax.lax.cond(work, _deliver, _quiet, state)


def _merge_entries(
    state: PviewState,
    src_rows,
    pre_id,
    pre_key_i32,
    pre_self,
    salt: int,
    params: PviewParams,
    adaptive: bool = False,
):
    """Merge each row's source's PRE-exchange table (k entries + the self
    record) into the row, sequentially by slot (deviation P4) — a
    lax.scan over the k + 1 record steps (an unrolled loop inlines k + 1
    copies of the accept-and-place graph and dominates the whole tick's
    XLA compile time). Returns (state, accept_count [N], top-P
    subjects/keys [N, P])."""
    n = state.capacity
    k = pre_id.shape[1]
    P = params.sync_announce
    has = src_rows >= 0
    src = jnp.maximum(src_rows, 0)
    # [k + 1, N] per-step record streams; step k is the self record
    subj_steps = jnp.concatenate([pre_id[src].T, src[None, :]], axis=0)
    cand_steps = jnp.concatenate(
        [pre_key_i32[src].T, pre_self[src][None, :]], axis=0
    )

    def body(carry, xs):
        if adaptive:
            st, acc_cnt, best_key, best_subj, sus_acc, adcnt = carry
        else:
            st, acc_cnt, best_key, best_subj, sus_acc = carry
        subj, cand = xs
        valid = has & (subj >= 0)
        st, acc, sus_cand = _apply_records(
            st, subj, cand, valid, salt, params.active_slots
        )
        sus_acc = jnp.maximum(sus_acc, sus_cand)
        if adaptive:
            # r14 confirmation counting (AD-1): accepted SUSPECT records
            acc_sus = acc & ((cand & 3) == RANK_SUSPECT)
            adcnt = adcnt.at[jnp.where(acc_sus, jnp.maximum(subj, 0), n)].add(
                acc_sus.astype(jnp.int32), mode="drop"
            )
        acc_cnt = acc_cnt + acc.astype(jnp.int32)
        # running top-P accepted keys (largest first; earlier step wins
        # ties — the re-gossip proposals, sparse deviation 3's cap)
        ins_k = jnp.where(acc, cand, NO_CANDIDATE)
        ins_s = subj
        for p in range(P):
            take = ins_k > best_key[:, p]
            old_k, old_s = best_key[:, p], best_subj[:, p]
            best_key = best_key.at[:, p].set(jnp.where(take, ins_k, old_k))
            best_subj = best_subj.at[:, p].set(jnp.where(take, ins_s, old_s))
            ins_k = jnp.where(take, old_k, ins_k)
            ins_s = jnp.where(take, old_s, ins_s)
        if adaptive:
            return (st, acc_cnt, best_key, best_subj, sus_acc, adcnt), None
        return (st, acc_cnt, best_key, best_subj, sus_acc), None

    carry0 = (
        state,
        jnp.zeros((n,), jnp.int32),
        jnp.full((n, P), NO_CANDIDATE, jnp.int32),
        jnp.zeros((n, P), jnp.int32),
        jnp.full((n,), NO_CANDIDATE, jnp.int32),
    )
    if adaptive:
        carry0 = carry0 + (jnp.zeros((n,), jnp.int32),)
        (state, acc_cnt, best_key, best_subj, sus_acc, adcnt), _ = jax.lax.scan(
            body, carry0, (subj_steps, cand_steps)
        )
        state = _register_sus(state, sus_acc)
        return state, acc_cnt, best_subj, best_key, adcnt, sus_acc
    (state, acc_cnt, best_key, best_subj, sus_acc), _ = jax.lax.scan(
        body, carry0, (subj_steps, cand_steps)
    )
    state = _register_sus(state, sus_acc)
    return state, acc_cnt, best_subj, best_key


def _sync_phase(state: PviewState, r, params: PviewParams, trace: bool = False,
                adaptive: bool = False, fused: bool = False):
    """Anti-entropy + shuffle: a due caller exchanges its table (plus self
    record) with one sampled active peer — both directions merge the
    other's PRE-exchange entries (deviation P4); multiple callers on one
    peer collapse to the highest slot (deviation P6). The passive-slot
    insertions this merge performs ARE the HyParView shuffle refresh.

    ``fused=True`` (r17) routes both direction merges through
    :func:`_merge_entries_compact` — the k + 1 accept-and-place steps run
    over the ≤ K participating rows (there are at most K ok callers, so
    at most K REQ receivers and K ACK receivers) instead of all N.
    Bit-identical; see the compact merge's docstring for the argument."""
    n = state.capacity
    rows = jnp.arange(n)
    P = params.sync_announce
    K = min(n, params.sync_slots or (n // params.sync_every + 32))
    due_p = ((state.tick + rows * params.sync_stagger) % params.sync_every) == 0
    due_f = state.force_sync & state.up
    due_p = due_p & state.up & ~due_f
    (cf,) = jnp.nonzero(due_f, size=K, fill_value=n)
    nf = (cf < n).sum()
    (cp,) = jnp.nonzero(due_p, size=K, fill_value=n)
    caller = cf.at[jnp.arange(K) + nf].set(cp, mode="drop")
    valid_c = caller < n
    caller = jnp.minimum(caller, n - 1)

    # SYNC peer draw over the UNION pool ``active slots ∪ seeds`` — the
    # reference's selectSyncAddress draws from seedMembers ∪ members
    # (MembershipProtocolImpl.java:461-472); with a full view the seed
    # share is vanishing, with a k-slot table it is S/(ka+S). This is the
    # partial-view re-bridging mechanism: after a partition's mutual kill
    # each side's table marks the other DEAD (unsampleable), and ONLY an
    # always-contactable seed re-connects the halves (the sparse engine's
    # extra_mask plays this role over its full-width column draw).
    ka = params.active_slots
    S = len(params.seed_rows)
    pool = ka + S
    u_try = r.sync_try[caller]  # [K, T]
    tries = jnp.minimum((u_try * np.float32(pool)).astype(jnp.int32), pool - 1)
    if S:
        seeds_arr = jnp.asarray(params.seed_rows, jnp.int32)
        seed_pick = seeds_arr[jnp.clip(tries - ka, 0, S - 1)]
    is_seed = tries >= ka
    slot_c = jnp.minimum(tries, ka - 1)
    sid = state.nbr_id[caller[:, None], slot_c]
    skey = state.nbr_key[caller[:, None], slot_c].astype(jnp.int32)
    tab_ok = ~is_seed & (sid >= 0) & ((skey & 3) != RANK_DEAD)
    if S:
        member_try = jnp.where(is_seed, seed_pick, jnp.maximum(sid, 0))
        ok_try = tab_ok | (is_seed & (seed_pick != caller[:, None]))
    else:
        member_try = jnp.maximum(sid, 0)
        ok_try = tab_ok
    peer = jnp.full(caller.shape, -1, jnp.int32)
    for t_i in range(params.sample_tries):
        peer = jnp.where(
            (peer < 0) & ok_try[:, t_i], member_try[:, t_i], peer
        )
    valid_pick = peer >= 0
    peer = jnp.maximum(peer, 0)
    if params.seed_rows:
        fb = seeds_arr[
            jnp.minimum((r.sync_fb[caller] * np.float32(S)).astype(jnp.int32), S - 1)
        ]
        use_fb = ~valid_pick & (fb != caller)
        peer = jnp.where(use_fb, fb, peer)
        valid_pick = valid_pick | use_fb
        # deterministic seed cadence (see PviewParams.seed_sync_every):
        # periodic callers only — forced (joiner) syncs keep the draw
        Q = params.seed_sync_every
        round_ = (state.tick + caller * params.sync_stagger) // params.sync_every
        sidx = (caller + round_ // Q) % S
        sp = seeds_arr[sidx]
        sp = jnp.where(sp == caller, seeds_arr[(sidx + 1) % S], sp)
        is_periodic = jnp.arange(K) >= nf
        use_seed = ((round_ % Q) == 0) & (sp != caller) & is_periodic & valid_c
        peer = jnp.where(use_seed, sp, peer)
        valid_pick = valid_pick | use_seed
    p_rt = _rt_timely(state, caller, peer, params.sync_timeout_ticks) \
        if params.delay_slots else _rt_at(state, caller, peer)
    ok = valid_c & valid_pick & state.up[peer] & (r.sync_edge[caller] < p_rt)

    # pre-exchange snapshot: both directions merge from these
    pre_id = state.nbr_id
    pre_key = _keys_i32(state)
    pre_self = state.self_key

    # REQ direction: winner caller per peer (deviation P6)
    inv_slot = (
        jnp.full((n,), -1, jnp.int32)
        .at[peer]
        .max(jnp.where(ok, jnp.arange(K, dtype=jnp.int32), -1))
    )
    req_src = jnp.where(inv_slot >= 0, caller[jnp.maximum(inv_slot, 0)], -1)
    merge = (
        functools.partial(_merge_entries_compact, K=K)
        if fused
        else _merge_entries
    )
    if adaptive:
        st, req_acc_n, req_subj, req_key, req_adc, req_adk = merge(
            state, req_src, pre_id, pre_key, pre_self, SALT_SYNC_REQ, params,
            adaptive=True,
        )
    else:
        st, req_acc_n, req_subj, req_key = merge(
            state, req_src, pre_id, pre_key, pre_self, SALT_SYNC_REQ, params
        )
    # ACK direction: distinct callers each merge their peer's pre-entries
    ack_src = (
        jnp.full((n,), -1, jnp.int32)
        .at[caller]
        .max(jnp.where(ok, peer, -1))
    )
    if adaptive:
        st, ack_acc_n, ack_subj, ack_key, ack_adc, ack_adk = merge(
            st, ack_src, pre_id, pre_key, pre_self, SALT_SYNC_ACK, params,
            adaptive=True,
        )
    else:
        st, ack_acc_n, ack_subj, ack_key = merge(
            st, ack_src, pre_id, pre_key, pre_self, SALT_SYNC_ACK, params
        )

    ok_full = jnp.zeros((n,), bool).at[caller].max(ok)
    st = st.replace(force_sync=st.force_sync & ~ok_full)

    # re-gossip proposals: top-P accepted per participant, REQ receivers
    # (peers) first then ACK receivers (callers) — [N·P] each direction.
    # The replication constraint on `origs` dodges an XLA:CPU SPMD
    # partitioner miscompile on 2-D scenarios×members meshes: this vector
    # is scenario-invariant (vmap leaves it unbatched), and the partitioner
    # rematerializes the members-sharded unbatched concat to replicated
    # via per-partition dynamic-update-slice + all-reduce over ALL devices
    # — each chunk is contributed once per scenario replica, so every
    # origin came back scaled by the scenario-axis size (mr_origin = 2x
    # the proposer row on a scenarios=2 mesh). Pinning it replicated makes
    # every device compute the tiny [N·P] iota locally instead; no-op off
    # mesh.
    _ragged = _ragged_ctx()
    if _ragged is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        rows_rep = jax.lax.with_sharding_constraint(
            rows, NamedSharding(_ragged.mesh, PartitionSpec())
        )
    else:
        rows_rep = rows

    def _props(subj2, key2, part_mask):
        subs = jnp.concatenate([subj2[:, p] for p in range(P)])
        keys_ = jnp.concatenate([key2[:, p] for p in range(P)])
        origs = jnp.concatenate([rows_rep] * P)
        if _ragged is not None:
            origs = jax.lax.with_sharding_constraint(
                origs, NamedSharding(_ragged.mesh, PartitionSpec())
            )
        vals = jnp.concatenate(
            [part_mask & (key2[:, p] > NO_CANDIDATE) for p in range(P)]
        )
        return subs, keys_, origs, vals

    props_p = _props(req_subj, req_key, req_src >= 0)
    props_c = _props(ack_subj, ack_key, ack_src >= 0)
    proposals = list(
        jnp.concatenate([a, b]) for a, b in zip(props_p, props_c)
    )
    if _ragged is not None:
        # same partitioner hazard as `origs` above, one concat later: the
        # merged [2·N·P] origin vector is still scenario-invariant
        proposals[2] = jax.lax.with_sharding_constraint(
            proposals[2], NamedSharding(_ragged.mesh, PartitionSpec())
        )
    proposals = tuple(proposals)
    metrics = {"sync_roundtrips": ok.sum()}
    if adaptive:
        metrics["_ad_cnt"] = req_adc + ack_adc
        metrics["_ad_key"] = jnp.maximum(req_adk, ack_adk)
    if trace:
        winner = ok & (inv_slot[peer] == jnp.arange(K))
        metrics["trace_sync"] = {
            "caller": caller.astype(jnp.int32),
            "valid": valid_c,
            "peer": peer.astype(jnp.int32),
            "ok": ok,
            "req_acc": jnp.where(winner, req_acc_n[peer], 0).astype(jnp.int32),
            "ack_acc": jnp.where(ok, ack_acc_n[caller], 0).astype(jnp.int32),
        }
    return st, proposals, metrics


def _refute_phase(state: PviewState, params: PviewParams):
    """Self-record refutation — row-local over ``self_key``; bumps route
    through :func:`.lattice.bump_inc` (narrow saturation)."""
    n = state.capacity
    rows = jnp.arange(n)
    kdt = _kdt(state)
    diag = state.self_key
    rank = diag & 3
    need = state.up & (
        (rank == RANK_SUSPECT)
        | (rank == RANK_DEAD)
        | (state.leaving & (rank != RANK_LEAVING))
    )
    V = min(n, params.refute_slots or max(64, n // 16))
    eff = need & (jnp.cumsum(need.astype(jnp.int32)) - 1 < V)
    announce_rank = jnp.where(state.leaving, RANK_LEAVING, RANK_ALIVE)
    bumped = bump_inc(diag.astype(kdt), announce_rank.astype(kdt)).astype(jnp.int32)
    new_diag = jnp.where(eff, bumped, diag)
    st = state.replace(self_key=new_diag)
    return st, (rows, new_diag, rows, eff)


def _rumor_sweeps(state: PviewState, params: PviewParams) -> PviewState:
    """Slot reclamation — sparse semantics with the static windows (P2)."""
    sweep = params.sweep_ticks
    spread = params.spread_ticks

    keep_u = state.tick - state.rumor_created <= sweep
    forwarding_u = (
        state.infected
        & state.up[:, None]
        & (state.tick - state.infected_at < spread)
    ).any(axis=0)
    keep_u = keep_u | forwarding_u
    if params.delay_slots:
        keep_u = keep_u | state.pending_inf.any(axis=(0, 1))
    state = state.replace(rumor_active=state.rumor_active & keep_u)

    def _sweep_m(state: PviewState):
        age = state.minf_age.astype(jnp.int32)
        forwarding_m = (
            (age > 0) & (age <= spread) & state.up[:, None]
        ).any(axis=0)
        keep_m = (state.tick - state.mr_created <= sweep) | forwarding_m
        pending_m = (
            state.pending_minf.any(axis=(0, 1))
            if params.delay_slots
            else jnp.zeros_like(keep_m)
        )
        keep_m = keep_m | pending_m
        if params.early_free:
            covered = (
                (state.minf_age > 0)
                | ~state.up[:, None]
                | (state.joined_at[:, None] > state.mr_created[None, :])
            ).all(axis=0)
            keep_m = keep_m & ~(covered & ~pending_m)
        keep_m = keep_m & state.mr_active
        freed = state.mr_active & ~keep_m
        state = state.replace(
            mr_active=keep_m,
            mr_subject=jnp.where(freed, -1, state.mr_subject),
            minf_age=jnp.where(freed[None, :], jnp.uint8(0), state.minf_age),
        )
        if params.delay_slots:
            state = state.replace(
                pending_minf=state.pending_minf & keep_m[None, None, :]
            )
        return state

    return jax.lax.cond(state.mr_active.any(), _sweep_m, lambda st: st, state)


# ---------------------------------------------------------------------------
# fused tick path (r17) — profile-guided phase fusion
#
# The r10-style phase profile at N=65536 (trace/profile.py, recorded in
# FUSED_BENCH_r17.json) puts ~75% of the pview tick in the gossip phase,
# and inside it ~95% in the A-pass record apply: each pass argmaxes a
# [N, M] bool `remaining` plane and clears the winner with a [N, M]
# onehot — ~8 full-plane passes per tick over a plane whose information
# content is the PACKED [N, M/32] word plane the delivery already
# produced (`recv_m_p`). The fused path never unpacks it: each apply
# pass extracts the lowest set bit per row with first-nonzero-word +
# count-trailing-zeros + clear-lowest-bit (32x less traffic per pass,
# identical column order, so the trajectory is bit-identical). The
# second-tier costs fall to the same treatment: the SYNC merge scans run
# their k+1 accept-and-place steps over the K≈N/sync_every participating
# rows instead of all N (non-participants are provable no-ops), the FD
# verdict hands its widened key plane to the maintenance sweep, and the
# gossip phase hands the sweep tail a packed forwarding plane it gets for
# free from the aging pass.
# ---------------------------------------------------------------------------


def _mr_apply_packed(state: PviewState, recv_m_p, zero_p, params: PviewParams,
                     adaptive: bool):
    """The fused twin of ``_gossip_phase._mr_apply``: A sequential apply
    passes over the PACKED eligibility words. Per pass and per row, the
    lowest still-eligible pool column is the lowest set bit of the first
    non-zero word — extracted with ``v & -v`` + popcount and cleared with
    ``v & (v - 1)`` — exactly the column the unfused argmax-and-clear
    picks, so the record stream (and therefore the state trajectory) is
    bit-identical while each pass touches [N, M/32] u32 words instead of
    a [N, M] bool plane plus a [N, M] onehot write.

    ``zero_p`` is ``pack_bits(minf_age == 0)`` of the POST-aging plane
    (produced inside the fused ``_mr_pre`` while the plane is hot).
    Returns the unfused branch's outputs plus the packed plane of bits
    extracted this tick (for the sweep-tail hand-off)."""
    from .bitplane import pack_bits as _pack_bits, popcount as _popcount

    n = state.capacity
    m = params.mr_pool
    W = recv_m_p.shape[1]
    rows = jnp.arange(n)
    cols = jnp.arange(m)
    ka = params.active_slots

    # origin-row exclusion, packed: column c's bit lands in row
    # mr_origin[c] (the legacy `mr_origin[None, :] != rows[:, None]`
    # mask, built by one M-sized scatter instead of an [N, M] compare)
    vo = (state.mr_origin >= 0) & (state.mr_origin < n)
    excl_p = (
        jnp.zeros((n + 1, W), jnp.uint32)
        .at[jnp.where(vo, state.mr_origin, n), cols // 32]
        .add(jnp.uint32(1) << (cols % 32).astype(jnp.uint32), mode="drop")[:n]
    )
    active_p = _pack_bits(state.mr_active[None, :])[0]
    rem0 = recv_m_p & zero_p & ~excl_p & active_p[None, :]
    rem0 = jnp.where(state.up[:, None], rem0, jnp.uint32(0))

    def apply_pass(carry, _):
        if adaptive:
            st, minf, rem_p, sus_acc, adcnt, delivered, accepts = carry
        else:
            st, minf, rem_p, sus_acc, delivered, accepts = carry
        nz = rem_p != 0
        got = nz.any(axis=1)
        w = jnp.argmax(nz, axis=1).astype(jnp.int32)
        v = rem_p[rows, w]
        lsb = v & (jnp.uint32(0) - v)
        b = _popcount(lsb - jnp.uint32(1)).astype(jnp.int32)
        col = jnp.where(got, w * 32 + b, 0)
        rem_p = rem_p.at[rows, w].set(v & (v - jnp.uint32(1)))
        subj = st.mr_subject[col]
        cand = st.mr_key[col]
        minf = minf.at[rows, col].max(
            jnp.where(got, jnp.uint8(1), jnp.uint8(0))
        )
        st, acc, sus_cand = _apply_records(
            st, subj, cand, got, SALT_GOSSIP, ka
        )
        sus_acc = jnp.maximum(sus_acc, sus_cand)
        if adaptive:
            acc_sus = acc & ((cand & 3) == RANK_SUSPECT)
            adcnt = adcnt.at[jnp.where(acc_sus, subj, n)].add(
                acc_sus.astype(jnp.int32), mode="drop"
            )
            return (
                st, minf, rem_p, sus_acc, adcnt,
                delivered + got.sum(), accepts + acc.sum(),
            ), None
        return (
            st, minf, rem_p, sus_acc,
            delivered + got.sum(), accepts + acc.sum(),
        ), None

    if adaptive:
        carry0 = (
            state, state.minf_age, rem0,
            jnp.full((n,), NO_CANDIDATE, jnp.int32),
            jnp.zeros((n,), jnp.int32),
            jnp.int32(0), jnp.int32(0),
        )
        (state, minf, rem_f, sus_acc, adcnt, delivered, accepts), _ = (
            jax.lax.scan(apply_pass, carry0, None, length=params.apply_slots)
        )
        state = _register_sus(state.replace(minf_age=minf), sus_acc)
        return state, delivered, accepts, rem0 ^ rem_f, adcnt, sus_acc
    carry0 = (
        state, state.minf_age, rem0,
        jnp.full((n,), NO_CANDIDATE, jnp.int32),
        jnp.int32(0), jnp.int32(0),
    )
    (state, minf, rem_f, sus_acc, delivered, accepts), _ = jax.lax.scan(
        apply_pass, carry0, None, length=params.apply_slots
    )
    state = _register_sus(state.replace(minf_age=minf), sus_acc)
    return state, delivered, accepts, rem0 ^ rem_f


def _gossip_phase_fused(state: PviewState, r, params: PviewParams,
                        adaptive: bool = False):
    """The fused spelling of :func:`_gossip_phase` — identical peer
    selection, edge draws, and delivery semantics (the bit-identity tests
    pin the whole trajectory), restructured so adjacent stages share
    intermediates:

    * ``_mr_pre`` also packs the post-aging ``minf_age == 0`` and
      forwarding-window planes while the aged plane is hot;
    * the per-fanout-slot inverse-sender delivery combine goes through
      ``params.delivery_kernel`` ("xla" = the legacy primitive sequence,
      "pallas" = :mod:`.pallas_delivery`, interpreted on CPU);
    * the A-pass record apply runs on the packed words
      (:func:`_mr_apply_packed`) instead of unpacking a [N, M] plane;
    * returns the packed forwarding plane for the sweep tail, so the
      rumor sweep never re-reads the [N, M] age plane.

    Returns ``(state, metrics, fwd_post_p)``."""
    n = state.capacity
    m = params.mr_pool
    rows = jnp.arange(n)
    D = params.delay_slots
    F = params.fanout
    R = params.rumor_slots
    spread = params.spread_ticks
    W = (m + 31) // 32
    from .bitplane import pack_bits as _pack_bits, unpack_bits as _unpack_bits

    work = state.rumor_active.any() | state.mr_active.any()
    if D:
        slot_now = state.tick % D
        work = (
            work
            | state.pending_inf[slot_now].any()
            | state.pending_minf[slot_now].any()
        )

    def _deliver(state: PviewState):
        mr_any = state.mr_active.any()
        if D:
            mr_any = mr_any | state.pending_minf[slot_now].any()
        young_u = (
            state.infected
            & state.rumor_active[None, :]
            & (state.tick - state.infected_at < spread)
        )
        spec = params.dissem
        bmask = _dz.rumor_budget_mask(spec, young_u.shape[1], state.tick)
        if bmask is not None:
            young_u = young_u & bmask[None, :]

        def _mr_pre(st: PviewState):
            age = st.minf_age
            age = jnp.where(
                age > 0, jnp.minimum(age, jnp.uint8(254)) + jnp.uint8(1), age
            )
            young_m = (
                (age > 0)
                & st.mr_active[None, :]
                & (age.astype(jnp.int32) <= spread)
            )
            fwd = (age > 0) & (age.astype(jnp.int32) <= spread)
            return age, _pack_bits(young_m), _pack_bits(age == 0), _pack_bits(fwd)

        def _mr_pre_skip(st: PviewState):
            z = jnp.zeros((n, W), jnp.uint32)
            return st.minf_age, z, z, z

        age, ym_p, zero_p, fwd_p = jax.lax.cond(
            mr_any, _mr_pre, _mr_pre_skip, state
        )
        state = state.replace(minf_age=age)
        if spec.uniform_selection:
            _slots, peers, peer_valid = _sample_slots(
                state, rows, r.gossip_try, F, params.sample_tries,
                params.active_slots,
            )
        else:
            peers, peer_valid = _dz.structured_peers(
                spec, n, state.tick,
                _dz.try_stride_uniforms(r.gossip_try, params.sample_tries),
            )

        yu_p = _pack_bits(young_u)
        Wm, Wu = ym_p.shape[1], yu_p.shape[1]
        payload = jnp.concatenate(
            [ym_p, yu_p, state.infected_from.astype(jnp.uint32)], axis=1
        )
        if D:
            recv_u = state.pending_inf[slot_now]
            recv_src = state.pending_src[slot_now]
            recv_m_p = _pack_bits(state.pending_minf[slot_now])
            pend_u = state.pending_inf
            pend_src = state.pending_src
            pend_m = state.pending_minf
        else:
            recv_u = jnp.zeros_like(state.infected)
            recv_src = jnp.full_like(state.infected_from, -1)
            recv_m_p = jnp.zeros_like(ym_p)

        sender_has = young_u.any(axis=1) | (ym_p != 0).any(axis=1)
        p_all = peers.T  # [F, N]
        rows_b = jnp.broadcast_to(rows, (F, n))
        ok_all = (
            peer_valid.T
            & sender_has[None, :]
            & state.up[None, :]
            & state.up[p_all]
            & (r.gossip_edge.T < (1.0 - _loss_at(state, rows_b, p_all)))
        )
        sent = ok_all.sum()
        if D:
            qd = jnp.broadcast_to(state.delay_q, (F, n))
            d_all = jnp.zeros((F, n), jnp.int32)
            qpow = qd
            for _ in range(1, D):
                d_all = d_all + (r.gossip_delay.T < qpow)
                qpow = qpow * qd
            ok_now_all = ok_all & (d_all == 0)
        else:
            ok_now_all = ok_all
        _ragged = _ragged_ctx()
        if _ragged is not None:
            # r20 sharded delivery (the pallas × mesh combination is
            # refused at builder time, so only the xla seam lands here)
            from .ragged_a2a import ragged_delivery_combine
            u_or, src_max, m_or, cnt, a2a_ovf = ragged_delivery_combine(
                payload, p_all, ok_now_all, state.rumor_origin, Wm, R,
                mesh=_ragged.mesh, axis=_ragged.axis, budget=_ragged.budget,
            )
        else:
            inv = (
                jnp.full((F, n), -1, jnp.int32)
                .at[jnp.arange(F)[:, None], p_all]
                .max(jnp.where(ok_now_all, rows[None, :], -1))
            )
            from .pallas_delivery import delivery_combine, delivery_combine_xla

            if params.delivery_kernel == "pallas":
                u_or, src_max, m_or, cnt = delivery_combine(
                    payload, inv, state.rumor_origin, Wm, R
                )
            else:
                u_or, src_max, m_or, cnt = delivery_combine_xla(
                    payload, inv, state.rumor_origin, Wm, R
                )
        recv_u = recv_u | u_or
        recv_src = jnp.maximum(recv_src, src_max)
        recv_m_p = recv_m_p | m_or
        rumor_sent = cnt
        if spec.wants_pull:
            for s in range(F):
                p_s = p_all[s]
                rev_u = fetch_uniform(state.tick, _dz.pull_salt(s), rows, p_s)
                rev_ok = ok_now_all[s] & (
                    rev_u < (1.0 - _loss_at(state, p_s, rows))
                )
                pl_rev = payload[p_s]
                yu_rev = _unpack_bits(pl_rev[:, Wm : Wm + Wu], R)
                from_rev = pl_rev[:, Wm + Wu :].astype(jnp.int32)
                reply_u = (
                    yu_rev
                    & rev_ok[:, None]
                    & (from_rev != rows[:, None])
                    & (state.rumor_origin[None, :] != rows[:, None])
                )
                recv_u = recv_u | reply_u
                recv_src = jnp.maximum(
                    recv_src, jnp.where(reply_u, p_s[:, None], -1)
                )
                recv_m_p = recv_m_p | jnp.where(
                    rev_ok[:, None], pl_rev[:, :Wm], jnp.uint32(0)
                )
                sent = sent + rev_ok.sum()
                rumor_sent = rumor_sent + reply_u.sum()
        if D:
            no_sender = jnp.full((n,), -1, jnp.int32)
            for s in range(F):
                ok_late = ok_all[s] & (d_all[s] > 0)
                inv_l = no_sender.at[p_all[s]].max(jnp.where(ok_late, rows, -1))
                jl = jnp.maximum(inv_l, 0)
                hasl = (inv_l >= 0)[:, None]
                pll = payload[jl]
                young_u_l = _unpack_bits(pll[:, Wm : Wm + Wu], R)
                lfrom = pll[:, Wm + Wu :].astype(jnp.int32)
                slot_d = (state.tick + d_all[s][jl]) % D
                late_u = (
                    young_u_l
                    & hasl
                    & (lfrom != rows[:, None])
                    & (state.rumor_origin[None, :] != rows[:, None])
                )
                pend_u = pend_u.at[slot_d, rows].max(late_u)
                pend_src = pend_src.at[slot_d, rows].max(
                    jnp.where(late_u, jl[:, None], -1)
                )
                pend_m = pend_m.at[slot_d, rows].max(
                    _unpack_bits(pll[:, :Wm], m)
                    & hasl
                    & (state.mr_origin[None, :] != rows[:, None])
                )

        newly_u = recv_u & ~state.infected & state.up[:, None] & state.rumor_active[None, :]
        state = state.replace(
            infected=state.infected | newly_u,
            infected_at=jnp.where(newly_u, state.tick, state.infected_at),
            infected_from=jnp.where(newly_u, recv_src, state.infected_from),
        )

        def _mr_apply(st: PviewState):
            out = _mr_apply_packed(st, recv_m_p, zero_p, params, adaptive)
            if adaptive:
                st, delivered, accepts, extracted, adcnt, sus_acc = out
                return st, delivered, accepts, fwd_p | extracted, adcnt, sus_acc
            st, delivered, accepts, extracted = out
            return st, delivered, accepts, fwd_p | extracted

        if adaptive:
            def _mr_skip(st: PviewState):
                return (
                    st, jnp.int32(0), jnp.int32(0), fwd_p,
                    jnp.zeros((n,), jnp.int32),
                    jnp.full((n,), NO_CANDIDATE, jnp.int32),
                )

            state, n_mr_deliveries, n_mr_accepts, fwd_post_p, g_ad_cnt, g_ad_key = (
                jax.lax.cond(mr_any, _mr_apply, _mr_skip, state)
            )
        else:
            state, n_mr_deliveries, n_mr_accepts, fwd_post_p = jax.lax.cond(
                mr_any, _mr_apply,
                lambda st: (st, jnp.int32(0), jnp.int32(0), fwd_p),
                state,
            )
        if D:
            state = state.replace(
                pending_inf=pend_u.at[slot_now].set(False),
                pending_src=pend_src.at[slot_now].set(-1),
                pending_minf=pend_m.at[slot_now].set(False),
            )
        mets = {
            "gossip_msgs": sent,
            "rumor_sends": rumor_sent,
            "rumor_deliveries": newly_u.sum(),
            "mr_deliveries": n_mr_deliveries,
            "mr_accepts": n_mr_accepts,
        }
        if _ragged is not None:
            mets["delivery_overflow"] = a2a_ovf
        if adaptive:
            mets["_ad_cnt"] = g_ad_cnt
            mets["_ad_key"] = g_ad_key
        return state, mets, fwd_post_p

    def _quiet(state: PviewState):
        mets = {
            "gossip_msgs": jnp.int32(0),
            "rumor_sends": jnp.int32(0),
            "rumor_deliveries": jnp.int32(0),
            "mr_deliveries": jnp.int32(0),
            "mr_accepts": jnp.int32(0),
        }
        if _ragged_ctx() is not None:
            mets["delivery_overflow"] = jnp.int32(0)
        if adaptive:
            mets["_ad_cnt"] = jnp.zeros((n,), jnp.int32)
            mets["_ad_key"] = jnp.full((n,), NO_CANDIDATE, jnp.int32)
        return state, mets, jnp.zeros((n, W), jnp.uint32)

    return jax.lax.cond(work, _deliver, _quiet, state)


def _merge_entries_compact(
    state: PviewState,
    src_rows,
    pre_id,
    pre_key_i32,
    pre_self,
    salt: int,
    params: PviewParams,
    K: int,
    adaptive: bool = False,
):
    """The fused twin of :func:`_merge_entries`: the k + 1 accept-and-place
    steps run over the COMPACTED [K] participating rows (``src_rows >= 0``)
    instead of all N. Non-participating rows are provable no-ops in the
    unfused scan (``valid=False`` never writes state and contributes
    NO_CANDIDATE everywhere), and at most K rows can participate by
    construction (both SYNC direction maps are built from the K-compacted
    caller list), so gathering the [K, k] sub-tables, scanning, and
    scattering back is bit-identical at ~N/K times less work per step."""
    n = state.capacity
    kdt = _kdt(state)
    P = params.sync_announce
    ka = params.active_slots
    k = pre_id.shape[1]
    (pidx,) = jnp.nonzero(src_rows >= 0, size=K, fill_value=n)
    ridx = jnp.minimum(pidx, n - 1)
    has = (pidx < n) & (src_rows[ridx] >= 0)
    src = jnp.maximum(src_rows[ridx], 0)
    subj_steps = jnp.concatenate([pre_id[src].T, src[None, :]], axis=0)
    cand_steps = jnp.concatenate(
        [pre_key_i32[src].T, pre_self[src][None, :]], axis=0
    )
    sub_id0 = state.nbr_id[ridx]
    sub_key0 = _keys_i32(state)[ridx]
    sub_self0 = state.self_key[ridx]
    karange = jnp.arange(K)
    krange = jnp.arange(k)

    def body(carry, xs):
        if adaptive:
            sub_id, sub_key, sub_self, acc_cnt, best_key, best_subj, sus_acc, adcnt = carry
        else:
            sub_id, sub_key, sub_self, acc_cnt, best_key, best_subj, sus_acc = carry
        subj, cand = xs
        valid = has & (subj >= 0)
        subj_c = jnp.clip(subj, 0, n - 1)
        to_self = valid & (subj == ridx)
        to_tab = valid & ~to_self & (subj >= 0)
        match = sub_id == subj[:, None]
        present = (match & to_tab[:, None]).any(axis=1)
        slot_p = jnp.argmax(match, axis=1).astype(jnp.int32)
        own_tab = jnp.where(present, sub_key[karange, slot_p], UNKNOWN_KEY)
        own = jnp.where(to_self, sub_self, own_tab)
        needs_fetch = (cand & 3) == RANK_ALIVE
        u = fetch_uniform(state.tick, salt, ridx, subj_c)
        fetch_ok = ~needs_fetch | (
            state.up[subj_c] & (u < _rt_at(state, ridx, subj_c))
        )
        accept = (
            (to_self | to_tab)
            & (cand > own)
            & ((own >= 0) | ((cand & 3) <= RANK_LEAVING))
            & fetch_ok
        )
        sub_self = jnp.where(accept & to_self, cand, sub_self)
        acc_t = accept & to_tab
        empty = sub_id < 0
        has_empty = empty.any(axis=1)
        slot_e = jnp.argmax(empty, axis=1).astype(jnp.int32)
        p_keys = sub_key[:, ka:]
        slot_v = (ka + jnp.argmin(p_keys, axis=1)).astype(jnp.int32)
        slot_w = jnp.where(present, slot_p, jnp.where(has_empty, slot_e, slot_v))
        onehot = acc_t[:, None] & (krange[None, :] == slot_w[:, None])
        # round-trip through the storage dtype — the unfused scan narrows
        # the accepted key into nbr_key and re-widens it next step
        cand_rt = cand.astype(kdt).astype(jnp.int32)
        sub_id = jnp.where(onehot, subj[:, None], sub_id)
        sub_key = jnp.where(onehot, cand_rt[:, None], sub_key)
        sus_in = jnp.where(
            accept & ((cand & 3) == RANK_SUSPECT), cand, NO_CANDIDATE
        )
        sus_acc = sus_acc.at[jnp.where(accept, subj_c, n)].max(
            sus_in, mode="drop"
        )
        if adaptive:
            acc_sus = accept & ((cand & 3) == RANK_SUSPECT)
            adcnt = adcnt.at[jnp.where(acc_sus, jnp.maximum(subj, 0), n)].add(
                acc_sus.astype(jnp.int32), mode="drop"
            )
        acc_cnt = acc_cnt + accept.astype(jnp.int32)
        ins_k = jnp.where(accept, cand, NO_CANDIDATE)
        ins_s = subj
        for p in range(P):
            take = ins_k > best_key[:, p]
            old_k, old_s = best_key[:, p], best_subj[:, p]
            best_key = best_key.at[:, p].set(jnp.where(take, ins_k, old_k))
            best_subj = best_subj.at[:, p].set(jnp.where(take, ins_s, old_s))
            ins_k = jnp.where(take, old_k, ins_k)
            ins_s = jnp.where(take, old_s, ins_s)
        if adaptive:
            return (sub_id, sub_key, sub_self, acc_cnt, best_key, best_subj,
                    sus_acc, adcnt), None
        return (sub_id, sub_key, sub_self, acc_cnt, best_key, best_subj,
                sus_acc), None

    carry0 = (
        sub_id0,
        sub_key0,
        sub_self0,
        jnp.zeros((K,), jnp.int32),
        jnp.full((K, P), NO_CANDIDATE, jnp.int32),
        jnp.zeros((K, P), jnp.int32),
        jnp.full((n + 1,), NO_CANDIDATE, jnp.int32),
    )
    if adaptive:
        carry0 = carry0 + (jnp.zeros((n,), jnp.int32),)
        (sub_id, sub_key, sub_self, acc_cnt, best_key, best_subj, sus_acc,
         adcnt), _ = jax.lax.scan(body, carry0, (subj_steps, cand_steps))
    else:
        (sub_id, sub_key, sub_self, acc_cnt, best_key, best_subj,
         sus_acc), _ = jax.lax.scan(body, carry0, (subj_steps, cand_steps))
    state = state.replace(
        nbr_id=state.nbr_id.at[pidx].set(sub_id, mode="drop"),
        nbr_key=state.nbr_key.at[pidx].set(
            sub_key.astype(kdt), mode="drop"
        ),
        self_key=state.self_key.at[pidx].set(sub_self, mode="drop"),
    )
    state = _register_sus(state, sus_acc[:n])
    acc_full = jnp.zeros((n,), jnp.int32).at[pidx].set(acc_cnt, mode="drop")
    subj_full = jnp.zeros((n, P), jnp.int32).at[pidx].set(
        best_subj, mode="drop"
    )
    key_full = jnp.full((n, P), NO_CANDIDATE, jnp.int32).at[pidx].set(
        best_key, mode="drop"
    )
    if adaptive:
        return state, acc_full, subj_full, key_full, adcnt, sus_acc[:n]
    return state, acc_full, subj_full, key_full


def _rumor_sweeps_fused(state: PviewState, params: PviewParams,
                        fwd_post_p) -> PviewState:
    """The fused spelling of :func:`_rumor_sweeps`: the membership-rumor
    forwarding reduction reads the PACKED forwarding plane the gossip
    phase handed over (produced for free from its aging pass + the bits
    its apply passes extracted) instead of re-reading the [N, M] u8 age
    plane from the scan carry — same booleans, 1/8 the plane traffic."""
    sweep = params.sweep_ticks
    m = params.mr_pool
    from .bitplane import unpack_bits as _unpack_bits

    keep_u = state.tick - state.rumor_created <= sweep
    forwarding_u = (
        state.infected
        & state.up[:, None]
        & (state.tick - state.infected_at < params.spread_ticks)
    ).any(axis=0)
    keep_u = keep_u | forwarding_u
    if params.delay_slots:
        keep_u = keep_u | state.pending_inf.any(axis=(0, 1))
    state = state.replace(rumor_active=state.rumor_active & keep_u)

    def _sweep_m(state: PviewState):
        if _ragged_ctx() is not None:
            # sharded spelling: the SPMD partitioner cannot lower a custom
            # u32 bitwise-or reduction across the member axis (XLA:CPU
            # rejects the cross-shard reduce computation), but unpacking
            # commutes with OR — the pred any() reduce is bit-identical
            # and partitions as a standard reduce-or
            forwarding_m = (
                _unpack_bits(fwd_post_p, m) & state.up[:, None]
            ).any(axis=0)
        else:
            fwd_up = jnp.where(state.up[:, None], fwd_post_p, jnp.uint32(0))
            fwd_words = jax.lax.reduce(
                fwd_up, jnp.uint32(0), jax.lax.bitwise_or, (0,)
            )
            forwarding_m = _unpack_bits(fwd_words[None, :], m)[0]
        keep_m = (state.tick - state.mr_created <= sweep) | forwarding_m
        pending_m = (
            state.pending_minf.any(axis=(0, 1))
            if params.delay_slots
            else jnp.zeros_like(keep_m)
        )
        keep_m = keep_m | pending_m
        if params.early_free:
            covered = (
                (state.minf_age > 0)
                | ~state.up[:, None]
                | (state.joined_at[:, None] > state.mr_created[None, :])
            ).all(axis=0)
            keep_m = keep_m & ~(covered & ~pending_m)
        keep_m = keep_m & state.mr_active
        freed = state.mr_active & ~keep_m
        state = state.replace(
            mr_active=keep_m,
            mr_subject=jnp.where(freed, -1, state.mr_subject),
            minf_age=jnp.where(freed[None, :], jnp.uint8(0), state.minf_age),
        )
        if params.delay_slots:
            state = state.replace(
                pending_minf=state.pending_minf & keep_m[None, None, :]
            )
        return state

    return jax.lax.cond(state.mr_active.any(), _sweep_m, lambda st: st, state)


# ---------------------------------------------------------------------------
# tick
# ---------------------------------------------------------------------------


def pview_tick(state: PviewState, key: jax.Array, params: PviewParams,
               trace=None, ad=None):
    """One gossip period for all N members, partial-view mode. Pure;
    jit me. Same two-subkey draw split and trace contract as the sparse
    tick (``trace`` arms the r10 capture; trajectory bit-identical).

    ``ad`` (an :class:`..adaptive.AdaptiveState`, r14) arms the adaptive
    failure-detection plane; the return becomes ``(state, ad', metrics)``.
    ``ad=None`` traces the byte-identical legacy program. The adaptive
    plane is three [N] i32 vectors — ``forbid_wide_values`` holds."""
    armed = ad is not None
    if armed:
        if trace is not None:
            raise ValueError(
                "trace-armed adaptive windows are not supported"
            )
        if params.adaptive.is_default:
            raise ValueError(
                "adaptive tick needs an enabled AdaptiveSpec on params"
            )
    state = state.replace(tick=state.tick + 1)
    fd_key, round_key = split_tick_key(key)
    r = draw_sparse_round(round_key, state.capacity, params.fanout, params.sample_tries)

    n = state.capacity
    rows = jnp.arange(n)
    no_props = (
        jnp.zeros((n,), jnp.int32),
        jnp.zeros((n,), jnp.int32),
        rows,
        jnp.zeros((n,), bool),
    )

    def _fd_on(st: PviewState):
        fd_r = draw_sparse_fd(fd_key, n, params.ping_req_k, params.sample_tries)
        return _fd_phase(st, fd_r, params, trace=trace is not None, ad=ad)

    def _fd_off(st: PviewState):
        m = {
            "fd_probes": jnp.int32(0),
            "fd_failed_probes": jnp.int32(0),
            "fd_new_suspects": jnp.int32(0),
        }
        if armed:
            m["_ad_miss"] = jnp.zeros((n,), bool)
            m["_ad_succ"] = jnp.zeros((n,), bool)
            m["_ad_cnt"] = jnp.zeros((n,), jnp.int32)
            m["_ad_key"] = jnp.full((n,), NO_CANDIDATE, jnp.int32)
        if trace is not None:
            from ..trace import capture as _tc

            m["trace_fd"] = _tc.zero_fd_trace(n, params.ping_req_k)
        return st, no_props, m

    fd_ran = (state.tick % params.fd_every) == 0
    state, props_fd, fd_m = jax.lax.cond(fd_ran, _fd_on, _fd_off, state)
    if trace is not None:
        state, props_exp, trace_sus = _maintenance_sweep(state, params, trace=trace)
    else:
        state, props_exp = _maintenance_sweep(state, params, ad=ad)
    state, g_m = _gossip_phase(state, r, params, adaptive=armed)
    state, props_sync, s_m = _sync_phase(
        state, r, params, trace=trace is not None, adaptive=armed
    )
    state, props_ref = _refute_phase(state, params)
    state = _rumor_sweeps(state, params)
    state, a_m = _alloc_phase(
        state, (props_fd, props_exp, props_ref, props_sync), params
    )

    trace_fd = fd_m.pop("trace_fd", None)
    trace_sync = s_m.pop("trace_sync", None)
    if armed:
        miss = fd_m.pop("_ad_miss")
        succ = fd_m.pop("_ad_succ")
        acc_cnt = fd_m.pop("_ad_cnt") + g_m.pop("_ad_cnt") + s_m.pop("_ad_cnt")
        acc_key = jnp.maximum(
            jnp.maximum(fd_m.pop("_ad_key"), g_m.pop("_ad_key")),
            s_m.pop("_ad_key"),
        )
        lh2, ck2, cf2 = _adp.fold(
            params.adaptive, ad.lh, ad.conf_key, ad.conf,
            acc_key=acc_key, acc_cnt=acc_cnt,
            miss=miss, succ=succ, refuted=props_ref[3], up=state.up,
        )
        ad = _adp.AdaptiveState(lh=lh2, conf_key=ck2, conf=cf2)
    metrics = {**fd_m, **g_m, **s_m, **a_m, **state_metrics(state, params)}
    if armed:
        metrics["adaptive_lh_high"] = ad.lh.max()
        metrics["adaptive_conf_high"] = ad.conf.max()
        return state, ad, metrics
    if trace is not None:
        from ..trace import capture as _tc

        trace_ref = props_ref[3][jnp.asarray(trace.tracer_rows, jnp.int32)]
        metrics["_trace_rows"] = _tc.build_trace_rows(
            trace,
            tick=state.tick,
            up=state.up,
            fd_ran=fd_ran,
            trace_fd=trace_fd,
            trace_sus=trace_sus,
            trace_ref=trace_ref,
            trace_sync=trace_sync,
            infected_b=state.infected,
            infected_at=state.infected_at,
            infected_from=state.infected_from,
        )
    return state, metrics


def state_metrics(state: PviewState, params: PviewParams) -> dict:
    """State-derived health metrics — the shared telemetry names, computed
    over the table EDGES (up observer + tabled subject) instead of full
    pairs: ``alive_view_fraction`` is live-edge agreement, the partial-view
    convergence measure the sentinels also use."""
    coverage = (
        (state.infected & state.up[:, None]).sum(0).astype(jnp.float32)
        / jnp.maximum(state.up.sum(), 1)
    )
    newest_u = jnp.where(
        state.infected, state.rumor_created[None, :], NEVER
    ).max(axis=1)
    seg_u = (
        state.rumor_active[None, :]
        & ~state.infected
        & (state.rumor_created[None, :] < newest_u[:, None])
        & state.up[:, None]
    ).sum(axis=1)

    def _seg_m(st: PviewState):
        newest_m = jnp.where(
            st.minf_age > 0, st.mr_created[None, :], NEVER
        ).max(axis=1)
        return (
            st.mr_active[None, :]
            & (st.minf_age == 0)
            & (st.mr_created[None, :] < newest_m[:, None])
            & st.up[:, None]
        ).sum(axis=1)

    seg_m = jax.lax.cond(
        state.mr_active.any() & ((state.tick % params.sweep_every) == 0),
        _seg_m,
        lambda st: jnp.zeros((state.capacity,), jnp.int32),
        state,
    )
    metrics = {
        "n_up": state.up.sum(),
        "mr_active_count": state.mr_active.sum(),
        "rumor_coverage": coverage,
        "gossip_segmentation": (seg_u + seg_m).max(),
    }
    if params.full_metrics:
        keys = _keys_i32(state)
        sid = state.nbr_id
        sidc = jnp.maximum(sid, 0)
        rank = keys & 3
        edges = (sid >= 0) & state.up[:, None] & state.up[sidc]
        n_edges = jnp.maximum(edges.sum(), 1)
        metrics["alive_view_fraction"] = (
            (edges & (rank == RANK_ALIVE)).sum().astype(jnp.float32) / n_edges
        )
        metrics["false_suspect_pairs"] = (edges & (rank == RANK_SUSPECT)).sum()
    else:
        metrics["alive_view_fraction"] = jnp.float32(0.0)
        metrics["false_suspect_pairs"] = jnp.int32(0)
    return metrics


def run_pview_ticks(
    state: PviewState,
    key: jax.Array,
    n_ticks: int,
    params: PviewParams,
    watch_rows: jax.Array | None = None,
):
    """Batched scan window — same contract as ``sparse.run_sparse_ticks``;
    watched rows return their SYNTHESIZED full-width key rows per tick
    ([n_ticks, W, N], -1 where untabled) so the driver's event diff works
    unchanged."""

    def body(carry, _):
        st, k = carry
        k, tick_key = jax.random.split(k)
        st, m = pview_tick(st, tick_key, params)
        if watch_rows is not None:
            m = dict(m, _watched_keys=view_rows(st, watch_rows))
        return (st, k), m

    (state, key), ms = jax.lax.scan(body, (state, key), None, length=n_ticks)
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, key, ms, watched


def run_pview_ticks_traced(
    state: PviewState,
    key: jax.Array,
    trace_buf: jax.Array,
    trace_cursor: jax.Array,
    n_ticks: int,
    params: PviewParams,
    trace,
    watch_rows: jax.Array | None = None,
):
    from ..trace import capture as _tc

    def body(carry, _):
        st, k, buf, cur = carry
        k, tick_key = jax.random.split(k)
        st, m = pview_tick(st, tick_key, params, trace=trace)
        buf, cur = _tc.append_rows(buf, cur, m.pop("_trace_rows"), trace.ring_len)
        if watch_rows is not None:
            m = dict(m, _watched_keys=view_rows(st, watch_rows))
        return (st, k, buf, cur), m

    (state, key, trace_buf, _cur), ms = jax.lax.scan(
        body, (state, key, trace_buf, trace_cursor), None, length=n_ticks
    )
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, key, ms, watched, trace_buf


def run_pview_ticks_adaptive(
    state: PviewState,
    ad,
    key: jax.Array,
    n_ticks: int,
    params: PviewParams,
    watch_rows: jax.Array | None = None,
):
    """Adaptive-armed :func:`run_pview_ticks` (r14)."""

    def body(carry, _):
        st, a, k = carry
        k, tick_key = jax.random.split(k)
        st, a, m = pview_tick(st, tick_key, params, ad=a)
        if watch_rows is not None:
            m = dict(m, _watched_keys=view_rows(st, watch_rows))
        return (st, a, k), m

    (state, ad, key), ms = jax.lax.scan(
        body, (state, ad, key), None, length=n_ticks
    )
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, ad, key, ms, watched


def make_pview_adaptive_run(params: PviewParams, n_ticks: int,
                            donate: bool = True):
    """Jitted :func:`run_pview_ticks_adaptive`: engine + adaptive state
    donated (argnums 0, 1). Refuses a default spec."""
    if params.adaptive.is_default:
        raise ValueError(
            "make_pview_adaptive_run needs an enabled AdaptiveSpec on "
            "params — the default spec's program is make_pview_run's"
        )
    return jax.jit(
        functools.partial(
            run_pview_ticks_adaptive, n_ticks=n_ticks, params=params
        ),
        donate_argnums=(0, 1) if donate else (),
    )


def make_pview_fleet_run(params: PviewParams, n_ticks: int, donate: bool = True):
    """Scenario-batched :func:`run_pview_ticks` (r15): the O(N·k) engine's
    fleet window — every batched value is ``[S, N, k]``-proportional, so
    the wide-value ban holds over the fleet program too (the r12 ``fleet``
    audit variant proves it)."""
    from .fleet import make_fleet_window

    return make_fleet_window(run_pview_ticks, params, n_ticks, donate=donate)


def make_pview_fleet_adaptive_run(
    params: PviewParams, n_ticks: int, donate: bool = True
):
    """Fleet twin of :func:`make_pview_adaptive_run` (argnums 0, 1
    donated). Refuses a default spec."""
    from .fleet import make_fleet_window

    if params.adaptive.is_default:
        raise ValueError(
            "make_pview_fleet_adaptive_run needs an enabled AdaptiveSpec "
            "on params — the default spec's program is make_pview_fleet_run's"
        )
    return make_fleet_window(
        run_pview_ticks_adaptive, params, n_ticks, donate=donate,
        donated=(0, 1),
    )


def make_pview_run(params: PviewParams, n_ticks: int, donate: bool = True):
    """Jitted window with the state DONATED — the pview twin of
    ``sparse.make_sparse_run`` (the one spelling the driver and every
    bench loop use)."""
    return jax.jit(
        functools.partial(run_pview_ticks, n_ticks=n_ticks, params=params),
        donate_argnums=0 if donate else (),
    )


def make_pview_traced_run(params: PviewParams, n_ticks: int, trace, donate: bool = True):
    return jax.jit(
        functools.partial(
            run_pview_ticks_traced, n_ticks=n_ticks, params=params, trace=trace
        ),
        donate_argnums=(0, 2) if donate else (),
    )


def pview_tick_fused(state: PviewState, key: jax.Array, params: PviewParams,
                     ad=None):
    """The fused-phase spelling of :func:`pview_tick` (r17): same phase
    ORDER and per-phase semantics — the bit-identity tests pin the whole
    trajectory against the unfused tick — but adjacent phases hand each
    other the intermediates the unfused tick re-derives:

    * FD → maintenance: the post-verdict i32 key plane;
    * gossip: packed A-pass apply + ``delivery_kernel`` combine
      (:func:`_gossip_phase_fused`);
    * SYNC: compacted K-row merges (:func:`_merge_entries_compact`);
    * gossip → sweep: the packed forwarding plane.

    No trace support (the r10 capture is a phase-boundary instrument —
    profile the unfused tick instead). ``ad`` arms the adaptive plane as
    in :func:`pview_tick`."""
    armed = ad is not None
    if armed and params.adaptive.is_default:
        raise ValueError(
            "adaptive tick needs an enabled AdaptiveSpec on params"
        )
    state = state.replace(tick=state.tick + 1)
    fd_key, round_key = split_tick_key(key)
    r = draw_sparse_round(round_key, state.capacity, params.fanout, params.sample_tries)

    n = state.capacity
    rows = jnp.arange(n)
    no_props = (
        jnp.zeros((n,), jnp.int32),
        jnp.zeros((n,), jnp.int32),
        rows,
        jnp.zeros((n,), bool),
    )

    def _fd_on(st: PviewState):
        fd_r = draw_sparse_fd(fd_key, n, params.ping_req_k, params.sample_tries)
        return _fd_phase(st, fd_r, params, ad=ad, fused=True)

    def _fd_off(st: PviewState):
        m = {
            "fd_probes": jnp.int32(0),
            "fd_failed_probes": jnp.int32(0),
            "fd_new_suspects": jnp.int32(0),
        }
        if armed:
            m["_ad_miss"] = jnp.zeros((n,), bool)
            m["_ad_succ"] = jnp.zeros((n,), bool)
            m["_ad_cnt"] = jnp.zeros((n,), jnp.int32)
            m["_ad_key"] = jnp.full((n,), NO_CANDIDATE, jnp.int32)
        # off-tick hand-off: nothing was written, so the plane the
        # maintenance sweep would re-widen IS the current one
        return st, no_props, m, _keys_i32(st)

    fd_ran = (state.tick % params.fd_every) == 0
    state, props_fd, fd_m, keys_h = jax.lax.cond(fd_ran, _fd_on, _fd_off, state)
    state, props_exp = _maintenance_sweep(state, params, ad=ad, keys_i32=keys_h)
    state, g_m, fwd_post_p = _gossip_phase_fused(state, r, params, adaptive=armed)
    state, props_sync, s_m = _sync_phase(
        state, r, params, adaptive=armed, fused=True
    )
    state, props_ref = _refute_phase(state, params)
    state = _rumor_sweeps_fused(state, params, fwd_post_p)
    state, a_m = _alloc_phase(
        state, (props_fd, props_exp, props_ref, props_sync), params
    )

    if armed:
        miss = fd_m.pop("_ad_miss")
        succ = fd_m.pop("_ad_succ")
        acc_cnt = fd_m.pop("_ad_cnt") + g_m.pop("_ad_cnt") + s_m.pop("_ad_cnt")
        acc_key = jnp.maximum(
            jnp.maximum(fd_m.pop("_ad_key"), g_m.pop("_ad_key")),
            s_m.pop("_ad_key"),
        )
        lh2, ck2, cf2 = _adp.fold(
            params.adaptive, ad.lh, ad.conf_key, ad.conf,
            acc_key=acc_key, acc_cnt=acc_cnt,
            miss=miss, succ=succ, refuted=props_ref[3], up=state.up,
        )
        ad = _adp.AdaptiveState(lh=lh2, conf_key=ck2, conf=cf2)
    metrics = {**fd_m, **g_m, **s_m, **a_m, **state_metrics(state, params)}
    if armed:
        metrics["adaptive_lh_high"] = ad.lh.max()
        metrics["adaptive_conf_high"] = ad.conf.max()
        return state, ad, metrics
    return state, metrics


def run_pview_ticks_fused(
    state: PviewState,
    key: jax.Array,
    n_ticks: int,
    params: PviewParams,
    watch_rows: jax.Array | None = None,
):
    """Fused-window twin of :func:`run_pview_ticks` — same signature and
    return contract, bit-identical trajectory."""

    def body(carry, _):
        st, k = carry
        k, tick_key = jax.random.split(k)
        st, m = pview_tick_fused(st, tick_key, params)
        if watch_rows is not None:
            m = dict(m, _watched_keys=view_rows(st, watch_rows))
        return (st, k), m

    (state, key), ms = jax.lax.scan(body, (state, key), None, length=n_ticks)
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, key, ms, watched


def run_pview_ticks_fused_adaptive(
    state: PviewState,
    ad,
    key: jax.Array,
    n_ticks: int,
    params: PviewParams,
    watch_rows: jax.Array | None = None,
):
    """Adaptive-armed :func:`run_pview_ticks_fused`."""

    def body(carry, _):
        st, a, k = carry
        k, tick_key = jax.random.split(k)
        st, a, m = pview_tick_fused(st, tick_key, params, ad=a)
        if watch_rows is not None:
            m = dict(m, _watched_keys=view_rows(st, watch_rows))
        return (st, a, k), m

    (state, ad, key), ms = jax.lax.scan(
        body, (state, ad, key), None, length=n_ticks
    )
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, ad, key, ms, watched


def make_pview_fused_run(params: PviewParams, n_ticks: int, donate: bool = True):
    """Jitted fused window, state donated — the drop-in fast spelling of
    :func:`make_pview_run` (same signature, bit-identical trajectory)."""
    return jax.jit(
        functools.partial(run_pview_ticks_fused, n_ticks=n_ticks, params=params),
        donate_argnums=0 if donate else (),
    )


def make_pview_fused_adaptive_run(params: PviewParams, n_ticks: int,
                                  donate: bool = True):
    """Fused twin of :func:`make_pview_adaptive_run` (argnums 0, 1
    donated). Refuses a default spec."""
    if params.adaptive.is_default:
        raise ValueError(
            "make_pview_fused_adaptive_run needs an enabled AdaptiveSpec on "
            "params — the default spec's program is make_pview_fused_run's"
        )
    return jax.jit(
        functools.partial(
            run_pview_ticks_fused_adaptive, n_ticks=n_ticks, params=params
        ),
        donate_argnums=(0, 1) if donate else (),
    )


def make_pview_fused_fleet_run(params: PviewParams, n_ticks: int,
                               donate: bool = True):
    """Fused twin of :func:`make_pview_fleet_run` — vmap over the fused
    window; the wide-value ban holds over the fused fleet program too."""
    from .fleet import make_fleet_window

    return make_fleet_window(run_pview_ticks_fused, params, n_ticks, donate=donate)


# ---------------------------------------------------------------------------
# synthesized views (host/driver seams — see ops.engine_api)
# ---------------------------------------------------------------------------


def view_rows(state: PviewState, rows) -> jax.Array:
    """Synthesize full-width [W, N] i32 key rows for ``rows``: each row's
    table scattered by subject (-1 where untabled) + its self record on
    the diagonal. O(W·(k + N)) — host-seam cost, never in the tick."""
    rows = jnp.asarray(rows, jnp.int32)
    n = state.capacity
    ids = state.nbr_id[rows]  # [W, k]
    keys = _keys_i32(state)[rows]
    full = (
        jnp.full((rows.shape[0], n + 1), UNKNOWN_KEY, jnp.int32)
        .at[jnp.arange(rows.shape[0])[:, None], jnp.where(ids >= 0, ids, n)]
        .max(keys, mode="drop")[:, :n]
    )
    return full.at[jnp.arange(rows.shape[0]), rows].set(state.self_key[rows])


def tracer_view_cols(state: PviewState, tracer_rows) -> jax.Array:
    """The tracers' [N, K] synthesized view-key COLUMNS: observer i's
    record about tracer t (-1 unknown; the tracer's own row carries its
    self record) — the pview feed for the trace plane's window-boundary
    dissemination diff."""
    tr = jnp.asarray(tracer_rows, jnp.int32)
    keys = _keys_i32(state)
    match = state.nbr_id[:, :, None] == tr[None, None, :]  # [N, k, K]
    cols = jnp.where(
        match & (state.nbr_id[:, :, None] >= 0), keys[:, :, None], UNKNOWN_KEY
    ).max(axis=1)
    return cols.at[tr, jnp.arange(tr.shape[0])].set(state.self_key[tr])


def remembered_rows(state: PviewState) -> jax.Array:
    """[N] bool — rows some up member still holds a record about (tables
    only; the driver's prefer-forgotten-rows join policy)."""
    n = state.capacity
    held = state.up[:, None] & (state.nbr_id >= 0)
    return (
        jnp.zeros((n + 1,), bool)
        .at[jnp.where(held, state.nbr_id, n)]
        .max(held, mode="drop")[:n]
    )


def staleness(state: PviewState):
    """Per-subject count of up observers holding a STALE record (identity/
    incarnation below the subject's own) — table edges only (unknown
    observers are not counted stale: a partial view is not staleness)."""
    n = state.capacity
    keys = _keys_i32(state)
    sid = state.nbr_id
    sidc = jnp.maximum(sid, 0)
    stale_edge = (
        (sid >= 0)
        & state.up[:, None]
        & state.up[sidc]
        & ((keys >> 2) < (state.self_key[sidc] >> 2))
    )
    stale = (
        jnp.zeros((n + 1,), jnp.int32)
        .at[jnp.where(stale_edge, sid, n)]
        .add(stale_edge.astype(jnp.int32), mode="drop")[:n]
    )
    return stale, state.up.sum()
