"""Vectorized TPU simulation kernel for the SWIM protocol.

This package is the TPU-native core mandated by SURVEY.md §2.3/§7 stage 4:
the reference's three periodic per-node loops (failure detector ping round
``FailureDetectorImpl.java:101-106``, gossip round
``GossipProtocolImpl.java:106-114``, periodic SYNC
``MembershipProtocolImpl.java:478-483``) fused into one pure
``tick(state, key) -> (state, metrics)`` transition over all N simulated
members, jit-compiled by XLA and shardable over a device mesh on the member
axis.

Modules:

* :mod:`lattice`  — the ``isOverrides`` record-precedence lattice as a
  packed monotone key (scatter-max-joinable; int32 wide / int16 narrow
  layouts, r9).
* :mod:`bitplane` — the repo's ONE word-packing spelling (bool ⇄ uint32
  bit planes, popcounts, bit-rank selection — r9).
* :mod:`rand`     — per-tick random draw layout shared by kernel and oracle.
* :mod:`state`    — ``SimState`` pytree + ``SimParams`` static config + host
  mutation helpers (join/crash/leave/rumor/link control).
* :mod:`tick`     — the tick kernel itself (FD, suspicion, gossip, SYNC,
  refutation, rumor sweep phases).
* :mod:`oracle`   — scalar NumPy reimplementation of identical tick
  semantics, used by equivalence tests.
* :mod:`sharding` — mesh construction + sharded jit of the tick.
* :mod:`sparse`   — the record-queue engine (bounded rumor pools; r3) with
  its own oracle (:mod:`sparse_oracle`).
* :mod:`pview`    — the O(N·k) partial-view engine (r11: [N, k] neighbor
  tables, no [N, N] plane anywhere) with its own oracle
  (:mod:`pview_oracle`).
* :mod:`engine_api` — the ONE engine-interface spelling: every consumer
  (driver, telemetry, trace, chaos, monitor) resolves dense/sparse/pview
  through one :class:`~.engine_api.EngineOps` descriptor (r11).
* :mod:`fleet`    — the scenario-batched fleet engine (r15): every
  engine's window vmapped over a leading [S] scenario axis (one XLA
  program advancing S×N members), the batched chaos-timeline fold, and
  the on-device Monte Carlo reductions behind the certification service.
"""

from .lattice import UNKNOWN, decode_key, precedence_key
from .state import SimParams, SimState, init_state
from .kernel import tick

__all__ = [
    "UNKNOWN",
    "decode_key",
    "precedence_key",
    "SimParams",
    "SimState",
    "init_state",
    "tick",
]
