"""Device-mesh sharding of the simulation: N members row-sharded over chips.

SURVEY.md §2.3: the member axis is the domain's one parallelism axis (the
DP analogue). Every ``[N, ...]`` state tensor is sharded on its first
(member-row) dimension over the ``"members"`` mesh axis with
``jax.sharding.NamedSharding``; rumor-slot vectors, scalars, and per-tick
metrics stay replicated. Cross-shard message delivery (gossip/SYNC
scatter-max into receiver rows, FD gathers of target columns) lowers to XLA
collectives over ICI automatically under GSPMD — the TPU-native equivalent
of the reference's loopback/NCCL-style delivery, per the sharding recipe:
pick a mesh, annotate shardings, let XLA insert collectives.

The driver's ``dryrun_multichip`` runs exactly this on a virtual CPU mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .kernel import run_ticks, tick
from .state import SimParams, SimState

MEMBER_AXIS = "members"


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (MEMBER_AXIS,))


def state_shardings(
    mesh: Mesh, dense_links: bool = True, delay_slots: int = 0
) -> SimState:
    """A SimState-shaped pytree of NamedShardings: member-axis tensors split
    on rows, small per-rumor/scalar leaves replicated. ``dense_links=False``
    matches states built with a scalar uniform loss (the memory-lean
    large-N mode), which must be replicated, not row-sharded.
    ``delay_slots=0`` marks the (empty) pending rings replicated — XLA emits
    zero-size outputs as replicated, and an explicit row spec on them makes
    jitted host mutators' outputs clash with the tick's in_shardings."""
    row = NamedSharding(mesh, P(MEMBER_AXIS))
    row2d = NamedSharding(mesh, P(MEMBER_AXIS, None))
    rep = NamedSharding(mesh, P())
    # [D, N, ...] rings: member axis is dim 1
    ring = NamedSharding(mesh, P(None, MEMBER_AXIS, None)) if delay_slots else rep
    return SimState(
        tick=rep,
        up=row,
        epoch=row,
        view_key=row2d,
        changed_at=row2d,
        force_sync=row,
        leaving=row,
        ns_id=row,
        ns_rel=rep,
        rumor_active=rep,
        rumor_origin=rep,
        rumor_created=rep,
        infected=row2d,
        infected_at=row2d,
        infected_from=row2d,
        loss=row2d if dense_links else rep,
        fetch_rt=row2d if dense_links else rep,
        delay_q=row2d if dense_links else rep,
        pending_key=ring,
        pending_inf=ring,
        pending_src=ring,
    )


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    """Place an existing (host/single-device) state onto the mesh."""
    return jax.device_put(
        state,
        state_shardings(mesh, state.loss.ndim != 0, state.pending_key.shape[0]),
    )


def _check_dense_word_alignment(mesh: Mesh, params: SimParams) -> None:
    """Dense-tick mesh preconditions. Plain row divisibility always; in the
    r9 packed mode (``key_dtype="i16"``) additionally require
    ``capacity % (32 * mesh.size) == 0`` — the SAME word-alignment rule the
    sparse builders enforce: the packed-mask sweeps (`_known_live_words`,
    the word samplers, the popcount health reductions) pack [N, N] masks
    into u32 words along columns, and word-aligned row shards keep every
    derived word plane shard-local under GSPMD (an unaligned capacity pads
    the word axis and silently reintroduces per-phase all-gathers)."""
    if params.capacity % mesh.size != 0:
        raise ValueError(
            f"capacity {params.capacity} not divisible by mesh size {mesh.size}"
        )
    if params.key_dtype == "i16" and params.capacity % (32 * mesh.size) != 0:
        raise ValueError(
            f"capacity {params.capacity} must be divisible by 32 * mesh size "
            f"({32 * mesh.size}) in packed (plane_dtype='i16') mode — same "
            "word-alignment rule as the sparse word builders (pad capacity "
            "up and leave the extra rows up=False; masks make padding free)"
        )


def make_sharded_tick(mesh: Mesh, params: SimParams, dense_links: bool = True):
    """jit the tick with explicit in/out shardings over ``mesh``.

    Capacity must be divisible by the mesh size (pad rows and leave them
    ``up=False`` otherwise — masks make padding free).
    """
    _check_dense_word_alignment(mesh, params)
    sh = state_shardings(mesh, dense_links, params.delay_slots)
    rep = NamedSharding(mesh, P())
    return jax.jit(
        partial(tick, params=params),
        in_shardings=(sh, rep),
        out_shardings=(sh, None),
    )


def sparse_state_shardings(mesh: Mesh, dense_links: bool = False, delay_slots: int = 0):
    """SparseState-shaped pytree of NamedShardings: every [N, ...] tensor
    row-sharded on the member axis; the [M]/[R] rumor-pool vectors and
    scalars replicated; [D, N, ...] pending rings sharded on dim 1. The
    membership-rumor pool being replicated is what makes dissemination
    cross-shard-cheap: senders scatter infection bits into receiver rows
    (one collective), while pool metadata needs no communication at all."""
    from .sparse import SparseState

    row = NamedSharding(mesh, P(MEMBER_AXIS))
    row2d = NamedSharding(mesh, P(MEMBER_AXIS, None))
    rep = NamedSharding(mesh, P())
    ring = NamedSharding(mesh, P(None, MEMBER_AXIS, None)) if delay_slots else rep
    return SparseState(
        tick=rep,
        up=row,
        epoch=row,
        joined_at=row,
        view_key=row2d,
        n_live=row,
        sus_key=row,
        sus_since=row,
        force_sync=row,
        leaving=row,
        ns_id=row,
        ns_rel=rep,
        mr_active=rep,
        mr_subject=rep,
        mr_key=rep,
        mr_created=rep,
        mr_origin=rep,
        minf_age=row2d,
        rumor_active=rep,
        rumor_origin=rep,
        rumor_created=rep,
        infected=row2d,
        infected_at=row2d,
        infected_from=row2d,
        loss=row2d if dense_links else rep,
        fetch_rt=row2d if dense_links else rep,
        delay_q=row2d if dense_links else rep,
        pending_minf=ring,
        pending_inf=ring,
        pending_src=ring,
    )


def shard_sparse_state(state, mesh: Mesh):
    return jax.device_put(
        state,
        sparse_state_shardings(mesh, state.loss.ndim != 0, state.pending_minf.shape[0]),
    )


def _check_sparse_word_alignment(mesh: Mesh, params) -> None:
    """Sparse-tick mesh preconditions. Beyond plain row divisibility, the
    word-sharded apply staging (``sparse._mr_apply``'s ``nd_T_p`` constraint,
    P(None, 'member')) requires rows-per-device to be a multiple of 32 so
    packed observer words align with the observer row shards — otherwise
    GSPMD pads the word axis and the collective-free packed-word block walk
    silently regresses into per-block all-gathers. Assert it up front."""
    if params.capacity % mesh.size != 0:
        raise ValueError(
            f"capacity {params.capacity} not divisible by mesh size {mesh.size}"
        )
    if params.capacity % (32 * mesh.size) != 0:
        raise ValueError(
            f"capacity {params.capacity} must be divisible by 32 * mesh size "
            f"({32 * mesh.size}): the word-sharded apply staging packs "
            "observers into u32 words that must align with the row shards "
            "(pad capacity up to the next multiple and leave the extra rows "
            "up=False — masks make padding free)"
        )


def make_sharded_sparse_tick(mesh: Mesh, params, dense_links: bool = False):
    from .sparse import mesh_context, sparse_tick

    _check_sparse_word_alignment(mesh, params)
    sh = sparse_state_shardings(mesh, dense_links, params.delay_slots)
    rep = NamedSharding(mesh, P())

    def fn(state, key):
        # the context is active DURING TRACING, which is when the tick's
        # internal with_sharding_constraint calls (the word-sharded apply
        # staging — see _mr_apply) need the mesh
        with mesh_context(mesh):
            return sparse_tick(state, key, params)

    return jax.jit(fn, in_shardings=(sh, rep), out_shardings=(sh, None))


def make_sharded_sparse_run(mesh: Mesh, params, n_ticks: int):
    from .sparse import mesh_context, run_sparse_ticks

    _check_sparse_word_alignment(mesh, params)

    def fn(state, key, watch_rows=None):
        with mesh_context(mesh):
            return run_sparse_ticks(
                state, key, n_ticks, params, watch_rows=watch_rows
            )

    # donate the carried state like the single-device path — without it the
    # window holds input AND output state copies (38.7 GB extra at the
    # flagship shape's view plane)
    return jax.jit(fn, donate_argnums=0)


def pview_state_shardings(mesh: Mesh, dense_links: bool = False, delay_slots: int = 0):
    """PviewState-shaped pytree of NamedShardings (r17): every [N, ...]
    tensor row-sharded on the member axis; the [M]/[R] pool vectors, the
    scalar link model, and the [G, G] partition-cell loss replicated;
    [D, N, ...] pending rings sharded on dim 1. Same placement logic as
    the sparse engine — the pview tick's cross-shard traffic is the
    delivery gather (each receiver's elected senders' payload rows) and
    the table-merge scatters, which GSPMD lowers to collectives; the
    bounded pools need no communication at all. ``dense_links`` is
    accepted for seam parity and must be falsy (pview has no [N, N] link
    plane — by construction, ``forbid_wide_values``)."""
    from .pview import PviewState

    if dense_links:
        raise ValueError(
            "the pview engine has no [N, N] link plane (dense_links must "
            "be False/None)"
        )
    row = NamedSharding(mesh, P(MEMBER_AXIS))
    row2d = NamedSharding(mesh, P(MEMBER_AXIS, None))
    rep = NamedSharding(mesh, P())
    ring = NamedSharding(mesh, P(None, MEMBER_AXIS, None)) if delay_slots else rep
    return PviewState(
        tick=rep,
        up=row,
        epoch=row,
        joined_at=row,
        self_key=row,
        nbr_id=row2d,
        nbr_key=row2d,
        sus_key=row,
        sus_since=row,
        force_sync=row,
        leaving=row,
        mr_active=rep,
        mr_subject=rep,
        mr_key=rep,
        mr_created=rep,
        mr_origin=rep,
        minf_age=row2d,
        rumor_active=rep,
        rumor_origin=rep,
        rumor_created=rep,
        infected=row2d,
        infected_at=row2d,
        infected_from=row2d,
        loss=rep,
        delay_q=rep,
        part_id=row,
        part_loss=rep,
        pending_minf=ring,
        pending_inf=ring,
        pending_src=ring,
    )


def shard_pview_state(state, mesh: Mesh):
    """Place an existing (host/single-device) pview state onto the mesh."""
    return jax.device_put(
        state,
        pview_state_shardings(mesh, False, state.pending_minf.shape[0]),
    )


def _check_pview_word_alignment(mesh: Mesh, params) -> None:
    """Pview-tick mesh preconditions: plain row divisibility always, and
    the 32-row word rule in every mode — the pview tick packs member-axis
    bit planes into u32 words unconditionally (the fd/suspicion masks,
    the delivery payload's user-rumor words, the r17 fused tick's packed
    membership-delivery planes), so row shards must stay word-aligned or
    GSPMD pads the word axis and the packed sweeps regress into
    per-phase all-gathers (the sparse builders' rule, applied to both
    key layouts)."""
    if params.capacity % mesh.size != 0:
        raise ValueError(
            f"capacity {params.capacity} not divisible by mesh size {mesh.size}"
        )
    if params.capacity % (32 * mesh.size) != 0:
        raise ValueError(
            f"capacity {params.capacity} must be divisible by 32 * mesh size "
            f"({32 * mesh.size}): the pview packed bit planes must align "
            "with the row shards (pad capacity up and leave the extra rows "
            "up=False — masks make padding free)"
        )


def make_sharded_pview_run(mesh: Mesh, params, n_ticks: int):
    """jit the batched ``run_pview_ticks`` window over ``mesh`` (r17).

    Input state must already be placed via :func:`shard_pview_state`;
    GSPMD propagates the row sharding through the scan. The carried state
    is donated like every window builder. The Pallas delivery kernel is
    single-device-only for now — refuse it up front rather than letting
    a whole-payload BlockSpec silently all-gather the table."""
    _check_pview_word_alignment(mesh, params)
    if getattr(params, "delivery_kernel", "xla") != "xla":
        raise ValueError(
            "delivery_kernel='pallas' is single-device for now — the "
            "kernel's whole-payload block would all-gather the table "
            "under GSPMD; use delivery_kernel='xla' on meshes"
        )
    from .pview import run_pview_ticks

    return jax.jit(
        partial(run_pview_ticks, n_ticks=n_ticks, params=params),
        donate_argnums=0,
    )


def make_sharded_pview_adaptive_run(mesh: Mesh, params, n_ticks: int):
    """Sharded adaptive pview window (r17 — the lift of the r14
    "adaptive is single-device for now" refusal, for this engine): the
    AdaptiveState's three [N] planes ride the donated carry row-sharded
    like every other member-axis tensor (place them with
    :func:`shard_adaptive_state`); argnums (0, 1) donated. Refuses a
    default spec (the legacy sharded window is the byte-identical
    program for that case)."""
    _check_pview_word_alignment(mesh, params)
    if getattr(params, "delivery_kernel", "xla") != "xla":
        raise ValueError(
            "delivery_kernel='pallas' is single-device for now — use "
            "delivery_kernel='xla' on meshes"
        )
    if params.adaptive.is_default:
        raise ValueError(
            "make_sharded_pview_adaptive_run needs an enabled AdaptiveSpec "
            "on params — the default spec's program is "
            "make_sharded_pview_run's"
        )
    from .pview import run_pview_ticks_adaptive

    return jax.jit(
        partial(run_pview_ticks_adaptive, n_ticks=n_ticks, params=params),
        donate_argnums=(0, 1),
    )


def shard_adaptive_state(ad, mesh: Mesh):
    """Place an AdaptiveState onto the mesh: all three planes are [N]
    member-axis tensors, so they row-shard like ``up``."""
    from ..adaptive import AdaptiveState

    row = NamedSharding(mesh, P(MEMBER_AXIS))
    return jax.device_put(ad, AdaptiveState(lh=row, conf_key=row, conf=row))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated placement on ``mesh`` — the home of every telemetry
    tensor (the [ring_len, n_metrics] metric ring, its append vectors, the
    staged per-window reductions). The ring is tiny and every window-summary
    reduction over sharded metrics comes out replicated under GSPMD, so an
    explicitly replicated ring keeps the append a collective-free local
    update on every chip instead of letting placement inference scatter it."""
    return NamedSharding(mesh, P())


def place_replicated(x, mesh: Mesh):
    """device_put onto the replicated sharding (telemetry ring placement)."""
    return jax.device_put(x, replicated_sharding(mesh))


def make_sharded_run(mesh: Mesh, params: SimParams, n_ticks: int, dense_links: bool = True):
    """jit the batched ``run_ticks`` window over ``mesh``.

    Input state must already be placed via :func:`shard_state`; GSPMD
    propagates the row sharding through the scan (stacked metrics and
    watched-row keys come out replicated/gathered as XLA chooses). The
    carried state is donated, like the sparse window builder — without it
    the window holds input AND output copies of every [N, N] plane."""
    _check_dense_word_alignment(mesh, params)
    return jax.jit(
        partial(run_ticks, n_ticks=n_ticks, params=params), donate_argnums=0
    )
