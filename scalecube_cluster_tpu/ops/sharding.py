"""Device-mesh sharding of the simulation: N members row-sharded over chips.

SURVEY.md §2.3: the member axis is the domain's one parallelism axis (the
DP analogue). Every ``[N, ...]`` state tensor is sharded on its first
(member-row) dimension over the ``"members"`` mesh axis with
``jax.sharding.NamedSharding``; rumor-slot vectors, scalars, and per-tick
metrics stay replicated. Cross-shard message delivery (gossip/SYNC
scatter-max into receiver rows, FD gathers of target columns) lowers to XLA
collectives over ICI automatically under GSPMD — the TPU-native equivalent
of the reference's loopback/NCCL-style delivery, per the sharding recipe:
pick a mesh, annotate shardings, let XLA insert collectives.

The driver's ``dryrun_multichip`` runs exactly this on a virtual CPU mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .kernel import run_ticks, tick
from .state import SimParams, SimState

MEMBER_AXIS = "members"


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (MEMBER_AXIS,))


def state_shardings(
    mesh: Mesh, dense_links: bool = True, delay_slots: int = 0
) -> SimState:
    """A SimState-shaped pytree of NamedShardings: member-axis tensors split
    on rows, small per-rumor/scalar leaves replicated. ``dense_links=False``
    matches states built with a scalar uniform loss (the memory-lean
    large-N mode), which must be replicated, not row-sharded.
    ``delay_slots=0`` marks the (empty) pending rings replicated — XLA emits
    zero-size outputs as replicated, and an explicit row spec on them makes
    jitted host mutators' outputs clash with the tick's in_shardings."""
    row = NamedSharding(mesh, P(MEMBER_AXIS))
    row2d = NamedSharding(mesh, P(MEMBER_AXIS, None))
    rep = NamedSharding(mesh, P())
    # [D, N, ...] rings: member axis is dim 1
    ring = NamedSharding(mesh, P(None, MEMBER_AXIS, None)) if delay_slots else rep
    return SimState(
        tick=rep,
        up=row,
        epoch=row,
        view_key=row2d,
        changed_at=row2d,
        force_sync=row,
        leaving=row,
        ns_id=row,
        ns_rel=rep,
        rumor_active=rep,
        rumor_origin=rep,
        rumor_created=rep,
        infected=row2d,
        infected_at=row2d,
        infected_from=row2d,
        loss=row2d if dense_links else rep,
        fetch_rt=row2d if dense_links else rep,
        delay_q=row2d if dense_links else rep,
        pending_key=ring,
        pending_inf=ring,
        pending_src=ring,
    )


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    """Place an existing (host/single-device) state onto the mesh."""
    return jax.device_put(
        state,
        state_shardings(mesh, state.loss.ndim != 0, state.pending_key.shape[0]),
    )


def _check_dense_word_alignment(mesh: Mesh, params: SimParams) -> None:
    """Dense-tick mesh preconditions. Plain row divisibility always; in the
    r9 packed mode (``key_dtype="i16"``) additionally require
    ``capacity % (32 * mesh.size) == 0`` — the SAME word-alignment rule the
    sparse builders enforce: the packed-mask sweeps (`_known_live_words`,
    the word samplers, the popcount health reductions) pack [N, N] masks
    into u32 words along columns, and word-aligned row shards keep every
    derived word plane shard-local under GSPMD (an unaligned capacity pads
    the word axis and silently reintroduces per-phase all-gathers)."""
    if params.capacity % mesh.size != 0:
        raise ValueError(
            f"capacity {params.capacity} not divisible by mesh size {mesh.size}"
        )
    if params.key_dtype == "i16" and params.capacity % (32 * mesh.size) != 0:
        raise ValueError(
            f"capacity {params.capacity} must be divisible by 32 * mesh size "
            f"({32 * mesh.size}) in packed (plane_dtype='i16') mode — same "
            "word-alignment rule as the sparse word builders (pad capacity "
            "up and leave the extra rows up=False; masks make padding free)"
        )


def make_sharded_tick(mesh: Mesh, params: SimParams, dense_links: bool = True):
    """jit the tick with explicit in/out shardings over ``mesh``.

    Capacity must be divisible by the mesh size (pad rows and leave them
    ``up=False`` otherwise — masks make padding free).
    """
    _check_dense_word_alignment(mesh, params)
    sh = state_shardings(mesh, dense_links, params.delay_slots)
    rep = NamedSharding(mesh, P())
    return jax.jit(
        partial(tick, params=params),
        in_shardings=(sh, rep),
        out_shardings=(sh, None),
    )


def sparse_state_shardings(mesh: Mesh, dense_links: bool = False, delay_slots: int = 0):
    """SparseState-shaped pytree of NamedShardings: every [N, ...] tensor
    row-sharded on the member axis; the [M]/[R] rumor-pool vectors and
    scalars replicated; [D, N, ...] pending rings sharded on dim 1. The
    membership-rumor pool being replicated is what makes dissemination
    cross-shard-cheap: senders scatter infection bits into receiver rows
    (one collective), while pool metadata needs no communication at all."""
    from .sparse import SparseState

    row = NamedSharding(mesh, P(MEMBER_AXIS))
    row2d = NamedSharding(mesh, P(MEMBER_AXIS, None))
    rep = NamedSharding(mesh, P())
    ring = NamedSharding(mesh, P(None, MEMBER_AXIS, None)) if delay_slots else rep
    return SparseState(
        tick=rep,
        up=row,
        epoch=row,
        joined_at=row,
        view_key=row2d,
        n_live=row,
        sus_key=row,
        sus_since=row,
        force_sync=row,
        leaving=row,
        ns_id=row,
        ns_rel=rep,
        mr_active=rep,
        mr_subject=rep,
        mr_key=rep,
        mr_created=rep,
        mr_origin=rep,
        minf_age=row2d,
        rumor_active=rep,
        rumor_origin=rep,
        rumor_created=rep,
        infected=row2d,
        infected_at=row2d,
        infected_from=row2d,
        loss=row2d if dense_links else rep,
        fetch_rt=row2d if dense_links else rep,
        delay_q=row2d if dense_links else rep,
        pending_minf=ring,
        pending_inf=ring,
        pending_src=ring,
    )


def shard_sparse_state(state, mesh: Mesh):
    return jax.device_put(
        state,
        sparse_state_shardings(mesh, state.loss.ndim != 0, state.pending_minf.shape[0]),
    )


def _check_sparse_word_alignment(mesh: Mesh, params) -> None:
    """Sparse-tick mesh preconditions. Beyond plain row divisibility, the
    word-sharded apply staging (``sparse._mr_apply``'s ``nd_T_p`` constraint,
    P(None, 'member')) requires rows-per-device to be a multiple of 32 so
    packed observer words align with the observer row shards — otherwise
    GSPMD pads the word axis and the collective-free packed-word block walk
    silently regresses into per-block all-gathers. Assert it up front."""
    if params.capacity % mesh.size != 0:
        raise ValueError(
            f"capacity {params.capacity} not divisible by mesh size {mesh.size}"
        )
    if params.capacity % (32 * mesh.size) != 0:
        raise ValueError(
            f"capacity {params.capacity} must be divisible by 32 * mesh size "
            f"({32 * mesh.size}): the word-sharded apply staging packs "
            "observers into u32 words that must align with the row shards "
            "(pad capacity up to the next multiple and leave the extra rows "
            "up=False — masks make padding free)"
        )


def make_sharded_sparse_tick(mesh: Mesh, params, dense_links: bool = False):
    from .sparse import mesh_context, sparse_tick

    _check_sparse_word_alignment(mesh, params)
    sh = sparse_state_shardings(mesh, dense_links, params.delay_slots)
    rep = NamedSharding(mesh, P())

    def fn(state, key):
        # the context is active DURING TRACING, which is when the tick's
        # internal with_sharding_constraint calls (the word-sharded apply
        # staging — see _mr_apply) need the mesh
        with mesh_context(mesh):
            return sparse_tick(state, key, params)

    return jax.jit(fn, in_shardings=(sh, rep), out_shardings=(sh, None))


def make_sharded_sparse_run(mesh: Mesh, params, n_ticks: int):
    from .sparse import mesh_context, run_sparse_ticks

    _check_sparse_word_alignment(mesh, params)

    def fn(state, key, watch_rows=None):
        with mesh_context(mesh):
            return run_sparse_ticks(
                state, key, n_ticks, params, watch_rows=watch_rows
            )

    # donate the carried state like the single-device path — without it the
    # window holds input AND output state copies (38.7 GB extra at the
    # flagship shape's view plane)
    return jax.jit(fn, donate_argnums=0)


def pview_state_shardings(mesh: Mesh, dense_links: bool = False, delay_slots: int = 0):
    """PviewState-shaped pytree of NamedShardings (r17): every [N, ...]
    tensor row-sharded on the member axis; the [M]/[R] pool vectors, the
    scalar link model, and the [G, G] partition-cell loss replicated;
    [D, N, ...] pending rings sharded on dim 1. Same placement logic as
    the sparse engine — the pview tick's cross-shard traffic is the
    delivery gather (each receiver's elected senders' payload rows) and
    the table-merge scatters, which GSPMD lowers to collectives; the
    bounded pools need no communication at all. ``dense_links`` is
    accepted for seam parity and must be falsy (pview has no [N, N] link
    plane — by construction, ``forbid_wide_values``)."""
    from .pview import PviewState

    if dense_links:
        raise ValueError(
            "the pview engine has no [N, N] link plane (dense_links must "
            "be False/None)"
        )
    row = NamedSharding(mesh, P(MEMBER_AXIS))
    row2d = NamedSharding(mesh, P(MEMBER_AXIS, None))
    rep = NamedSharding(mesh, P())
    ring = NamedSharding(mesh, P(None, MEMBER_AXIS, None)) if delay_slots else rep
    return PviewState(
        tick=rep,
        up=row,
        epoch=row,
        joined_at=row,
        self_key=row,
        nbr_id=row2d,
        nbr_key=row2d,
        sus_key=row,
        sus_since=row,
        force_sync=row,
        leaving=row,
        mr_active=rep,
        mr_subject=rep,
        mr_key=rep,
        mr_created=rep,
        mr_origin=rep,
        minf_age=row2d,
        rumor_active=rep,
        rumor_origin=rep,
        rumor_created=rep,
        infected=row2d,
        infected_at=row2d,
        infected_from=row2d,
        loss=rep,
        delay_q=rep,
        part_id=row,
        part_loss=rep,
        pending_minf=ring,
        pending_inf=ring,
        pending_src=ring,
    )


def shard_pview_state(state, mesh: Mesh):
    """Place an existing (host/single-device) pview state onto the mesh."""
    return jax.device_put(
        state,
        pview_state_shardings(mesh, False, state.pending_minf.shape[0]),
    )


def member_mesh_size(mesh: Mesh) -> int:
    """The member-axis extent of ``mesh`` — ``mesh.size`` for a 1-D member
    mesh, the ``"members"`` component of a 2-D scenarios×members mesh.
    Meshes built from ``jax.devices()`` span EVERY process (the dcn
    ``global_mesh``), so this is the GLOBAL shard count the alignment
    rules bind against — never a per-host device count."""
    return dict(mesh.shape).get(MEMBER_AXIS, mesh.size)


def _check_pview_word_alignment(mesh: Mesh, params) -> None:
    """Pview-tick mesh preconditions: plain row divisibility always, and
    the 32-row word rule in every mode — the pview tick packs member-axis
    bit planes into u32 words unconditionally (the fd/suspicion masks,
    the delivery payload's user-rumor words, the r17 fused tick's packed
    membership-delivery planes), so row shards must stay word-aligned or
    GSPMD pads the word axis and the packed sweeps regress into
    per-phase all-gathers (the sparse builders' rule, applied to both
    key layouts). The divisor is the GLOBAL member-axis size: on a dcn
    multi-process mesh every host sees all processes' devices, so the
    rule binds the whole job's shard count, not one host's."""
    size = member_mesh_size(mesh)
    if params.capacity % size != 0:
        raise ValueError(
            f"capacity {params.capacity} not divisible by mesh size {size}"
        )
    if params.capacity % (32 * size) != 0:
        raise ValueError(
            f"capacity {params.capacity} must be divisible by 32 * mesh size "
            f"({32 * size}): the pview packed bit planes must align "
            "with the row shards (pad capacity up and leave the extra rows "
            "up=False — masks make padding free)"
        )


def _refuse_pallas_on_mesh(params) -> None:
    if getattr(params, "delivery_kernel", "xla") != "xla":
        raise ValueError(
            "delivery_kernel='pallas' is single-device — the mesh "
            "delivery path is the ragged all-to-all exchange "
            "(docs/SHARDING.md), which replaces the payload gather the "
            "kernel spells; use delivery_kernel='xla' on meshes"
        )


def make_sharded_pview_run(mesh: Mesh, params, n_ticks: int,
                           a2a_budget: int | None = None):
    """jit the batched ``run_pview_ticks`` window over ``mesh``, with the
    delivery step rewritten as the shard-local election + ragged
    all-to-all record exchange (r20, :mod:`.ragged_a2a`).

    Input state must already be placed via :func:`shard_pview_state`;
    GSPMD propagates the row sharding through the scan, and the
    :func:`~.pview.ragged_delivery_context` armed INSIDE the jitted
    closure (the sparse ``mesh_context`` precedent — the context must be
    active during tracing) swaps the global inverse-sender election for
    the member-axis exchange. ``a2a_budget`` overrides the per-(src, dst)
    record budget (None = the lossless default — bit-identical to the
    single-device trajectory); smaller budgets drop deterministically and
    surface the ``delivery_overflow`` metric. The carried state is
    donated like every window builder. The Pallas delivery kernel is
    single-device-only for now — refuse it up front rather than letting
    a whole-payload BlockSpec silently all-gather the table."""
    _check_pview_word_alignment(mesh, params)
    _refuse_pallas_on_mesh(params)
    from .pview import ragged_delivery_context, run_pview_ticks

    def fn(state, key, watch_rows=None):
        with ragged_delivery_context(mesh, MEMBER_AXIS, a2a_budget):
            return run_pview_ticks(
                state, key, n_ticks, params, watch_rows=watch_rows
            )

    return jax.jit(fn, donate_argnums=0)


def make_sharded_pview_adaptive_run(mesh: Mesh, params, n_ticks: int,
                                    a2a_budget: int | None = None):
    """Sharded adaptive pview window (r17 — the lift of the r14
    "adaptive is single-device for now" refusal, for this engine): the
    AdaptiveState's three [N] planes ride the donated carry row-sharded
    like every other member-axis tensor (place them with
    :func:`shard_adaptive_state`); argnums (0, 1) donated. Refuses a
    default spec (the legacy sharded window is the byte-identical
    program for that case). Delivery runs the r20 ragged exchange like
    :func:`make_sharded_pview_run`."""
    _check_pview_word_alignment(mesh, params)
    _refuse_pallas_on_mesh(params)
    if params.adaptive.is_default:
        raise ValueError(
            "make_sharded_pview_adaptive_run needs an enabled AdaptiveSpec "
            "on params — the default spec's program is "
            "make_sharded_pview_run's"
        )
    from .pview import ragged_delivery_context, run_pview_ticks_adaptive

    def fn(state, ad, key, watch_rows=None):
        with ragged_delivery_context(mesh, MEMBER_AXIS, a2a_budget):
            return run_pview_ticks_adaptive(
                state, ad, key, n_ticks, params, watch_rows=watch_rows
            )

    return jax.jit(fn, donate_argnums=(0, 1))


def make_sharded_pview_fused_run(mesh: Mesh, params, n_ticks: int,
                                 a2a_budget: int | None = None):
    """Sharded FUSED pview window (r20): the fused tick's delivery seam
    runs the same ragged exchange as the unfused sharded window (the
    pallas × mesh combination stays refused), so the sharded fused
    trajectory is bit-identical to single-device fused — which is itself
    bit-identical to unfused."""
    _check_pview_word_alignment(mesh, params)
    _refuse_pallas_on_mesh(params)
    from .pview import ragged_delivery_context, run_pview_ticks_fused

    def fn(state, key, watch_rows=None):
        with ragged_delivery_context(mesh, MEMBER_AXIS, a2a_budget):
            return run_pview_ticks_fused(
                state, key, n_ticks, params, watch_rows=watch_rows
            )

    return jax.jit(fn, donate_argnums=0)


def make_sharded_pview_traced_run(mesh: Mesh, params, n_ticks: int, trace,
                                  a2a_budget: int | None = None):
    """Sharded TRACE-ARMED pview window (r20 — the lift of the r14
    "trace capture is single-device for now" refusal, for this engine):
    the trace ring rides the donated carry REPLICATED (place it with
    :func:`place_replicated`; the ring append is a row-global gather of
    W tracer rows, which stays a cheap replicated update), while the
    member planes shard as usual and delivery runs the ragged exchange.
    Argnums (0, 2) donated — state and ring, the single-device traced
    window's exact discipline."""
    _check_pview_word_alignment(mesh, params)
    _refuse_pallas_on_mesh(params)
    from .pview import ragged_delivery_context, run_pview_ticks_traced

    def fn(state, key, trace_buf, trace_cursor, watch_rows=None):
        with ragged_delivery_context(mesh, MEMBER_AXIS, a2a_budget):
            return run_pview_ticks_traced(
                state, key, trace_buf, trace_cursor, n_ticks, params, trace,
                watch_rows=watch_rows,
            )

    return jax.jit(fn, donate_argnums=(0, 2))


def make_pview_mesh2d(n_scenarios: int, devices=None) -> Mesh:
    """A 2-D scenarios×members mesh (r20): the r15 fleet axis composed
    with the member axis. Scenarios are independent — the scenario axis
    carries ZERO collectives — and the ragged delivery all-to-all runs
    on the member axis only, so S_sc × S_m devices advance S_sc clusters
    of row-sharded members each in one XLA program."""
    from .fleet import FLEET_AXIS

    devices = list(devices) if devices is not None else jax.devices()
    if n_scenarios <= 0 or len(devices) % n_scenarios:
        raise ValueError(
            f"{len(devices)} devices do not factor into "
            f"{n_scenarios} scenario rows"
        )
    arr = np.asarray(devices).reshape(n_scenarios, len(devices) // n_scenarios)
    return Mesh(arr, (FLEET_AXIS, MEMBER_AXIS))


def shard_pview_fleet(fleet_state, mesh: Mesh):
    """Commit a stacked [S, ...] pview fleet onto a 2-D scenarios×members
    mesh: scenario axis on every leaf's dim 0, member axis where the
    serial placement (:func:`pview_state_shardings`) row-shards — dim 1
    for planes, dim 2 for the [D, N, ...] pending rings. Zero-size
    leaves replicate (the :func:`~.fleet.shard_fleet` rule)."""
    delay_slots = fleet_state.pending_minf.shape[1]
    base = pview_state_shardings(mesh, False, delay_slots)
    from .fleet import FLEET_AXIS

    def lift(x, sh):
        if not x.size:
            return jax.device_put(x, NamedSharding(mesh, P()))
        return jax.device_put(
            x, NamedSharding(mesh, P(FLEET_AXIS, *sh.spec))
        )

    return jax.tree.map(lift, fleet_state, base)


def make_sharded_pview_fleet_run(mesh: Mesh, params, n_ticks: int,
                                 a2a_budget: int | None = None):
    """Fleet window on the 2-D scenarios×members mesh (r20):
    ``jit(vmap(core, spmd_axis_name=FLEET_AXIS))`` over the ragged-armed
    window core. The vmap batch axis is bound to the scenario mesh axis,
    so the per-scenario shard_map partitions only the member axis — the
    scenario axis stays collective-free and each scenario's trajectory
    is bit-identical to its serial sharded run (the r15 fleet contract
    composed with the r20 sharding contract). Fleet state donated; place
    it with :func:`shard_pview_fleet`."""
    from .fleet import FLEET_AXIS

    shape = dict(mesh.shape)
    if FLEET_AXIS not in shape or MEMBER_AXIS not in shape:
        raise ValueError(
            "make_sharded_pview_fleet_run needs a 2-D scenarios×members "
            f"mesh (make_pview_mesh2d); got axes {tuple(shape)}"
        )
    _check_pview_word_alignment(mesh, params)
    _refuse_pallas_on_mesh(params)
    from .pview import ragged_delivery_context, run_pview_ticks

    def fn(fleet_state, keys):
        with ragged_delivery_context(mesh, MEMBER_AXIS, a2a_budget):
            run = partial(run_pview_ticks, n_ticks=n_ticks, params=params)
            return jax.vmap(run, spmd_axis_name=FLEET_AXIS)(fleet_state, keys)

    return jax.jit(fn, donate_argnums=0)


def shard_adaptive_state(ad, mesh: Mesh):
    """Place an AdaptiveState onto the mesh: all three planes are [N]
    member-axis tensors, so they row-shard like ``up``."""
    from ..adaptive import AdaptiveState

    row = NamedSharding(mesh, P(MEMBER_AXIS))
    return jax.device_put(ad, AdaptiveState(lh=row, conf_key=row, conf=row))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated placement on ``mesh`` — the home of every telemetry
    tensor (the [ring_len, n_metrics] metric ring, its append vectors, the
    staged per-window reductions). The ring is tiny and every window-summary
    reduction over sharded metrics comes out replicated under GSPMD, so an
    explicitly replicated ring keeps the append a collective-free local
    update on every chip instead of letting placement inference scatter it."""
    return NamedSharding(mesh, P())


def place_replicated(x, mesh: Mesh):
    """device_put onto the replicated sharding (telemetry ring placement)."""
    return jax.device_put(x, replicated_sharding(mesh))


def make_sharded_metric_append(mesh: Mesh):
    """Sharded twin of the donated MetricRing row append (r21).

    Same ``buf.at[idx].set(row)`` spelling as the single-device append, with
    every operand pinned to the replicated sharding so the update stays a
    collective-free local write on each chip — placement inference never
    gets a vote. The ring buffer is donated exactly like its single-device
    twin (the r12 audit matrix carries this program as
    ``sharded-telemetry-append`` and proves the alias + transfer-freeness
    statically)."""
    rep = replicated_sharding(mesh)
    return jax.jit(
        lambda buf, row, idx: buf.at[idx].set(row),
        donate_argnums=0,
        in_shardings=(rep, rep, rep),
        out_shardings=rep,
    )


def make_sharded_telemetry_row(mesh: Mesh, row_fn):
    """jit a telemetry row reduction with the output pinned replicated (r21).

    ``row_fn`` is the plane's row closure (engine window-vector + sentinel
    columns). Its inputs are whatever the sharded window produced — stacked
    per-tick metrics and the post-window state, in their GSPMD-chosen
    shardings; every reduction inside comes out replicated under GSPMD, and
    the explicit ``out_shardings`` pin makes that a checked contract instead
    of an inference accident, so the ring append that follows is local."""
    return jax.jit(row_fn, out_shardings=replicated_sharding(mesh))


def make_sharded_run(mesh: Mesh, params: SimParams, n_ticks: int, dense_links: bool = True):
    """jit the batched ``run_ticks`` window over ``mesh``.

    Input state must already be placed via :func:`shard_state`; GSPMD
    propagates the row sharding through the scan (stacked metrics and
    watched-row keys come out replicated/gathered as XLA chooses). The
    carried state is donated, like the sparse window builder — without it
    the window holds input AND output copies of every [N, N] plane."""
    _check_dense_word_alignment(mesh, params)
    return jax.jit(
        partial(run_ticks, n_ticks=n_ticks, params=params), donate_argnums=0
    )
