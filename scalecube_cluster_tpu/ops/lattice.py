"""The membership-record precedence lattice, vectorized.

Reference semantics (``cluster/membership/MembershipRecord.java:67-90``, and
the scalar oracle :func:`scalecube_cluster_tpu.models.record.overrides_codes`):

* identical records never override (idempotence);
* DEAD is absorbing — nothing overrides DEAD, DEAD overrides everything;
* otherwise higher incarnation wins;
* at equal incarnation, SUSPECT overrides ALIVE / LEAVING and nothing else.

To make the merge a **scatter-max reduction** (many senders may deliver
records for the same receiver row in one tick; the combiner must be
commutative + associative), each ``(status, incarnation)`` is packed into one
monotone int32 key::

    key = DEAD_KEY                    if status == DEAD
        = incarnation * 4 + rank      otherwise,
          rank: ALIVE -> 0, LEAVING -> 1, SUSPECT -> 2

``new overrides old  <=>  key(new) > key(old)`` — exactly the reference truth
table, with two deliberate, documented deviations forced by totalizing the
(partial) order:

1. At equal incarnation LEAVING gets rank 1 > ALIVE's 0, so a LEAVING
   candidate beats a same-incarnation ALIVE record. In the reference neither
   overrides the other; since LEAVING is only ever self-announced, the
   conflicting pair originates from the same node and LEAVING is strictly the
   newer fact, so resolving toward LEAVING is the faithful choice.
2. A DEAD record never overrides an existing DEAD record regardless of
   incarnation (reference: same — DEAD is terminal), so DEAD keys carry no
   incarnation; on accepting DEAD the receiver keeps its previously-known
   incarnation.

The "no record yet" case (reference: only ALIVE/LEAVING accepted against an
absent record) is NOT part of the key: unknown entries get key ``-1`` and a
separate accept gate blocks SUSPECT/DEAD candidates for unknown members
(see ``tick._merge``).

Incarnations must stay below ``2**28`` to fit the packing; they only grow by
refutations/metadata bumps, so this is never a practical limit.
"""

from __future__ import annotations

import jax.numpy as jnp

# Status codes (match models.member.MemberStatus + kernel-internal UNKNOWN).
ALIVE = 0
SUSPECT = 1
LEAVING = 2
DEAD = 3
UNKNOWN = 4  # kernel-internal: "I have no record for this member"

DEAD_KEY = jnp.int32(1 << 30)
UNKNOWN_KEY = jnp.int32(-1)
NO_CANDIDATE = jnp.iinfo(jnp.int32).min  # scatter-max identity

# rank lookup by status code: ALIVE->0, SUSPECT->2, LEAVING->1 (DEAD/UNKNOWN
# handled separately but given harmless entries).
_RANK = jnp.array([0, 2, 1, 0, 0], dtype=jnp.int32)
# status lookup by rank: 0->ALIVE, 1->LEAVING, 2->SUSPECT
_RANK_TO_STATUS = jnp.array([ALIVE, LEAVING, SUSPECT, ALIVE], dtype=jnp.int8)


def precedence_key(status: jnp.ndarray, incarnation: jnp.ndarray) -> jnp.ndarray:
    """Pack (status, incarnation) into the monotone int32 precedence key.

    UNKNOWN entries map to ``UNKNOWN_KEY`` (-1) so any known record beats
    them (the ALIVE/LEAVING-only gate is applied separately).
    """
    status = status.astype(jnp.int32)
    live_key = incarnation.astype(jnp.int32) * 4 + _RANK[status]
    key = jnp.where(status == DEAD, DEAD_KEY, live_key)
    return jnp.where(status == UNKNOWN, UNKNOWN_KEY, key)


def decode_key(
    key: jnp.ndarray, old_inc: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Unpack a winning candidate key back to ``(status, incarnation)``.

    DEAD keys carry no incarnation — the receiver keeps ``old_inc``
    (deviation 2 in the module docstring).
    """
    is_dead = key == DEAD_KEY
    inc = jnp.where(is_dead, old_inc, key >> 2)
    status = jnp.where(
        is_dead, jnp.int8(DEAD), _RANK_TO_STATUS[(key & 3).astype(jnp.int32)]
    )
    return status, inc.astype(jnp.int32)
