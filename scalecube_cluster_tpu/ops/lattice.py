"""The membership-record precedence lattice, vectorized.

Reference semantics (``cluster/membership/MembershipRecord.java:67-90``, and
the scalar oracle :func:`scalecube_cluster_tpu.models.record.overrides_codes`):

* identical records never override (idempotence);
* DEAD is absorbing — nothing overrides DEAD, DEAD overrides everything;
* otherwise higher incarnation wins;
* at equal incarnation, SUSPECT overrides ALIVE / LEAVING and nothing else.

To make the merge a **scatter-max reduction** (many senders may deliver
records for the same receiver row in one tick; the combiner must be
commutative + associative), each ``(epoch, status, incarnation)`` is packed
into one monotone int32 key::

    key = epoch << 23 | incarnation << 2 | rank
    rank: ALIVE -> 0, LEAVING -> 1, SUSPECT -> 2, DEAD -> 3

The **epoch** (8 bits, high) is the row's identity generation: a crashed
row reused by a fresh joiner gets ``epoch+1`` (``state.join_row``). Because
epoch occupies the top bits, every record of the new identity strictly
dominates every record (even DEAD tombstones) of the old one — which is the
sim's vectorized DEST_GONE: the reference answers a probe for a restarted
member with AckType.DEST_GONE and the prober deletes the old identity
(``FailureDetectorImpl.computeMemberStatus:382-404``,
``onPing:227-259``); here the probe ACK (and any gossip/SYNC) carries the
target's current self key, whose higher epoch overrides the stale record in
one step, and the host driver maps the epoch flip to REMOVED(old identity) +
ADDED(new identity) events — the reference's net outcome (restart = new
member id, old one dead). Epoch wraps at 256 reuses of one row; the driver
prefers forgotten rows precisely so a row is never re-occupied while live
peers still hold near-wrap records.

``new overrides old  <=>  key(new) > key(old)`` — the reference truth table
with three deliberate, documented deviations forced by totalizing the
(partial) order over a table that, unlike the reference's, holds DEAD
tombstones:

1. At equal incarnation LEAVING (rank 1) beats ALIVE (rank 0); in the
   reference neither overrides the other. LEAVING is only ever
   self-announced, so the conflicting pair comes from the same node and
   LEAVING is strictly the newer fact.
2. **DEAD is absorbing per incarnation, not absolutely.** ``DEAD@i`` beats
   every status at incarnation ``<= i`` but loses to any record with a
   higher incarnation. The reference's DEAD is absolute — but its tables
   never *hold* DEAD (the member is removed on the spot,
   ``onDeadMemberDetected:740-767``) and its gossip layer dedups each death
   rumor per receiver (``SequenceIdCollector``), so each node processes a
   given death exactly once. This kernel keeps DEAD in the table for the
   rumor-spread window instead; were DEAD absolute, a refuting node
   (``onSelfMemberDetected`` bumps incarnation past the rumor) could chase
   its own death rumor in sustained reinfection waves — absorbing-per-
   incarnation makes the refuted ``ALIVE@i+1`` dominate everywhere, exactly
   the reference's net outcome (death processed once, refutation wins).
3. A stale ``DEAD@i`` does NOT override records at incarnation ``> i``
   (consequence of 2); the reference would remove-and-readd instead.

The "no record yet" case (reference: only ALIVE/LEAVING accepted against an
absent record) is NOT part of the key: unknown entries get key ``-1`` and a
separate accept gate blocks SUSPECT/DEAD candidates for unknown members
(see the merge-accept gates in ``kernel``'s gossip/SYNC phases).

Incarnations must stay below ``2**21`` to fit the packing; they only grow by
refutations/metadata bumps, so this is never a practical limit.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

# Status codes (match models.member.MemberStatus + kernel-internal UNKNOWN).
ALIVE = 0
SUSPECT = 1
LEAVING = 2
DEAD = 3
UNKNOWN = 4  # kernel-internal: "I have no record for this member"

# Host-side python ints (NOT jnp scalars: a module-level jnp constant would
# initialize an XLA backend at import time, which breaks multi-process
# workers that must call jax.distributed.initialize first — see ops.dcn).
UNKNOWN_KEY = -1
# Wide-layout scatter-max identity (python int). Dtype-generic call
# sites use :func:`no_candidate` instead (i16 keys use int16 min).
NO_CANDIDATE = jnp.iinfo(jnp.int32).min

# Ranks inside the packed key (key & 3). Note -1 (UNKNOWN_KEY) & 3 == 3, so
# rank tests against ALIVE/LEAVING/SUSPECT are safe without a key >= 0 guard;
# only DEAD tests must also check key >= 0.
RANK_ALIVE = 0
RANK_LEAVING = 1
RANK_SUSPECT = 2
RANK_DEAD = 3

# Bit layout (wide / i32 keys): rank [0:2), incarnation [2:23), epoch [23:31).
INC_BITS = 21
EPOCH_SHIFT = 2 + INC_BITS
INC_MASK = (1 << INC_BITS) - 1
EPOCH_MASK = 0xFF


import dataclasses as _dc


@_dc.dataclass(frozen=True)
class KeyLayout:
    """Bit layout of one packed precedence-key dtype.

    r9 adds a NARROW (int16) key: ``epoch << 11 | incarnation << 2 | rank``
    — 2 bytes/cell instead of 4 on the dominant [N, N] plane (the dense
    tick is bandwidth-bound; see ops/bitplane.py). The narrowing rule,
    enforced at every key-construction site:

    * **incarnation saturates** at ``inc_mask`` (511 for i16): refutation /
      metadata bumps use :func:`bump_inc`, which clamps instead of carrying
      into the epoch bits. Saturation keeps the lattice monotone (a key
      never regresses) at the cost of refutations past the cap no longer
      out-ranking the matching SUSPECT — 511 suspicion episodes of one
      member inside one identity epoch, far outside any bench or chaos
      scenario, and a documented reason to run ``plane_dtype="i32"``.
    * **epoch folds** to ``epoch_mask`` (mod 16 for i16, mod 256 for i32):
      row-reuse generations wrap sooner, so the driver's prefer-forgotten-
      rows policy carries more of the aliasing burden (same rule as the
      i32 wrap at 256, just a shorter cycle — see ``state.join_row``).

    While every incarnation stays below the cap and every row is reused
    fewer than ``epoch_mask + 1`` times, the narrow key's DECODED
    (status, incarnation, epoch) trajectory is bit-identical to the wide
    key's — the packed-vs-unpacked lockstep contract r9's tests pin.
    """

    inc_bits: int
    epoch_bits: int

    @property
    def epoch_shift(self) -> int:
        return 2 + self.inc_bits

    @property
    def inc_mask(self) -> int:
        return (1 << self.inc_bits) - 1

    @property
    def epoch_mask(self) -> int:
        return (1 << self.epoch_bits) - 1


#: wide layout (int32): the r0-r8 layout, the oracle-lockstep default.
LAYOUT_I32 = KeyLayout(inc_bits=INC_BITS, epoch_bits=8)
#: narrow layout (int16): rank [0:2), incarnation [2:11), epoch [11:15).
LAYOUT_I16 = KeyLayout(inc_bits=9, epoch_bits=4)

#: SimParams.key_dtype / SimConfig.plane_dtype spellings -> (np dtype, layout)
KEY_DTYPES = {"i32": _np.int32, "i16": _np.int16}
_LAYOUTS = {"i32": LAYOUT_I32, "i16": LAYOUT_I16}


def layout_for(dtype) -> KeyLayout:
    """KeyLayout for a key array/dtype (i16 -> narrow, anything else wide)."""
    if _np.dtype(dtype) == _np.int16:
        return LAYOUT_I16
    return LAYOUT_I32


def layout_of(name: str) -> KeyLayout:
    """KeyLayout for a config spelling ("i32" / "i16")."""
    return _LAYOUTS[name]


def key_np_dtype(name: str):
    if name not in KEY_DTYPES:
        raise ValueError(f"key dtype must be one of {sorted(KEY_DTYPES)}, got {name!r}")
    return KEY_DTYPES[name]


def no_candidate(dtype) -> int:
    """Scatter-max identity for a key dtype (its most negative value)."""
    return int(_np.iinfo(_np.dtype(dtype)).min)

# rank lookup by status code: ALIVE->0, SUSPECT->2, LEAVING->1, DEAD->3
# (numpy at module scope — converted to device constants inside the jitted
# functions — so importing this module never touches an XLA backend)
_RANK = _np.array([0, 2, 1, 3, 0], dtype=_np.int32)
# status lookup by rank: 0->ALIVE, 1->LEAVING, 2->SUSPECT, 3->DEAD
_RANK_TO_STATUS = _np.array([ALIVE, LEAVING, SUSPECT, DEAD], dtype=_np.int8)


def precedence_key(
    status: jnp.ndarray,
    incarnation: jnp.ndarray,
    epoch: jnp.ndarray | int = 0,
    dtype=jnp.int32,
) -> jnp.ndarray:
    """Pack (status, incarnation[, epoch]) into the monotone key of
    ``dtype`` (int32 wide / int16 narrow — see :class:`KeyLayout` for the
    narrow saturation + fold rule, applied here at the one packing site).

    UNKNOWN entries map to ``UNKNOWN_KEY`` (-1) so any known record beats
    them (the ALIVE/LEAVING-only gate is applied separately).
    """
    lay = layout_for(dtype)
    status = status.astype(jnp.int32)
    inc = jnp.minimum(incarnation.astype(jnp.int32), lay.inc_mask)
    key = (
        ((jnp.int32(epoch) & lay.epoch_mask) << lay.epoch_shift)
        | (inc << 2)
        | jnp.asarray(_RANK)[status]
    )
    return jnp.where(status == UNKNOWN, UNKNOWN_KEY, key).astype(dtype)


def bump_inc(key: jnp.ndarray, rank) -> jnp.ndarray:
    """Incarnation+1 at the same epoch with the given rank — the refutation
    / metadata-update bump, SATURATING at the layout's incarnation cap so a
    narrow key can never carry into its epoch bits (a carry would
    impersonate the row's next identity). Identical to the historical
    ``((key >> 2) + 1) << 2 | rank`` everywhere below the cap."""
    lay = layout_for(key.dtype)
    inc = jnp.minimum(((key >> 2) & lay.inc_mask) + 1, lay.inc_mask)
    epoch_bits = (key >> lay.epoch_shift) << lay.epoch_shift
    return (epoch_bits | (inc << 2) | rank).astype(key.dtype)


def decode_key(key: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Unpack a winning candidate key back to ``(status, incarnation)``."""
    lay = layout_for(key.dtype)
    status = jnp.asarray(_RANK_TO_STATUS)[(key & 3).astype(jnp.int32)]
    return status, ((key >> 2) & lay.inc_mask).astype(jnp.int32)


def key_status(key: jnp.ndarray) -> jnp.ndarray:
    """Status code of a packed table key; UNKNOWN where no record (key < 0)."""
    return jnp.where(
        key < 0, jnp.int8(UNKNOWN), jnp.asarray(_RANK_TO_STATUS)[(key & 3).astype(jnp.int32)]
    )


def key_inc(key: jnp.ndarray) -> jnp.ndarray:
    """Incarnation of a packed table key; 0 where no record. Layout follows
    the key dtype (narrow int16 keys decode with the narrow masks)."""
    lay = layout_for(key.dtype)
    return jnp.where(key < 0, 0, (key >> 2) & lay.inc_mask).astype(jnp.int32)


def key_epoch(key: jnp.ndarray) -> jnp.ndarray:
    """Identity epoch of a packed table key; 0 where no record."""
    lay = layout_for(key.dtype)
    return jnp.where(
        key < 0, 0, (key >> lay.epoch_shift) & lay.epoch_mask
    ).astype(jnp.int32)
