"""Scalar (per-node-loop NumPy) oracle of the PARTIAL-VIEW tick semantics.

Mirror of :mod:`.pview` the way :mod:`.sparse_oracle` mirrors :mod:`.sparse`
(SURVEY.md §4's lockstep-equivalence strategy): per-node Python loops
consuming byte-identical draws from :func:`.rand.draw_sparse_randoms` —
the pview engine deliberately consumes the sparse draw layout, interpreted
as active-slot indexes — and the equivalence suite steps both and compares
the full state every tick. All float comparisons replay the kernel's
float32 op order; all tie-breaking (first rejection try, lowest slot,
lowest pool column, step-order top-P insertion, highest-row/slot collision
winner) is mirrored exactly.
"""

from __future__ import annotations

import numpy as np

from .. import adaptive as _adp
from ..dissemination import strategies as _dz
from .lattice import RANK_ALIVE, RANK_DEAD, RANK_LEAVING, RANK_SUSPECT
from .rand import (
    SALT_GOSSIP,
    SALT_SYNC_ACK,
    SALT_SYNC_REQ,
    draw_sparse_randoms,
    fetch_uniform,
)
from .pview import PviewParams, PviewState

NO_CAND = np.iinfo(np.int32).min
NEVER = -(1 << 30)

_FIELDS = (
    "up", "epoch", "joined_at", "self_key", "nbr_id", "nbr_key", "sus_key",
    "sus_since", "force_sync", "leaving", "mr_active", "mr_subject", "mr_key",
    "mr_created", "mr_origin", "minf_age", "rumor_active", "rumor_origin",
    "rumor_created", "infected", "infected_at", "infected_from", "loss",
    "delay_q", "part_id", "part_loss", "pending_minf", "pending_inf",
    "pending_src",
)


class _PO:
    """Mutable numpy mirror of PviewState."""

    def __init__(self, state: PviewState):
        self.tick = int(state.tick)
        for name in _FIELDS:
            setattr(self, name, np.asarray(getattr(state, name)).copy())

    def snap(self):
        import copy

        return copy.deepcopy(self)

    def key_i32(self, i: int, s: int) -> int:
        return int(np.int32(self.nbr_key[i, s]))


def _loss(o, i, j):
    base = np.float32(o.loss)
    part = np.float32(o.part_loss[int(o.part_id[i]), int(o.part_id[j])])
    return max(base, part)


def _rt(o, i, j):
    return np.float32(
        (np.float32(1.0) - _loss(o, i, j)) * (np.float32(1.0) - _loss(o, j, i))
    )


def _timely(q1, q2, t: int) -> np.float32:
    q1, q2 = np.float32(q1), np.float32(q2)
    h = np.float32(1.0)
    acc = np.float32(1.0)
    q2p = np.float32(1.0)
    for _ in range(t):
        q2p = np.float32(q2p * q2)
        h = np.float32(np.float32(q1 * h) + q2p)
        acc = np.float32(acc + h)
    return np.float32(np.float32((np.float32(1.0) - q1) * (np.float32(1.0) - q2)) * acc)


def _rt_timely(o, i, j, t, D):
    p = _rt(o, i, j)
    if D:
        q = np.float32(o.delay_q)
        p = np.float32(p * _timely(q, q, t))
    return p


def _pick_slots(o, row: int, u: np.ndarray, n_picks: int, tries: int, ka: int):
    """Mirror of ``pview._sample_slots`` for one row: first valid try wins;
    slot distinctness (== member distinctness by the table invariant)."""
    sels: list[int] = []
    for p in range(n_picks):
        sel = -1
        for t in range(tries):
            c = min(int(np.float32(np.float32(u[p * tries + t]) * np.float32(ka))), ka - 1)
            ok = int(o.nbr_id[row, c]) >= 0 and (o.key_i32(row, c) & 3) != RANK_DEAD
            ok = ok and all(c != q for q in sels)
            if sel < 0 and ok:
                sel = c
        sels.append(sel)
    slots = [max(s, 0) for s in sels]
    members = [max(int(o.nbr_id[row, s]), 0) for s in slots]
    valid = [s >= 0 for s in sels]
    return slots, members, valid


def _fetch_ok(o, salt: int, i: int, j: int) -> bool:
    u = np.float32(fetch_uniform(o.tick, salt, i, j, xp=np))
    return bool(o.up[j]) and bool(u < _rt(o, i, j))


class _SusBatch:
    """The kernel registers suspicion episodes per phase as ONE scatter-max
    against the pre-phase ``sus_key`` (stamps move only when the max rises).
    This mirrors that batch semantics."""

    def __init__(self, n: int):
        self.cand = np.full(n, NO_CAND, np.int64)

    def add(self, subj: int, key: int) -> None:
        self.cand[subj] = max(self.cand[subj], key)

    def commit(self, o) -> None:
        for j in range(len(self.cand)):
            if self.cand[j] > int(o.sus_key[j]):
                o.sus_key[j] = self.cand[j]
                o.sus_since[j] = o.tick


def _apply_record_b(o, i, subj, cand, salt, ka, sus: _SusBatch):
    k = o.nbr_id.shape[1]
    if subj < 0:
        return False
    if subj == i:
        own = int(o.self_key[i])
        slot_kind = "self"
    else:
        slot_p = next((s for s in range(k) if int(o.nbr_id[i, s]) == subj), None)
        own = o.key_i32(i, slot_p) if slot_p is not None else -1
        slot_kind = slot_p
    if cand <= own:
        return False
    if own < 0 and (cand & 3) > RANK_LEAVING:
        return False
    if (cand & 3) == RANK_ALIVE and not _fetch_ok(o, salt, i, subj):
        return False
    if slot_kind == "self":
        o.self_key[i] = cand
    else:
        if slot_kind is not None:
            w = slot_kind
        else:
            empties = [s for s in range(k) if int(o.nbr_id[i, s]) < 0]
            if empties:
                w = empties[0]
            else:
                p_keys = [o.key_i32(i, s) for s in range(ka, k)]
                w = ka + int(np.argmin(np.asarray(p_keys, np.int64)))
            o.nbr_id[i, w] = subj
        o.nbr_key[i, w] = o.nbr_key.dtype.type(cand)
    if (cand & 3) == RANK_SUSPECT:
        sus.add(subj, cand)
    return True


def pview_oracle_tick(state: PviewState, key, params: PviewParams,
                      ad=None) -> _PO:
    """``ad`` (r14) is a dict ``{"lh", "conf_key", "conf"}`` of [N] int32
    numpy arrays mirroring :class:`..adaptive.AdaptiveState`; the folded
    next state comes back as ``o.ad`` (see ``oracle.oracle_tick``)."""
    n = params.capacity
    f, k_req, T = params.fanout, params.ping_req_k, params.sample_tries
    M, R = params.mr_pool, params.rumor_slots
    D = params.delay_slots
    k = params.view_slots
    ka = params.active_slots
    P = params.sync_announce
    spread = params.spread_ticks
    o = _PO(state)
    o.tick += 1
    t = o.tick
    r = draw_sparse_randoms(key, n, f, k_req, T)
    r = {name: np.asarray(getattr(r, name)) for name in r._fields}

    armed = ad is not None
    if armed:
        aspec = params.adaptive
        ad_miss = np.zeros(n, bool)
        ad_succ = np.zeros(n, bool)
        ad_refuted = np.zeros(n, bool)
        ad_cnt = np.zeros(n, np.int64)
        ad_keym = np.full(n, NO_CAND, np.int64)

        def _ad_note(j: int, cand: int) -> None:
            if (cand & 3) == RANK_SUSPECT:
                ad_cnt[j] += 1
                ad_keym[j] = max(ad_keym[j], cand)

    proposals: list[tuple[list, list, list, list]] = []

    # ---- FD phase ----
    fd_props = ([0] * n, [0] * n, list(range(n)), [False] * n)
    if (t % params.fd_every) == 0:
        pre = o.snap()
        sus = _SusBatch(n)
        V_fd = min(n, params.fd_accept_slots or max(64, n // 16))
        accepted_so_far = 0
        for i in range(n):
            slots, members, valid = _pick_slots(pre, i, r["fd_try"][i], 1 + k_req, T, ka)
            if not (valid[0] and pre.up[i]):
                continue
            tgt_slot, tgt = slots[0], members[0]
            t_dir = params.fd_direct_timeout_ticks
            if armed:
                t_dir = t_dir * (1 + int(ad["lh"][i]))
            p_direct = _rt_timely(pre, i, tgt, t_dir, D)
            ack = bool(pre.up[tgt]) and bool(r["fd_direct"][i] < p_direct)
            for s in range(k_req):
                if ack:
                    break
                if not valid[1 + s]:
                    continue
                rl = members[1 + s]
                p4 = np.float32(_rt(pre, i, rl) * _rt(pre, rl, tgt))
                if D:
                    q = np.float32(pre.delay_q)
                    p4 = np.float32(p4 * _timely(q, q, params.fd_leg_timeout_ticks))
                    p4 = np.float32(p4 * _timely(q, q, params.fd_leg_timeout_ticks))
                if pre.up[rl] and pre.up[tgt] and r["fd_relay"][i, s] < p4:
                    ack = True
            own = pre.key_i32(i, tgt_slot)
            if ack:
                cand = (int(pre.self_key[tgt]) >> 2) << 2
            else:
                cand = ((own >> 2) << 2) | RANK_SUSPECT
            if armed:
                ad_miss[i] = not ack
                ad_succ[i] = bool(ack)
            if cand > own:
                accepted_so_far += 1
                if accepted_so_far > V_fd:
                    continue
                o.nbr_key[i, tgt_slot] = o.nbr_key.dtype.type(cand)
                fd_props[0][i] = tgt
                fd_props[1][i] = cand
                fd_props[3][i] = True
                if not ack:
                    sus.add(tgt, cand)
                    if armed:
                        _ad_note(tgt, cand)
        sus.commit(o)
    proposals.append(fd_props)

    # ---- maintenance sweep: suspicion expiry + active-view promotion ----
    exp_props = ([0] * n, [0] * n, list(range(n)), [False] * n)
    if (t % params.sweep_every) == 0:
        if bool((o.sus_since > NEVER).any()):
            timeout = params.suspicion_timeout_ticks
            base0 = params.log2n * params.fd_every

            def _timeout_of(i: int, subj: int, kij: int) -> int:
                if not armed:
                    return timeout
                L = aspec.levels
                in_ep = kij <= int(ad["conf_key"][subj])
                num = (
                    _adp.conf_mult_num_scalar(aspec, int(ad["conf"][subj]))
                    if in_ep
                    else aspec.max_mult * L
                )
                return (base0 * num * (1 + int(ad["lh"][i]))) // L

            expired = np.zeros((n, k), bool)
            for i in range(n):
                if not o.up[i]:
                    continue
                for s in range(k):
                    subj = int(o.nbr_id[i, s])
                    if subj < 0:
                        continue
                    kij = o.key_i32(i, s)
                    if (
                        (kij & 3) == RANK_SUSPECT
                        and t - int(o.sus_since[subj]) >= _timeout_of(i, subj, kij)
                        and kij <= int(o.sus_key[subj])
                    ):
                        expired[i, s] = True
            # per-subject announcer election: lowest expiring observer row
            first_row: dict[int, int] = {}
            for i in range(n):
                for s in range(k):
                    if expired[i, s]:
                        subj = int(o.nbr_id[i, s])
                        first_row.setdefault(subj, i)
            for i in range(n):
                for s in range(k):
                    if not expired[i, s]:
                        continue
                    subj = int(o.nbr_id[i, s])
                    o.nbr_key[i, s] = o.nbr_key.dtype.type(o.key_i32(i, s) + 1)
                    if not exp_props[3][i] and first_row.get(subj) == i:
                        exp_props[0][i] = subj
                        exp_props[1][i] = o.key_i32(i, s)
                        exp_props[3][i] = True
            # self expiry (never announces — deviation P7)
            for i in range(n):
                sk = int(o.self_key[i])
                if (
                    o.up[i]
                    and (sk & 3) == RANK_SUSPECT
                    and t - int(o.sus_since[i]) >= _timeout_of(i, i, sk)
                    and sk <= int(o.sus_key[i])
                ):
                    o.self_key[i] = sk + 1
            any_suspect_left = any(
                o.up[i]
                and (
                    (int(o.self_key[i]) & 3) == RANK_SUSPECT
                    or any(
                        int(o.nbr_id[i, s]) >= 0
                        and (o.key_i32(i, s) & 3) == RANK_SUSPECT
                        for s in range(k)
                    )
                )
                for i in range(n)
            )
            if not any_suspect_left:
                o.sus_key[:] = NO_CAND
                o.sus_since[:] = NEVER
        # tombstone purge (deviation P8): every purge_sweeps-th sweep,
        # forget every DEAD table entry (engine order: expire → purge →
        # promote, so a same-sweep expiry is purged too — its announcement
        # proposal was already captured)
        if ((t // params.sweep_every) % params.purge_sweeps) == 0:
            for i in range(n):
                for s in range(k):
                    if int(o.nbr_id[i, s]) >= 0 and (o.key_i32(i, s) & 3) == RANK_DEAD:
                        o.nbr_id[i, s] = -1
                        o.nbr_key[i, s] = o.nbr_key.dtype.type(-1)
        # promotion sweep: ascending active slots swap in the best live
        # passive entry when empty/DEAD
        for i in range(n):
            for a in range(ka):
                a_id = int(o.nbr_id[i, a])
                a_key = o.key_i32(i, a)
                bad = a_id < 0 or (a_key & 3) == RANK_DEAD
                if not bad:
                    continue
                best, best_key = None, NO_CAND
                for s in range(ka, k):
                    if int(o.nbr_id[i, s]) < 0:
                        continue
                    skey = o.key_i32(i, s)
                    if (skey & 3) == RANK_DEAD:
                        continue
                    if skey > best_key:
                        best, best_key = s, skey
                if best is None:
                    continue
                o.nbr_id[i, a], o.nbr_id[i, best] = o.nbr_id[i, best], o.nbr_id[i, a]
                o.nbr_key[i, a], o.nbr_key[i, best] = (
                    o.nbr_key[i, best], o.nbr_key[i, a],
                )
    proposals.append(exp_props)

    # ---- gossip phase ----
    slot_now = t % D if D else 0
    work = bool(o.rumor_active.any()) or bool(o.mr_active.any())
    if D:
        work = work or bool(o.pending_inf[slot_now].any()) or bool(
            o.pending_minf[slot_now].any()
        )
    if work:
        age = o.minf_age
        mr_any = bool(o.mr_active.any()) or (
            D and bool(o.pending_minf[slot_now].any())
        )
        if mr_any:
            o.minf_age = np.where(
                age > 0, np.minimum(age, np.uint8(254)) + np.uint8(1), age
            ).astype(np.uint8)
        pre = o.snap()
        recv_u = pre.pending_inf[slot_now].copy() if D else np.zeros((n, R), bool)
        recv_src = (
            pre.pending_src[slot_now].copy() if D else np.full((n, R), -1, np.int32)
        )
        recv_m = pre.pending_minf[slot_now].copy() if D else np.zeros((n, M), bool)
        young_u = np.zeros((n, R), bool)
        young_m = np.zeros((n, M), bool)
        peers_all = np.zeros((n, f), np.int32)
        valid_all = np.zeros((n, f), bool)
        spec = params.dissem
        for i in range(n):
            if spec.uniform_selection:
                _s, peers_all[i], valid_all[i] = _pick_slots(
                    pre, i, r["gossip_try"][i], f, T, ka
                )
            else:
                peers_all[i], valid_all[i] = _dz.structured_peer_row(
                    spec, n, t, i, r["gossip_try"][i][::T]
                )
            for ru in range(R):
                young_u[i, ru] = (
                    pre.infected[i, ru]
                    and pre.rumor_active[ru]
                    and t - int(pre.infected_at[i, ru]) < spread
                    # r13 pipelined payload budget (DZ-3)
                    and _dz.budget_ok(spec, ru, t, R)
                )
            if mr_any:
                for mm in range(M):
                    young_m[i, mm] = (
                        pre.mr_active[mm]
                        and 0 < int(pre.minf_age[i, mm]) <= spread
                    )
        sender_has = young_u.any(axis=1) | young_m.any(axis=1)
        for s in range(f):
            inv_now = np.full(n, -1, np.int32)
            inv_late = np.full(n, -1, np.int32)
            d_of = np.zeros(n, np.int32)
            for j in range(n):
                if not (valid_all[j, s] and sender_has[j] and pre.up[j]):
                    continue
                p = int(peers_all[j, s])
                if not pre.up[p]:
                    continue
                if not bool(
                    r["gossip_edge"][j, s] < (np.float32(1.0) - _loss(pre, j, p))
                ):
                    continue
                dd = 0
                if D:
                    qd = np.float32(pre.delay_q)
                    qpow = qd
                    for _ in range(1, D):
                        if r["gossip_delay"][j, s] < qpow:
                            dd += 1
                        qpow = np.float32(qpow * qd)
                d_of[j] = dd
                if dd == 0:
                    inv_now[p] = max(inv_now[p], j)
                else:
                    inv_late[p] = max(inv_late[p], j)
                if spec.wants_pull and dd == 0:
                    # push-pull reply (pview.py DZ-2 mirror): the peer the
                    # undelayed contact reached answers with ITS payload,
                    # gated on the reverse-link hashed draw
                    rev = np.float32(
                        fetch_uniform(t, _dz.pull_salt(s), j, p, xp=np)
                    )
                    if rev < (np.float32(1.0) - _loss(pre, p, j)):
                        for ru in range(R):
                            if (
                                young_u[p, ru]
                                and int(pre.infected_from[p, ru]) != j
                                and int(pre.rumor_origin[ru]) != j
                            ):
                                recv_u[j, ru] = True
                                recv_src[j, ru] = max(int(recv_src[j, ru]), p)
                        for mm in range(M):
                            if young_m[p, mm] and int(pre.mr_origin[mm]) != j:
                                recv_m[j, mm] = True
            for i in range(n):
                j = int(inv_now[i])
                if j >= 0:
                    for ru in range(R):
                        if (
                            young_u[j, ru]
                            and int(pre.infected_from[j, ru]) != i
                            and int(pre.rumor_origin[ru]) != i
                        ):
                            recv_u[i, ru] = True
                            recv_src[i, ru] = max(int(recv_src[i, ru]), j)
                    for mm in range(M):
                        if young_m[j, mm] and int(pre.mr_origin[mm]) != i:
                            recv_m[i, mm] = True
                jl = int(inv_late[i])
                if jl >= 0:
                    sd = (t + int(d_of[jl])) % D
                    for ru in range(R):
                        if (
                            young_u[jl, ru]
                            and int(pre.infected_from[jl, ru]) != i
                            and int(pre.rumor_origin[ru]) != i
                        ):
                            o.pending_inf[sd, i, ru] = True
                            o.pending_src[sd, i, ru] = max(
                                int(o.pending_src[sd, i, ru]), jl
                            )
                    for mm in range(M):
                        if young_m[jl, mm] and int(pre.mr_origin[mm]) != i:
                            o.pending_minf[sd, i, mm] = True

        for i in range(n):
            if not pre.up[i]:
                continue
            for ru in range(R):
                if recv_u[i, ru] and pre.rumor_active[ru] and not pre.infected[i, ru]:
                    o.infected[i, ru] = True
                    o.infected_at[i, ru] = t
                    o.infected_from[i, ru] = recv_src[i, ru]

        # membership-rumor apply, capped at A per receiver (deviation P5):
        # pass a takes each row's LOWEST still-eligible pool slot; the pick
        # is marked delivered (minf_age = 1) whether or not the record is
        # accepted; gates read the CURRENT (carry) tables.
        if mr_any:
            A = params.apply_slots
            eligible = {
                i: [
                    mm
                    for mm in range(M)
                    if recv_m[i, mm]
                    and int(pre.mr_origin[mm]) != i
                    and int(o.minf_age[i, mm]) == 0
                    and o.up[i]
                    and pre.mr_active[mm]
                ]
                for i in range(n)
            }
            for a in range(A):
                sus = _SusBatch(n)
                for i in range(n):
                    if a >= len(eligible[i]):
                        continue
                    mm = eligible[i][a]
                    o.minf_age[i, mm] = 1
                    subj_m = int(pre.mr_subject[mm])
                    cand_m = int(pre.mr_key[mm])
                    if _apply_record_b(
                        o, i, subj_m, cand_m, SALT_GOSSIP, ka, sus,
                    ) and armed:
                        _ad_note(subj_m, cand_m)
                sus.commit(o)
        if D:
            o.pending_inf[slot_now] = False
            o.pending_src[slot_now] = -1
            o.pending_minf[slot_now] = False

    # ---- SYNC phase ----
    pre = o.snap()
    K = min(n, params.sync_slots or (n // params.sync_every + 32))
    due_force = [i for i in range(n) if pre.up[i] and bool(pre.force_sync[i])]
    due_periodic = [
        i
        for i in range(n)
        if pre.up[i]
        and not bool(pre.force_sync[i])
        and ((t + i * params.sync_stagger) % params.sync_every) == 0
    ]
    due_rows = (due_force[:K] + due_periodic[:K])[:K]
    pairs = []  # (slot_index_in_K, caller, peer)
    S_seeds = len(params.seed_rows)
    pool = ka + S_seeds
    for slot_i, i in enumerate(due_rows):
        # union-pool draw: active slots ∪ seeds (pview._sync_phase)
        p, ok_pick = 0, False
        for t_i in range(T):
            c = min(
                int(np.float32(np.float32(r["sync_try"][i][t_i]) * np.float32(pool))),
                pool - 1,
            )
            if c >= ka:
                cand_p = int(params.seed_rows[min(c - ka, S_seeds - 1)])
                ok = cand_p != i
            else:
                cand_p = max(int(pre.nbr_id[i, c]), 0)
                ok = int(pre.nbr_id[i, c]) >= 0 and (
                    pre.key_i32(i, c) & 3
                ) != RANK_DEAD
            if ok:
                p, ok_pick = cand_p, True
                break
        if not ok_pick and params.seed_rows:
            S = len(params.seed_rows)
            fb = params.seed_rows[
                min(int(np.float32(np.float32(r["sync_fb"][i]) * np.float32(S))), S - 1)
            ]
            if fb != i:
                p = int(fb)
                ok_pick = True
        if params.seed_rows and i in due_periodic:
            # deterministic seed cadence (pview seed_sync_every)
            Q = params.seed_sync_every
            round_ = (t + i * params.sync_stagger) // params.sync_every
            if (round_ % Q) == 0:
                sidx = (i + round_ // Q) % S_seeds
                sp = int(params.seed_rows[sidx])
                if sp == i:
                    sp = int(params.seed_rows[(sidx + 1) % S_seeds])
                if sp != i:
                    p, ok_pick = sp, True
        if not ok_pick:
            continue
        p_rt = _rt_timely(pre, i, p, params.sync_timeout_ticks, D)
        if pre.up[p] and bool(r["sync_edge"][i] < p_rt):
            o.force_sync[i] = False
            pairs.append((slot_i, i, p))

    def _entries_of(src: int):
        out = []
        for s in range(k):
            subj = int(pre.nbr_id[src, s])
            out.append((subj, pre.key_i32(src, s)))
        out.append((src, int(pre.self_key[src])))
        return out

    def _merge(dst_src: dict[int, int], salt: int):
        """dst row -> src row; k+1 sequential steps, one _SusBatch per
        step (mirrors the kernel's per-step scatter-max + commit)."""
        acc_cnt = {i: 0 for i in dst_src}
        best: dict[int, list] = {i: [(NO_CAND, 0)] * P for i in dst_src}
        for s in range(k + 1):
            sus = _SusBatch(n)
            for i, src in dst_src.items():
                subj, cand = _entries_of(src)[s]
                if subj < 0:
                    continue
                acc = _apply_record_b(o, i, subj, cand, salt, ka, sus)
                if acc:
                    if armed:
                        _ad_note(subj, cand)
                    acc_cnt[i] += 1
                    ins_k, ins_s = cand, subj
                    b = best[i]
                    for p in range(P):
                        if ins_k > b[p][0]:
                            b[p], (ins_k, ins_s) = (ins_k, ins_s), b[p]
                    best[i] = b
            sus.commit(o)
        return acc_cnt, best

    # REQ: winner caller per peer = highest K-slot (deviation P6); pairs
    # iterate ascending slot, so the last write per peer is the winner
    req_srcs: dict[int, int] = {}
    for _slot_i, i, p in pairs:
        req_srcs[p] = i
    _req_acc, req_best = _merge(req_srcs, SALT_SYNC_REQ)
    # ACK: every ok caller merges its peer's pre-entries
    ack_srcs = {i: p for _si, i, p in pairs}
    _ack_acc, ack_best = _merge(ack_srcs, SALT_SYNC_ACK)

    # proposals: REQ receivers then ACK receivers, [N·P] each, p-major
    def _props_of(best: dict[int, list]):
        subs = [[0] * n for _ in range(P)]
        keys_ = [[0] * n for _ in range(P)]
        vals = [[False] * n for _ in range(P)]
        for i, b in best.items():
            for p in range(P):
                kk, ss = b[p]
                if kk > NO_CAND:
                    subs[p][i] = ss
                    keys_[p][i] = kk
                    vals[p][i] = True
        flat = lambda a: [x for chunk in a for x in chunk]
        return (
            flat(subs), flat(keys_), flat([list(range(n))] * P), flat(vals)
        )

    sp = _props_of(req_best)
    sc = _props_of(ack_best)
    sync_props = tuple(a + b for a, b in zip(sp, sc))

    # ---- refutation ----
    ref_props = ([0] * n, [0] * n, list(range(n)), [False] * n)
    V_ref = min(n, params.refute_slots or max(64, n // 16))
    lay_inc_mask = {"int16": (1 << 9) - 1}.get(str(o.nbr_key.dtype), (1 << 21) - 1)
    epoch_shift = {"int16": 11}.get(str(o.nbr_key.dtype), 23)
    needed_so_far = 0
    for i in range(n):
        diag = int(o.self_key[i])
        rank = diag & 3
        need = bool(o.up[i]) and (
            rank == RANK_SUSPECT
            or rank == RANK_DEAD
            or (bool(o.leaving[i]) and rank != RANK_LEAVING)
        )
        if need:
            needed_so_far += 1
            if needed_so_far > V_ref:
                need = False
        new_rank = RANK_LEAVING if o.leaving[i] else RANK_ALIVE
        # bump_inc with the layout's saturation (narrow keys clamp)
        inc = min(((diag >> 2) & lay_inc_mask) + 1, lay_inc_mask)
        epoch_bits = (diag >> epoch_shift) << epoch_shift
        new_diag = (epoch_bits | (inc << 2) | new_rank) if need else diag
        ref_props[0][i] = i
        ref_props[1][i] = new_diag
        ref_props[3][i] = need
        if need:
            if armed:
                ad_refuted[i] = True
            o.self_key[i] = new_diag
    proposals.append(ref_props)
    proposals.append(sync_props)

    # ---- rumor sweeps (static windows — deviation P2) ----
    sweep = params.sweep_ticks
    for ru in range(R):
        if not o.rumor_active[ru] or t - int(o.rumor_created[ru]) <= sweep:
            continue
        if D and bool(o.pending_inf[:, :, ru].any()):
            continue
        if any(
            o.infected[i, ru] and o.up[i] and t - int(o.infected_at[i, ru]) < spread
            for i in range(n)
        ):
            continue
        o.rumor_active[ru] = False
    if bool(o.mr_active.any()):
        for mm in range(M):
            if not o.mr_active[mm]:
                continue
            pending = D and bool(o.pending_minf[:, :, mm].any())
            forwarding = any(
                o.up[i] and 0 < int(o.minf_age[i, mm]) <= spread for i in range(n)
            )
            keep = (t - int(o.mr_created[mm]) <= sweep) or forwarding or pending
            if params.early_free:
                covered = all(
                    (not o.up[i])
                    or int(o.minf_age[i, mm]) > 0
                    or int(o.joined_at[i]) > int(o.mr_created[mm])
                    for i in range(n)
                )
                if covered and not pending:
                    keep = False
            if not keep:
                o.mr_active[mm] = False
                o.mr_subject[mm] = -1
                o.minf_age[:, mm] = 0
                if D:
                    o.pending_minf[:, :, mm] = False

    # ---- announcement allocation (sparse._alloc_phase mirror) ----
    E = params.announce_slots
    subject = [x for p in proposals for x in p[0]]
    key_l = [x for p in proposals for x in p[1]]
    origin = [x for p in proposals for x in p[2]]
    valid = [x for p in proposals for x in p[3]]
    pool_key_by_subject: dict[int, int] = {}
    for mm in range(M):
        if o.mr_active[mm]:
            pool_key_by_subject[int(o.mr_subject[mm])] = int(o.mr_key[mm])
    valid = [
        v and int(key_l[ci]) > pool_key_by_subject.get(int(subject[ci]), NO_CAND)
        for ci, v in enumerate(valid)
    ]
    if any(valid):
        n_prio = sum(len(p[0]) for p in proposals[:3])
        compact = [i for i, v in enumerate(valid) if v][:E]
        entries = [
            (int(subject[ci]), int(key_l[ci]), int(origin[ci]), ci < n_prio)
            for ci in compact
        ]
        wins = []
        for e, (s, kk, oo, pr) in enumerate(entries):
            lose = any(
                s2 == s and (k2 > kk or (k2 == kk and e2 < e))
                for e2, (s2, k2, _o2, _p2) in enumerate(entries)
                if e2 != e
            )
            if not lose:
                wins.append((s, kk, oo, pr))
        pool_by_subject = {
            int(o.mr_subject[mm]): mm for mm in range(M) if o.mr_active[mm]
        }
        pre_mr_key = o.mr_key.copy()
        free = [mm for mm in range(M) if not o.mr_active[mm]][:E]
        replace_tgt = {
            pool_by_subject[s]
            for s, kk, _oo, _pr in wins
            if s in pool_by_subject and kk > int(o.mr_key[pool_by_subject[s]])
        }
        need_m = [0] * M
        cov_m = [0] * M
        for mm in range(M):
            for i in range(n):
                if o.up[i] and not int(o.joined_at[i]) > int(o.mr_created[mm]):
                    need_m[mm] += 1
                    if int(o.minf_age[i, mm]) > 0:
                        cov_m[mm] += 1
        victims = sorted(
            (
                mm
                for mm in range(M)
                if o.mr_active[mm]
                and mm not in replace_tgt
                and 2 * cov_m[mm] >= need_m[mm]
            ),
            key=lambda mm: (need_m[mm] - cov_m[mm], mm),
        )[: min(E, M)]
        a0 = int(np.sum(o.mr_active))
        cap_npr = (M * 7) // 8
        fi = 0
        vi = 0
        evicted_slots: set[int] = set()
        for s, kk, oo, pr in wins:
            if s in pool_by_subject:
                slot = pool_by_subject[s]
                if kk <= int(pre_mr_key[slot]):
                    continue
                assert slot not in evicted_slots
                o.minf_age[:, slot] = 0
                if D:
                    o.pending_minf[:, :, slot] = False
            else:
                rr = fi
                fi += 1
                if rr < len(free) and (pr or a0 + rr < cap_npr):
                    slot = free[rr]
                elif pr and vi < len(victims):
                    slot = victims[vi]
                    vi += 1
                    evicted_slots.add(slot)
                    o.minf_age[:, slot] = 0
                    if D:
                        o.pending_minf[:, :, slot] = False
                else:
                    continue
            o.mr_active[slot] = True
            o.mr_subject[slot] = s
            o.mr_key[slot] = kk
            o.mr_created[slot] = t
            o.mr_origin[slot] = oo
            o.minf_age[oo, slot] = 1
    if armed:
        lh2, ck2, cf2 = _adp.fold(
            aspec,
            ad["lh"].astype(np.int32),
            ad["conf_key"].astype(np.int32),
            ad["conf"].astype(np.int32),
            acc_key=ad_keym.astype(np.int32),
            acc_cnt=np.minimum(ad_cnt, np.iinfo(np.int32).max).astype(np.int32),
            miss=ad_miss,
            succ=ad_succ,
            refuted=ad_refuted,
            up=o.up,
            xp=np,
        )
        o.ad = {"lh": lh2, "conf_key": ck2, "conf": cf2}
    return o


def assert_pview_equivalent(state: PviewState, o: _PO) -> None:
    pairs = {"tick": (int(state.tick), o.tick)}
    for name in _FIELDS:
        pairs[name] = (np.asarray(getattr(state, name)), getattr(o, name))
    for name, (a, b) in pairs.items():
        a, b = np.asarray(a), np.asarray(b)
        if not np.array_equal(a, b):
            diff = np.argwhere(np.atleast_1d(a != b))
            raise AssertionError(
                f"pview kernel/oracle divergence in {name} at "
                f"{diff[:10].tolist()} (kernel="
                f"{a[tuple(diff[0])] if diff.size else a}, "
                f"oracle={b[tuple(diff[0])] if diff.size else b})"
            )
