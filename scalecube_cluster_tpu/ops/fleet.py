"""The fleet engine (r15): scenario-batched vmap windows.

Every engine window is a pure ``window(state, key) -> (state', key', ms)``
program, so batching a leading SCENARIO axis is just ``vmap``: one XLA
program advances S independent clusters of N members each — S×N member
ticks per dispatch — and the whole Monte Carlo axis (seeds × chaos
draws × origins) runs at device speed with zero per-scenario dispatch
overhead. This module is the ONE spelling of that batching:

* :func:`make_fleet_window` — the generic builder: ``jit(vmap(core))``
  with the fleet state DONATED (the r6 double-buffered discipline covers
  the stacked pytree exactly as it covers a single state — the donated
  argnums are audited by the r12 ``fleet`` matrix variant).
* :func:`make_fleet_run` / :func:`make_fleet_adaptive_run` — the
  engine-resolving entry points (``SimParams`` → dense, ``SparseParams``
  → sparse, ``PviewParams`` → pview, the historical driver contract);
  each engine also registers its own builder on
  :class:`~.engine_api.EngineOps` (``make_fleet_run`` /
  ``make_fleet_adaptive_run``).
* fleet-state plumbing — :func:`fleet_broadcast` / :func:`fleet_stack` /
  :func:`fleet_row` / :func:`fleet_size` / :func:`fleet_keys` /
  :func:`fleet_inject_rumor`.
* :class:`FleetOps` + :func:`fleet_timeline` — the batched
  ``StateTimeline`` fold: the chaos mutator surface of an engine ops
  module, vmapped over the scenario axis, so one compiled-schedule
  scenario replays onto ALL S clusters between fleet windows (pure
  device ops, nothing read back — the r7 discipline, S-wide).

Batching rules (the contract docs/FLEET.md spells out):

* **What varies per scenario**: everything in the STATE — the PRNG key
  chain, rumor origins/slots, up masks, loss/delay planes, view planes.
  Each scenario's row ``s`` evolves exactly as a serial single-cluster
  run with the same state and key would: the per-row trajectory is
  BIT-IDENTICAL to the unbatched window (pinned by
  ``tests/test_fleet.py`` for all three engines), because vmap batches
  every op elementwise and the per-tick key chain
  (``key, k = split(key)``) is a per-row function of the row's own key.
* **What may NOT vary**: anything STATIC — capacity, fanout, dissem
  spec, key dtype, tick counts, adaptive knobs. Those are compiled into
  the program; a cell of the Monte Carlo matrix that changes one of
  them is a different fleet program (the certify service builds one
  fleet window per cell for exactly this reason).
* **Quiet-tick caveat**: ``lax.cond`` under vmap runs BOTH branches and
  materializes a select over every state leaf, so the serial engines'
  quiet-tick skips (no gossip payload, no suspicion anywhere) do not
  apply per row — a fleet window does the active-tick work for every
  scenario every tick, plus the select traffic. The dense engine's
  static ``SimParams.quiet_gates=False`` switch (the FLEET PROFILE)
  drops the gates and traces the active branches alone — value-identical
  by construction (each gated branch is a no-op when its gate is closed)
  and what the MC certification service and config14 run. Monte Carlo
  runs are active by construction; idle-heavy workloads belong on the
  serial windows.
* **Device parallelism**: scenarios are independent, so
  :func:`fleet_mesh` + :func:`shard_fleet` split the S axis over the
  local devices with zero collectives — still ONE XLA program per
  window. On CPU this is what engages the cores (XLA:CPU executes a
  single-device op stream serially; one partition per virtual device
  runs them concurrently); on a TPU slice it is fleet-per-chip.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

#: the scenario mesh axis — orthogonal to ops/sharding.py's "members"
#: axis: scenarios are INDEPENDENT, so sharding S over devices needs no
#: collectives at all (GSPMD partitions every batched op cleanly)
FLEET_AXIS = "scenarios"


def make_fleet_window(
    core: Callable,
    params,
    n_ticks: int,
    donate: bool = True,
    donated: tuple = (0,),
):
    """``jit(vmap(core))`` over a leading [S] scenario axis.

    ``core`` is an engine's raw window function with the shared signature
    ``core(*batched_args, n_ticks=, params=)`` — ``run_ticks`` /
    ``run_sparse_ticks`` / ``run_pview_ticks`` take ``(state, key)``,
    the adaptive cores ``(state, ad, key)``. Every positional argument
    is mapped on axis 0; ``donated`` names the argnums donated to the
    compiled program (the fleet state — and the adaptive state for the
    adaptive cores), exactly the serial builders' donation discipline
    lifted to the stacked pytree."""
    run = functools.partial(core, n_ticks=n_ticks, params=params)
    return jax.jit(jax.vmap(run), donate_argnums=donated if donate else ())


def make_fleet_run(params, n_ticks: int, donate: bool = True):
    """The engine-resolving fleet window builder: one jitted program
    advancing ``S`` independent clusters (state pytree stacked to
    ``[S, ...]``, keys ``[S, 2]``), fleet state donated. ``S`` is read
    from the arrays at call time (one compile per distinct S)."""
    from . import engine_api

    eng = engine_api.resolve(params)
    if eng.make_fleet_run is None:  # pragma: no cover — all engines register
        raise ValueError(f"engine {eng.name!r} registers no fleet builder")
    return eng.make_fleet_run(params, n_ticks, donate)


def make_fleet_adaptive_run(params, n_ticks: int, donate: bool = True):
    """Fleet twin of the engines' ``make_adaptive_run`` (r14): the
    AdaptiveState pytree rides stacked to ``[S, ...]`` and is donated
    alongside the fleet state (argnums 0, 1). Refuses a default spec —
    the legacy fleet builder is the byte-identical program then."""
    from . import engine_api

    eng = engine_api.resolve(params)
    if eng.make_fleet_adaptive_run is None:
        raise ValueError(
            f"engine {eng.name!r} registers no adaptive fleet builder"
        )
    return eng.make_fleet_adaptive_run(params, n_ticks, donate)


# ---------------------------------------------------------------------------
# scenario-axis sharding (the fleet's device-parallel mode)
# ---------------------------------------------------------------------------


def fleet_mesh(devices=None):
    """A 1-D ``scenarios`` mesh over the local devices. Scenarios are
    independent, so the fleet's sharded mode needs NO collectives: GSPMD
    partitions every batched op on the leading axis and each device
    advances its S/devices clusters — one XLA program, device-parallel.
    (On CPU the 8-virtual-device mesh is what actually engages the cores:
    XLA:CPU runs one partition per device thread, where the single-device
    fleet program executes its op stream serially.)"""
    from jax.sharding import Mesh

    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (FLEET_AXIS,))


def shard_fleet(tree, mesh):
    """Commit a fleet pytree (state, keys, fold accumulators) to the
    scenario mesh: every non-empty leaf split on its leading [S] axis,
    zero-size leaves (e.g. delay rings at delay_slots=0) replicated. S
    must divide by the mesh size. The jitted fleet window then compiles
    for these shardings by propagation — no in_shardings plumbing, and
    donation covers the sharded buffers exactly as on one device."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    s = fleet_size(tree)
    if s % mesh.size:
        raise ValueError(
            f"fleet size {s} does not divide over the {mesh.size}-device "
            "scenario mesh"
        )
    shard = NamedSharding(mesh, P(FLEET_AXIS))
    rep = NamedSharding(mesh, P())
    return jax.device_put(
        tree, jax.tree.map(lambda x: shard if x.size else rep, tree)
    )


# ---------------------------------------------------------------------------
# fleet-state plumbing
# ---------------------------------------------------------------------------


def fleet_size(fleet_state) -> int:
    """S — the scenario-axis length of a stacked state pytree."""
    return jax.tree.leaves(fleet_state)[0].shape[0]


def fleet_stack(states: Sequence):
    """Stack per-scenario states (same treedef, same shapes) into one
    fleet state — for fleets whose scenarios start from DIFFERENT
    states. Identical starts should use :func:`fleet_broadcast`."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def fleet_broadcast(state, s: int):
    """One state replicated to a [S, ...] fleet (the Monte Carlo start:
    S identical clusters whose trajectories then diverge purely through
    their per-scenario keys and injected mutations). Materialized copies
    — the fleet state must own its buffers to be donatable."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (s,) + x.shape), state
    )


def fleet_row(fleet_state, s: int):
    """Scenario ``s`` as an unbatched engine state (host-side slicing —
    the bit-identity tests' decode seam; not a hot-path op)."""
    return jax.tree.map(lambda x: x[s], fleet_state)


def fleet_keys(seeds) -> jax.Array:
    """[S, 2] PRNG keys, row s == ``jax.random.PRNGKey(seeds[s])`` exactly
    (one vmapped threefry seed — the serial control of the bit-identity
    contract uses the scalar spelling on the same seed)."""
    seeds = jnp.asarray(seeds, jnp.int32)
    return jax.vmap(jax.random.PRNGKey)(seeds)


def fleet_inject_rumor(ops, fleet_state, slot: int, origins):
    """Per-scenario ``spread_rumor`` (one vmapped host mutation): scenario
    ``s`` starts the rumor in ``slot`` at row ``origins[s]``. ``ops`` is
    the engine's ops module (``ops.state`` / ``ops.sparse`` /
    ``ops.pview`` — the same mutator surface everywhere)."""
    origins = jnp.asarray(origins, jnp.int32)
    return jax.vmap(lambda st, o: ops.spread_rumor(st, int(slot), o))(
        fleet_state, origins
    )


def fleet_uniform_loss(ops, fleet_state, floors, floor: bool = True):
    """Per-scenario ambient uniform-loss write (one vmapped mutation):
    scenario ``s`` gets floor ``floors[s]`` (a FRACTION, not percent) —
    the r16 condition-grid seam for runs whose ambient floor is part of
    the start state rather than a scheduled ``LossStorm`` (the
    adaptive-knob sweep's loss axis)."""
    floors = jnp.asarray(floors, jnp.float32)
    return jax.vmap(lambda st, p: ops.set_uniform_loss(st, p, floor=floor))(
        fleet_state, floors
    )


# ---------------------------------------------------------------------------
# the batched StateTimeline fold
# ---------------------------------------------------------------------------

#: engine ops-module callables the chaos StateTimeline replays (the
#: complete mutator surface chaos/engine.py dispatches to)
_TIMELINE_MUTATORS = frozenset({
    "crash_rows", "crash_row", "join_row", "join_rows", "begin_leave",
    "set_link_loss", "set_link_delay", "set_uniform_loss",
    "block_partition", "heal_partition", "spread_rumor", "update_metadata",
    # r18 additions: the named partition-heal seam, the precomputed-q delay
    # write, the assign-vector partition spellings, and the byzantine
    # refute squash — all pure state->state, so the default vmap treatment
    # is exactly right when no FleetVary intercepts them
    "heal_partition_pair", "set_link_delay_q",
    "block_partition_assign", "heal_partition_assign", "drop_refutes",
})


@dataclasses.dataclass(frozen=True)
class FleetVary:
    """Per-scenario variation of a shared chaos schedule (r16, ROADMAP 3d).

    The r15 batched :class:`StateTimeline` fold replays ONE compiled
    schedule fleet-wide — only PRNG chains and injected rumor origins
    varied per scenario. This declares the two schedule ARGUMENTS that may
    additionally vary, which is what lets one fleet sweep a whole
    condition grid (the r16 controller certification's loss-floor grid,
    a crash-row sweep) in one compiled program:

    * ``crash_rows`` — [S] i32: scenario ``s``'s ``Crash`` event kills row
      ``crash_rows[s]`` instead of the scheduled row. Requires the
      scenario to script exactly ONE ``Crash`` event naming ONE row (the
      detection folds need one subject per scenario); validated at
      :func:`fleet_timeline` build.
    * ``loss_pct`` — [S] f32 PERCENT: every uniform-loss FLOOR write
      (``LossStorm`` starts, ambient floors applied through the timeline)
      uses ``loss_pct[s]`` instead of the scheduled pct. The non-floor
      restore path (storm end) is untouched — it replays the stashed
      per-scenario planes. Mid-storm link mutations still clear to the
      SCHEDULED pct (the storm-replay ``clear`` floor is a host value);
      keep varied-floor scenarios free of mid-storm link events, or
      accept the scheduled floor on those writes.
    * ``delay_ticks`` — [S] f32 mean delay in TICKS (r18, the named r16
      leftover): every scheduled POSITIVE link-delay write (``SlowMember``
      / ``SlowEpoch`` starts) uses scenario ``s``'s mean instead of the
      scripted one. The mean→q transcendental runs on HOST here, once per
      scenario at build time; the timeline then vmaps the precomputed [S]
      q vector through ``ops.set_link_delay_q``. Teardown writes (mean 0)
      stay broadcast. Dense delay engines only — refused loudly otherwise.
    * ``partition_assign`` — [S, N] i32 (r18, the other named leftover):
      scenario ``s``'s ``Partition`` uses GROUP ASSIGNMENT
      ``partition_assign[s]`` (``-1`` = bystander keeps links) instead of
      the scripted groups — block and heal both ride it, so one compiled
      fleet sweeps partition SHAPES (minority/majority cuts, moved
      bridges). Requires exactly one ``Partition`` event, no ``ZoneOutage``
      (its block would be intercepted too), and an ops module with the
      assign-vector spellings (dense links) — refused loudly otherwise.
    """

    crash_rows: Optional[object] = None  # [S] i32 (array-like)
    loss_pct: Optional[object] = None  # [S] f32, percent
    delay_ticks: Optional[object] = None  # [S] f32, mean delay in ticks
    partition_assign: Optional[object] = None  # [S, N] i32, -1 = bystander

    def validate(self, scenario) -> None:
        from ..chaos.events import (
            Crash,
            Partition,
            ScenarioError,
            SlowEpoch,
            SlowMember,
            ZoneOutage,
        )

        if self.crash_rows is not None:
            crashes = [e for e in scenario.events if isinstance(e, Crash)]
            if len(crashes) != 1 or len(crashes[0].rows) != 1:
                raise ScenarioError(
                    "FleetVary.crash_rows needs a scenario with exactly one "
                    "Crash event naming one row (the per-scenario subject "
                    f"it replaces); {scenario.name!r} schedules "
                    f"{[list(c.rows) for c in crashes]}"
                )
        if self.delay_ticks is not None:
            slows = [e for e in scenario.events
                     if isinstance(e, (SlowMember, SlowEpoch))]
            if not slows:
                raise ScenarioError(
                    "FleetVary.delay_ticks varies the scheduled link-delay "
                    f"writes, but {scenario.name!r} schedules no SlowMember/"
                    "SlowEpoch event — nothing to vary"
                )
        if self.partition_assign is not None:
            parts = [e for e in scenario.events if isinstance(e, Partition)]
            zones = [e for e in scenario.events if isinstance(e, ZoneOutage)]
            if len(parts) != 1 or zones:
                raise ScenarioError(
                    "FleetVary.partition_assign needs a scenario with "
                    "exactly one Partition event and no ZoneOutage (every "
                    "block/heal in the schedule is replaced by the "
                    f"per-scenario assignment); {scenario.name!r} schedules "
                    f"{len(parts)} Partition + {len(zones)} ZoneOutage"
                )


class FleetOps:
    """The chaos-mutator surface of an engine ops module, vmapped over the
    scenario axis — what makes ``StateTimeline`` (r7) a BATCHED fold:
    every scheduled action (crash, partition, storm, degraded-cohort
    write, restart) applies to all S scenarios in one traced device op,
    with the event arguments broadcast (a timeline's schedule is shared
    across the fleet; per-scenario variation enters through the PRNG
    keys and any per-scenario state mutation applied via
    :func:`fleet_inject_rumor` / your own ``jax.vmap``). Non-mutator
    attributes (``GROUP_PARTITIONS`` etc.) pass through untouched.

    A :class:`FleetVary` (r16) swaps the crash-row / uniform-loss-floor
    ARGUMENTS per scenario: the named mutators then vmap over (state,
    per-scenario argument) instead of broadcasting the scheduled value."""

    def __init__(self, ops, vary: Optional[FleetVary] = None):
        self._ops = ops
        self._vary = vary

    def __getattr__(self, name):
        target = getattr(self._ops, name)
        if name not in _TIMELINE_MUTATORS or not callable(target):
            return target
        vary = self._vary

        if name == "crash_rows" and vary is not None \
                and vary.crash_rows is not None:
            rows_s = jnp.asarray(vary.crash_rows, jnp.int32)

            def vmapped(fleet_state, _rows, **kwargs):
                # the scheduled cohort is REPLACED by the per-scenario row
                return jax.vmap(lambda st, r: target(st, r[None]))(
                    fleet_state, rows_s
                )

            return vmapped

        if name == "set_link_delay" and vary is not None \
                and vary.delay_ticks is not None:
            from .state import delay_mean_to_q

            q_s = jnp.asarray(
                [delay_mean_to_q(float(m)) for m in vary.delay_ticks],
                jnp.float32,
            )
            target_q = getattr(self._ops, "set_link_delay_q")

            def vmapped(fleet_state, src, dst, mean, **kwargs):
                if float(mean) > 0:
                    # a scheduled delay START carries the per-scenario mean
                    # (as its host-precomputed q); teardown (mean 0) stays
                    # the broadcast zero write
                    return jax.vmap(
                        lambda st, q: target_q(st, src, dst, q)
                    )(fleet_state, q_s)
                return jax.vmap(lambda st: target(st, src, dst, mean))(
                    fleet_state
                )

            return vmapped

        if name in ("block_partition", "heal_partition_pair") \
                and vary is not None and vary.partition_assign is not None:
            assign_s = jnp.asarray(vary.partition_assign, jnp.int32)
            if name == "block_partition":
                block = getattr(self._ops, "block_partition_assign")

                def vmapped(fleet_state, _a, _b, **kwargs):
                    return jax.vmap(block)(fleet_state, assign_s)

            else:
                heal = getattr(self._ops, "heal_partition_assign")

                def vmapped(fleet_state, _a, _b, clear=0.0, **kwargs):
                    return jax.vmap(
                        lambda st, g: heal(st, g, clear=clear)
                    )(fleet_state, assign_s)

            return vmapped

        if name == "set_uniform_loss" and vary is not None \
                and vary.loss_pct is not None:
            frac_s = jnp.asarray(vary.loss_pct, jnp.float32) / 100.0

            def vmapped(fleet_state, loss, floor=False):
                if not floor:
                    # restore/explicit writes keep the scheduled value —
                    # only FLOOR writes (storm starts, ambient floors)
                    # carry the per-scenario condition
                    return jax.vmap(lambda st: target(st, loss, floor=floor))(
                        fleet_state
                    )
                return jax.vmap(lambda st, p: target(st, p, floor=True))(
                    fleet_state, frac_s
                )

            return vmapped

        def vmapped(fleet_state, *args, **kwargs):
            return jax.vmap(lambda st: target(st, *args, **kwargs))(
                fleet_state
            )

        return vmapped


def fleet_timeline(scenario, ops, dense_links: bool, horizon=None,
                   vary: Optional[FleetVary] = None):
    """A chaos :class:`~..chaos.engine.StateTimeline` whose compiled
    schedule replays onto a FLEET state: same validation, same ordered
    (tick, seq) fold, same loss-storm stash/replay semantics — each
    action one vmapped device op over all S scenarios. ``vary`` (r16)
    makes the crash row / uniform-loss floor per-scenario arguments
    (:class:`FleetVary`), so one compiled fleet sweeps a condition grid."""
    from ..chaos.engine import StateTimeline

    if vary is not None:
        from ..chaos.events import ScenarioError

        vary.validate(scenario)
        if vary.delay_ticks is not None and (
            not dense_links or not hasattr(ops, "set_link_delay_q")
        ):
            raise ScenarioError(
                "FleetVary.delay_ticks needs the dense delay plane and an "
                "ops module with set_link_delay_q (the precomputed-q "
                f"write); {getattr(ops, '__name__', ops)!r} with "
                f"dense_links={dense_links} cannot batch per-scenario "
                "delays"
            )
        if vary.partition_assign is not None and (
            not dense_links or not hasattr(ops, "block_partition_assign")
        ):
            raise ScenarioError(
                "FleetVary.partition_assign needs dense [N, N] links and "
                "an ops module with the assign-vector partition spellings "
                f"(block/heal_partition_assign); "
                f"{getattr(ops, '__name__', ops)!r} with "
                f"dense_links={dense_links} cannot batch per-scenario "
                "partition shapes"
            )
    return StateTimeline(
        scenario, FleetOps(ops, vary), dense_links=dense_links,
        horizon=horizon,
    )


# ---------------------------------------------------------------------------
# on-device fleet reductions (the Monte Carlo folds)
# ---------------------------------------------------------------------------


def fold_first_full_coverage(hit_tick, coverage, window_start):
    """Latch per-scenario first-full-coverage ticks from one fleet
    window's stacked coverage curves. ``hit_tick`` [S] i32 (-1 = not yet),
    ``coverage`` [S, T] (one rumor slot's curve), ``window_start`` the
    absolute tick at window entry. Pure jnp — jit me; the accumulator
    stays on device across windows (no per-seed readback, the r6 rule)."""
    hit = coverage >= 1.0  # [S, T]
    any_hit = hit.any(axis=1)
    first = jnp.argmax(hit, axis=1).astype(jnp.int32)  # first True per row
    cand = jnp.int32(window_start) + first + 1
    return jnp.where((hit_tick < 0) & any_hit, cand, hit_tick)


def fleet_false_dead(fleet_state, watch_up_mask):
    """[S] i32: per scenario, how many WATCHED rows (degraded-but-alive
    cohort / never-faulted members) are currently tombstoned DEAD by any
    up observer — the chaos false-positive sentinel's core check
    (``chaos.sentinels`` guarantee 1), vmapped. ``watch_up_mask`` [N]
    bool is the watch cohort; rank DEAD == 3 with ``key >= 0`` excludes
    unknown cells exactly as ``kernel.sentinel_core`` does. Dense/sparse
    view-plane states only (the engines the MC fp service runs)."""

    def one(st):
        vk = st.view_key
        dead = (vk >= 0) & ((vk & 3) == 3)
        watched = watch_up_mask & st.up
        return (
            (dead & st.up[:, None] & watched[None, :])
            .any(axis=0)
            .sum()
            .astype(jnp.int32)
        )

    return jax.vmap(one)(fleet_state)


def _crash_detected_one(st, r):
    """Scalar detection predicate (guarantee 2): every up observer reads
    row ``r`` at rank DEAD (unknown key -1 also reads rank 3, matching
    the reference's removal)."""
    col = st.view_key[:, r]
    n = st.up.shape[0]
    others_up = st.up & (jnp.arange(n) != r)
    return (~others_up | ((col & 3) == 3)).all()


def fleet_crash_detected(fleet_state, crash_row: int):
    """[S] bool: per scenario, does EVERY up observer read ``crash_row``
    at rank DEAD? The detection-latency sentinel's check, vmapped for
    the MC certification fold."""
    return jax.vmap(lambda st: _crash_detected_one(st, crash_row))(
        fleet_state
    )


def fleet_crash_detected_varied(fleet_state, crash_rows):
    """[S] bool twin of :func:`fleet_crash_detected` for a
    :class:`FleetVary`-varied fleet: scenario ``s``'s detection subject is
    ``crash_rows[s]`` (the per-scenario row the varied timeline killed)."""
    rows = jnp.asarray(crash_rows, jnp.int32)
    return jax.vmap(_crash_detected_one)(fleet_state, rows)
