"""DCN / multi-host layer: the simulation spanning processes and slices.

SURVEY.md §2.3's fourth parallelism component: within one slice the
row-sharded tick rides ICI collectives (:mod:`.sharding`); ACROSS hosts or
slices the same program runs under ``jax.distributed`` — each process
contributes its local devices to one global mesh and XLA routes the
inter-slice collectives over DCN. This is the analogue of the reference's
WAN deployment profile (``ClusterConfig.defaultWanConfig``,
``ClusterConfig.java:72-79``): same protocol, bigger/laggier fabric — the
knobs that change are config (WAN profile), not code.

Usage (one process per host/slice, e.g. under SLURM/GKE or manual spawn)::

    from scalecube_cluster_tpu.ops import dcn
    dcn.initialize(coordinator_address="host0:9777",
                   num_processes=4, process_id=rank)   # or env-driven
    mesh = dcn.global_mesh()                 # all processes' devices
    params = SimParams(capacity=N, ...)
    state = dcn.make_global_state(params, n_initial=N, mesh=mesh)
    step = make_sharded_run(mesh, params, n_ticks=100)
    state, key, metrics, _ = step(state, jax.random.PRNGKey(0))

Every process executes the same program on the same inputs (SPMD); arrays
are materialized per-process via ``jax.make_array_from_callback`` so no
host ever needs another host's shard.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

# These imports are BACKEND-FREE by design: no sibling module materializes a
# jnp value at import time (module constants are python ints / numpy — see
# the notes in lattice.py/state.py). That invariant is what makes
# ``from scalecube_cluster_tpu.ops import dcn`` safe as the first import of
# a multi-process worker, BEFORE jax.distributed.initialize() runs; the
# two-process smoke test (tests/test_dcn.py) would fail on any regression.
from .sharding import MEMBER_AXIS, make_mesh, state_shardings
from .state import SimParams, SimState, init_state


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> None:
    """Join the multi-process JAX runtime (``jax.distributed.initialize``).

    Arguments fall back to the standard env vars
    (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID``) and, on managed TPU pods, to automatic cluster
    detection (args all None). Call BEFORE any other jax API touches a
    backend. No-op if the runtime is already initialized (re-entrant
    drivers)."""
    try:  # re-entrancy guard that must NOT itself touch a backend
        from jax._src.distributed import global_state as _gs

        if _gs.client is not None:
            return  # already joined a multi-process world
    except ImportError:
        pass  # future jax moved it: let initialize() raise on double-init
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    _select_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def cpu_collectives_available() -> bool:
    """True when this jaxlib can run MULTIPROCESS computations on the CPU
    backend (a built-in gloo collectives implementation). The stock XLA
    CPU client refuses cross-process programs outright ("Multiprocess
    computations aren't implemented on the CPU backend") unless a CPU
    collectives implementation is selected — :func:`initialize` selects
    gloo when available; this probe is the test-gating spelling."""
    try:
        from jax._src import xla_bridge as _xb
        import jaxlib.xla_extension as _xe

        # the flag must accept "gloo" AND this jaxlib must actually ship
        # the gloo collectives (CPU_COLLECTIVES_IMPLEMENTATIONS only
        # enumerates the flag's legal spellings, not what was compiled in)
        return "gloo" in tuple(_xb.CPU_COLLECTIVES_IMPLEMENTATIONS) and hasattr(
            _xe, "make_gloo_tcp_collectives"
        )
    except Exception:
        return False


def _select_cpu_collectives() -> None:
    """On the CPU backend, select the gloo collectives implementation (the
    flag defaults to "none", under which a multi-process CPU computation
    fails at dispatch). Harmless on TPU/GPU: the flag only affects the CPU
    client, and we leave any explicit user setting alone."""
    if not cpu_collectives_available():
        return
    from jax._src import xla_bridge as _xb

    # the flag object, not jax.config.<name> — the jax.config attribute
    # is not materialized for this Flag on current jax
    if _xb.CPU_COLLECTIVES_IMPLEMENTATION.value in (None, "none"):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")


def process_info() -> tuple[int, int]:
    """(process_index, process_count) of this host in the global runtime."""
    return jax.process_index(), jax.process_count()


def global_mesh():
    """One mesh (axis: ``sharding.MEMBER_AXIS``) over EVERY process's
    devices (``jax.devices()`` is global after :func:`initialize`); the
    member axis spans ICI within a slice and DCN between slices
    automatically."""
    return make_mesh(jax.devices())


def make_global_state(
    params: SimParams,
    n_initial: int,
    mesh,
    **init_kwargs,
) -> SimState:
    """Build the initial SimState as GLOBAL arrays over a (possibly
    multi-host) mesh.

    ``jax.device_put`` of a host-local array only works single-host;
    multi-host arrays must be assembled from per-process shards. Every
    process deterministically computes the same host-side init (pure
    function of params) and hands ``jax.make_array_from_callback`` just the
    slices its own devices hold — no cross-host transfer, and no per-DEVICE
    duplication on device memory. Each HOST does still materialize the full
    init in its own RAM once (the 100k lean state is ~77 GB — fits a
    standard 128 GB host); a shard-local init that builds only the local
    row block is the upgrade path for states beyond host RAM.
    """
    import numpy as np

    host_state = init_state(params, n_initial, **init_kwargs)
    shardings = state_shardings(
        mesh, host_state.loss.ndim != 0, host_state.pending_key.shape[0]
    )

    def _globalize(leaf, sharding):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    return jax.tree_util.tree_map(_globalize, host_state, shardings)


def make_global_pview_state(params, n_initial: int, mesh, **init_kwargs):
    """The pview twin of :func:`make_global_state` (r20): build the
    initial ``PviewState`` as GLOBAL arrays over a (possibly multi-host)
    mesh — every process computes the same deterministic host init and
    contributes only the row shards its own devices hold. The per-host
    init cost is O(N·k), not O(N²), so host RAM stops being the scale
    ceiling long before the dense engine's upgrade path matters."""
    import numpy as np

    from .pview import init_pview_state
    from .sharding import pview_state_shardings

    host_state = init_pview_state(params, n_initial, **init_kwargs)
    shardings = pview_state_shardings(
        mesh, False, host_state.pending_minf.shape[0]
    )

    def _globalize(leaf, sharding):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    return jax.tree_util.tree_map(_globalize, host_state, shardings)
