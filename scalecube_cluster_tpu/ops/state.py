"""Simulation state tensors + static parameters + host-side mutation helpers.

The state is the struct-of-arrays encoding of N SWIM nodes' *replicated*
views (SURVEY.md §2.2 "membership table = N×N replicated-state tensor"):
row ``i`` of every ``view_*`` matrix is node i's local membership table, the
TPU analogue of the reference's per-node ``membershipTable``
(``MembershipProtocolImpl.java:88-91``). All shapes are static (capacity N);
dynamic membership (joins, crashes, leaves) is masks + host edits between
ticks — no retracing (SURVEY.md §7 hard part iii).

Wall-clock → tick-time mapping (hard part ii): one tick = one gossip period
(``SimConfig.tick_interval``); the FD round fires every
``fd_every = ping_interval / tick_interval`` ticks and SYNC every
``sync_every = sync_interval / tick_interval`` ticks, per-node staggered.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..adaptive import AdaptiveSpec
from ..config import ClusterConfig
from ..dissemination.spec import DissemSpec
from . import bitplane
from .lattice import (
    ALIVE,
    RANK_LEAVING,
    UNKNOWN_KEY,
    bump_inc,
    key_inc,
    key_np_dtype,
    key_status,
    no_candidate,
    precedence_key,
)

# Host-side python ints (NOT jnp scalars — a module-level jnp constant would
# initialize an XLA backend at import, breaking multi-process workers that
# must call jax.distributed.initialize first; see ops.dcn).
NEVER = -(1 << 30)  # "changed long ago" sentinel for changed_at
NO_CANDIDATE_I32 = jnp.iinfo(jnp.int32).min  # scatter-max identity
# ALIVE @ incarnation 0 @ epoch 0 packed key (epoch<<23 | inc<<2 | rank_alive)
ALIVE0_KEY = 0


@dataclasses.dataclass(frozen=True)
class SimParams:
    """Static (hashable) kernel parameters, derived from ``ClusterConfig``.

    Mirrors the reference's config surface in tick units:
    fanout/repeat_mult (``GossipConfig.java:9-11``), ping_req_k
    (``FailureDetectorConfig.java:11``), suspicion_mult + sync interval
    (``MembershipConfig.java:14-16``).
    """

    capacity: int
    fanout: int = 3
    repeat_mult: int = 3
    ping_req_k: int = 3
    fd_every: int = 5  # ping_interval / tick_interval
    sync_every: int = 150  # sync_interval / tick_interval
    sync_stagger: int = 1
    # Link-delay modeling (NetworkEmulator's exponential mean delay,
    # NetworkEmulator.java:349-369). delay_slots is the pending-delivery
    # ring depth D: gossip messages can land up to D-1 ticks late; 0
    # disables delay entirely (no rings allocated, zero overhead).
    delay_slots: int = 0
    # Request-response timeout budgets in whole ticks, used by the delay
    # model's timeliness factors (P(round trip <= budget)); with zero delay
    # every factor is exactly 1.0 and trajectories are unchanged.
    # Direct ping: ping_timeout (500ms/200ms = 2 whole ticks).
    fd_direct_timeout_ticks: int = 2
    # Indirect probe: the remaining interval is split across the two round
    # trips (issuer<->relay, relay<->target), one tick each by default.
    fd_leg_timeout_ticks: int = 1
    # SYNC: syncTimeout (3s/200ms = 15 ticks).
    sync_timeout_ticks: int = 15
    # Static cap on SYNC callers processed per tick (0 = auto:
    # capacity/sync_every + 32 headroom). Stagger spreads periodic syncs to
    # ~capacity/sync_every per tick; the headroom absorbs join bootstraps.
    # Overflowing callers simply wait: periodic ones hit their next stagger
    # slot, forced ones (force_sync) retry next tick.
    sync_slots: int = 0
    suspicion_mult: int = 5
    rumor_slots: int = 64
    # Static switch for the [N, N] health metrics (alive_view_fraction /
    # false_suspect_pairs). They cost ~3-4 full-matrix passes per tick —
    # ~20% of an active tick at large N — so throughput-focused runs that
    # only need rumor coverage / counters can turn them off (the fields are
    # then emitted as 0, keeping the metrics pytree shape stable for scan).
    full_metrics: bool = True
    # Apply the hierarchical-namespace relatedness gate
    # (areNamespacesRelated, MembershipProtocolImpl.java:511-536) to every
    # merge accept: records about subjects whose namespace group is
    # unrelated to the receiver's are never applied, so unrelated members
    # never enter a view (and therefore never get probed or gossiped to —
    # the reference's member lists have the same property). Zero-cost when
    # False (no gate ops are traced).
    namespace_gate: bool = False
    # Rows that act as configured seed members: always in the SYNC peer pool
    # even when absent from the local view (the reference's selectSyncAddress
    # draws from seedMembers ∪ members, MembershipProtocolImpl.java:461-472 —
    # this is what re-bridges a fully partitioned cluster after both sides
    # removed each other).
    seed_rows: tuple = ()
    # Packed-plane mode (r9, ISSUE 4): "i32" keeps the r0-r8 wide key plane
    # and the legacy full-width mask sweeps; "i16" stores view_key (and the
    # pending-key rings) as narrow int16 precedence keys — half the bytes on
    # the tick's dominant plane — and switches the kernel's selection
    # sampler, cluster-size counts, and health reductions to word-parallel
    # popcount sweeps over packed bit planes (ops/bitplane.py). Decoded
    # trajectories are bit-identical between the modes while incarnations
    # stay under the narrow cap and row reuse under the narrow epoch fold
    # (lattice.KeyLayout documents the saturation rule; the lockstep tests
    # in tests/test_bitplane_engine.py pin it). Config spelling:
    # ClusterConfig.sim.plane_dtype.
    key_dtype: str = "i32"
    # Dissemination strategy/topology (r13, dissemination/): the default
    # spec traces the byte-identical legacy program; non-default specs swap
    # ONLY the gossip phase's peer selection / payload policy (FD and SYNC
    # keep the reference's uniform semantics). Config spelling:
    # ClusterConfig.dissemination.
    dissem: DissemSpec = DissemSpec()
    # Quiet-tick gates (r15). The kernel guards its rare/idle work behind
    # ``lax.cond`` — the FD round off-ticks, the fully-quiescent gossip
    # tick, the no-suspect suspicion sweep, the nobody-refuting diagonal
    # write. Each guarded branch is a VALUE-IDENTICAL no-op when its gate
    # is closed (a sweep with no suspects expires nothing, a delivery with
    # no payload accepts nothing), so the gates are pure dispatch-cost
    # optimizations. Under vmap (the r15 fleet engine) a batched-predicate
    # cond runs BOTH branches and materializes a select over every state
    # leaf — [S, N, N] copies per cond per tick that the serial engine
    # never pays. ``quiet_gates=False`` statically traces the ACTIVE
    # branch only: trajectories stay bit-identical (pinned by
    # tests/test_fleet.py), and the fleet program drops the select
    # traffic. Keep True for serial windows (the skips are why quiet
    # steady-state ticks are nearly free); the fleet builders' callers
    # (the MC certification service, config14) set False.
    quiet_gates: bool = True
    # Adaptive failure detection (r14, adaptive.py): the default spec is
    # the byte-identical legacy program; an enabled spec arms the
    # Lifeguard-style local-health + confirmation-scaled suspicion plane
    # (windows built via make_adaptive_run, AdaptiveState threaded through
    # the scan carry). Config spelling: ClusterConfig.adaptive.
    adaptive: AdaptiveSpec = AdaptiveSpec()

    @staticmethod
    def from_config(
        config: ClusterConfig,
        capacity: int | None = None,
        initial_size: int | None = None,
        seed_rows: tuple = (0,),
    ) -> "SimParams":
        """Derive kernel params from a ClusterConfig. Capacity resolution:
        explicit ``capacity`` arg > ``config.sim.capacity`` > ``initial_size``
        (the documented capacity==0 fallback in SimConfig). ``seed_rows``
        default to row 0 — a seedless sim cannot re-bridge healed partitions
        (see the SYNC-peer note in kernel._sync_phase)."""
        sim = config.sim
        cap = capacity or sim.capacity or (initial_size or 0)
        if cap <= 1:
            raise ValueError(
                "sim capacity must be > 1 (set config.sim.capacity, or pass "
                "capacity= / initial_size=)"
            )
        dt = sim.tick_interval
        return SimParams(
            capacity=cap,
            key_dtype=sim.plane_dtype,
            fanout=config.gossip.gossip_fanout,
            repeat_mult=config.gossip.gossip_repeat_mult,
            ping_req_k=config.failure_detector.ping_req_members,
            fd_every=max(1, round(config.failure_detector.ping_interval / dt)),
            sync_every=max(1, round(config.membership.sync_interval / dt)),
            suspicion_mult=config.membership.suspicion_mult,
            rumor_slots=sim.rumor_slots,
            seed_rows=tuple(seed_rows),
            delay_slots=sim.delay_slots,
            fd_direct_timeout_ticks=max(
                0, int(config.failure_detector.ping_timeout / dt)
            ),
            fd_leg_timeout_ticks=max(
                0,
                int(
                    (config.failure_detector.ping_interval
                     - config.failure_detector.ping_timeout) / dt / 2
                ),
            ),
            sync_timeout_ticks=max(0, int(config.membership.sync_timeout / dt)),
            dissem=DissemSpec.from_config(config),
            adaptive=AdaptiveSpec.from_config(config),
        )


class SimState(struct.PyTreeNode):
    """One cluster simulation: N nodes' replicated SWIM state + rumor pool.

    ``view_key[i, j]`` — node i's record for j as the packed precedence key
    ``epoch << 23 | incarnation << 2 | rank`` (:mod:`.lattice`), or
    ``UNKNOWN_KEY`` (-1) when i has no record. Storing the key directly (rather than separate
    status/incarnation planes) makes the merge a one-matrix scatter-max and
    is the memory-lean layout for large N: 8 bytes/cell total with
    ``changed_at``, so N=100k row-sharded fits a v5e-8 (~10 GB/chip).
    Decoded ``view_status`` / ``view_inc`` views are provided as properties
    for host-side consumers.

    ``changed_at[i, j]`` — tick at which i's record for j last changed; a
    record is piggybacked on gossip while ``tick - changed_at <
    repeat_mult * ceil_log2(cluster_size_i)``, the reference's gossip-age
    rule (``GossipProtocolImpl.java:311-320``). For SUSPECT cells it doubles
    as the suspicion-timer start (``MembershipProtocolImpl.java:805-823``):
    every accepted change that leaves a cell SUSPECT is itself the start of
    a (new) suspicion window, so the two stamps are provably equal whenever
    the cell is SUSPECT and a separate ``suspect_since`` plane would be
    redundant. Because each cell's precedence key is strictly monotone
    (DEAD records are kept as tombstones, never removed — ``lattice.py``
    deviation 2 makes them beatable by a higher-incarnation refutation), a
    given record is accepted — and therefore forwarded — at most once per
    cell: every rumor's total circulation is bounded (SIR) and the cluster
    state converges monotonically, with no death-rumor/refutation cycles and
    no stale-record resurrection. DEAD = "removed" at the membership-API
    level (the driver emits REMOVED on the DEAD transition, exactly when the
    reference removes the member, ``onDeadMemberDetected:740-767``).

    Rumor pool: R slots of user gossip (``spreadGossip``), infection bitmap
    ``infected[i, r]`` + ``infected_at`` for the forwarding-age rule; dedup
    (the reference's ``SequenceIdCollector``) is the OR-semantics of the
    bitmap itself. ``infected_from[i, r]`` is the peer that delivered r to i
    (-1 at the origin / before infection): the compact analogue of the
    reference's per-gossip known-infected set (``GossipState.java:18``,
    receiver adds the sender, ``onGossipReq:201-215``) — a sender skips
    forwarding r to its own infection source and to r's origin, which is
    what keeps the per-node message count inside the ``ClusterMath`` bound's
    constant (``ClusterMath.java:54-67``).

    ``loss[i, j]`` — directed link drop probability (the NetworkEmulator's
    outbound loss, ``NetworkEmulator.java:349-369``, as a dense matrix;
    block = loss 1.0).

    ``fetch_rt[i, j]`` — DERIVED: round-trip success probability
    ``(1-loss[i,j])·(1-loss[j,i])``, the metadata-fetch / request-response
    gate probability. Maintained by the host mutators whenever ``loss``
    changes (losses change only between ticks) because computing it in-tick
    needs ``loss.T`` — a materialized [N, N] transpose per tick that
    measured a ~2.5x tick slowdown on TPU. Scalar in the lean-loss mode.

    Link delay (the emulator's exponentially-distributed mean delay,
    ``NetworkEmulator.java:349-369``): ``delay_q[i, j]`` is the geometric
    "one more tick" parameter ``q = exp(-tick_interval / mean_delay)``
    (0 = no delay), computed by the HOST mutators — the only transcendental
    in the delay model, so the kernel and the scalar oracle only ever do
    pure f32 multiplies/compares and stay bit-exact across backends. A
    gossip message drawn delay ``d`` (P(d≥k) = q^k, capped at
    ``delay_slots-1``) lands in the pending rings ``pending_key`` /
    ``pending_inf`` / ``pending_src`` and merges at its arrival tick
    through the normal accept gates (infection stamps carry the ARRIVAL
    tick, so a late receiver forwards the rumor on its own age window, like
    the reference's per-receiver gossip periods). Request-response paths
    (ping, indirect legs, SYNC) don't buffer; they multiply their success
    probability by the closed-form chance the geometric round trip fits the
    protocol timeout — with q=0 those factors are exactly 1.0, so zero-delay
    states reproduce the undelayed trajectories bit-for-bit.
    """

    tick: jax.Array  # i32 scalar
    up: jax.Array  # bool [N] — process running (host/churn controlled)
    epoch: jax.Array  # i32 [N] — row identity generation (bumped on reuse)
    view_key: jax.Array  # i32/i16 [N, N] — packed precedence key, -1 = unknown
    changed_at: jax.Array  # i32 [N, N]
    force_sync: jax.Array  # bool [N] — immediate SYNC request (join bootstrap)
    leaving: jax.Array  # bool [N] — graceful-leave intent (survives record overwrites)
    ns_id: jax.Array  # i32 [N] — namespace group of each row (0 = default)
    ns_rel: jax.Array  # bool [G, G] — precomputed relatedness (host-built)
    rumor_active: jax.Array  # bool [R]
    rumor_origin: jax.Array  # i32 [R]
    rumor_created: jax.Array  # i32 [R]
    infected: jax.Array  # u32 [N, ceil(R/32)] — WORD-PACKED infection bitmap (r9)
    infected_at: jax.Array  # i32 [N, R]
    infected_from: jax.Array  # i32 [N, R] — delivering peer, -1 origin/none
    loss: jax.Array  # f32 [N, N]
    fetch_rt: jax.Array  # f32 [N, N] — derived round-trip probability (see above)
    delay_q: jax.Array  # f32 [N, N] or scalar — geometric delay parameter
    pending_key: jax.Array  # i32/i16 [D, N, N] — delayed candidate-key ring
    pending_inf: jax.Array  # u32 [D, N, ceil(R/32)] — WORD-PACKED delayed-infection ring
    pending_src: jax.Array  # i32 [D, N, R] — delayed rumor source ring

    @property
    def capacity(self) -> int:
        return self.up.shape[0]

    @property
    def rumor_slots(self) -> int:
        return self.rumor_origin.shape[0]

    @property
    def infected_bool(self) -> jax.Array:
        """Unpacked bool [N, R] view of the word-packed infection bitmap —
        for host-side consumers (tests, snapshots, the oracle); the kernel
        unpacks locally where it needs elementwise [N, R] work and keeps
        the stored plane packed (ops/bitplane.py layout)."""
        return bitplane.unpack_bits(self.infected, self.rumor_slots)

    @property
    def pending_inf_bool(self) -> jax.Array:
        """Unpacked bool [D, N, R] view of the pending-infection ring."""
        return bitplane.unpack_bits(self.pending_inf, self.rumor_slots)

    @property
    def view_status(self) -> jax.Array:
        """Decoded status plane (i8, UNKNOWN where no record) — a derived
        view for host-side consumers; the kernel works on ``view_key``."""
        return key_status(self.view_key)

    @property
    def view_inc(self) -> jax.Array:
        """Decoded incarnation plane (i32, 0 where no record)."""
        return key_inc(self.view_key)


def delay_mean_to_q(mean_delay_ticks: float) -> float:
    """Exponential mean delay (in ticks) → geometric parameter q (f32).
    The single place the transcendental runs — on HOST, never in-tick."""
    if mean_delay_ticks <= 0:
        return 0.0
    return float(np.float32(np.exp(np.float32(-1.0 / mean_delay_ticks))))


def build_namespace_tables(namespaces):
    """Per-row namespace strings -> (ns_id [N] i32, ns_rel [G, G] bool) via
    the reference's prefix-hierarchy relatedness
    (``areNamespacesRelated``, ``MembershipProtocolImpl.java:511-536``)."""
    from ..utils.namespaces import are_namespaces_related

    uniq = sorted(set(namespaces))
    gid = {ns: g for g, ns in enumerate(uniq)}
    ids = np.asarray([gid[ns] for ns in namespaces], np.int32)
    g = len(uniq)
    rel = np.zeros((g, g), bool)
    for a in uniq:
        for b in uniq:
            rel[gid[a], gid[b]] = are_namespaces_related(a, b)
    return ids, rel


def init_state(
    params: SimParams,
    n_initial: int,
    warm: bool = True,
    dense_links: bool = True,
    uniform_loss: float = 0.0,
    uniform_delay: float = 0.0,
    namespaces=None,
) -> SimState:
    """Fresh simulation with rows ``0..n_initial-1`` up.

    ``warm=True``: a converged cluster (everyone holds ALIVE@0 records for
    everyone) — the right starting point for FD / gossip / churn benches.
    ``warm=False``: cold rows know only themselves; use :func:`join_row` /
    seed knowledge + SYNC to converge (join-path tests).

    ``dense_links=False`` stores the link loss (and delay parameter) as one
    scalar instead of the [N, N] matrices — required at very large N (each
    dense float32 matrix alone is 40 GB at N=100k); per-link emulator
    controls then raise until densified.

    ``uniform_delay`` is the mean link delay in TICKS (exponential mean, the
    emulator's model); nonzero delay requires ``params.delay_slots > 0``.
    """
    n = params.capacity
    r = params.rumor_slots
    kd = key_np_dtype(params.key_dtype)  # validates the spelling too
    noc = no_candidate(kd)
    wr = bitplane.words_for(r)
    up = jnp.arange(n) < n_initial
    if namespaces is not None:
        ids_np, rel_np = build_namespace_tables(list(namespaces))
        ns_id = jnp.asarray(ids_np)
        ns_rel = jnp.asarray(rel_np)
        related = ns_rel[ns_id[:, None], ns_id[None, :]]
    else:
        ns_id = jnp.zeros((n,), jnp.int32)
        ns_rel = jnp.ones((1, 1), bool)
        related = None
    if warm:
        known = up[:, None] & up[None, :]
        if related is not None:
            known = known & (related | jnp.eye(n, dtype=bool))
        view_key = jnp.where(known, ALIVE0_KEY, UNKNOWN_KEY).astype(kd)
    else:
        diag = jnp.eye(n, dtype=bool) & up[:, None]
        view_key = jnp.where(diag, ALIVE0_KEY, UNKNOWN_KEY).astype(kd)
    loss = (
        jnp.full((n, n), uniform_loss, jnp.float32)
        if dense_links
        else jnp.float32(uniform_loss)
    )
    if uniform_delay > 0 and params.delay_slots <= 0:
        raise ValueError("uniform_delay > 0 requires params.delay_slots > 0")
    if params.delay_slots > 0 and not dense_links:
        raise ValueError(
            "delay_slots > 0 allocates [D, N, N] pending rings, which defeats "
            "the lean dense_links=False mode — use the dense regime for the "
            "delay emulator, or delay_slots=0 at large N"
        )
    q = delay_mean_to_q(uniform_delay)
    delay_q = jnp.full((n, n), q, jnp.float32) if dense_links else jnp.float32(q)
    d = max(0, params.delay_slots)
    return SimState(
        tick=jnp.int32(0),
        up=up,
        epoch=jnp.zeros((n,), jnp.int32),
        view_key=view_key,
        # tick stamps are semantically i32 (absolute tick numbers compared
        # against unbounded windows) — not a packable mask, not a key
        changed_at=jnp.full((n, n), NEVER, jnp.int32),  # lint: allow-wide-plane
        force_sync=jnp.zeros((n,), bool),
        leaving=jnp.zeros((n,), bool),
        ns_id=ns_id,
        ns_rel=ns_rel,
        rumor_active=jnp.zeros((r,), bool),
        rumor_origin=jnp.zeros((r,), jnp.int32),
        rumor_created=jnp.zeros((r,), jnp.int32),
        infected=jnp.zeros((n, wr), jnp.uint32),
        infected_at=jnp.zeros((n, r), jnp.int32),
        infected_from=jnp.full((n, r), -1, jnp.int32),
        loss=loss,
        fetch_rt=_roundtrip(loss),
        delay_q=delay_q,
        pending_key=jnp.full((d, n, n), noc, kd),
        pending_inf=jnp.zeros((d, n, wr), jnp.uint32),
        pending_src=jnp.full((d, n, r), -1, jnp.int32),
    )


def _roundtrip(loss: jax.Array) -> jax.Array:
    """(1-loss)·(1-lossᵀ) — the derived fetch/request round-trip matrix.
    Transpose over the LAST TWO axes: a fleet-stacked [S, N, N] loss plane
    (r15 batched StateTimeline fold) must transpose per scenario, and for
    the serial [N, N] plane swapaxes(-1, -2) IS ``.T``. Anything below
    rank 2 is the UNIFORM-loss mode — the 0-d scalar, or its
    fleet-stacked [S] form (one uniform loss per scenario) — where the
    round trip is symmetric and elementwise."""
    if loss.ndim < 2:
        return ((1.0 - loss) * (1.0 - loss)).astype(jnp.float32)
    return ((1.0 - loss) * (1.0 - jnp.swapaxes(loss, -1, -2))).astype(
        jnp.float32
    )


# ---------------------------------------------------------------------------
# Host-side mutation helpers (pure state -> state, called between ticks).
# These are the sim analogues of lifecycle APIs: Cluster start/shutdown,
# leaveCluster (MembershipProtocolImpl.java:233-242), spreadGossip
# (GossipProtocolImpl.java:126-130), NetworkEmulator block/loss controls.
# ---------------------------------------------------------------------------


def join_row(state: SimState, row: int, seed_rows: jax.Array | list[int]) -> SimState:
    """Activate ``row`` as a fresh member that knows only itself + the seeds.

    Seeds are recorded as ALIVE@0 placeholders (the reference treats seeds as
    bare addresses, ``MembershipProtocolImpl.start0:250-291``); the forced
    initial SYNC then pulls the real table, like the reference's startup SYNC.

    A reused row (a previous occupant crashed/left) gets identity epoch
    ``old+1`` in its self record's high key bits, so the new identity's
    records dominate every stale record of the old occupant — the restart =
    new-member-id rule (and the sim's DEST_GONE; see :mod:`.lattice`). The
    epoch wraps at 256 reuses of a single row; callers (``SimDriver.join``)
    prefer rows no live peer remembers, so near-wrap aliasing never has a
    stale record to collide with.
    """
    kd = state.view_key.dtype
    seed_rows = jnp.asarray(seed_rows, jnp.int32)
    was_used = state.view_key[row, row] >= 0  # row had a previous occupant
    new_epoch = jnp.where(was_used, (state.epoch[row] + 1) & 0xFF, state.epoch[row])
    self_key = precedence_key(jnp.int32(ALIVE), jnp.int32(0), new_epoch, dtype=kd)
    # Seed placeholders carry the seeds' CURRENT epochs — an epoch-0
    # placeholder for a seed that has itself restarted would read as a
    # phantom old identity (and emit a bogus REMOVED+ADDED pair at any
    # watcher the placeholder reaches via the bootstrap SYNC).
    seed_keys = precedence_key(
        jnp.full(seed_rows.shape, ALIVE, jnp.int32),
        jnp.int32(0),
        state.epoch[seed_rows],
        dtype=kd,
    )
    row_key = (
        jnp.full((state.capacity,), UNKNOWN_KEY, kd)
        .at[seed_rows]
        .set(seed_keys)
        .at[row]
        .set(self_key)
    )
    return state.replace(
        up=state.up.at[row].set(True),
        epoch=state.epoch.at[row].set(new_epoch),
        view_key=state.view_key.at[row].set(row_key),
        changed_at=state.changed_at.at[row].set(NEVER).at[row, row].set(state.tick),
        force_sync=state.force_sync.at[row].set(True),
        leaving=state.leaving.at[row].set(False),
        infected=state.infected.at[row].set(0),
        infected_from=state.infected_from.at[row].set(-1),
        # messages still in flight TO this row were addressed to the dead
        # previous occupant (the reference loses them with the connection);
        # the fresh identity must not receive them
        pending_key=state.pending_key.at[:, row].set(no_candidate(kd)),
        pending_inf=state.pending_inf.at[:, row].set(0),
        pending_src=state.pending_src.at[:, row].set(-1),
    )


def join_rows(state: SimState, rows, seed_rows) -> SimState:
    """Vectorized :func:`join_row` for a whole churn burst of DISTINCT rows.

    Semantically identical to folding ``join_row`` over ``rows``, but one
    traced program instead of ~6 copy-on-write device ops per joiner —
    essential under churn at large N, where each host-side ``.at[]`` op on
    an [N, N] plane copies the full matrix (a 163-joiner burst at N=16k
    measured ~25 s un-jitted vs milliseconds jitted+donated). Jit me with
    ``donate_argnums=0``; ``rows``/``seed_rows`` may be traced arrays of
    static shape."""
    kd = state.view_key.dtype
    rows = jnp.asarray(rows, jnp.int32)  # [K]
    seed_rows = jnp.asarray(seed_rows, jnp.int32)  # [S]
    k = rows.shape[0]
    was_used = state.view_key[rows, rows] >= 0
    new_epoch = jnp.where(was_used, (state.epoch[rows] + 1) & 0xFF, state.epoch[rows])
    self_keys = precedence_key(
        jnp.full((k,), ALIVE, jnp.int32), jnp.zeros((k,), jnp.int32), new_epoch,
        dtype=kd,
    )
    # Seed placeholders use POST-burst epochs: if a seed row is itself being
    # rejoined in this burst, the other joiners must record it at its NEW
    # epoch (equivalent to folding join_row with the seed rows joined first)
    # — a stale-epoch placeholder reads as a phantom old identity.
    epoch_after = state.epoch.at[rows].set(new_epoch)
    seed_keys = precedence_key(
        jnp.full(seed_rows.shape, ALIVE, jnp.int32),
        jnp.zeros(seed_rows.shape, jnp.int32),
        epoch_after[seed_rows],
        dtype=kd,
    )
    row_key = (
        jnp.full((k, state.capacity), UNKNOWN_KEY, kd)
        .at[:, seed_rows]
        .set(seed_keys[None, :])
        .at[jnp.arange(k), rows]
        .set(self_keys)
    )
    return state.replace(
        up=state.up.at[rows].set(True),
        epoch=state.epoch.at[rows].set(new_epoch),
        view_key=state.view_key.at[rows].set(row_key),
        changed_at=state.changed_at.at[rows]
        .set(NEVER)
        .at[rows, rows]
        .set(state.tick),
        force_sync=state.force_sync.at[rows].set(True),
        leaving=state.leaving.at[rows].set(False),
        infected=state.infected.at[rows].set(0),
        infected_from=state.infected_from.at[rows].set(-1),
        pending_key=state.pending_key.at[:, rows].set(no_candidate(kd)),
        pending_inf=state.pending_inf.at[:, rows].set(0),
        pending_src=state.pending_src.at[:, rows].set(-1),
    )


def crash_row(state: SimState, row: int) -> SimState:
    """Hard-kill ``row`` (no goodbye — peers must detect via FD + suspicion)."""
    return state.replace(up=state.up.at[row].set(False))


def begin_leave(state: SimState, row: int) -> SimState:
    """Graceful leave: announce LEAVING (self record), keep running so the
    rumor spreads; call :func:`crash_row` a few ticks later to stop.
    Mirrors leaveCluster's LEAVING gossip (``MembershipProtocolImpl.java:233-242``).
    The ``leaving`` mask records the intent outside the overwritable record,
    so refutation re-announces LEAVING (the reference keeps its OWN status,
    ``onSelfMemberDetected``'s r0.status), never resurrecting a leaver."""
    own = state.view_key[row, row]
    leaving_key = ((own >> 2) << 2) | RANK_LEAVING  # keep incarnation
    return state.replace(
        view_key=state.view_key.at[row, row].set(leaving_key),
        changed_at=state.changed_at.at[row, row].set(state.tick),
        leaving=state.leaving.at[row].set(True),
    )


def update_metadata(state: SimState, row: int) -> SimState:
    """Metadata update = own-incarnation bump re-announced ALIVE, exactly the
    reference's ``ClusterImpl.updateMetadata`` path (bump incarnation → peers
    accept the higher-incarnation ALIVE → refetch metadata → UPDATED events,
    ``ClusterImpl.java:497-501``). Peers' UPDATED events are host-side diffs
    of ``view_inc`` increases at ALIVE status; blob versions live on host."""
    own = state.view_key[row, row]
    # +1 incarnation, same rank — through the layout-aware saturating bump
    # (identical to the historical ``.add(4)`` below the narrow cap)
    return state.replace(
        view_key=state.view_key.at[row, row].set(bump_inc(own, own & 3)),
        changed_at=state.changed_at.at[row, row].set(state.tick),
    )


def spread_rumor(state: SimState, slot: int, origin: int) -> SimState:
    """Start a user rumor from ``origin`` in ``slot`` (Cluster.spreadGossip).
    The infection bitmap is word-packed: clear the slot's bit column, then
    set the origin's bit (single-word edits, not an [N, R] rewrite)."""
    infected = bitplane.set_bit(
        bitplane.clear_col(state.infected, slot), origin, slot
    )
    return state.replace(
        rumor_active=state.rumor_active.at[slot].set(True),
        rumor_origin=state.rumor_origin.at[slot].set(origin),
        rumor_created=state.rumor_created.at[slot].set(state.tick),
        infected=infected,
        infected_at=state.infected_at.at[origin, slot].set(state.tick),
        infected_from=state.infected_from.at[:, slot].set(-1),
    )


def set_link_loss(state: SimState, src, dst, loss: float) -> SimState:
    """Set outbound loss on directed link(s) src->dst (emulator
    setOutboundSettings); scalars or sequences on either side."""
    if state.loss.ndim == 0:
        raise ValueError(
            "per-link loss needs dense links; init_state(dense_links=True)"
        )
    src = jnp.atleast_1d(jnp.asarray(src))
    dst = jnp.atleast_1d(jnp.asarray(dst))
    new_loss = state.loss.at[src[:, None], dst[None, :]].set(loss)
    # fetch_rt partial update: only the [src, dst] and mirrored [dst, src]
    # blocks change — O(|src|·|dst|), not a full N² recompute + transpose
    # per host mutation. g[d, s] = loss[d, s] (the reverse legs, unchanged
    # by this call unless inside the block, hence read from new_loss).
    g = new_loss[dst[:, None], src[None, :]]
    fwd = (1.0 - jnp.float32(loss)) * (1.0 - g)  # [D, S] value at (s, d) = fwd.T
    new_rt = state.fetch_rt.at[src[:, None], dst[None, :]].set(fwd.T)
    new_rt = new_rt.at[dst[:, None], src[None, :]].set(fwd)
    return state.replace(loss=new_loss, fetch_rt=new_rt)


def set_link_delay(state: SimState, src, dst, mean_delay_ticks: float) -> SimState:
    """Set the outbound mean delay (in ticks) on directed link(s) src->dst
    (the emulator's ``setOutboundSettings`` delay half). Host-side: converts
    the mean to the geometric q here so the kernel stays transcendental-free."""
    if state.delay_q.ndim == 0:
        raise ValueError(
            "per-link delay needs dense links; init_state(dense_links=True)"
        )
    if mean_delay_ticks > 0 and state.pending_key.shape[0] == 0:
        raise ValueError("link delay requires params.delay_slots > 0")
    src = jnp.atleast_1d(jnp.asarray(src))
    dst = jnp.atleast_1d(jnp.asarray(dst))
    q = delay_mean_to_q(mean_delay_ticks)
    return state.replace(
        delay_q=state.delay_q.at[src[:, None], dst[None, :]].set(q)
    )


def set_uniform_loss(state: SimState, loss: float, floor: bool = False) -> SimState:
    """Uniform link loss across every link (chaos LossStorm site). With
    ``floor=True`` existing losses only ever RISE (``max(loss_ij, loss)``)
    so partition blocks survive a storm; dense mode rewrites the matrix,
    scalar mode swaps the one loss scalar. ``fetch_rt`` is re-derived (the
    one full recompute is fine: losses change only between ticks)."""
    if state.loss.ndim == 0:
        new_loss = jnp.float32(jnp.maximum(state.loss, loss) if floor else loss)
    else:
        new_loss = (
            jnp.maximum(state.loss, jnp.float32(loss))
            if floor
            else jnp.full_like(state.loss, loss)
        )
    return state.replace(loss=new_loss, fetch_rt=_roundtrip(new_loss))


def crash_rows(state: SimState, rows) -> SimState:
    """Vectorized hard-kill of a whole crash cohort (chaos Crash site)."""
    return state.replace(up=state.up.at[jnp.asarray(rows, jnp.int32)].set(False))


def block_partition(state: SimState, group_a, group_b) -> SimState:
    """Symmetric partition: drop all traffic between the two groups."""
    s = set_link_loss(state, group_a, group_b, 1.0)
    return set_link_loss(s, group_b, group_a, 1.0)


def heal_partition(state: SimState, group_a, group_b) -> SimState:
    s = set_link_loss(state, group_a, group_b, 0.0)
    return set_link_loss(s, group_b, group_a, 0.0)


def heal_partition_pair(
    state: SimState, group_a, group_b, clear: float = 0.0
) -> SimState:
    """Heal the symmetric block between two groups down to ``clear`` (the
    active storm's floor during a LossStorm, else 0). Value-identical to two
    directed :func:`set_link_loss` writes — it exists as a NAMED operation so
    the chaos timeline's partition heals are interceptable per-mutator (the
    fleet layer varies partition assignment per scenario by capturing this
    name; a bare ``set_link_loss`` spelling is indistinguishable from an
    asym-loss teardown)."""
    s = set_link_loss(state, group_a, group_b, clear)
    return set_link_loss(s, group_b, group_a, clear)


def set_link_delay_q(state: SimState, src, dst, q) -> SimState:
    """Traceable sibling of :func:`set_link_delay`: writes an ALREADY
    CONVERTED geometric parameter ``q`` (device scalar or traced value) on
    directed link(s) src->dst. The mean→q transcendental stays on host
    (:func:`delay_mean_to_q`); this entry point exists so the fleet layer
    can vmap a per-scenario [S] vector of precomputed q values over the
    batched delay plane."""
    if state.delay_q.ndim == 0:
        raise ValueError(
            "per-link delay needs dense links; init_state(dense_links=True)"
        )
    if state.pending_key.shape[0] == 0:
        raise ValueError("link delay requires params.delay_slots > 0")
    src = jnp.atleast_1d(jnp.asarray(src))
    dst = jnp.atleast_1d(jnp.asarray(dst))
    return state.replace(
        delay_q=state.delay_q.at[src[:, None], dst[None, :]].set(
            jnp.asarray(q, jnp.float32)
        )
    )


def block_partition_assign(state: SimState, assign) -> SimState:
    """Partition from a per-row GROUP ASSIGNMENT vector instead of explicit
    row lists: ``assign[i]`` is row i's group id, ``-1`` = bystander (keeps
    all links). Blocks every cross-group link; value-identical to
    :func:`block_partition` over the corresponding groups. Fully traceable
    in ``assign`` — the fleet layer vmaps an [S, N] assignment plane to give
    every scenario its own partition shape."""
    if state.loss.ndim == 0:
        raise ValueError(
            "per-link loss needs dense links; init_state(dense_links=True)"
        )
    assign = jnp.asarray(assign, jnp.int32)
    cross = (
        (assign[:, None] != assign[None, :])
        & (assign[:, None] >= 0)
        & (assign[None, :] >= 0)
    )
    new_loss = jnp.where(cross, jnp.float32(1.0), state.loss)
    return state.replace(loss=new_loss, fetch_rt=_roundtrip(new_loss))


def heal_partition_assign(state: SimState, assign, clear=0.0) -> SimState:
    """Inverse of :func:`block_partition_assign`: every cross-group link
    drops to ``clear`` (the storm floor during a LossStorm, else 0)."""
    if state.loss.ndim == 0:
        raise ValueError(
            "per-link loss needs dense links; init_state(dense_links=True)"
        )
    assign = jnp.asarray(assign, jnp.int32)
    cross = (
        (assign[:, None] != assign[None, :])
        & (assign[:, None] >= 0)
        & (assign[None, :] >= 0)
    )
    new_loss = jnp.where(cross, jnp.float32(clear), state.loss)
    return state.replace(loss=new_loss, fetch_rt=_roundtrip(new_loss))


def drop_refutes(state: SimState, rows) -> SimState:
    """Byzantine-adjacent refute suppression (chaos ``DroppedRefute`` site):
    for each row in ``rows``, if the row's OWN self record has refuted — its
    diagonal key exceeds the strongest record the REST of the cluster holds
    for it, and that external record is SUSPECT/DEAD — rewind the diagonal
    to the external record, as if the refutation message never existed.

    Sound as a between-window squash because the refute phase runs AFTER
    gossip/SYNC inside a tick: a refute bumped during tick t cannot reach any
    peer before tick t+1, so squashing at the t/t+1 seam suppresses it
    completely. Each later refute re-bumps from the squashed record, so the
    incarnation never runs away. The squashed cell is re-stamped at the
    current tick — the row keeps gossiping the *suspicion about itself* (it
    accepted the verdict it could not refute), and its own suspicion timer
    restarts, so the row never self-transitions to DEAD while squashed.
    Dense state only (needs the [N, N] view + changed_at planes)."""
    from .lattice import RANK_DEAD, RANK_SUSPECT

    rows = jnp.asarray(rows, jnp.int32)
    vk = state.view_key
    n = state.capacity
    col = vk[:, rows]  # [N, K]: every observer's record for each target
    is_self = jnp.arange(n)[:, None] == rows[None, :]
    ext = jnp.max(
        jnp.where(is_self, no_candidate(vk.dtype), col), axis=0
    )  # [K] strongest EXTERNAL record per target
    diag = vk[rows, rows]
    ext_rank = (ext & 3).astype(jnp.int32)
    squash = (
        (diag > ext)
        & (ext >= 0)  # someone actually holds a record
        & ((ext_rank == RANK_SUSPECT) | (ext_rank == RANK_DEAD))
    )
    return state.replace(
        view_key=vk.at[rows, rows].set(jnp.where(squash, ext, diag)),
        changed_at=state.changed_at.at[rows, rows].set(
            jnp.where(squash, state.tick, state.changed_at[rows, rows])
        ),
    )


def snapshot(state: SimState) -> dict[str, np.ndarray]:
    """Host checkpoint: the full state as numpy arrays (SURVEY.md §5.4 —
    checkpoint/resume is an addition over the reference, whose state is soft)."""
    return {f.name: np.asarray(getattr(state, f.name)) for f in dataclasses.fields(SimState)}


def restore(arrays: dict[str, np.ndarray]) -> SimState:
    # Pre-r9 (checkpoint schema <= 2) archives stored the infection planes
    # as bool [N, R] / [D, N, R]; the r9 state packs them into uint32 words.
    # Pack on load — dtype-sniffed rather than schema-gated, so the restore
    # is self-healing for any caller that hands us legacy planes.
    arrays = dict(arrays)
    for name in ("infected", "pending_inf"):
        if name in arrays and arrays[name].dtype != np.uint32:
            arrays[name] = bitplane.pack_bits(
                np.asarray(arrays[name], bool), xp=np
            )
    # copy=True is load-bearing: jnp.asarray ZERO-COPIES a 64-byte-aligned
    # numpy array on CPU, so the restored leaves would alias npz-loaded
    # buffers — which the driver then DONATES into the tick window. The
    # donated alias is a use-after-free once the npz dict is collected
    # (observed as a restored driver diverging with foreign data after a
    # few windows); jax-owned copies make restored state donation-safe.
    return SimState(**{k: jnp.array(v, copy=True) for k, v in arrays.items()})
