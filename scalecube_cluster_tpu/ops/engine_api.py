"""The ONE engine-interface spelling (r11).

Before this module, every engine consumer — SimDriver's window dispatch,
the telemetry plane's ring vector, the trace plane's window-boundary diff,
the chaos runner's sentinel check, the monitor's health snapshot — picked
between the dense and sparse engines with its own ``driver.sparse``
branch, and adding a third engine meant touching them all. Now each engine
registers one :class:`EngineOps` descriptor and every consumer resolves
through :func:`resolve` / :func:`of_driver`:

* **window builders** — ``make_run`` / ``make_traced_run`` /
  ``make_sharded_run`` (None when the engine is single-device), all jit
  with the state (and trace ring) DONATED: the r6 double-buffered
  dispatch discipline is part of the interface, not per-engine folklore.
* **telemetry seam** — ``telemetry_series`` + ``telemetry_window_vector``
  (the r8 metric-ring row).
* **trace seam** — ``tracer_view_cols`` (the r10 window-boundary
  dissemination diff's input: observer-by-tracer key columns, synthesized
  for table engines that hold no [N, N] plane).
* **chaos seam** — ``sentinel_init`` / ``sentinel_reduce`` (the r7
  invariant sentinels).
* **host-view seams** — ``view_row`` (one observer's full-width key row,
  for event diffs / ``view_of``), ``remembered_rows`` (the driver's
  prefer-forgotten-rows join policy), ``staleness`` (the health
  snapshot's identity-dissemination reduce), ``key_plane`` (the narrow-
  layout checkpoint guard), ``pool_slots`` (bounded-pool sizing).
* **audit seam (r12)** — ``contracts`` (:class:`EngineContracts`, the
  static program contracts the audit plane proves over this engine's
  compiled window programs) and ``state_shardings`` (abstract mesh
  placements, so the auditor can lower the mesh-sharded variants without
  allocating a state).
* **fleet seam (r15)** — ``make_fleet_run`` / ``make_fleet_adaptive_run``
  (:mod:`.fleet`): the window vmapped over a leading [S] scenario axis,
  fleet state donated — one XLA program advancing S independent
  clusters, the Monte Carlo certification service's engine surface.

Engines: ``dense`` (:mod:`.kernel` / :mod:`.state`), ``sparse``
(:mod:`.sparse`), ``pview`` (:mod:`.pview` — the r11 O(N·k) partial-view
engine).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class EngineContracts:
    """One engine's static program contracts (r12 audit plane).

    Every flag here is a claim the repo already relies on at runtime; the
    audit plane (:mod:`scalecube_cluster_tpu.audit`) proves each one over
    the engine's CLOSED JAXPR / compiled HLO instead of sampling it from
    runs:

    * ``donation_alias`` — every leaf of every donated argument of the
      window builders must appear in the compiled ``input_output_alias``
      map (a dropped alias is the r6 double-buffer silently degrading to
      a copy), and no donated input may escape the program unchanged
      alongside its aliased update (the caller would see stale donated
      data — the r6 use-after-free shape).
    * ``transfer_free`` — no host-callback / outfeed / infeed primitive
      anywhere in the closed jaxpr (the IR-level form of r6's
      zero-per-window-d2h rule; decorator indirection can't hide a call
      from the jaxpr walk the way it can from a source regex).
    * ``no_plane_materialization`` — no in-scan gather of a wide
      (capacity²-proportional) plane whose value escapes ONLY to the
      per-tick stacked outputs: the measured r10 ~18%/tick pattern (any
      such consumer forces an extra full-plane materialization per tick).
    * ``forbid_wide_values`` — pview only: NO value of any kind in the
      whole closed jaxpr may have two or more capacity-scaled dims. This
      is the O(N·k) no-[N,N]-anywhere guarantee as an IR-level fact
      (the source lint bans allocations; this bans every intermediate
      the compiler actually builds).
    * ``memory_factor`` / ``memory_overhead_mib`` — the window's
      compiler-reported peak (``memory_analysis``: args + temps +
      un-aliased outputs) must stay within
      ``factor * abstract_state_bytes + overhead`` — the declared window
      budget the r9/r11 max-N ladders probe, as a per-engine gate. The
      overhead term absorbs fixed small-N costs (keys, pools, stacked
      per-tick metrics); at ladder-scale N the factor is the whole gate.
    * ``restore_module`` — the module whose checkpoint ``restore()`` seam
      must satisfy the r6 ``jnp.array(copy=True)`` rule (no zero-copy
      host alias ever enters donatable state); checked by the AST lint.
    * ``key_dtypes`` — the key layouts the audit matrix covers.
    * ``strategy_variants`` — (strategy, topology) pairs (r13): the
      non-default dissemination specs whose window programs enter the
      audit matrix alongside the default push/full program, so every
      shipped (engine x strategy) window proves the same donation /
      transfer-freeness / materialization / memory contracts. State
      shapes are spec-independent (circulant adjacency is closed-form),
      so the variants share the engine's abstract state.
    """

    donation_alias: bool = True
    transfer_free: bool = True
    no_plane_materialization: bool = True
    forbid_wide_values: bool = False
    memory_factor: float = 3.0
    memory_overhead_mib: float = 2.0
    restore_module: Optional[str] = None
    key_dtypes: tuple = ("i32",)
    strategy_variants: tuple = ()
    #: r15 fleet variant's memory budget factor (peak / (S × one state)).
    #: Batched windows trade the serial engines' lax.cond quiet-tick skips
    #: for select-over-both-branches, so a fleet window legitimately stages
    #: more live temps per scenario than the serial budget admits; None
    #: inherits ``memory_factor``. The overhead term is shared (fixed
    #: small-S costs amortize across the fleet).
    fleet_memory_factor: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class EngineOps:
    """One engine's plug surface (see the module docstring)."""

    name: str
    ops: object  # host-mutator module (join/crash/leave/links/snapshot/...)
    init_state: Callable  # (params, n_initial, warm, dense_links) -> state
    make_run: Callable  # (params, n_ticks) -> jitted donated window
    make_traced_run: Callable  # (params, n_ticks, trace) -> jitted window
    make_sharded_run: Optional[Callable]  # (mesh, params, n_ticks, dense) or None
    shard_state: Optional[Callable]  # (state, mesh) -> state, or None
    telemetry_series: tuple
    telemetry_window_vector: Callable
    sentinel_init: Callable  # (state, spec) -> accumulator dict
    sentinel_reduce: Callable  # (state, sent, spec) -> sent
    view_row: Callable  # (state, row) -> [N] i32 device keys
    tracer_view_cols: Callable  # (state, tracer_rows) -> [N, K] i32
    remembered_rows: Callable  # (state) -> [N] bool
    staleness: Callable  # (state) -> (stale [N] i32, n_up)
    key_plane: Optional[Callable]  # (state) -> narrow-capable key array
    pool_slots: Optional[Callable]  # (params) -> bounded-pool size
    dense_links_default: bool
    supports_mesh: bool
    has_pool: bool
    # r12 audit seam: the static contracts the audit plane proves over this
    # engine's window programs, and the abstract mesh placements the
    # auditor lowers the sharded variants with (None = single-device only)
    contracts: EngineContracts = EngineContracts()
    state_shardings: Optional[Callable] = None  # (mesh, dense_links, delay_slots)
    #: r14 adaptive-FD window builder ((params, n_ticks) -> jitted window
    #: with (state, adaptive_state) donated, argnums (0, 1)); every engine
    #: registers one — the spec on params must be enabled or it refuses
    make_adaptive_run: Optional[Callable] = None
    #: r15 fleet builders ((params, n_ticks, donate=True) -> jitted vmapped
    #: window over a leading [S] scenario axis, fleet state donated — see
    #: :mod:`.fleet` for the batching rules): the scenario-batched window
    #: and its adaptive twin ((state, ad) donated, argnums (0, 1))
    make_fleet_run: Optional[Callable] = None
    make_fleet_adaptive_run: Optional[Callable] = None
    #: r17 fused tick windows ((params, n_ticks, donate=True) -> jitted
    #: donated window over the engine's FUSED tick — adjacent phases share
    #: intermediates instead of re-deriving them; trajectories are
    #: bit-identical to the unfused windows, pinned by tests/test_fused.py).
    #: Every engine registers all three; the adaptive twin refuses a
    #: default spec (r13/r14 rule), the fleet twin batches scenarios.
    make_fused_run: Optional[Callable] = None
    make_fused_adaptive_run: Optional[Callable] = None
    make_fused_fleet_run: Optional[Callable] = None
    #: r17 sharded adaptive window ((mesh, params, n_ticks) -> jitted
    #: window, (state, adaptive_state) donated, both mesh-placed). None
    #: keeps the r14 "adaptive is single-device" refusal for the engine.
    make_sharded_adaptive_run: Optional[Callable] = None
    #: r20 sharded twins ((mesh, params, n_ticks) -> jitted window):
    #: ``make_sharded_fused_run`` runs the FUSED tick over the mesh;
    #: ``make_sharded_traced_run`` ((mesh, params, n_ticks, trace)) lifts
    #: the r14 "trace capture is single-device" refusal — the ring rides
    #: the donated carry replicated while the member planes shard;
    #: ``make_sharded_fleet_run`` composes the r15 scenario axis with the
    #: member axis on a 2-D mesh (vmap spmd_axis_name over the sharded
    #: core — zero scenario-axis collectives). None keeps the engine's
    #: loud single-device refusal for that capability.
    make_sharded_fused_run: Optional[Callable] = None
    make_sharded_traced_run: Optional[Callable] = None
    make_sharded_fleet_run: Optional[Callable] = None
    #: r21 sharded twin of the donated MetricRing append ((mesh) -> jitted
    #: ``buf.at[idx].set(row)`` with every operand pinned replicated and the
    #: ring buffer donated). The ring layout is engine-agnostic, so one
    #: spelling serves every mesh-capable engine; the audit matrix lowers it
    #: as the ``sharded-telemetry-append`` variant. None keeps the engine's
    #: telemetry ring off-mesh.
    make_sharded_telemetry_append: Optional[Callable] = None


# -- shared seams for the two full-view-plane engines (dense + sparse both
# hold the same [N, N] view_key / [N] up state shape) ------------------------


def _plane_view_row(state, row):
    return state.view_key[row].astype(jnp.int32)


def _plane_tracer_view_cols(state, rows):
    # rows is a small index list consumed by the gather below; it never
    # becomes donatable state
    idx = jnp.asarray(rows, jnp.int32)  # lint: allow-zero-copy (index only)
    return state.view_key[:, idx].astype(jnp.int32)


def _plane_remembered_rows(state):
    return ((state.view_key >= 0) & state.up[:, None]).any(axis=0)


def _plane_staleness(state):
    up = state.up
    vk = state.view_key
    diag = jnp.diagonal(vk)
    stale = (
        jnp.where(
            up[:, None] & up[None, :] & ((vk >> 2) < (diag >> 2)[None, :]),
            1, 0,
        ).sum(axis=0).astype(jnp.int32)
    )
    return stale, up.sum()


def _plane_sentinel_init(sparse):
    from ..chaos.sentinels import init_sentinel_state

    return lambda state, spec: init_sentinel_state(
        state.view_key, spec, sparse=sparse
    )


def _sharded_metric_append(mesh):
    # shared across engines: the metric ring layout is engine-agnostic
    from .sharding import make_sharded_metric_append

    return make_sharded_metric_append(mesh)


def _dense_engine() -> EngineOps:
    from . import kernel as K
    from . import state as S

    def _sharded(mesh, params, n_ticks, dense_links):
        from .sharding import make_sharded_run

        return make_sharded_run(mesh, params, n_ticks, dense_links)

    def _shard_state(state, mesh):
        from .sharding import shard_state

        return shard_state(state, mesh)

    def _shardings(mesh, dense_links, delay_slots):
        from .sharding import state_shardings

        return state_shardings(mesh, dense_links, delay_slots)

    return EngineOps(
        name="dense",
        ops=S,
        init_state=lambda p, n, warm, dense_links: S.init_state(
            p, n, warm=warm, dense_links=dense_links
        ),
        make_run=K.make_run,
        make_traced_run=K.make_traced_run,
        make_sharded_run=_sharded,
        shard_state=_shard_state,
        telemetry_series=tuple(K.TELEMETRY_SERIES),
        telemetry_window_vector=K.telemetry_window_vector,
        sentinel_init=_plane_sentinel_init(sparse=False),
        sentinel_reduce=K.sentinel_reduce,
        view_row=_plane_view_row,
        tracer_view_cols=_plane_tracer_view_cols,
        remembered_rows=_plane_remembered_rows,
        staleness=_plane_staleness,
        key_plane=lambda state: state.view_key,
        pool_slots=None,
        dense_links_default=True,
        supports_mesh=True,
        has_pool=False,
        # measured peak/state at N=128, 4-tick donated window: 1.82x — the
        # factor leaves refactor headroom without ever admitting a second
        # full copy of the [N, N] planes (that is ~2x state on its own,
        # before temps)
        contracts=EngineContracts(
            memory_factor=2.5,
            restore_module="scalecube_cluster_tpu.ops.state",
            key_dtypes=("i32", "i16"),
            # r13: push_pull gathers the contacted peers' piggyback rows —
            # the heaviest non-default strategy program — plus one
            # deterministic-schedule representative; r14 adds the tuneable
            # family (the fifth strategy) to the audited set
            strategy_variants=(
                ("push_pull", "expander"), ("accelerated", "ring"),
                ("tuneable", "expander"),
            ),
        ),
        state_shardings=_shardings,
        make_adaptive_run=K.make_adaptive_run,
        make_fleet_run=K.make_fleet_run,
        make_fleet_adaptive_run=K.make_fleet_adaptive_run,
        make_fused_run=K.make_fused_run,
        make_fused_adaptive_run=K.make_fused_adaptive_run,
        make_fused_fleet_run=K.make_fused_fleet_run,
        make_sharded_telemetry_append=_sharded_metric_append,
    )


def _sparse_engine() -> EngineOps:
    from . import sparse as SP

    def _sharded(mesh, params, n_ticks, dense_links):
        from .sharding import make_sharded_sparse_run

        return make_sharded_sparse_run(mesh, params, n_ticks)

    def _shard_state(state, mesh):
        from .sharding import shard_sparse_state

        return shard_sparse_state(state, mesh)

    def _shardings(mesh, dense_links, delay_slots):
        from .sharding import sparse_state_shardings

        return sparse_state_shardings(mesh, dense_links, delay_slots)

    return EngineOps(
        name="sparse",
        ops=SP,
        init_state=lambda p, n, warm, dense_links: SP.init_sparse_state(
            p, n, warm=warm, dense_links=dense_links
        ),
        make_run=SP.make_sparse_run,
        make_traced_run=SP.make_sparse_traced_run,
        make_sharded_run=_sharded,
        shard_state=_shard_state,
        telemetry_series=tuple(SP.TELEMETRY_SERIES),
        telemetry_window_vector=SP.telemetry_window_vector,
        sentinel_init=_plane_sentinel_init(sparse=True),
        sentinel_reduce=SP.sentinel_reduce,
        view_row=_plane_view_row,
        tracer_view_cols=_plane_tracer_view_cols,
        remembered_rows=_plane_remembered_rows,
        staleness=_plane_staleness,
        key_plane=None,  # sparse keys are i32-only; no narrow checkpoint guard
        pool_slots=lambda params: params.mr_slots,
        dense_links_default=False,
        supports_mesh=True,
        has_pool=True,
        # measured peak/state at N=128, 4-tick donated window: 4.01x — the
        # sparse tick stages per-phase [N, M] working sets (apply staging,
        # pool sweeps) that the dense engine does not, so its honest budget
        # sits higher; the factor still forbids a second whole-state copy
        # on top of the staging
        contracts=EngineContracts(
            memory_factor=5.0,
            restore_module="scalecube_cluster_tpu.ops.sparse",
            strategy_variants=(("pipelined", "expander"),),
            # measured fleet peak/(S × state) at N=128, S=4, 4-tick window:
            # 5.36x — vmap turns the quiet-tick lax.conds into selects that
            # run both branches, so the per-scenario staging sits above the
            # serial 4.01x; 6.0 forbids a second whole-fleet copy on top
            fleet_memory_factor=6.0,
        ),
        state_shardings=_shardings,
        make_adaptive_run=SP.make_sparse_adaptive_run,
        make_fleet_run=SP.make_sparse_fleet_run,
        make_fleet_adaptive_run=SP.make_sparse_fleet_adaptive_run,
        make_fused_run=SP.make_sparse_fused_run,
        make_fused_adaptive_run=SP.make_sparse_fused_adaptive_run,
        make_fused_fleet_run=SP.make_sparse_fused_fleet_run,
        make_sharded_telemetry_append=_sharded_metric_append,
    )


def _pview_engine() -> EngineOps:
    from . import pview as PV

    def _init(p, n, warm, dense_links):
        if dense_links:
            raise ValueError(
                "the pview engine has no [N, N] link plane — partitions use "
                "the group model (dense_links must be False/None)"
            )
        return PV.init_pview_state(p, n, warm=warm)

    def _sharded(mesh, params, n_ticks, dense_links):
        from .sharding import make_sharded_pview_run

        return make_sharded_pview_run(mesh, params, n_ticks)

    def _sharded_adaptive(mesh, params, n_ticks):
        from .sharding import make_sharded_pview_adaptive_run

        return make_sharded_pview_adaptive_run(mesh, params, n_ticks)

    def _sharded_fused(mesh, params, n_ticks):
        from .sharding import make_sharded_pview_fused_run

        return make_sharded_pview_fused_run(mesh, params, n_ticks)

    def _sharded_traced(mesh, params, n_ticks, trace):
        from .sharding import make_sharded_pview_traced_run

        return make_sharded_pview_traced_run(mesh, params, n_ticks, trace)

    def _sharded_fleet(mesh, params, n_ticks):
        from .sharding import make_sharded_pview_fleet_run

        return make_sharded_pview_fleet_run(mesh, params, n_ticks)

    def _shard_state(state, mesh):
        from .sharding import shard_pview_state

        return shard_pview_state(state, mesh)

    def _shardings(mesh, dense_links, delay_slots):
        from .sharding import pview_state_shardings

        return pview_state_shardings(mesh, dense_links, delay_slots)

    return EngineOps(
        name="pview",
        ops=PV,
        init_state=_init,
        make_run=PV.make_pview_run,
        make_traced_run=PV.make_pview_traced_run,
        make_sharded_run=_sharded,
        shard_state=_shard_state,
        telemetry_series=tuple(PV.TELEMETRY_SERIES),
        telemetry_window_vector=PV.telemetry_window_vector,
        sentinel_init=PV.sentinel_init,
        sentinel_reduce=PV.sentinel_reduce,
        view_row=lambda state, row: PV.view_rows(state, [row])[0],
        tracer_view_cols=PV.tracer_view_cols,
        remembered_rows=PV.remembered_rows,
        staleness=PV.staleness,
        key_plane=lambda state: state.nbr_key,
        pool_slots=lambda params: params.mr_pool,
        dense_links_default=False,
        supports_mesh=True,
        has_pool=True,
        # forbid_wide_values IS the engine: no value of any kind in the
        # closed jaxpr may carry two capacity-scaled dims (the r11 O(N·k)
        # guarantee as an IR fact). Measured peak/state at N=128, 4-tick
        # window: 3.43x (table merges stage k+1-record working sets).
        contracts=EngineContracts(
            forbid_wide_values=True,
            memory_factor=4.5,
            restore_module="scalecube_cluster_tpu.ops.pview",
            key_dtypes=("i32", "i16"),
            # measured fleet peak/(S × state) at N=128, S=4, 4-tick window:
            # 4.90x (cond→select staging, same shape as sparse); 5.5 keeps
            # the no-second-fleet-copy rule with modest refactor headroom
            fleet_memory_factor=5.5,
            # r13: the closed-form circulant selection must keep the
            # no-[N, N]-anywhere guarantee — forbid_wide_values is proved
            # over the strategy windows too
            strategy_variants=(
                ("accelerated", "expander"), ("push_pull", "ring"),
            ),
        ),
        state_shardings=_shardings,
        make_adaptive_run=PV.make_pview_adaptive_run,
        make_fleet_run=PV.make_pview_fleet_run,
        make_fleet_adaptive_run=PV.make_pview_fleet_adaptive_run,
        make_fused_run=PV.make_pview_fused_run,
        make_fused_adaptive_run=PV.make_pview_fused_adaptive_run,
        make_fused_fleet_run=PV.make_pview_fused_fleet_run,
        make_sharded_adaptive_run=_sharded_adaptive,
        make_sharded_fused_run=_sharded_fused,
        make_sharded_traced_run=_sharded_traced,
        make_sharded_fleet_run=_sharded_fleet,
        make_sharded_telemetry_append=_sharded_metric_append,
    )


_BUILDERS = {
    "dense": _dense_engine,
    "sparse": _sparse_engine,
    "pview": _pview_engine,
}
_CACHE: dict = {}


def engine(name: str) -> EngineOps:
    """The registered :class:`EngineOps` by name ("dense"/"sparse"/"pview")."""
    if name not in _BUILDERS:
        raise ValueError(f"unknown engine {name!r}; one of {sorted(_BUILDERS)}")
    if name not in _CACHE:
        _CACHE[name] = _BUILDERS[name]()
    return _CACHE[name]


def resolve(params) -> EngineOps:
    """The engine a params object selects (by type — the historical driver
    contract: SimParams → dense, SparseParams → sparse, PviewParams →
    pview)."""
    from .pview import PviewParams
    from .sparse import SparseParams
    from .state import SimParams

    if isinstance(params, PviewParams):
        return engine("pview")
    if isinstance(params, SparseParams):
        return engine("sparse")
    if isinstance(params, SimParams):
        return engine("dense")
    raise TypeError(
        f"params {type(params).__name__} selects no engine (expected "
        "SimParams, SparseParams, or PviewParams)"
    )


def of_driver(driver) -> EngineOps:
    """The driver's engine (drivers cache it as ``driver._eng``)."""
    eng = getattr(driver, "_eng", None)
    return eng if eng is not None else resolve(driver.params)
