"""Per-tick random draw layout, shared by the kernel and the scalar oracle.

All of a tick's randomness is materialized up-front in a fixed order and
shape, so the NumPy oracle (:mod:`.oracle`) can call this same function and
replay byte-identical draws — the lockstep-equivalence strategy of
SURVEY.md §4 ("identical RNG seeds/link matrices → identical state
trajectories").

Draws per tick (N members, fanout f, ping-req k):

* ``fd_sel``      [N, 1+k] — rank draws for probe-target + relay selection
  (distinct sampling without replacement over the live view, see
  ``kernel._sample_distinct``).
* ``fd_direct``   [N]      — direct-ping delivery draw.
* ``fd_relay``    [N, k]   — per-relay indirect-probe delivery draws.
* ``gossip_sel``  [N, f]   — fanout peer rank draws.
* ``gossip_edge`` [N, f]   — per-gossip-edge delivery draws (one message per
  edge carries both membership records and user rumors, exactly as the
  reference's single GOSSIP_REQ does — so one draw per edge).
* ``sync_sel``    [N]      — SYNC peer rank draw.
* ``sync_edge``   [N]      — SYNC round-trip delivery draw.

Total per-tick randomness is O(N·(f+k)). The round-1 layout instead drew
three full [N, N] score matrices and top_k-sorted them just to pick ≤4
distinct peers per row — the dominant O(N²·log N) term of the tick.
"""

from __future__ import annotations

import contextlib
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as _np


class FdRandoms(NamedTuple):
    fd_sel: jax.Array
    fd_direct: jax.Array
    fd_relay: jax.Array


class RoundRandoms(NamedTuple):
    gossip_sel: jax.Array
    gossip_edge: jax.Array
    gossip_delay: jax.Array
    sync_sel: jax.Array
    sync_edge: jax.Array


class TickRandoms(NamedTuple):
    """Union view used by the scalar oracle (kernel consumes the parts)."""

    fd_sel: jax.Array
    fd_direct: jax.Array
    fd_relay: jax.Array
    gossip_sel: jax.Array
    gossip_edge: jax.Array
    gossip_delay: jax.Array
    sync_sel: jax.Array
    sync_edge: jax.Array


# Phase salts for the stateless fetch hash (must differ per merge site so a
# cell's draw is independent across the phases of one tick). The salt enters
# the mixer additively before the row index, so fetch(s1, i, j) ==
# fetch(s2, i + (s1 - s2) mod 2^32, j): salts must differ (in either
# direction mod 2^32) by at least the max row count or one phase's draws are
# a row-shifted copy of another's. These sit exactly 2^30 / 2^31 apart, so
# no pair of rows below 2^30 can collide across phases.
SALT_GOSSIP = 0x40000000
SALT_SYNC_REQ = 0x80000000
SALT_SYNC_ACK = 0xC0000000
# Pull-reply delivery draws (r13 push-pull strategy): one salt per fanout
# slot — SALT_PULL + s * SALT_PULL_STRIDE for slot s. The stride (2^25)
# keeps slots' draws row-independent below 2^25 members per the shift rule
# above, and the whole family [0x20000000, 0x30000000) stays at least 2^28
# away from the merge-site salts for any fanout <= 8.
SALT_PULL = 0x20000000
SALT_PULL_STRIDE = 0x02000000


def fetch_uniform(tick, salt: int, i, j, xp=jnp):
    """Uniform [0,1) draw for the metadata-fetch round trip of receiver ``i``
    about subject ``j`` at ``tick`` (reference: ALIVE records are applied
    only after a successful GET_METADATA_REQ/RESP exchange,
    ``MembershipProtocolImpl.java:636-658``; SURVEY.md §2.2 MetadataStore row
    prescribes "fetch success = link-matrix draw" for sim mode).

    Stateless counter-based hash (Jenkins-style add/shift/xor rounds over
    (tick, salt, i, j)) instead of a keyed [N, N] threefry draw: the
    selection-sampler rework removed the tick's O(N²) RNG cost and this
    keeps it that way. The wide-broadcast rounds use ONLY adds, shifts, and
    xors — TPU has no native 32-bit vector multiply, and a multiplicative
    mixer measured ~3x slower per tick; the one scalar multiply (tick
    seeding) stays off the [N, N] path. Identical uint32 arithmetic under
    ``xp=jnp`` (kernel) and ``xp=np`` (scalar oracle) keeps the lockstep
    equivalence bit-exact.
    """
    u32 = xp.uint32
    # uint32 wraparound is the point of the mixer; numpy warns on scalar
    # overflow (jax doesn't), so silence it for the oracle path only.
    # The i-side is mixed FULLY before j enters: ``i`` broadcasts narrow
    # ([N, 1]-ish) while ``j`` broadcasts wide, so front-loading rounds onto
    # the i-side halves the wide-tensor op count (the gate is evaluated on
    # [N, N] / [N, M] planes every tick).
    guard = _np.errstate(over="ignore") if xp is _np else contextlib.nullcontext()
    with guard:
        h0 = xp.asarray(tick).astype(u32) * u32(0x9E3779B1) + u32(salt)
        a = xp.asarray(i).astype(u32) + h0
        a = a + (a << u32(10))
        a = a ^ (a >> u32(6))
        a = a + (a << u32(3))
        a = a ^ (a >> u32(11))
        a = a + (a << u32(15))
        b = a + xp.asarray(j).astype(u32)
        b = b + (b << u32(10))
        b = b ^ (b >> u32(6))
        b = b + (b << u32(3))
        b = b ^ (b >> u32(11))
        # The high-shift round must stay on the j-side: without it an
        # adjacent-j delta of 1 only reaches ~2^13 before extraction, so the
        # top-24-bit draws across one receiver row are nearly constant and
        # the fetch gate passes/fails whole rows together under loss
        # (min per-row std 0.0002 without this round, 0.273 ≈ iid with it —
        # guarded by test_rand_stats.py).
        b = b + (b << u32(15))
    return (b >> u32(8)).astype(xp.float32) * xp.float32(1.0 / (1 << 24))


class SparseFdRandoms(NamedTuple):
    """Sparse-mode FD draws: rejection-sampling tries instead of rank draws."""

    fd_try: jax.Array  # [N, (1+k)*T] uniforms -> column tries
    fd_direct: jax.Array  # [N]
    fd_relay: jax.Array  # [N, k]


class SparseRoundRandoms(NamedTuple):
    gossip_try: jax.Array  # [N, f*T]
    gossip_edge: jax.Array  # [N, f]
    gossip_delay: jax.Array  # [N, f]
    sync_try: jax.Array  # [N, T]
    sync_fb: jax.Array  # [N] — seed-fallback pick when rejection misses
    sync_edge: jax.Array  # [N]


class SparseRandoms(NamedTuple):
    """Union view for the sparse scalar oracle."""

    fd_try: jax.Array
    fd_direct: jax.Array
    fd_relay: jax.Array
    gossip_try: jax.Array
    gossip_edge: jax.Array
    gossip_delay: jax.Array
    sync_try: jax.Array
    sync_fb: jax.Array
    sync_edge: jax.Array


def draw_sparse_fd(key: jax.Array, n: int, ping_req_k: int, tries: int) -> SparseFdRandoms:
    k1, k2, k3 = jax.random.split(key, 3)
    return SparseFdRandoms(
        fd_try=jax.random.uniform(k1, (n, (1 + ping_req_k) * tries), dtype=jnp.float32),
        fd_direct=jax.random.uniform(k2, (n,), dtype=jnp.float32),
        fd_relay=jax.random.uniform(k3, (n, ping_req_k), dtype=jnp.float32),
    )


def draw_sparse_round(key: jax.Array, n: int, fanout: int, tries: int) -> SparseRoundRandoms:
    k4, k5, k6, k7, k8, k9 = jax.random.split(key, 6)
    return SparseRoundRandoms(
        gossip_try=jax.random.uniform(k4, (n, fanout * tries), dtype=jnp.float32),
        gossip_edge=jax.random.uniform(k5, (n, fanout), dtype=jnp.float32),
        gossip_delay=jax.random.uniform(k8, (n, fanout), dtype=jnp.float32),
        sync_try=jax.random.uniform(k6, (n, tries), dtype=jnp.float32),
        sync_fb=jax.random.uniform(k9, (n,), dtype=jnp.float32),
        sync_edge=jax.random.uniform(k7, (n,), dtype=jnp.float32),
    )


def draw_sparse_randoms(
    key: jax.Array, n: int, fanout: int, ping_req_k: int, tries: int
) -> SparseRandoms:
    """All of a sparse tick's draws (oracle-side convenience; matches the
    kernel's two-subkey layout exactly)."""
    fd_key, round_key = split_tick_key(key)
    fd = draw_sparse_fd(fd_key, n, ping_req_k, tries)
    rd = draw_sparse_round(round_key, n, fanout, tries)
    return SparseRandoms(*fd, *rd)


def split_tick_key(key: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(fd_key, round_key). FD draws live under their own subkey so the
    kernel can skip generating them entirely on non-FD ticks (lax.cond)
    without perturbing the gossip/SYNC draw stream — the oracle derives the
    same two subkeys and stays lockstep."""
    k = jax.random.split(key, 2)
    return k[0], k[1]


def draw_fd_randoms(key: jax.Array, n: int, ping_req_k: int) -> FdRandoms:
    k1, k2, k3 = jax.random.split(key, 3)
    return FdRandoms(
        fd_sel=jax.random.uniform(k1, (n, 1 + ping_req_k), dtype=jnp.float32),
        fd_direct=jax.random.uniform(k2, (n,), dtype=jnp.float32),
        fd_relay=jax.random.uniform(k3, (n, ping_req_k), dtype=jnp.float32),
    )


def draw_round_randoms(key: jax.Array, n: int, fanout: int) -> RoundRandoms:
    k4, k5, k6, k7, k8 = jax.random.split(key, 5)
    return RoundRandoms(
        gossip_sel=jax.random.uniform(k4, (n, fanout), dtype=jnp.float32),
        gossip_edge=jax.random.uniform(k5, (n, fanout), dtype=jnp.float32),
        gossip_delay=jax.random.uniform(k8, (n, fanout), dtype=jnp.float32),
        sync_sel=jax.random.uniform(k6, (n,), dtype=jnp.float32),
        sync_edge=jax.random.uniform(k7, (n,), dtype=jnp.float32),
    )


def draw_tick_randoms(key: jax.Array, n: int, fanout: int, ping_req_k: int) -> TickRandoms:
    """All of a tick's draws (oracle-side convenience; matches the kernel's
    two-subkey layout exactly)."""
    fd_key, round_key = split_tick_key(key)
    fd = draw_fd_randoms(fd_key, n, ping_req_k)
    rd = draw_round_randoms(round_key, n, fanout)
    return TickRandoms(*fd, *rd)
