"""Per-tick random draw layout, shared by the kernel and the scalar oracle.

All of a tick's randomness is materialized up-front in a fixed order and
shape, so the NumPy oracle (:mod:`.oracle`) can call this same function and
replay byte-identical draws — the lockstep-equivalence strategy of
SURVEY.md §4 ("identical RNG seeds/link matrices → identical state
trajectories").

Draws per tick (N members, fanout f, ping-req k):

* ``fd_scores``    [N, N]  — Gumbel-free uniform scores for probe target +
  relay selection (top-(k+1) over masked scores = sample w/o replacement).
* ``fd_direct``    [N]     — direct-ping delivery draw.
* ``fd_relay``     [N, k]  — per-relay indirect-probe delivery draws.
* ``gossip_scores``[N, N]  — fanout peer selection scores.
* ``gossip_edge``  [N, f]  — per-gossip-edge delivery draws (one message per
  edge carries both membership records and user rumors, exactly as the
  reference's single GOSSIP_REQ does — so one draw per edge).
* ``sync_scores``  [N, N]  — SYNC peer selection scores.
* ``sync_edge``    [N]     — SYNC round-trip delivery draw.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class TickRandoms(NamedTuple):
    fd_scores: jax.Array
    fd_direct: jax.Array
    fd_relay: jax.Array
    gossip_scores: jax.Array
    gossip_edge: jax.Array
    sync_scores: jax.Array
    sync_edge: jax.Array


def draw_tick_randoms(key: jax.Array, n: int, fanout: int, ping_req_k: int) -> TickRandoms:
    """Split ``key`` into the tick's uniform draws (fixed order and shapes)."""
    k1, k2, k3, k4, k5, k6, k7 = jax.random.split(key, 7)
    return TickRandoms(
        fd_scores=jax.random.uniform(k1, (n, n), dtype=jnp.float32),
        fd_direct=jax.random.uniform(k2, (n,), dtype=jnp.float32),
        fd_relay=jax.random.uniform(k3, (n, ping_req_k), dtype=jnp.float32),
        gossip_scores=jax.random.uniform(k4, (n, n), dtype=jnp.float32),
        gossip_edge=jax.random.uniform(k5, (n, fanout), dtype=jnp.float32),
        sync_scores=jax.random.uniform(k6, (n, n), dtype=jnp.float32),
        sync_edge=jax.random.uniform(k7, (n,), dtype=jnp.float32),
    )
