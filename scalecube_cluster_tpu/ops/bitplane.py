"""Word-packed bit-plane layout: the ONE packing spelling in the repo (r9).

Every boolean "plane" the engines carry or derive — the dense rumor
infection bitmaps (``SimState.infected`` / ``pending_inf``), the sparse
delivery payload words (``ops/sparse.py``), and the dense kernel's derived
``known`` / live-view masks — shares a single layout: bits pack along the
LAST axis, little-endian within a 32-bit word::

    packed[..., w] bit b  <=>  bool_plane[..., w * 32 + b]

``bool [..., L]  <->  uint32 [..., ceil(L/32)]``, with the tail word's
unused high bits ALWAYS ZERO (pack pads with False; mutators must preserve
the invariant — :func:`tail_mask` is the word mask of valid bits). Keeping
the dead bits zero is what makes :func:`popcount` reductions correct
without re-masking at every use.

Why this exists (ISSUE 4 tentpole): the dense tick is memory-bandwidth-
bound, and a bool plane costs one BYTE per edge on every pass. Packing
turns mask traffic into 1/8 the bytes and turns mask reductions
(cluster-size counts, alive-view fractions, selection-sampler candidate
ranks) into word-parallel popcounts. The sparse engine proved the layout
first (its delivery payloads travel packed); r9 lifts those helpers here
and makes the dense engine store + sweep its bit planes the same way.

Design note — derived masks are NOT stored: ``known`` (``view_key >= 0``)
and the live-view mask (``rank != DEAD``) are recomputed (and packed) from
``view_key`` inside the tick rather than carried as state. A stored copy
would be a second source of truth the merge phases could desynchronize;
packing the derived mask costs one fused pass over the plane that produced
it, which every consumer was already paying.

All helpers take ``xp`` (``jnp`` or ``np``) so the scalar oracle replays
the exact packing arithmetic host-side, like :func:`.rand.fetch_uniform`.
Reductions are integer end-to-end (uint32 words -> int32 counts): no
float64 promotion can sneak into a packed reduction
(``tools/lint_plane_dtypes.py`` guards the spelling).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

WORD = 32  # bits per packed word (uint32 lanes — the TPU-native int width)


def words_for(length: int) -> int:
    """Packed words needed for ``length`` bits (ceil division)."""
    return (int(length) + WORD - 1) // WORD


def tail_mask(length: int, xp=jnp):
    """uint32 [W] mask of VALID bits per word: all-ones except the tail
    word, whose bits past ``length % 32`` are zero. The packed-plane
    invariant is ``plane == plane & tail_mask`` everywhere."""
    w = words_for(length)
    full = np.full((w,), 0xFFFFFFFF, np.uint32)
    rem = int(length) % WORD
    if rem:
        full[-1] = np.uint32((1 << rem) - 1)
    return xp.asarray(full)


def pack_bits(x, xp=jnp):
    """bool [..., L] -> uint32 [..., ceil(L/32)] bitmap words.

    Tail bits beyond L are padded False, so the tail-word invariant holds
    by construction. (Lifted from the sparse engine's ``_pack_bits``, r4;
    generalized to any leading shape for the [D, N, R] pending rings.)"""
    *lead, L = x.shape
    w = words_for(L)
    pad = w * WORD - L
    if pad:
        widths = [(0, 0)] * len(lead) + [(0, pad)]
        x = xp.pad(x, widths)
    xr = x.reshape(*lead, w, WORD).astype(xp.uint32)
    shifts = xp.arange(WORD, dtype=xp.uint32)
    return (xr << shifts).sum(axis=-1, dtype=xp.uint32)


def unpack_bits(p, length: int, xp=jnp):
    """uint32 [..., W] -> bool [..., length]."""
    *lead, w = p.shape
    bits = (p[..., None] >> xp.arange(WORD, dtype=xp.uint32)) & xp.uint32(1)
    return bits.astype(bool).reshape(*lead, w * WORD)[..., :length]


# -- word-parallel boolean algebra (trivial, but naming the ops keeps call
# -- sites readable and gives the lint one spelling to bless) --------------


def word_and(a, b):
    return a & b


def word_or(a, b):
    return a | b


def word_andnot(a, b):
    """a & ~b — the masked-clear sweep (e.g. "known minus self")."""
    return a & ~b


def popcount(w, xp=jnp):
    """Per-word set-bit counts, uint32 -> int32 (SWAR, no float anywhere).

    The classic 5-op parallel bit count; integer end-to-end so packed
    reductions can never promote to float64 under x64 mode."""
    u32 = xp.uint32
    w = w.astype(u32)
    w = w - ((w >> u32(1)) & u32(0x55555555))
    w = (w & u32(0x33333333)) + ((w >> u32(2)) & u32(0x33333333))
    w = (w + (w >> u32(4))) & u32(0x0F0F0F0F)
    return ((w * u32(0x01010101)) >> u32(24)).astype(xp.int32)


def popcount_rows(p, xp=jnp):
    """uint32 [..., W] -> int32 [...]: set bits along the packed axis (the
    word-parallel replacement of ``bool_plane.sum(axis=-1)``)."""
    return popcount(p, xp=xp).sum(axis=-1, dtype=xp.int32)


def popcount_total(p, xp=jnp):
    """Whole-plane set-bit count as an int32 scalar."""
    return popcount(p, xp=xp).sum(dtype=xp.int32)


def row_gather(p, idx):
    """Gather packed rows ``p[idx]`` — one gather of W words per row
    instead of L bools (how the sparse payload pull has always worked;
    named here so dense call sites use the same spelling)."""
    return p[idx]


def diag_words(n: int, xp=jnp):
    """uint32 [N, W]: row i holds the single bit for column i — the packed
    identity matrix, for clearing/checking self-bits in [N, N] masks."""
    rows = xp.arange(n, dtype=xp.uint32)
    w = words_for(n)
    word_idx = xp.arange(w, dtype=xp.uint32)
    return xp.where(
        word_idx[None, :] == (rows // WORD)[:, None],
        xp.uint32(1) << (rows % WORD)[:, None],
        xp.uint32(0),
    )


def select_bit(word, r, xp=jnp):
    """Index of the ``r``-th (1-indexed) set bit of each uint32 ``word``.

    Branch-free 32-step sweep: the running popcount first equals ``r`` AT
    the r-th set bit and only increments on set bits, so the matching
    position is unique. Out-of-range ranks (r < 1 or r > popcount) return
    0 — callers mask those slots (same garbage-but-masked contract as the
    selection samplers)."""
    word = word.astype(xp.uint32)
    r = r.astype(xp.int32)
    cnt = xp.zeros(word.shape, xp.int32)
    out = xp.zeros(word.shape, xp.int32)
    for b in range(WORD):
        bit = ((word >> xp.uint32(b)) & xp.uint32(1)).astype(xp.int32)
        cnt = cnt + bit
        out = xp.where((bit == 1) & (cnt == r), xp.int32(b), out)
    return out


# -- single-bit / single-column mutators (host-side state edits) -----------


def set_bit(p, row, col):
    """Set bit ``col`` of packed row ``row`` (jnp, copy-on-write)."""
    w, b = int(col) // WORD, int(col) % WORD
    return p.at[row, w].set(p[row, w] | jnp.uint32(1 << b))


def clear_col(p, col):
    """Clear bit ``col`` across ALL rows of a packed [N, W] plane."""
    w, b = int(col) // WORD, int(col) % WORD
    return p.at[:, w].set(p[:, w] & jnp.uint32(~(1 << b) & 0xFFFFFFFF))


def col_bits(p, col):
    """bool [...]: bit ``col`` of every packed row (one word gather, not an
    unpack of the whole plane)."""
    w, b = int(col) // WORD, int(col) % WORD
    return (p[..., w] >> jnp.uint32(b)) & jnp.uint32(1) == 1
