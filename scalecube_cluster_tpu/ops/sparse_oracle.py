"""Scalar (per-node-loop NumPy) oracle of the SPARSE tick semantics.

Mirror of :mod:`.sparse` the way :mod:`.oracle` mirrors :mod:`.kernel`
(SURVEY.md §4's lockstep-equivalence strategy): per-node Python loops
consuming byte-identical draws from :func:`.rand.draw_sparse_randoms`; the
equivalence suite steps both and compares the full state every tick. All
float comparisons replay the kernel's float32 op order; all tie-breaking
(first rejection try, earliest duplicate proposal, ascending free slots,
first-max argmax) is mirrored exactly.
"""

from __future__ import annotations

import numpy as np

from .. import adaptive as _adp
from ..dissemination import strategies as _dz
from .lattice import RANK_ALIVE, RANK_DEAD, RANK_LEAVING, RANK_SUSPECT
from .rand import (
    SALT_GOSSIP,
    SALT_SYNC_ACK,
    SALT_SYNC_REQ,
    draw_sparse_randoms,
    fetch_uniform,
)
from .sparse import SparseParams, SparseState

NO_CAND = np.iinfo(np.int32).min
NEVER = -(1 << 30)


def _ceil_log2(n: int) -> int:
    return int(n).bit_length() if n > 0 else 0


class _SO:
    """Mutable numpy mirror of SparseState."""

    def __init__(self, state: SparseState):
        self.tick = int(state.tick)
        for name in (
            "up", "epoch", "joined_at", "view_key", "n_live", "sus_key",
            "sus_since",
            "force_sync", "leaving", "ns_id", "ns_rel", "mr_active", "mr_subject", "mr_key",
            "mr_created", "mr_origin", "minf_age", "rumor_active",
            "rumor_origin", "rumor_created", "infected", "infected_at",
            "infected_from", "loss", "fetch_rt", "delay_q", "pending_minf",
            "pending_inf", "pending_src",
        ):
            setattr(self, name, np.asarray(getattr(state, name)).copy())

    def snap(self):
        import copy

        return copy.deepcopy(self)


def _loss(o, i, j):
    return np.float32(o.loss) if o.loss.ndim == 0 else o.loss[i, j]


def _rt(o, i, j):
    return np.float32(o.fetch_rt) if o.fetch_rt.ndim == 0 else o.fetch_rt[i, j]


def _dq(o, i, j):
    return np.float32(o.delay_q) if o.delay_q.ndim == 0 else o.delay_q[i, j]


def _timely(q1, q2, t: int) -> np.float32:
    q1, q2 = np.float32(q1), np.float32(q2)
    h = np.float32(1.0)
    acc = np.float32(1.0)
    q2p = np.float32(1.0)
    for _ in range(t):
        q2p = np.float32(q2p * q2)
        h = np.float32(np.float32(q1 * h) + q2p)
        acc = np.float32(acc + h)
    return np.float32(np.float32((np.float32(1.0) - q1) * (np.float32(1.0) - q2)) * acc)


def _pick_rejection(o, row: int, u: np.ndarray, n_picks: int, tries: int,
                    seed_mask=None):
    """Mirror of ``sparse._sample_rejection`` for one row: first valid try
    wins; picks held as raw -1-able values for distinctness checks."""
    n = o.up.shape[0]
    sels: list[int] = []
    for p in range(n_picks):
        sel = -1
        for t in range(tries):
            c = min(int(np.float32(np.float32(u[p * tries + t]) * np.float32(n))), n - 1)
            ok = c != row
            live = (int(o.view_key[row, c]) & 3) != RANK_DEAD
            if seed_mask is not None:
                live = live or bool(seed_mask[c])
            ok = ok and live and all(c != q for q in sels)
            if sel < 0 and ok:
                sel = c
        sels.append(sel)
    idx = np.asarray([max(s, 0) for s in sels], np.int32)
    valid = np.asarray([s >= 0 for s in sels], bool)
    return idx, valid


def _fetch_ok(o, salt: int, i: int, j: int) -> bool:
    u = np.float32(fetch_uniform(o.tick, salt, i, j, xp=np))
    p = _rt(o, i, j)
    return bool(o.up[j]) and bool(u < p)


def _accept_gates(o, i: int, j: int, cand: int, salt: int) -> bool:
    own = int(o.view_key[i, j])
    if cand <= own:
        return False
    if own < 0 and (cand & 3) > RANK_LEAVING:
        return False
    if (cand & 3) == RANK_ALIVE and not _fetch_ok(o, salt, i, j):
        return False
    return True


def sparse_oracle_tick(state: SparseState, key, params: SparseParams,
                       ad=None) -> _SO:
    """``ad`` (r14) is a dict ``{"lh", "conf_key", "conf"}`` of [N] int32
    numpy arrays mirroring :class:`..adaptive.AdaptiveState`; the folded
    next state comes back as ``o.ad`` (see ``oracle.oracle_tick``)."""
    n = params.capacity
    f, k_req, T = params.fanout, params.ping_req_k, params.sample_tries
    M, R = params.mr_slots, params.rumor_slots
    D = params.delay_slots
    o = _SO(state)
    o.tick += 1
    t = o.tick
    r = draw_sparse_randoms(key, n, f, k_req, T)
    r = {name: np.asarray(getattr(r, name)) for name in r._fields}

    armed = ad is not None
    if armed:
        aspec = params.adaptive
        ad_miss = np.zeros(n, bool)
        ad_succ = np.zeros(n, bool)
        ad_refuted = np.zeros(n, bool)
        ad_cnt = np.zeros(n, np.int64)
        ad_keym = np.full(n, NO_CAND, np.int64)

        def _ad_note(j: int, cand: int) -> None:
            if (cand & 3) == RANK_SUSPECT:
                ad_cnt[j] += 1
                ad_keym[j] = max(ad_keym[j], cand)

    proposals: list[tuple[list, list, list, list]] = []

    # ---- FD phase ----
    fd_props = ([0] * n, [0] * n, list(range(n)), [False] * n)
    if (t % params.fd_every) == 0:
        pre = o.snap()
        sus_cand = np.full(n, NO_CAND, np.int64)
        V_fd = min(n, params.fd_accept_slots or max(64, n // 16))
        accepted_so_far = 0
        for i in range(n):
            sel, valid = _pick_rejection(pre, i, r["fd_try"][i], 1 + k_req, T)
            if not (valid[0] and pre.up[i]):
                continue
            tgt = int(sel[0])
            p_direct = _rt(pre, i, tgt)
            if D:
                t_dir = params.fd_direct_timeout_ticks
                if armed:
                    t_dir = t_dir * (1 + int(ad["lh"][i]))
                p_direct = np.float32(
                    p_direct
                    * _timely(_dq(pre, i, tgt), _dq(pre, tgt, i), t_dir)
                )
            ack = bool(pre.up[tgt]) and bool(r["fd_direct"][i] < p_direct)
            for s in range(k_req):
                if ack:
                    break
                if not valid[1 + s]:
                    continue
                rl = int(sel[1 + s])
                p4 = np.float32(_rt(pre, i, rl) * _rt(pre, rl, tgt))
                if D:
                    p4 = np.float32(
                        p4 * _timely(_dq(pre, i, rl), _dq(pre, rl, i),
                                     params.fd_leg_timeout_ticks)
                    )
                    p4 = np.float32(
                        p4 * _timely(_dq(pre, rl, tgt), _dq(pre, tgt, rl),
                                     params.fd_leg_timeout_ticks)
                    )
                if pre.up[rl] and pre.up[tgt] and r["fd_relay"][i, s] < p4:
                    ack = True
            own = int(pre.view_key[i, tgt])
            if ack:
                cand = (int(pre.view_key[tgt, tgt]) >> 2) << 2
            else:
                cand = ((own >> 2) << 2) | RANK_SUSPECT
            if armed:
                ad_miss[i] = not ack
                ad_succ[i] = bool(ack)
            if cand > own:
                # verdict throttle: first V accepting rows write this round
                accepted_so_far += 1
                if accepted_so_far > V_fd:
                    continue
                o.view_key[i, tgt] = cand
                fd_props[0][i] = tgt
                fd_props[1][i] = cand
                fd_props[3][i] = True
                if not ack:
                    sus_cand[tgt] = max(sus_cand[tgt], cand)
                    if armed:
                        _ad_note(tgt, cand)
        for j in range(n):
            if sus_cand[j] > int(o.sus_key[j]):
                o.sus_key[j] = sus_cand[j]
                o.sus_since[j] = t
    proposals.append(fd_props)

    # ---- suspicion expiry sweep (per-episode stamps, every sweep_every) ----
    exp_props = ([0] * n, [0] * n, list(range(n)), [False] * n)
    if (t % params.sweep_every) == 0 and bool((o.sus_since > NEVER).any()):
        base = {
            i: _ceil_log2(int(o.n_live[i])) * params.fd_every for i in range(n)
        }
        expired = np.zeros((n, n), bool)
        for i in range(n):
            if not o.up[i]:
                continue
            for j in range(n):
                kij = int(o.view_key[i, j])
                if (kij & 3) != RANK_SUSPECT:
                    continue
                if armed:
                    L = aspec.levels
                    in_ep = kij <= int(ad["conf_key"][j])
                    num = (
                        _adp.conf_mult_num_scalar(aspec, int(ad["conf"][j]))
                        if in_ep
                        else aspec.max_mult * L
                    )
                    timeout_ij = (
                        base[i] * num * (1 + int(ad["lh"][i]))
                    ) // L
                else:
                    timeout_ij = params.suspicion_mult * base[i]
                if (
                    t - int(o.sus_since[j]) >= timeout_ij
                    and kij <= int(o.sus_key[j])
                ):
                    expired[i, j] = True
        # per-subject announcer election: first expiring row; each elected
        # row proposes its first such column (sparse._suspicion_sweep)
        first_row = expired.argmax(axis=0)
        for i in range(n):
            for j in range(n):
                if not expired[i, j]:
                    continue
                o.view_key[i, j] += 1
                o.n_live[i] -= 1
                if not exp_props[3][i] and first_row[j] == i:
                    exp_props[0][i] = j
                    exp_props[1][i] = int(o.view_key[i, j])
                    exp_props[3][i] = True
        # episode reset when no up observer holds any SUSPECT cell
        any_suspect_left = bool(
            (((o.view_key & 3) == RANK_SUSPECT) & o.up[:, None]).any()
        )
        if not any_suspect_left:
            o.sus_key[:] = NO_CAND
            o.sus_since[:] = NEVER
    proposals.append(exp_props)

    # ---- gossip phase ----
    slot_now = t % D if D else 0
    work = bool(o.rumor_active.any()) or bool(o.mr_active.any())
    if D:
        work = work or bool(o.pending_inf[slot_now].any()) or bool(
            o.pending_minf[slot_now].any()
        )
    if work:
        age = o.minf_age
        o.minf_age = np.where(
            age > 0, np.minimum(age, np.uint8(254)) + np.uint8(1), age
        ).astype(np.uint8)
        pre = o.snap()
        spread = {
            i: params.repeat_mult * _ceil_log2(int(pre.n_live[i])) for i in range(n)
        }
        recv_u = (
            pre.pending_inf[slot_now].copy() if D else np.zeros((n, R), bool)
        )
        recv_src = (
            pre.pending_src[slot_now].copy() if D else np.full((n, R), -1, np.int32)
        )
        recv_m = (
            pre.pending_minf[slot_now].copy() if D else np.zeros((n, M), bool)
        )
        # per-sender payloads + peer picks (receiver-independent)
        young_u = np.zeros((n, R), bool)
        young_m = np.zeros((n, M), bool)
        peers_all = np.zeros((n, f), np.int32)
        valid_all = np.zeros((n, f), bool)
        spec = params.dissem
        for i in range(n):
            if spec.uniform_selection:
                peers_all[i], valid_all[i] = _pick_rejection(
                    pre, i, r["gossip_try"][i], f, T
                )
            else:
                peers_all[i], valid_all[i] = _dz.structured_peer_row(
                    spec, n, t, i, r["gossip_try"][i][::T]
                )
            for ru in range(R):
                young_u[i, ru] = (
                    pre.infected[i, ru]
                    and pre.rumor_active[ru]
                    and t - int(pre.infected_at[i, ru]) < spread[i]
                    # r13 pipelined payload budget (DZ-3)
                    and _dz.budget_ok(spec, ru, t, R)
                )
            for m in range(M):
                young_m[i, m] = (
                    pre.mr_active[m]
                    and 0 < int(pre.minf_age[i, m]) <= spread[i]
                )
        sender_has = young_u.any(axis=1) | young_m.any(axis=1)
        # receiver-pulled delivery via per-slot inverse sender indexes
        # (sparse.py deviation 6: highest-row sender wins slot collisions;
        # known-infected/origin filters applied receiver-side)
        for s in range(f):
            inv_now = np.full(n, -1, np.int32)
            inv_late = np.full(n, -1, np.int32)
            d_of = np.zeros(n, np.int32)
            for j in range(n):  # senders
                if not (valid_all[j, s] and sender_has[j] and pre.up[j]):
                    continue
                p = int(peers_all[j, s])
                if not pre.up[p]:
                    continue
                if not bool(
                    r["gossip_edge"][j, s] < (np.float32(1.0) - _loss(pre, j, p))
                ):
                    continue
                dd = 0
                if D:
                    qd = _dq(pre, j, p)
                    qpow = qd
                    for _ in range(1, D):
                        if r["gossip_delay"][j, s] < qpow:
                            dd += 1
                        qpow = np.float32(qpow * qd)
                d_of[j] = dd
                if dd == 0:
                    inv_now[p] = max(inv_now[p], j)
                else:
                    inv_late[p] = max(inv_late[p], j)
                if spec.wants_pull and dd == 0:
                    # push-pull reply (sparse.py DZ-2 mirror): the peer the
                    # undelayed contact reached answers with ITS payload,
                    # gated on the reverse-link hashed draw
                    rev = np.float32(
                        fetch_uniform(t, _dz.pull_salt(s), j, p, xp=np)
                    )
                    if rev < (np.float32(1.0) - _loss(pre, p, j)):
                        for ru in range(R):
                            if (
                                young_u[p, ru]
                                and int(pre.infected_from[p, ru]) != j
                                and int(pre.rumor_origin[ru]) != j
                            ):
                                recv_u[j, ru] = True
                                recv_src[j, ru] = max(int(recv_src[j, ru]), p)
                        for m in range(M):
                            if young_m[p, m] and int(pre.mr_origin[m]) != j:
                                recv_m[j, m] = True
            for i in range(n):  # receivers
                j = int(inv_now[i])
                if j >= 0:
                    for ru in range(R):
                        if (
                            young_u[j, ru]
                            and int(pre.infected_from[j, ru]) != i
                            and int(pre.rumor_origin[ru]) != i
                        ):
                            recv_u[i, ru] = True
                            recv_src[i, ru] = max(int(recv_src[i, ru]), j)
                    for m in range(M):
                        if young_m[j, m] and int(pre.mr_origin[m]) != i:
                            recv_m[i, m] = True
                jl = int(inv_late[i])
                if jl >= 0:
                    sd = (t + int(d_of[jl])) % D
                    for ru in range(R):
                        if (
                            young_u[jl, ru]
                            and int(pre.infected_from[jl, ru]) != i
                            and int(pre.rumor_origin[ru]) != i
                        ):
                            o.pending_inf[sd, i, ru] = True
                            o.pending_src[sd, i, ru] = max(
                                int(o.pending_src[sd, i, ru]), jl
                            )
                    for m in range(M):
                        if young_m[jl, m] and int(pre.mr_origin[m]) != i:
                            o.pending_minf[sd, i, m] = True

        # user-rumor infection
        for i in range(n):
            if not pre.up[i]:
                continue
            for ru in range(R):
                if recv_u[i, ru] and pre.rumor_active[ru] and not pre.infected[i, ru]:
                    o.infected[i, ru] = True
                    o.infected_at[i, ru] = t
                    o.infected_from[i, ru] = recv_src[i, ru]
        # membership-rumor infection + one-shot record application.
        # Mirrors the kernel's vectorized order: gates read the PRE-apply
        # table (own), scatter-max resolves duplicate subjects, liveness
        # deltas count each distinct subject once (first active slot).
        newly = np.zeros((n, M), bool)
        for i in range(n):
            if not pre.up[i]:
                continue
            for m in range(M):
                if recv_m[i, m] and pre.mr_active[m] and int(pre.minf_age[i, m]) == 0:
                    newly[i, m] = True
                    o.minf_age[i, m] = 1
        # pool subjects are unique among active slots (allocation supersedes
        # in place), so each accepted candidate applies directly
        for i in range(n):
            delta = 0
            for m in range(M):
                if not newly[i, m]:
                    continue
                subj = int(pre.mr_subject[m])
                cand = int(pre.mr_key[m])
                own = int(pre.view_key[i, subj])
                if cand <= own:
                    continue
                if own < 0 and (cand & 3) > RANK_LEAVING:
                    continue
                if (cand & 3) == RANK_ALIVE and not _fetch_ok(
                    pre, SALT_GOSSIP, i, subj
                ):
                    continue
                if params.namespace_gate and not bool(
                    pre.ns_rel[pre.ns_id[i], pre.ns_id[subj]]
                ):
                    continue
                o.view_key[i, subj] = cand
                delta += int((cand & 3) != RANK_DEAD) - int((own & 3) != RANK_DEAD)
                if armed:
                    _ad_note(subj, cand)
                if (cand & 3) == RANK_SUSPECT and cand > int(o.sus_key[subj]):
                    o.sus_key[subj] = cand
                    o.sus_since[subj] = t
            o.n_live[i] += delta
        if D:
            o.pending_inf[slot_now] = False
            o.pending_src[slot_now] = -1
            o.pending_minf[slot_now] = False

    # ---- SYNC phase ----
    pre = o.snap()
    K = min(n, params.sync_slots or (n // params.sync_every + 32))
    P = params.sync_announce
    # force-sync callers take compaction slots before periodic ones (r5 —
    # mirrors the kernel's two-stage nonzero layout: force ascending, then
    # periodic ascending, first K)
    due_force = [i for i in range(n) if pre.up[i] and bool(pre.force_sync[i])]
    due_periodic = [
        i
        for i in range(n)
        if pre.up[i]
        and not bool(pre.force_sync[i])
        and ((t + i * params.sync_stagger) % params.sync_every) == 0
    ]
    due_rows = (due_force[:K] + due_periodic[:K])[:K]
    seed_mask = None
    if params.seed_rows:
        seed_mask = np.zeros(n, bool)
        seed_mask[list(params.seed_rows)] = True
    pairs = []  # (slot_index_in_K, caller, peer) for ok round trips
    for slot_i, i in enumerate(due_rows):
        peers, valid = _pick_rejection(
            pre, i, r["sync_try"][i], 1, T, seed_mask=seed_mask
        )
        p = int(peers[0])
        ok_pick = bool(valid[0])
        if not ok_pick and params.seed_rows:
            # seed fallback (sparse._sync_phase): a too-sparse live view
            # draws a configured seed directly
            S = len(params.seed_rows)
            fb = params.seed_rows[
                min(int(np.float32(np.float32(r["sync_fb"][i]) * np.float32(S))), S - 1)
            ]
            if fb != i:
                p = int(fb)
                ok_pick = True
        if not ok_pick:
            continue
        p_rt = _rt(pre, i, p)
        if D:
            p_rt = np.float32(
                p_rt * _timely(_dq(pre, i, p), _dq(pre, p, i),
                               params.sync_timeout_ticks)
            )
        if pre.up[p] and bool(r["sync_edge"][i] < p_rt):
            o.force_sync[i] = False
            pairs.append((slot_i, i, p))

    # REQ: per-peer scatter-max of caller tables, then gates on the winner
    sus_cand = np.full(n, NO_CAND, np.int64)
    by_peer: dict[int, list[int]] = {}
    for slot_i, i, p in pairs:
        by_peer.setdefault(p, []).append(i)
    first_peer = set()
    seen_p: set = set()
    for slot_i, i, p in pairs:
        if p not in seen_p:
            seen_p.add(p)
            first_peer.add(slot_i)
    peer_new_rows: dict[int, np.ndarray] = {}
    for p, callers in by_peer.items():
        buf = pre.view_key[p].copy()
        for i in callers:
            buf = np.maximum(buf, pre.view_key[i])
        new_row = pre.view_key[p].copy()
        for j in range(n):
            cand = int(buf[j])
            own = int(pre.view_key[p, j])
            if cand <= own:
                continue
            if own < 0 and (cand & 3) > RANK_LEAVING:
                continue
            if (cand & 3) == RANK_ALIVE and not _fetch_ok(pre, SALT_SYNC_REQ, p, j):
                continue
            if params.namespace_gate and not bool(
                pre.ns_rel[pre.ns_id[p], pre.ns_id[j]]
            ):
                continue
            new_row[j] = cand
            if armed:
                _ad_note(j, cand)
            if (cand & 3) == RANK_SUSPECT:
                sus_cand[j] = max(sus_cand[j], cand)
        delta = int(
            ((new_row & 3) != RANK_DEAD).sum() - ((pre.view_key[p] & 3) != RANK_DEAD).sum()
        )
        o.view_key[p] = np.maximum(o.view_key[p], new_row)
        o.n_live[p] += delta
        peer_new_rows[p] = new_row

    # ACK: peer's post-REQ row back to each caller
    mid = o.snap()
    caller_acc: dict[int, np.ndarray] = {}
    for slot_i, i, p in pairs:
        ack = mid.view_key[p]
        own_row = mid.view_key[i].copy()
        acc = np.zeros(n, bool)
        new_row = own_row.copy()
        for j in range(n):
            cand = int(ack[j])
            own = int(own_row[j])
            if cand <= own:
                continue
            if own < 0 and (cand & 3) > RANK_LEAVING:
                continue
            if (cand & 3) == RANK_ALIVE and not _fetch_ok(mid, SALT_SYNC_ACK, i, j):
                continue
            if params.namespace_gate and not bool(
                mid.ns_rel[mid.ns_id[i], mid.ns_id[j]]
            ):
                continue
            new_row[j] = cand
            acc[j] = True
            if armed:
                _ad_note(j, cand)
            if (cand & 3) == RANK_SUSPECT:
                sus_cand[j] = max(sus_cand[j], cand)
        delta = int(
            ((new_row & 3) != RANK_DEAD).sum() - ((own_row & 3) != RANK_DEAD).sum()
        )
        o.view_key[i] = np.maximum(o.view_key[i], new_row)
        o.n_live[i] += delta
        caller_acc[i] = np.where(acc, ack, NO_CAND)
    for j in range(n):
        if sus_cand[j] > int(o.sus_key[j]):
            o.sus_key[j] = sus_cand[j]
            o.sus_since[j] = t

    # SYNC re-gossip proposals: top-P accepted keys per participant, mirrored
    # in the kernel's iteration-major concat order over K static slots
    def _top_props(rows_by_slot, acc_by_slot, owner_valid_by_slot):
        subs = [[0] * K for _ in range(P)]
        keys = [[0] * K for _ in range(P)]
        origs = [[0] * K for _ in range(P)]
        vals = [[False] * K for _ in range(P)]
        for slot_i in range(K):
            owner = rows_by_slot.get(slot_i)
            if owner is None:
                continue
            rem = acc_by_slot.get(slot_i)
            for p_i in range(P):
                origs[p_i][slot_i] = owner
                if rem is None:
                    continue
                col = int(np.argmax(rem))
                val = int(rem[col])
                good = val > NO_CAND and owner_valid_by_slot.get(slot_i, False)
                subs[p_i][slot_i] = col
                keys[p_i][slot_i] = val
                vals[p_i][slot_i] = good
                rem = rem.copy()
                rem[col] = NO_CAND
                acc_by_slot[slot_i] = rem
        flat = lambda a: [x for chunk in a for x in chunk]
        return (flat(subs), flat(keys), flat(origs), flat(vals))

    # peers: accepted = cells where the merged row changed, first-peer only
    rows_p, acc_p, valid_p = {}, {}, {}
    for slot_i, i, p in pairs:
        rows_p[slot_i] = p
        if slot_i in first_peer:
            new_row = peer_new_rows[p]
            changed = new_row != pre.view_key[p]
            acc_p[slot_i] = np.where(changed, new_row, NO_CAND).astype(np.int64)
            valid_p[slot_i] = True
    # kernel origin field is `peer` for every slot (invalid slots carry the
    # clamped caller row, but valid=False so values don't matter except
    # origin placement — mirror only valid slots, rest are zeros/False)
    props_p = _top_props(rows_p, acc_p, valid_p)
    rows_c, acc_c, valid_c2 = {}, {}, {}
    for slot_i, i, p in pairs:
        rows_c[slot_i] = i
        acc_c[slot_i] = caller_acc[i].astype(np.int64)
        valid_c2[slot_i] = True
    props_c = _top_props(rows_c, acc_c, valid_c2)
    sync_props = tuple(a + b for a, b in zip(props_p, props_c))

    # ---- refutation (throttled like the FD write) ----
    ref_props = ([0] * n, [0] * n, list(range(n)), [False] * n)
    V_ref = min(n, params.refute_slots or max(64, n // 16))
    needed_so_far = 0
    for i in range(n):
        diag = int(o.view_key[i, i])
        rank = diag & 3
        need = bool(o.up[i]) and (
            rank == RANK_SUSPECT
            or rank == RANK_DEAD
            or (bool(o.leaving[i]) and rank != RANK_LEAVING)
        )
        if need:
            needed_so_far += 1
            if needed_so_far > V_ref:
                need = False
        new_rank = RANK_LEAVING if o.leaving[i] else RANK_ALIVE
        new_diag = (((diag >> 2) + 1) << 2) | new_rank if need else diag
        ref_props[0][i] = i
        ref_props[1][i] = new_diag
        ref_props[3][i] = need
        if need:
            if armed:
                ad_refuted[i] = True
            if rank == RANK_DEAD:
                o.n_live[i] += 1
            o.view_key[i, i] = new_diag
    proposals.append(ref_props)
    proposals.append(sync_props)

    # ---- rumor sweeps ----
    n_up = int(o.up.sum())
    sweep = 2 * (params.repeat_mult * _ceil_log2(n_up) + 1)
    spread = {i: params.repeat_mult * _ceil_log2(int(o.n_live[i])) for i in range(n)}
    for ru in range(R):
        if not o.rumor_active[ru] or t - int(o.rumor_created[ru]) <= sweep:
            continue
        if D and bool(o.pending_inf[:, :, ru].any()):
            continue
        if any(
            o.infected[i, ru] and o.up[i] and t - int(o.infected_at[i, ru]) < spread[i]
            for i in range(n)
        ):
            continue
        o.rumor_active[ru] = False
    for m in range(M):
        if not o.mr_active[m]:
            continue
        pending = D and bool(o.pending_minf[:, :, m].any())
        forwarding = any(
            o.up[i] and 0 < int(o.minf_age[i, m]) <= spread[i] for i in range(n)
        )
        keep = (t - int(o.mr_created[m]) <= sweep) or forwarding or pending
        if params.early_free:
            # joined-after-creation members are exempt (deviation 5, r5).
            # The reference WOULD still forward in-window gossips to them
            # (new members enter remoteMembers and the gossip peer draw);
            # the joiner's forced initial SYNC is what bounds the gap here
            covered = all(
                (not o.up[i])
                or int(o.minf_age[i, m]) > 0
                or int(o.joined_at[i]) > int(o.mr_created[m])
                for i in range(n)
            )
            if covered and not pending:
                keep = False
        if not keep:
            o.mr_active[m] = False
            o.mr_subject[m] = -1
            o.minf_age[:, m] = 0
            if D:
                o.pending_minf[:, :, m] = False

    # ---- announcement allocation ----
    E = params.announce_slots
    subject = [x for p in proposals for x in p[0]]
    key_l = [x for p in proposals for x in p[1]]
    origin = [x for p in proposals for x in p[2]]
    valid = [x for p in proposals for x in p[3]]
    # pre-compaction pool dedup (r5): proposals already covered by an
    # equal-or-stronger active rumor are invalidated BEFORE the E window
    # (mirrors the kernel's pool_key_by_subject scatter in _alloc_phase)
    pool_key_by_subject: dict[int, int] = {}
    for mm in range(M):
        if o.mr_active[mm]:
            pool_key_by_subject[int(o.mr_subject[mm])] = int(o.mr_key[mm])
    valid = [
        v
        and int(key_l[ci]) > pool_key_by_subject.get(int(subject[ci]), NO_CAND)
        for ci, v in enumerate(valid)
    ]
    if any(valid):
        # priority classes = the first three proposal segments (fd, expiry,
        # refute); sync re-gossip never evicts (kernel's _alloc_phase prio)
        n_prio = sum(len(p[0]) for p in proposals[:3])
        compact = [i for i, v in enumerate(valid) if v][:E]
        entries = [
            (int(subject[ci]), int(key_l[ci]), int(origin[ci]), ci < n_prio)
            for ci in compact
        ]
        # batch dedup by subject: max key wins, tie -> earliest entry
        wins = []
        for e, (s, kk, oo, pr) in enumerate(entries):
            lose = any(
                s2 == s and (k2 > kk or (k2 == kk and e2 < e))
                for e2, (s2, k2, _o2, _p2) in enumerate(entries)
                if e2 != e
            )
            if not lose:
                wins.append((s, kk, oo, pr))
        pool_by_subject = {
            int(o.mr_subject[m]): m for m in range(M) if o.mr_active[m]
        }
        # supersede comparisons read the PRE-batch keys, like the kernel's
        # vectorized `replace` (an earlier win may have evicted-and-reused
        # the matched slot; the kernel still compares against the old key
        # and no-ops — the live key would wrongly overwrite the new tenant)
        pre_mr_key = o.mr_key.copy()
        free = [m for m in range(M) if not o.mr_active[m]][:E]
        # priority-eviction victim queue (deviation 3, r5), computed ONCE
        # from the pre-allocation pool exactly like the kernel: fewest
        # still-uncovered NEEDING members first (up & not exempt by the
        # joined-after-creation rule), ties to the lowest slot; batch
        # replace-targets and sub-majority slots excluded; min(E, M) victims
        replace_tgt = {
            pool_by_subject[s]
            for s, kk, _oo, _pr in wins
            if s in pool_by_subject and kk > int(o.mr_key[pool_by_subject[s]])
        }
        need_m = [0] * M
        cov_m = [0] * M
        for m in range(M):
            for i in range(n):
                if o.up[i] and not int(o.joined_at[i]) > int(o.mr_created[m]):
                    need_m[m] += 1
                    if int(o.minf_age[i, m]) > 0:
                        cov_m[m] += 1
        victims = sorted(
            (
                m
                for m in range(M)
                if o.mr_active[m]
                and m not in replace_tgt
                and 2 * cov_m[m] >= need_m[m]
            ),
            key=lambda m: (need_m[m] - cov_m[m], m),
        )[: min(E, M)]
        # SYNC-allocation backpressure (deviation 3, r5): non-priority fresh
        # allocations stop at 7/8 occupancy, exactly like the kernel's
        # rank-based cap. A capped entry still CONSUMES its fresh rank (the
        # kernel's cumsum rank has the same property), so the free slot it
        # would have taken is skipped for later entries.
        a0 = int(np.sum(o.mr_active))
        cap_npr = (M * 7) // 8
        fi = 0  # fresh rank: consumed by EVERY fresh win (kernel cumsum)
        vi = 0
        evicted_slots: set[int] = set()
        for s, kk, oo, pr in wins:
            if s in pool_by_subject:
                slot = pool_by_subject[s]
                if kk <= int(pre_mr_key[slot]):
                    continue  # already covered by an equal/stronger rumor
                assert slot not in evicted_slots  # kernel: replace targets
                # are excluded from eviction, so this cannot collide
                # supersede in place: old infection column + pending cleared
                o.minf_age[:, slot] = 0
                if D:
                    o.pending_minf[:, :, slot] = False
            else:
                r = fi
                fi += 1
                if r < len(free) and (pr or a0 + r < cap_npr):
                    slot = free[r]
                elif pr and vi < len(victims):
                    slot = victims[vi]
                    vi += 1
                    evicted_slots.add(slot)
                    o.minf_age[:, slot] = 0
                    if D:
                        o.pending_minf[:, :, slot] = False
                else:
                    continue
            o.mr_active[slot] = True
            o.mr_subject[slot] = s
            o.mr_key[slot] = kk
            o.mr_created[slot] = t
            o.mr_origin[slot] = oo
            o.minf_age[oo, slot] = 1
    if armed:
        lh2, ck2, cf2 = _adp.fold(
            aspec,
            ad["lh"].astype(np.int32),
            ad["conf_key"].astype(np.int32),
            ad["conf"].astype(np.int32),
            acc_key=ad_keym.astype(np.int32),
            acc_cnt=np.minimum(ad_cnt, np.iinfo(np.int32).max).astype(np.int32),
            miss=ad_miss,
            succ=ad_succ,
            refuted=ad_refuted,
            up=o.up,
            xp=np,
        )
        o.ad = {"lh": lh2, "conf_key": ck2, "conf": cf2}
    return o


def assert_sparse_equivalent(state: SparseState, o: _SO) -> None:
    pairs = {"tick": (int(state.tick), o.tick)}
    for name in (
        "up", "epoch", "joined_at", "view_key", "n_live", "sus_key",
        "sus_since",
        "force_sync", "leaving", "mr_active", "mr_subject", "mr_key",
        "mr_created", "mr_origin", "minf_age", "rumor_active", "rumor_origin",
        "rumor_created", "infected", "infected_at", "infected_from",
        "pending_minf", "pending_inf", "pending_src",
    ):
        pairs[name] = (np.asarray(getattr(state, name)), getattr(o, name))
    for name, (a, b) in pairs.items():
        a, b = np.asarray(a), np.asarray(b)
        if not np.array_equal(a, b):
            diff = np.argwhere(a != b)
            raise AssertionError(
                f"sparse kernel/oracle divergence in {name} at "
                f"{diff[:10].tolist()} (kernel="
                f"{a[tuple(diff[0])] if diff.size else a}, "
                f"oracle={b[tuple(diff[0])] if diff.size else b})"
            )
