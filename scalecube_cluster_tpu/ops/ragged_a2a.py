"""Ragged all-to-all delivery exchange for the sharded pview engine (r20).

The pview delivery step is an inverse-sender election: for fanout slot f
and receiver p, among every sender s with ``ok_now[f, s]`` whose slot-f
target is p, the MAX sender index wins and its payload row is delivered.
The single-device spelling (``ops/pview.py`` / ``delivery_combine_xla``)
computes the [F, N] inverse index with a global scatter-max and then
gathers whole payload rows — under GSPMD that gather all-gathers the
row-sharded [N, Wt] payload table onto every shard, which is exactly the
traffic pattern row sharding exists to avoid.

This module is the shard-local rewrite. Each shard owns L = N/S member
rows (senders AND receivers — the row shard is the same on both sides):

1. **Record build** — every local sender row j contributes one candidate
   record per fanout slot: ``(receiver, f, sender, payload_row)``. At
   most F·L records per shard, by construction.
2. **Bucketing** — records are grouped by destination shard
   (``receiver // L``) into a static ``[S, B, 3 + Wt]`` u32 send buffer:
   budget B slots per destination, deterministic first-B-in-record-order
   placement (record order is fanout-slot-major, local-row-minor — a
   pure function of the trace, so drops are reproducible). Records past
   the budget are COUNTED, not silently lost: the overflow counter is
   psummed and surfaced as the ``delivery_overflow`` metric — the
   static-shape sentinel the audit plane can see.
3. **Exchange** — one ``jax.lax.all_to_all`` (tiled) over the member
   axis: shard d receives every other shard's bucket-d rows. This is the
   ONLY member-axis collective the delivery leg needs.
4. **Shard-local election** — scatter-max of ``sender + 1`` into the
   local [F, L] inverse table, then a second scatter-max of the unique
   winner's payload words (a (f, sender) pair targets one receiver, so
   the winner's record is unique and max == copy). The receiver-side
   fold (OR / max / count) is then ``delivery_combine_xla``'s exact
   math on local rows.

**Bit-identity**: with the default budget B = F·L one bucket can hold
every record a shard can produce, so nothing is ever dropped and the
elected (sender, payload) per (f, receiver) equals the global election's
— the sharded trajectory is bit-identical to single-device (proved in
tests/test_sharding.py). Smaller budgets drop deterministically and fire
the sentinel (tests/test_ragged_a2a.py holds falsifiability both ways).

No value here carries two capacity-scaled dims: the buffers are
``[S, B, 3 + Wt]`` / ``[S·B, 3 + Wt]`` with S·B ≤ F·N and Wt capacity-
independent, so ``forbid_wide_values`` holds over the armed program
(the r12 ``sharded`` audit variant proves it per-shard).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .bitplane import unpack_bits

#: u32 header words per exchanged record, before the Wt payload words:
#: local receiver row, fanout slot, sender + 1 (0 = empty bucket slot)
HEADER_WORDS = 3


def default_budget(fanout: int, capacity: int, mesh_size: int) -> int:
    """The provably-lossless per-(src, dst) bucket budget: one shard
    emits at most ``fanout * (capacity // mesh_size)`` records TOTAL, so
    a bucket of that size can never overflow regardless of how skewed
    the receiver draw is."""
    return fanout * (capacity // mesh_size)


def ragged_delivery_combine(
    payload: jax.Array,
    p_all: jax.Array,
    ok_now_all: jax.Array,
    rumor_origin: jax.Array,
    Wm: int,
    R: int,
    *,
    mesh,
    axis: str,
    budget: int | None = None,
):
    """Shard-local election + ragged all-to-all record exchange.

    Args:
      payload: [N, Wt] u32 row-sharded sender payload (``Wm`` membership
        words, Wu packed user-rumor words, R infected-from lanes).
      p_all: [F, N] i32 per-slot receiver targets (global row ids),
        sharded on dim 1.
      ok_now_all: [F, N] bool undelayed-send mask, sharded on dim 1.
      rumor_origin: [R] i32, replicated.
      Wm, R: static word/lane splits of the payload.
      mesh: the device mesh; ``axis`` names its member axis.
      budget: per-(src, dst) record budget B (None = the lossless
        ``default_budget`` — bit-identical to the global election).

    Returns ``(u_or [N, R] bool, src_max [N, R] i32, m_or [N, Wm] u32,
    cnt i32, overflow i32)`` — the first three row-sharded, the counters
    replicated (psummed). ``overflow`` counts records dropped by budget
    saturation this tick (0 under the default budget, by construction).
    """
    from jax.experimental.shard_map import shard_map

    F, n = p_all.shape
    S = mesh.shape[axis]
    if n % S:
        raise ValueError(
            f"capacity {n} not divisible by member-mesh size {S}"
        )
    L = n // S
    Wt = payload.shape[1]
    Wu = Wt - Wm - R
    B = budget if budget is not None else default_budget(F, n, S)
    if not (0 < B <= F * L):
        raise ValueError(
            f"a2a budget must be in (0, F*L] = (0, {F * L}]: got {B} "
            "(budgets beyond F*L waste exchange bytes on provably-empty "
            "slots)"
        )
    WREC = HEADER_WORDS + Wt

    def local(payload_l, p_l, ok_l, origin):
        # payload_l [L, Wt] u32; p_l / ok_l [F, L]; origin [R]
        me = jax.lax.axis_index(axis)
        base = (me * L).astype(jnp.int32)
        # -- 1. records, fanout-slot-major / local-row-minor ----------------
        recv_g = p_l.reshape(-1)  # [F*L] global receiver ids
        valid = ok_l.reshape(-1)
        sender1 = jnp.tile(
            (base + jnp.arange(L, dtype=jnp.int32) + 1).astype(jnp.uint32), F
        )
        fidx = jnp.repeat(jnp.arange(F, dtype=jnp.uint32), L)
        pl_rec = jnp.broadcast_to(
            payload_l[None], (F, L, Wt)
        ).reshape(F * L, Wt)
        lr = (recv_g % L).astype(jnp.uint32)
        dest = recv_g // L
        rec = jnp.concatenate(
            [
                lr[:, None],
                fidx[:, None],
                jnp.where(valid, sender1, jnp.uint32(0))[:, None],
                pl_rec,
            ],
            axis=1,
        )
        # -- 2. bucket by destination shard, static budget B ----------------
        buf = jnp.zeros((S, B, WREC), jnp.uint32)
        overflow = jnp.int32(0)
        for d in range(S):
            mask = valid & (dest == d)
            pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
            keep = mask & (pos < B)
            buf = buf.at[d, jnp.where(keep, pos, B)].max(
                jnp.where(keep[:, None], rec, jnp.uint32(0)), mode="drop"
            )
            overflow = overflow + jnp.maximum(
                mask.sum(dtype=jnp.int32) - B, 0
            )
        # -- 3. the one member-axis collective ------------------------------
        got = jax.lax.all_to_all(
            buf, axis, split_axis=0, concat_axis=0, tiled=True
        ).reshape(S * B, WREC)
        # -- 4. shard-local election + delivery fold ------------------------
        r_lr = jnp.minimum(got[:, 0], jnp.uint32(L - 1)).astype(jnp.int32)
        r_f = jnp.minimum(got[:, 1], jnp.uint32(F - 1)).astype(jnp.int32)
        r_s1 = got[:, 2]  # sender + 1; 0 = empty slot
        r_pl = got[:, HEADER_WORDS:]
        vr = r_s1 > 0
        inv1 = (
            jnp.zeros((F, L), jnp.uint32)
            .at[r_f, r_lr]
            .max(jnp.where(vr, r_s1, jnp.uint32(0)))
        )
        win = vr & (r_s1 == inv1[r_f, r_lr])
        pl_e = (
            jnp.zeros((F, L, Wt), jnp.uint32)
            .at[r_f, r_lr]
            .max(jnp.where(win[:, None], r_pl, jnp.uint32(0)))
        )
        has = (inv1 > 0)[:, :, None]
        j_all = jnp.maximum(inv1.astype(jnp.int32) - 1, 0)
        grow = base + jnp.arange(L, dtype=jnp.int32)
        yu = unpack_bits(pl_e[:, :, Wm : Wm + Wu], R)
        frm = pl_e[:, :, Wm + Wu :].astype(jnp.int32)
        deliver = (
            yu
            & has
            & (frm != grow[None, :, None])
            & (origin[None, None, :] != grow[None, :, None])
        )
        u_or = deliver.any(axis=0)
        src_max = jnp.where(deliver, j_all[:, :, None], -1).max(axis=0)
        m_or = functools.reduce(
            jnp.bitwise_or,
            [
                jnp.where(has[s], pl_e[s, :, :Wm], jnp.uint32(0))
                for s in range(F)
            ],
            jnp.zeros((L, Wm), jnp.uint32),
        )
        cnt = jax.lax.psum(deliver.sum(), axis)
        overflow = jax.lax.psum(overflow, axis)
        return u_or, src_max, m_or, cnt, overflow

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis, None), P(None, axis), P(None, axis), P()),
        out_specs=(P(axis, None), P(axis, None), P(axis, None), P(), P()),
        check_rep=False,
    )(payload, p_all, ok_now_all, rumor_origin)
