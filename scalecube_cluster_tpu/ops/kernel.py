"""The vectorized SWIM tick: all N nodes' protocol rounds as one XLA program.

One call advances simulated time by one gossip period and runs, in order:

1. **FD phase** (every ``fd_every`` ticks) — each up node picks a probe
   target uniformly from its live view (the reference's shuffled round-robin,
   ``FailureDetectorImpl.selectPingMember:352-361``; random-without-
   replacement has the same per-round marginal), direct ping succeeds with
   probability ``(1-loss_ij)(1-loss_ji)`` (times the chance the round trip
   beats pingTimeout under the delay model) iff the target is up; on
   failure, ``k`` relays run the indirect probe (``doPingReq:173-210``);
   all-fail ⇒ SUSPECT verdict, any-ack ⇒ ALIVE verdict carrying the
   target's current self-key — including its identity EPOCH, so a probe of
   a restarted row overrides the stale identity in one step (the DEST_GONE
   verdict, ``computeMemberStatus:382-404``; see :mod:`.lattice`). The
   sub-interval ping timeout + remainder-of-interval indirect window
   collapse into phases of a single tick (SURVEY.md §7 hard part i).
2. **Suspicion sweep** — SUSPECT entries older than
   ``suspicion_mult * ceil_log2(n_i) * fd_every`` ticks become DEAD
   (``ClusterMath.suspicionTimeout`` in tick units; timer-per-entry matrix
   compared against the tick counter, SURVEY.md §2.2).
3. **Gossip phase** (every tick) — each up node picks ``fanout`` distinct
   peers (``selectGossipMembers:322-343``) and sends one message carrying
   (a) every membership record changed within the last
   ``repeat_mult*ceil_log2(n_i)`` ticks (``selectGossipsToSend:311-320``)
   and (b) every young user rumor it's infected with, MINUS rumors the peer
   is known to have (its delivery source / origin — the reference's
   per-gossip infected set, ``GossipState.java:18``), which keeps message
   cost inside the ClusterMath bound. Delivery is one Bernoulli draw per
   edge plus a geometric delay draw: messages land 0..D-1 ticks later
   through scatter-max pending rings. Receivers fold records in via the
   scatter-max precedence-key join (:mod:`.lattice`) — ALIVE winners gated
   on a metadata-fetch round trip to the subject
   (``MembershipProtocolImpl.java:636-658``) — and OR in rumor infections
   (bitmap OR = the SequenceIdCollector dedup — double delivery is
   impossible).
4. **SYNC phase** — nodes whose stagger slot matches (or with
   ``force_sync``, the join bootstrap) pick one random live peer and run the
   full-table exchange: request merge into the peer, then the peer's merged
   table back into the caller (``doSync:339-357``, ``onSync:394-415``).
   Anti-entropy: this is what heals partitions the rumor window missed.
5. **Refutation** — any up node whose own diagonal record turned SUSPECT
   bumps its incarnation and re-announces ALIVE
   (``onSelfMemberDetected:686-708``), which re-enters the gossip stream via
   ``changed_at``.
6. **Rumor sweep** — a slot is reclaimed once its creation window
   (``2*(spread+1)`` periods, ``getGossipsToRemove:350-358``) has passed,
   no copy is still in flight, and no receiver is inside its own forwarding
   window (the reference's per-node hold after arrival).

Everything is static-shaped and branch-free (masks, no Python control flow
on traced values); the per-tick cost is O(N²·fanout) elementwise work — no
MXU, pure VPU/HBM, which is why the dense formulation stays fast to ~16k
members on one chip and shards row-wise beyond that (see :mod:`.sharding`).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from .. import adaptive as _adp
from ..dissemination import strategies as dz
from . import bitplane as bp
from .lattice import (
    RANK_ALIVE,
    RANK_DEAD,
    RANK_LEAVING,
    RANK_SUSPECT,
    bump_inc,
    key_np_dtype,
    no_candidate,
)
from .rand import (
    SALT_GOSSIP,
    SALT_SYNC_ACK,
    SALT_SYNC_REQ,
    FdRandoms,
    RoundRandoms,
    draw_fd_randoms,
    draw_round_randoms,
    fetch_uniform,
    split_tick_key,
)
from .state import NEVER as NEVER_I32, NO_CANDIDATE_I32, SimParams, SimState


def ceil_log2(n: jnp.ndarray) -> jnp.ndarray:
    """Reference ``ClusterMath.ceilLog2 = 32 - numberOfLeadingZeros(n)``
    (``ClusterMath.java:133-135``), exactly, via integer compare-and-count."""
    n = n.astype(jnp.int32)
    return (n[..., None] >= (1 << jnp.arange(31, dtype=jnp.int32))).sum(-1).astype(jnp.int32)


def _packed(params: SimParams) -> bool:
    """Static switch: the r9 packed mode (narrow i16 keys + word-parallel
    bit-plane sweeps) vs the legacy full-width spellings. Both modes compute
    the SAME picks, counts, and accepted records — the packed path just
    moves mask traffic into uint32 words (ops/bitplane.py)."""
    return params.key_dtype == "i16"


def _noc(params: SimParams) -> int:
    """Scatter-max identity for the configured key dtype (python int —
    weakly typed at use sites, so i16 planes stay i16)."""
    return no_candidate(key_np_dtype(params.key_dtype))


def _live_view_mask(state: SimState) -> jax.Array:
    """candidates[i, j] — j is in node i's member list (known, not DEAD, not
    self): the FD ping list / gossip member list / SYNC address pool, which
    the reference maintains from ADDED/REMOVED events
    (``FailureDetectorImpl.java:321-333``). Rank != DEAD alone suffices: the
    only negative table key is -1 (unknown), whose rank bits also read 3."""
    n = state.capacity
    known_live = (state.view_key & 3) != RANK_DEAD
    return known_live & ~jnp.eye(n, dtype=bool)


def _known_live_words(state: SimState) -> jax.Array:
    """Word-packed ``rank != DEAD`` plane (diag INCLUDED) — the packed
    mode's one derived membership bit plane per phase, serving cluster-size
    popcounts and (self-bit cleared) the selection samplers. Derived, never
    stored: see the design note in :mod:`.bitplane`."""
    return bp.pack_bits((state.view_key & 3) != RANK_DEAD)


def _cluster_size(state: SimState) -> jax.Array:
    """Node i's view of cluster size (incl. itself) — drives the log2 knobs."""
    return ((state.view_key & 3) != RANK_DEAD).sum(axis=1).astype(jnp.int32)


# NOTE on the merge-accept gate (spelled inline at each phase's merge —
# the gossip scatter-max fold, the SYNC REQ and ACK merges): ``buf`` holds
# the cellwise max of own key and every delivered candidate, and a cell
# accepts iff the winner strictly overrides (``buf > own``), the
# null-record rule holds (SUSPECT/DEAD rejected for unknown members —
# ``MembershipRecord.isOverrides``), the receiver is up, and the ALIVE
# metadata-fetch gate passes. Accepted updates (re-)enter the gossip
# stream via ``changed_at``; because each cell's key is strictly monotone
# (DEAD is a kept tombstone — ``lattice.py`` deviation 2), a given key is
# accepted at most once per cell, so every rumor's forwarding is bounded
# (SIR) and the system converges monotonically. (A standalone ``_merge``
# helper used to restate this; it had no callers and hardcoded the i32
# NO_CANDIDATE sentinel, so the r9 key-dtype work removed it rather than
# leave a dtype trap.)


def _sample_distinct(mask: jax.Array, u: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row k distinct uniform picks (without replacement) from the
    candidate set ``mask[i]``, consuming one uniform per pick (``u`` is
    [N, k]) instead of a full [N, N] score matrix.

    Exact sampling without replacement by rank insertion: the s-th draw
    picks a rank in ``[0, c_i - s)`` and is shifted up past the ``s``
    already-taken ranks in ascending order; ranks map to column indices
    through the mask's per-row cumsum (binary search). Same per-round
    uniform marginal as the reference's shuffled-cursor selection
    (``FailureDetectorImpl.selectPingMember:352-361``) and as round 1's
    masked top-k — but O(N·k + N²) cheap elementwise work in place of the
    O(N²) threefry + O(N²·log N) sort that dominated the round-1 tick.

    Returns (idx [N, k], valid [N, k]); invalid slots hold a clipped
    in-bounds index and must stay masked by the caller. Invalid slots can
    only follow valid ones (slot s is valid iff ``s < c_i``), so garbage
    ranks never perturb valid draws.
    """
    k = u.shape[1]
    c = mask.sum(axis=1).astype(jnp.int32)  # [N] candidate counts
    cs = jnp.cumsum(mask.astype(jnp.int32), axis=1)  # [N, N]
    # rank -> column: first j with cs[i, j] >= x+1 for all k draws at once —
    # one batched binary search over the sorted cumsum rows (O(N·k·log N))
    # instead of k full [N, N] argmax sweeps. Invalid slots (x+1 > c) return
    # n (clipped below): garbage the caller masks via `valid`.
    targets = _insertion_ranks(c, u) + 1  # [N, k]
    idx = jax.vmap(lambda row, t: jnp.searchsorted(row, t, side="left"))(cs, targets)
    idx = jnp.minimum(idx, mask.shape[1] - 1).astype(jnp.int32)
    valid = jnp.arange(k, dtype=jnp.int32)[None, :] < c[:, None]
    return idx, valid


def _insertion_ranks(c: jax.Array, u: jax.Array) -> jax.Array:
    """The shared rank-insertion draw of both samplers: the s-th pick draws
    a rank in ``[0, c - s)`` and is shifted up past the already-taken ranks
    in ascending order. Depends only on the candidate COUNTS, so the packed
    and full-width samplers consume identical uniforms into identical
    ranks — the lockstep invariant between the modes."""
    k = u.shape[1]
    ranks: list[jax.Array] = []
    for s in range(k):
        avail = jnp.maximum(c - s, 1)
        x = (u[:, s] * avail.astype(jnp.float32)).astype(jnp.int32)
        x = jnp.minimum(x, avail - 1)
        if ranks:
            prev = jnp.sort(jnp.stack(ranks, 0), axis=0)  # [s, N] ascending
            for t in range(len(ranks)):
                x = x + (x >= prev[t]).astype(jnp.int32)
        ranks.append(x)
    return jnp.stack(ranks, 1)  # [N, k]


def _sample_distinct_words(
    mask_w: jax.Array, n: int, u: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Word-parallel :func:`_sample_distinct`: same picks, packed mask.

    The full-width sampler's dominant cost at large N is the [N, N] int32
    cumsum it materializes just to map ranks to columns (~64 MB written +
    re-read per selection at N=4096 — the single biggest term of the r8 FD
    tick). Here the cumulative counts live at WORD granularity
    ([N, ceil(N/32)] via popcount), the binary search runs over words, and
    the final bit offset comes from a 32-step in-word bit-rank sweep
    (:func:`.bitplane.select_bit`) — the rank→column answer is the same
    "first column with cumulative count >= target" in both spellings, so
    picks are bit-identical given the same mask and uniforms.

    Returns (idx [N, k], valid [N, k]) under the same garbage-but-masked
    contract as the full-width sampler."""
    k = u.shape[1]
    pc = bp.popcount(mask_w)  # [N, W] per-word counts
    cs = jnp.cumsum(pc, axis=1)  # [N, W] — words, not columns
    c = cs[:, -1]  # [N] candidate counts
    targets = _insertion_ranks(c, u) + 1  # [N, k]
    wi = jax.vmap(lambda row, t: jnp.searchsorted(row, t, side="left"))(cs, targets)
    wi = jnp.minimum(wi, mask_w.shape[1] - 1).astype(jnp.int32)
    prior = jnp.where(
        wi > 0, jnp.take_along_axis(cs, jnp.maximum(wi - 1, 0), axis=1), 0
    )
    word = jnp.take_along_axis(mask_w, wi, axis=1)  # [N, k]
    bit = bp.select_bit(word, targets - prior)
    idx = jnp.minimum(wi * bp.WORD + bit, n - 1).astype(jnp.int32)
    valid = jnp.arange(k, dtype=jnp.int32)[None, :] < c[:, None]
    return idx, valid


def _loss_at(state: SimState, i, j) -> jnp.ndarray:
    """Directed-link loss lookup. ``state.loss`` is either the dense [N, N]
    matrix (emulator mode) or a 0-d scalar (uniform loss — the memory-lean
    mode for very large N, where a dense float32 matrix would dominate HBM:
    40 GB at N=100k)."""
    if state.loss.ndim == 0:
        return jnp.broadcast_to(state.loss, jnp.shape(i))
    return state.loss[i, j]


def _rt_at(state: SimState, i, j) -> jnp.ndarray:
    """Round-trip success probability i→j→i — one gather into the derived
    ``fetch_rt`` matrix (the single source of the ``(1-loss)·(1-loss.T)``
    formula, maintained by the host mutators). Used by every
    request-response leg: ping, ping ACK, SYNC, metadata fetch."""
    if state.fetch_rt.ndim == 0:
        return jnp.broadcast_to(state.fetch_rt, jnp.shape(i))
    return state.fetch_rt[i, j]


def _delay_q_at(state: SimState, i, j) -> jnp.ndarray:
    """Geometric delay parameter of the directed link i→j (0 = no delay)."""
    if state.delay_q.ndim == 0:
        return jnp.broadcast_to(state.delay_q, jnp.shape(i))
    return state.delay_q[i, j]


def _timely_rt(q1: jax.Array, q2: jax.Array, t: int) -> jax.Array:
    """P(two independent geometric(q) legs sum to ≤ t ticks) — the chance a
    request-response round trip beats its protocol timeout under the link
    delay model. Closed-form polynomial in (q1, q2) via the convolution
    recurrence h_s = q1·h_{s-1} + q2^s (pure f32 multiply/add, bit-exact
    against the oracle on any backend; no transcendentals). With q = 0 (no
    delay) this is EXACTLY 1.0, so multiplying it in never perturbs
    zero-delay trajectories."""
    h = jnp.ones_like(q1)  # h_0
    acc = h
    q2p = jnp.ones_like(q2)
    for _ in range(t):
        q2p = q2p * q2
        h = q1 * h + q2p
        acc = acc + h
    return (1.0 - q1) * (1.0 - q2) * acc


def _edge_ok(state: SimState, src: jax.Array, dst: jax.Array, draw: jax.Array) -> jax.Array:
    """Delivery draw for a directed message src->dst (sender+receiver up,
    Bernoulli on outbound loss — NetworkEmulator.java:349-369)."""
    p = 1.0 - _loss_at(state, src, dst)
    return state.up[src] & state.up[dst] & (draw < p)


def _fetch_gate(
    state: SimState,
    salt: int,
    i: jax.Array,
    j: jax.Array,
    cand_key: jax.Array,
    p_fetch: jax.Array,
) -> jax.Array:
    """Metadata-fetch gate for merge winners: an ALIVE-rank candidate about
    subject ``j`` is applied at receiver ``i`` only if the fetch round trip
    i→j→i succeeds (subject up + one Bernoulli on both link directions) —
    the reference accepts ALIVE only after GET_METADATA_REQ/RESP completes
    (``MembershipProtocolImpl.java:636-658``); on failure the record is
    simply not applied and a later redelivery retries, like the reference's
    dropped update. SUSPECT/LEAVING/DEAD candidates pass untouched (the
    reference fetches only for ALIVE), as does the FD phase's direct ALIVE
    verdict — there the ACK just arrived from the subject itself over the
    very link a fetch would use.

    ``p_fetch`` comes from the precomputed ``state.fetch_rt`` (whole matrix
    at the gossip site, row-gathers at the SYNC sites). Never spell it as
    ``loss[i, j] · loss[j, i]`` with broadcast index arrays in-tick: the two
    [N, N] gathers measured a ~60x tick slowdown on TPU, and even the
    view-based ``(1-loss)·(1-loss.T)`` costs ~2.5x from the materialized
    per-tick transpose — which is why the matrix is derived state.

    Broadcasting: ``i``/``j`` index arrays shaped like ``cand_key``.
    """
    needs = (cand_key & 3) == RANK_ALIVE  # UNKNOWN (-1) reads rank 3: exempt
    u = fetch_uniform(state.tick, salt, i, j)
    ok = state.up[j] & (u < p_fetch)
    return ~needs | ok


# ---------------------------------------------------------------------------


def _fd_phase(
    state: SimState, r: FdRandoms, params: SimParams, trace: bool = False,
    ad=None,
) -> tuple[SimState, dict[str, jax.Array]]:
    n = state.capacity
    rows = jnp.arange(n)

    if _packed(params):
        # live view as packed words, self-bit cleared word-parallel
        selw = bp.word_andnot(_known_live_words(state), bp.diag_words(n))
        sel_idx, sel_valid = _sample_distinct_words(selw, n, r.fd_sel)
    else:
        sel_idx, sel_valid = _sample_distinct(_live_view_mask(state), r.fd_sel)
    tgt = sel_idx[:, 0]
    has_tgt = sel_valid[:, 0] & state.up

    # Direct ping: PING out + ACK back must both survive (request-response
    # round trip = one fetch_rt lookup), and under the delay model the round
    # trip must also beat pingTimeout (FailureDetectorConfig.java:10 — the
    # sub-interval timeout, SURVEY.md §7 hard part i).
    p_direct = _rt_at(state, rows, tgt)
    if params.delay_slots:
        if ad is not None:
            # Lifeguard LHA (r14, AD-4): each prober's DIRECT timeout
            # stretches to t_base * (1 + lh_i) — a degraded member gives
            # its own round trips more time before accusing anyone
            p_direct = p_direct * _adp.scaled_timely_rt(
                _delay_q_at(state, rows, tgt),
                _delay_q_at(state, tgt, rows),
                params.fd_direct_timeout_ticks,
                ad.lh,
                params.adaptive.lh_max,
            )
        else:
            p_direct = p_direct * _timely_rt(
                _delay_q_at(state, rows, tgt),
                _delay_q_at(state, tgt, rows),
                params.fd_direct_timeout_ticks,
            )
    direct_ok = has_tgt & state.up[tgt] & (r.fd_direct < p_direct)

    # Indirect probe via k relays: PING_REQ -> transit PING -> transit ACK ->
    # forwarded ACK (four hops, FailureDetectorImpl.java:173-315) = the
    # issuer↔relay round trip times the relay↔target round trip, each of
    # which must fit its share of the remaining interval under delay.
    relays = sel_idx[:, 1:]  # [N, k]
    relay_valid = sel_valid[:, 1:]
    tgt_b = tgt[:, None]
    p_relay = _rt_at(state, rows[:, None], relays) * _rt_at(state, relays, tgt_b)
    if params.delay_slots:
        p_relay = p_relay * _timely_rt(
            _delay_q_at(state, rows[:, None], relays),
            _delay_q_at(state, relays, rows[:, None]),
            params.fd_leg_timeout_ticks,
        )
        p_relay = p_relay * _timely_rt(
            _delay_q_at(state, relays, tgt_b),
            _delay_q_at(state, tgt_b, relays),
            params.fd_leg_timeout_ticks,
        )
    relay_ok = (
        relay_valid
        & state.up[relays]
        & state.up[tgt_b]
        & (r.fd_relay < p_relay)
    )
    ack = direct_ok | relay_ok.any(axis=1)

    # Verdict records, written at (i, tgt_i) through the overrides gate.
    # ALIVE verdict carries the target's self-incarnation (the ALIVE-again
    # SYNC effect); SUSPECT suspects the incarnation we currently know.
    # Targets come from the live view, so own_key >= 0 wherever has_tgt.
    own_key = state.view_key[rows, tgt]
    alive_key = (state.view_key[tgt, tgt] >> 2) << 2
    suspect_key = ((own_key >> 2) << 2) | RANK_SUSPECT
    cand_key = jnp.where(ack, alive_key, suspect_key)
    accept = has_tgt & (cand_key > own_key)

    # One accepted verdict per row at column tgt_i — written as a streaming
    # one-hot select (cheaper than a scattered copy-on-write of both planes).
    hit = (rows[None, :] == tgt[:, None]) & accept[:, None]
    st = state.replace(
        view_key=jnp.where(hit, cand_key[:, None], state.view_key),
        changed_at=jnp.where(hit, state.tick, state.changed_at),
    )
    metrics = {
        "fd_probes": has_tgt.sum(),
        # raw per-round failures (direct + ALL relay paths missed) — compare
        # against scalar rounds whose EVERY relay verdict was SUSPECT (an
        # indirect round emits one verdict per relay; see
        # benchmarks/config3b_scalar_vs_kernel_fd.py's per-period grouping)
        "fd_failed_probes": (has_tgt & ~ack).sum(),
        "fd_new_suspects": (accept & ~ack).sum(),
    }
    if ad is not None:
        # adaptive evidence exports (r14): own-probe outcomes feed lh; new
        # SUSPECT verdicts are the episode's origin confirmations (AD-1)
        sus_w = accept & ~ack
        metrics["_ad_miss"] = has_tgt & ~ack
        metrics["_ad_succ"] = has_tgt & ack
        metrics["_ad_cnt"] = (
            jnp.zeros((n,), jnp.int32).at[tgt].add(sus_w.astype(jnp.int32))
        )
        metrics["_ad_key"] = (
            jnp.full((n,), NO_CANDIDATE_I32, jnp.int32)
            .at[tgt]
            .max(jnp.where(sus_w, cand_key.astype(jnp.int32), NO_CANDIDATE_I32))
        )
    if trace:
        # trace-plane export (r10): the probe internals the causal trace
        # ring records — values this phase already computed, so an armed
        # trace changes NOTHING about the state math (lockstep-tested).
        # ``suspect`` marks rows whose verdict RAISED a suspicion (the
        # detection lineage's origin events).
        metrics["trace_fd"] = {
            "tgt": tgt.astype(jnp.int32),
            "has_tgt": has_tgt,
            "ack": ack,
            "direct_ok": direct_ok,
            "suspect": accept & ~ack,
            "relays": relays.astype(jnp.int32),
            "relay_valid": relay_valid,
            "relay_ok": relay_ok,
        }
    return st, metrics


def _suspicion_phase(state: SimState, params: SimParams, trace=None, ad=None):
    """SUSPECT cells whose suspicion window expired become DEAD at the same
    incarnation (rank 2 -> 3 is key+1). ``changed_at`` is the suspicion
    start: every accepted change that leaves a cell SUSPECT also (re)stamps
    it, so a separate suspect_since plane would always equal it.

    ``trace`` (a TraceSpec) switches the return to ``(state, sus_tr)`` with
    the tracers' expiry transitions exported from the sweep branch's own
    ``expired`` temp (r10 — reading a branch temp is free; reading the
    carried plane post-hoc is a full extra materialization per tick).

    ``ad`` (an :class:`..adaptive.AdaptiveState`, r14) swaps the static
    timeout for the confirmation-scaled, observer-health-scaled window:
    ``base_i * mult(conf_j) * (1 + lh_i) // L`` — well-corroborated
    suspicions expire at ``min_mult``, lone accusations from a degraded
    observer age out at ``max_mult * (1 + lh)``."""
    recompute = _packed(params)
    # Packed mode recomputes the suspect mask INSIDE the rare sweep branch:
    # a mask captured by the lax.cond closure is a cond operand, so the
    # legacy spelling materializes an [N, N] bool plane every tick just to
    # take its any() — on the quiet steady state that write+read was the
    # single biggest term of the packed tick. The gate reduce fuses into
    # one pass over view_key; the sweep branch (rare) pays the recompute.
    suspect = None if recompute else (state.view_key & 3) == RANK_SUSPECT

    def _sweep(state: SimState):
        sus = (
            (state.view_key & 3) == RANK_SUSPECT if recompute else suspect
        )
        if ad is not None:
            aspec = params.adaptive
            L = aspec.levels
            base = ceil_log2(_cluster_size(state)) * params.fd_every  # [N]
            num_conf = _adp.conf_mult_num(aspec, ad.conf)  # [N]
            # a cell whose suspicion is NEWER than the episode gets no
            # acceleration from the episode's confirmations (AD-1)
            in_ep = state.view_key.astype(jnp.int32) <= ad.conf_key[None, :]
            num = jnp.where(
                in_ep, num_conf[None, :], jnp.int32(aspec.max_mult * L)
            )
            factor = base * (1 + ad.lh)  # [N] — AD-3 observer scaling
            timeout = (factor[:, None] * num) // jnp.int32(L)  # [N, N]
            overdue = state.tick - state.changed_at >= timeout
        else:
            timeout = (
                params.suspicion_mult
                * ceil_log2(_cluster_size(state))
                * params.fd_every
            )
            overdue = state.tick - state.changed_at >= timeout[:, None]
        expired = sus & overdue & state.up[:, None]
        st = state.replace(
            view_key=jnp.where(expired, state.view_key + 1, state.view_key),
            changed_at=jnp.where(expired, state.tick, state.changed_at),
        )
        if trace is not None:
            from ..trace import capture as _tc

            return st, _tc.expiry_trace(expired, trace)
        return st

    def _skip(st: SimState):
        if trace is not None:
            from ..trace import capture as _tc

            return st, _tc.zero_sus_trace(trace)
        return st

    # No SUSPECT cell anywhere (the healthy steady state) -> nothing can
    # expire; skip the timer compare + both plane writes. The sweep with
    # no suspects expires nothing, so the ungated spelling (quiet_gates
    # off — the fleet profile, where a vmapped cond would run both
    # branches AND select) is value-identical.
    if not params.quiet_gates:
        return _sweep(state)
    has_suspect = (
        ((state.view_key & 3) == RANK_SUSPECT).any() if recompute else suspect.any()
    )
    return jax.lax.cond(has_suspect, _sweep, _skip, state)


def _gossip_phase(
    state: SimState, r: RoundRandoms, params: SimParams, adaptive: bool = False
) -> tuple[SimState, dict[str, jax.Array]]:
    n = state.capacity
    R = params.rumor_slots
    NOC = _noc(params)
    rows = jnp.arange(n)
    if _packed(params):
        # one packed live plane serves the spread window (popcount cluster
        # sizes) AND, self-bit cleared, the fanout peer sampler below
        klw = _known_live_words(state)
        spread = params.repeat_mult * ceil_log2(bp.popcount_rows(klw))  # [N]
    else:
        klw = None
        spread = params.repeat_mult * ceil_log2(_cluster_size(state))  # [N]

    def _young_of(st: SimState) -> jax.Array:
        return (st.view_key >= 0) & (st.tick - st.changed_at < spread[:, None])

    # Packed mode defers the [N, N] young plane to the active branch: a
    # plane captured by the _deliver closure is a lax.cond operand and gets
    # MATERIALIZED every tick — quiet ticks only need its any(-1) reduce,
    # which fuses into one pass over view_key/changed_at. (The [N, R]
    # rumor plane is tiny and stays shared.)
    young = None if _packed(params) else _young_of(state)
    inf_b = bp.unpack_bits(state.infected, R)  # stored packed (r9)
    rumor_young = (
        inf_b
        & state.rumor_active[None, :]
        & (state.tick - state.infected_at < spread[:, None])
    )  # [N, R]
    # Dissemination strategy seam (r13): the default spec traces exactly
    # the legacy program below; ``bmask`` is the pipelined strategy's
    # rotating payload window (DZ-3: user rumors only) and ``rumor_pay``
    # the slots a message may carry. ``rumor_young`` itself stays
    # unbudgeted — the quiet gate must see out-of-window rumors as pending
    # work for a later rotation.
    spec = params.dissem
    bmask = dz.rumor_budget_mask(spec, R, state.tick)
    rumor_pay = rumor_young if bmask is None else rumor_young & bmask[None, :]
    # A node only sends a GOSSIP_REQ when it has something to put in it — the
    # reference sends nothing when selectGossipsToSend comes back empty
    # (doSpreadGossip:141-184 iterates selected gossips). So (a) message
    # counts only tally payload-bearing sends, and (b) a fully quiescent
    # cluster (converged steady state: nothing young, no live rumors) skips
    # peer selection + delivery + merge entirely — the dominant per-tick cost
    # drops out exactly when the real system would go quiet on the wire.
    # Under the delay model, messages already in flight (the current tick's
    # pending-ring slot) are work too, even if every sender is quiet.
    young_any_pre = _young_of(state).any(axis=1) if young is None else young.any(axis=1)
    sender_has = young_any_pre | rumor_young.any(axis=1)  # [N]
    D = params.delay_slots
    gossip_work = sender_has.any()
    if D:
        slot_now = state.tick % D
        arriving_key = state.pending_key[slot_now]  # [N, N]
        arriving_inf = bp.unpack_bits(state.pending_inf[slot_now], R)  # [N, R]
        arriving_src = state.pending_src[slot_now]  # [N, R]
        gossip_work = gossip_work | (arriving_key > NOC).any() | arriving_inf.any()

    def _deliver(state: SimState) -> tuple[SimState, dict[str, jax.Array]]:
        if not spec.uniform_selection:
            # structured topology / deterministic schedule: closed-form
            # circulant targets (DZ-1: sends gate on up[src] & up[dst],
            # not on the sender's view of the neighbor)
            peers, peer_valid = dz.structured_peers(
                spec, n, state.tick, r.gossip_sel
            )
        elif _packed(params):
            peers, peer_valid = _sample_distinct_words(
                bp.word_andnot(klw, bp.diag_words(n)), n, r.gossip_sel
            )
        else:
            peers, peer_valid = _sample_distinct(_live_view_mask(state), r.gossip_sel)
        yg = _young_of(state) if young is None else young
        piggyback = jnp.where(yg, state.view_key, NOC)  # [N, N]
        # Scatter-max deliveries directly onto a working copy of the table
        # (buf = max(own, best delivered candidate) cellwise), then apply
        # the overrides gate on the winner: buf > own ⟺ the best candidate
        # overrides, in which case buf IS that candidate. Saves a separate
        # recv buffer + merge pass. Messages whose delay draw lands them on
        # this tick's ring slot were read above and join the same merge.
        if D:
            buf = jnp.maximum(state.view_key, arriving_key)
            recv_inf = arriving_inf
            recv_src = arriving_src
            pend_key = state.pending_key
            # the infection ring is STORED packed; the in-phase scatters
            # need per-receiver bool rows, so the (small-D fidelity) ring
            # round-trips through bools and repacks at the end
            pend_inf_b = bp.unpack_bits(state.pending_inf, R)
            pend_src = state.pending_src
        else:
            buf = state.view_key
            recv_inf = jnp.zeros((n, R), bool)
            recv_src = jnp.full_like(state.infected_from, -1)
        young_any = yg.any(axis=1)  # [N] — membership payload exists
        sent = jnp.int32(0)
        rumor_sent = jnp.int32(0)
        for s in range(params.fanout):
            p = peers[:, s]
            # Known-infected filter (selectGossipsToSend:311-320 via
            # GossipState's infected set): don't hand r back to the peer
            # that delivered it to us, nor to its origin — the two members
            # this sender KNOWS are infected. This is what keeps rumor
            # message counts inside the ClusterMath per-node bound's
            # constant instead of fanout-times it.
            payload_r = (
                rumor_pay
                & (state.infected_from != p[:, None])
                & (state.rumor_origin[None, :] != p[:, None])
            )
            # A GOSSIP_REQ goes out only if THIS peer's payload is nonempty
            # after filtering (the reference sends nothing when
            # selectGossipsToSend comes back empty for that member).
            has_payload = young_any | payload_r.any(axis=1)
            ok = (
                peer_valid[:, s]
                & has_payload
                & _edge_ok(state, rows, p, r.gossip_edge[:, s])
            )
            sent = sent + ok.sum()
            send_r = payload_r & ok[:, None]
            rumor_sent = rumor_sent + send_r.sum()
            if D:
                # Per-edge integer delay d: P(d ≥ k) = q^k (geometric floor
                # of the emulator's exponential), capped at D-1 ring slots.
                # Sequential f32 powers keep it transcendental-free.
                qd = _delay_q_at(state, rows, p)
                d = jnp.zeros((state.capacity,), jnp.int32)
                qpow = qd
                for _ in range(1, D):
                    d = d + (r.gossip_delay[:, s] < qpow)
                    qpow = qpow * qd
                ok_now = ok & (d == 0)
                ok_late = ok & (d > 0)
                slot_d = (state.tick + d) % D  # d ∈ [1, D-1] ⇒ never slot_now
                pend_key = pend_key.at[slot_d, p].max(
                    jnp.where(ok_late[:, None], piggyback, NOC)
                )
                late_r = send_r & ok_late[:, None]
                pend_inf_b = pend_inf_b.at[slot_d, p].max(late_r)
                pend_src = pend_src.at[slot_d, p].max(
                    jnp.where(late_r, rows[:, None], -1)
                )
            else:
                ok_now = ok
            buf = buf.at[p].max(jnp.where(ok_now[:, None], piggyback, NOC))
            now_r = send_r & ok_now[:, None]
            recv_inf = recv_inf.at[p].max(now_r)
            recv_src = recv_src.at[p].max(jnp.where(now_r, rows[:, None], -1))
            if spec.wants_pull:
                # push-pull reply (DZ-2): the contacted peer answers the
                # SAME undelayed contact with ITS young records + rumors,
                # gated on one hashed reverse-link delivery draw. The
                # reply merges into the same cellwise scatter-max join,
                # so ordering against the forward deliveries is moot.
                rev_u = fetch_uniform(state.tick, dz.pull_salt(s), rows, p)
                rev_ok = ok_now & (rev_u < (1.0 - _loss_at(state, p, rows)))
                buf = jnp.maximum(
                    buf, jnp.where(rev_ok[:, None], piggyback[p], NOC)
                )
                reply_r = (
                    rumor_pay[p]
                    & (state.infected_from[p] != rows[:, None])
                    & (state.rumor_origin[None, :] != rows[:, None])
                    & rev_ok[:, None]
                )
                recv_inf = recv_inf | reply_r
                recv_src = jnp.maximum(
                    recv_src, jnp.where(reply_r, p[:, None], -1)
                )
                sent = sent + rev_ok.sum()
                rumor_sent = rumor_sent + reply_r.sum()

        own = state.view_key
        accept = (
            (buf > own)
            & ((own >= 0) | ((buf & 3) <= RANK_LEAVING))
            & state.up[:, None]
            & _fetch_gate(
                state, SALT_GOSSIP, rows[:, None], rows[None, :], buf, state.fetch_rt
            )
        )
        if params.namespace_gate:
            # hierarchical-namespace relatedness gate (areNamespacesRelated,
            # MembershipProtocolImpl.java:511-536): a record about an
            # unrelated subject is never applied
            accept = accept & state.ns_rel[state.ns_id[:, None], state.ns_id[None, :]]
        st = state.replace(
            view_key=jnp.where(accept, buf, own),
            changed_at=jnp.where(accept, state.tick, state.changed_at),
        )

        newly_inf = recv_inf & ~inf_b & st.up[:, None] & st.rumor_active[None, :]
        st = st.replace(
            # the infection merge is the literal word-parallel OR of the
            # packed bitmaps (SequenceIdCollector dedup = bitmap OR)
            infected=bp.word_or(st.infected, bp.pack_bits(newly_inf)),
            infected_at=jnp.where(newly_inf, st.tick, st.infected_at),
            # remember one delivering peer (max row id among this tick's
            # senders — deterministic, oracle-mirrorable) as the compact
            # known-infected set for the forwarding filter above
            infected_from=jnp.where(newly_inf, recv_src, st.infected_from),
        )
        if D:
            # current slot is consumed; d ≥ 1 scatters never target it
            st = st.replace(
                pending_key=pend_key.at[slot_now].set(NOC),
                pending_inf=bp.pack_bits(pend_inf_b.at[slot_now].set(False)),
                pending_src=pend_src.at[slot_now].set(-1),
            )
        m = {
            "gossip_msgs": sent,
            "rumor_sends": rumor_sent,
            "rumor_deliveries": newly_inf.sum(),
        }
        if adaptive:
            # confirmation evidence (r14, AD-1/AD-2): every accepted
            # SUSPECT record counts one believer; the per-subject max key
            # is the episode candidate
            sus_acc = accept & ((buf & 3) == RANK_SUSPECT)
            m["_ad_cnt"] = sus_acc.astype(jnp.int32).sum(axis=0)
            m["_ad_key"] = jnp.where(
                sus_acc, buf.astype(jnp.int32), NO_CANDIDATE_I32
            ).max(axis=0)
        return st, m

    def _quiet(state: SimState) -> tuple[SimState, dict[str, jax.Array]]:
        m = {
            "gossip_msgs": jnp.int32(0),
            "rumor_sends": jnp.int32(0),
            "rumor_deliveries": jnp.int32(0),
        }
        if adaptive:
            m["_ad_cnt"] = jnp.zeros((n,), jnp.int32)
            m["_ad_key"] = jnp.full((n,), NO_CANDIDATE_I32, jnp.int32)
        return state, m

    # a delivery with no payload anywhere sends nothing and accepts
    # nothing — the quiet gate is a pure dispatch-cost skip, so the
    # ungated fleet profile traces _deliver alone (value-identical)
    if not params.quiet_gates:
        return _deliver(state)
    return jax.lax.cond(gossip_work, _deliver, _quiet, state)


def _sync_phase(
    state: SimState, r: RoundRandoms, params: SimParams, trace: bool = False,
    adaptive: bool = False,
) -> tuple[SimState, dict[str, jax.Array]]:
    """Anti-entropy full-table exchange for this tick's due callers.

    Stagger makes only ~capacity/sync_every rows due per tick, so the due
    set is compacted into K static caller slots (``jnp.nonzero(size=K)``,
    ascending row order) and all per-caller work — peer selection, the
    caller-table scatter, the ACK merge — happens on [K, N] gathers instead
    of full [N, N] passes. Only the REQ-side merge stays full-matrix
    (several callers may pick the same peer; the scatter-max + one merge
    pass resolves duplicates exactly like the peer's sequential merges
    would). Callers beyond K wait for their next slot (forced bootstraps
    retry next tick — see SimParams.sync_slots)."""
    n = state.capacity
    rows = jnp.arange(n)
    K = min(n, params.sync_slots or (n // params.sync_every + 32))
    due = ((state.tick + rows * params.sync_stagger) % params.sync_every) == 0
    due = (due | state.force_sync) & state.up
    (caller,) = jnp.nonzero(due, size=K, fill_value=n)
    valid_c = caller < n
    caller = jnp.minimum(caller, n - 1)  # in-bounds; masked by valid_c

    # SYNC peers come from the live view PLUS the configured seeds
    # (selectSyncAddress: seedMembers ∪ members) — seeds re-bridge healed
    # partitions after mutual removal.
    NOC = _noc(params)
    caller_tables = state.view_key[caller]  # [K, N] — packed-word row gather
    cand = (caller_tables & 3) != RANK_DEAD
    if params.seed_rows:
        seed_mask = jnp.zeros((n,), bool).at[jnp.asarray(params.seed_rows)].set(True)
        cand = cand | seed_mask[None, :]
    cand = cand & (rows[None, :] != caller[:, None])
    if _packed(params):
        peer_idx, peer_valid = _sample_distinct_words(
            bp.pack_bits(cand), n, r.sync_sel[caller][:, None]
        )
    else:
        peer_idx, peer_valid = _sample_distinct(cand, r.sync_sel[caller][:, None])
    peer = peer_idx[:, 0]  # [K]
    # Round trip: SYNC out and SYNC_ACK back must both survive (and beat
    # syncTimeout under the delay model — MembershipConfig.java:15).
    p_rt = _rt_at(state, caller, peer)
    if params.delay_slots:
        p_rt = p_rt * _timely_rt(
            _delay_q_at(state, caller, peer),
            _delay_q_at(state, peer, caller),
            params.sync_timeout_ticks,
        )
    ok = valid_c & peer_valid[:, 0] & state.up[peer] & (r.sync_edge[caller] < p_rt)

    # SYNC request: callers' full tables scattered into peers (several
    # callers may hit one peer; scatter-max resolves duplicates, exactly as
    # the peer's sequential merges would — the join is associative). The
    # table IS view_key: unknown cells are -1, which no receiver ever
    # accepts (-1 > own requires own < -1, impossible). The overrides gate
    # is applied on the scatter-maxed winner per cell (buf > own ⟺ the best
    # delivered candidate overrides), then written back row-locally: only
    # the ≤K peer rows are touched, and duplicate peer slots recompute the
    # identical row so the scatter-max write is conflict-free.
    own_p = state.view_key[peer]  # [K, N] — gathered BEFORE any scatter
    buf = state.view_key.at[peer].max(
        jnp.where(ok[:, None], caller_tables, NOC)
    )
    buf_p = buf[peer]  # [K, N]
    acc = (
        (buf_p > own_p)
        & ((own_p >= 0) | ((buf_p & 3) <= RANK_LEAVING))
        & state.up[peer][:, None]
        & _fetch_gate(
            state,
            SALT_SYNC_REQ,
            peer[:, None],
            rows[None, :],
            buf_p,
            state.fetch_rt if state.fetch_rt.ndim == 0 else state.fetch_rt[peer],
        )
    )
    if params.namespace_gate:
        acc = acc & state.ns_rel[state.ns_id[peer][:, None], state.ns_id[None, :]]
    if _packed(params):
        # Fold the write-back into the scatter-maxed buffer itself instead
        # of re-scattering into the ORIGINAL plane: ``state.view_key`` has
        # no later consumer then, so the vk -> buf -> merged chain aliases
        # in place (one full-plane copy per SYNC instead of two). Exactly
        # the legacy cells: duplicate peer slots compute identical rows
        # (every term is a function of the peer row alone), and
        # ``where(acc, buf_p, own_p) >= own_p = vk[peer]`` cellwise, so
        # .set here equals the legacy .max.
        merged_vk = buf.at[peer].set(jnp.where(acc, buf_p, own_p))
    else:
        merged_vk = state.view_key.at[peer].max(jnp.where(acc, buf_p, own_p))
    st = state.replace(
        view_key=merged_vk,
        changed_at=state.changed_at.at[peer].max(
            jnp.where(acc, state.tick, jnp.int32(-(1 << 30)))
        ),
    )

    # SYNC_ACK: the peer's (post-merge) table straight back to each caller.
    # Row-local: accepted keys only grow, so scatter-max writes the merged
    # caller rows without touching the rest of the matrix (invalid/duplicate
    # slots contribute values that lose the max, a no-op).
    ack_cand = jnp.where(ok[:, None], st.view_key[peer], NOC)  # [K, N]
    own_rows = st.view_key[caller]
    accept = (
        (ack_cand > own_rows)
        & ((own_rows >= 0) | ((ack_cand & 3) <= RANK_LEAVING))
        & state.up[caller][:, None]
        & _fetch_gate(
            st,
            SALT_SYNC_ACK,
            caller[:, None],
            rows[None, :],
            ack_cand,
            st.fetch_rt if st.fetch_rt.ndim == 0 else st.fetch_rt[caller],
        )
    )
    if params.namespace_gate:
        accept = accept & state.ns_rel[state.ns_id[caller][:, None], state.ns_id[None, :]]
    st = st.replace(
        view_key=st.view_key.at[caller].max(jnp.where(accept, ack_cand, own_rows)),
        changed_at=st.changed_at.at[caller].max(
            jnp.where(accept, st.tick, jnp.int32(-(1 << 30)))
        ),
    )

    # A joiner's bootstrap SYNC retries every tick until one round-trip
    # actually lands (a lost initial SYNC must not strand the joiner until
    # its periodic stagger slot — cf. the reference's initial-sync-to-seeds
    # start phase, MembershipProtocolImpl.start0:250-291).
    ok_full = jnp.zeros((n,), bool).at[caller].max(ok)
    st = st.replace(force_sync=st.force_sync & ~ok_full)
    metrics = {"sync_roundtrips": ok.sum()}
    if adaptive:
        # confirmation evidence (r14, AD-1): accepted SUSPECT records in
        # both merge directions. Duplicate peer slots recompute IDENTICAL
        # acc rows, so the REQ count gates on the first slot per peer
        # (callers are distinct rows — the ACK side needs no gate).
        peer_eff = jnp.where(ok, peer, -1 - jnp.arange(K, dtype=jnp.int32))
        first_p = ok & (
            jnp.argmax(peer_eff[:, None] == peer_eff[None, :], axis=1)
            == jnp.arange(K)
        )
        m_req = acc & first_p[:, None] & ((buf_p & 3) == RANK_SUSPECT)
        m_ack = accept & ((ack_cand & 3) == RANK_SUSPECT)
        metrics["_ad_cnt"] = (
            m_req.astype(jnp.int32).sum(axis=0)
            + m_ack.astype(jnp.int32).sum(axis=0)
        )
        metrics["_ad_key"] = jnp.maximum(
            jnp.where(m_req, buf_p.astype(jnp.int32), NO_CANDIDATE_I32).max(axis=0),
            jnp.where(m_ack, ack_cand.astype(jnp.int32), NO_CANDIDATE_I32).max(axis=0),
        )
    if trace:
        # trace-plane export (r10): this tick's caller compaction + merge
        # outcomes (SYNC initiated/merged spans) — read-only internals
        metrics["trace_sync"] = {
            "caller": caller.astype(jnp.int32),
            "valid": valid_c,
            "peer": peer.astype(jnp.int32),
            "ok": ok,
            "req_acc": acc.sum(axis=1).astype(jnp.int32),
            "ack_acc": accept.sum(axis=1).astype(jnp.int32),
        }
    return st, metrics


def _refute_phase(
    state: SimState, trace=None, adaptive: bool = False,
    quiet_gates: bool = True,
):
    """A running node that finds itself SUSPECT — or even DEAD (a lingering
    cross-partition death rumor can land after a heal) — re-announces ALIVE
    with a bumped incarnation. The reference refutes ANY overriding record
    about self this way, keeping its own liveness and bumping past the
    rumor's incarnation (``onSelfMemberDetected:686-708``: r2 =
    (self, status, max(inc)+1)); without the DEAD case a node declared dead
    by others becomes a permanent zombie — up, but invisible forever.
    Deliberate LEAVING (self-initiated) is not refuted.

    ``trace`` switches the return to ``(state, refuted_tr)`` — the tracers'
    [K] self-refutation mask, read off the phase's own ``need`` vector."""
    n = state.capacity
    rows = jnp.arange(n)
    diag = state.view_key[rows, rows]
    rank = diag & 3
    # a leaver whose diagonal was overwritten (or echoed back) also refutes —
    # but re-announces LEAVING, not ALIVE: the reference keeps its own status
    # (r2 = (self, r0.status, inc+1)), so a graceful leave is never cancelled
    need = state.up & (
        (rank == RANK_SUSPECT)
        | (rank == RANK_DEAD)
        | (state.leaving & (rank != RANK_LEAVING))
    )
    announce_rank = jnp.where(state.leaving, RANK_LEAVING, RANK_ALIVE)
    # incarnation+1 through the layout-aware SATURATING bump: a narrow
    # (i16) key must never carry into its epoch bits (lattice.bump_inc;
    # identical to the historical +1 below the cap)
    new_diag = bump_inc(diag, announce_rank)

    def _apply(st: SimState) -> SimState:
        return st.replace(
            view_key=st.view_key.at[rows, rows].set(jnp.where(need, new_diag, diag)),
            changed_at=st.changed_at.at[rows, rows].set(
                jnp.where(need, st.tick, st.changed_at[rows, rows])
            ),
        )

    # In a healthy cluster nobody is refuting; skip the diagonal writes
    # (which force a copy-on-write of both [N, N] planes) entirely then.
    # With need all-False the write re-sets every diagonal to itself, so
    # the ungated fleet profile is value-identical.
    if not quiet_gates:
        st = _apply(state)
    else:
        st = jax.lax.cond(need.any(), _apply, lambda st: st, state)
    if trace is not None:
        return st, need[jnp.asarray(trace.tracer_rows, jnp.int32)]
    if adaptive:
        # r14 lh evidence: someone suspected ME — I look flaky from outside
        return st, need
    return st


def _rumor_sweep(state: SimState, params: SimParams, *, inf_b=None,
                 n_up=None) -> SimState:
    """Reclaim rumor slots. The reference sweeps per NODE: each holds a
    gossip for its own sweep window after ARRIVAL (getGossipsToRemove
    :350-358). The global slot therefore stays live while (a) the creation
    window runs, (b) any copy is still in flight (delay rings), or (c) any
    up receiver is still inside its own forwarding window (a late receiver
    must get to spread what it just learned — GossipDelayTest.java:33-70's
    late node still disseminates). Lifetime stays bounded: once everyone
    reachable is infected, the last infection + spread ends it.

    ``inf_b`` / ``n_up`` (r17, fused tick only): the unpacked infection
    plane and up-count the fused tail computes ONCE and shares with
    ``state_metrics`` (neither input is written between the two reads).
    ``None`` keeps the legacy per-phase spelling — byte-identical default
    program."""
    n_up = (state.up.sum() if n_up is None else n_up).astype(jnp.int32)
    sweep = 2 * (params.repeat_mult * ceil_log2(n_up) + 1)
    keep = state.tick - state.rumor_created <= sweep
    if _packed(params):
        sizes = bp.popcount_rows(_known_live_words(state))
    else:
        sizes = _cluster_size(state)
    spread = params.repeat_mult * ceil_log2(sizes)  # [N]
    if inf_b is None:
        inf_b = bp.unpack_bits(state.infected, params.rumor_slots)
    forwarding = (
        inf_b
        & state.up[:, None]
        & (state.tick - state.infected_at < spread[:, None])
    ).any(axis=0)
    keep = keep | forwarding
    if params.delay_slots:
        keep = keep | bp.unpack_bits(
            state.pending_inf, params.rumor_slots
        ).any(axis=(0, 1))
    return state.replace(rumor_active=state.rumor_active & keep)


# ---------------------------------------------------------------------------


def tick(
    state: SimState, key: jax.Array, params: SimParams, trace=None, ad=None,
    fused: bool = False,
) -> tuple[SimState, dict[str, Any]]:
    """Advance the whole cluster by one gossip period. Pure; jit/shard me.

    ``trace`` (a :class:`..trace.schema.TraceSpec`, static) arms the causal
    trace plane (r10): the metrics dict gains a ``_trace_rows`` [K, F] i32
    block built from phase internals — pure reads of [N]-sized values the
    tick computes anyway (never a read of the carried [N, N] planes, which
    would cost a full extra materialization per tick), so the state
    trajectory is BIT-IDENTICAL armed vs unarmed and the armed tick stays
    within noise (the lockstep + overhead gates pin both, for both
    engines).

    ``ad`` (an :class:`..adaptive.AdaptiveState`, r14) arms the adaptive
    failure-detection plane; the return becomes ``(state, ad', metrics)``.
    ``ad=None`` (the default) traces the byte-identical legacy program —
    no adaptive op, branch, or state exists in the jaxpr then.

    ``fused`` (r17): the tick-tail fusion — the rumor sweep's forwarding
    reduce and the telemetry block share ONE unpack of the packed
    infection plane and one up-count instead of re-deriving them per
    phase. The dense tick's phases otherwise genuinely depend on each
    other's writes (SYNC rewrites ``view_key``, which sizes/forwarding
    read), so the dense fusion is the tail hand-off only; bit-identical
    trajectory (tests), ``fused=False`` traces the legacy program."""
    armed = ad is not None
    if fused and trace is not None:
        raise ValueError(
            "the fused tick has no trace plane — profile/trace the "
            "unfused tick (bit-identical trajectory)"
        )
    if armed:
        if trace is not None:
            raise ValueError(
                "trace-armed adaptive windows are not supported — run the "
                "trace plane on a static-FD driver, or drop arm_trace"
            )
        if params.adaptive.is_default:
            raise ValueError(
                "adaptive tick needs an enabled AdaptiveSpec on params "
                "(params.adaptive = AdaptiveSpec(enabled=True, ...))"
            )
    state = state.replace(tick=state.tick + 1)
    fd_key, round_key = split_tick_key(key)
    r = draw_round_randoms(round_key, state.capacity, params.fanout)

    # The FD round only fires every fd_every ticks; lax.cond skips both the
    # phase and its [N,N] random draws entirely on the other ticks (the
    # draws live under fd_key, so skipping them never perturbs the
    # gossip/SYNC stream).
    def _fd_on(st: SimState) -> tuple[SimState, dict[str, jax.Array]]:
        fd_r = draw_fd_randoms(fd_key, st.capacity, params.ping_req_k)
        return _fd_phase(st, fd_r, params, trace=trace is not None, ad=ad)

    def _fd_off(st: SimState) -> tuple[SimState, dict[str, jax.Array]]:
        m = {
            "fd_probes": jnp.int32(0),
            "fd_failed_probes": jnp.int32(0),
            "fd_new_suspects": jnp.int32(0),
        }
        if armed:
            n = st.capacity
            m["_ad_miss"] = jnp.zeros((n,), bool)
            m["_ad_succ"] = jnp.zeros((n,), bool)
            m["_ad_cnt"] = jnp.zeros((n,), jnp.int32)
            m["_ad_key"] = jnp.full((n,), NO_CANDIDATE_I32, jnp.int32)
        if trace is not None:
            from ..trace import capture as _tc

            m["trace_fd"] = _tc.zero_fd_trace(st.capacity, params.ping_req_k)
        return st, m

    fd_ran = (state.tick % params.fd_every) == 0
    if params.fd_every == 1 and not params.quiet_gates:
        # the gate is vestigial when the FD round fires every tick — the
        # fleet profile traces _fd_on directly instead of paying a vmapped
        # cond's run-both-branches + state-wide select
        state, fd_m = _fd_on(state)
    else:
        state, fd_m = jax.lax.cond(fd_ran, _fd_on, _fd_off, state)
    if trace is not None:
        state, trace_sus = _suspicion_phase(state, params, trace=trace)
    else:
        state = _suspicion_phase(state, params, ad=ad)
    state, g_m = _gossip_phase(state, r, params, adaptive=armed)
    state, s_m = _sync_phase(
        state, r, params, trace=trace is not None, adaptive=armed
    )
    if trace is not None:
        state, trace_ref = _refute_phase(state, trace=trace)
    elif armed:
        state, refuted = _refute_phase(
            state, adaptive=True, quiet_gates=params.quiet_gates
        )
    else:
        state = _refute_phase(state, quiet_gates=params.quiet_gates)
    if fused:
        inf_b = bp.unpack_bits(state.infected, params.rumor_slots)
        n_up = state.up.sum()
        state = _rumor_sweep(state, params, inf_b=inf_b, n_up=n_up)
    else:
        inf_b = n_up = None
        state = _rumor_sweep(state, params)

    trace_fd = fd_m.pop("trace_fd", None)
    trace_sync = s_m.pop("trace_sync", None)
    if armed:
        miss = fd_m.pop("_ad_miss")
        succ = fd_m.pop("_ad_succ")
        acc_cnt = fd_m.pop("_ad_cnt") + g_m.pop("_ad_cnt") + s_m.pop("_ad_cnt")
        acc_key = jnp.maximum(
            jnp.maximum(fd_m.pop("_ad_key"), g_m.pop("_ad_key")),
            s_m.pop("_ad_key"),
        )
        lh2, ck2, cf2 = _adp.fold(
            params.adaptive, ad.lh, ad.conf_key, ad.conf,
            acc_key=acc_key, acc_cnt=acc_cnt,
            miss=miss, succ=succ, refuted=refuted, up=state.up,
        )
        ad = _adp.AdaptiveState(lh=lh2, conf_key=ck2, conf=cf2)
    metrics = {
        **fd_m, **g_m, **s_m,
        **state_metrics(state, params, inf_b=inf_b, n_up=n_up),
    }
    if armed:
        metrics["adaptive_lh_high"] = ad.lh.max()
        metrics["adaptive_conf_high"] = ad.conf.max()
        return state, ad, metrics
    if trace is not None:
        from ..trace import capture as _tc

        metrics["_trace_rows"] = _tc.build_trace_rows(
            trace,
            tick=state.tick,
            up=state.up,
            fd_ran=fd_ran,
            trace_fd=trace_fd,
            trace_sus=trace_sus,
            trace_ref=trace_ref,
            trace_sync=trace_sync,
            # XLA CSEs this against state_metrics' unpack of the same plane
            infected_b=bp.unpack_bits(state.infected, params.rumor_slots),
            infected_at=state.infected_at,
            infected_from=state.infected_from,
        )
    return state, metrics


def state_metrics(state: SimState, params: SimParams, *, inf_b=None,
                  n_up=None) -> dict[str, Any]:
    """The tick's state-derived health metrics — factored out (r10) so the
    phase-split profiler's "telemetry" phase runs the EXACT spelling the
    fused tick uses (one source, no drift).

    ``inf_b`` / ``n_up`` (r17): fused-tail hand-off from
    :func:`_rumor_sweep` — see its docstring; ``None`` = legacy."""
    if n_up is None:
        n_up = state.up.sum()
    if params.full_metrics:
        up2 = state.up[:, None] & state.up[None, :]
        off_diag = ~jnp.eye(state.capacity, dtype=bool)
        rank = state.view_key & 3  # -1 (unknown) reads rank 3, never ALIVE/SUSPECT
        if _packed(params):
            # word-parallel health reductions: pack the pair masks once,
            # count set bits with integer popcounts (no [N, N] i32 reduce,
            # no float promotion — same integers as the bool sums)
            pairs = jnp.maximum(n_up * n_up - n_up, 1)
            base = up2 & off_diag
            alive_pairs = bp.popcount_total(bp.pack_bits(base & (rank == RANK_ALIVE)))
            false_suspects = bp.popcount_total(
                bp.pack_bits(base & (rank == RANK_SUSPECT))
            )
        else:
            pairs = jnp.maximum(up2.sum() - state.up.sum(), 1)  # ordered up-pairs, excl self
            alive_pairs = (up2 & off_diag & (rank == RANK_ALIVE)).sum()
            false_suspects = (up2 & off_diag & (rank == RANK_SUSPECT)).sum()
        alive_frac = alive_pairs.astype(jnp.float32) / pairs
    else:  # static lite mode: skip the [N, N] health passes
        alive_frac = jnp.float32(0.0)
        false_suspects = jnp.int32(0)
    if inf_b is None:
        inf_b = bp.unpack_bits(state.infected, params.rumor_slots)
    coverage = (
        (inf_b & state.up[:, None]).sum(0).astype(jnp.float32)
        / jnp.maximum(n_up, 1)
    )
    # Gossip segmentation (the reference warns when a receiver's
    # SequenceIdCollector fragments past a threshold,
    # GossipProtocolImpl.java:217-236, GossipConfig.java:12): per node, the
    # number of ACTIVE rumors it is missing that are OLDER than its newest
    # infection — holes in its receive stream. Reported as the worst node's
    # count; the driver warns past the configured threshold.
    newest = jnp.where(
        inf_b, state.rumor_created[None, :], NEVER_I32
    ).max(axis=1)
    seg = (
        (
            state.rumor_active[None, :]
            & ~inf_b
            & (state.rumor_created[None, :] < newest[:, None])
            & state.up[:, None]
        )
        .sum(axis=1)
        .max()
    )
    return {
        "n_up": n_up,
        "alive_view_fraction": alive_frac,
        "false_suspect_pairs": false_suspects,
        "rumor_coverage": coverage,  # [R]
        "gossip_segmentation": seg,
    }


def run_ticks(
    state: SimState,
    key: jax.Array,
    n_ticks: int,
    params: SimParams,
    watch_rows: jax.Array | None = None,
    fused: bool = False,
) -> tuple[SimState, jax.Array, dict[str, Any], jax.Array | None]:
    """Advance ``n_ticks`` gossip periods in ONE XLA call (``lax.scan``).

    Dispatching tick-by-tick from Python costs a host round trip per period —
    on a tunneled TPU that's ~100x the tick's actual device time. Batching is
    the TPU-idiomatic driver loop: one dispatch runs the whole window
    on-device and per-tick metrics come back stacked ([n_ticks, ...]) in a
    single transfer at the end.

    The key chain is ``key, k = split(key)`` per tick — byte-identical to
    the host loop the tests and the scalar oracle use, so
    ``run_ticks(s, key, n)`` reproduces exactly the trajectory of n calls to
    :func:`tick` with host-side splitting. Returns the advanced key so
    callers can continue the same chain.

    ``watch_rows`` (static-shaped [W] row indices) additionally returns the
    watched rows' ``view_key`` after every tick ([n_ticks, W, N]) so the
    host can diff membership events for a whole window from one transfer
    (the reference's per-node event streams, ``MembershipEvent.java:15-20``).
    """

    def body(carry, _):
        st, k = carry
        k, tick_key = jax.random.split(k)
        st, m = tick(st, tick_key, params, fused=fused)
        if watch_rows is not None:
            m = dict(m, _watched_keys=st.view_key[watch_rows])
        return (st, k), m

    (state, key), ms = jax.lax.scan(body, (state, key), None, length=n_ticks)
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, key, ms, watched


# Per-window telemetry series (r8 metric rings). Order is the ring's column
# layout; names are the docs/TELEMETRY.md + /metrics contract. The vector is
# computed by PURE jnp reductions over the window's stacked metrics and the
# post-window state — staged on device like the r6 health accumulators, so
# an armed telemetry plane adds zero per-window device→host transfers.
TELEMETRY_SERIES = (
    "tick",  # window-end tick
    "window_ticks",
    "n_up",
    "fd_probes",
    "fd_failed_probes",
    "fd_new_suspects",
    "gossip_msgs",
    "rumor_sends",
    "rumor_deliveries",
    "sync_roundtrips",
    "gossip_segmentation_max",
    "rumor_coverage_mean",  # over ACTIVE slots, at window end
    "rumor_coverage_min",
    "rumor_active_slots",
    "alive_view_fraction",  # 0 when params.full_metrics is off
    "false_suspect_pairs_max",
    "convergence_lag",  # 1 - alive_view_fraction (meaningful iff full_metrics)
    # r14 adaptive-FD gauges (0 on static-FD windows): worst local-health
    # score and deepest confirmation count seen across the window
    "adaptive_lh_max",
    "adaptive_conf_max",
)

#: window metrics reduced by SUM into the telemetry vector (counters);
#: everything else is a max or an end-of-window gauge.
_TELEM_SUMS = (
    "fd_probes", "fd_failed_probes", "fd_new_suspects", "gossip_msgs",
    "rumor_sends", "rumor_deliveries", "sync_roundtrips",
)


def telemetry_window_core(ms: dict, state) -> list[jax.Array]:
    """The engine-shared prefix of the telemetry window vector (everything in
    :data:`TELEMETRY_SERIES`), as a list of f32 scalars. ``ms`` is a window's
    stacked per-tick metrics (each leaf ``[n_ticks, ...]``); ``state`` the
    post-window state. Pure jnp — callable on dense, sparse, and mesh-sharded
    outputs alike (reductions come out replicated under GSPMD)."""
    f32 = jnp.float32
    n_ticks = next(iter(ms.values())).shape[0]
    cov = ms["rumor_coverage"][-1]  # [R], end of window
    active = state.rumor_active
    n_active = jnp.maximum(active.sum(), 1)
    cov_act = jnp.where(active, cov, 0.0)
    alive_frac = ms["alive_view_fraction"][-1].astype(f32)
    vec = [
        state.tick.astype(f32),
        f32(n_ticks),
        ms["n_up"][-1].astype(f32),
        *(ms[name].sum().astype(f32) for name in _TELEM_SUMS),
        ms["gossip_segmentation"].max().astype(f32),
        (cov_act.sum() / n_active).astype(f32),
        jnp.where(
            active.any(), jnp.where(active, cov, jnp.inf).min(), 0.0
        ).astype(f32),
        active.sum().astype(f32),
        alive_frac,
        ms["false_suspect_pairs"].max().astype(f32),
        (1.0 - alive_frac).astype(f32),
        # adaptive gauges exist only in adaptive windows' metrics (r14);
        # static windows report 0 so the ring layout stays engine-stable
        (
            ms["adaptive_lh_high"].max().astype(f32)
            if "adaptive_lh_high" in ms else f32(0.0)
        ),
        (
            ms["adaptive_conf_high"].max().astype(f32)
            if "adaptive_conf_high" in ms else f32(0.0)
        ),
    ]
    return vec


def telemetry_window_vector(ms: dict, state: SimState) -> jax.Array:
    """Dense-engine telemetry row: one [len(TELEMETRY_SERIES)] f32 vector per
    window, appended to the device metric ring by the telemetry plane."""
    return jnp.stack(telemetry_window_core(ms, state))


def sentinel_core(
    view_key: jax.Array,
    up: jax.Array,
    tick: jax.Array,
    sent: dict,
    spec: dict,
) -> dict:
    """One chaos-sentinel check over the shared view planes (chaos/sentinels
    semantics; this array-level core serves BOTH engines — dense here, the
    sparse wrapper in :func:`.sparse.sentinel_reduce`). Pure jnp reductions:
    staged on device, nothing transferred — the r6 zero-readback discipline.

    ``sent`` is the accumulator pytree from ``chaos.sentinels
    .init_sentinel_state``; ``spec`` the uploaded ``SentinelSpec`` arrays.
    Every update is latching/monotone, so sampled invocation is sound:

    * ``false_dead_max`` — never-faulted up subjects currently tombstoned
      (``key >= 0`` excludes unknown) by any up observer; DEAD latches until
      a rejoin, so a sampled max cannot miss a violation.
    * ``detect_tick[k]`` — first sampled tick at which EVERY up observer
      reads crashed row k at rank DEAD (unknown, key -1, also reads rank 3:
      "not a member" counts as detected, matching the reference's removal).
    * ``conv_tick[c]`` — first sampled tick >= the recovery boundary where
      all up pairs read each other ALIVE.
    * ``key_regressions`` — self-record packed keys (epoch|inc|rank) that
      moved BACKWARD since the previous check: a lattice-monotonicity break
      (restarts bump the epoch high bits, so a legitimate rejoin still
      rises).
    """
    n = view_key.shape[0]
    rows = jnp.arange(n)
    rank = view_key & 3  # UNKNOWN (-1) reads rank 3
    rel = tick - spec["t0"]  # scenario-relative tick (spec ticks are relative)

    diag = view_key[rows, rows]
    sent = dict(sent)
    sent["key_regressions"] = sent["key_regressions"] + (
        diag < sent["prev_diag"]
    ).sum().astype(jnp.int32)
    sent["prev_diag"] = diag

    nf_up = spec["never_faulted"] & up
    false_dead = (
        (view_key >= 0) & (rank == RANK_DEAD) & up[:, None] & nf_up[None, :]
    ).any(axis=0).sum().astype(jnp.int32)
    sent["false_dead_max"] = jnp.maximum(sent["false_dead_max"], false_dead)

    if "fp_watch" in spec:
        # r14 false-positive sentinel: degraded-but-alive watched members
        # (SlowMember / AsymmetricLoss / FlakyObserver cohorts) currently
        # tombstoned by any up observer. Latching max like false_dead —
        # sampling is sound. The key ships only when the cohort is
        # non-empty, so legacy scenarios trace the legacy check program.
        fp_up = spec["fp_watch"] & up
        fp_dead = (
            (view_key >= 0) & (rank == RANK_DEAD) & up[:, None] & fp_up[None, :]
        ).any(axis=0).sum().astype(jnp.int32)
        sent["fp_dead_max"] = jnp.maximum(sent["fp_dead_max"], fp_dead)

    crash_rows = spec["crash_rows"]
    if crash_rows.shape[0]:
        cols = rank[:, crash_rows]  # [N, K]
        others_up = up[:, None] & (rows[:, None] != crash_rows[None, :])
        detected = (~others_up | (cols == RANK_DEAD)).all(axis=0)
        active = (
            (rel >= spec["crash_at"])
            & (rel <= spec["crash_until"])
            & (sent["detect_tick"] < 0)
        )
        sent["detect_tick"] = jnp.where(
            active & detected, rel, sent["detect_tick"]
        )

    if spec["conv_from"].shape[0]:
        up2 = up[:, None] & up[None, :] & ~jnp.eye(n, dtype=bool)
        converged = (~up2 | (rank == RANK_ALIVE)).all()
        active = (rel >= spec["conv_from"]) & (sent["conv_tick"] < 0)
        sent["conv_tick"] = jnp.where(
            active & converged, rel, sent["conv_tick"]
        )
    return sent


def sentinel_reduce(state: SimState, sent: dict, spec: dict) -> dict:
    """Dense-engine chaos sentinel check (see :func:`sentinel_core`)."""
    return sentinel_core(state.view_key, state.up, state.tick, sent, spec)


def run_ticks_traced(
    state: SimState,
    key: jax.Array,
    trace_buf: jax.Array,
    trace_cursor: jax.Array,
    n_ticks: int,
    params: SimParams,
    trace,
    watch_rows: jax.Array | None = None,
) -> tuple[SimState, jax.Array, dict[str, Any], jax.Array | None, jax.Array]:
    """Trace-armed :func:`run_ticks` (r10): the same window scan with the
    causal trace ring threaded through the carry — each tick appends its
    [K, F] record block in place at the device-carried cursor. The key
    chain and every state op are IDENTICAL to the unarmed window, so the
    trajectory stays bit-identical; the ring buffer is donated by
    :func:`make_traced_run` so the append never copies it. ``trace_cursor``
    comes from the host mirror (appends are a static K·n_ticks per window,
    so the host cursor never needs a device read)."""
    from ..trace import capture as _tc

    def body(carry, _):
        st, k, buf, cur = carry
        k, tick_key = jax.random.split(k)
        st, m = tick(st, tick_key, params, trace=trace)
        buf, cur = _tc.append_rows(
            buf, cur, m.pop("_trace_rows"), trace.ring_len
        )
        if watch_rows is not None:
            m = dict(m, _watched_keys=st.view_key[watch_rows])
        return (st, k, buf, cur), m

    (state, key, trace_buf, _cur), ms = jax.lax.scan(
        body, (state, key, trace_buf, trace_cursor), None, length=n_ticks
    )
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, key, ms, watched, trace_buf


def make_traced_run(params: SimParams, n_ticks: int, trace, donate: bool = True):
    """Jitted :func:`run_ticks_traced` window: state AND trace ring donated
    (argnums 0, 2) — the armed driver's per-window path stays in-place and
    transfer-free exactly like :func:`make_run`'s."""
    from functools import partial

    return jax.jit(
        partial(run_ticks_traced, n_ticks=n_ticks, params=params, trace=trace),
        donate_argnums=(0, 2) if donate else (),
    )


def run_ticks_adaptive(
    state: SimState,
    ad,
    key: jax.Array,
    n_ticks: int,
    params: SimParams,
    watch_rows: jax.Array | None = None,
    fused: bool = False,
):
    """Adaptive-armed :func:`run_ticks` (r14): the window scan threads the
    :class:`..adaptive.AdaptiveState` through the carry alongside the
    engine state. Same key chain as the legacy window."""

    def body(carry, _):
        st, a, k = carry
        k, tick_key = jax.random.split(k)
        st, a, m = tick(st, tick_key, params, ad=a, fused=fused)
        if watch_rows is not None:
            m = dict(m, _watched_keys=st.view_key[watch_rows])
        return (st, a, k), m

    (state, ad, key), ms = jax.lax.scan(
        body, (state, ad, key), None, length=n_ticks
    )
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, ad, key, ms, watched


def make_adaptive_run(params: SimParams, n_ticks: int, donate: bool = True):
    """Jitted :func:`run_ticks_adaptive` window: engine state AND adaptive
    state donated (argnums 0, 1) — the r6 double-buffered discipline covers
    the adaptive planes too. Refuses a default spec: the legacy builders
    are the byte-identical program for that case (the r13/r14 rule)."""
    from functools import partial

    if params.adaptive.is_default:
        raise ValueError(
            "make_adaptive_run needs an enabled AdaptiveSpec on params — "
            "the default spec's program is make_run's (byte-identical "
            "legacy window)"
        )
    return jax.jit(
        partial(run_ticks_adaptive, n_ticks=n_ticks, params=params),
        donate_argnums=(0, 1) if donate else (),
    )


def make_fleet_run(params: SimParams, n_ticks: int, donate: bool = True):
    """Scenario-batched :func:`run_ticks` (r15): one jitted program
    advancing S independent clusters — the state pytree stacked to
    ``[S, ...]``, keys ``[S, 2]``, fleet state DONATED. Row ``s``'s
    trajectory is bit-identical to a serial :func:`run_ticks` on the same
    (state, key); see :mod:`.fleet` for the batching rules."""
    from .fleet import make_fleet_window

    return make_fleet_window(run_ticks, params, n_ticks, donate=donate)


def make_fleet_adaptive_run(params: SimParams, n_ticks: int, donate: bool = True):
    """Fleet twin of :func:`make_adaptive_run`: ``[S, ...]`` engine AND
    adaptive states donated (argnums 0, 1). Refuses a default spec."""
    from .fleet import make_fleet_window

    if params.adaptive.is_default:
        raise ValueError(
            "make_fleet_adaptive_run needs an enabled AdaptiveSpec on "
            "params — the default spec's program is make_fleet_run's"
        )
    return make_fleet_window(
        run_ticks_adaptive, params, n_ticks, donate=donate, donated=(0, 1)
    )


def make_run(params: SimParams, n_ticks: int, donate: bool = True):
    """Jitted :func:`run_ticks` window with the state buffers DONATED.

    Donation lets XLA update the carried state in place instead of copying
    every [N, N] plane (view_key, changed_at, loss, fetch_rt, delay_q —
    ~5 x 67 MB per window at N=4096) at window entry; combined with JAX
    async dispatch this is what makes back-to-back windows run device-bound
    (the driver's pipelined step). The caller must treat the state it
    passed in as CONSUMED — only the returned state is valid afterwards,
    which is exactly how ``SimDriver`` (and every bench loop here) already
    threads it. ``donate=False`` builds the copying variant, kept for
    before/after measurement (benchmarks/config6_dispatch.py) and for
    callers that must retain the input (lockstep equivalence tests).
    """
    from functools import partial

    return jax.jit(
        partial(run_ticks, n_ticks=n_ticks, params=params),
        donate_argnums=0 if donate else (),
    )


# --------------------------------------------------------------------------
# fused tick windows (r17): the dense tail hand-off (shared infection-plane
# unpack + up-count between sweep and telemetry) as first-class window
# builders. Named wrappers (not lambdas/partials) so the fleet batcher and
# the audit plane can introspect them.
# --------------------------------------------------------------------------


def run_ticks_fused(state, key, n_ticks, params, watch_rows=None):
    """:func:`run_ticks` over the fused tick (bit-identical trajectory)."""
    return run_ticks(state, key, n_ticks, params, watch_rows, fused=True)


def run_ticks_fused_adaptive(state, ad, key, n_ticks, params, watch_rows=None):
    """:func:`run_ticks_adaptive` over the fused tick."""
    return run_ticks_adaptive(
        state, ad, key, n_ticks, params, watch_rows, fused=True
    )


def make_fused_run(params: SimParams, n_ticks: int, donate: bool = True):
    """Jitted fused-tick window, state DONATED — the r17 twin of
    :func:`make_run`. The trajectory is bit-identical to the unfused
    window (tests/test_fused.py); only the program differs (one
    infection-plane unpack + one up-count shared across sweep and
    telemetry instead of per-phase re-derivation)."""
    from functools import partial

    return jax.jit(
        partial(run_ticks_fused, n_ticks=n_ticks, params=params),
        donate_argnums=0 if donate else (),
    )


def make_fused_adaptive_run(params: SimParams, n_ticks: int, donate: bool = True):
    """Fused twin of :func:`make_adaptive_run` (donates argnums 0, 1).
    Refuses a default spec, same r13/r14 rule."""
    from functools import partial

    if params.adaptive.is_default:
        raise ValueError(
            "make_fused_adaptive_run needs an enabled AdaptiveSpec on "
            "params — the default spec's program is make_fused_run's"
        )
    return jax.jit(
        partial(run_ticks_fused_adaptive, n_ticks=n_ticks, params=params),
        donate_argnums=(0, 1) if donate else (),
    )


def make_fused_fleet_run(params: SimParams, n_ticks: int, donate: bool = True):
    """Fused twin of :func:`make_fleet_run`: scenario-batched fused-tick
    window, fleet state donated."""
    from .fleet import make_fleet_window

    return make_fleet_window(run_ticks_fused, params, n_ticks, donate=donate)
