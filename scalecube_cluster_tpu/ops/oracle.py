"""Scalar (per-node-loop NumPy) oracle of the tick semantics.

SURVEY.md §4's equivalence strategy: "run K scalar-Python SWIM nodes and the
vectorized kernel with identical RNG seeds/link matrices and assert identical
state trajectories". This module re-implements :mod:`.kernel`'s tick with
explicit per-node Python loops — structured like the reference's per-node
protocol objects, not like the tensor kernel — consuming byte-identical
random draws from :func:`.rand.draw_tick_randoms`. Equivalence tests step
both and compare full states every tick.

Float comparisons (delivery draws vs. loss products) are done in float32 in
the same association order as the kernel so thresholds match bit-exactly.
"""

from __future__ import annotations

import numpy as np

from .. import adaptive as _adp
from ..dissemination import strategies as dz
from . import bitplane
from .lattice import (
    ALIVE,
    DEAD,
    LEAVING,
    RANK_ALIVE,
    RANK_DEAD,
    RANK_LEAVING,
    RANK_SUSPECT,
    SUSPECT,
    UNKNOWN,
    layout_for,
)
from .rand import (
    SALT_GOSSIP,
    SALT_SYNC_ACK,
    SALT_SYNC_REQ,
    draw_tick_randoms,
    fetch_uniform,
)
from .state import SimParams, SimState

_RANK = {ALIVE: 0, LEAVING: 1, SUSPECT: 2, DEAD: 3}
_RANK_TO_STATUS = {0: ALIVE, 1: LEAVING, 2: SUSPECT, 3: DEAD}


def _ceil_log2(n: int) -> int:
    return int(n).bit_length() if n > 0 else 0


def _sample_distinct_row(mask: np.ndarray, u: np.ndarray):
    """Scalar mirror of ``kernel._sample_distinct`` for one row.

    Must be bit-exact: rank draw = float32(u) * float32(avail) truncated,
    insertion shift over the already-taken ranks in ascending order, rank →
    column via first-hit argmax on the mask cumsum."""
    c = int(mask.sum())
    cs = np.cumsum(mask.astype(np.int32))
    k = len(u)
    idx = np.zeros(k, np.int32)
    valid = np.zeros(k, bool)
    taken: list = []
    for s in range(k):
        avail = max(c - s, 1)
        x = int(np.float32(u[s]) * np.float32(avail))
        x = min(x, avail - 1)
        for p in sorted(taken):
            if x >= p:
                x += 1
        taken.append(x)
        valid[s] = s < c
        # first j with cs[j] >= x+1 — same first-hit as the kernel's batched
        # searchsorted for valid slots (invalid slots yield garbage on both
        # sides, 0 here vs n-1 there, and are masked via `valid` everywhere)
        idx[s] = int(np.argmax(cs >= x + 1))
    return idx, valid


class _O:
    """Mutable numpy mirror of SimState (packed-key table layout)."""

    def __init__(self, state: SimState):
        self.tick = int(state.tick)
        self.up = np.asarray(state.up).copy()
        self.epoch = np.asarray(state.epoch).copy()  # tick-invariant (host-bumped)
        self.key = np.asarray(state.view_key).copy()
        self.changed = np.asarray(state.changed_at).copy()
        self.force_sync = np.asarray(state.force_sync).copy()
        self.leaving = np.asarray(state.leaving).copy()
        self.r_active = np.asarray(state.rumor_active).copy()
        self.r_origin = np.asarray(state.rumor_origin).copy()
        self.r_created = np.asarray(state.rumor_created).copy()
        # the state stores the infection bitmaps word-packed (r9); the
        # oracle loops per (node, slot), so mirror them as bools
        r = state.infected_at.shape[1]
        self.infected = bitplane.unpack_bits(
            np.asarray(state.infected), r, xp=np
        ).copy()
        self.infected_at = np.asarray(state.infected_at).copy()
        self.infected_from = np.asarray(state.infected_from).copy()
        self.ns_id = np.asarray(state.ns_id).copy()
        self.ns_rel = np.asarray(state.ns_rel).copy()
        self.loss = np.asarray(state.loss).copy()
        self.fetch_rt = np.asarray(state.fetch_rt).copy()
        self.delay_q = np.asarray(state.delay_q).copy()
        self.pending_key = np.asarray(state.pending_key).copy()
        self.pending_inf = bitplane.unpack_bits(
            np.asarray(state.pending_inf), r, xp=np
        ).copy()
        self.pending_src = np.asarray(state.pending_src).copy()

    def snap(self):
        import copy

        return copy.deepcopy(self)


def _loss(o: "_O", i: int, j: int) -> np.float32:
    return np.float32(o.loss) if o.loss.ndim == 0 else o.loss[i, j]


def _rt(o: "_O", i: int, j: int) -> np.float32:
    """Round-trip probability i→j→i (mirror of kernel._rt_at)."""
    return np.float32(o.fetch_rt) if o.fetch_rt.ndim == 0 else o.fetch_rt[i, j]


def _delay_q(o: "_O", i: int, j: int) -> np.float32:
    return np.float32(o.delay_q) if o.delay_q.ndim == 0 else o.delay_q[i, j]


def _timely(q1: np.float32, q2: np.float32, t: int) -> np.float32:
    """Scalar mirror of ``kernel._timely_rt`` — identical f32 op sequence."""
    q1 = np.float32(q1)
    q2 = np.float32(q2)
    h = np.float32(1.0)
    acc = np.float32(1.0)
    q2p = np.float32(1.0)
    for _ in range(t):
        q2p = np.float32(q2p * q2)
        h = np.float32(np.float32(q1 * h) + q2p)
        acc = np.float32(acc + h)
    return np.float32(np.float32((np.float32(1.0) - q1) * (np.float32(1.0) - q2)) * acc)


def _live_mask(o: _O, i: int) -> np.ndarray:
    m = (o.key[i] & 3) != RANK_DEAD  # -1 (unknown) reads rank 3 too
    m[i] = False
    return m


def _cluster_size(o: _O, i: int) -> int:
    return int(((o.key[i] & 3) != RANK_DEAD).sum())


def _accept_into(o: _O, i: int, j: int, cand_key: int, salt: int,
                 namespace_gate: bool = False) -> bool:
    """The overrides gate + metadata-fetch gate + write, identical to the
    kernel's merge accept (incl. ``kernel._fetch_gate``) for one cell."""
    own = int(o.key[i, j])
    if cand_key <= own:
        return False
    if namespace_gate and not bool(o.ns_rel[o.ns_id[i], o.ns_id[j]]):
        return False
    known = own >= 0
    if not known and (cand_key & 3) > RANK_LEAVING:
        return False
    if (cand_key & 3) == RANK_ALIVE:  # ALIVE needs the fetch round trip
        u = np.float32(fetch_uniform(o.tick, salt, i, j, xp=np))
        p = (
            np.float32(o.fetch_rt)
            if o.fetch_rt.ndim == 0
            else o.fetch_rt[i, j]
        )
        if not (bool(o.up[j]) and u < p):
            return False
    o.key[i, j] = cand_key
    o.changed[i, j] = o.tick
    return True


def oracle_tick(state: SimState, key, params: SimParams, ad=None) -> _O:
    """One tick of the scalar oracle; returns the mutated numpy mirror.

    ``ad`` (r14) is a dict ``{"lh", "conf_key", "conf"}`` of [N] int32
    numpy arrays mirroring :class:`..adaptive.AdaptiveState`; when given,
    the tick mirrors the adaptive kernel (scaled probe timeout, adaptive
    suspicion sweep, confirmation counting at every merge accept) and the
    returned mirror carries the folded next state as ``o.ad``."""
    n, f, k = params.capacity, params.fanout, params.ping_req_k
    o = _O(state)
    o.tick += 1
    t = o.tick
    r = draw_tick_randoms(key, n, f, k)
    r = {name: np.asarray(getattr(r, name)) for name in r._fields}

    armed = ad is not None
    if armed:
        aspec = params.adaptive
        ad_miss = np.zeros(n, bool)
        ad_succ = np.zeros(n, bool)
        ad_refuted = np.zeros(n, bool)
        ad_cnt = np.zeros(n, np.int64)
        ad_key = np.full(n, np.iinfo(np.int32).min, np.int64)

        def _ad_note(j: int, cand: int) -> None:
            if (cand & 3) == RANK_SUSPECT:
                ad_cnt[j] += 1
                ad_key[j] = max(ad_key[j], cand)

    # ---- FD phase (reads a pre-phase snapshot, like the kernel) ----
    pre = o.snap()
    fd_on = (t % params.fd_every) == 0
    if fd_on:
        for i in range(n):
            if not pre.up[i]:
                continue
            sel, valid = _sample_distinct_row(_live_mask(pre, i), r["fd_sel"][i])
            if not valid[0]:
                continue
            tgt = int(sel[0])
            p_direct = _rt(pre, i, tgt)
            if params.delay_slots:
                t_dir = params.fd_direct_timeout_ticks
                if armed:
                    # Lifeguard LHA: the prober's own timeout stretch
                    t_dir = t_dir * (1 + int(ad["lh"][i]))
                p_direct = np.float32(
                    p_direct
                    * _timely(
                        _delay_q(pre, i, tgt),
                        _delay_q(pre, tgt, i),
                        t_dir,
                    )
                )
            ack = bool(pre.up[tgt]) and bool(r["fd_direct"][i] < p_direct)
            for s in range(k):
                if ack:
                    break
                if not valid[1 + s]:
                    continue
                rl = int(sel[1 + s])
                p4 = _rt(pre, i, rl) * _rt(pre, rl, tgt)
                if params.delay_slots:
                    p4 = np.float32(
                        p4
                        * _timely(
                            _delay_q(pre, i, rl),
                            _delay_q(pre, rl, i),
                            params.fd_leg_timeout_ticks,
                        )
                    )
                    p4 = np.float32(
                        p4
                        * _timely(
                            _delay_q(pre, rl, tgt),
                            _delay_q(pre, tgt, rl),
                            params.fd_leg_timeout_ticks,
                        )
                    )
                if pre.up[rl] and pre.up[tgt] and r["fd_relay"][i, s] < p4:
                    ack = True
            own = int(pre.key[i, tgt])  # targets come from the live view: >= 0
            if ack:
                cand = (int(pre.key[tgt, tgt]) >> 2) << 2  # ALIVE @ target's self-inc
            else:
                cand = ((own >> 2) << 2) | RANK_SUSPECT
            if armed:
                ad_miss[i] = not ack
                ad_succ[i] = bool(ack)
            if cand > own:
                o.key[i, tgt] = cand
                o.changed[i, tgt] = t
                if armed and not ack:
                    _ad_note(tgt, cand)

    # ---- suspicion sweep ----
    for i in range(n):
        if not o.up[i]:
            continue
        base = _ceil_log2(_cluster_size(o, i)) * params.fd_every
        timeout = params.suspicion_mult * base
        for j in range(n):
            if (o.key[i, j] & 3) != RANK_SUSPECT:
                continue
            if armed:
                # confirmation-scaled + observer-health-scaled window
                L = aspec.levels
                in_ep = int(o.key[i, j]) <= int(ad["conf_key"][j])
                num = (
                    _adp.conf_mult_num_scalar(aspec, int(ad["conf"][j]))
                    if in_ep
                    else aspec.max_mult * L
                )
                timeout_ij = (base * num * (1 + int(ad["lh"][i]))) // L
            else:
                timeout_ij = timeout
            if t - o.changed[i, j] >= timeout_ij:
                o.key[i, j] += 1  # SUSPECT -> DEAD at the same incarnation
                o.changed[i, j] = t

    # ---- gossip phase ----
    pre = o.snap()
    D = params.delay_slots
    recv_key = np.full((n, n), np.iinfo(np.int64).min, dtype=np.int64)
    recv_inf = np.zeros_like(pre.infected)
    recv_src = np.full_like(pre.infected_from, -1)
    if D:
        # in-flight messages landing this tick join the same merge
        slot_now = t % D
        arr_key = pre.pending_key[slot_now]
        arr_inf = pre.pending_inf[slot_now]
        arr_src = pre.pending_src[slot_now]
        noc = np.iinfo(arr_key.dtype).min  # key-dtype scatter-max identity
        for i in range(n):
            for j in range(n):
                if arr_key[i, j] > noc:
                    recv_key[i, j] = max(recv_key[i, j], int(arr_key[i, j]))
            for ru in range(params.rumor_slots):
                if arr_inf[i, ru]:
                    recv_inf[i, ru] = True
                    recv_src[i, ru] = max(recv_src[i, ru], int(arr_src[i, ru]))
    spec = params.dissem
    R = params.rumor_slots

    def _young_row(pr, a: int, spread_a: int):
        """Sender ``a``'s sendable user-rumor slots toward peer ``b`` is a
        per-peer filter; this is the peer-independent part (+ the r13
        pipelined budget window, DZ-3)."""
        return [
            ru
            for ru in range(R)
            if pr.infected[a, ru]
            and pr.r_active[ru]
            and t - pr.infected_at[a, ru] < spread_a
            and dz.budget_ok(spec, ru, t, R)
        ]

    for i in range(n):
        if not pre.up[i]:
            continue
        spread = params.repeat_mult * _ceil_log2(_cluster_size(pre, i))
        if spec.uniform_selection:
            peers, valid = _sample_distinct_row(
                _live_mask(pre, i), r["gossip_sel"][i]
            )
        else:
            peers, valid = dz.structured_peer_row(
                spec, n, t, i, r["gossip_sel"][i]
            )
        young_rumors_i = _young_row(pre, i, spread)
        # loop-invariant half of the kernel's has_payload gate (the pull
        # reply's eligibility) — hoisted out of the fanout loop
        young_any_i = spec.wants_pull and any(
            pre.key[i, j] >= 0 and t - pre.changed[i, j] < spread
            for j in range(n)
        )
        for s in range(f):
            if not valid[s]:
                continue
            p = int(peers[s])
            if not pre.up[p]:
                continue
            if not r["gossip_edge"][i, s] < (np.float32(1.0) - _loss(pre, i, p)):
                continue
            # per-edge delay draw: d = #{k in 1..D-1 : u < q^k}
            dd = 0
            if D:
                qd = _delay_q(pre, i, p)
                qpow = qd
                for _ in range(1, D):
                    if r["gossip_delay"][i, s] < qpow:
                        dd += 1
                    qpow = np.float32(qpow * qd)
            if dd == 0:
                for j in range(n):
                    if pre.key[i, j] >= 0 and t - pre.changed[i, j] < spread:
                        recv_key[p, j] = max(recv_key[p, j], int(pre.key[i, j]))
            else:
                slot_d = (t + dd) % D
                for j in range(n):
                    if pre.key[i, j] >= 0 and t - pre.changed[i, j] < spread:
                        o.pending_key[slot_d, p, j] = max(
                            int(o.pending_key[slot_d, p, j]), int(pre.key[i, j])
                        )
            send_rumors = [
                ru
                for ru in young_rumors_i
                # known-infected filter: skip the peer that delivered
                # this rumor to us, and its origin (kernel._deliver)
                if pre.infected_from[i, ru] != p and pre.r_origin[ru] != p
            ]
            for ru in send_rumors:
                if dd == 0:
                    recv_inf[p, ru] = True
                    recv_src[p, ru] = max(recv_src[p, ru], i)
                else:
                    slot_d = (t + dd) % D
                    o.pending_inf[slot_d, p, ru] = True
                    o.pending_src[slot_d, p, ru] = max(
                        int(o.pending_src[slot_d, p, ru]), i
                    )
            if spec.wants_pull and dd == 0:
                # push-pull reply (kernel DZ-2): fires iff the kernel's
                # forward ``ok`` fired — i.e. the contact actually carried
                # payload — and the reverse-link hashed draw survives
                if not (young_any_i or send_rumors):
                    continue
                rev = np.float32(fetch_uniform(t, dz.pull_salt(s), i, p, xp=np))
                if not rev < (np.float32(1.0) - _loss(pre, p, i)):
                    continue
                spread_p = params.repeat_mult * _ceil_log2(_cluster_size(pre, p))
                for j in range(n):
                    if pre.key[p, j] >= 0 and t - pre.changed[p, j] < spread_p:
                        recv_key[i, j] = max(recv_key[i, j], int(pre.key[p, j]))
                for ru in _young_row(pre, p, spread_p):
                    if pre.infected_from[p, ru] != i and pre.r_origin[ru] != i:
                        recv_inf[i, ru] = True
                        recv_src[i, ru] = max(recv_src[i, ru], p)
    for i in range(n):
        if not pre.up[i]:
            continue
        for j in range(n):
            if recv_key[i, j] > np.iinfo(np.int64).min:
                cand_g = int(recv_key[i, j])
                if _accept_into(o, i, j, cand_g, SALT_GOSSIP,
                                params.namespace_gate) and armed:
                    _ad_note(j, cand_g)
        for ru in range(params.rumor_slots):
            if recv_inf[i, ru] and pre.r_active[ru] and not o.infected[i, ru]:
                o.infected[i, ru] = True
                o.infected_at[i, ru] = t
                o.infected_from[i, ru] = recv_src[i, ru]
    if D:
        # the consumed ring slot resets (kernel clears it after the merge)
        o.pending_key[slot_now] = np.iinfo(o.pending_key.dtype).min
        o.pending_inf[slot_now] = False
        o.pending_src[slot_now] = -1

    # ---- SYNC phase ----
    pre = o.snap()
    callers = []
    # Static caller-slot cap, mirroring kernel._sync_phase's nonzero(size=K)
    # compaction: the first K due rows in ascending order get slots; the rest
    # wait for their next stagger slot / retry via force_sync.
    K = min(n, params.sync_slots or (n // params.sync_every + 32))
    slots_used = 0
    for i in range(n):
        if not pre.up[i]:
            continue
        due = ((t + i * params.sync_stagger) % params.sync_every) == 0 or bool(
            pre.force_sync[i]
        )
        if not due:
            continue
        if slots_used >= K:
            continue
        slots_used += 1
        sync_cand = _live_mask(pre, i)
        for srow in params.seed_rows:
            if srow != i:
                sync_cand[srow] = True
        peers, valid = _sample_distinct_row(sync_cand, np.asarray([r["sync_sel"][i]]))
        if not valid[0]:
            continue
        p = int(peers[0])
        p_rt = _rt(pre, i, p)
        if params.delay_slots:
            p_rt = np.float32(
                p_rt
                * _timely(
                    _delay_q(pre, i, p),
                    _delay_q(pre, p, i),
                    params.sync_timeout_ticks,
                )
            )
        if pre.up[p] and r["sync_edge"][i] < p_rt:
            # bootstrap force_sync clears only on a successful round-trip
            o.force_sync[i] = False
            callers.append((i, p))
    # request: all callers' tables (pre-snapshot) merged into peers
    recv_key = {}
    for i, p in callers:
        for j in range(n):
            if pre.key[i, j] >= 0:
                cand = int(pre.key[i, j])
                recv_key[(p, j)] = max(recv_key.get((p, j), cand), cand)
    for (p, j), cand in recv_key.items():
        if _accept_into(o, p, j, cand, SALT_SYNC_REQ,
                        params.namespace_gate) and armed:
            _ad_note(j, cand)
    # ack: peers' post-request tables back to callers (one snapshot for all)
    mid = o.snap()
    for i, p in callers:
        for j in range(n):
            if mid.key[p, j] >= 0:
                cand_a = int(mid.key[p, j])
                if _accept_into(o, i, j, cand_a, SALT_SYNC_ACK,
                                params.namespace_gate) and armed:
                    _ad_note(j, cand_a)

    # ---- refutation (SUSPECT/DEAD self-record, or overwritten leave intent;
    # a leaver re-announces LEAVING — see kernel._refute_phase) ----
    for i in range(n):
        if not o.up[i]:
            continue
        diag = int(o.key[i, i])
        rank = diag & 3
        if rank in (RANK_SUSPECT, RANK_DEAD) or (o.leaving[i] and rank != RANK_LEAVING):
            if armed:
                ad_refuted[i] = True
            new_rank = RANK_LEAVING if o.leaving[i] else RANK_ALIVE
            # layout-aware SATURATING bump (mirror of lattice.bump_inc):
            # a narrow key must never carry into its epoch bits
            lay = layout_for(o.key.dtype)
            inc = min(((diag >> 2) & lay.inc_mask) + 1, lay.inc_mask)
            o.key[i, i] = (
                ((diag >> lay.epoch_shift) << lay.epoch_shift)
                | (inc << 2)
                | new_rank
            )
            o.changed[i, i] = t

    # ---- rumor sweep (per-receiver hold semantics, kernel._rumor_sweep) ----
    n_up = int(o.up.sum())
    sweep = 2 * (params.repeat_mult * _ceil_log2(n_up) + 1)
    for ru in range(params.rumor_slots):
        if o.r_active[ru] and t - o.r_created[ru] > sweep:
            # still in flight?
            if params.delay_slots and bool(o.pending_inf[:, :, ru].any()):
                continue
            # some up receiver still inside its own forwarding window?
            forwarding = any(
                o.infected[i, ru]
                and o.up[i]
                and t - o.infected_at[i, ru]
                < params.repeat_mult * _ceil_log2(_cluster_size(o, i))
                for i in range(n)
            )
            if forwarding:
                continue
            o.r_active[ru] = False

    if armed:
        lh2, ck2, cf2 = _adp.fold(
            aspec,
            ad["lh"].astype(np.int32),
            ad["conf_key"].astype(np.int32),
            ad["conf"].astype(np.int32),
            acc_key=np.clip(
                ad_key, np.iinfo(np.int32).min, np.iinfo(np.int32).max
            ).astype(np.int32),
            acc_cnt=np.minimum(ad_cnt, np.iinfo(np.int32).max).astype(np.int32),
            miss=ad_miss,
            succ=ad_succ,
            refuted=ad_refuted,
            up=o.up,
            xp=np,
        )
        o.ad = {"lh": lh2, "conf_key": ck2, "conf": cf2}
    return o


def assert_equivalent(state: SimState, o: _O) -> None:
    """Raise AssertionError with a field name if kernel and oracle diverge."""
    pairs = {
        "tick": (int(state.tick), o.tick),
        "up": (np.asarray(state.up), o.up),
        "epoch": (np.asarray(state.epoch), o.epoch),
        "view_key": (np.asarray(state.view_key), o.key),
        "changed_at": (np.asarray(state.changed_at), o.changed),
        "force_sync": (np.asarray(state.force_sync), o.force_sync),
        "leaving": (np.asarray(state.leaving), o.leaving),
        "rumor_active": (np.asarray(state.rumor_active), o.r_active),
        "infected": (
            bitplane.unpack_bits(
                np.asarray(state.infected), o.infected.shape[1], xp=np
            ),
            o.infected,
        ),
        "infected_at": (np.asarray(state.infected_at), o.infected_at),
        "infected_from": (np.asarray(state.infected_from), o.infected_from),
        "pending_key": (np.asarray(state.pending_key), o.pending_key),
        "pending_inf": (
            bitplane.unpack_bits(
                np.asarray(state.pending_inf), o.infected.shape[1], xp=np
            ),
            o.pending_inf,
        ),
        "pending_src": (np.asarray(state.pending_src), o.pending_src),
    }
    for name, (a, b) in pairs.items():
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            diff = np.argwhere(np.asarray(a) != np.asarray(b))
            raise AssertionError(
                f"kernel/oracle divergence in {name} at {diff[:10].tolist()} "
                f"(kernel={np.asarray(a)[tuple(diff[0])] if diff.size else a}, "
                f"oracle={np.asarray(b)[tuple(diff[0])] if diff.size else b})"
            )
