"""The sparse ("record-queue") SWIM tick: large-N mode without O(N²) per-tick work.

The dense kernel (:mod:`.kernel`) does O(N²) elementwise work per active tick
(young-window scan + whole-row piggyback + dense suspicion sweep), which is
measured ~N²-shaped and sub-realtime past ~16k members on one chip. This
module is the scaling mode SURVEY.md §7 hard part (v) prescribes — per-tick
work O(N·f·log N)-ish, not O(N²) — built the way the reference itself
disseminates membership: **membership changes are gossips**. Every accepted
non-gossip update is re-gossiped by the reference
(``MembershipProtocolImpl.spreadMembershipGossipUnlessGossiped:836-843``);
here those records live in a bounded pool of M membership-rumor slots
(subject row + packed precedence key + origin) with per-node infection ages,
spread by the exact infection-style protocol user rumors already use
(``GossipProtocolImpl`` semantics). Dissemination cost scales with *change
rate*, not with N²:

* the only [N, N] plane is ``view_key`` itself (4 B/cell — ``changed_at`` is
  gone: gossip ages live on the rumors, suspicion stamps on the episodes);
* per-tick gossip work is O(N·M) on a one-byte infection-age plane (u8),
  plus O(N·f·T) for peer sampling; M is sized by live-change volume
  (events/tick × spread window), far below N for every real workload;
* peer selection is bounded **rejection sampling** (T uniform tries against
  the live view) instead of the dense kernel's exact rank-insertion over an
  [N, N] cumsum;
* suspicion timers are per-subject **episode stamps** (``sus_key`` /
  ``sus_since``, [N]) checked by a dense expiry sweep only every
  ``sweep_every`` ticks — O(N²/B) amortized;
* SYNC stays the dense kernel's compacted-caller O(K·N) design (anti-entropy
  is *supposed* to move whole tables);
* the delay model composes leanly: pending infection rings are [D, N, M]
  (never [D, N, N]), and FD/SYNC round trips use the same closed-form
  timeliness factors as the dense kernel (VERDICT r2 item #4).

Deliberate deviations from the reference (each mirrored bit-exactly by the
scalar oracle :mod:`.sparse_oracle`, and safe for the protocol's guarantees):

1. **Suspicion timer per episode, not per cell.** The reference schedules a
   timer per (observer, subject) at its own accept time
   (``scheduleSuspicionTimeoutTask:805-823``). Here the FIRST registration of
   a suspicion episode (subject + key, at any observer) stamps
   ``sus_since[subject]``; every observer expires against that stamp, checked
   every ``sweep_every`` ticks. Late-learning observers therefore expire up
   to one dissemination delay (≪ timeout: spread ≈ 3·log2 N ticks vs timeout
   = 5·log2 N·fd_every) earlier than their private timer would — the
   refutation window the timeout exists to provide is preserved.
2. **Origin-only known-infected filter.** The dense kernel tracks one
   delivering peer per infection (``infected_from``); per-source tracking for
   membership rumors would cost a 4 B/cell [N, M] plane and ~3 extra passes
   per tick — the exact cost this mode exists to avoid. Senders skip only the
   rumor's origin. (User rumors keep the full filter — their pool is tiny.)
3. **Bounded announcements with priority eviction.** New-rumor allocation is
   capped per tick (``announce_slots``) and per SYNC participant
   (``sync_announce`` — the reference re-gossips every sync-accepted
   record); the suspicion sweep announces one expiry per observer per sweep
   (every observer's own timer fires anyway — the rumor merely accelerates).
   The reference's gossip queue admits every accepted record unconditionally
   and sweeps only by age (``GossipProtocolImpl.getGossipsToRemove:350-358``);
   the bounded-pool analogue (r5): a PRIORITY fact (FD verdict, suspicion
   expiry, refutation, join/leave/metadata announce) that finds the pool
   full EVICTS the most-covered majority-spread rumor instead of dropping —
   the newest facts always get residency, and what is sacrificed is re-sends
   of a record ~everyone already merged (its tail heals via SYNC). Only SYNC
   re-gossip (pool duplicates by construction) and priority facts with no
   majority-covered victim are ever dropped; drops are counted per source
   (``announce_dropped_*``) and evictions as ``pool_evicted``. SYNC
   allocations additionally stop at 7/8 pool occupancy (backpressure):
   without the reserve, the sync flood refills every freed slot with a
   young sub-majority rumor and burst-time priority facts find no
   evictable victim.
4. **Bounded rejection sampling** can miss a pick with probability
   (1 - live_fraction)^T per draw (T = ``sample_tries``); a miss skips that
   probe/peer for one round — statistically negligible at the live fractions
   SWIM operates at, and the scalar oracle consumes identical draws.
5. **Early rumor free**: a membership rumor whose up-members are all infected
   (and nothing in flight) frees its slot before the reference's age-based
   sweep (``getGossipsToRemove:350-358``) would — fewer redundant sends, no
   semantic difference (every reachable node already merged it). Members who
   joined AFTER the rumor was created are exempt from its coverage
   requirement (r5). This IS a deviation in its own right: in the reference
   a new member enters ``remoteMembers`` (``GossipProtocolImpl.java:253``)
   and ``selectGossipMembers`` draws from that live list, so gossips still
   inside their spread window DO keep reaching it — the reference only
   stops forwarding once the spread window closes. What bounds the gap here
   is the joiner's forced initial SYNC: its full-table merge hands the
   joiner every fact the freed rumor carried, so the at-most-one-spread-
   window of missed forwards never outlives the bootstrap exchange.
   (Without the exemption the continuous joiner influx at large N keeps
   coverage perpetually one-joiner-short and residency degrades to the full
   age sweep — the measured r4 pool-saturation mechanism at N=49,152.)
   Age-based sweep still bounds the lifetime of never-fully-covered rumors.
6. **Receiver-pulled delivery with slot-collision drop.** Deliveries resolve
   through per-fanout-slot inverse sender indexes (one [N] point scatter +
   row gathers — ~2x the throughput of scattering payload planes by
   receiver): when several senders pick the same receiver in the same slot,
   only the highest-row sender's message lands that tick (the rest retry
   while their forwarding windows last — a second-order extra-loss term,
   ~fanout/N per edge). The known-infected/origin filters apply
   receiver-side, which cannot change state evolution (a filtered receiver
   is by definition already infected); message counters tally deliveries
   AFTER the origin/known-from filters and slot-collision drops (they count
   rumor payloads that actually landed, a lower bound on wire sends — the
   scalar oracle mirrors the same accounting).

Memory at flagship scale (v5e, 16 GB/chip): N=98,304 sharded over 8 chips =
4.8 GB/chip for ``view_key`` + pool planes (compile-proven at 11.6
GiB/device incl. donation — ``COMPILE_PROOF_100K.json``). Round 4's
scatter-free tick (see the design notes in ``_mr_apply`` / ``_sync_phase`` /
``_fd_phase._write``: every point/column scatter into the [N, N] view
forced a whole-matrix layout copy, and SYNC's gather-after-scatter staged
another) moved the single-chip ceiling from N=32,768 (r3: 36,864 faulted)
to **N=49,152 running 60 sim-seconds of churn end-to-end** (compiled
memory upper bound 14.7 GiB vs 23.5 faulting in r3 — the
``single_chip_memory`` entries in ``BENCH_RESULTS_r04.json``). N=65,536 needs 17.2 GB for the
view matrix alone and can never fit one chip.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from .. import adaptive as _adp
from ..adaptive import AdaptiveSpec
from ..dissemination import strategies as _dz
from ..dissemination.spec import DissemSpec
from .kernel import TELEMETRY_SERIES as _CORE_TELEMETRY_SERIES, ceil_log2
from .lattice import (
    ALIVE,
    RANK_ALIVE,
    RANK_DEAD,
    RANK_LEAVING,
    RANK_SUSPECT,
    UNKNOWN_KEY,
    precedence_key,
)
from .rand import (
    SALT_GOSSIP,
    SALT_SYNC_ACK,
    SALT_SYNC_REQ,
    SparseRandoms,
    draw_sparse_fd,
    draw_sparse_round,
    fetch_uniform,
    split_tick_key,
)
from .state import ALIVE0_KEY, NEVER, NO_CANDIDATE_I32, delay_mean_to_q

NO_CANDIDATE = NO_CANDIDATE_I32

# Active device mesh during sharded tracing (set by the sharding module's
# make_sharded_sparse_* builders). The tick itself is mesh-agnostic; a few
# staging tensors carry explicit sharding constraints when a mesh is active
# because GSPMD's default placement for them forces per-block all-gathers
# (see _mr_apply's word-sharded delivery bitmap).
_ACTIVE_MESH: contextvars.ContextVar = contextvars.ContextVar(
    "sparse_active_mesh", default=None
)


@contextlib.contextmanager
def mesh_context(mesh):
    token = _ACTIVE_MESH.set(mesh)
    try:
        yield
    finally:
        _ACTIVE_MESH.reset(token)


def _constrain(x, *spec):
    """with_sharding_constraint iff a sharded trace is active (no-op on the
    single-device path). ``"member"`` entries resolve to the active mesh's
    member axis."""
    mesh = _ACTIVE_MESH.get()
    if mesh is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    axis = mesh.axis_names[0]
    spec = tuple(axis if s == "member" else s for s in spec)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*spec))
    )


@dataclasses.dataclass(frozen=True)
class SparseParams:
    """Static parameters of the sparse tick (hashable; close over in jit).

    Shared protocol knobs mirror :class:`.state.SimParams` (same reference
    anchors); the sparse-only knobs size the bounded structures:
    ``mr_slots`` (M, membership-rumor pool), ``announce_slots`` (E, new
    rumors per tick), ``sample_tries`` (T, rejection draws per pick),
    ``sweep_every`` (B, suspicion expiry period), ``sync_announce`` (P,
    re-gossip cap per SYNC participant).
    """

    capacity: int
    fanout: int = 3
    repeat_mult: int = 3
    ping_req_k: int = 3
    fd_every: int = 5
    sync_every: int = 150
    sync_stagger: int = 1
    suspicion_mult: int = 5
    sweep_every: int = 8
    # 4 tries/pick (r5 default, was 8): deviation-4 miss probability is
    # (1-live_fraction)^4 per pick — 1e-8 at the ~0.99 live fractions SWIM
    # operates at — and the sampler is ~7 ms/tick at 49k at tries=8 (the
    # parameter-isolation table in docs/TPU_LAYOUT_NOTES.md); halving it
    # was the final ~5% to >=1x realtime at N=49,152 single-chip.
    sample_tries: int = 4
    rumor_slots: int = 16
    mr_slots: int = 1024
    announce_slots: int = 256
    sync_slots: int = 0
    sync_announce: int = 2
    # Per-round cap on FD verdicts / refutations WRITTEN per tick (0 = auto:
    # max(64, capacity // 16)). Point scatters into the [N, N] table
    # serialize per index on TPU (~1 µs each), so the usually-near-empty
    # accept sets are compacted to this many slots; throttled rows simply
    # retry next round (their trigger condition persists). Mass events
    # (partition waves) stretch by a few FD intervals — negligible against
    # the suspicion timeout, and mirrored exactly by the oracle.
    fd_accept_slots: int = 0
    refute_slots: int = 0
    delay_slots: int = 0
    # Column-block width of the membership-apply dense pass (VERDICT r3
    # item 1). The apply walks the view matrix in contiguous column blocks
    # (dynamic_slice → elementwise merge → dynamic_update_slice), which XLA
    # aliases fully in place — point/column scatters would instead force a
    # whole-matrix layout copy per tick (the true cause of the r3
    # single-chip ceiling; see _mr_apply). 0 = auto: whole width up to
    # N=8192 (tests/small N pay zero loop overhead), else the largest
    # power-of-two divisor of N ≤ 2048; must divide capacity when set
    # explicitly. Blocking is BIT-EXACT (disjoint column ranges, identical
    # per-cell expressions — lockstep-verified in test_sparse_chunked.py).
    apply_block: int = 0
    fd_direct_timeout_ticks: int = 2
    fd_leg_timeout_ticks: int = 1
    sync_timeout_ticks: int = 15
    seed_rows: tuple = ()
    early_free: bool = True
    full_metrics: bool = False

    @staticmethod
    def from_config(
        config,
        capacity: int | None = None,
        initial_size: int | None = None,
        seed_rows: tuple = (0,),
        mr_slots: int | None = None,
    ) -> "SparseParams":
        """Derive sparse-engine params from a ClusterConfig — the same
        tick-unit mapping as ``SimParams.from_config`` (one tick = one
        gossip period), plus pool sizing (default capacity // 16 — the r5
        measured 1%/s-churn demand is ~N/27 with the joiner-exempt
        early-free, so N/16 is ~1.7x headroom, and priority eviction +
        sync backpressure absorb bursts beyond it; the r4 N/8 default
        predates the early-free fix)."""
        sim = config.sim
        cap = capacity or sim.capacity or (initial_size or 0)
        if cap <= 1:
            raise ValueError(
                "sim capacity must be > 1 (set config.sim.capacity, or pass "
                "capacity= / initial_size=)"
            )
        dt = sim.tick_interval
        return SparseParams(
            capacity=cap,
            fanout=config.gossip.gossip_fanout,
            repeat_mult=config.gossip.gossip_repeat_mult,
            ping_req_k=config.failure_detector.ping_req_members,
            fd_every=max(1, round(config.failure_detector.ping_interval / dt)),
            sync_every=max(1, round(config.membership.sync_interval / dt)),
            suspicion_mult=config.membership.suspicion_mult,
            rumor_slots=sim.rumor_slots,
            mr_slots=mr_slots or max(256, cap // 16),
            seed_rows=tuple(seed_rows),
            delay_slots=sim.delay_slots,
            fd_direct_timeout_ticks=max(
                0, int(config.failure_detector.ping_timeout / dt)
            ),
            fd_leg_timeout_ticks=max(
                0,
                int(
                    (config.failure_detector.ping_interval
                     - config.failure_detector.ping_timeout) / dt / 2
                ),
            ),
            sync_timeout_ticks=max(0, int(config.membership.sync_timeout / dt)),
            dissem=DissemSpec.from_config(config),
            adaptive=AdaptiveSpec.from_config(config),
        )

    # hierarchical-namespace relatedness gate on every merge accept
    # (areNamespacesRelated, MembershipProtocolImpl.java:511-536); zero-cost
    # when False. Unrelated records never enter a view, so peer selection
    # (drawn from the view) needs no extra gating.
    namespace_gate: bool = False
    # Dissemination strategy/topology (r13, dissemination/): the default
    # spec traces the byte-identical legacy program; non-default specs swap
    # only the gossip phase's peer selection / payload policy.
    dissem: DissemSpec = DissemSpec()
    # Adaptive failure detection (r14, adaptive.py): default = byte-identical
    # legacy program; enabled specs arm the Lifeguard-style plane (windows
    # built via make_sparse_adaptive_run).
    adaptive: AdaptiveSpec = AdaptiveSpec()


class SparseState(struct.PyTreeNode):
    """Lean large-N simulation state.

    ``view_key[i, j]`` — as the dense :class:`.state.SimState`: node i's
    record for j as the packed monotone precedence key (:mod:`.lattice`),
    -1 unknown. The ONLY N×N plane.

    ``n_live[i]`` — incrementally maintained count of non-DEAD known columns
    in row i (incl. self): drives every ``ceilLog2(cluster size)`` knob
    (``ClusterMath.java:111-135``) without an O(N²) recount.

    ``sus_key[subject]`` / ``sus_since[subject]`` — current suspicion
    episode: the highest SUSPECT-rank key ever accepted about ``subject`` and
    the tick its value last rose (deviation 1 above).

    Membership-rumor pool (M slots): ``mr_subject/mr_key/mr_origin/
    mr_created/mr_active`` + the u8 infection-age plane ``minf_age[i, m]``
    (0 = not infected; else ticks-since-infection + 1, saturating at 255 —
    every forwarding window is ≤ ``repeat_mult·ceilLog2(N) < 255``). Infection
    marking doubles as the reference's ``SequenceIdCollector`` dedup: a rumor
    is applied to the table exactly once per receiver, at first infection.

    User-rumor pool: identical fields/semantics to the dense state
    (``rumor_*``, ``infected*`` — the full known-infected filter retained).

    Links: ``loss`` / ``fetch_rt`` / ``delay_q`` scalar (uniform, the lean
    default) or dense [N, N] (emulator mode at moderate N).
    """

    tick: jax.Array
    up: jax.Array  # bool [N]
    epoch: jax.Array  # i32 [N]
    joined_at: jax.Array  # i32 [N] — tick of the row's latest join (0 at init)
    view_key: jax.Array  # i32 [N, N]
    n_live: jax.Array  # i32 [N]
    sus_key: jax.Array  # i32 [N]
    sus_since: jax.Array  # i32 [N]
    force_sync: jax.Array  # bool [N]
    leaving: jax.Array  # bool [N]
    ns_id: jax.Array  # i32 [N] — namespace group per row
    ns_rel: jax.Array  # bool [G, G] — host-built relatedness table
    mr_active: jax.Array  # bool [M]
    mr_subject: jax.Array  # i32 [M]
    mr_key: jax.Array  # i32 [M]
    mr_created: jax.Array  # i32 [M]
    mr_origin: jax.Array  # i32 [M]
    minf_age: jax.Array  # u8 [N, M]
    rumor_active: jax.Array  # bool [R]
    rumor_origin: jax.Array  # i32 [R]
    rumor_created: jax.Array  # i32 [R]
    infected: jax.Array  # bool [N, R]
    infected_at: jax.Array  # i32 [N, R]
    infected_from: jax.Array  # i32 [N, R]
    loss: jax.Array
    fetch_rt: jax.Array
    delay_q: jax.Array
    pending_minf: jax.Array  # bool [D, N, M]
    pending_inf: jax.Array  # bool [D, N, R]
    pending_src: jax.Array  # i32 [D, N, R]

    @property
    def capacity(self) -> int:
        return self.up.shape[0]


# ---------------------------------------------------------------------------
# construction + host mutators
# ---------------------------------------------------------------------------


def _roundtrip(loss: jax.Array) -> jax.Array:
    if loss.ndim == 0:
        return ((1.0 - loss) * (1.0 - loss)).astype(jnp.float32)
    return ((1.0 - loss) * (1.0 - loss.T)).astype(jnp.float32)


def init_sparse_state(
    params: SparseParams,
    n_initial: int,
    warm: bool = True,
    dense_links: bool = False,
    uniform_loss: float = 0.0,
    uniform_delay: float = 0.0,
    namespaces=None,
) -> SparseState:
    """Fresh sparse-mode simulation; rows ``0..n_initial-1`` up.

    ``dense_links`` defaults to False (scalar uniform loss) — this mode
    exists for N where an [N, N] float link matrix is unaffordable; pass
    True for emulator-controlled runs at moderate N."""
    n, m, r = params.capacity, params.mr_slots, params.rumor_slots
    up = jnp.arange(n) < n_initial
    if namespaces is not None:
        from .state import build_namespace_tables

        ids_np, rel_np = build_namespace_tables(list(namespaces))
        ns_id = jnp.asarray(ids_np)
        ns_rel = jnp.asarray(rel_np)
        related = ns_rel[ns_id[:, None], ns_id[None, :]] | jnp.eye(n, dtype=bool)
    else:
        ns_id = jnp.zeros((n,), jnp.int32)
        ns_rel = jnp.ones((1, 1), bool)
        related = None
    if warm:
        if related is not None:
            known = up[:, None] & up[None, :] & related
            n_live = known.sum(axis=1).astype(jnp.int32)
            view_key = jnp.where(known, ALIVE0_KEY, UNKNOWN_KEY).astype(jnp.int32)
            del known
        else:
            # fused under jit so the [N, N] bool staging plane never
            # materializes (eagerly it is 2.4 GB at 49k and pushes the
            # one-op working set past the chip's compute residency)
            view_key = jax.jit(
                lambda u: jnp.where(
                    u[:, None] & u[None, :], ALIVE0_KEY, UNKNOWN_KEY
                ).astype(jnp.int32)
            )(up)
            n_live = jnp.where(up, n_initial, 0).astype(jnp.int32)
    else:
        diag = jnp.eye(n, dtype=bool) & up[:, None]
        view_key = jnp.where(diag, ALIVE0_KEY, UNKNOWN_KEY).astype(jnp.int32)
        n_live = up.astype(jnp.int32)
    if uniform_delay > 0 and params.delay_slots <= 0:
        raise ValueError("uniform_delay > 0 requires params.delay_slots > 0")
    loss = (
        jnp.full((n, n), uniform_loss, jnp.float32)
        if dense_links
        else jnp.float32(uniform_loss)
    )
    q = delay_mean_to_q(uniform_delay)
    delay_q = jnp.full((n, n), q, jnp.float32) if dense_links else jnp.float32(q)
    d = max(0, params.delay_slots)
    return SparseState(
        tick=jnp.int32(0),
        up=up,
        epoch=jnp.zeros((n,), jnp.int32),
        joined_at=jnp.zeros((n,), jnp.int32),
        view_key=view_key,
        n_live=n_live,
        sus_key=jnp.full((n,), NO_CANDIDATE, jnp.int32),
        sus_since=jnp.full((n,), NEVER, jnp.int32),
        force_sync=jnp.zeros((n,), bool),
        leaving=jnp.zeros((n,), bool),
        ns_id=ns_id,
        ns_rel=ns_rel,
        mr_active=jnp.zeros((m,), bool),
        mr_subject=jnp.full((m,), -1, jnp.int32),
        mr_key=jnp.zeros((m,), jnp.int32),
        mr_created=jnp.zeros((m,), jnp.int32),
        mr_origin=jnp.zeros((m,), jnp.int32),
        minf_age=jnp.zeros((n, m), jnp.uint8),
        rumor_active=jnp.zeros((r,), bool),
        rumor_origin=jnp.zeros((r,), jnp.int32),
        rumor_created=jnp.zeros((r,), jnp.int32),
        infected=jnp.zeros((n, r), bool),
        infected_at=jnp.zeros((n, r), jnp.int32),
        infected_from=jnp.full((n, r), -1, jnp.int32),
        loss=loss,
        fetch_rt=_roundtrip(loss),
        delay_q=delay_q,
        pending_minf=jnp.zeros((d, n, m), bool),
        pending_inf=jnp.zeros((d, n, r), bool),
        pending_src=jnp.full((d, n, r), -1, jnp.int32),
    )


def _allocate(state: SparseState, subj_p, key_p, orig_p, got, prio):
    """Allocate/supersede membership rumors for E compacted proposals.

    POOL INVARIANT: active slots carry UNIQUE subjects. A proposal matching
    an active subject with a strictly HIGHER key supersedes that slot in
    place (the old rumor's infection column and pending deliveries are
    cleared — the superseded record loses every merge anyway, so spreading
    the stronger fact instead is strictly faster); lower/equal keys are
    already covered and are skipped. Fresh subjects take ascending free
    slots. Batch duplicates: max key wins, ties to the earliest entry.

    PRIORITY EVICTION (deviation 3, r5): ``prio`` (required — every caller
    must classify its proposals) marks priority entries. A fresh
    PRIORITY winner (FD verdict, suspicion expiry, refutation, join/leave
    announce — anything that is not SYNC re-gossip of pool contents) that
    finds no free slot EVICTS the active rumor closest to done: the fewest
    still-uncovered members among those who NEED it (up and not exempt by
    the joined-after-creation rule), ties to the lowest slot, among slots
    with a majority of their needing members covered and not superseded by
    this batch. The evicted rumor's tail heals via SYNC — the reference's
    queue admits every accepted record unconditionally
    (``GossipProtocolImpl.java:350-358`` sweeps only by age), and this is
    the bounded-memory analogue: the newest facts always get residency,
    what's sacrificed is re-sends of a rumor ~everyone already merged.
    Prio winners drop only when no majority-covered victim exists (counted
    by the caller's per-source drop attribution).

    Returns (state, allocated_count, no_slot_mask, evicted_count) — the
    mask marks fresh winners that found no slot (after eviction), per
    proposal entry; the caller attributes those drops to their source.
    """
    E = subj_p.shape[0]
    M = state.mr_active.shape[0]
    s = jnp.where(got, subj_p, -9)  # sentinel: matches nothing real
    same_s = s[:, None] == s[None, :]
    tie_earlier = jnp.tri(E, E, -1, dtype=bool)  # [e, e']: e' < e
    lose = (
        same_s
        & (
            (key_p[None, :] > key_p[:, None])
            | ((key_p[None, :] == key_p[:, None]) & tie_earlier)
        )
    ).any(axis=1)
    win = got & ~lose
    match = (s[:, None] == state.mr_subject[None, :]) & state.mr_active[None, :]
    has_match = match.any(axis=1)
    mslot = jnp.argmax(match, axis=1).astype(jnp.int32)
    replace = win & has_match & (key_p > state.mr_key[mslot])
    fresh = win & ~has_match
    rank = jnp.cumsum(fresh.astype(jnp.int32)) - 1
    (free,) = jnp.nonzero(~state.mr_active, size=E, fill_value=M)
    slot_fresh = free[jnp.clip(rank, 0, E - 1)]
    ok_fresh = fresh & (slot_fresh < M)
    # SYNC-allocation backpressure (deviation 3, r5): non-priority
    # allocations (sync re-gossip — duplicates of table state that any
    # stale node also gets through its own sync) stop at 7/8 pool
    # occupancy. Without the reserve, the sync flood refills every
    # freed slot with a brand-new (sub-majority-covered) rumor, so at
    # churn-burst time the pool holds no evictable majority-covered
    # victims and priority facts drop — the measured 49k residual after
    # eviction landed. rank-based: the e-th fresh winner sees occupancy
    # a0 + rank (a conservative upper bound — replaces don't add slots).
    cap_npr = (M * 7) // 8
    a0 = state.mr_active.sum().astype(jnp.int32)
    ok_fresh = ok_fresh & (prio | (a0 + rank < cap_npr))
    need = fresh & ~ok_fresh & prio
    K = min(E, M)
    erank_raw = jnp.cumsum(need.astype(jnp.int32)) - 1
    erank = jnp.clip(erank_raw, 0, K - 1)

    def _ev(_):
        # who still NEEDS each rumor: up members not exempt by the
        # joined-after-creation rule (down members neither need nor can
        # receive it — counting them as "covered" would let a barely-
        # spread rumor masquerade as a victim in down-heavy clusters).
        # The [N, M] pass runs only when a prio winner needs a slot.
        needs = state.up[:, None] & ~(
            state.joined_at[:, None] > state.mr_created[None, :]
        )
        need_m = needs.sum(axis=0).astype(jnp.int32)
        cov_m = (needs & (state.minf_age > 0)).sum(axis=0).astype(jnp.int32)
        replace_tgt = (
            jnp.zeros((M + 1,), bool)
            .at[jnp.where(replace, mslot, M)]
            .set(True)[:M]
        )
        # victim = fewest still-uncovered needing members ("closest to
        # done"), gated on a majority of its needing members covered
        evictable = state.mr_active & ~replace_tgt & (2 * cov_m >= need_m)
        score = jnp.where(evictable, cov_m - need_m, jnp.iinfo(jnp.int32).min)
        vals, victims = jax.lax.top_k(score, K)  # ties -> lowest slot
        ok_e = need & (erank_raw < K) & (vals[erank] > jnp.iinfo(jnp.int32).min)
        return ok_e, victims[erank].astype(jnp.int32)

    def _no(_):
        return jnp.zeros((E,), bool), jnp.full((E,), M, jnp.int32)

    ok_evict, slot_evict = jax.lax.cond(need.any(), _ev, _no, None)
    do = replace | ok_fresh | ok_evict
    slot = jnp.where(replace, mslot, jnp.minimum(slot_fresh, M - 1))
    slot = jnp.where(ok_evict, slot_evict, slot)
    slot = jnp.where(do, slot, M)  # non-allocating entries dropped OOB
    # Distinct OOB sentinels (M + e): the unique_indices=True scatters below
    # promise ALL indices distinct, and a repeated sentinel — even one that
    # mode="drop" discards — makes that promise false (JAX documents the
    # result as undefined). In-bounds entries are unique by the pool
    # invariant (replace slots), top_k distinctness (evict slots, disjoint
    # from replace targets by construction); M + arange keeps the sentinels
    # unique too.
    clear_slot = jnp.where(
        replace | ok_evict, slot, M + jnp.arange(E, dtype=jnp.int32)
    )
    age = state.minf_age.at[:, clear_slot].set(
        jnp.uint8(0), mode="drop", unique_indices=True
    )
    age = age.at[orig_p, slot].set(jnp.uint8(1), mode="drop")
    st = state.replace(
        mr_active=state.mr_active.at[slot].set(True, mode="drop"),
        mr_subject=state.mr_subject.at[slot].set(s, mode="drop"),
        mr_key=state.mr_key.at[slot].set(key_p, mode="drop"),
        mr_created=state.mr_created.at[slot].set(state.tick, mode="drop"),
        mr_origin=state.mr_origin.at[slot].set(orig_p, mode="drop"),
        minf_age=age,
    )
    if state.pending_minf.shape[0]:
        st = st.replace(
            pending_minf=state.pending_minf.at[:, :, clear_slot].set(
                False, mode="drop", unique_indices=True
            )
        )
    return st, do.sum(), fresh & ~ok_fresh & ~ok_evict, ok_evict.sum()


def announce(state: SparseState, subject, key, origin) -> SparseState:
    """Host-side membership-rumor allocation (join/leave/metadata paths —
    the in-tick analogue is the allocation phase). Supersedes an existing
    rumor about the same subject when strictly newer; when the pool is full
    it evicts the most-covered majority-spread rumor (priority eviction,
    deviation 3). A drop remains possible only when NO majority-covered
    victim exists (a pool full of brand-new facts) — the fact then reaches
    peers via force_sync/SYNC; ``SimDriver.join`` detects and counts this
    (``announce_dropped_host``) so /health still sees it."""
    st, _a, _d, _e = _allocate(
        state,
        jnp.asarray([subject], jnp.int32),
        jnp.asarray([key], jnp.int32),
        jnp.asarray([origin], jnp.int32),
        jnp.ones((1,), bool),
        prio=jnp.ones((1,), bool),
    )
    return st


def join_row(state: SparseState, row: int, seed_rows) -> SparseState:
    """Activate ``row`` as a fresh member knowing itself + seed placeholders;
    identical identity-epoch semantics to the dense ``state.join_row``
    (restart = new member id via the epoch bits — :mod:`.lattice`). Also
    self-announces the new identity as a membership rumor (the reference
    seed's sync-accept re-gossip spreads a joiner; the self-announce plus the
    SYNC participants' ``sync_announce`` cover both paths)."""
    seed_rows = jnp.asarray(seed_rows, jnp.int32)
    # The epoch bump is staged FIRST so the ``was_used`` view_key gather
    # escapes only into the epoch scatter (r19): a pre-scatter read of the
    # [N, N] plane flowing into later outputs pins the old buffer and
    # forces the jitted donated spelling to copy the whole plane (~70 MB
    # per interactive join at the 4096-member point) instead of updating
    # it in place. Everything downstream re-derives from the BUMPED epoch.
    was_used = state.view_key[row, row] >= 0
    state = state.replace(
        epoch=state.epoch.at[row].set(
            jnp.where(was_used, (state.epoch[row] + 1) & 0xFF, state.epoch[row])
        )
    )
    new_epoch = state.epoch[row]
    self_key = precedence_key(jnp.int32(ALIVE), jnp.int32(0), new_epoch)
    seed_keys = precedence_key(
        jnp.full(seed_rows.shape, ALIVE, jnp.int32),
        jnp.int32(0),
        state.epoch[seed_rows],
    )
    row_key = (
        jnp.full((state.capacity,), UNKNOWN_KEY, jnp.int32)
        .at[seed_rows]
        .set(seed_keys)
        .at[row]
        .set(self_key)
    )
    n_live_row = ((row_key & 3) != RANK_DEAD).sum().astype(jnp.int32)
    state = state.replace(
        up=state.up.at[row].set(True),
        joined_at=state.joined_at.at[row].set(state.tick),
        view_key=state.view_key.at[row].set(row_key),
        n_live=state.n_live.at[row].set(n_live_row),
        force_sync=state.force_sync.at[row].set(True),
        leaving=state.leaving.at[row].set(False),
        minf_age=state.minf_age.at[row].set(0),
        infected=state.infected.at[row].set(False),
        infected_from=state.infected_from.at[row].set(-1),
        pending_minf=state.pending_minf.at[:, row].set(False)
        if state.pending_minf.shape[0]
        else state.pending_minf,
        pending_inf=state.pending_inf.at[:, row].set(False)
        if state.pending_inf.shape[0]
        else state.pending_inf,
        pending_src=state.pending_src.at[:, row].set(-1)
        if state.pending_src.shape[0]
        else state.pending_src,
    )
    return announce(state, row, self_key, row)


def join_rows(state: SparseState, rows, seed_rows) -> SparseState:
    """Vectorized churn-burst join (distinct ``rows``); jit with
    ``donate_argnums=0``. Mirrors the dense ``state.join_rows`` (post-burst
    seed epochs) and allocates one self-announce rumor per joiner (pool
    permitting)."""
    rows = jnp.asarray(rows, jnp.int32)
    seed_rows = jnp.asarray(seed_rows, jnp.int32)
    k = rows.shape[0]
    was_used = state.view_key[rows, rows] >= 0
    new_epoch = jnp.where(was_used, (state.epoch[rows] + 1) & 0xFF, state.epoch[rows])
    self_keys = precedence_key(
        jnp.full((k,), ALIVE, jnp.int32), jnp.zeros((k,), jnp.int32), new_epoch
    )
    epoch_after = state.epoch.at[rows].set(new_epoch)
    seed_keys = precedence_key(
        jnp.full(seed_rows.shape, ALIVE, jnp.int32),
        jnp.zeros(seed_rows.shape, jnp.int32),
        epoch_after[seed_rows],
    )
    row_key = (
        jnp.full((k, state.capacity), UNKNOWN_KEY, jnp.int32)
        .at[:, seed_rows]
        .set(seed_keys[None, :])
        .at[jnp.arange(k), rows]
        .set(self_keys)
    )
    n_live_rows = ((row_key & 3) != RANK_DEAD).sum(axis=1).astype(jnp.int32)
    state = state.replace(
        up=state.up.at[rows].set(True),
        epoch=epoch_after,
        joined_at=state.joined_at.at[rows].set(state.tick),
        view_key=state.view_key.at[rows].set(row_key),
        n_live=state.n_live.at[rows].set(n_live_rows),
        force_sync=state.force_sync.at[rows].set(True),
        leaving=state.leaving.at[rows].set(False),
        minf_age=state.minf_age.at[rows].set(0),
        infected=state.infected.at[rows].set(False),
        infected_from=state.infected_from.at[rows].set(-1),
        pending_minf=state.pending_minf.at[:, rows].set(False)
        if state.pending_minf.shape[0]
        else state.pending_minf,
        pending_inf=state.pending_inf.at[:, rows].set(False)
        if state.pending_inf.shape[0]
        else state.pending_inf,
        pending_src=state.pending_src.at[:, rows].set(-1)
        if state.pending_src.shape[0]
        else state.pending_src,
    )
    # batch self-announces (supersede-capable: a joiner's fresh epoch beats a
    # lingering death rumor about the same row); a full pool EVICTS
    # most-covered rumors rather than dropping joiner identities (priority
    # eviction, deviation 3 — the r4 49k staleness collapse traced exactly
    # to joins announced into a saturated pool)
    state, _a, _d, _e = _allocate(
        state, rows, self_keys, rows, jnp.ones((k,), bool),
        prio=jnp.ones((k,), bool),
    )
    return state


def crash_row(state: SparseState, row: int) -> SparseState:
    return state.replace(up=state.up.at[row].set(False))


def begin_leave(state: SparseState, row: int) -> SparseState:
    """Graceful leave: LEAVING self-record + announcement rumor (the
    reference's leaveCluster LEAVING gossip,
    ``MembershipProtocolImpl.java:233-242``)."""
    # scatter first, re-gather after (r19): a pre-scatter ``own`` gather
    # escaping into the announce would force the jitted donated spelling
    # to copy the whole [N, N] plane (see update_metadata below)
    state = state.replace(
        view_key=state.view_key.at[row, row].set(
            ((state.view_key[row, row] >> 2) << 2) | RANK_LEAVING
        ),
        leaving=state.leaving.at[row].set(True),
    )
    return announce(state, row, state.view_key[row, row], row)


def update_metadata(state: SparseState, row: int) -> SparseState:
    """Metadata update = own-incarnation bump re-announced ALIVE
    (``ClusterImpl.updateMetadata``, ``ClusterImpl.java:497-501``).

    The bump scatters FIRST and the announce key is re-gathered from the
    updated plane (r19): a pre-scatter gather that escapes into the
    announce would pin a read of the old ``view_key``, forcing the jitted
    donated spelling to copy the whole [N, N] plane instead of updating
    in place (~70 MB per interactive op at the 4096-member point)."""
    state = state.replace(view_key=state.view_key.at[row, row].add(4))
    return announce(state, row, state.view_key[row, row], row)


def spread_rumor(state: SparseState, slot: int, origin: int) -> SparseState:
    """Start a user rumor (Cluster.spreadGossip) — dense-state semantics."""
    return state.replace(
        rumor_active=state.rumor_active.at[slot].set(True),
        rumor_origin=state.rumor_origin.at[slot].set(origin),
        rumor_created=state.rumor_created.at[slot].set(state.tick),
        infected=state.infected.at[:, slot].set(False).at[origin, slot].set(True),
        infected_at=state.infected_at.at[origin, slot].set(state.tick),
        infected_from=state.infected_from.at[:, slot].set(-1),
    )


def set_link_loss(state: SparseState, src, dst, loss: float) -> SparseState:
    if state.loss.ndim == 0:
        raise ValueError(
            "per-link loss needs dense links; init_sparse_state(dense_links=True)"
        )
    src = jnp.atleast_1d(jnp.asarray(src))
    dst = jnp.atleast_1d(jnp.asarray(dst))
    new_loss = state.loss.at[src[:, None], dst[None, :]].set(loss)
    g = new_loss[dst[:, None], src[None, :]]
    fwd = (1.0 - jnp.float32(loss)) * (1.0 - g)
    new_rt = state.fetch_rt.at[src[:, None], dst[None, :]].set(fwd.T)
    new_rt = new_rt.at[dst[:, None], src[None, :]].set(fwd)
    return state.replace(loss=new_loss, fetch_rt=new_rt)


def set_link_delay(state: SparseState, src, dst, mean_delay_ticks: float) -> SparseState:
    if state.delay_q.ndim == 0:
        raise ValueError(
            "per-link delay needs dense links; init_sparse_state(dense_links=True)"
        )
    if mean_delay_ticks > 0 and state.pending_minf.shape[0] == 0:
        raise ValueError("link delay requires params.delay_slots > 0")
    src = jnp.atleast_1d(jnp.asarray(src))
    dst = jnp.atleast_1d(jnp.asarray(dst))
    q = delay_mean_to_q(mean_delay_ticks)
    return state.replace(delay_q=state.delay_q.at[src[:, None], dst[None, :]].set(q))


def block_partition(state: SparseState, group_a, group_b) -> SparseState:
    s = set_link_loss(state, group_a, group_b, 1.0)
    return set_link_loss(s, group_b, group_a, 1.0)


def heal_partition(state: SparseState, group_a, group_b) -> SparseState:
    s = set_link_loss(state, group_a, group_b, 0.0)
    return set_link_loss(s, group_b, group_a, 0.0)


def set_uniform_loss(
    state: SparseState, loss: float, floor: bool = False
) -> SparseState:
    """Uniform link loss across every link (chaos LossStorm site). Scalar
    mode swaps the one loss scalar; dense mode rewrites the matrix — with
    ``floor=True`` existing losses only ever RISE (``max(loss_ij, loss)``),
    so partition blocks survive a storm. ``fetch_rt`` is re-derived here
    (losses change only between ticks; see the dense state's account)."""
    if state.loss.ndim == 0:
        new_loss = jnp.float32(
            jnp.maximum(state.loss, loss) if floor else loss
        )
    else:
        new_loss = (
            jnp.maximum(state.loss, jnp.float32(loss))
            if floor
            else jnp.full_like(state.loss, loss)
        )
    return state.replace(loss=new_loss, fetch_rt=_roundtrip(new_loss))


def crash_rows(state: SparseState, rows) -> SparseState:
    """Vectorized hard-kill of a whole crash cohort (chaos Crash site)."""
    return state.replace(
        up=state.up.at[jnp.asarray(rows, jnp.int32)].set(False)
    )


def sentinel_reduce(state: SparseState, sent: dict, spec: dict) -> dict:
    """Sparse-engine chaos sentinel check: the shared view-plane core
    (:func:`.kernel.sentinel_core`) plus the sparse-only internal
    consistency sentinel — ``n_live`` (the incrementally maintained
    non-DEAD column count that drives every ceilLog2 knob) must equal a
    fresh recount for every up row; drift means the incremental updates
    and the merge disagreed, a corruption no protocol-level check sees."""
    from .kernel import sentinel_core

    sent = sentinel_core(state.view_key, state.up, state.tick, sent, spec)
    recount = ((state.view_key & 3) != RANK_DEAD).sum(axis=1).astype(jnp.int32)
    drift = (state.up & (recount != state.n_live)).sum().astype(jnp.int32)
    sent["n_live_drift"] = sent.get("n_live_drift", jnp.int32(0)) + drift
    return sent


# Sparse telemetry ring layout (r8): the engine-shared prefix (see
# kernel.TELEMETRY_SERIES) plus the bounded-pool backpressure series — the
# exact failure mode the r4 churn run exposed, now a per-window time series
# instead of a one-shot snapshot.
TELEMETRY_SERIES = _CORE_TELEMETRY_SERIES + (
    "announced",
    "announce_dropped",
    "pool_evicted",
    "mr_active_high_water",
)


def telemetry_window_vector(ms: dict, state: SparseState) -> jax.Array:
    """Sparse-engine telemetry row: the shared core vector plus the pool
    series, as one [len(TELEMETRY_SERIES)] f32 vector. Pure jnp — zero
    device→host transfers; the mesh-sharded builders produce replicated
    metric leaves so the same reduction serves the sharded driver."""
    from .kernel import telemetry_window_core

    f32 = jnp.float32
    vec = telemetry_window_core(ms, state)
    vec.extend(
        [
            ms["announced"].sum().astype(f32),
            ms["announce_dropped"].sum().astype(f32),
            ms["pool_evicted"].sum().astype(f32),
            ms["mr_active_count"].max().astype(f32),
        ]
    )
    return jnp.stack(vec)


def snapshot(state: SparseState) -> dict:
    return {
        f.name: np.asarray(getattr(state, f.name))
        for f in dataclasses.fields(SparseState)
    }


def restore(arrays: dict) -> SparseState:
    arrays = dict(arrays)
    # pre-r5 checkpoints have no joined_at; all-zeros (joined at init) is
    # the exact pre-r5 semantics (nobody exempt from rumor coverage)
    if "joined_at" not in arrays:
        arrays["joined_at"] = np.zeros(np.shape(arrays["up"]), np.int32)
    # copy=True: jnp.asarray zero-copies aligned numpy buffers on CPU and
    # the driver DONATES restored state into the tick window — see the
    # dense state.restore for the full use-after-free account
    return SparseState(**{k: jnp.array(v, copy=True) for k, v in arrays.items()})


# ---------------------------------------------------------------------------
# in-tick helpers
# ---------------------------------------------------------------------------


def _loss_at(state: SparseState, i, j):
    if state.loss.ndim == 0:
        return jnp.broadcast_to(state.loss, jnp.shape(i))
    return state.loss[i, j]


def _rt_at(state: SparseState, i, j):
    if state.fetch_rt.ndim == 0:
        return jnp.broadcast_to(state.fetch_rt, jnp.shape(i))
    return state.fetch_rt[i, j]


def _delay_q_at(state: SparseState, i, j):
    if state.delay_q.ndim == 0:
        return jnp.broadcast_to(state.delay_q, jnp.shape(i))
    return state.delay_q[i, j]


def _timely_rt(q1, q2, t: int):
    """P(two geometric legs sum ≤ t) — identical to ``kernel._timely_rt``."""
    h = jnp.ones_like(q1)
    acc = h
    q2p = jnp.ones_like(q2)
    for _ in range(t):
        q2p = q2p * q2
        h = q1 * h + q2p
        acc = acc + h
    return (1.0 - q1) * (1.0 - q2) * acc


def _fetch_gate(state: SparseState, salt: int, i, j, cand_key, p_fetch):
    """ALIVE-rank candidates gated on the metadata-fetch round trip
    (``MembershipProtocolImpl.java:636-658``) — same stateless hash draw as
    the dense kernel so loss semantics match across modes."""
    needs = (cand_key & 3) == RANK_ALIVE
    u = fetch_uniform(state.tick, salt, i, j)
    ok = state.up[j] & (u < p_fetch)
    return ~needs | ok


def _sample_rejection(
    state: SparseState, rows, u, n_picks: int, tries: int, extra_mask=None
):
    """Per-row ``n_picks`` distinct draws from the live view by bounded
    rejection: each pick takes the first of ``tries`` uniform column draws
    that is not self, not DEAD/unknown in the row's view (rank != 3 — the
    -1 unknown key also reads rank 3), optionally allowed by ``extra_mask``
    [N]-indexed (the SYNC seed pool), and distinct from earlier picks.

    Returns (idx [N, n_picks] clamped, valid [N, n_picks]). Deviation 4:
    a pick can come up empty with prob (1-live_frac)^tries.
    """
    n = state.capacity
    # ALL try-columns materialized and validated in ONE [R, P·T] gather (the
    # sampled state is the pre-phase table, constant across tries — per-try
    # point-gathers measured ~10x slower as separate kernels)
    cols = jnp.minimum((u * np.float32(n)).astype(jnp.int32), n - 1)  # [R, P*T]
    live = (state.view_key[rows[:, None], cols] & 3) != RANK_DEAD
    if extra_mask is not None:
        live = live | extra_mask[cols]
    ok_base = (cols != rows[:, None]) & live
    picks = []
    for p in range(n_picks):
        sel = jnp.full(rows.shape, -1, jnp.int32)
        for t in range(tries):
            c = cols[:, p * tries + t]
            ok = ok_base[:, p * tries + t]
            for q in picks:
                ok = ok & (c != q)  # q == -1 never collides
            sel = jnp.where((sel < 0) & ok, c, sel)
        picks.append(sel)
    idx = jnp.stack(picks, 1)
    return jnp.maximum(idx, 0), idx >= 0


def _chunk(total: int, requested: int, threshold: int, auto_block: int, word: int = 1) -> int:
    """Resolve a working-set block size (see SparseParams.apply_block).

    ``requested`` (non-zero) is validated and used as-is; auto (0) keeps the
    whole plane when ``total <= threshold`` (test/small-N sizes pay zero
    loop overhead) and otherwise picks the largest power-of-two divisor of
    ``total`` that is ≤ ``auto_block`` and a multiple of ``word``. Falls
    back to unchunked when no such divisor exists (odd sizes)."""
    if requested:
        if requested < 0 or total % requested or requested % word:
            raise ValueError(
                f"block {requested} must be positive, divide {total}, and be "
                f"a multiple of {word}"
            )
        return requested
    if total <= threshold:
        return total
    b = auto_block
    # floor at auto_block/16: a degenerate tiny divisor (e.g. 2 for
    # total=16386) would trade the temp win for thousands of sequential
    # loop steps — past the floor, unchunked is the better program
    while b >= max(2 * word, auto_block // 16):
        if total % b == 0:
            return b
        b //= 2
    return total


# Packing helpers moved to ops/bitplane.py (r9): ONE packing spelling in
# the repo, shared with the dense engine's packed planes. The local names
# stay so the sparse word builders read as before.
from .bitplane import pack_bits as _pack_bits, unpack_bits as _unpack_bits


# ---------------------------------------------------------------------------
# phases
# ---------------------------------------------------------------------------


def _fd_phase(state: SparseState, r, params: SparseParams, trace: bool = False,
              ad=None):
    """Vectorized FD round (``FailureDetectorImpl`` semantics, as the dense
    kernel's ``_fd_phase``) with rejection-sampled target/relay selection.
    Returns (state, proposals, metrics)."""
    n = state.capacity
    rows = jnp.arange(n)
    sel, valid = _sample_rejection(
        state, rows, r.fd_try, 1 + params.ping_req_k, params.sample_tries
    )
    tgt = sel[:, 0]
    has_tgt = valid[:, 0] & state.up

    p_direct = _rt_at(state, rows, tgt)
    if params.delay_slots:
        if ad is not None:
            # Lifeguard LHA (r14, AD-4): the prober's own direct timeout
            # stretches to t_base * (1 + lh_i)
            p_direct = p_direct * _adp.scaled_timely_rt(
                _delay_q_at(state, rows, tgt),
                _delay_q_at(state, tgt, rows),
                params.fd_direct_timeout_ticks,
                ad.lh,
                params.adaptive.lh_max,
            )
        else:
            p_direct = p_direct * _timely_rt(
                _delay_q_at(state, rows, tgt),
                _delay_q_at(state, tgt, rows),
                params.fd_direct_timeout_ticks,
            )
    direct_ok = has_tgt & state.up[tgt] & (r.fd_direct < p_direct)

    relays = sel[:, 1:]
    relay_valid = valid[:, 1:]
    tgt_b = tgt[:, None]
    p_relay = _rt_at(state, rows[:, None], relays) * _rt_at(state, relays, tgt_b)
    if params.delay_slots:
        p_relay = p_relay * _timely_rt(
            _delay_q_at(state, rows[:, None], relays),
            _delay_q_at(state, relays, rows[:, None]),
            params.fd_leg_timeout_ticks,
        )
        p_relay = p_relay * _timely_rt(
            _delay_q_at(state, relays, tgt_b),
            _delay_q_at(state, tgt_b, relays),
            params.fd_leg_timeout_ticks,
        )
    relay_ok = relay_valid & state.up[relays] & state.up[tgt_b] & (r.fd_relay < p_relay)
    ack = direct_ok | relay_ok.any(axis=1)

    own_key = state.view_key[rows, tgt]
    alive_key = (state.view_key[tgt, tgt] >> 2) << 2
    suspect_key = ((own_key >> 2) << 2) | RANK_SUSPECT
    cand = jnp.where(ack, alive_key, suspect_key)
    accept = has_tgt & (cand > own_key)
    # verdict throttle: first V accepting rows write this round, the rest
    # retry next FD round (see SparseParams.fd_accept_slots)
    V = min(n, params.fd_accept_slots or max(64, n // 16))
    eff = accept & (jnp.cumsum(accept.astype(jnp.int32)) - 1 < V)

    def _write(st: SparseState) -> SparseState:
        # one-hot elementwise write (j == tgt[i]), NOT a point scatter: any
        # scatter into the [N, N] table forces a whole-matrix layout copy on
        # TPU, while this fuses into one aliased in-place pass. The V-slot
        # throttle (eff) is kept purely for protocol semantics (bounded
        # verdict writes per round, mirrored by the oracle).
        return st.replace(
            view_key=jnp.where(
                eff[:, None] & (rows[None, :] == tgt[:, None]),
                cand[:, None],
                st.view_key,
            )
        )

    st = jax.lax.cond(eff.any(), _write, lambda s: s, state)
    # suspicion-episode registration (deviation 1)
    sus_cand = (
        jnp.full((n,), NO_CANDIDATE, jnp.int32)
        .at[tgt]
        .max(jnp.where(eff & ~ack, cand, NO_CANDIDATE))
    )
    new_sus = jnp.maximum(st.sus_key, sus_cand)
    st = st.replace(
        sus_key=new_sus,
        sus_since=jnp.where(new_sus > st.sus_key, st.tick, st.sus_since),
    )
    # FD verdicts flip between non-DEAD ranks only (targets come from the
    # live view; ALIVE/SUSPECT are both live) — n_live is unchanged.
    proposals = (tgt, cand, rows, eff)
    metrics = {
        "fd_probes": has_tgt.sum(),
        "fd_failed_probes": (has_tgt & ~ack).sum(),
        "fd_new_suspects": (eff & ~ack).sum(),
    }
    if ad is not None:
        # adaptive evidence exports (r14): miss/succ feed lh un-throttled;
        # confirmations count only WRITTEN suspect verdicts (eff)
        sus_w = eff & ~ack
        metrics["_ad_miss"] = has_tgt & ~ack
        metrics["_ad_succ"] = has_tgt & ack
        metrics["_ad_cnt"] = (
            jnp.zeros((n,), jnp.int32).at[tgt].add(sus_w.astype(jnp.int32))
        )
        metrics["_ad_key"] = (
            jnp.full((n,), NO_CANDIDATE, jnp.int32)
            .at[tgt]
            .max(jnp.where(sus_w, cand, NO_CANDIDATE))
        )
    if trace:
        # trace-plane export (r10, same contract as kernel._fd_phase):
        # already-computed probe internals — zero effect on the state math
        metrics["trace_fd"] = {
            "tgt": tgt.astype(jnp.int32),
            "has_tgt": has_tgt,
            "ack": ack,
            "direct_ok": direct_ok,
            "suspect": eff & ~ack,
            "relays": relays.astype(jnp.int32),
            "relay_valid": relay_valid,
            "relay_ok": relay_ok,
        }
    return st, proposals, metrics


def _suspicion_sweep(state: SparseState, params: SparseParams, trace=None,
                     ad=None):
    """Dense expiry pass, every ``sweep_every`` ticks: SUSPECT cells whose
    subject's episode stamp is older than the observer's suspicion timeout
    become DEAD at the same incarnation (rank +1). O(N²/B) amortized.
    Returns (state, proposals) — plus the tracers' expiry export when
    ``trace`` (a TraceSpec) is set (r10; read off the sweep branch's own
    ``expired`` temp, see ``trace.capture.expiry_trace``).

    ``ad`` (r14) swaps the static timeout for the confirmation-scaled,
    observer-health-scaled window (see ``kernel._suspicion_phase``)."""
    n = state.capacity
    rows = jnp.arange(n)
    no_props = (
        jnp.zeros((n,), jnp.int32),
        jnp.zeros((n,), jnp.int32),
        rows,
        jnp.zeros((n,), bool),
    )

    def _sweep(st: SparseState):
        if ad is not None:
            aspec = params.adaptive
            L = aspec.levels
            base = ceil_log2(st.n_live) * params.fd_every  # [N]
            num_conf = _adp.conf_mult_num(aspec, ad.conf)  # [N]
            in_ep = st.view_key <= ad.conf_key[None, :]
            num = jnp.where(
                in_ep, num_conf[None, :], jnp.int32(aspec.max_mult * L)
            )
            factor = base * (1 + ad.lh)  # [N] — AD-3 observer scaling
            timeout2 = (factor[:, None] * num) // jnp.int32(L)  # [N, N]
            overdue = (st.tick - st.sus_since)[None, :] >= timeout2
        else:
            timeout = (
                params.suspicion_mult * ceil_log2(st.n_live) * params.fd_every
            )
            overdue = (st.tick - st.sus_since)[None, :] >= timeout[:, None]
        suspect = (st.view_key & 3) == RANK_SUSPECT
        expired = (
            suspect
            & st.up[:, None]
            & overdue
            & (st.view_key <= st.sus_key[None, :])
        )
        new_key = jnp.where(expired, st.view_key + 1, st.view_key)
        n_live = st.n_live - expired.sum(axis=1).astype(jnp.int32)
        # episode reset: when NO up observer holds any SUSPECT cell after
        # this sweep, all episodes are over — clearing the stamps re-arms
        # the has_suspects skip gate (otherwise one transient suspicion
        # would leave the O(N²) scan running every sweep_every forever)
        any_suspect_left = (
            ((new_key & 3) == RANK_SUSPECT) & st.up[:, None]
        ).any()
        sus_key = jnp.where(any_suspect_left, st.sus_key, NO_CANDIDATE)
        sus_since = jnp.where(any_suspect_left, st.sus_since, NEVER)
        # announce each expiring SUBJECT once: the first (lowest) expiring
        # row is the elected announcer (deviation 3) — without the election,
        # every observer proposes the same DEAD fact and floods the
        # allocation compaction window on mass-expiry sweeps
        first_row = jnp.argmax(expired, axis=0)  # [N] per subject
        mine = expired & (first_row[None, :] == rows[:, None])
        any_exp = mine.any(axis=1)
        col = jnp.argmax(mine, axis=1).astype(jnp.int32)
        key = new_key[rows, col]
        out = (
            st.replace(
                view_key=new_key, n_live=n_live, sus_key=sus_key,
                sus_since=sus_since,
            ),
            (col, key, rows, any_exp),
        )
        if trace is not None:
            from ..trace import capture as _tc

            return out + (_tc.expiry_trace(expired, trace),)
        return out

    def _skip(st: SparseState):
        if trace is not None:
            from ..trace import capture as _tc

            return st, no_props, _tc.zero_sus_trace(trace)
        return st, no_props

    # cheap gate: no registered episode young enough to matter -> skip scan
    has_suspects = (state.sus_since > NEVER).any()
    on_tick = (state.tick % params.sweep_every) == 0
    return jax.lax.cond(on_tick & has_suspects, _sweep, _skip, state)


def _gossip_phase(state: SparseState, r, params: SparseParams,
                  adaptive: bool = False, fused: bool = False):
    """Infection-style dissemination of user rumors ([N, R], full fidelity)
    and membership rumors ([N, M], origin-filter — deviation 2). One message
    per (sender, peer) edge carries both payloads, as the reference's single
    GOSSIP_REQ does. Quiescent clusters (no active rumor, nothing pending)
    skip the whole phase.

    ``fused`` (r17): additionally returns the sweep's early-free coverage
    vector ([M] bool) computed from THIS phase's post-apply pool planes —
    the gossip→sweep hand-off. Valid because nothing between gossip and
    the sweeps writes ``minf_age``/``up``/``joined_at``/``mr_created``
    (sync/refute touch only ``n_live`` and the view planes), and the
    sweep's [N, M] gate (``mr_active.any()``) implies this phase's
    ``mr_any`` gate — whenever the sweep would need coverage, the fused
    hand-off computed it under the live branch."""
    n = state.capacity
    m = params.mr_slots
    rows = jnp.arange(n)
    D = params.delay_slots

    work = state.rumor_active.any() | state.mr_active.any()
    if D:
        slot_now = state.tick % D
        work = (
            work
            | state.pending_inf[slot_now].any()
            | state.pending_minf[slot_now].any()
        )

    def _deliver(state: SparseState):
        mr_any = state.mr_active.any()
        if D:
            mr_any = mr_any | state.pending_minf[slot_now].any()
        spread = params.repeat_mult * ceil_log2(state.n_live)  # [N]
        young_u = (
            state.infected
            & state.rumor_active[None, :]
            & (state.tick - state.infected_at < spread[:, None])
        )
        # dissemination strategy seam (r13): pipelined budget window over
        # the USER-rumor payload (DZ-3; the default spec is a no-op)
        spec = params.dissem
        bmask = _dz.rumor_budget_mask(spec, young_u.shape[1], state.tick)
        if bmask is not None:
            young_u = young_u & bmask[None, :]

        # ALL [N, M] work is gated on the pool being non-empty: a pure
        # user-rumor dissemination (or any membership-quiet stretch) skips
        # the age pass, young window, packing, apply, and sweep entirely.
        def _mr_pre(st: SparseState):
            age = st.minf_age
            age = jnp.where(
                age > 0, jnp.minimum(age, jnp.uint8(254)) + jnp.uint8(1), age
            )
            # age = tick - infection_tick + 1 after this tick's increment, so
            # age <= spread  <=>  tick - infection_tick < spread — exactly
            # the dense kernel's (and the reference's) forwarding window
            young_m = (
                (age > 0)
                & st.mr_active[None, :]
                & (age.astype(jnp.int32) <= spread[:, None])
            )
            return age, _pack_bits(young_m)

        def _mr_pre_skip(st: SparseState):
            return st.minf_age, jnp.zeros(
                (n, (m + 31) // 32), jnp.uint32
            )

        age, ym_p = jax.lax.cond(mr_any, _mr_pre, _mr_pre_skip, state)
        state = state.replace(minf_age=age)
        if spec.uniform_selection:
            peers, peer_valid = _sample_rejection(
                state, rows, r.gossip_try, params.fanout, params.sample_tries
            )
        else:
            # structured topology / deterministic schedule (DZ-1): closed-
            # form circulant targets; the random strategies consume the
            # first try column of each pick's rejection block
            peers, peer_valid = _dz.structured_peers(
                spec, n, state.tick,
                _dz.try_stride_uniforms(r.gossip_try, params.sample_tries),
            )

        # ONE combined per-sender payload row [packed-M | packed-R | from]:
        # row-gathers cost per ROW on TPU (~independent of row width), so the
        # three per-slot payload lookups collapse into a single gather
        yu_p = _pack_bits(young_u)  # [N, Wu] u32
        Wm, Wu = ym_p.shape[1], yu_p.shape[1]
        payload = jnp.concatenate(
            [ym_p, yu_p, state.infected_from.astype(jnp.uint32)], axis=1
        )
        if D:
            recv_u = state.pending_inf[slot_now]
            recv_src = state.pending_src[slot_now]
            recv_m_p = _pack_bits(state.pending_minf[slot_now])
            pend_u = state.pending_inf
            pend_src = state.pending_src
            pend_m = state.pending_minf
        else:
            recv_u = jnp.zeros_like(state.infected)
            recv_src = jnp.full_like(state.infected_from, -1)
            recv_m_p = jnp.zeros_like(ym_p)

        # Delivery is RECEIVER-pulled through per-slot inverse sender
        # indexes: one [N] point scatter builds inv_s (the sender that
        # reached each receiver in fanout slot s), then row GATHERS pull the
        # payloads — measured ~2x the throughput of scattering [N, ·] payload
        # planes by receiver. Two deliberate consequences (deviation 6):
        # (a) when several senders pick the same receiver in the SAME slot,
        # only the highest-row sender's message lands (the others retry
        # while their forwarding window lasts — statistically a second-order
        # extra-loss term, ~fanout/N per edge); (b) the known-infected /
        # origin filters apply receiver-side (a filtered receiver is already
        # infected, so state evolution is unchanged; rumor_sent tallies
        # deliveries AFTER those filters and slot-collision drops — a lower
        # bound on wire sends, see deviation 6 in the module docstring).
        sender_has = young_u.any(axis=1) | (ym_p != 0).any(axis=1)
        # ALL fanout slots batched into [F, N] tensors — TPU executes
        # kernels serially, so three sequential per-slot accumulate chains
        # cost three sets of launch overheads; one stacked chain + a final
        # OR/max-reduce costs one.
        F = params.fanout
        R = state.infected.shape[1]
        p_all = peers.T  # [F, N]
        rows_b = jnp.broadcast_to(rows, (F, n))
        ok_all = (
            peer_valid.T
            & sender_has[None, :]
            & state.up[None, :]
            & state.up[p_all]
            & (r.gossip_edge.T < (1.0 - _loss_at(state, rows_b, p_all)))
        )
        sent = ok_all.sum()
        if D:
            qd = _delay_q_at(state, rows_b, p_all)
            d_all = jnp.zeros((F, n), jnp.int32)
            qpow = qd
            for _ in range(1, D):
                d_all = d_all + (r.gossip_delay.T < qpow)
                qpow = qpow * qd
            ok_now_all = ok_all & (d_all == 0)
        else:
            ok_now_all = ok_all
        inv = (
            jnp.full((F, n), -1, jnp.int32)
            .at[jnp.arange(F)[:, None], p_all]
            .max(jnp.where(ok_now_all, rows[None, :], -1))
        )
        j_all = jnp.maximum(inv, 0)  # [F, N]
        has_all = (inv >= 0)[:, :, None]
        pl_all = payload[j_all]  # [F, N, Wm+Wu+R] — ONE gather
        yu_all = _unpack_bits(pl_all[:, :, Wm : Wm + Wu], R)
        from_all = pl_all[:, :, Wm + Wu :].astype(jnp.int32)
        deliver_u_all = (
            yu_all
            & has_all
            & (from_all != rows[None, :, None])
            & (state.rumor_origin[None, None, :] != rows[None, :, None])
        )
        recv_u = recv_u | deliver_u_all.any(axis=0)
        recv_src = jnp.maximum(
            recv_src,
            jnp.where(deliver_u_all, j_all[:, :, None], -1).max(axis=0),
        )
        import functools as _ft

        recv_m_p = _ft.reduce(
            jnp.bitwise_or,
            [jnp.where(has_all[s], pl_all[s, :, :Wm], jnp.uint32(0)) for s in range(F)],
            recv_m_p,
        )
        rumor_sent = deliver_u_all.sum()
        if spec.wants_pull:
            # push-pull reply (DZ-2): every sender whose undelayed contact
            # landed pulls the peer's payload back over the same round
            # trip — a per-slot row gather (each sender has exactly one
            # target per slot, so no inverse index is needed), gated on
            # one hashed reverse-link draw per contact
            for s in range(F):
                p_s = p_all[s]
                rev_u = fetch_uniform(state.tick, _dz.pull_salt(s), rows, p_s)
                rev_ok = ok_now_all[s] & (
                    rev_u < (1.0 - _loss_at(state, p_s, rows))
                )
                pl_rev = payload[p_s]
                yu_rev = _unpack_bits(pl_rev[:, Wm : Wm + Wu], R)
                from_rev = pl_rev[:, Wm + Wu :].astype(jnp.int32)
                reply_u = (
                    yu_rev
                    & rev_ok[:, None]
                    & (from_rev != rows[:, None])
                    & (state.rumor_origin[None, :] != rows[:, None])
                )
                recv_u = recv_u | reply_u
                recv_src = jnp.maximum(
                    recv_src, jnp.where(reply_u, p_s[:, None], -1)
                )
                recv_m_p = recv_m_p | jnp.where(
                    rev_ok[:, None], pl_rev[:, :Wm], jnp.uint32(0)
                )
                sent = sent + rev_ok.sum()
                rumor_sent = rumor_sent + reply_u.sum()
        if D:
            # late deliveries stay per-slot (delay runs are small-N
            # fidelity configurations; the rings force per-slot scatters)
            no_sender = jnp.full((n,), -1, jnp.int32)
            for s in range(F):
                ok_late = ok_all[s] & (d_all[s] > 0)
                inv_l = no_sender.at[p_all[s]].max(jnp.where(ok_late, rows, -1))
                jl = jnp.maximum(inv_l, 0)
                hasl = (inv_l >= 0)[:, None]
                pll = payload[jl]
                young_u_l = _unpack_bits(pll[:, Wm : Wm + Wu], R)
                lfrom = pll[:, Wm + Wu :].astype(jnp.int32)
                slot_d = (state.tick + d_all[s][jl]) % D
                late_u = (
                    young_u_l
                    & hasl
                    & (lfrom != rows[:, None])
                    & (state.rumor_origin[None, :] != rows[:, None])
                )
                pend_u = pend_u.at[slot_d, rows].max(late_u)
                pend_src = pend_src.at[slot_d, rows].max(
                    jnp.where(late_u, jl[:, None], -1)
                )
                pend_m = pend_m.at[slot_d, rows].max(
                    _unpack_bits(pll[:, :Wm], m)
                    & hasl
                    & (state.mr_origin[None, :] != rows[:, None])
                )

        # user-rumor infection (bitmap OR = SequenceIdCollector dedup)
        newly_u = recv_u & ~state.infected & state.up[:, None] & state.rumor_active[None, :]
        state = state.replace(
            infected=state.infected | newly_u,
            infected_at=jnp.where(newly_u, state.tick, state.infected_at),
            infected_from=jnp.where(newly_u, recv_src, state.infected_from),
        )

        # membership-rumor infection + one-shot record application — all
        # [N, M] work gated on the pool being non-empty (mr_any).
        #
        # SCATTER-FREE at scale (round 4): on this TPU backend ANY point or
        # column scatter into the donated [N, N] view matrix forces XLA to
        # copy the whole matrix into a column-major layout (9 GB/tick at
        # N=49k — the true cause of the r3 single-chip ceiling). The apply
        # therefore goes dense-but-elementwise: the slot-space `newly` plane
        # is scattered ROW-wise into a TRANSPOSED [subject, observer] bool
        # bitmap (row scatters don't relayout), and the view update runs in
        # contiguous column blocks of dynamic_slice → elementwise merge →
        # dynamic_update_slice, which XLA aliases fully in place. Blocks are
        # bit-exact with the old slot-space formulation: the accept gate,
        # fetch draws, delta, and episode registration are the identical
        # per-cell expressions, just evaluated at (observer, subject)
        # instead of (observer, slot).
        def _mr_apply(state: SparseState):
            recv_m = _unpack_bits(recv_m_p, m) & (
                state.mr_origin[None, :] != rows[:, None]
            )
            newly = (
                recv_m
                & (state.minf_age == 0)
                & state.up[:, None]
                & state.mr_active[None, :]
            )
            minf = jnp.where(newly, jnp.uint8(1), state.minf_age)
            # subject-dense staging, PACKED along observers: row scatters on
            # this backend are bytes-bound (~measured 9x cheaper for packed
            # u32 rows than full bool rows), so the [subject, observer]
            # bitmap is built as [N, ceil(N/32)] u32 words; pool invariant
            # (unique subjects among active slots) makes the scatter
            # collision-free; inactive slots go out of bounds and drop
            subj_rows = jnp.where(state.mr_active, state.mr_subject, n)
            Wo = (n + 31) // 32
            nd_T_p = (
                jnp.zeros((n, Wo), jnp.uint32)
                .at[subj_rows]
                .max(_pack_bits(newly.T), mode="drop")
            )  # [subject, packed observers]
            # Sharded placement (r5, VERDICT r4 item 3): WORD-sharded, not
            # subject-sharded. Each device needs ALL subjects' bits for ITS
            # observers — with the default subject-row sharding, every
            # column-block dynamic_slice below all-gathers its subject
            # range (298 all-gathers/tick in the r4 census). Word-sharding
            # aligns with the observer row shards (rows/device is a
            # multiple of 32 at every real mesh size), the packing of
            # newly.T is word-local, and the subject-row scatter writes
            # each device's own word columns — the whole staging and the
            # block walk become collective-free.
            nd_T_p = _constrain(nd_T_p, None, "member")
            cand_j = (
                jnp.full((n,), NO_CANDIDATE, jnp.int32)
                .at[subj_rows]
                .max(jnp.where(state.mr_active, state.mr_key, NO_CANDIDATE), mode="drop")
            )
            # replicated: built from replicated pool vectors, read by every
            # device's block walk
            cand_j = _constrain(cand_j, None)
            bit_idx = jnp.arange(32, dtype=jnp.uint32)

            NB = _chunk(n, params.apply_block, 8192, 2048)
            nb = n // NB

            # rank-3 variant for the flagship shape (n % 32 == 0, no
            # namespace gate): own reshapes [N, NB] -> [Wo, 32, NB] as a
            # free row-major bitcast and the bit expansion never reshapes
            # at all — measured ~9% faster than the rank-2 expansion. The
            # two paths compute identical cells (lockstep-verified).
            rank3 = n % 32 == 0 and not params.namespace_gate

            def _block(b, carry):
                if adaptive:
                    vk, ndT, cj, dacc, sus, cnt, adcnt = carry
                else:
                    vk, ndT, cj, dacc, sus, cnt = carry
                c0 = b * NB
                cols = c0 + jnp.arange(NB, dtype=jnp.int32)
                # [NB, Wo] packed words -> small transpose -> bit expansion
                # along the (major) observer axis; the explicit transpose is
                # the layout boundary that keeps the expansion's layout
                # preference away from the vk carry (see r4 design notes)
                pbT = jax.lax.dynamic_slice(ndT, (c0, 0), (NB, Wo)).T  # [Wo, NB]
                cand = jax.lax.dynamic_slice(cj, (c0,), (NB,))
                own = jax.lax.dynamic_slice(vk, (0, c0), (n, NB))
                up_cols = jax.lax.dynamic_slice(state.up, (c0,), (NB,))
                p_fetch = (
                    state.fetch_rt
                    if state.fetch_rt.ndim == 0
                    else jax.lax.dynamic_slice(state.fetch_rt, (0, c0), (n, NB))
                )
                if rank3:
                    nd = ((pbT[:, None, :] >> bit_idx[None, :, None]) & 1).astype(
                        bool
                    )  # [Wo, 32, NB] — no reshape
                    cand_b = cand[None, None, :]
                    own_b = own.reshape(Wo, 32, NB)  # free bitcast
                    i_obs = rows.reshape(Wo, 32, 1)
                    j_sub = cols[None, None, :]
                    up_b = up_cols[None, None, :]
                    pf = p_fetch if p_fetch.ndim == 0 else p_fetch.reshape(Wo, 32, NB)
                else:
                    nd = (
                        ((pbT[:, None, :] >> bit_idx[None, :, None]) & 1)
                        .astype(bool)
                        .reshape(Wo * 32, NB)[:n]
                    )  # [N, NB]
                    cand_b = cand[None, :]
                    own_b = own
                    i_obs = rows[:, None]
                    j_sub = cols[None, :]
                    up_b = up_cols[None, :]
                    pf = p_fetch
                needs = (cand_b & 3) == RANK_ALIVE
                u = fetch_uniform(state.tick, SALT_GOSSIP, i_obs, j_sub)
                fetch_ok = ~needs | (up_b & (u < pf))
                accept = (
                    nd
                    & (cand_b > own_b)
                    & ((own_b >= 0) | ((cand_b & 3) <= RANK_LEAVING))
                    & fetch_ok
                )
                if params.namespace_gate:
                    ns_cols = jax.lax.dynamic_slice(state.ns_id, (c0,), (NB,))
                    accept = accept & state.ns_rel[
                        state.ns_id[:, None], ns_cols[None, :]
                    ]
                new_own = jnp.where(accept, cand_b, own_b)
                delta = (
                    ((new_own & 3) != RANK_DEAD).astype(jnp.int32)
                    - ((own_b & 3) != RANK_DEAD).astype(jnp.int32)
                )
                sus_b = jnp.where(
                    accept & ((cand_b & 3) == RANK_SUSPECT), cand_b, NO_CANDIDATE
                )
                if rank3:
                    vk = jax.lax.dynamic_update_slice(
                        vk, new_own.reshape(n, NB), (0, c0)
                    )
                    dacc = dacc + delta.sum(axis=2).reshape(n)
                    sus_col = sus_b.max(axis=(0, 1))
                else:
                    vk = jax.lax.dynamic_update_slice(vk, new_own, (0, c0))
                    dacc = dacc + delta.sum(axis=1)
                    sus_col = sus_b.max(axis=0)
                cnt = cnt + accept.sum()
                # episode registration for accepted SUSPECT records
                sus = jax.lax.dynamic_update_slice(sus, sus_col, (c0,))
                if adaptive:
                    # r14 confirmation counting: accepted SUSPECT records
                    # per subject column (AD-1)
                    acc_sus = accept & ((cand_b & 3) == RANK_SUSPECT)
                    if rank3:
                        adcnt_col = acc_sus.astype(jnp.int32).sum(axis=(0, 1))
                    else:
                        adcnt_col = acc_sus.astype(jnp.int32).sum(axis=0)
                    adcnt = jax.lax.dynamic_update_slice(
                        adcnt, adcnt_col, (c0,)
                    )
                    return vk, ndT, cj, dacc, sus, cnt, adcnt
                return vk, ndT, cj, dacc, sus, cnt

            # nd_T and cand_j ride the carry DELIBERATELY (not closed over):
            # this is part of the measured layout recipe — the loop variant
            # that achieved zero view-matrix copies threaded them, and
            # loop-invariant operands reaching the body other ways re-poison
            # layout assignment (see the r4 design notes above).
            carry0 = (
                state.view_key,
                nd_T_p,
                cand_j,
                jnp.zeros((n,), jnp.int32),
                jnp.full((n,), NO_CANDIDATE, jnp.int32),
                jnp.int32(0),
            )
            if adaptive:
                carry0 = carry0 + (jnp.zeros((n,), jnp.int32),)
            if nb == 1:
                carry = _block(0, carry0)
            else:
                carry = jax.lax.fori_loop(0, nb, _block, carry0)
            if adaptive:
                vk, _ndT, _cj, delta, sus_cand, acc_cnt, ad_cnt = carry
            else:
                vk, _ndT, _cj, delta, sus_cand, acc_cnt = carry
            new_sus = jnp.maximum(state.sus_key, sus_cand)
            state = state.replace(
                view_key=vk,
                minf_age=minf,
                n_live=state.n_live + delta,
                sus_key=new_sus,
                sus_since=jnp.where(
                    new_sus > state.sus_key, state.tick, state.sus_since
                ),
            )
            if adaptive:
                # sus_cand IS the per-subject max accepted SUSPECT key —
                # the r14 episode-key contribution (AD-1)
                return state, newly.sum(), acc_cnt, ad_cnt, sus_cand
            return state, newly.sum(), acc_cnt

        if adaptive:
            def _mr_skip(st: SparseState):
                return (
                    st, jnp.int32(0), jnp.int32(0),
                    jnp.zeros((n,), jnp.int32),
                    jnp.full((n,), NO_CANDIDATE, jnp.int32),
                )

            state, n_mr_deliveries, n_mr_accepts, g_ad_cnt, g_ad_key = (
                jax.lax.cond(mr_any, _mr_apply, _mr_skip, state)
            )
        else:
            state, n_mr_deliveries, n_mr_accepts = jax.lax.cond(
                mr_any, _mr_apply, lambda st: (st, jnp.int32(0), jnp.int32(0)),
                state,
            )
        if D:
            state = state.replace(
                pending_inf=pend_u.at[slot_now].set(False),
                pending_src=pend_src.at[slot_now].set(-1),
                pending_minf=pend_m.at[slot_now].set(False),
            )
        mets = {
            "gossip_msgs": sent,
            "rumor_sends": rumor_sent,
            "rumor_deliveries": newly_u.sum(),
            "mr_deliveries": n_mr_deliveries,
            "mr_accepts": n_mr_accepts,
        }
        if adaptive:
            mets["_ad_cnt"] = g_ad_cnt
            mets["_ad_key"] = g_ad_key
        if fused:
            if params.early_free:
                covered = jax.lax.cond(
                    mr_any,
                    lambda st: (
                        (st.minf_age > 0)
                        | ~st.up[:, None]
                        | (st.joined_at[:, None] > st.mr_created[None, :])
                    ).all(axis=0),
                    lambda st: jnp.zeros((m,), bool),
                    state,
                )
            else:
                covered = jnp.zeros((m,), bool)
            return state, mets, covered
        return state, mets

    def _quiet(state: SparseState):
        mets = {
            "gossip_msgs": jnp.int32(0),
            "rumor_sends": jnp.int32(0),
            "rumor_deliveries": jnp.int32(0),
            "mr_deliveries": jnp.int32(0),
            "mr_accepts": jnp.int32(0),
        }
        if adaptive:
            mets["_ad_cnt"] = jnp.zeros((n,), jnp.int32)
            mets["_ad_key"] = jnp.full((n,), NO_CANDIDATE, jnp.int32)
        if fused:
            # work==False implies mr_active is all-false, so the sweep's
            # [N, M] branch (the only coverage consumer) is skipped too
            return state, mets, jnp.zeros((m,), bool)
        return state, mets

    return jax.lax.cond(work, _deliver, _quiet, state)


def _sync_phase(state: SparseState, r, params: SparseParams, trace: bool = False,
                adaptive: bool = False):
    """Anti-entropy full-table exchange — the dense kernel's compacted-K
    design (O(K·N)), minus ``changed_at``, plus liveness-delta upkeep,
    episode registration, and capped re-gossip proposals (deviation 3;
    the reference re-gossips EVERY sync-accepted record,
    ``spreadMembershipGossipUnlessGossiped:836-843``)."""
    n = state.capacity
    rows = jnp.arange(n)
    P = params.sync_announce
    K = min(n, params.sync_slots or (n // params.sync_every + 32))
    due_p = ((state.tick + rows * params.sync_stagger) % params.sync_every) == 0
    due_f = state.force_sync & state.up
    due_p = due_p & state.up & ~due_f
    # FORCE-SYNC callers take compaction slots BEFORE periodic ones (r5).
    # The reference's join IS an immediate sync (MembershipProtocolImpl
    # .start -> doInitialSync); with a single ascending-row compaction, a
    # churn burst's high-row joiners queued behind ~N/sync_every periodic
    # callers for tens of ticks — past their announce-rumor forwarding
    # window (spread is sized by their seeds-only view), which killed their
    # identity dissemination outright (the r4/r5 deaf-joiner collapse at
    # 49k). Displaced periodic callers just miss one period — benign
    # anti-entropy redundancy, and the overflow behavior the K cap already
    # had. Layout: force callers ascending, then periodic ascending.
    (cf,) = jnp.nonzero(due_f, size=K, fill_value=n)
    nf = (cf < n).sum()
    (cp,) = jnp.nonzero(due_p, size=K, fill_value=n)
    caller = cf.at[jnp.arange(K) + nf].set(cp, mode="drop")
    valid_c = caller < n
    caller = jnp.minimum(caller, n - 1)
    # replicate the K-staging at its SOURCE: every [K]-indexed vector below
    # derives from `caller`, and without the constraint GSPMD re-gathers
    # each one independently (~40 small all-gathers/tick in the op-def
    # census — the largest collective class in the sharded program)
    caller = _constrain(caller, None)
    valid_c = _constrain(valid_c, None)

    if params.seed_rows:
        seed_mask = jnp.zeros((n,), bool).at[jnp.asarray(params.seed_rows)].set(True)
    else:
        seed_mask = None
    peer_idx, peer_valid = _sample_rejection(
        state, caller, r.sync_try[caller], 1, params.sample_tries, extra_mask=seed_mask
    )
    peer = _constrain(peer_idx[:, 0], None)
    valid_pick = _constrain(peer_valid[:, 0], None)
    if params.seed_rows:
        # Seed fallback: a caller whose live view is too sparse for rejection
        # sampling (a fresh joiner knows only the seeds — ~S/N hit rate)
        # draws a configured seed directly. This is the reference's own
        # bootstrap bias: selectSyncAddress draws from seedMembers ∪ members
        # (MembershipProtocolImpl.java:461-472), and a joiner's member list
        # IS the seed list. Without it, bootstrap SYNC stalls ~N/(S·T) ticks.
        seeds_arr = jnp.asarray(params.seed_rows, jnp.int32)
        S = len(params.seed_rows)
        fb = seeds_arr[
            jnp.minimum((r.sync_fb[caller] * np.float32(S)).astype(jnp.int32), S - 1)
        ]
        use_fb = ~valid_pick & (fb != caller)
        peer = jnp.where(use_fb, fb, peer)
        valid_pick = valid_pick | use_fb
    p_rt = _rt_at(state, caller, peer)
    if params.delay_slots:
        p_rt = p_rt * _timely_rt(
            _delay_q_at(state, caller, peer),
            _delay_q_at(state, peer, caller),
            params.sync_timeout_ticks,
        )
    ok = _constrain(
        valid_c & valid_pick & state.up[peer] & (r.sync_edge[caller] < p_rt),
        None,
    )

    # NO-REGATHER staging (round 4): the tick must never row-gather from a
    # big buffer it just scattered into — XLA's mini-gather lowering stages
    # the whole [N, N] operand in halves (a full-matrix copy per tick; with
    # the old apply scatter's layout copy, the true cause of the r3
    # single-chip ceiling and of SYNC's 40 ms/tick at 36k). Both gathers
    # here read the PRISTINE pre-sync carry; the ACK phase never re-gathers:
    # a peer row after the request merge IS new_p (duplicate slots write
    # identical rows), and a caller row after the request merge is
    # max(caller_table, new_p of the dup-group whose peer equals the caller)
    # — reconstructed from a [K, K] match instead of a gather.
    #
    # Merge slots sharing a peer COMPACTLY ([K, K] + [K, N] scratch):
    # dup_to_first[k] = first slot with slot k's peer; invalid slots get
    # unique sentinels so they form singleton groups.
    caller_tables = state.view_key[caller]  # [K, N]
    own_p = state.view_key[peer]  # [K, N]
    peer_eff = jnp.where(ok, peer, -1 - jnp.arange(K, dtype=jnp.int32))
    dup_to_first = jnp.argmax(peer_eff[:, None] == peer_eff[None, :], axis=1)
    first_p = ok & (dup_to_first == jnp.arange(K))
    cand_k = jnp.where(ok[:, None], caller_tables, NO_CANDIDATE)  # [K, N]
    merged = jnp.full((K, n), NO_CANDIDATE, jnp.int32).at[dup_to_first].max(cand_k)
    buf_p = jnp.maximum(own_p, merged[dup_to_first])  # [K, N]
    acc = (
        (buf_p > own_p)
        & ((own_p >= 0) | ((buf_p & 3) <= RANK_LEAVING))
        & state.up[peer][:, None]
        & _fetch_gate(
            state,
            SALT_SYNC_REQ,
            peer[:, None],
            rows[None, :],
            buf_p,
            state.fetch_rt if state.fetch_rt.ndim == 0 else state.fetch_rt[peer],
        )
    )
    if params.namespace_gate:
        acc = acc & state.ns_rel[state.ns_id[peer][:, None], state.ns_id[None, :]]
    new_p = jnp.where(acc, buf_p, own_p)  # >= own_p, so row-max == overwrite
    # duplicate peer slots recompute the IDENTICAL merged row; liveness
    # deltas count each distinct peer once (first_p)
    delta_p = (
        ((new_p & 3) != RANK_DEAD).astype(jnp.int32)
        - ((own_p & 3) != RANK_DEAD).astype(jnp.int32)
    ).sum(axis=1) * first_p.astype(jnp.int32)
    st = state.replace(
        view_key=state.view_key.at[peer].max(new_p),
        n_live=state.n_live.at[peer].add(delta_p),
    )
    sus_req = jnp.where(acc & ((buf_p & 3) == RANK_SUSPECT), buf_p, NO_CANDIDATE).max(
        axis=0
    )  # [N]

    # SYNC_ACK: peer's post-merge table back to the caller, regather-free
    ack_cand = jnp.where(ok[:, None], new_p, NO_CANDIDATE)
    match = (caller[:, None] == peer[None, :]) & ok[None, :]
    has_m = match.any(axis=1)
    contrib = jnp.where(
        has_m[:, None], new_p[jnp.argmax(match, axis=1)], NO_CANDIDATE
    )
    own_rows = jnp.maximum(caller_tables, contrib)  # post-request caller rows
    accept = (
        (ack_cand > own_rows)
        & ((own_rows >= 0) | ((ack_cand & 3) <= RANK_LEAVING))
        & state.up[caller][:, None]
        & _fetch_gate(
            st,
            SALT_SYNC_ACK,
            caller[:, None],
            rows[None, :],
            ack_cand,
            st.fetch_rt if st.fetch_rt.ndim == 0 else st.fetch_rt[caller],
        )
    )
    if params.namespace_gate:
        accept = accept & state.ns_rel[
            state.ns_id[caller][:, None], state.ns_id[None, :]
        ]
    new_c = jnp.where(accept, ack_cand, own_rows)
    delta_c = (
        ((new_c & 3) != RANK_DEAD).astype(jnp.int32)
        - ((own_rows & 3) != RANK_DEAD).astype(jnp.int32)
    ).sum(axis=1) * valid_c.astype(jnp.int32)
    st = st.replace(
        view_key=st.view_key.at[caller].max(new_c),
        n_live=st.n_live.at[caller].add(delta_c),
    )
    sus_ack = jnp.where(
        accept & ((ack_cand & 3) == RANK_SUSPECT), ack_cand, NO_CANDIDATE
    ).max(axis=0)
    sus_cand = jnp.maximum(sus_req, sus_ack)
    new_sus = jnp.maximum(st.sus_key, sus_cand)
    st = st.replace(
        sus_key=new_sus,
        sus_since=jnp.where(new_sus > st.sus_key, st.tick, st.sus_since),
    )

    ok_full = jnp.zeros((n,), bool).at[caller].max(ok)
    st = st.replace(force_sync=st.force_sync & ~ok_full)

    # capped re-gossip: top-P accepted keys per participant row (largest key
    # first — freshest identities/incarnations are the newsworthy ones)
    def _top_props(acc_mask, cand_vals, owner_rows, owner_valid):
        subs, keys, origs, vals = [], [], [], []
        remaining = jnp.where(acc_mask, cand_vals, NO_CANDIDATE)
        for _ in range(P):
            col = jnp.argmax(remaining, axis=1).astype(jnp.int32)
            val = remaining[jnp.arange(remaining.shape[0]), col]
            good = (val > NO_CANDIDATE) & owner_valid
            subs.append(col)
            keys.append(val)
            origs.append(owner_rows)
            vals.append(good)
            remaining = remaining.at[jnp.arange(remaining.shape[0]), col].set(
                NO_CANDIDATE
            )
        return (
            jnp.concatenate(subs),
            jnp.concatenate(keys),
            jnp.concatenate(origs),
            jnp.concatenate(vals),
        )

    props_p = _top_props(acc & first_p[:, None], buf_p, peer, ok & first_p)
    props_c = _top_props(accept, ack_cand, caller, ok)
    proposals = tuple(
        jnp.concatenate([a, b]) for a, b in zip(props_p, props_c)
    )
    metrics = {"sync_roundtrips": ok.sum()}
    if adaptive:
        # r14 confirmation evidence: accepted SUSPECT records both ways.
        # Duplicate peer slots recompute identical acc rows — count the
        # first slot per peer only (callers are distinct).
        m_req = acc & first_p[:, None] & ((buf_p & 3) == RANK_SUSPECT)
        m_ack = accept & ((ack_cand & 3) == RANK_SUSPECT)
        metrics["_ad_cnt"] = (
            m_req.astype(jnp.int32).sum(axis=0)
            + m_ack.astype(jnp.int32).sum(axis=0)
        )
        # sus_req/sus_ack are already the per-subject max accepted SUSPECT
        # keys of the two directions (the episode-key contribution)
        metrics["_ad_key"] = sus_cand
    if trace:
        # trace-plane export (r10, same contract as kernel._sync_phase)
        metrics["trace_sync"] = {
            "caller": caller.astype(jnp.int32),
            "valid": valid_c,
            "peer": peer.astype(jnp.int32),
            "ok": ok,
            "req_acc": acc.sum(axis=1).astype(jnp.int32),
            "ack_acc": accept.sum(axis=1).astype(jnp.int32),
        }
    return st, proposals, metrics


def _refute_phase(state: SparseState, params: SparseParams):
    """Self-record refutation (SUSPECT/DEAD diagonal, or overwritten leave
    intent) — row-local; the refuted record is proposed as a rumor (the
    reference gossips the bumped ALIVE, ``onSelfMemberDetected:686-708``)."""
    n = state.capacity
    rows = jnp.arange(n)
    diag = state.view_key[rows, rows]
    rank = diag & 3
    need = state.up & (
        (rank == RANK_SUSPECT)
        | (rank == RANK_DEAD)
        | (state.leaving & (rank != RANK_LEAVING))
    )
    # same compaction/throttle as the FD write: refutes are near-zero per
    # tick; throttled rows still need refuting next tick and retry
    V = min(n, params.refute_slots or max(64, n // 16))
    eff = need & (jnp.cumsum(need.astype(jnp.int32)) - 1 < V)
    announce_rank = jnp.where(state.leaving, RANK_LEAVING, RANK_ALIVE)
    new_diag = jnp.where(eff, (((diag >> 2) + 1) << 2) | announce_rank, diag)

    def _apply(st: SparseState):
        # one-hot elementwise diagonal write — see _fd_phase._write for why
        # this must not be a point scatter. A DEAD diagonal was counted out
        # of the row's own live view, hence the regain.
        regain = (eff & (rank == RANK_DEAD)).astype(jnp.int32)
        return st.replace(
            view_key=jnp.where(
                eff[:, None] & (rows[None, :] == rows[:, None]),
                new_diag[:, None],
                st.view_key,
            ),
            n_live=st.n_live + regain,
        )

    st = jax.lax.cond(eff.any(), _apply, lambda s: s, state)
    return st, (rows, new_diag, rows, eff)


def _rumor_sweeps(state: SparseState, params: SparseParams, *,
                  covered=None, n_up=None) -> SparseState:
    """Slot reclamation. User rumors: dense-kernel semantics. Membership
    rumors: same age/forwarder/pending rules on the u8 plane, plus the
    early full-coverage free (deviation 5).

    ``covered``/``n_up`` (r17, fused tick): pre-computed early-free
    coverage ([M] bool, from the gossip phase's hand-off) and up-count —
    bit-identical to the in-phase derivations (nothing in between writes
    the planes they read); ``None`` traces the legacy spelling."""
    n_up = (state.up.sum() if n_up is None else n_up).astype(jnp.int32)
    sweep = 2 * (params.repeat_mult * ceil_log2(n_up) + 1)
    spread = params.repeat_mult * ceil_log2(state.n_live)  # [N]

    keep_u = state.tick - state.rumor_created <= sweep
    forwarding_u = (
        state.infected
        & state.up[:, None]
        & (state.tick - state.infected_at < spread[:, None])
    ).any(axis=0)
    keep_u = keep_u | forwarding_u
    if params.delay_slots:
        keep_u = keep_u | state.pending_inf.any(axis=(0, 1))

    state = state.replace(rumor_active=state.rumor_active & keep_u)

    def _sweep_m(state: SparseState):
        age = state.minf_age.astype(jnp.int32)
        forwarding_m = (
            (age > 0) & (age <= spread[:, None]) & state.up[:, None]
        ).any(axis=0)
        keep_m = (state.tick - state.mr_created <= sweep) | forwarding_m
        pending_m = (
            state.pending_minf.any(axis=(0, 1))
            if params.delay_slots
            else jnp.zeros_like(keep_m)
        )
        keep_m = keep_m | pending_m
        if params.early_free:
            # members who joined AFTER a rumor was created are exempt from
            # its coverage requirement (deviation 5). The reference DOES
            # keep forwarding in-window gossips to a new member (it joins
            # remoteMembers, GossipProtocolImpl.java:253, and
            # selectGossipMembers draws from that list); what bounds the
            # exemption's gap is the joiner's forced initial SYNC — its
            # full-table merge (onSyncAck) delivers every fact a freed
            # rumor carried, and the joiner's own row was wiped at join
            # anyway. Without the exemption, the continuous joiner influx
            # at large N keeps every rumor's coverage perpetually
            # one-joiner-short, early-free never fires, and residency
            # degrades to the full age sweep — the measured r4
            # pool-saturation mechanism at N=49,152.
            cov = (
                (
                    (state.minf_age > 0)
                    | ~state.up[:, None]
                    | (state.joined_at[:, None] > state.mr_created[None, :])
                ).all(axis=0)
                if covered is None
                else covered
            )
            keep_m = keep_m & ~(cov & ~pending_m)
        keep_m = keep_m & state.mr_active
        freed = state.mr_active & ~keep_m
        state = state.replace(
            mr_active=keep_m,
            mr_subject=jnp.where(freed, -1, state.mr_subject),
            minf_age=jnp.where(freed[None, :], jnp.uint8(0), state.minf_age),
        )
        if params.delay_slots:
            state = state.replace(
                pending_minf=state.pending_minf & keep_m[None, None, :]
            )
        return state

    # the membership sweep's [N, M] passes are skipped while the pool is
    # empty (same gating as the gossip phase's membership sections)
    return jax.lax.cond(state.mr_active.any(), _sweep_m, lambda st: st, state)


def _alloc_phase(state: SparseState, proposals, params: SparseParams):
    """Turn this tick's accepted-change proposals into new membership rumors.

    Proposals (subject, key, origin, valid) from FD verdicts, suspicion
    expiries, SYNC re-gossip, and refutations are compacted to E =
    ``announce_slots`` entries, deduplicated (stable sort by packed
    (subject, key); first proposer wins) against both the batch and the
    active pool, and assigned ascending free slots. Dropped proposals are
    counted (``announce_dropped``) — they reach stragglers via SYNC."""
    E = params.announce_slots
    n = state.capacity
    subject = jnp.concatenate([p[0] for p in proposals])
    key = jnp.concatenate([p[1] for p in proposals])
    origin = jnp.concatenate([p[2] for p in proposals])
    valid = jnp.concatenate([p[3] for p in proposals])
    # Pre-compaction pool dedup (r5): a proposal whose subject already has
    # an equal-or-stronger active rumor would be SKIPPED at allocation
    # ("already covered"), but when it lands beyond the E-compaction window
    # it was counted as a DROP instead. Under churn most FD verdicts are
    # duplicate suspicions of the same few subjects (every prober of a
    # crashed node proposes the same key), which both miscounted
    # announce_dropped_fd by orders of magnitude and crowded genuine facts
    # out of the window. One [M]->[N] scatter builds the strongest active
    # key per subject; covered proposals are invalidated up front.
    pool_key_by_subject = (
        jnp.full((n + 1,), NO_CANDIDATE, jnp.int32)
        .at[jnp.where(state.mr_active, state.mr_subject, n)]
        .max(jnp.where(state.mr_active, state.mr_key, NO_CANDIDATE), mode="drop")
    )[:n]
    valid = valid & (key > pool_key_by_subject[jnp.clip(subject, 0, n - 1)])
    L = subject.shape[0]
    # segment boundaries of the concatenated proposal vector, for per-source
    # drop attribution (r4 staleness analysis: WHICH facts the compaction
    # window crowds out — sync re-gossip drops are pool duplicates and
    # harmless, fd/expiry/refute drops would delay genuinely new facts)
    seg_ends = np.cumsum([int(p[0].shape[0]) for p in proposals])

    def _alloc(state: SparseState):
        (idx,) = jnp.nonzero(valid, size=E, fill_value=L)
        got = idx < L
        idx = jnp.minimum(idx, L - 1)
        # priority classes = the first three segments (fd, expiry, refute):
        # genuinely new facts evict most-covered rumors when the pool is
        # full; sync re-gossip (pool duplicates by construction) never does
        prio = got & (idx < int(seg_ends[2]))
        st, allocated, no_slot, evicted = _allocate(
            state, subject[idx], key[idx], origin[idx], got, prio=prio
        )
        # dropped = compaction overflow (valid proposals beyond E) + fresh
        # winners that found no free slot; batch duplicates and superseded/
        # already-covered proposals are not drops. BOTH kinds attribute to
        # their proposal source: no_slot is a per-compacted-entry mask whose
        # entries map back to positions in the concatenated vector via idx.
        rank = jnp.cumsum(valid.astype(jnp.int32)) - 1
        over = valid & (rank >= E)
        pos = jnp.arange(L)
        noslot_pos = (
            jnp.zeros((L,), bool).at[idx].max(no_slot & got, mode="drop")
        )
        dropped_pos = over | noslot_pos
        seg_drops = [
            jnp.where((pos >= lo) & (pos < hi), dropped_pos, False).sum()
            for lo, hi in zip([0, *seg_ends[:-1]], seg_ends)
        ]
        overflow = valid.sum() - got.sum()
        return st, {
            "announce_dropped": overflow + no_slot.sum(),
            "announce_dropped_fd": seg_drops[0],
            "announce_dropped_expiry": seg_drops[1],
            "announce_dropped_refute": seg_drops[2],
            "announce_dropped_sync": seg_drops[3],
            "announced": allocated,
            "pool_evicted": evicted,
        }

    def _skip(state: SparseState):
        z = jnp.int32(0)
        return state, {
            "announce_dropped": z,
            "announce_dropped_fd": z,
            "announce_dropped_expiry": z,
            "announce_dropped_refute": z,
            "announce_dropped_sync": z,
            "announced": z,
            "pool_evicted": z,
        }

    return jax.lax.cond(valid.any(), _alloc, _skip, state)


# ---------------------------------------------------------------------------
# tick
# ---------------------------------------------------------------------------


def sparse_tick(state: SparseState, key: jax.Array, params: SparseParams,
                trace=None, ad=None, fused: bool = False):
    """One gossip period for all N members, sparse mode. Pure; jit/shard me.

    ``trace`` (a :class:`..trace.schema.TraceSpec`, static) arms the causal
    trace plane — same contract as ``kernel.tick``: the metrics dict gains
    a ``_trace_rows`` [K, F] block built from read-only [N]-sized phase
    internals (never a read of the carried [N, N] planes); the state
    trajectory is bit-identical armed vs unarmed.

    ``ad`` (an :class:`..adaptive.AdaptiveState`, r14) arms the adaptive
    failure-detection plane; the return becomes ``(state, ad', metrics)``.
    ``ad=None`` traces the byte-identical legacy program.

    ``fused`` (r17): the gossip→sweep hand-off — the sweep's early-free
    coverage vector comes from the gossip phase's post-apply planes and
    ONE up-count is shared between sweep and telemetry, instead of each
    phase re-deriving them. Bit-identical trajectory (tests);
    ``fused=False`` traces the legacy program."""
    armed = ad is not None
    if fused and trace is not None:
        raise ValueError(
            "the fused tick has no trace plane — profile/trace the "
            "unfused tick (bit-identical trajectory)"
        )
    if armed:
        if trace is not None:
            raise ValueError(
                "trace-armed adaptive windows are not supported"
            )
        if params.adaptive.is_default:
            raise ValueError(
                "adaptive tick needs an enabled AdaptiveSpec on params"
            )
    state = state.replace(tick=state.tick + 1)
    fd_key, round_key = split_tick_key(key)
    r = draw_sparse_round(round_key, state.capacity, params.fanout, params.sample_tries)

    n = state.capacity
    rows = jnp.arange(n)
    no_props = (
        jnp.zeros((n,), jnp.int32),
        jnp.zeros((n,), jnp.int32),
        rows,
        jnp.zeros((n,), bool),
    )

    def _fd_on(st: SparseState):
        fd_r = draw_sparse_fd(fd_key, n, params.ping_req_k, params.sample_tries)
        return _fd_phase(st, fd_r, params, trace=trace is not None, ad=ad)

    def _fd_off(st: SparseState):
        m = {
            "fd_probes": jnp.int32(0),
            "fd_failed_probes": jnp.int32(0),
            "fd_new_suspects": jnp.int32(0),
        }
        if armed:
            m["_ad_miss"] = jnp.zeros((n,), bool)
            m["_ad_succ"] = jnp.zeros((n,), bool)
            m["_ad_cnt"] = jnp.zeros((n,), jnp.int32)
            m["_ad_key"] = jnp.full((n,), NO_CANDIDATE, jnp.int32)
        if trace is not None:
            from ..trace import capture as _tc

            m["trace_fd"] = _tc.zero_fd_trace(n, params.ping_req_k)
        return st, no_props, m

    fd_ran = (state.tick % params.fd_every) == 0
    state, props_fd, fd_m = jax.lax.cond(fd_ran, _fd_on, _fd_off, state)
    if trace is not None:
        state, props_exp, trace_sus = _suspicion_sweep(state, params, trace=trace)
    else:
        state, props_exp = _suspicion_sweep(state, params, ad=ad)
    if fused:
        state, g_m, covered = _gossip_phase(
            state, r, params, adaptive=armed, fused=True
        )
    else:
        state, g_m = _gossip_phase(state, r, params, adaptive=armed)
        covered = None
    state, props_sync, s_m = _sync_phase(
        state, r, params, trace=trace is not None, adaptive=armed
    )
    state, props_ref = _refute_phase(state, params)
    if fused:
        n_up = state.up.sum()
        state = _rumor_sweeps(state, params, covered=covered, n_up=n_up)
    else:
        n_up = None
        state = _rumor_sweeps(state, params)
    # allocation compaction takes the first E valid proposals in this order:
    # refutations rank BEFORE the sync re-gossip flood (sync proposals are
    # mostly pool duplicates; a crowded-out refutation is a lingering zombie)
    state, a_m = _alloc_phase(
        state, (props_fd, props_exp, props_ref, props_sync), params
    )

    trace_fd = fd_m.pop("trace_fd", None)
    trace_sync = s_m.pop("trace_sync", None)
    if armed:
        miss = fd_m.pop("_ad_miss")
        succ = fd_m.pop("_ad_succ")
        acc_cnt = fd_m.pop("_ad_cnt") + g_m.pop("_ad_cnt") + s_m.pop("_ad_cnt")
        acc_key = jnp.maximum(
            jnp.maximum(fd_m.pop("_ad_key"), g_m.pop("_ad_key")),
            s_m.pop("_ad_key"),
        )
        lh2, ck2, cf2 = _adp.fold(
            params.adaptive, ad.lh, ad.conf_key, ad.conf,
            acc_key=acc_key, acc_cnt=acc_cnt,
            miss=miss, succ=succ, refuted=props_ref[3], up=state.up,
        )
        ad = _adp.AdaptiveState(lh=lh2, conf_key=ck2, conf=cf2)
    metrics = {
        **fd_m, **g_m, **s_m, **a_m,
        **state_metrics(state, params, n_up=n_up),
    }
    if armed:
        metrics["adaptive_lh_high"] = ad.lh.max()
        metrics["adaptive_conf_high"] = ad.conf.max()
        return state, ad, metrics
    if trace is not None:
        from ..trace import capture as _tc

        # self-refutations ride the refute phase's own proposal mask (the
        # eff throttle — exactly the rows whose diagonal re-announced)
        trace_ref = props_ref[3][jnp.asarray(trace.tracer_rows, jnp.int32)]
        metrics["_trace_rows"] = _tc.build_trace_rows(
            trace,
            tick=state.tick,
            up=state.up,
            fd_ran=fd_ran,
            trace_fd=trace_fd,
            trace_sus=trace_sus,
            trace_ref=trace_ref,
            trace_sync=trace_sync,
            infected_b=state.infected,
            infected_at=state.infected_at,
            infected_from=state.infected_from,
        )
    return state, metrics


def state_metrics(state: SparseState, params: SparseParams, *,
                  n_up=None) -> dict:
    """The sparse tick's state-derived health metrics — factored out (r10)
    so the phase-split profiler's "telemetry" phase runs the EXACT spelling
    the fused tick uses (see ``kernel.state_metrics``). ``n_up`` (r17):
    pre-computed up-count from the fused tick (``up`` is not written
    between the sweeps and here — the alloc phase touches only the rumor
    pool); ``None`` re-derives it (legacy)."""
    n = state.capacity
    if n_up is None:
        n_up = state.up.sum()
    coverage = (
        (state.infected & state.up[:, None]).sum(0).astype(jnp.float32)
        / jnp.maximum(n_up, 1)
    )
    # segmentation over BOTH pools (user rumors + membership rumors): holes
    # in a node's receive stream — see kernel.tick's metric of the same name
    newest_u = jnp.where(
        state.infected, state.rumor_created[None, :], NEVER
    ).max(axis=1)
    seg_u = (
        state.rumor_active[None, :]
        & ~state.infected
        & (state.rumor_created[None, :] < newest_u[:, None])
        & state.up[:, None]
    ).sum(axis=1)
    def _seg_m(st: SparseState):
        newest_m = jnp.where(
            st.minf_age > 0, st.mr_created[None, :], NEVER
        ).max(axis=1)
        return (
            st.mr_active[None, :]
            & (st.minf_age == 0)
            & (st.mr_created[None, :] < newest_m[:, None])
            & st.up[:, None]
        ).sum(axis=1)

    # the membership-pool segmentation scan is [N, M] work; sampling it on
    # sweep ticks only (it is a MONITORING metric, not protocol state —
    # never read by the tick, not oracle-compared) keeps the common tick
    # free of two extra [N, M] passes at flagship pool sizes
    seg_m = jax.lax.cond(
        state.mr_active.any() & ((state.tick % params.sweep_every) == 0),
        _seg_m,
        lambda st: jnp.zeros((state.capacity,), jnp.int32),
        state,
    )
    metrics = {
        "n_up": n_up,
        "mr_active_count": state.mr_active.sum(),
        "rumor_coverage": coverage,
        "gossip_segmentation": (seg_u + seg_m).max(),
    }
    if params.full_metrics:
        up2 = state.up[:, None] & state.up[None, :]
        pairs = jnp.maximum(up2.sum() - n_up, 1)
        off_diag = ~jnp.eye(n, dtype=bool)
        rank = state.view_key & 3
        metrics["alive_view_fraction"] = (
            (up2 & off_diag & (rank == RANK_ALIVE)).sum().astype(jnp.float32) / pairs
        )
        metrics["false_suspect_pairs"] = (up2 & off_diag & (rank == RANK_SUSPECT)).sum()
    else:
        metrics["alive_view_fraction"] = jnp.float32(0.0)
        metrics["false_suspect_pairs"] = jnp.int32(0)
    return metrics


def run_sparse_ticks(
    state: SparseState,
    key: jax.Array,
    n_ticks: int,
    params: SparseParams,
    watch_rows: jax.Array | None = None,
    fused: bool = False,
):
    """Batched scan window — same contract as ``kernel.run_ticks`` (same
    per-tick key chain as host-side splitting; watched rows' view keys
    stacked per tick)."""

    def body(carry, _):
        st, k = carry
        k, tick_key = jax.random.split(k)
        st, m = sparse_tick(st, tick_key, params, fused=fused)
        if watch_rows is not None:
            m = dict(m, _watched_keys=st.view_key[watch_rows])
        return (st, k), m

    (state, key), ms = jax.lax.scan(body, (state, key), None, length=n_ticks)
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, key, ms, watched


def run_sparse_ticks_traced(
    state: SparseState,
    key: jax.Array,
    trace_buf: jax.Array,
    trace_cursor: jax.Array,
    n_ticks: int,
    params: SparseParams,
    trace,
    watch_rows: jax.Array | None = None,
):
    """Trace-armed window scan — the sparse twin of
    ``kernel.run_ticks_traced`` (same carry-threaded ring append, same
    bit-identical-trajectory contract)."""
    from ..trace import capture as _tc

    def body(carry, _):
        st, k, buf, cur = carry
        k, tick_key = jax.random.split(k)
        st, m = sparse_tick(st, tick_key, params, trace=trace)
        buf, cur = _tc.append_rows(
            buf, cur, m.pop("_trace_rows"), trace.ring_len
        )
        if watch_rows is not None:
            m = dict(m, _watched_keys=st.view_key[watch_rows])
        return (st, k, buf, cur), m

    (state, key, trace_buf, _cur), ms = jax.lax.scan(
        body, (state, key, trace_buf, trace_cursor), None, length=n_ticks
    )
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, key, ms, watched, trace_buf


def make_sparse_traced_run(
    params: SparseParams, n_ticks: int, trace, donate: bool = True
):
    """Jitted :func:`run_sparse_ticks_traced` with state + trace ring
    donated (argnums 0, 2) — see ``kernel.make_traced_run``."""
    import functools

    return jax.jit(
        functools.partial(
            run_sparse_ticks_traced, n_ticks=n_ticks, params=params, trace=trace
        ),
        donate_argnums=(0, 2) if donate else (),
    )


def run_sparse_ticks_adaptive(
    state: SparseState,
    ad,
    key: jax.Array,
    n_ticks: int,
    params: SparseParams,
    watch_rows: jax.Array | None = None,
    fused: bool = False,
):
    """Adaptive-armed :func:`run_sparse_ticks` (r14): the AdaptiveState
    rides the scan carry alongside the engine state; same key chain."""

    def body(carry, _):
        st, a, k = carry
        k, tick_key = jax.random.split(k)
        st, a, m = sparse_tick(st, tick_key, params, ad=a, fused=fused)
        if watch_rows is not None:
            m = dict(m, _watched_keys=st.view_key[watch_rows])
        return (st, a, k), m

    (state, ad, key), ms = jax.lax.scan(
        body, (state, ad, key), None, length=n_ticks
    )
    watched = ms.pop("_watched_keys") if watch_rows is not None else None
    return state, ad, key, ms, watched


def make_sparse_adaptive_run(params: SparseParams, n_ticks: int,
                             donate: bool = True):
    """Jitted :func:`run_sparse_ticks_adaptive`: engine + adaptive state
    donated (argnums 0, 1). Refuses a default spec (the legacy builder is
    the byte-identical program for that case)."""
    import functools

    if params.adaptive.is_default:
        raise ValueError(
            "make_sparse_adaptive_run needs an enabled AdaptiveSpec on "
            "params — the default spec's program is make_sparse_run's"
        )
    return jax.jit(
        functools.partial(
            run_sparse_ticks_adaptive, n_ticks=n_ticks, params=params
        ),
        donate_argnums=(0, 1) if donate else (),
    )


def make_sparse_fleet_run(params: SparseParams, n_ticks: int, donate: bool = True):
    """Scenario-batched :func:`run_sparse_ticks` (r15) — the sparse twin
    of ``kernel.make_fleet_run``: state stacked to ``[S, ...]``, keys
    ``[S, 2]``, fleet state donated; row trajectories bit-identical to
    serial windows on the same (state, key)."""
    from .fleet import make_fleet_window

    return make_fleet_window(run_sparse_ticks, params, n_ticks, donate=donate)


def make_sparse_fleet_adaptive_run(
    params: SparseParams, n_ticks: int, donate: bool = True
):
    """Fleet twin of :func:`make_sparse_adaptive_run` (argnums 0, 1
    donated). Refuses a default spec."""
    from .fleet import make_fleet_window

    if params.adaptive.is_default:
        raise ValueError(
            "make_sparse_fleet_adaptive_run needs an enabled AdaptiveSpec "
            "on params — the default spec's program is "
            "make_sparse_fleet_run's"
        )
    return make_fleet_window(
        run_sparse_ticks_adaptive, params, n_ticks, donate=donate,
        donated=(0, 1),
    )


def make_sparse_run(params: SparseParams, n_ticks: int, donate: bool = True):
    """Jitted :func:`run_sparse_ticks` window with the state DONATED — the
    sparse twin of ``kernel.make_run``. Donation is not optional at large N
    (an un-donated window holds TWO copies of the view matrix: 19.4 GB at
    49k, past the chip on its own — the bench loops have always donated);
    this builder makes it the one shared spelling the driver, bench.py, and
    the dispatch-pipeline bench all use. ``donate=False`` is for lockstep
    comparisons that must keep the input state alive."""
    import functools

    return jax.jit(
        functools.partial(run_sparse_ticks, n_ticks=n_ticks, params=params),
        donate_argnums=0 if donate else (),
    )


# --------------------------------------------------------------------------
# fused tick windows (r17): gossip→sweep coverage hand-off + shared
# up-count as first-class window builders (see sparse_tick's ``fused``).
# --------------------------------------------------------------------------


def run_sparse_ticks_fused(state, key, n_ticks, params, watch_rows=None):
    """:func:`run_sparse_ticks` over the fused tick (bit-identical
    trajectory)."""
    return run_sparse_ticks(state, key, n_ticks, params, watch_rows,
                            fused=True)


def run_sparse_ticks_fused_adaptive(state, ad, key, n_ticks, params,
                                    watch_rows=None):
    """:func:`run_sparse_ticks_adaptive` over the fused tick."""
    return run_sparse_ticks_adaptive(state, ad, key, n_ticks, params,
                                     watch_rows, fused=True)


def make_sparse_fused_run(params: SparseParams, n_ticks: int,
                          donate: bool = True):
    """Jitted fused-tick window, state DONATED — the r17 twin of
    :func:`make_sparse_run`. Bit-identical trajectory to the unfused
    window (tests/test_fused.py); the program drops the sweep's own
    [N, M] coverage reduce (it reuses the gossip phase's) and one
    up-count."""
    import functools

    return jax.jit(
        functools.partial(
            run_sparse_ticks_fused, n_ticks=n_ticks, params=params
        ),
        donate_argnums=0 if donate else (),
    )


def make_sparse_fused_adaptive_run(params: SparseParams, n_ticks: int,
                                   donate: bool = True):
    """Fused twin of :func:`make_sparse_adaptive_run` (donates argnums
    0, 1). Refuses a default spec."""
    import functools

    if params.adaptive.is_default:
        raise ValueError(
            "make_sparse_fused_adaptive_run needs an enabled AdaptiveSpec "
            "on params — the default spec's program is "
            "make_sparse_fused_run's"
        )
    return jax.jit(
        functools.partial(
            run_sparse_ticks_fused_adaptive, n_ticks=n_ticks, params=params
        ),
        donate_argnums=(0, 1) if donate else (),
    )


def make_sparse_fused_fleet_run(params: SparseParams, n_ticks: int,
                                donate: bool = True):
    """Fused twin of :func:`make_sparse_fleet_run`: scenario-batched
    fused-tick window, fleet state donated."""
    from .fleet import make_fleet_window

    return make_fleet_window(
        run_sparse_ticks_fused, params, n_ticks, donate=donate
    )
