"""Cluster event model.

Parity with reference ``MembershipEvent`` (cluster-api
``MembershipEvent.java:13-91``: ADDED/REMOVED/LEAVING/UPDATED with old/new
metadata and timestamp) and ``FailureDetectorEvent``
(``fdetector/FailureDetectorEvent.java:8``).
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Optional

from .member import Member, MemberStatus


class MembershipEventType(enum.Enum):
    ADDED = "added"
    REMOVED = "removed"
    LEAVING = "leaving"
    UPDATED = "updated"


@dataclass(frozen=True)
class MembershipEvent:
    """Membership change notification with optional old/new metadata blobs."""

    type: MembershipEventType
    member: Member
    old_metadata: Optional[bytes] = None
    new_metadata: Optional[bytes] = None
    timestamp: float = field(default_factory=time.time)

    # -- factories (reference MembershipEvent.java:42-78) ------------------
    @staticmethod
    def added(member: Member, metadata: Optional[bytes] = None, ts: Optional[float] = None) -> "MembershipEvent":
        return MembershipEvent(
            MembershipEventType.ADDED, member, None, metadata,
            ts if ts is not None else time.time(),
        )

    @staticmethod
    def removed(member: Member, metadata: Optional[bytes] = None, ts: Optional[float] = None) -> "MembershipEvent":
        return MembershipEvent(
            MembershipEventType.REMOVED, member, metadata, None,
            ts if ts is not None else time.time(),
        )

    @staticmethod
    def leaving(member: Member, metadata: Optional[bytes] = None, ts: Optional[float] = None) -> "MembershipEvent":
        return MembershipEvent(
            MembershipEventType.LEAVING, member, metadata, metadata,
            ts if ts is not None else time.time(),
        )

    @staticmethod
    def updated(member: Member, old_metadata: Optional[bytes], new_metadata: Optional[bytes],
                ts: Optional[float] = None) -> "MembershipEvent":
        return MembershipEvent(
            MembershipEventType.UPDATED, member, old_metadata, new_metadata,
            ts if ts is not None else time.time(),
        )

    # -- predicates --------------------------------------------------------
    @property
    def is_added(self) -> bool:
        return self.type is MembershipEventType.ADDED

    @property
    def is_removed(self) -> bool:
        return self.type is MembershipEventType.REMOVED

    @property
    def is_leaving(self) -> bool:
        return self.type is MembershipEventType.LEAVING

    @property
    def is_updated(self) -> bool:
        return self.type is MembershipEventType.UPDATED

    def __str__(self) -> str:
        return f"MembershipEvent({self.type.value}, {self.member})"


@dataclass(frozen=True)
class FailureDetectorEvent:
    """Per-probe verdict emitted by the failure detector toward membership.

    ``period`` is the FD round that produced the verdict (an indirect probe
    publishes one verdict per relay path, all for the same period — group by
    it to reason about whole rounds)."""

    member: Member
    status: MemberStatus
    period: Optional[int] = None

    def __str__(self) -> str:
        return f"FailureDetectorEvent({self.member}, {self.status.name})"
