from .events import FailureDetectorEvent, MembershipEvent, MembershipEventType
from .member import Member, MemberStatus, new_member_id
from .message import Message
from .record import MembershipRecord

__all__ = [
    "Member",
    "MemberStatus",
    "MembershipRecord",
    "MembershipEvent",
    "MembershipEventType",
    "FailureDetectorEvent",
    "Message",
    "new_member_id",
]
