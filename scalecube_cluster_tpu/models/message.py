"""Message model: qualifier-routed headers + opaque payload.

Parity with reference ``Message`` (transport-api ``Message.java:19-230``):
reserved headers ``qualifier`` (``q``), ``correlation_id`` (``cid``) and
``sender`` with the same routing semantics (every protocol component filters
``listen()`` by qualifier; request/response correlates on ``cid``).

The wire form is codec-pluggable (see ``transport/codecs.py``). For the
simulated path messages are packed columnar (qualifier -> int enum, payload ->
fixed-width tensor slots) by ``sim/sim_transport.py``.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional

# Reserved header names (reference Message.java:27-39).
HEADER_QUALIFIER = "q"
HEADER_CORRELATION_ID = "cid"
HEADER_SENDER = "sender"

# Protocol qualifiers (reference FailureDetectorImpl.java:35-37,
# GossipProtocolImpl.java:38, MembershipProtocolImpl.java:68-70,
# MetadataStoreImpl.java:28-29).
Q_PING = "sc/fdetector/ping"
Q_PING_REQ = "sc/fdetector/pingReq"
Q_PING_ACK = "sc/fdetector/pingAck"
Q_GOSSIP_REQ = "sc/gossip/req"
Q_MEMBERSHIP_SYNC = "sc/membership/sync"
Q_MEMBERSHIP_SYNC_ACK = "sc/membership/syncAck"
Q_MEMBERSHIP_GOSSIP = "sc/membership/gossip"
Q_METADATA_REQ = "sc/metadata/req"
Q_METADATA_RESP = "sc/metadata/resp"

#: Qualifiers hidden from user-level ``listen()`` (reference
#: ClusterImpl.SYSTEM_MESSAGES, ClusterImpl.java:62-76).
SYSTEM_QUALIFIERS = frozenset(
    {
        Q_PING,
        Q_PING_REQ,
        Q_PING_ACK,
        Q_GOSSIP_REQ,
        Q_MEMBERSHIP_SYNC,
        Q_MEMBERSHIP_SYNC_ACK,
        Q_METADATA_REQ,
        Q_METADATA_RESP,
    }
)

#: Gossip qualifiers hidden from user gossip listeners (ClusterImpl.java:386-389).
SYSTEM_GOSSIP_QUALIFIERS = frozenset({Q_MEMBERSHIP_GOSSIP})

_cid_counter = itertools.count()


def new_correlation_id(prefix: str = "") -> str:
    """Monotone correlation id (reference CorrelationIdGenerator.java:6)."""
    return f"{prefix}-{next(_cid_counter):x}" if prefix else f"{next(_cid_counter):x}"


@dataclass(frozen=True)
class Message:
    """Immutable header-map + data message.

    ``data`` is an arbitrary (codec-serializable) payload. Use
    :meth:`with_data` / builder-style ``replace`` helpers to derive messages.
    """

    headers: Dict[str, str] = field(default_factory=dict)
    data: Any = None

    # -- builders ----------------------------------------------------------
    @staticmethod
    def with_data(data: Any, qualifier: Optional[str] = None, **headers: str) -> "Message":
        hdrs = dict(headers)
        if qualifier is not None:
            hdrs[HEADER_QUALIFIER] = qualifier
        return Message(headers=hdrs, data=data)

    @staticmethod
    def from_message(msg: "Message", **overrides: Any) -> "Message":
        return replace(msg, **overrides)

    def with_header(self, name: str, value: str) -> "Message":
        hdrs = dict(self.headers)
        hdrs[name] = value
        return Message(headers=hdrs, data=self.data)

    # -- reserved header accessors ----------------------------------------
    @property
    def qualifier(self) -> Optional[str]:
        return self.headers.get(HEADER_QUALIFIER)

    @property
    def correlation_id(self) -> Optional[str]:
        return self.headers.get(HEADER_CORRELATION_ID)

    @property
    def sender(self) -> Optional[str]:
        return self.headers.get(HEADER_SENDER)

    def header(self, name: str) -> Optional[str]:
        return self.headers.get(name)

    def __str__(self) -> str:
        return f"Message(q={self.qualifier}, cid={self.correlation_id}, data={self.data!r})"
