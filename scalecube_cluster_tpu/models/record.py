"""Membership record + the precedence ("overrides") lattice.

Behavioral parity with reference ``MembershipRecord.isOverrides``
(``cluster/membership/MembershipRecord.java:67-90``):

* against no existing record, only ALIVE / LEAVING are accepted;
* an identical record never overrides (idempotence);
* DEAD is absorbing: nothing overrides DEAD, DEAD overrides everything else;
* otherwise higher incarnation wins;
* at equal incarnation, SUSPECT overrides ALIVE / LEAVING (and nothing else).

This scalar implementation is the oracle for the vectorized lattice join in
``ops/lattice.py`` (property tests assert elementwise agreement).
"""

from __future__ import annotations

from dataclasses import dataclass

from .member import Member, MemberStatus


@dataclass(frozen=True)
class MembershipRecord:
    """A (member, status, incarnation) triple — one row of a membership table."""

    member: Member
    status: MemberStatus
    incarnation: int = 0

    @property
    def is_alive(self) -> bool:
        return self.status == MemberStatus.ALIVE

    @property
    def is_suspect(self) -> bool:
        return self.status == MemberStatus.SUSPECT

    @property
    def is_leaving(self) -> bool:
        return self.status == MemberStatus.LEAVING

    @property
    def is_dead(self) -> bool:
        return self.status == MemberStatus.DEAD

    def overrides(self, existing: "MembershipRecord | None") -> bool:
        """True if this record should replace ``existing`` in a membership table."""
        if existing is None:
            return self.is_alive or self.is_leaving
        if existing.member.id != self.member.id:
            raise ValueError("can't compare records for different members")
        if self == existing:
            return False
        if existing.is_dead:
            return False
        if self.is_dead:
            return True
        if self.incarnation == existing.incarnation:
            return self.is_suspect and (existing.is_alive or existing.is_leaving)
        return self.incarnation > existing.incarnation

    def __str__(self) -> str:
        return f"{{m: {self.member}, s: {self.status.name}, inc: {self.incarnation}}}"


def overrides_codes(
    new_status: int, new_inc: int, old_status: int, old_inc: int
) -> bool:
    """Pure-integer form of the overrides lattice (same truth table as
    :meth:`MembershipRecord.overrides` against a present record).

    This is the exact scalar function the vectorized kernel implements; kept
    here so tests can compare kernel output against it elementwise.
    """
    dead = MemberStatus.DEAD
    suspect = MemberStatus.SUSPECT
    if new_status == old_status and new_inc == old_inc:
        return False
    if old_status == dead:
        return False
    if new_status == dead:
        return True
    if new_inc == old_inc:
        return new_status == suspect and old_status in (
            MemberStatus.ALIVE,
            MemberStatus.LEAVING,
        )
    return new_inc > old_inc
