"""Member identity and status model.

Capability parity with the reference's ``Member`` (cluster-api
``io/scalecube/cluster/Member.java:16``) and ``MemberStatus``
(``cluster/membership/MemberStatus.java:3-18``): a member is identified by
``(id, address, namespace)``; ``alias`` is display-only and excluded from
equality, exactly as the reference excludes it (``Member.java:88-102``).

In simulation mode members are integer rows of state tensors; ``Member`` is
the host-side handle with an ``id <-> row`` mapping kept by the sim bridge.
"""

from __future__ import annotations

import enum
import uuid
from dataclasses import dataclass, field
from typing import Optional


class MemberStatus(enum.IntEnum):
    """Lifecycle states of a member in the SWIM state machine.

    Integer codes are the on-device encoding used by the vectorized kernel
    (``ops/lattice.py``); the ordering is chosen so DEAD is the lattice top.
    """

    ALIVE = 0
    SUSPECT = 1
    LEAVING = 2
    DEAD = 3


def new_member_id() -> str:
    """Default member-id generator (UUID4 string, reference ClusterConfig.java:36)."""
    return str(uuid.uuid4())


@dataclass(frozen=True)
class Member:
    """Cluster member: id + optional alias + address + namespace.

    Equality and hashing use ``(id, address, namespace)`` only — the alias is
    cosmetic (reference ``Member.java:88-111``).
    """

    id: str
    address: str
    namespace: str = "default"
    alias: Optional[str] = field(default=None, compare=False)

    def __post_init__(self) -> None:
        if not self.id:
            raise ValueError("member id must be non-empty")
        if not self.address:
            raise ValueError("member address must be non-empty")
        if not self.namespace:
            raise ValueError("member namespace must be non-empty")

    def __str__(self) -> str:
        name = self.alias if self.alias is not None else self.id
        return f"{self.namespace}:{name}@{self.address}"
