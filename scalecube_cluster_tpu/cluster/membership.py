"""SWIM membership protocol: suspicion, incarnation refutation, SYNC anti-entropy.

Behavioral parity with reference ``MembershipProtocolImpl``
(``cluster/membership/MembershipProtocolImpl.java:54-944``):

* startup: initial SYNC to all seeds, merge answers arriving within
  ``sync_timeout``, then periodic SYNC to one random seed-or-member every
  ``sync_interval`` (``start0`` :250-291, ``doSync`` :339-357,
  ``selectSyncAddress`` :461-472);
* core merge ``update_membership`` (:569-664): namespace relatedness gate
  (:511-536), precedence lattice (``MembershipRecord.overrides``) with the
  LEAVING exception (a LEAVING r0 is always re-processed), self-rumor
  refutation bumping own incarnation (``onSelfMemberDetected`` :686-708),
  SUSPECT scheduling the ``suspicion_mult*ceil_log2(N)*ping_interval`` timer
  (:805-823) that declares DEAD (:825-834), DEAD removing member + metadata
  and emitting REMOVED (:740-767), ALIVE accepted only after a successful
  metadata fetch (:636-658), LEAVING flow (:233-242, :710-733) including
  late-ALIVE-after-LEAVING (:666-684);
* every accepted non-gossip update is re-gossiped
  (``spreadMembershipGossipUnlessGossiped`` :836-843);
* FD verdicts merge in via ``onFailureDetectorEvent`` (:418-449) — note
  ALIVE-after-SUSPECT triggers a SYNC to the member instead of a direct
  override; membership rumors via ``onMembershipGossip`` (:452-459).

Vectorized analogue: ``ops/kernel.py``'s merge/suspicion phases — an elementwise
lattice join over N×N (status, incarnation) tensors, suspicion timers a
deadline matrix compared against the tick counter.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

from ..config import ClusterConfig
from ..models.events import FailureDetectorEvent, MembershipEvent
from ..models.member import Member, MemberStatus
from ..models.message import (
    HEADER_CORRELATION_ID,
    Message,
    Q_MEMBERSHIP_GOSSIP,
    Q_MEMBERSHIP_SYNC,
    Q_MEMBERSHIP_SYNC_ACK,
    new_correlation_id,
)
from ..models.record import MembershipRecord
from ..transport.api import Transport
from ..utils.cluster_math import suspicion_timeout
from ..utils.namespaces import are_namespaces_related
from ..utils.streams import EventStream
from .gossip import GossipProtocol
from .metadata import MetadataStore

_log = logging.getLogger(__name__)


class MembershipUpdateReason(enum.Enum):
    """Reference MembershipProtocolImpl update reasons enum (:58-64)."""

    FAILURE_DETECTOR_EVENT = "fd"
    MEMBERSHIP_GOSSIP = "gossip"
    SYNC = "sync"
    INITIAL_SYNC = "initial-sync"
    SUSPICION_TIMEOUT = "suspicion-timeout"


@dataclass(frozen=True)
class SyncData:
    """Full-table SYNC payload (reference SyncData.java:18)."""

    membership: List[MembershipRecord]


class MembershipProtocol:
    """One node's membership component."""

    def __init__(
        self,
        local_member: Member,
        transport: Transport,
        config: ClusterConfig,
        seed_members: Sequence[str],
        failure_detector_events: EventStream,
        gossip: GossipProtocol,
        metadata_store: MetadataStore,
        rng: Optional[random.Random] = None,
    ) -> None:
        self._local = local_member
        self._transport = transport
        self._config = config
        self._m_config = config.membership
        self._rng = rng or random.Random()
        self._gossip = gossip
        self._metadata_store = metadata_store

        # Protocol state (reference :88-91)
        self._membership_table: Dict[str, MembershipRecord] = {
            local_member.id: MembershipRecord(local_member, MemberStatus.ALIVE, 0)
        }
        self._members: Dict[str, Member] = {local_member.id: local_member}
        self._alive_emitted: Set[str] = set()
        self._removed_history: List[MembershipEvent] = []
        self._suspicion_tasks: Dict[str, asyncio.TimerHandle] = {}

        # Exclude own address from seeds (reference cleanup of self-seed)
        self._seed_members = [a for a in seed_members if a != local_member.address]

        self._events: EventStream = EventStream()
        self._events.subscribe(self._on_member_removed)
        self._sync_task: Optional[asyncio.Task] = None
        self._inflight: Set[asyncio.Task] = set()
        self._stopped = False
        self._unsubs = [
            transport.listen().subscribe(self._on_message),
            failure_detector_events.subscribe(self._on_failure_detector_event),
            gossip.listen().subscribe(self._on_gossip_message),
        ]

    # -- accessors ---------------------------------------------------------
    def listen(self) -> EventStream:
        """Stream of :class:`MembershipEvent`."""
        return self._events

    def members(self) -> List[Member]:
        return list(self._members.values())

    def other_members(self) -> List[Member]:
        return [m for m in self._members.values() if m.id != self._local.id]

    def member(self, member_id: str) -> Optional[Member]:
        return self._members.get(member_id)

    def member_by_address(self, address: str) -> Optional[Member]:
        for m in self._members.values():
            if m.address == address:
                return m
        return None

    def membership_records(self) -> List[MembershipRecord]:
        return list(self._membership_table.values())

    @property
    def incarnation(self) -> int:
        return self._membership_table[self._local.id].incarnation

    def alive_members(self) -> List[Member]:
        return [r.member for r in self._membership_table.values() if r.is_alive]

    def suspected_members(self) -> List[Member]:
        return [r.member for r in self._membership_table.values() if r.is_suspect]

    def removed_members(self) -> List[Member]:
        return [e.member for e in self._removed_history]

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        """Initial sync with all seeds, then periodic sync (start0 :250-291)."""
        if not self._seed_members:
            self._schedule_periodic_sync()
            return
        _log.info("[%s] initial sync to seeds: %s", self._local, self._seed_members)
        msg = self._prepare_sync_message(Q_MEMBERSHIP_SYNC, new_correlation_id(self._local.id))
        tasks = [
            asyncio.ensure_future(
                self._transport.request_response(seed, msg, timeout=self._m_config.sync_timeout)
            )
            for seed in self._seed_members
        ]
        done, pending = await asyncio.wait(tasks, timeout=self._m_config.sync_timeout)
        for task in pending:
            task.cancel()
        for task in done:
            if task.cancelled() or task.exception() is not None:
                continue
            ack = task.result()
            sync_data: SyncData = ack.data
            await self._sync_membership(sync_data, on_start=True)
        self._schedule_periodic_sync()

    def stop(self) -> None:
        self._stopped = True
        for unsub in self._unsubs:
            unsub()
        if self._sync_task is not None:
            self._sync_task.cancel()
        for handle in self._suspicion_tasks.values():
            handle.cancel()
        self._suspicion_tasks.clear()
        for task in list(self._inflight):
            task.cancel()

    async def leave(self) -> None:
        """Graceful leave: bump incarnation, gossip LEAVING (leaveCluster :233-242)."""
        r0 = self._membership_table[self._local.id]
        r1 = MembershipRecord(self._local, MemberStatus.LEAVING, r0.incarnation + 1)
        self._membership_table[self._local.id] = r1
        await self._spread_membership_gossip(r1)

    async def update_incarnation(self) -> None:
        """Bump own incarnation and gossip it — carries metadata updates to
        peers (reference MembershipProtocol.updateIncarnation)."""
        r0 = self._membership_table[self._local.id]
        r1 = MembershipRecord(self._local, r0.status, r0.incarnation + 1)
        self._membership_table[self._local.id] = r1
        await self._spread_membership_gossip(r1)

    # -- periodic sync (doSync :339-357) -----------------------------------
    def _schedule_periodic_sync(self) -> None:
        if not self._stopped:
            self._sync_task = asyncio.ensure_future(self._sync_loop())

    async def _sync_loop(self) -> None:
        while True:
            await asyncio.sleep(self._m_config.sync_interval)
            address = self._select_sync_address()
            if address is None:
                continue
            msg = self._prepare_sync_message(Q_MEMBERSHIP_SYNC, None)
            await self._send_quietly(address, msg)

    def _select_sync_address(self) -> Optional[str]:
        addresses = list(
            {*self._seed_members, *(m.address for m in self.other_members())}
        )
        if not addresses:
            return None
        return self._rng.choice(addresses)

    def _prepare_sync_message(self, qualifier: str, cid: Optional[str]) -> Message:
        data = SyncData(self.membership_records())
        msg = Message.with_data(data, qualifier=qualifier)
        if cid is not None:
            msg = msg.with_header(HEADER_CORRELATION_ID, cid)
        return msg

    # -- message handlers --------------------------------------------------
    def _on_message(self, message: Message) -> None:
        q = message.qualifier
        if q == Q_MEMBERSHIP_SYNC:
            self._spawn(self._on_sync(message))
        elif q == Q_MEMBERSHIP_SYNC_ACK and message.correlation_id is None:
            # cid-carrying SYNC_ACKs are consumed by request_response futures
            self._spawn(self._sync_membership(message.data, on_start=False))

    async def _on_sync(self, message: Message) -> None:
        """Merge incoming table, reply with own table (onSync :394-415)."""
        sender = message.sender
        await self._sync_membership(message.data, on_start=False)
        if sender is None:
            return
        reply = self._prepare_sync_message(Q_MEMBERSHIP_SYNC_ACK, message.correlation_id)
        await self._send_quietly(sender, reply)

    async def _sync_membership(self, sync_data: SyncData, on_start: bool) -> None:
        reason = (
            MembershipUpdateReason.INITIAL_SYNC if on_start else MembershipUpdateReason.SYNC
        )
        for record in sync_data.membership:
            try:
                await self.update_membership(record, reason)
            except Exception as exc:  # noqa: BLE001
                _log.debug("[%s][syncMembership][%s] error: %s", self._local, reason, exc)

    def _on_failure_detector_event(self, event: FailureDetectorEvent) -> None:
        """(onFailureDetectorEvent :418-449)"""
        r0 = self._membership_table.get(event.member.id)
        if r0 is None or r0.status == event.status:
            return
        _log.debug("[%s] fd status change: %s", self._local, event)
        if event.status == MemberStatus.ALIVE:
            # ALIVE won't override SUSPECT; send SYNC to force the member to
            # re-spread ALIVE with a bumped incarnation (reference :427-442).
            msg = self._prepare_sync_message(Q_MEMBERSHIP_SYNC, None)
            self._spawn(self._send_quietly(event.member.address, msg))
        else:
            record = MembershipRecord(r0.member, event.status, r0.incarnation)
            self._spawn(
                self.update_membership(record, MembershipUpdateReason.FAILURE_DETECTOR_EVENT)
            )

    def _on_gossip_message(self, message: Message) -> None:
        """(onMembershipGossip :452-459)"""
        if message.qualifier == Q_MEMBERSHIP_GOSSIP:
            record: MembershipRecord = message.data
            self._spawn(self.update_membership(record, MembershipUpdateReason.MEMBERSHIP_GOSSIP))

    # -- the core merge (updateMembership :569-664) ------------------------
    async def update_membership(
        self, r1: MembershipRecord, reason: MembershipUpdateReason
    ) -> None:
        if r1 is None:
            raise ValueError("membership record can't be None")
        # Namespace gate
        if not are_namespaces_related(self._m_config.namespace, r1.member.namespace):
            _log.debug(
                "[%s][updateMembership][%s] skipping, namespace mismatch: %s vs %s",
                self._local, reason, self._m_config.namespace, r1.member.namespace,
            )
            return

        r0 = self._membership_table.get(r1.member.id)

        # If r0 is LEAVING we process the update regardless of precedence
        if (r0 is None or not r0.is_leaving) and not r1.overrides(r0):
            return

        # Update about the local member: refute by incarnation bump
        if r1.member.address == self._local.address:
            if r1.member.id == self._local.id:
                self._on_self_member_detected(r0, r1, reason)
            return

        if r1.is_leaving:
            await self._on_leaving_detected(r0, r1)
            return

        if r1.is_dead:
            self._on_dead_member_detected(r1)
            return

        if r1.is_suspect:
            if r0 is None or not r0.is_leaving:
                self._membership_table[r1.member.id] = r1
            self._schedule_suspicion_timeout(r1)
            self._spread_gossip_unless_gossiped(r1, reason)
            return

        if r1.is_alive:
            if r0 is not None and r0.is_leaving:
                self._on_alive_after_leaving(r1)
                return
            if r0 is None or r0.incarnation < r1.incarnation:
                try:
                    metadata1 = await self._metadata_store.fetch_metadata(r1.member)
                except Exception as exc:  # noqa: BLE001
                    _log.warning(
                        "[%s][updateMembership][%s] skipping add/update of %s: "
                        "metadata fetch failed (%s)",
                        self._local, reason, r1, exc,
                    )
                    return
                # Metadata received -> member is genuinely alive
                self._cancel_suspicion_timeout(r1.member.id)
                self._spread_gossip_unless_gossiped(r1, reason)
                metadata0 = self._metadata_store.update_metadata(r1.member, metadata1)
                self._on_alive_member_detected(r1, metadata0, metadata1)

    # -- state-machine tails -----------------------------------------------
    def _on_self_member_detected(
        self, r0: MembershipRecord, r1: MembershipRecord, reason: MembershipUpdateReason
    ) -> None:
        """Refutation: bump incarnation, re-gossip own record
        (onSelfMemberDetected :686-708)."""
        incarnation = max(r0.incarnation, r1.incarnation)
        r2 = MembershipRecord(self._local, r0.status, incarnation + 1)
        self._membership_table[self._local.id] = r2
        _log.debug(
            "[%s][updateMembership][%s] refuting %s with %s", self._local, reason, r1, r2
        )
        self._spawn(self._spread_membership_gossip(r2))

    def _on_alive_after_leaving(self, r1: MembershipRecord) -> None:
        """Late ALIVE when LEAVING already known (onAliveAfterLeaving :666-684)."""
        member = r1.member
        self._members[member.id] = member
        if member.id not in self._alive_emitted:
            self._alive_emitted.add(member.id)
            self._publish(MembershipEvent.added(member, None))
            self._publish(MembershipEvent.leaving(member, None))

    async def _on_leaving_detected(
        self, r0: Optional[MembershipRecord], r1: MembershipRecord
    ) -> None:
        """(onLeavingDetected :710-733)"""
        member = r1.member
        self._membership_table[member.id] = r1
        if r0 is not None and (
            r0.is_alive or (r0.is_suspect and member.id in self._alive_emitted)
        ):
            metadata = self._metadata_store.member_metadata(member)
            self._publish(MembershipEvent.leaving(member, metadata))
        if r0 is None or not r0.is_leaving:
            self._schedule_suspicion_timeout(r1)
            await self._spread_membership_gossip(r1)

    def _on_dead_member_detected(self, r1: MembershipRecord) -> None:
        """(onDeadMemberDetected :740-767)"""
        member = r1.member
        self._cancel_suspicion_timeout(member.id)
        if member.id not in self._members:
            return
        del self._members[member.id]
        r0 = self._membership_table.pop(member.id)
        metadata = self._metadata_store.remove_metadata(member)
        self._alive_emitted.discard(member.id)
        if r0.is_leaving:
            _log.info("[%s] member left gracefully: %s", self._local, member)
        else:
            _log.info("[%s] member left without notification: %s", self._local, member)
        self._publish(MembershipEvent.removed(member, metadata))

    def _on_alive_member_detected(
        self, r1: MembershipRecord, metadata0: Optional[bytes], metadata1: bytes
    ) -> None:
        """(onAliveMemberDetected :769-795)"""
        member = r1.member
        exists = member.id in self._members
        event: Optional[MembershipEvent] = None
        if not exists:
            event = MembershipEvent.added(member, metadata1)
        elif metadata1 != metadata0:
            event = MembershipEvent.updated(member, metadata0, metadata1)
        self._members[member.id] = member
        self._membership_table[member.id] = r1
        if event is not None:
            self._publish(event)
            if event.is_added:
                self._alive_emitted.add(member.id)

    # -- suspicion timers (scheduleSuspicionTimeoutTask :805-823) ----------
    def _schedule_suspicion_timeout(self, record: MembershipRecord) -> None:
        member_id = record.member.id
        if member_id in self._suspicion_tasks:
            return
        timeout = suspicion_timeout(
            self._m_config.suspicion_mult,
            len(self._membership_table),
            self._config.failure_detector.ping_interval,
        )
        _log.debug("[%s] scheduled suspicion timeout %.3fs for %s", self._local, timeout, member_id)
        loop = asyncio.get_event_loop()
        self._suspicion_tasks[member_id] = loop.call_later(
            timeout, self._on_suspicion_timeout, member_id
        )

    def _cancel_suspicion_timeout(self, member_id: str) -> None:
        handle = self._suspicion_tasks.pop(member_id, None)
        if handle is not None:
            handle.cancel()

    def _on_suspicion_timeout(self, member_id: str) -> None:
        """(onSuspicionTimeout :825-834)"""
        self._suspicion_tasks.pop(member_id, None)
        record = self._membership_table.get(member_id)
        if record is not None:
            _log.debug("[%s] declaring suspected member %s DEAD", self._local, record)
            dead = MembershipRecord(record.member, MemberStatus.DEAD, record.incarnation)
            self._spawn(self.update_membership(dead, MembershipUpdateReason.SUSPICION_TIMEOUT))

    # -- gossip spread -----------------------------------------------------
    def _spread_gossip_unless_gossiped(
        self, record: MembershipRecord, reason: MembershipUpdateReason
    ) -> None:
        """(spreadMembershipGossipUnlessGossiped :836-843)"""
        if reason not in (
            MembershipUpdateReason.MEMBERSHIP_GOSSIP,
            MembershipUpdateReason.INITIAL_SYNC,
        ):
            self._spawn(self._spread_membership_gossip(record))

    async def _spread_membership_gossip(self, record: MembershipRecord) -> None:
        msg = Message.with_data(record, qualifier=Q_MEMBERSHIP_GOSSIP)
        self._gossip.spread(msg)  # future resolution not awaited, as in reference

    # -- misc --------------------------------------------------------------
    def _publish(self, event: MembershipEvent) -> None:
        _log.info("[%s][publishEvent] %s", self._local, event)
        self._events.emit(event)

    def _on_member_removed(self, event: MembershipEvent) -> None:
        """Removed-members ring (onMemberRemoved :934-943)."""
        if not event.is_removed:
            return
        size = self._m_config.removed_members_history_size
        if size <= 0:
            return
        self._removed_history.append(event)
        if len(self._removed_history) > size:
            self._removed_history.pop(0)

    def _spawn(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def _send_quietly(self, address: str, message: Message) -> None:
        try:
            await self._transport.send(address, message)
        except Exception as exc:  # noqa: BLE001
            _log.debug("[%s] failed to send %s to %s: %s", self._local, message.qualifier, address, exc)
