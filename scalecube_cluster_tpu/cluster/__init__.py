from .cluster import Cluster, ClusterMessageHandler, SenderAwareTransport, new_cluster
from .failure_detector import AckType, FailureDetector, PingData
from .gossip import Gossip, GossipProtocol, GossipRequest, GossipState
from .membership import MembershipProtocol, MembershipUpdateReason, SyncData
from .metadata import GetMetadataRequest, GetMetadataResponse, MetadataStore

__all__ = [
    "Cluster",
    "ClusterMessageHandler",
    "SenderAwareTransport",
    "new_cluster",
    "FailureDetector",
    "PingData",
    "AckType",
    "GossipProtocol",
    "Gossip",
    "GossipState",
    "GossipRequest",
    "MembershipProtocol",
    "MembershipUpdateReason",
    "SyncData",
    "MetadataStore",
    "GetMetadataRequest",
    "GetMetadataResponse",
]
