"""Metadata store: local metadata + cache of remote members' metadata.

Behavioral parity with reference ``MetadataStoreImpl``
(``cluster/metadata/MetadataStoreImpl.java:22-251``): serialized metadata
blobs cached per member; remote fetch over ``GET_METADATA_REQ/RESP``
request-response with ``metadata_timeout`` (``fetchMetadata`` :146-185); own
metadata served on request only when the requested member id matches
(``onMetadataRequest`` :201-240).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Any, Dict, Optional, Set

from ..models.member import Member
from ..models.message import (
    HEADER_CORRELATION_ID,
    Message,
    Q_METADATA_REQ,
    Q_METADATA_RESP,
)
from ..transport.api import Transport
from ..transport.codecs import MetadataCodec

_log = logging.getLogger(__name__)


@dataclass(frozen=True)
class GetMetadataRequest:
    """Reference GetMetadataRequest.java:12."""

    member: Member


@dataclass(frozen=True)
class GetMetadataResponse:
    """Reference GetMetadataResponse.java:15."""

    member: Member
    metadata: bytes


class MetadataStore:
    """One node's metadata component."""

    def __init__(
        self,
        local_member: Member,
        transport: Transport,
        codec: MetadataCodec,
        initial_metadata: Any,
        metadata_timeout: float,
    ) -> None:
        self._local = local_member
        self._transport = transport
        self._codec = codec
        self._metadata_timeout = metadata_timeout
        self._local_metadata: Optional[Any] = initial_metadata
        self._cache: Dict[str, bytes] = {}
        self._inflight: Set[asyncio.Task] = set()
        self._unsub = transport.listen().subscribe(self._on_message)

    def start(self) -> None:  # symmetry with other components
        pass

    def stop(self) -> None:
        self._unsub()
        for task in list(self._inflight):
            task.cancel()
        self._cache.clear()

    # -- local metadata ----------------------------------------------------
    def metadata(self) -> Optional[Any]:
        return self._local_metadata

    def update_local_metadata(self, metadata: Any) -> Optional[Any]:
        previous, self._local_metadata = self._local_metadata, metadata
        return previous

    # -- remote cache ------------------------------------------------------
    def member_metadata(self, member: Member) -> Optional[bytes]:
        return self._cache.get(member.id)

    def update_metadata(self, member: Member, metadata: bytes) -> Optional[bytes]:
        """Cache serialized metadata of a remote member; returns previous."""
        if member.id == self._local.id:
            raise ValueError("use update_local_metadata for the local member")
        previous = self._cache.get(member.id)
        self._cache[member.id] = metadata
        return previous

    def remove_metadata(self, member: Member) -> Optional[bytes]:
        return self._cache.pop(member.id, None)

    # -- rpc ---------------------------------------------------------------
    async def fetch_metadata(self, member: Member) -> bytes:
        """Fetch serialized metadata from ``member`` (fetchMetadata :146-185)."""
        request = Message.with_data(GetMetadataRequest(member), qualifier=Q_METADATA_REQ)
        response = await self._transport.request_response(
            member.address, request, timeout=self._metadata_timeout
        )
        data: GetMetadataResponse = response.data
        return data.metadata

    def _on_message(self, message: Message) -> None:
        if message.qualifier != Q_METADATA_REQ:
            return
        request: GetMetadataRequest = message.data
        if request.member.id != self._local.id:
            # Request for a different (restarted?) member on this address —
            # ignore; issuer's fetch times out (onMetadataRequest :201-240).
            _log.debug(
                "[%s] ignoring metadata request for %s", self._local, request.member
            )
            return
        blob = self.serialize_local()
        response = Message.with_data(
            GetMetadataResponse(self._local, blob), qualifier=Q_METADATA_RESP
        )
        if message.correlation_id is not None:
            response = response.with_header(HEADER_CORRELATION_ID, message.correlation_id)
        sender = message.sender
        if sender is None:
            return
        task = asyncio.ensure_future(self._send_quietly(sender, response))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    def serialize_local(self) -> bytes:
        return self._codec.serialize(self._local_metadata)

    def deserialize(self, blob: bytes) -> Any:
        return self._codec.deserialize(blob)

    async def _send_quietly(self, address: str, message: Message) -> None:
        try:
            await self._transport.send(address, message)
        except Exception as exc:  # noqa: BLE001
            _log.debug("[%s] failed to send metadata resp to %s: %s", self._local, address, exc)
