"""Infection-style gossip dissemination.

Behavioral parity with reference ``GossipProtocolImpl``
(``cluster/gossip/GossipProtocolImpl.java:32-387``):

* ``spread()`` enqueues a rumor with a local monotone ``sequence_id``
  (``createAndPutGossip`` :190-199) and resolves once the rumor has most
  likely disseminated (``:360-368``);
* every ``gossip_interval`` pick ``gossip_fanout`` members via a shuffled
  sliding window (``selectGossipMembers`` :322-343) and send every rumor not
  yet known-infected at the peer and younger than
  ``repeat_mult * ceil_log2(N)`` periods (``selectGossipsToSend`` :311-320);
* receivers dedup by ``(gossiper_id, sequence_id)`` via
  :class:`SequenceIdCollector` and mark the sender infected
  (``onGossipReq`` :201-215);
* rumors are swept after ``2*(spread+1)`` periods (``getGossipsToRemove``
  :350-358); too many dedup gaps triggers the segmentation warning
  (``checkGossipSegmentation`` :217-236).

Vectorized analogue: ``ops/kernel.py``'s gossip phase — rumor state as (slots × N)
infection bitmaps, fanout-sample + scatter per tick, dedup as bitmap OR.
"""

from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..config import GossipConfig
from ..models.events import MembershipEvent
from ..models.member import Member
from ..models.message import Message, Q_GOSSIP_REQ
from ..transport.api import Transport
from ..utils.cluster_math import gossip_periods_to_spread, gossip_periods_to_sweep
from ..utils.intervals import SequenceIdCollector
from ..utils.streams import EventStream

_log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Gossip:
    """One rumor (reference Gossip.java:12-29): origin id, per-origin sequence
    id, and the user message payload."""

    gossiper_id: str
    sequence_id: int
    message: Message

    @property
    def gossip_id(self) -> str:
        return f"{self.gossiper_id}-{self.sequence_id}"


@dataclass
class GossipState:
    """Local bookkeeping for one rumor (reference GossipState.java:18):
    period at which this node got infected + peers known infected."""

    gossip: Gossip
    infection_period: int
    infected: Set[str] = field(default_factory=set)


@dataclass(frozen=True)
class GossipRequest:
    """Wire payload of one GOSSIP_REQ (reference GossipRequest.java:14)."""

    gossips: List[Gossip]
    from_id: str


class GossipProtocol:
    """One node's gossip component."""

    def __init__(
        self,
        local_member: Member,
        transport: Transport,
        membership_events: EventStream,
        config: GossipConfig,
        rng: Optional[random.Random] = None,
    ) -> None:
        self._local = local_member
        self._transport = transport
        self._config = config
        self._rng = rng or random.Random()
        self._messages: EventStream = EventStream()  # delivered rumor payloads
        self._remote_members: List[Member] = []
        self._remote_members_index = -1
        self._current_period = 0
        self._sequence_id = 0
        self._gossips: Dict[str, GossipState] = {}
        self._futures: Dict[str, asyncio.Future] = {}
        self._collectors: Dict[str, SequenceIdCollector] = {}
        self._loop_task: Optional[asyncio.Task] = None
        self._inflight: Set[asyncio.Task] = set()
        self._unsubs = [
            transport.listen().subscribe(self._on_message),
            membership_events.subscribe(self._on_member_event),
        ]

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._loop_task = asyncio.ensure_future(self._run())

    def stop(self) -> None:
        for unsub in self._unsubs:
            unsub()
        if self._loop_task is not None:
            self._loop_task.cancel()
        for task in list(self._inflight):
            task.cancel()
        for fut in self._futures.values():
            if not fut.done():
                fut.cancel()

    def listen(self) -> EventStream:
        """Stream of delivered rumor payload messages."""
        return self._messages

    @property
    def current_period(self) -> int:
        return self._current_period

    # -- spread API --------------------------------------------------------
    def spread(self, message: Message) -> "asyncio.Future[str]":
        """Enqueue a rumor; the future resolves to the gossip id once it has
        most likely disseminated (reference spread :126-130)."""
        gossip = Gossip(self._local.id, self._sequence_id, message)
        self._sequence_id += 1
        state = GossipState(gossip, self._current_period)
        self._gossips[gossip.gossip_id] = state
        self._ensure_collector(self._local.id).add(gossip.sequence_id)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._futures[gossip.gossip_id] = fut
        return fut

    # -- periodic spread loop (doSpreadGossip :141-184) --------------------
    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self._config.gossip_interval)
            self._do_spread_gossip()

    def _do_spread_gossip(self) -> None:
        period = self._current_period
        self._current_period += 1
        self._check_segmentation()
        if not self._gossips:
            return
        for member in self._select_gossip_members():
            self._spread_gossips_to(period, member)
        # sweep
        for gossip_id in self._gossips_to_remove(period):
            del self._gossips[gossip_id]
        # complete spread futures
        for gossip_id in self._gossips_that_most_likely_disseminated(period):
            fut = self._futures.pop(gossip_id, None)
            if fut is not None and not fut.done():
                fut.set_result(gossip_id)

    def _spread_gossips_to(self, period: int, member: Member) -> None:
        gossips = self._select_gossips_to_send(period, member)
        if not gossips:
            return
        request = GossipRequest(gossips, self._local.id)
        msg = Message.with_data(request, qualifier=Q_GOSSIP_REQ)
        task = asyncio.ensure_future(self._send_quietly(member.address, msg))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def _send_quietly(self, address: str, message: Message) -> None:
        try:
            await self._transport.send(address, message)
        except Exception as exc:  # noqa: BLE001
            _log.debug("[%s] failed to send gossip to %s: %s", self._local, address, exc)

    # -- receive (onGossipReq :201-215) ------------------------------------
    def _on_message(self, message: Message) -> None:
        if message.qualifier != Q_GOSSIP_REQ:
            return
        period = self._current_period
        request: GossipRequest = message.data
        for gossip in request.gossips:
            if self._ensure_collector(gossip.gossiper_id).add(gossip.sequence_id):
                state = self._gossips.get(gossip.gossip_id)
                if state is None:  # new rumor: store + deliver
                    state = GossipState(gossip, period)
                    self._gossips[gossip.gossip_id] = state
                    self._messages.emit(gossip.message)
                state.infected.add(request.from_id)

    def _check_segmentation(self) -> None:
        threshold = self._config.gossip_segmentation_threshold
        for origin, collector in self._collectors.items():
            if collector.size() > threshold:
                _log.warning(
                    "[%s][%s] too many missed gossips from %s — node was suspected "
                    "for a long time or has connectivity problems; resetting dedup",
                    self._local,
                    self._current_period,
                    origin,
                )
                collector.clear()

    # -- membership feed (onMemberEvent :238-261) --------------------------
    def on_membership_event(self, event: MembershipEvent) -> None:
        self._on_member_event(event)

    def _on_member_event(self, event: MembershipEvent) -> None:
        member = event.member
        if event.is_removed:
            if member in self._remote_members:
                self._remote_members.remove(member)
            self._collectors.pop(member.id, None)
        if event.is_added and member.id != self._local.id:
            self._remote_members.append(member)

    # -- selection ---------------------------------------------------------
    def _select_gossip_members(self) -> List[Member]:
        fanout = self._config.gossip_fanout
        members = self._remote_members
        if len(members) < fanout:
            return list(members)
        if self._remote_members_index < 0 or self._remote_members_index + fanout > len(members):
            self._rng.shuffle(members)
            self._remote_members_index = 0
        selected = members[self._remote_members_index : self._remote_members_index + fanout]
        self._remote_members_index += fanout
        return selected

    def _select_gossips_to_send(self, period: int, member: Member) -> List[Gossip]:
        periods_to_spread = gossip_periods_to_spread(
            self._config.gossip_repeat_mult, len(self._remote_members) + 1
        )
        return [
            s.gossip
            for s in self._gossips.values()
            if s.infection_period + periods_to_spread >= period and member.id not in s.infected
        ]

    def _gossips_to_remove(self, period: int) -> List[str]:
        periods_to_sweep = gossip_periods_to_sweep(
            self._config.gossip_repeat_mult, len(self._remote_members) + 1
        )
        return [
            gid for gid, s in self._gossips.items() if period > s.infection_period + periods_to_sweep
        ]

    def _gossips_that_most_likely_disseminated(self, period: int) -> List[str]:
        periods_to_spread = gossip_periods_to_spread(
            self._config.gossip_repeat_mult, len(self._remote_members) + 1
        )
        return [
            gid
            for gid, s in self._gossips.items()
            if period > s.infection_period + periods_to_spread
        ]

    def _ensure_collector(self, origin_id: str) -> SequenceIdCollector:
        return self._collectors.setdefault(origin_id, SequenceIdCollector())

    # -- introspection (tests) ---------------------------------------------
    def gossip_segmentation(self, origin_id: str) -> int:
        collector = self._collectors.get(origin_id)
        return collector.size() if collector is not None else 0
