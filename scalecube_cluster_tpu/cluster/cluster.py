"""The ``Cluster`` facade: builds and wires transport + 4 protocol components.

Behavioral parity with reference ``ClusterImpl`` (``cluster/ClusterImpl.java``)
and the ``Cluster`` interface (``cluster-api/Cluster.java:10-151``):

* fluent copy-on-write configuration (``config/membership/gossip/
  failure_detector/transport`` lenses, ClusterImpl.java:143-226);
* start: validate config -> bind transport -> wrap SenderAwareTransport
  (stamps sender header, :556-604) -> create local member (with external
  host/port NAT mapping, :403-417) -> construct FD/gossip/metadata/membership
  -> start FD, gossip, metadata, handler, membership (order :301-307);
* user ``listen`` filtered from protocol traffic (SYSTEM_MESSAGES :62-76,
  filters :381-394);
* graceful shutdown: LEAVING gossip -> dispose components -> stop transport
  (``doShutdown`` :508-544);
* ``update_metadata`` = store update + incarnation bump (:497-501).

API surface: ``address, member(), members(), other_members(), member(id),
member_by_address(), metadata(), metadata_of(), update_metadata(), send(),
request_response(), spread_gossip(), listen_messages(), listen_gossip(),
listen_membership(), shutdown(), on_shutdown``.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Callable, List, Optional, Sequence

from ..config import ClusterConfig, Lens
from ..models.events import MembershipEvent
from ..models.member import Member
from ..models.message import (
    HEADER_SENDER,
    Message,
    SYSTEM_GOSSIP_QUALIFIERS,
    SYSTEM_QUALIFIERS,
)
from ..transport.api import Listeners, Transport, create_transport
from ..transport.codecs import metadata_codec
from ..utils.streams import EventStream
from .failure_detector import FailureDetector
from .gossip import GossipProtocol
from .membership import MembershipProtocol
from .metadata import MetadataStore

_log = logging.getLogger(__name__)


class ClusterMessageHandler:
    """User callback surface (reference ClusterMessageHandler.java:6-18).
    Subclass or pass plain callables to :meth:`Cluster.handler`."""

    def on_message(self, message: Message) -> None: ...

    def on_gossip(self, gossip: Message) -> None: ...

    def on_membership_event(self, event: MembershipEvent) -> None: ...


class SenderAwareTransport(Transport):
    """Stamps the sender header on every outbound message
    (reference ClusterImpl.SenderAwareTransport :556-604)."""

    def __init__(self, delegate: Transport):
        self._delegate = delegate

    @property
    def address(self) -> str:
        return self._delegate.address

    @property
    def is_stopped(self) -> bool:
        return self._delegate.is_stopped

    async def start(self) -> "SenderAwareTransport":
        await self._delegate.start()
        return self

    async def stop(self) -> None:
        await self._delegate.stop()

    async def send(self, address: str, message: Message) -> None:
        await self._delegate.send(address, message.with_header(HEADER_SENDER, self.address))

    def listen(self) -> Listeners:
        return self._delegate.listen()


class Cluster:
    """Facade over one cluster node (reference Cluster.java + ClusterImpl)."""

    def __init__(self, config: Optional[ClusterConfig] = None):
        self._config = config or ClusterConfig.default_lan()
        self._handler_factory: Optional[Callable[["Cluster"], ClusterMessageHandler]] = None
        self._transport_factory_fn: Optional[Callable[[], Transport]] = None
        self._started = False
        self._shutdown_event: Optional[asyncio.Event] = None
        # wired at start()
        self._transport: Optional[Transport] = None
        self._local_member: Optional[Member] = None
        self._failure_detector: Optional[FailureDetector] = None
        self._gossip: Optional[GossipProtocol] = None
        self._metadata_store: Optional[MetadataStore] = None
        self._membership: Optional[MembershipProtocol] = None
        self._unsubs: List[Callable[[], None]] = []

    # -- fluent config (copy-on-write, ClusterImpl.java:143-226) -----------
    def _with_config(self, config: ClusterConfig) -> "Cluster":
        clone = Cluster(config)
        clone._handler_factory = self._handler_factory
        clone._transport_factory_fn = self._transport_factory_fn
        return clone

    def config(self, op: Lens) -> "Cluster":
        return self._with_config(op(self._config))

    def membership(self, op: Lens) -> "Cluster":
        return self._with_config(self._config.with_membership(op))

    def gossip(self, op: Lens) -> "Cluster":
        return self._with_config(self._config.with_gossip(op))

    def failure_detector(self, op: Lens) -> "Cluster":
        return self._with_config(self._config.with_failure_detector(op))

    def transport(self, op: Lens) -> "Cluster":
        return self._with_config(self._config.with_transport(op))

    def transport_factory(self, factory: Callable[[], Transport]) -> "Cluster":
        """Inject a custom transport instance factory (testlib uses this to
        wrap transports in NetworkEmulatorTransport, reference BaseTest)."""
        clone = self._with_config(self._config)
        clone._transport_factory_fn = factory
        return clone

    def handler(self, factory: Callable[["Cluster"], ClusterMessageHandler]) -> "Cluster":
        clone = self._with_config(self._config)
        clone._handler_factory = factory
        return clone

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "Cluster":
        """Validate, bind, wire, join (doStart0 :249-312)."""
        if self._started:
            raise RuntimeError("cluster already started")
        config = self._config.validate()
        self._shutdown_event = asyncio.Event()

        raw = (
            self._transport_factory_fn()
            if self._transport_factory_fn is not None
            else create_transport(config.transport)
        )
        if raw.is_stopped:
            raise RuntimeError("injected transport is stopped")
        try:
            raw.address
            started = True
        except Exception:  # noqa: BLE001 - not yet started
            started = False
        if not started:
            await raw.start()
        transport = SenderAwareTransport(raw)
        self._transport = transport

        self._local_member = self._create_local_member(transport.address, config)
        rng = random.Random()

        fd_events: EventStream = EventStream()
        self._metadata_store = MetadataStore(
            self._local_member,
            transport,
            metadata_codec(config.metadata_codec),
            config.metadata,
            config.metadata_timeout,
        )
        self._failure_detector = FailureDetector(
            self._local_member, transport, EventStream(), config.failure_detector, rng
        )
        self._gossip = GossipProtocol(
            self._local_member, transport, EventStream(), config.gossip, rng
        )
        self._membership = MembershipProtocol(
            self._local_member,
            transport,
            config,
            config.membership.seed_members,
            self._failure_detector.listen(),
            self._gossip,
            self._metadata_store,
            rng,
        )
        # FD and gossip follow membership events (constructor wiring in
        # reference ClusterImpl.java:260-291)
        self._unsubs.append(
            self._membership.listen().subscribe(self._failure_detector.on_membership_event)
        )
        self._unsubs.append(
            self._membership.listen().subscribe(self._gossip.on_membership_event)
        )

        # Start order (reference :301-307): FD, gossip, metadata, handler, membership
        self._failure_detector.start()
        self._gossip.start()
        self._metadata_store.start()
        self._wire_handler()
        await self._membership.start()
        self._started = True
        return self

    def start_await(self) -> "Cluster":
        """Blocking start (reference startAwait :241-243)."""
        return asyncio.get_event_loop().run_until_complete(self.start())

    def _create_local_member(self, address: str, config: ClusterConfig) -> Member:
        """(createLocalMember :403-417 incl. external host/port NAT)"""
        member_id = config.member_id_generator()
        if config.external_host is not None or config.external_port is not None:
            scheme, _, rest = address.partition("://")
            host, _, port = rest.rpartition(":")
            host = config.external_host or host
            port = str(config.external_port) if config.external_port is not None else port
            address = f"{scheme}://{host}:{port}"
        return Member(
            id=member_id,
            address=address,
            namespace=config.membership.namespace,
            alias=config.member_alias,
        )

    def _wire_handler(self) -> None:
        """System-message filtering so user streams never see protocol traffic
        (SYSTEM_MESSAGES :62-76, listen filters :381-394)."""
        handler = self._handler_factory(self) if self._handler_factory else None

        def on_message(msg: Message) -> None:
            if msg.qualifier in SYSTEM_QUALIFIERS:
                return
            self._user_messages.emit(msg)
            if handler is not None:
                try:
                    handler.on_message(msg)
                except Exception:  # noqa: BLE001
                    _log.exception("user on_message failed")

        def on_gossip(msg: Message) -> None:
            if msg.qualifier in SYSTEM_GOSSIP_QUALIFIERS:
                return
            self._user_gossip.emit(msg)
            if handler is not None:
                try:
                    handler.on_gossip(msg)
                except Exception:  # noqa: BLE001
                    _log.exception("user on_gossip failed")

        def on_membership(event: MembershipEvent) -> None:
            if handler is not None:
                try:
                    handler.on_membership_event(event)
                except Exception:  # noqa: BLE001
                    _log.exception("user on_membership_event failed")

        self._user_messages = EventStream()
        self._user_gossip = EventStream()
        self._unsubs.append(self._transport.listen().subscribe(on_message))
        self._unsubs.append(self._gossip.listen().subscribe(on_gossip))
        self._unsubs.append(self._membership.listen().subscribe(on_membership))

    async def shutdown(self) -> None:
        """Graceful: LEAVING gossip -> brief grace for dissemination ->
        dispose components -> stop transport (doShutdown :508-544)."""
        if not self._started:
            return
        self._started = False
        _log.info("[%s] shutting down", self._local_member)
        try:
            await self._membership.leave()
            # Give the LEAVING rumor a couple of gossip periods to spread
            await asyncio.sleep(2 * self._config.gossip.gossip_interval)
        except Exception as exc:  # noqa: BLE001
            _log.warning("[%s] leave failed: %s", self._local_member, exc)
        for unsub in self._unsubs:
            unsub()
        self._metadata_store.stop()
        self._membership.stop()
        self._gossip.stop()
        self._failure_detector.stop()
        await self._transport.stop()
        self._shutdown_event.set()
        _log.info("[%s] shutdown complete", self._local_member)

    @property
    def on_shutdown(self) -> asyncio.Event:
        return self._shutdown_event

    # -- introspection -----------------------------------------------------
    def _require_started(self):
        if self._membership is None:
            raise RuntimeError("cluster is not started")

    @property
    def address(self) -> str:
        self._require_started()
        return self._local_member.address

    def member(self) -> Member:
        self._require_started()
        return self._local_member

    def members(self) -> List[Member]:
        self._require_started()
        return self._membership.members()

    def other_members(self) -> List[Member]:
        self._require_started()
        return self._membership.other_members()

    def member_by_id(self, member_id: str) -> Optional[Member]:
        self._require_started()
        return self._membership.member(member_id)

    def member_by_address(self, address: str) -> Optional[Member]:
        self._require_started()
        return self._membership.member_by_address(address)

    # -- metadata ----------------------------------------------------------
    def metadata(self) -> Optional[Any]:
        self._require_started()
        return self._metadata_store.metadata()

    def metadata_of(self, member: Member) -> Optional[Any]:
        self._require_started()
        if member.id == self._local_member.id:
            return self.metadata()
        blob = self._metadata_store.member_metadata(member)
        return None if blob is None else self._metadata_store.deserialize(blob)

    async def update_metadata(self, metadata: Any) -> None:
        """(ClusterImpl.updateMetadata :497-501)"""
        self._require_started()
        self._metadata_store.update_local_metadata(metadata)
        await self._membership.update_incarnation()

    # -- messaging ---------------------------------------------------------
    async def send(self, target: "Member | str", message: Message) -> None:
        self._require_started()
        address = target.address if isinstance(target, Member) else target
        await self._transport.send(address, message)

    async def request_response(
        self, target: "Member | str", request: Message, timeout: float = 3.0
    ) -> Message:
        self._require_started()
        address = target.address if isinstance(target, Member) else target
        return await self._transport.request_response(address, request, timeout)

    def spread_gossip(self, message: Message) -> "asyncio.Future[str]":
        self._require_started()
        return self._gossip.spread(message)

    # -- streams -----------------------------------------------------------
    def listen_messages(self) -> EventStream:
        self._require_started()
        return self._user_messages

    def listen_gossip(self) -> EventStream:
        self._require_started()
        return self._user_gossip

    def listen_membership(self) -> EventStream:
        self._require_started()
        return self._membership.listen()

    # -- test/monitor hooks (reference getMembershipRecords etc.) ----------
    @property
    def membership_protocol(self) -> MembershipProtocol:
        self._require_started()
        return self._membership

    @property
    def gossip_protocol(self) -> GossipProtocol:
        self._require_started()
        return self._gossip

    @property
    def failure_detector_component(self) -> FailureDetector:
        self._require_started()
        return self._failure_detector

    @property
    def metadata_store(self) -> MetadataStore:
        self._require_started()
        return self._metadata_store

    @property
    def transport_instance(self) -> Transport:
        self._require_started()
        return self._transport

    def transport_events(self) -> Optional[Listeners]:
        """The underlying transport's lifecycle-event stream (reconnect
        backoff / give-up, connection loss — stream transports only; None
        for transports without one). The r8 telemetry bus attaches here:
        ``bus.attach_cluster(cluster)`` merges these with membership events
        into the unified tick-stamped record stream."""
        self._require_started()
        # unwrap the decorator chain (SenderAwareTransport, and e.g. a
        # NetworkEmulator wrapper under it) until some layer carries the
        # event stream — the real wire transport may sit several deep
        transport = self._transport
        while transport is not None:
            fn = getattr(transport, "transport_events", None)
            if fn is not None:
                return fn()
            transport = getattr(transport, "_delegate", None)
        return None


def new_cluster(config: Optional[ClusterConfig] = None) -> Cluster:
    """Entry point mirroring ``new ClusterImpl()``."""
    return Cluster(config)
