"""SWIM failure detector: periodic random probe + indirect probes via relays.

Behavioral parity with reference ``FailureDetectorImpl``
(``cluster/fdetector/FailureDetectorImpl.java:29-427``):

* every ``ping_interval`` pick the next member from a shuffled round-robin
  list (``selectPingMember`` :352-361 — reshuffle when the cursor wraps) and
  direct-PING it with ``ping_timeout`` (``doPing`` :126-171);
* on timeout pick ``ping_req_members`` random relays (``selectPingReqMembers``
  :363-375) and send PING_REQ with the remaining ``interval - timeout`` budget
  (``doPingReq`` :173-210);
* relays forward a transit PING carrying the original issuer (``onPingReq``
  :262-285) and route the transit ACK back (``onTransitPingAck`` :291-315);
* ACKs carry ``DEST_OK``/``DEST_GONE``; GONE (id mismatch at the probed
  address — a restarted member) yields DEAD, OK yields ALIVE, total silence
  yields SUSPECT (``computeMemberStatus`` :382-404, ``onPing`` :227-259);
* the ping list follows membership ADDED (insert at random position) /
  REMOVED events (``onMemberEvent`` :321-346).

The vectorized analogue is ``ops/kernel.py``'s FD phase — one FD round per tick with the
same verdict function expressed as Bernoulli draws on the link matrix.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
from dataclasses import dataclass, replace
from typing import List, Optional, Set

from ..config import FailureDetectorConfig
from ..models.events import FailureDetectorEvent, MembershipEvent
from ..models.member import Member, MemberStatus
from ..models.message import (
    HEADER_CORRELATION_ID,
    Message,
    Q_PING,
    Q_PING_ACK,
    Q_PING_REQ,
    new_correlation_id,
)
from ..transport.api import Transport
from ..utils.streams import EventStream

_log = logging.getLogger(__name__)


class AckType(enum.Enum):
    """PingData.AckType (reference PingData.java:15-29)."""

    DEST_OK = "DEST_OK"
    DEST_GONE = "DEST_GONE"


@dataclass(frozen=True)
class PingData:
    """Probe payload (reference PingData.java:11-37): issuer, target, and —
    for transit pings routed through a relay — the original issuer."""

    from_member: Member
    to_member: Member
    original_issuer: Optional[Member] = None
    ack_type: Optional[AckType] = None

    def with_ack_type(self, ack_type: AckType) -> "PingData":
        return replace(self, ack_type=ack_type)


class FailureDetector:
    """One node's failure detector component."""

    def __init__(
        self,
        local_member: Member,
        transport: Transport,
        membership_events: EventStream,
        config: FailureDetectorConfig,
        rng: Optional[random.Random] = None,
    ) -> None:
        self._local = local_member
        self._transport = transport
        self._config = config
        self._rng = rng or random.Random()
        self._events: EventStream = EventStream()
        self._ping_members: List[Member] = []
        self._ping_member_index = 0
        self._current_period = 0
        self._loop_task: Optional[asyncio.Task] = None
        self._inflight: Set[asyncio.Task] = set()
        self._unsubs = [
            transport.listen().subscribe(self._on_message),
            membership_events.subscribe(self._on_member_event),
        ]

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._loop_task = asyncio.ensure_future(self._run())

    def stop(self) -> None:
        for unsub in self._unsubs:
            unsub()
        if self._loop_task is not None:
            self._loop_task.cancel()
        for task in list(self._inflight):
            task.cancel()

    def listen(self) -> EventStream:
        """Stream of :class:`FailureDetectorEvent` verdicts."""
        return self._events

    @property
    def current_period(self) -> int:
        return self._current_period

    # -- periodic probe loop (reference start :101-106) --------------------
    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self._config.ping_interval)
            self._do_ping()

    def _spawn(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    def _do_ping(self) -> None:
        period = self._current_period
        self._current_period += 1
        ping_member = self._select_ping_member()
        if ping_member is None:
            return
        self._spawn(self._ping(period, ping_member))

    async def _ping(self, period: int, ping_member: Member) -> None:
        cid = new_correlation_id(self._local.id)
        ping_msg = Message.with_data(
            PingData(self._local, ping_member), qualifier=Q_PING, cid=cid
        )
        _log.debug("[%s][%s] send ping to %s", self._local, period, ping_member)
        try:
            ack = await self._transport.request_response(
                ping_member.address, ping_msg, timeout=self._config.ping_timeout
            )
        except Exception:  # noqa: BLE001 - timeout or send failure -> indirect probe
            time_left = self._config.ping_interval - self._config.ping_timeout
            relays = self._select_ping_req_members(ping_member)
            if time_left <= 0 or not relays:
                self._publish(period, ping_member, MemberStatus.SUSPECT)
            else:
                await self._ping_req(period, ping_member, relays, cid, time_left)
            return
        self._publish(period, ping_member, self._compute_status(ack))

    async def _ping_req(
        self, period: int, ping_member: Member, relays: List[Member], cid: str, timeout: float
    ) -> None:
        """Indirect probe via each relay in parallel (doPingReq :173-210);
        each relay path publishes its own verdict, as in the reference."""
        data = PingData(self._local, ping_member)
        msg = Message.with_data(data, qualifier=Q_PING_REQ, cid=cid)

        async def one(relay: Member) -> None:
            try:
                ack = await self._transport.request_response(relay.address, msg, timeout=timeout)
                self._publish(period, ping_member, self._compute_status(ack))
            except Exception:  # noqa: BLE001
                self._publish(period, ping_member, MemberStatus.SUSPECT)

        await asyncio.gather(*(one(r) for r in relays))

    # -- message handlers --------------------------------------------------
    def _on_message(self, message: Message) -> None:
        q = message.qualifier
        if q == Q_PING:
            self._on_ping(message)
        elif q == Q_PING_REQ:
            self._on_ping_req(message)
        elif q == Q_PING_ACK and isinstance(message.data, PingData) and message.data.original_issuer is not None:
            self._on_transit_ping_ack(message)

    def _on_ping(self, message: Message) -> None:
        """Answer PING with ACK; DEST_GONE if the probed id isn't us
        (restarted member on the same address, onPing :227-259)."""
        data: PingData = message.data
        data = data.with_ack_type(AckType.DEST_OK)
        if data.to_member.id != self._local.id:
            data = data.with_ack_type(AckType.DEST_GONE)
        ack = Message.with_data(data, qualifier=Q_PING_ACK)
        if message.correlation_id is not None:
            ack = ack.with_header(HEADER_CORRELATION_ID, message.correlation_id)
        self._spawn(self._send_quietly(data.from_member.address, ack))

    def _on_ping_req(self, message: Message) -> None:
        """Relay: forward transit PING to the target (onPingReq :262-285)."""
        data: PingData = message.data
        transit = PingData(self._local, data.to_member, original_issuer=data.from_member)
        ping = Message.with_data(transit, qualifier=Q_PING)
        if message.correlation_id is not None:
            ping = ping.with_header(HEADER_CORRELATION_ID, message.correlation_id)
        self._spawn(self._send_quietly(data.to_member.address, ping))

    def _on_transit_ping_ack(self, message: Message) -> None:
        """Relay: route the transit ACK back to the original issuer as a plain
        ACK (onTransitPingAck :291-315)."""
        data: PingData = message.data
        issuer = data.original_issuer
        plain = PingData(issuer, data.to_member, ack_type=data.ack_type)
        ack = Message.with_data(plain, qualifier=Q_PING_ACK)
        if message.correlation_id is not None:
            ack = ack.with_header(HEADER_CORRELATION_ID, message.correlation_id)
        self._spawn(self._send_quietly(issuer.address, ack))

    async def _send_quietly(self, address: str, message: Message) -> None:
        try:
            await self._transport.send(address, message)
        except Exception as exc:  # noqa: BLE001
            _log.debug("[%s] failed to send %s to %s: %s", self._local, message.qualifier, address, exc)

    # -- membership feed (onMemberEvent :321-346) --------------------------
    def on_membership_event(self, event: MembershipEvent) -> None:
        self._on_member_event(event)

    def _on_member_event(self, event: MembershipEvent) -> None:
        member = event.member
        if event.is_removed and member in self._ping_members:
            self._ping_members.remove(member)
        if event.is_added and member.id != self._local.id:
            index = self._rng.randrange(len(self._ping_members)) if self._ping_members else 0
            self._ping_members.insert(index, member)

    # -- selection ---------------------------------------------------------
    def _select_ping_member(self) -> Optional[Member]:
        if not self._ping_members:
            return None
        if self._ping_member_index >= len(self._ping_members):
            self._ping_member_index = 0
            self._rng.shuffle(self._ping_members)
        member = self._ping_members[self._ping_member_index]
        self._ping_member_index += 1
        return member

    def _select_ping_req_members(self, ping_member: Member) -> List[Member]:
        k = self._config.ping_req_members
        if k <= 0:
            return []
        candidates = [m for m in self._ping_members if m != ping_member]
        self._rng.shuffle(candidates)
        return candidates[:k]

    # -- verdicts ----------------------------------------------------------
    def _publish(self, period: int, member: Member, status: MemberStatus) -> None:
        _log.debug("[%s][%s] member %s detected as %s", self._local, period, member, status.name)
        self._events.emit(FailureDetectorEvent(member, status, period=period))

    @staticmethod
    def _compute_status(ack: Message) -> MemberStatus:
        data: PingData = ack.data
        if data.ack_type is None:
            return MemberStatus.ALIVE
        if data.ack_type == AckType.DEST_OK:
            return MemberStatus.ALIVE
        if data.ack_type == AckType.DEST_GONE:
            return MemberStatus.DEAD
        return MemberStatus.SUSPECT
