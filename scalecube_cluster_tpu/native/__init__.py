"""Native (C) runtime components with pure-Python fallbacks.

``load_codec()`` returns the compiled ``_sc_codec`` extension, building it
on first use with the system compiler (no pip/installation involved); if no
compiler is available the caller falls back to the pure-Python
implementation of the identical wire format.
"""

from __future__ import annotations

import importlib.util
import logging
import pathlib
import subprocess
import sysconfig

_log = logging.getLogger(__name__)
_DIR = pathlib.Path(__file__).parent
_SO = _DIR / "_sc_codec.so"


def build_codec() -> bool:
    """Compile codec.c into _sc_codec.so next to this file. Returns success."""
    include = sysconfig.get_paths()["include"]
    cmd = [
        "cc", "-O2", "-shared", "-fPIC",
        f"-I{include}",
        str(_DIR / "codec.c"),
        "-o", str(_SO),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        _log.info("native codec build failed (%s); using pure-Python fallback", e)
        return False


_BUILD_FAILED = False


def _stale() -> bool:
    """The .so must be rebuilt when missing or older than its source (a
    stale binary from an earlier codec.c — or another Python/ABI — must not
    be loaded as-is)."""
    if not _SO.exists():
        return True
    src = _DIR / "codec.c"
    return src.exists() and src.stat().st_mtime > _SO.stat().st_mtime


def load_codec():
    """Import the native codec module, (re)building it if missing or stale;
    None if unavailable. A failed build is cached for the process lifetime
    so callers don't repeatedly shell out to the compiler."""
    global _BUILD_FAILED
    if _BUILD_FAILED:
        return None
    if _stale():
        if not build_codec():
            _BUILD_FAILED = True
            return None
    spec = importlib.util.spec_from_file_location("_sc_codec", _SO)
    if spec is None or spec.loader is None:
        return None
    module = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(module)
    except Exception as e:  # any load failure -> pure-Python wire
        _log.info("native codec load failed (%s); using pure-Python fallback", e)
        return None
    return module
