"""Native (C) runtime components with pure-Python fallbacks.

``load_codec()`` returns the compiled ``_sc_codec`` extension, building it
on first use with the system compiler (no pip/installation involved); if no
compiler is available the caller falls back to the pure-Python
implementation of the identical wire format.
"""

from __future__ import annotations

import importlib.util
import logging
import pathlib
import subprocess
import sysconfig

_log = logging.getLogger(__name__)
_DIR = pathlib.Path(__file__).parent
_SO = _DIR / "_sc_codec.so"


def build_codec() -> bool:
    """Compile codec.c into _sc_codec.so next to this file. Returns success."""
    include = sysconfig.get_paths()["include"]
    cmd = [
        "cc", "-O2", "-shared", "-fPIC",
        f"-I{include}",
        str(_DIR / "codec.c"),
        "-o", str(_SO),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        _log.info("native codec build failed (%s); using pure-Python fallback", e)
        return False


_BUILD_FAILED = False


def load_codec():
    """Import the native codec module, building it if needed; None if
    unavailable. A failed build is cached for the process lifetime so
    callers don't repeatedly shell out to the compiler."""
    global _BUILD_FAILED
    if _BUILD_FAILED:
        return None
    if not _SO.exists():
        if not build_codec():
            _BUILD_FAILED = True
            return None
    spec = importlib.util.spec_from_file_location("_sc_codec", _SO)
    if spec is None or spec.loader is None:
        return None
    module = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(module)
    except ImportError:
        return None
    return module
