/* Compact binary message codec — the wire-format hot path in C.
 *
 * The reference's per-message hot path runs on Netty's native
 * epoll/zero-copy layer with pluggable MessageCodecs
 * (TransportImpl.java:240-260); this extension is the analogue for the
 * asyncio TCP transport: header-map + payload packing without pickling
 * overhead, and a language-neutral format (a non-Python peer can speak it).
 *
 * Wire format (all big-endian):
 *   magic   2 bytes  'S''1'
 *   hcount  u16      number of headers
 *   per header:  klen u16, key bytes (utf-8), vlen u32, value bytes (utf-8)
 *   plen    u32      payload length, then payload bytes
 *
 * Python-level contract (mirrored by the pure-Python fallback in
 * transport/native_codec.py):
 *   encode(headers: dict[str, str], payload: bytes) -> bytes
 *   decode(buf: bytes) -> (dict[str, str], bytes)
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

static void put_u16(unsigned char *p, unsigned int v) {
    p[0] = (v >> 8) & 0xff; p[1] = v & 0xff;
}
static void put_u32(unsigned char *p, unsigned long v) {
    p[0] = (v >> 24) & 0xff; p[1] = (v >> 16) & 0xff;
    p[2] = (v >> 8) & 0xff;  p[3] = v & 0xff;
}
static unsigned int get_u16(const unsigned char *p) {
    return ((unsigned int)p[0] << 8) | p[1];
}
static unsigned long get_u32(const unsigned char *p) {
    return ((unsigned long)p[0] << 24) | ((unsigned long)p[1] << 16)
         | ((unsigned long)p[2] << 8) | p[3];
}

static PyObject *codec_encode(PyObject *self, PyObject *args) {
    PyObject *headers; Py_buffer payload;
    if (!PyArg_ParseTuple(args, "O!y*", &PyDict_Type, &headers, &payload))
        return NULL;

    Py_ssize_t hcount = PyDict_Size(headers);
    if (hcount > 0xffff) {
        PyBuffer_Release(&payload);
        PyErr_SetString(PyExc_ValueError, "too many headers");
        return NULL;
    }

    /* first pass: compute size, grab utf-8 views (owned refs kept in a list) */
    PyObject *pairs = PyList_New(0);
    if (!pairs) { PyBuffer_Release(&payload); return NULL; }
    Py_ssize_t total = 2 + 2 + 4 + payload.len;
    PyObject *key, *value; Py_ssize_t pos = 0;
    while (PyDict_Next(headers, &pos, &key, &value)) {
        if (!PyUnicode_Check(key) || !PyUnicode_Check(value)) {
            Py_DECREF(pairs); PyBuffer_Release(&payload);
            PyErr_SetString(PyExc_TypeError, "headers must be str->str");
            return NULL;
        }
        Py_ssize_t klen, vlen;
        const char *k = PyUnicode_AsUTF8AndSize(key, &klen);
        const char *v = PyUnicode_AsUTF8AndSize(value, &vlen);
        if (!k || !v || klen > 0xffff || vlen > 0xffffffffL) {
            Py_DECREF(pairs); PyBuffer_Release(&payload);
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_ValueError, "header too large");
            return NULL;
        }
        PyObject *pair = Py_BuildValue("(OO)", key, value);
        if (!pair || PyList_Append(pairs, pair) < 0) {
            Py_XDECREF(pair); Py_DECREF(pairs); PyBuffer_Release(&payload);
            return NULL;
        }
        Py_DECREF(pair);
        total += 2 + klen + 4 + vlen;
    }

    PyObject *out = PyBytes_FromStringAndSize(NULL, total);
    if (!out) { Py_DECREF(pairs); PyBuffer_Release(&payload); return NULL; }
    unsigned char *p = (unsigned char *)PyBytes_AS_STRING(out);
    *p++ = 'S'; *p++ = '1';
    put_u16(p, (unsigned int)hcount); p += 2;
    for (Py_ssize_t i = 0; i < PyList_GET_SIZE(pairs); i++) {
        PyObject *pair = PyList_GET_ITEM(pairs, i);
        Py_ssize_t klen, vlen;
        const char *k = PyUnicode_AsUTF8AndSize(PyTuple_GET_ITEM(pair, 0), &klen);
        const char *v = PyUnicode_AsUTF8AndSize(PyTuple_GET_ITEM(pair, 1), &vlen);
        put_u16(p, (unsigned int)klen); p += 2;
        memcpy(p, k, klen); p += klen;
        put_u32(p, (unsigned long)vlen); p += 4;
        memcpy(p, v, vlen); p += vlen;
    }
    put_u32(p, (unsigned long)payload.len); p += 4;
    memcpy(p, payload.buf, payload.len);
    Py_DECREF(pairs);
    PyBuffer_Release(&payload);
    return out;
}

static PyObject *codec_decode(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf))
        return NULL;
    const unsigned char *p = (const unsigned char *)buf.buf;
    const unsigned char *end = p + buf.len;

    if (buf.len < 8 || p[0] != 'S' || p[1] != '1') {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_ValueError, "bad magic");
        return NULL;
    }
    p += 2;
    unsigned int hcount = get_u16(p); p += 2;

    PyObject *headers = PyDict_New();
    if (!headers) { PyBuffer_Release(&buf); return NULL; }
    for (unsigned int i = 0; i < hcount; i++) {
        if (p + 2 > end) goto truncated;
        unsigned int klen = get_u16(p); p += 2;
        if (p + klen + 4 > end) goto truncated;
        PyObject *k = PyUnicode_DecodeUTF8((const char *)p, klen, "strict");
        p += klen;
        unsigned long vlen = get_u32(p); p += 4;
        if (!k || p + vlen > end) { Py_XDECREF(k); goto truncated; }
        PyObject *v = PyUnicode_DecodeUTF8((const char *)p, vlen, "strict");
        p += vlen;
        if (!v || PyDict_SetItem(headers, k, v) < 0) {
            Py_DECREF(k); Py_XDECREF(v);
            Py_DECREF(headers); PyBuffer_Release(&buf);
            return NULL;
        }
        Py_DECREF(k); Py_DECREF(v);
    }
    if (p + 4 > end) goto truncated;
    {
        unsigned long plen = get_u32(p); p += 4;
        if (p + plen > end) goto truncated;
        PyObject *payload = PyBytes_FromStringAndSize((const char *)p, plen);
        PyBuffer_Release(&buf);
        if (!payload) { Py_DECREF(headers); return NULL; }
        PyObject *result = Py_BuildValue("(NN)", headers, payload);
        return result;
    }

truncated:
    Py_DECREF(headers);
    PyBuffer_Release(&buf);
    if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "truncated frame");
    return NULL;
}

static PyMethodDef codec_methods[] = {
    {"encode", codec_encode, METH_VARARGS,
     "encode(headers: dict[str, str], payload: bytes) -> bytes"},
    {"decode", codec_decode, METH_VARARGS,
     "decode(buf: bytes) -> (dict[str, str], bytes)"},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef codec_module = {
    PyModuleDef_HEAD_INIT, "_sc_codec",
    "Native binary message codec for scalecube_cluster_tpu", -1, codec_methods
};

PyMODINIT_FUNC PyInit__sc_codec(void) {
    return PyModule_Create(&codec_module);
}
