"""Telemetry plane (r8): on-device metric rings, the unified event bus, the
OpenMetrics exporter, and the crash flight recorder.

The observability subsystem that turns "works at scale" into "measured at
scale": per-window time series recorded ON DEVICE with zero added
device→host transfers (:mod:`.rings`), every discrete event — membership,
chaos, transport — merged into one bounded tick-stamped stream
(:mod:`.bus`), standard Prometheus/OpenMetrics export for both the sim
drivers and the scalar engine (:mod:`.openmetrics`), and an atomic
post-mortem artifact when a sentinel fires mid-soak (:mod:`.flight`).

Entry points: ``SimDriver.arm_telemetry()`` returns the armed
:class:`TelemetryPlane`; ``MonitorServer.register_telemetry`` serves
``GET /metrics`` and ``GET /events``.
"""

from .bus import BusRecord, TelemetryBus
from .flight import (
    FlightRecorderError,
    load_flight_dump,
    replay_timeline,
    write_flight_dump,
)
from .openmetrics import CONTENT_TYPE, Histogram, cluster_families, driver_families, render
from .plane import SENTINEL_SERIES, TelemetryPlane
from .rings import MetricRing

__all__ = [
    "BusRecord",
    "TelemetryBus",
    "FlightRecorderError",
    "load_flight_dump",
    "replay_timeline",
    "write_flight_dump",
    "CONTENT_TYPE",
    "Histogram",
    "cluster_families",
    "driver_families",
    "render",
    "SENTINEL_SERIES",
    "TelemetryPlane",
    "MetricRing",
]
