"""Unified event bus: one bounded, tick-stamped, subscribable event stream.

Before r8 the system's discrete events lived in three unrelated places —
membership events on per-watch ``EventStream``s, chaos scenario/sentinel
events inside the runner's report, and ``TransportEvent``s on each stream
transport's private listener — so correlating "the partition healed, then
the reconnect storm hit, then row 7 rejoined" meant scraping three logs.
The :class:`TelemetryBus` merges them into a single ordered record stream:

* every record carries a monotone ``seq`` (total order), the sim ``tick``
  it was observed at (the driver's host-side tick shadow — stamping NEVER
  reads the device), a wall-clock ``ts``, a ``source`` ("driver",
  "membership", "chaos", "transport", "checkpoint", ...), a ``kind``, and
  free-form ``fields``;
* retention is BOUNDED (``TelemetryConfig.bus_capacity``); evictions are
  counted, never silent;
* subscribers get records as they are published (the ``EventStream``
  fan-out semantics — one bad subscriber never breaks the rest), which is
  how the bus feeds :class:`..monitor.TickLogger` and the monitor's
  ``/events`` endpoint;
* ``attach_*`` helpers wire the three legacy streams in.
"""

from __future__ import annotations

import threading
import time
from collections import Counter, deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.streams import EventStream


@dataclass(frozen=True)
class BusRecord:
    """One merged telemetry event (JSON-ready via :meth:`as_dict`)."""

    seq: int
    tick: int
    ts: float
    source: str
    kind: str
    fields: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "seq": self.seq,
            "tick": self.tick,
            "ts": self.ts,
            "source": self.source,
            "kind": self.kind,
            **self.fields,
        }


class TelemetryBus:
    """Bounded, ordered, subscribable merge of every event source."""

    def __init__(self, capacity: int = 4096):
        if capacity <= 0:
            raise ValueError("bus capacity must be > 0")
        self.capacity = int(capacity)
        self._records: deque = deque(maxlen=self.capacity)
        self._stream: EventStream = EventStream()
        self._seq = 0
        self._evicted = 0
        self._counts: Counter = Counter()  # (source, kind) -> published
        self._lock = threading.Lock()
        self._unsubs: List[Callable[[], None]] = []

    # -- publishing ----------------------------------------------------------
    def publish(
        self, source: str, kind: str, tick: int = -1, **fields
    ) -> BusRecord:
        """Append one record (thread-safe; called from the sim thread, the
        monitor thread, and asyncio transport callbacks alike)."""
        with self._lock:
            rec = BusRecord(
                seq=self._seq, tick=int(tick), ts=time.time(),
                source=source, kind=kind, fields=dict(fields),
            )
            self._seq += 1
            if len(self._records) == self.capacity:
                self._evicted += 1
            self._records.append(rec)
            self._counts[(source, kind)] += 1
        self._stream.emit(rec)
        return rec

    # -- consumption ---------------------------------------------------------
    def subscribe(self, handler: Callable[[BusRecord], None]) -> Callable[[], None]:
        return self._stream.subscribe(handler)

    def tail(self, n: Optional[int] = None) -> List[BusRecord]:
        """The newest ``n`` retained records (default: all), oldest first."""
        with self._lock:
            records = list(self._records)
        return records if n is None else records[-int(n):]

    def counts(self) -> Dict[Tuple[str, str], int]:
        """(source, kind) -> total records ever published (monotone — the
        OpenMetrics counter source, unaffected by ring eviction)."""
        with self._lock:
            return dict(self._counts)

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "retained": len(self._records),
                "published": self._seq,
                "evicted": self._evicted,
            }

    # -- source adapters ------------------------------------------------------
    def attach_transport(
        self, transport, tick_fn: Optional[Callable[[], int]] = None
    ) -> Callable[[], None]:
        """Merge a stream transport's ``transport_events()`` (reconnect
        backoff / give-up, connection loss) into the bus."""
        tick_fn = tick_fn or (lambda: -1)

        def on_event(ev) -> None:
            self.publish(
                "transport", ev.kind, tick=tick_fn(), address=ev.address,
                attempts=ev.attempts, delay=ev.delay, error=ev.error,
            )

        unsub = transport.transport_events().subscribe(on_event)
        self._unsubs.append(unsub)
        return unsub

    def attach_membership(
        self,
        stream: EventStream,
        observer: str,
        tick_fn: Optional[Callable[[], int]] = None,
    ) -> Callable[[], None]:
        """Merge one observer's ``MembershipEvent`` stream (a driver watch
        or the scalar engine's ``listen_membership()``) into the bus."""
        tick_fn = tick_fn or (lambda: -1)

        def on_event(ev) -> None:
            self.publish(
                "membership", ev.type.name.lower(), tick=tick_fn(),
                observer=observer, member=ev.member.id,
                address=ev.member.address,
            )

        unsub = stream.subscribe(on_event)
        self._unsubs.append(unsub)
        return unsub

    def attach_cluster(
        self, cluster, tick_fn: Optional[Callable[[], int]] = None
    ) -> List[Callable[[], None]]:
        """Merge one scalar-engine Cluster node's membership events AND its
        transport lifecycle events (when the transport has any) into the
        bus; returns the unsubscribers."""
        unsubs = [
            self.attach_membership(
                cluster.listen_membership(), cluster.member().id, tick_fn
            )
        ]
        events = cluster.transport_events()
        if events is not None:
            tf = tick_fn or (lambda: -1)

            def on_event(ev) -> None:
                self.publish(
                    "transport", ev.kind, tick=tf(), address=ev.address,
                    attempts=ev.attempts, delay=ev.delay, error=ev.error,
                )

            unsub = events.subscribe(on_event)
            self._unsubs.append(unsub)
            unsubs.append(unsub)
        return unsubs

    def pipe_to_tick_logger(self, tick_logger) -> Callable[[], None]:
        """Forward every bus record into a :class:`..monitor.TickLogger` as a
        structured event line (the bus IS the logger's event source now)."""

        def on_record(rec: BusRecord) -> None:
            tick_logger.log_event(
                rec.tick, f"{rec.source}:{rec.kind}", seq=rec.seq, **rec.fields
            )

        unsub = self.subscribe(on_record)
        self._unsubs.append(unsub)
        return unsub

    def close(self) -> None:
        """Detach every adapter subscription this bus created."""
        for unsub in self._unsubs:
            unsub()
        self._unsubs.clear()
