"""On-device metric rings: fixed-shape [ring_len, n_metrics] window series.

The r6/r7 deferred-accumulator discipline, generalized from "a handful of
scalar reductions" to a full per-window TIME SERIES: every ``step()``
appends one f32 row (the engine's ``telemetry_window_vector`` — see
``ops.kernel.TELEMETRY_SERIES`` / ``ops.sparse.TELEMETRY_SERIES``) to a
circular device buffer via a donated jitted update. Nothing is transferred
per window — the row is a pure jnp reduction over the window's stacked
metrics, and the ring lives on device until an explicit sync point
(:meth:`MetricRing.snapshot`, a ``/metrics`` scrape, or a flight-recorder
dump) reads it back in one coalesced transfer.

The cursor is HOST state (one Python int): a window append is a host event,
so the host always knows how many rows exist and where the next one goes —
no device round trip is ever needed to index the ring. Under a mesh the
buffer is placed replicated (``ops.sharding.replicated_sharding``): window
summaries of sharded metrics come out replicated under GSPMD, so the append
stays a collective-free local update on every chip.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


def ring_tail(buf, appended: int, ring_len: int, k: Optional[int] = None):
    """Oldest-first unroll of a circular buffer's newest ``k`` rows — THE
    wrap-ordering spelling, shared by the metric ring and the r10 trace
    ring (``trace/rings.py``) so the two cannot drift. ``appended`` is the
    total rows ever written (cursor = appended % ring_len). Reading ``buf``
    is the caller's sync point; callers hold the driver lock."""
    have = min(appended, ring_len)
    k = have if k is None else min(int(k), have)
    if k <= 0:  # empty read: no device transfer
        return np.zeros((0,) + tuple(buf.shape[1:]), buf.dtype)
    host = np.asarray(buf)
    if appended >= ring_len:  # wrapped: unroll from the cursor
        cursor = appended % ring_len
        ordered = np.concatenate([host[cursor:], host[:cursor]], axis=0)
    else:
        ordered = host[:have]
    return ordered[-k:]


class MetricRing:
    """Circular [ring_len, n_metrics] f32 device buffer of per-window rows.

    ``names`` fixes the column layout (the engine's ``TELEMETRY_SERIES``).
    :meth:`append` is the per-window device-only path; :meth:`snapshot` /
    :meth:`last` are the host sync points.
    """

    def __init__(self, names: Sequence[str], ring_len: int, mesh=None):
        import jax
        import jax.numpy as jnp

        if ring_len <= 0:
            raise ValueError("ring_len must be > 0")
        self.names = tuple(names)
        self.ring_len = int(ring_len)
        buf = jnp.zeros((self.ring_len, len(self.names)), jnp.float32)
        if mesh is not None:
            from ..ops.sharding import make_sharded_metric_append, place_replicated

            buf = place_replicated(buf, mesh)
        self._buf = buf
        self._windows = 0  # host-side append count (cursor = windows % len)
        # the newest appended row, RETAINED (r19): the append donates only
        # the ring buffer — the row itself is the fresh output of an
        # undonated jit, immutable once created, so holding a reference
        # gives scrapes a lock-free read of the newest complete window
        self._last_row = None
        # donated in-place row write: the ring must never force a copy of
        # itself per window (it is carried across every step of a run).
        # On a mesh the append is the r21 sharded twin — same spelling with
        # every operand pinned replicated (collective-free local write)
        if mesh is not None:
            self._append = make_sharded_metric_append(mesh)
        else:
            self._append = jax.jit(
                lambda buf, row, idx: buf.at[idx].set(row), donate_argnums=0
            )

    @property
    def windows(self) -> int:
        """Total rows ever appended (>= ring_len means the ring wrapped)."""
        return self._windows

    def append(self, row) -> None:
        """Write one window row ([n_metrics] f32 device array). Pure device
        op — zero device→host transfers."""
        import jax.numpy as jnp

        idx = jnp.int32(self._windows % self.ring_len)
        self._last_row = row  # not donated below — safe to retain
        self._buf = self._append(self._buf, row, idx)
        self._windows += 1

    def last(self, k: Optional[int] = None) -> np.ndarray:
        """The most recent ``k`` rows (default: all retained), OLDEST first —
        one coalesced device→host transfer (the sync point)."""
        return ring_tail(self._buf, self._windows, self.ring_len, k)

    def snapshot(self, k: Optional[int] = None) -> Dict[str, object]:
        """Host view of the ring: column names + the last ``k`` rows in
        time order + append count. THE ring readback site."""
        rows = self.last(k)
        return {
            "names": list(self.names),
            "ring_len": self.ring_len,
            "windows": self._windows,
            "rows": rows,
        }

    def latest_values(self) -> Dict[str, float]:
        """name -> value of the newest row ({} before the first append).

        Lock-free by design (r19): reads the retained last-appended row,
        never the donated ring buffer — a ``/metrics`` scrape landing while
        a mega-sim window holds the driver lock serves the newest COMPLETE
        window immediately instead of waiting out the window's compute."""
        if self._last_row is None:
            return {}
        vals = np.asarray(self._last_row)
        return {n: float(v) for n, v in zip(self.names, vals)}

    def series(self, name: str, k: Optional[int] = None) -> List[float]:
        """One named column of the retained window series, oldest first."""
        col = self.names.index(name)
        return [float(v) for v in self.last(k)[:, col]]
