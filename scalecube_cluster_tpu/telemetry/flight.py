"""Crash flight recorder: the post-mortem artifact for a mid-soak failure.

When a sentinel fires, a checkpoint fails to restore, or an operator asks,
the telemetry plane dumps the last K metric-ring windows plus the tail of
the event bus to ONE crash-safe JSON artifact — the same
mkstemp+fsync+``os.replace`` machinery the r7 checkpoints use, so a crash
mid-dump can never leave a torn file where the post-mortem should be.

:func:`load_flight_dump` validates schema + engine fields and
:func:`replay_timeline` merges the ring rows and bus records into one
tick-ordered human-readable timeline — "what the cluster was doing in the
K windows before it died", without a debugger or a rerun.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Dict, List, Optional

FLIGHT_SCHEMA = 2


class FlightRecorderError(RuntimeError):
    """A flight dump that cannot be loaded (truncated, corrupt, or from a
    future schema) — the checkpoint-error analogue for post-mortems."""


def _backend_name() -> str:
    """``jax.default_backend()`` without making a jax-less load path crash
    (the loader/replay tooling imports this module host-side)."""
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


def write_flight_dump(
    path: str,
    *,
    reason: str,
    engine: str,
    ring_snapshot: dict,
    bus_tail: List[dict],
    context: Optional[dict] = None,
    trace: Optional[dict] = None,
    reconstruction: Optional[dict] = None,
    tick_range: Optional[List[int]] = None,
) -> str:
    """Atomically write one flight artifact; returns the final path.

    Crash-safe exactly like ``SimDriver.checkpoint``: mkstemp in the target
    directory (concurrent dumps never truncate each other), fsync, then one
    ``os.replace`` — the artifact either fully exists or not at all.

    ``trace`` (r10) is the causal-trace section an armed trace plane
    contributes (``TracePlane.flight_section``): the trace-ring tail plus
    the sewn span tree for each violating member — post-mortems carry
    causality, not just the how-much series. Optional, so pre-r10 dumps
    and unarmed drivers keep the schema (readers treat it as absent).

    ``reconstruction`` (r18, schema 2) embeds everything
    :func:`..replay.scenario_from_flight` needs to rebuild and RE-RUN the
    incident: engine + params doc + seed + the armed scenario's event
    timeline + the recorded verdict. When the writer has no armed chaos
    runner to describe, pass ``None`` — the loader then marks the dump
    ``reconstruction: "partial"`` (same as every pre-r18 artifact)."""
    rows = ring_snapshot["rows"]
    doc = {
        "_schema": FLIGHT_SCHEMA,
        "ts": time.time(),
        "reason": reason,
        "engine": engine,
        # provenance stamps (the r13 backend-stamp rule, applied to the
        # post-mortem surface): which backend the dying sim ran on, how
        # many host CPUs, and the absolute tick span the artifact covers
        "backend": _backend_name(),
        "host_cpus": os.cpu_count(),
        "ring": {
            "names": list(ring_snapshot["names"]),
            "windows_total": int(ring_snapshot["windows"]),
            "rows": [[float(v) for v in row] for row in rows],
        },
        "events": list(bus_tail),
        "context": context or {},
    }
    if tick_range is not None:
        doc["tick_range"] = [int(tick_range[0]), int(tick_range[1])]
    if reconstruction is not None:
        doc["reconstruction"] = reconstruction
    if trace is not None:
        doc["trace"] = trace
    target = os.path.abspath(path)
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(target) + ".tmp-",
        dir=os.path.dirname(target),
    )
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(doc, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, target)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return target


def load_flight_dump(path: str) -> dict:
    """Load + validate one artifact; raises :class:`FlightRecorderError` on
    anything that isn't a complete dump this build understands."""
    try:
        with open(path, "r") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        raise
    except Exception as exc:  # json/unicode deep failures -> one clear error
        raise FlightRecorderError(
            f"flight dump {path!r} is unreadable (truncated or corrupt): {exc}"
        ) from exc
    schema = int(doc.get("_schema", 0))
    if schema > FLIGHT_SCHEMA:
        raise FlightRecorderError(
            f"flight dump {path!r} has schema {schema}, newer than this "
            f"build's {FLIGHT_SCHEMA} — refusing a partial decode"
        )
    for key in ("reason", "engine", "ring", "events"):
        if key not in doc:
            raise FlightRecorderError(
                f"flight dump {path!r} is missing {key!r} (truncated?)"
            )
    # versioned upgrade (r18): schema-1 artifacts — and schema-2 dumps whose
    # writer had no armed chaos runner to describe — carry no reconstruction
    # inputs. Mark that EXPLICITLY so replay tooling refuses with "this dump
    # predates/lacks reconstruction" instead of a KeyError.
    if "reconstruction" not in doc:
        doc["reconstruction"] = "partial"
    return doc


def replay_timeline(dump: dict) -> List[str]:
    """Merge ring windows + bus events into one tick-ordered, human-readable
    timeline (the loader's whole point: a post-mortem someone can READ)."""
    names = dump["ring"]["names"]
    try:
        tick_col = names.index("tick")
    except ValueError:
        tick_col = None
    entries: List[tuple] = []  # (tick, order, line)
    for row in dump["ring"]["rows"]:
        tick = int(row[tick_col]) if tick_col is not None else -1
        interesting = {
            n: v
            for n, v in zip(names, row)
            if n not in ("tick", "window_ticks") and v
        }
        detail = ", ".join(
            f"{n}={v:g}" for n, v in sorted(interesting.items())
        ) or "quiet"
        entries.append((tick, 0, f"[tick {tick:>8}] window  {detail}"))
    for ev in dump["events"]:
        tick = int(ev.get("tick", -1))
        detail = ", ".join(
            f"{k}={v}" for k, v in sorted(ev.items())
            if k not in ("tick", "ts", "seq", "source", "kind") and v != ""
        )
        line = (
            f"[tick {tick:>8}] event   {ev.get('source', '?')}:"
            f"{ev.get('kind', '?')}" + (f" ({detail})" if detail else "")
        )
        entries.append((tick, 1 + int(ev.get("seq", 0)), line))
    header = [
        f"flight dump: reason={dump['reason']} engine={dump['engine']} "
        f"ts={time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime(dump.get('ts', 0)))}",
        f"ring: {len(dump['ring']['rows'])} window(s) of "
        f"{len(names)} series; {len(dump['events'])} bus event(s)",
    ]
    if dump.get("trace"):
        tr = dump["trace"]
        header.append(
            f"trace: {len(tr.get('rows', []))} ring record(s), span trees "
            f"for {sorted(tr.get('span_trees', {}))}"
        )
    if dump.get("context"):
        header.append(f"context: {json.dumps(dump['context'], sort_keys=True)}")
    return header + [line for _, _, line in sorted(entries, key=lambda e: (e[0], e[1]))]


def default_dump_path(directory: Optional[str], reason: str) -> str:
    """flight-<utc-stamp>-<reason>.json under ``directory`` (or cwd)."""
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)
    name = f"flight-{stamp}-{safe}-{os.getpid()}.json"
    return os.path.join(directory or ".", name)
