"""OpenMetrics / Prometheus text exposition for both engines.

``GET /metrics`` on :class:`..monitor.MonitorServer` renders metric
families — counters, gauges, histograms — in the Prometheus text format
(version 0.0.4, with a trailing ``# EOF`` so OpenMetrics parsers accept it
too). Families come from provider callables registered on the server:

* :func:`driver_families` — a :class:`SimDriver` + its armed
  :class:`.plane.TelemetryPlane`: dispatch counters, announce-drop
  counters by reason, the newest metric-ring row as gauges, the
  window-dispatch / tick-latency / detection-latency / rumor-spread
  histograms, and event-bus counters. Rendering is a SCRAPE SYNC POINT —
  it flushes the driver's deferred reductions and reads the ring's newest
  row, exactly like ``/health`` (poll cadence, never window cadence).
* :func:`cluster_families` — the scalar/real-transport engine's
  :class:`..cluster.Cluster`: cluster size, incarnation, per-status member
  counts, plus transport-event counters when a bus is attached.

Everything here is dependency-free host code (stdlib only — the repo rule).
"""

from __future__ import annotations

import math
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PREFIX = "scalecube"

#: content type of the rendered exposition
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class Histogram:
    """Fixed-bucket cumulative histogram (the Prometheus model): host-side
    observations only (wall-clock timings, report-derived latencies), so it
    never touches the device."""

    def __init__(self, buckets: Sequence[float]):
        if list(buckets) != sorted(buckets) or not buckets:
            raise ValueError("histogram buckets must be non-empty ascending")
        self.buckets = tuple(float(b) for b in buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +Inf bucket last
        self.total = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.counts[bisect_left(self.buckets, value)] += 1
        self.total += 1
        self.sum += float(value)

    def samples(self, name: str, labels: Optional[dict] = None) -> List[tuple]:
        """Cumulative ``_bucket``/``_sum``/``_count`` sample tuples."""
        labels = labels or {}
        out, acc = [], 0
        for le, c in zip(self.buckets, self.counts):
            acc += c
            out.append((f"{name}_bucket", {**labels, "le": _fmt(le)}, acc))
        out.append((f"{name}_bucket", {**labels, "le": "+Inf"}, self.total))
        out.append((f"{name}_sum", labels, self.sum))
        out.append((f"{name}_count", labels, self.total))
        return out


def family(name: str, ftype: str, help_: str, samples: Iterable[tuple]) -> dict:
    """One metric family: ``samples`` is an iterable of
    ``(sample_name, labels_dict, value)`` tuples."""
    return {"name": name, "type": ftype, "help": help_, "samples": list(samples)}


def _fmt(v) -> str:
    """Prometheus sample-value / le-label formatting."""
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape(value: str) -> str:
    return (
        str(value).replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')
    )


def render(families: Iterable[dict]) -> str:
    """Prometheus text exposition of the given families (stable order as
    given; duplicate family names are the caller's bug)."""
    lines: List[str] = []
    for fam in families:
        lines.append(f"# HELP {fam['name']} {fam['help']}")
        lines.append(f"# TYPE {fam['name']} {fam['type']}")
        for sample in fam["samples"]:
            sname, labels, value = sample
            if labels:
                lab = ",".join(
                    f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
                )
                lines.append(f"{sname}{{{lab}}} {_fmt(value)}")
            else:
                lines.append(f"{sname} {_fmt(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def _bus_families(bus) -> List[dict]:
    stats = bus.stats()
    return [
        family(
            f"{PREFIX}_bus_events_total", "counter",
            "Telemetry-bus records published, by source and kind.",
            [
                (f"{PREFIX}_bus_events_total",
                 {"source": src, "kind": kind}, n)
                for (src, kind), n in sorted(bus.counts().items())
            ],
        ),
        family(
            f"{PREFIX}_bus_evicted_total", "counter",
            "Telemetry-bus records evicted by the bounded retention.",
            [(f"{PREFIX}_bus_evicted_total", {}, stats["evicted"])],
        ),
        # r10 satellite: the bus's bounded-retention state as GAUGES — the
        # eviction counter alone can't tell "about to drop" from "idle"
        family(
            f"{PREFIX}_bus_retained", "gauge",
            "Telemetry-bus records currently retained.",
            [(f"{PREFIX}_bus_retained", {}, stats["retained"])],
        ),
        family(
            f"{PREFIX}_bus_capacity", "gauge",
            "Telemetry-bus bounded retention capacity.",
            [(f"{PREFIX}_bus_capacity", {}, stats["capacity"])],
        ),
    ]


def driver_families(driver, plane) -> List[dict]:
    """Metric families for one SimDriver + armed TelemetryPlane. Calling
    this IS the scrape sync point: it flushes the deferred reductions and
    reads the metric ring's newest row back (one coalesced transfer)."""
    counters = dict(driver.health_counters)  # property read = the flush
    ds = driver.dispatch_snapshot()
    engine = driver.engine
    base = {"engine": engine}
    fams = [
        family(
            f"{PREFIX}_ticks_total", "counter",
            "Simulated gossip periods dispatched.",
            [(f"{PREFIX}_ticks_total", base, ds["ticks_dispatched"])],
        ),
        family(
            f"{PREFIX}_windows_total", "counter",
            "Jitted windows dispatched.",
            [(f"{PREFIX}_windows_total", base, ds["windows_dispatched"])],
        ),
        family(
            f"{PREFIX}_readbacks_total", "counter",
            "Device-to-host transfer events (sync points only on the "
            "no-consumer path).",
            [(f"{PREFIX}_readbacks_total", base, ds["readbacks"])],
        ),
        family(
            f"{PREFIX}_flushes_total", "counter",
            "Coalesced deferred-reduction flushes.",
            [(f"{PREFIX}_flushes_total", base, ds["flushes"])],
        ),
        family(
            f"{PREFIX}_dispatch_queue_depth", "gauge",
            "Windows enqueued since the last host sync.",
            [(f"{PREFIX}_dispatch_queue_depth", base, ds["queue_depth"])],
        ),
        family(
            f"{PREFIX}_announce_dropped_total", "counter",
            "Membership-rumor announce drops, by reason.",
            [
                (f"{PREFIX}_announce_dropped_total",
                 {**base, "reason": name[len("announce_dropped_"):] or "total"},
                 v)
                for name, v in sorted(counters.items())
                if name.startswith("announce_dropped_")
            ],
        ),
        family(
            f"{PREFIX}_announced_total", "counter",
            "Membership rumors allocated into the pool.",
            [(f"{PREFIX}_announced_total", base, counters.get("announced", 0))],
        ),
        family(
            f"{PREFIX}_pool_evicted_total", "counter",
            "Priority evictions of majority-covered rumors.",
            [(f"{PREFIX}_pool_evicted_total", base,
              counters.get("pool_evicted", 0))],
        ),
        # r21: the ragged all-to-all budget-drop sentinel, accumulated
        # device-side per window like the other counters (0 everywhere
        # except budgeted sharded pview runs — a live sentinel, always
        # exposed so dashboards can alert on the first nonzero)
        family(
            f"{PREFIX}_delivery_overflow_total", "counter",
            "Gossip records dropped by the ragged-delivery budget "
            "(sharded pview windows).",
            [(f"{PREFIX}_delivery_overflow_total", base,
              counters.get("delivery_overflow", 0))],
        ),
    ]
    if driver.mesh is not None:
        fams.append(
            family(
                f"{PREFIX}_mesh_devices", "gauge",
                "Devices in the driver's mesh, by axis.",
                [
                    (f"{PREFIX}_mesh_devices", {**base, "axis": str(ax)}, int(sz))
                    for ax, sz in sorted(dict(driver.mesh.shape).items())
                ],
            )
        )
    # newest ring row -> per-series gauges (the live window values; the
    # full retained series rides the flight recorder, not the scrape).
    # NO driver lock (r19): latest_values reads the ring's RETAINED last
    # row — a never-donated buffer — not the donated ring itself, so the
    # scrape cannot hit the deleted pre-append array (the r6 hazard) and
    # never queues behind a mega-sim window's compute. Full-ring reads
    # (flight dumps, plane.snapshot) still take the lock.
    latest = plane.ring.latest_values()
    fams.append(
        family(
            f"{PREFIX}_window", "gauge",
            "Newest metric-ring window row, by series name.",
            [
                (f"{PREFIX}_window", {**base, "series": name}, value)
                for name, value in sorted(latest.items())
            ],
        )
    )
    fams.append(
        family(
            f"{PREFIX}_ring_windows_total", "counter",
            "Window rows appended to the device metric ring.",
            [(f"{PREFIX}_ring_windows_total", base, plane.ring.windows)],
        )
    )
    # r10 satellite: device-ring cursor position + wrap count as gauges
    # (host-side cursor arithmetic — the scrape does not touch the device
    # for these; how much retained history a flight dump would carry)
    fams.append(
        family(
            f"{PREFIX}_ring_cursor", "gauge",
            "Device metric-ring write cursor (next row index).",
            [(f"{PREFIX}_ring_cursor", base,
              plane.ring.windows % plane.ring.ring_len)],
        )
    )
    fams.append(
        family(
            f"{PREFIX}_ring_wraps_total", "counter",
            "Times the device metric ring lapped itself (history overwritten).",
            [(f"{PREFIX}_ring_wraps_total", base,
              plane.ring.windows // plane.ring.ring_len)],
        )
    )
    tplane = getattr(driver, "_trace", None)
    if tplane is not None:
        # counters use the LIFETIME totals (monotone across the
        # restore-path ring clear — a decreasing counter corrupts
        # Prometheus rate()/increase() over the restore boundary)
        fams.append(
            family(
                f"{PREFIX}_trace_records_total", "counter",
                "Records appended to the device trace ring (lifetime).",
                [(f"{PREFIX}_trace_records_total", base,
                  tplane.ring.records_total)],
            )
        )
        fams.append(
            family(
                f"{PREFIX}_trace_ring_cursor", "gauge",
                "Device trace-ring write cursor (next record index).",
                [(f"{PREFIX}_trace_ring_cursor", base, tplane.ring.cursor)],
            )
        )
        fams.append(
            family(
                f"{PREFIX}_trace_ring_wraps_total", "counter",
                "Times the device trace ring lapped itself (lifetime).",
                [(f"{PREFIX}_trace_ring_wraps_total", base,
                  tplane.ring.wraps_total)],
            )
        )
    for hname, hist, help_ in (
        ("window_dispatch_seconds", plane.hist_dispatch,
         "Host wall time to enqueue one jitted window."),
        ("tick_latency_seconds", plane.hist_tick,
         "Per-tick host latency (window dispatch time / ticks)."),
        ("detection_latency_ticks", plane.hist_detection,
         "Crash-detection latency observed by chaos sentinels, in ticks."),
        ("rumor_spread_ticks", plane.hist_spread,
         "Ticks from rumor creation to full coverage."),
    ):
        fams.append(
            family(f"{PREFIX}_{hname}", "histogram", help_,
                   hist.samples(f"{PREFIX}_{hname}", base))
        )
    fams.extend(_bus_families(plane.bus))
    return fams


def _parse_value(text: str) -> float:
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    return float(text)


def _parse_labels(text: str) -> Dict[str, str]:
    """Parse a ``k="v",k2="v2"`` label body (the inverse of :func:`render`'s
    label formatting, including the escape rules)."""
    labels: Dict[str, str] = {}
    i, n = 0, len(text)
    while i < n:
        eq = text.index("=", i)
        key = text[i:eq].strip().lstrip(",").strip()
        if text[eq + 1] != '"':
            raise ValueError(f"unquoted label value at {text[eq:]!r}")
        j = eq + 2
        out = []
        while True:
            c = text[j]
            if c == "\\":
                nxt = text[j + 1]
                out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
            elif c == '"':
                break
            else:
                out.append(c)
                j += 1
        labels[key] = "".join(out)
        i = j + 1
    return labels


def parse_exposition(text: str) -> List[dict]:
    """Parse a Prometheus 0.0.4 text exposition back into family dicts —
    the inverse of :func:`render`, used by the federation route to fold
    worker scrapes. Tolerates the trailing ``# EOF`` and unknown comment
    lines; samples seen before any ``# TYPE`` get type ``untyped``."""
    fams: List[dict] = []
    by_name: Dict[str, dict] = {}
    helps: Dict[str, str] = {}

    def fam_for(sample_name: str) -> dict:
        # histogram/summary samples attach to their base family name
        base = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[: -len(suffix)] in by_name:
                base = base[: -len(suffix)]
                break
        if base not in by_name:
            by_name[base] = family(
                base, "untyped", helps.get(base, ""), []
            )
            fams.append(by_name[base])
        return by_name[base]

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                name = parts[2]
                ftype = parts[3] if len(parts) > 3 else "untyped"
                if name not in by_name:
                    by_name[name] = family(name, ftype, helps.get(name, ""), [])
                    fams.append(by_name[name])
                else:
                    by_name[name]["type"] = ftype
            elif len(parts) >= 3 and parts[1] == "HELP":
                name = parts[2]
                help_ = parts[3] if len(parts) > 3 else ""
                helps[name] = help_
                if name in by_name:
                    by_name[name]["help"] = help_
            continue
        if "{" in line:
            brace = line.index("{")
            sname = line[:brace]
            close = line.rindex("}")
            labels = _parse_labels(line[brace + 1:close])
            value = _parse_value(line[close + 1:].strip().split()[0])
        else:
            fields = line.split()
            sname, value, labels = fields[0], _parse_value(fields[1]), {}
        fam_for(sname)["samples"].append((sname, labels, value))
    return fams


def federated_families(expositions: Dict[str, str]) -> List[dict]:
    """Fold per-worker expositions (shard label -> scrape text) into one
    family list: every sample is re-emitted verbatim with a ``shard``
    label added, families merged by name (first worker's TYPE/HELP wins,
    stable order). Values pass through untouched, so each (series, shard)
    stream keeps the source counter's lifetime monotonicity — the r10
    Prometheus rule federates shard-wise instead of summing away."""
    merged: List[dict] = []
    by_name: Dict[str, dict] = {}
    for shard, text in expositions.items():
        for fam in parse_exposition(text):
            tgt = by_name.get(fam["name"])
            if tgt is None:
                tgt = family(fam["name"], fam["type"], fam["help"], [])
                by_name[fam["name"]] = tgt
                merged.append(tgt)
            tgt["samples"].extend(
                (sname, {**labels, "shard": str(shard)}, value)
                for sname, labels, value in fam["samples"]
            )
    return merged


def cluster_families(cluster, bus=None) -> List[dict]:
    """Metric families for one scalar-engine Cluster node."""
    mp = cluster.membership_protocol
    member = cluster.member()
    base = {"engine": "scalar", "member": member.id}
    fams = [
        family(
            f"{PREFIX}_cluster_size", "gauge",
            "Members in this node's view (incl. itself).",
            [(f"{PREFIX}_cluster_size", base, len(mp.members()))],
        ),
        family(
            f"{PREFIX}_incarnation", "gauge",
            "This node's own incarnation number.",
            [(f"{PREFIX}_incarnation", base, mp.incarnation)],
        ),
        family(
            f"{PREFIX}_members", "gauge",
            "Members by status, as seen by this node.",
            [
                (f"{PREFIX}_members", {**base, "status": "alive"},
                 len(mp.alive_members())),
                (f"{PREFIX}_members", {**base, "status": "suspected"},
                 len(mp.suspected_members())),
                (f"{PREFIX}_members", {**base, "status": "removed"},
                 len(mp.removed_members())),
            ],
        ),
    ]
    if bus is not None:
        fams.extend(_bus_families(bus))
    return fams
