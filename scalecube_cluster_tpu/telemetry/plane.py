"""TelemetryPlane: one object wiring rings + bus + exporter + flight recorder
onto a :class:`..sim.SimDriver`.

Arming (``SimDriver.arm_telemetry``) is consumer-NEUTRAL in the r6 sense:
the per-window work is one pure-jnp reduction (the engine's
``telemetry_window_vector`` plus the armed chaos runner's sentinel margins)
appended to the device metric ring by a donated jitted update — zero
device→host transfers, zero effect on the protocol state trajectory
(the row is computed FROM the window's metric outputs; it never feeds back
into the tick). Host transfers happen only at the explicit sync points:
a ``/metrics`` scrape, :meth:`collect`, or a flight-recorder dump.
"""

from __future__ import annotations

from typing import List, Optional

from ..config import TelemetryConfig
from .bus import TelemetryBus
from .flight import default_dump_path, write_flight_dump
from .openmetrics import Histogram, driver_families, render
from .rings import MetricRing

#: ring columns appended after the engine series: the armed chaos runner's
#: latching sentinel accumulators, sampled per window (0 when unarmed)
SENTINEL_SERIES = ("sentinel_false_dead_max", "sentinel_key_regressions")

#: default bucket boundaries for the tick-count histograms (detection
#: latency, rumor spread) — powers of two up to a long suspicion window
TICK_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


class TelemetryPlane:
    """The armed telemetry state of one driver (driver._telemetry)."""

    def __init__(self, driver, config: Optional[TelemetryConfig] = None,
                 bus: Optional[TelemetryBus] = None):
        import jax
        import jax.numpy as jnp

        cfg = config or TelemetryConfig()
        self.config = cfg
        self.driver = driver
        # ONE engine-dispatch spelling (r11): the ring's series layout and
        # window-vector reduction come from the EngineOps descriptor
        from ..ops import engine_api

        eng = engine_api.of_driver(driver)
        self.names = tuple(eng.telemetry_series) + SENTINEL_SERIES
        self.ring = MetricRing(self.names, cfg.ring_len, mesh=driver.mesh)
        self.bus = bus or TelemetryBus(cfg.bus_capacity)
        self.hist_dispatch = Histogram(cfg.latency_buckets)
        self.hist_tick = Histogram(cfg.latency_buckets)
        self.hist_detection = Histogram(TICK_BUCKETS)
        self.hist_spread = Histogram(TICK_BUCKETS)
        self.flight_dumps: List[str] = []
        # one cached device zero for the unarmed sentinel columns (a fresh
        # jnp scalar per window would be a per-window host→device upload)
        self._zero = jnp.int32(0)
        vector_fn = eng.telemetry_window_vector
        if "shard_peak_mem_mb" in self.names:
            # r21: the per-shard donated-state footprint is computed ONCE at
            # arm time from host-side sharding metadata (no transfer) and
            # baked into the row jit as a trace-time constant — a per-window
            # host→device upload would break the zero-transfer contract
            import functools

            vector_fn = functools.partial(
                vector_fn, shard_mem_mb=self._shard_state_mb()
            )

        def _row(ms, state, false_dead, key_regr):
            return jnp.concatenate(
                [
                    vector_fn(ms, state),
                    jnp.stack([false_dead, key_regr]).astype(jnp.float32),
                ]
            )

        if driver.mesh is not None:
            # r21 sharded twin of the row reduction: output pinned replicated
            # so the ring append that consumes it stays a local write
            from ..ops.sharding import make_sharded_telemetry_row

            self._row_fn = make_sharded_telemetry_row(driver.mesh, _row)
        else:
            self._row_fn = jax.jit(_row)

    def _shard_state_mb(self) -> float:
        """Per-shard bytes of the driver's donated state, in MiB — pure host
        metadata (shapes × shardings × itemsizes), never a device read. On
        an unsharded driver this is the whole state footprint."""
        import jax

        total = 0
        for leaf in jax.tree_util.tree_leaves(self.driver.state):
            shape = tuple(leaf.shape)
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None:
                shape = tuple(sharding.shard_shape(shape))
            n = 1
            for dim in shape:
                n *= int(dim)
            total += n * leaf.dtype.itemsize
        return total / (1024.0 * 1024.0)

    # -- the per-window device path (called under the driver lock) -----------
    def on_window(self, ms, state, n_ticks: int, dispatch_s: float) -> None:
        """Fold one window into the ring (pure device ops) and the host-side
        latency histograms (wall-clock only — no transfers)."""
        runner = self.driver._chaos
        sent = getattr(runner, "_sent", None) if runner is not None else None
        false_dead = sent["false_dead_max"] if sent else self._zero
        key_regr = sent["key_regressions"] if sent else self._zero
        self.ring.append(self._row_fn(ms, state, false_dead, key_regr))
        self.hist_dispatch.observe(dispatch_s)
        self.hist_tick.observe(dispatch_s / max(n_ticks, 1))

    # -- sync points ----------------------------------------------------------
    # Every ring read takes the DRIVER lock: the sim thread's append donates
    # the ring buffer, so an unsynchronized monitor-thread read can hit the
    # deleted pre-append array ("Array has been deleted" — the same race the
    # r6 driver lock exists for). The lock is reentrant; sim-thread callers
    # nest fine.

    def collect(self, k: Optional[int] = None) -> dict:
        """Ring snapshot + bus stats (one coalesced device→host transfer)."""
        with self.driver._lock:
            snap = self.ring.snapshot(k)
        self.driver._note_readback(1)
        return {
            "ring": {
                "names": snap["names"],
                "windows": snap["windows"],
                "rows": [[float(v) for v in row] for row in snap["rows"]],
            },
            "bus": self.bus.stats(),
            "flight_dumps": list(self.flight_dumps),
        }

    def families(self) -> list:
        """This driver's OpenMetrics families — THE scrape path (the
        monitor's /metrics provider and :meth:`metrics_text` both route
        here, so the sync-point bookkeeping has one spelling)."""
        fams = driver_families(self.driver, self)
        self.driver._note_readback(1)  # the ring's newest-row read
        return fams

    def metrics_text(self) -> str:
        """The ``GET /metrics`` body — rendering IS the scrape sync point."""
        return render(self.families())

    # -- chaos ingestion -------------------------------------------------------
    def ingest_chaos_report(self, report: dict) -> Optional[str]:
        """Feed one FINAL scenario report: detection latencies into the
        histogram, sentinel outcomes onto the bus, and — on any violation —
        a flight-recorder dump. Returns the dump path if one was written.
        Call once per completed scenario (the runner does)."""
        sent = report.get("sentinels") or {}
        for det in sent.get("detections", ()):
            if det.get("detected_at") is not None:
                self.hist_detection.observe(
                    det["detected_at"] - det["crashed_at"]
                )
        self.bus.publish(
            "chaos", "scenario_complete", tick=self.driver._host_tick,
            scenario=report.get("scenario", "?"),
            violations=report.get("violations", 0),
            ok=report.get("ok", True),
        )
        if report.get("violations"):
            return self.flight_record(
                "sentinel_violation",
                context={
                    "scenario": report.get("scenario"),
                    "violations": report.get("violations"),
                    "sentinels": sent,
                },
            )
        return None

    # -- flight recorder -------------------------------------------------------
    def _reconstruction_section(self) -> Optional[dict]:
        """The schema-2 reconstruction block: everything ``replay.py`` needs
        to rebuild a fresh driver + scenario and RE-RUN the incident. Only
        an armed chaos runner makes a dump reconstructable — without one
        there is no event timeline to replay, and the loader marks the
        artifact ``reconstruction: "partial"`` instead."""
        import dataclasses

        runner = getattr(self.driver, "_chaos", None)
        if runner is None:
            return None
        from ..chaos.events import scenario_to_dict

        d = self.driver
        last = runner.last_report
        verdict = None
        if last is not None and last.get("sentinels") is not None:
            verdict = {
                "ok": bool(last.get("ok", True)),
                "violations": int(last.get("violations", 0)),
                "ticks_run": int(last.get("ticks_run", runner.rel_tick)),
            }
        return {
            "engine": d.engine,
            "n_initial": int(d.n_initial),
            "capacity": int(d.params.capacity),
            # seed is None on drivers older than the r18 stamp (a restored
            # pickle, a hand-built harness) — replay then refuses loudly
            "seed": getattr(d, "seed", None),
            "warm": bool(getattr(d, "_init_warm", True)),
            "dense_links": bool(d._dense_links),
            "params": dataclasses.asdict(d.params),
            "scenario": scenario_to_dict(runner.scenario),
            "t0": int(runner.t0),
            "max_window": int(runner.max_window),
            "ticks_run": int(runner.rel_tick),
            "sentinels_armed": runner._sent is not None,
            "verdict": verdict,
            # r21: mesh shape stamp for sharded drivers — a SIBLING key of
            # ``params`` (``replay.params_from_doc`` refuses unknown params
            # fields). Replay reconstructs UNSHARDED, which is sound: the
            # sharded trajectory is bit-identical to the single-device one.
            "mesh_axes": (
                {str(k): int(v) for k, v in dict(d.mesh.shape).items()}
                if d.mesh is not None else None
            ),
        }

    def flight_record(self, reason: str, context: Optional[dict] = None,
                      path: Optional[str] = None) -> str:
        """Dump the last K ring windows + the bus tail atomically; returns
        the artifact path. Reading the ring here is a sync point — by
        design: the flight is recorded when something already went wrong."""
        self.bus.publish(
            "flight", "dump", tick=self.driver._host_tick, reason=reason
        )
        with self.driver._lock:
            snap = self.ring.snapshot(self.config.flight_windows)
        self.driver._note_readback(1)
        # r10: an armed trace plane contributes the causal section — the
        # trace-ring tail + sewn span trees for the violating members (rows
        # of failed detection obligations in the context, when present)
        trace_doc = None
        tplane = getattr(self.driver, "_trace", None)
        if tplane is not None:
            sent = (context or {}).get("sentinels") or {}
            bad = [
                det["row"] for det in sent.get("detections", ())
                if not det.get("ok", True)
            ] or list(tplane.spec.tracer_rows)
            trace_doc = tplane.flight_section(bad)
        target = path or default_dump_path(self.config.flight_dir, reason)
        recon = self._reconstruction_section()
        tick_hi = int(self.driver._host_tick)
        tick_lo = int(recon["t0"]) if recon is not None else 0
        out = write_flight_dump(
            target,
            reason=reason,
            engine=self.driver.engine,
            ring_snapshot=snap,
            bus_tail=[r.as_dict() for r in self.bus.tail()],
            context=context,
            trace=trace_doc,
            reconstruction=recon,
            tick_range=[tick_lo, tick_hi],
        )
        self.flight_dumps.append(out)
        return out

    # -- timestamping hook for bus adapters -----------------------------------
    def tick_now(self) -> int:
        """The driver's host-side tick shadow (never a device read)."""
        return self.driver._host_tick
