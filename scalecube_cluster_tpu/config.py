"""Immutable configuration with LAN / WAN / local / sim profiles.

Parity with reference cluster-api configs:

* ``ClusterConfig`` (``ClusterConfig.java:25-428``) — root config with nested
  sub-configs mutated through functional lenses (``UnaryOperator`` in the
  reference; plain ``cfg.membership(lambda m: m.replace(...))`` here),
  member-id generator, alias, external host/port NAT mapping.
* ``FailureDetectorConfig`` (``FailureDetectorConfig.java:9-21``) — LAN
  1000/500/3, WAN 5000/3000/3, local 1000/200/1 (ms).
* ``GossipConfig`` (``GossipConfig.java:9-20``) — LAN 200ms/f3/m3, WAN fanout
  4, local 100ms/m2; segmentation threshold 1000.
* ``MembershipConfig`` (``MembershipConfig.java:14-32``) — LAN 30s/3s/5, WAN
  60s/6, local 15s/3; namespace "default"; removed-history 42.
* ``TransportConfig`` (``TransportConfig.java:8-22``) — port 0, connect
  timeout 3s, max frame 2MB, pluggable codec/factory.

Additional ``sim`` profile (new, no reference analogue): knobs for the
vectorized TPU simulation — tick granularity, dense-link emulation, member
capacity, rumor-slot count.

All times are float seconds (the reference uses ms ints; seconds compose
better with asyncio and with tick-time mapping in the kernel).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field, replace
from typing import Callable, List, Optional, Sequence

from .models.member import new_member_id
from .utils.namespaces import validate_namespace

DEFAULT_NAMESPACE = "default"


@dataclass(frozen=True)
class FailureDetectorConfig:
    """Random-probe failure detector knobs (reference FailureDetectorConfig.java)."""

    ping_interval: float = 1.0
    ping_timeout: float = 0.5
    ping_req_members: int = 3

    def replace(self, **kw) -> "FailureDetectorConfig":
        return replace(self, **kw)

    @staticmethod
    def default_lan() -> "FailureDetectorConfig":
        return FailureDetectorConfig()

    @staticmethod
    def default_wan() -> "FailureDetectorConfig":
        return FailureDetectorConfig(ping_interval=5.0, ping_timeout=3.0)

    @staticmethod
    def default_local() -> "FailureDetectorConfig":
        return FailureDetectorConfig(ping_interval=1.0, ping_timeout=0.2, ping_req_members=1)


@dataclass(frozen=True)
class GossipConfig:
    """Infection-style dissemination knobs (reference GossipConfig.java)."""

    gossip_interval: float = 0.2
    gossip_fanout: int = 3
    gossip_repeat_mult: int = 3
    gossip_segmentation_threshold: int = 1000

    def replace(self, **kw) -> "GossipConfig":
        return replace(self, **kw)

    @staticmethod
    def default_lan() -> "GossipConfig":
        return GossipConfig()

    @staticmethod
    def default_wan() -> "GossipConfig":
        return GossipConfig(gossip_fanout=4)

    @staticmethod
    def default_local() -> "GossipConfig":
        return GossipConfig(gossip_interval=0.1, gossip_repeat_mult=2)


@dataclass(frozen=True)
class MembershipConfig:
    """SWIM membership + suspicion + SYNC knobs (reference MembershipConfig.java)."""

    seed_members: Sequence[str] = ()
    sync_interval: float = 30.0
    sync_timeout: float = 3.0
    suspicion_mult: int = 5
    namespace: str = DEFAULT_NAMESPACE
    removed_members_history_size: int = 42

    def replace(self, **kw) -> "MembershipConfig":
        return replace(self, **kw)

    @staticmethod
    def default_lan() -> "MembershipConfig":
        return MembershipConfig()

    @staticmethod
    def default_wan() -> "MembershipConfig":
        return MembershipConfig(sync_interval=60.0, suspicion_mult=6)

    @staticmethod
    def default_local() -> "MembershipConfig":
        return MembershipConfig(sync_interval=15.0, suspicion_mult=3)


@dataclass(frozen=True)
class TransportConfig:
    """Transport knobs (reference TransportConfig.java:8-22)."""

    port: int = 0  # 0 = ephemeral
    host: str = "127.0.0.1"
    connect_timeout: float = 3.0
    max_frame_length: int = 2 * 1024 * 1024
    message_codec: str = "jdk"  # codec registry key, see transport/codecs.py
    transport_factory: Optional[str] = None  # factory registry key; None -> default
    # Bounded reconnect for the stream (TCP/WebSocket) outbound path: a
    # failed connect or mid-send connection drop retries up to
    # ``reconnect_max_retries`` extra times with exponential backoff
    # (base * 2^attempt, capped at max, +-50% jitter so a rebooting peer
    # isn't stampeded); the give-up surfaces as a "reconnect_giveup"
    # transport event. 0 retries restores the old fail-fast behavior.
    reconnect_max_retries: int = 2
    reconnect_base_delay: float = 0.05
    reconnect_max_delay: float = 1.0

    def replace(self, **kw) -> "TransportConfig":
        return replace(self, **kw)


@dataclass(frozen=True)
class SimConfig:
    """Vectorized-simulation knobs (new; no reference analogue).

    ``tick_interval`` is the wall-clock meaning of one kernel tick; by default
    equal to the gossip interval so one tick = one gossip period and FD /
    sync rounds fire every ``ping_interval / tick_interval`` ticks.
    """

    capacity: int = 0  # max member rows; 0 -> derived from initial cluster size
    tick_interval: float = 0.2
    rumor_slots: int = 64  # concurrent user-rumor capacity per cluster
    # reserved: bounded per-node piggyback ring for the sparse record-queue
    # tick (README §Roadmap); the dense kernel derives the piggyback set
    # from changed_at ages instead
    record_queue: int = 32
    dense_links: bool = True  # dense NxN loss/delay matrices (sim emulator)
    delay_slots: int = 0  # pending-delivery ring depth (max link delay + 1 ticks)
    # Precedence-key plane dtype (r9 bit-plane compaction): "i32" is the
    # r0-r8 wide layout (the oracle-lockstep default — incarnations to
    # 2^21, 256 row-reuse epochs); "i16" halves the dominant [N, N] key
    # plane and switches the dense kernel to word-parallel packed-mask
    # sweeps (ops/bitplane.py), under the narrow saturation rule
    # (incarnation cap 511 + epoch fold 16 — lattice.KeyLayout).
    plane_dtype: str = "i32"
    # Partial-view engine knobs (r11, ops/pview.py): ``view_slots`` is k,
    # the per-member neighbor-table width ([N, k] — the O(N·k) memory
    # budget); ``active_slots`` the HyParView-style active-view prefix
    # sampled for FD probes / gossip fanout / SYNC peers (the remainder is
    # the passive healing reservoir refreshed by the SYNC-folded shuffle).
    view_slots: int = 24
    active_slots: int = 8
    seed: int = 0
    # Persistent XLA compilation-cache directory (None = disabled; the
    # SCALECUBE_COMPILE_CACHE_DIR env var is the non-config fallback).
    # Keyed on the lowered program, which covers capacity / mesh / every
    # static kernel knob — repeated bench runs and the flagship program
    # skip recompilation (see scalecube_cluster_tpu.compile_cache).
    compile_cache_dir: Optional[str] = None

    def replace(self, **kw) -> "SimConfig":
        return replace(self, **kw)


@dataclass(frozen=True)
class DisseminationConfig:
    """Dissemination strategy-zoo knobs (r13; no reference analogue — the
    reference ships uniform-random push only, ``GossipProtocolImpl``).

    ``strategy`` selects the gossip phase's peer-selection + payload
    policy (``push`` / ``push_pull`` / ``pipelined`` / ``accelerated``)
    and ``topology`` the overlay the fanout peers are drawn from
    (``full`` / ``ring`` / ``torus`` / ``expander`` / ``geo``) — see
    ``dissemination/spec.py`` for the catalog and docs/DISSEMINATION.md
    for the certified-bound table. The defaults reproduce the legacy
    program byte-for-byte. FD probes and SYNC anti-entropy always keep
    the reference's uniform semantics."""

    strategy: str = "push"
    topology: str = "full"
    degree: int = 0  # expander/geo chord budget (0 = auto ceil_log2)
    torus_rows: int = 0  # 0 = auto (largest divisor <= sqrt(N))
    geo_zones: int = 4
    geo_wan_delay_ticks: int = 0  # mean cross-zone delay, in ticks
    pipeline_budget: int = 1  # pipelined: rumor slots per message
    tuneable_mix: float = 0.5  # tuneable: P(deterministic chord) per send

    def replace(self, **kw) -> "DisseminationConfig":
        return replace(self, **kw)


@dataclass(frozen=True)
class AdaptiveConfig:
    """Adaptive failure-detection knobs (r14; Lifeguard-lineage — no
    reference analogue: scalecube-cluster ships static suspicion math).

    ``enabled=False`` (the default) keeps the byte-identical legacy window
    programs. Armed, each member tracks a local-health score (own
    probe-miss/refutation evidence, ``lh_max`` cap) scaling its direct
    probe timeout and the suspicion sweeps it runs, and suspicion
    time-to-DEAD interpolates log-scaled from ``max_mult`` (lone
    accusation) to ``min_mult`` (>= ``conf_target`` accepted
    confirmations). See ``adaptive.py`` / docs/ADAPTIVE_FD.md."""

    enabled: bool = False
    lh_max: int = 8
    min_mult: int = 5
    max_mult: int = 10
    conf_target: int = 4

    def replace(self, **kw) -> "AdaptiveConfig":
        return replace(self, **kw)


@dataclass(frozen=True)
class ControlConfig:
    """Closed-loop control-plane knobs (r16; no reference analogue — the
    telemetry-driven knob-steering controller, see ``control.py`` /
    docs/CONTROL.md).

    The loop constants only: ``epoch_windows`` windows per control epoch
    (sensor reads + decisions run at epoch cadence), the asymmetric
    anti-flap dwell (``dwell_up`` epochs to raise protection,
    ``dwell_down`` to relax it), the per-epoch actuation clamp
    (``max_step`` ladder rungs), and the downward ``hysteresis`` margin.
    The rung LADDER itself is code (``control.DEFAULT_LADDER``, seeded
    from the offline adaptive-knob map) — pass a custom
    ``control.ControlSpec`` to ``SimDriver.arm_control`` to change it."""

    epoch_windows: int = 4
    dwell_up: int = 2
    dwell_down: int = 4
    max_step: int = 1
    hysteresis: float = 0.6
    #: r19: false-positive pressure gate — a ``suspect_rate`` (new
    #: suspicions per probe) at or above this votes the target ONE rung up
    #: through the normal dwell machinery. 0.0 (default) keeps the sensor
    #: passive/logged-only, the r16-certified behavior.
    suspect_gate: float = 0.0
    #: r21: spread-lag gate (ROADMAP item 4) — a view dissemination
    #: deficit (``convergence_lag``, measured only when
    #: ``alive_view_fraction`` is live) at or above this votes the target
    #: ONE rung up through the same dwell machinery. 0.0 keeps it passive.
    spread_lag_gate: float = 0.0

    def replace(self, **kw) -> "ControlConfig":
        return replace(self, **kw)


@dataclass(frozen=True)
class ChaosConfig:
    """Chaos scenario-engine knobs (new; no reference analogue — the sim's
    fault-injection + invariant-sentinel subsystem, see ``chaos/``).

    ``check_interval_ticks`` is the sentinel sampling cadence (sentinel facts
    are latching/monotone, so sampling is sound and keeps an armed-but-idle
    engine within noise of the plain pipelined driver). The budgets default
    to protocol math when 0 (suspicion window + dissemination slack for
    detection; 8 sync intervals + detection slack for re-convergence).
    ``loss_storm_immunity_pct`` is the uniform-loss level at or above which
    the no-false-DEAD sentinel stops vouching for untouched members (heavy
    adversarial loss can legitimately suspect anyone)."""

    check_interval_ticks: int = 32
    detect_budget_ticks: int = 0  # 0 = auto from protocol math
    converge_budget_ticks: int = 0  # 0 = auto
    loss_storm_immunity_pct: float = 50.0

    def replace(self, **kw) -> "ChaosConfig":
        return replace(self, **kw)


@dataclass(frozen=True)
class TelemetryConfig:
    """Telemetry-plane knobs (new; no reference analogue — the r8 on-device
    metric rings, unified event bus, OpenMetrics exporter, and crash flight
    recorder, see ``telemetry/``).

    ``ring_len`` is the number of per-window rows the device metric ring
    retains ([ring_len, n_metrics] f32, overwritten circularly — host reads
    happen only at flush()/scrape sync points, never per window).
    ``bus_capacity`` bounds the unified event bus (oldest records are
    evicted; evictions are counted, never silent). ``flight_windows`` is K,
    the ring-window depth a flight-recorder dump captures, and
    ``flight_dir`` is where dump artifacts land (None = current directory
    at dump time). ``latency_buckets`` are the histogram bucket upper
    bounds, in seconds, for the window-dispatch / tick-latency histograms
    the ``/metrics`` endpoint exports."""

    ring_len: int = 512
    bus_capacity: int = 4096
    flight_windows: int = 64
    flight_dir: Optional[str] = None
    latency_buckets: Sequence[float] = (
        0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    )

    def replace(self, **kw) -> "TelemetryConfig":
        return replace(self, **kw)


@dataclass(frozen=True)
class TraceConfig:
    """Causal-trace-plane knobs (new; no reference analogue — the r10
    on-device protocol span capture, see ``trace/``). The reference gets
    causal traces for free from per-message DEBUG logs; the lockstep
    tensor engine samples K "tracer" members + T traced rumor slots into a
    fixed-shape device ring instead.

    ``tracers`` — how many tracer members to sample when no explicit
    ``tracer_rows`` are given (the first K rows). ``tracer_rows`` —
    explicit tracer rows (wins over ``tracers``). ``rumor_slots`` — the
    traced user-rumor slots (their infection trees are sewable).
    ``ring_len`` — device trace-ring rows retained ([ring_len, n_fields]
    int32; K rows append per tick, so ring_len/K ticks of history).
    ``tick_us`` — microseconds one tick maps to in Perfetto exports
    (display scaling only; never touches the engine)."""

    tracers: int = 4
    tracer_rows: Sequence[int] = ()
    rumor_slots: Sequence[int] = ()
    ring_len: int = 8192
    tick_us: float = 1000.0

    def replace(self, **kw) -> "TraceConfig":
        return replace(self, **kw)


Lens = Callable


@dataclass(frozen=True)
class ClusterConfig:
    """Root config. Copy-on-write: every ``xxx()`` lens returns a new config
    (reference ClusterConfig fluent API, ClusterImpl.java:143-226)."""

    failure_detector: FailureDetectorConfig = field(default_factory=FailureDetectorConfig)
    gossip: GossipConfig = field(default_factory=GossipConfig)
    membership: MembershipConfig = field(default_factory=MembershipConfig)
    transport: TransportConfig = field(default_factory=TransportConfig)
    sim: SimConfig = field(default_factory=SimConfig)
    dissemination: DisseminationConfig = field(default_factory=DisseminationConfig)
    adaptive: AdaptiveConfig = field(default_factory=AdaptiveConfig)
    control: ControlConfig = field(default_factory=ControlConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)

    member_alias: Optional[str] = None
    external_host: Optional[str] = None  # container NAT mapping (ClusterConfig.java:236-300)
    external_port: Optional[int] = None
    metadata: Optional[bytes] = None
    metadata_timeout: float = 3.0
    metadata_codec: str = "jdk"
    member_id_generator: Callable[[], str] = field(default=new_member_id, compare=False)

    # -- profiles (reference ClusterConfig.java:54-93) ---------------------
    @staticmethod
    def default_lan() -> "ClusterConfig":
        return ClusterConfig()

    @staticmethod
    def default_wan() -> "ClusterConfig":
        return ClusterConfig(
            failure_detector=FailureDetectorConfig.default_wan(),
            gossip=GossipConfig.default_wan(),
            membership=MembershipConfig.default_wan(),
        )

    @staticmethod
    def default_local() -> "ClusterConfig":
        return ClusterConfig(
            failure_detector=FailureDetectorConfig.default_local(),
            gossip=GossipConfig.default_local(),
            membership=MembershipConfig.default_local(),
        )

    @staticmethod
    def default_sim() -> "ClusterConfig":
        """Profile for the vectorized simulation: local-ish timers, tick-aligned."""
        cfg = ClusterConfig.default_local()
        return dataclasses.replace(cfg, sim=SimConfig(tick_interval=cfg.gossip.gossip_interval))

    # -- functional lenses over sub-configs --------------------------------
    def with_failure_detector(self, op: Lens) -> "ClusterConfig":
        return replace(self, failure_detector=op(self.failure_detector))

    def with_gossip(self, op: Lens) -> "ClusterConfig":
        return replace(self, gossip=op(self.gossip))

    def with_membership(self, op: Lens) -> "ClusterConfig":
        return replace(self, membership=op(self.membership))

    def with_transport(self, op: Lens) -> "ClusterConfig":
        return replace(self, transport=op(self.transport))

    def with_sim(self, op: Lens) -> "ClusterConfig":
        return replace(self, sim=op(self.sim))

    def with_dissemination(self, op: Lens) -> "ClusterConfig":
        return replace(self, dissemination=op(self.dissemination))

    def with_adaptive(self, op: Lens) -> "ClusterConfig":
        return replace(self, adaptive=op(self.adaptive))

    def with_control(self, op: Lens) -> "ClusterConfig":
        return replace(self, control=op(self.control))

    def with_chaos(self, op: Lens) -> "ClusterConfig":
        return replace(self, chaos=op(self.chaos))

    def with_telemetry(self, op: Lens) -> "ClusterConfig":
        return replace(self, telemetry=op(self.telemetry))

    def with_trace(self, op: Lens) -> "ClusterConfig":
        return replace(self, trace=op(self.trace))

    def replace(self, **kw) -> "ClusterConfig":
        return replace(self, **kw)

    # -- validation (reference ClusterImpl.validateConfiguration :314-354) -
    def validate(self) -> "ClusterConfig":
        validate_namespace(self.membership.namespace)
        if self.failure_detector.ping_interval <= 0:
            raise ValueError("ping_interval must be > 0")
        if self.failure_detector.ping_timeout <= 0:
            raise ValueError("ping_timeout must be > 0")
        if self.failure_detector.ping_timeout >= self.failure_detector.ping_interval:
            raise ValueError("ping_timeout must be < ping_interval")
        if self.gossip.gossip_interval <= 0:
            raise ValueError("gossip_interval must be > 0")
        if self.gossip.gossip_fanout <= 0:
            raise ValueError("gossip_fanout must be > 0")
        if self.gossip.gossip_repeat_mult <= 0:
            raise ValueError("gossip_repeat_mult must be > 0")
        if self.membership.sync_interval <= 0:
            raise ValueError("sync_interval must be > 0")
        if self.membership.suspicion_mult <= 0:
            raise ValueError("suspicion_mult must be > 0")
        if self.metadata_timeout <= 0:
            raise ValueError("metadata_timeout must be > 0")
        if self.transport.reconnect_max_retries < 0:
            raise ValueError("reconnect_max_retries must be >= 0")
        if self.transport.reconnect_base_delay < 0:
            raise ValueError("reconnect_base_delay must be >= 0")
        if self.sim.plane_dtype not in ("i32", "i16"):
            raise ValueError("sim.plane_dtype must be 'i32' or 'i16'")
        if not (0 < self.sim.active_slots < self.sim.view_slots):
            raise ValueError(
                "need 0 < sim.active_slots < sim.view_slots (the pview "
                "passive reservoir must be non-empty)"
            )
        # the spec dataclass owns strategy/topology validation (one
        # spelling for config- and params-level construction)
        from .dissemination.spec import DissemSpec

        DissemSpec.from_config(self)
        # the adaptive spec dataclass owns its knob validation likewise
        from .adaptive import AdaptiveSpec

        AdaptiveSpec.from_config(self)
        # the control spec dataclass owns the loop-constant validation
        from .control import ControlSpec

        ControlSpec.from_config(self)
        if self.chaos.check_interval_ticks <= 0:
            raise ValueError("chaos.check_interval_ticks must be > 0")
        if not (0.0 <= self.chaos.loss_storm_immunity_pct <= 100.0):
            raise ValueError("chaos.loss_storm_immunity_pct must be in [0, 100]")
        if self.telemetry.ring_len <= 0:
            raise ValueError("telemetry.ring_len must be > 0")
        if self.telemetry.bus_capacity <= 0:
            raise ValueError("telemetry.bus_capacity must be > 0")
        if self.telemetry.flight_windows <= 0:
            raise ValueError("telemetry.flight_windows must be > 0")
        if list(self.telemetry.latency_buckets) != sorted(
            self.telemetry.latency_buckets
        ) or any(b <= 0 for b in self.telemetry.latency_buckets):
            raise ValueError(
                "telemetry.latency_buckets must be positive and ascending"
            )
        if self.trace.ring_len <= 0:
            raise ValueError("trace.ring_len must be > 0")
        if self.trace.tracers <= 0 and not self.trace.tracer_rows:
            raise ValueError(
                "trace.tracers must be > 0 (or set explicit trace.tracer_rows)"
            )
        if any(r < 0 for r in self.trace.tracer_rows):
            raise ValueError("trace.tracer_rows must be non-negative")
        if any(s < 0 for s in self.trace.rumor_slots):
            raise ValueError("trace.rumor_slots must be non-negative")
        if self.trace.tick_us <= 0:
            raise ValueError("trace.tick_us must be > 0")
        return self


def suspicion_timeout_for(config: ClusterConfig, cluster_size: int) -> float:
    """Suspicion timeout derived from config + cluster size (seconds)."""
    from .utils.cluster_math import suspicion_timeout

    return suspicion_timeout(
        config.membership.suspicion_mult, cluster_size, config.failure_detector.ping_interval
    )
