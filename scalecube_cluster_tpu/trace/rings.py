"""The donated device trace ring: fixed-shape [ring_len, n_fields] int32.

Same discipline as the r8 metric ring (``telemetry/rings.py``), scaled to
K rows per TICK instead of one row per window: the buffer lives on device
and is threaded THROUGH the traced window program (the scan body appends
each tick's [K, F] block in place; the driver donates the buffer alongside
the state), so an armed trace plane adds zero per-window device→host
transfers. The cursor is HOST state — appends per window are a static
``K * n_ticks``, so the host always knows where the ring stands without a
device read; :meth:`last` / :meth:`snapshot` are the sync points.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .schema import TraceSpec


class TraceRing:
    """Host handle of the device trace buffer + its cursor arithmetic."""

    def __init__(self, spec: TraceSpec):
        import jax.numpy as jnp

        self.spec = spec
        self.buf = jnp.zeros((spec.ring_len, spec.n_fields), jnp.int32)
        # set by the driver when tracing on a mesh (r20): clear() must
        # reallocate the buffer REPLICATED there, not on the default device
        self._mesh = None
        # records in the CURRENT timeline (cursor = records % ring_len);
        # host state — advanced by the driver after each traced window
        self.records = 0
        # lifetime append count: MONOTONE (survives the restore-path
        # clear()) — the /metrics counter source; a Prometheus counter
        # must never decrease short of a process restart
        self.records_total = 0

    @property
    def cursor(self) -> int:
        return self.records % self.spec.ring_len

    @property
    def wraps(self) -> int:
        """Times the current timeline's ring lapped itself."""
        return self.records // self.spec.ring_len

    @property
    def wraps_total(self) -> int:
        """Lifetime lap count (monotone — the /metrics counter source)."""
        return self.records_total // self.spec.ring_len

    def clear(self) -> None:
        """Drop every retained record (fresh zeroed buffer, cursor 0) —
        the restore path: records from an abandoned timeline must not sew
        into the restored one. The lifetime totals keep counting."""
        import jax.numpy as jnp

        self.buf = jnp.zeros((self.spec.ring_len, self.spec.n_fields),
                             jnp.int32)
        if self._mesh is not None:
            from ..ops.sharding import place_replicated

            self.buf = place_replicated(self.buf, self._mesh)
        self.records = 0

    def device_cursor(self):
        """The cursor as a device scalar for the next window's append
        chain (an upload, never a readback)."""
        import jax.numpy as jnp

        return jnp.int32(self.cursor)

    def advance(self, n_records: int) -> None:
        self.records += int(n_records)
        self.records_total += int(n_records)

    def last(self, k: Optional[int] = None) -> np.ndarray:
        """The newest ``k`` records (default: all retained), OLDEST first —
        one coalesced device→host transfer through the shared
        ``telemetry.rings.ring_tail`` unroll. Callers must hold the driver
        lock (the per-window append donates this buffer)."""
        from ..telemetry.rings import ring_tail

        return np.asarray(
            ring_tail(self.buf, self.records, self.spec.ring_len, k),
            np.int32,
        )

    def snapshot(self, k: Optional[int] = None) -> Dict[str, object]:
        return {
            "fields": self.spec.field_names(),
            "ring_len": self.spec.ring_len,
            "records": self.records,
            "records_total": self.records_total,
            "cursor": self.cursor,
            "wraps": self.wraps,
            "rows": self.last(k),
        }
