"""Render sewn spans and profiler timelines as Chrome-trace/Perfetto JSON.

The Chrome trace-event format (the JSON Perfetto and ``chrome://tracing``
both load) is the one observability surface everything funnels into:

* protocol span trees (:mod:`.spans`) — "X" complete events on a
  tick-clock track, one process per subject, nesting by span depth, with
  span events as "i" instants;
* rumor infection trees — one "X" per infected node at its arrival tick,
  thread = tree depth, so the waterfall IS the propagation tree;
* tick-phase profiler runs (:mod:`.profile`) — "X" events on a wall-clock
  track, one thread per phase.

Two clocks coexist (protocol ticks vs host wall): each rides its own
``pid`` track and ticks are mapped to microseconds via ``tick_us``
(default 1000 µs = 1 ms per tick, so a 200 ms gossip period renders
compactly). Everything is stdlib-only and JSON-ready; ``json.dump`` of any
return value is a loadable Perfetto file (the tier-1 test holds that).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from .spans import flatten_spans

#: pid tracks of the combined timeline
PID_HOST = 1  # wall-clock: host dispatch + device phase timings
PID_SPANS = 2  # tick-clock: protocol span trees
PID_RUMORS = 3  # tick-clock: rumor infection trees


def _meta(pid: int, name: str) -> Dict:
    return {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": name}}


def spans_to_events(
    tree: Dict, tick_us: float = 1000.0, pid: int = PID_SPANS
) -> List[Dict]:
    """One span tree -> Chrome events ("X" per span, "i" per span event).
    Thread id = nesting depth, so the track renders as a flame of the
    probe-miss → suspect → dead chain."""
    out: List[Dict] = []

    def _walk(node, depth):
        start = node["start_tick"] * tick_us
        dur = max(node["end_tick"] - node["start_tick"], 1) * tick_us
        out.append({
            "name": node["name"],
            "cat": "protocol",
            "ph": "X",
            "ts": start,
            "dur": dur,
            "pid": pid,
            "tid": depth,
            "args": {
                "span_id": node["span_id"],
                "parent_span_id": node["parent_span_id"],
                **{k: v for k, v in node["attributes"].items()
                   if v is not None},
            },
        })
        for ev in node["events"]:
            out.append({
                "name": ev.get("name", "event"),
                "cat": "protocol",
                "ph": "i",
                "s": "t",
                "ts": ev["tick"] * tick_us,
                "pid": pid,
                "tid": depth,
                "args": {k: v for k, v in ev.items()
                         if k not in ("tick", "name")},
            })
        for c in node["children"]:
            _walk(c, depth + 1)

    _walk(tree, 0)
    return out


def rumor_tree_to_events(
    tree: Dict, tick_us: float = 1000.0, pid: int = PID_RUMORS
) -> List[Dict]:
    """One infection tree -> Chrome events: each node an "X" at its arrival
    tick on the thread of its tree depth, args carrying the infecting edge
    — the waterfall reads as the propagation frontier advancing."""
    out: List[Dict] = [{
        "name": f"rumor(slot={tree['slot']})",
        "cat": "rumor",
        "ph": "X",
        "ts": 0.0,
        "dur": max(tree.get("last_infection_tick") or 1, 1) * tick_us,
        "pid": pid,
        "tid": 0,
        "args": {"origin": tree["origin"], "n_infected": tree["n_infected"],
                 "depth": tree["depth"]},
    }]

    def _walk(node, depth):
        out.append({
            "name": f"infect(row={node['row']})",
            "cat": "rumor",
            "ph": "X",
            "ts": node["at"] * tick_us,
            "dur": tick_us,
            "pid": pid,
            "tid": depth + 1,
            "args": {"row": node["row"], "from": node["from"],
                     "at_tick": node["at"]},
        })
        for c in node["children"]:
            _walk(c, depth + 1)

    _walk(tree["root"], 0)
    return out


def profile_to_events(profile: Dict, pid: int = PID_HOST) -> List[Dict]:
    """A :func:`..trace.profile` result -> wall-clock Chrome events (one
    thread per phase; ts anchored at the run's own zero)."""
    out: List[Dict] = []
    tids = {}
    for ev in profile.get("timeline", ()):
        tid = tids.setdefault(ev["phase"], len(tids))
        out.append({
            "name": ev["phase"],
            "cat": "device_phase",
            "ph": "X",
            "ts": ev["start_s"] * 1e6,
            "dur": max(ev["dur_s"] * 1e6, 0.01),
            "pid": pid,
            "tid": tid,
            "args": {"tick": ev.get("tick")},
        })
    return out


def chrome_trace(
    span_trees: Sequence[Dict] = (),
    rumor_trees: Sequence[Dict] = (),
    profile: Optional[Dict] = None,
    tick_us: float = 1000.0,
) -> Dict:
    """The combined Perfetto document: protocol spans, rumor trees, and the
    phase profiler interleaved on their labelled clock tracks."""
    events: List[Dict] = []
    if profile is not None:
        events.append(_meta(PID_HOST, "host+device phases (wall clock)"))
        events.extend(profile_to_events(profile))
    if span_trees:
        events.append(_meta(PID_SPANS, "protocol spans (tick clock)"))
        for tree in span_trees:
            events.extend(spans_to_events(tree, tick_us))
    if rumor_trees:
        events.append(_meta(PID_RUMORS, "rumor infection trees (tick clock)"))
        for tree in rumor_trees:
            events.extend(rumor_tree_to_events(tree, tick_us))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"tick_us": tick_us, "source": "scalecube_cluster_tpu"},
    }


def to_otel_spans(span_trees: Sequence[Dict]) -> List[Dict]:
    """Span trees -> flat OpenTelemetry-style span dicts (the shape an OTLP
    adapter would serialize; tick time base, documented in docs/TRACING.md)."""
    out: List[Dict] = []
    for tree in span_trees:
        out.extend(flatten_spans(tree))
    return out


def write_chrome_trace(path: str, doc: Dict) -> str:
    """Write one Perfetto-loadable JSON file; returns the path."""
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path
